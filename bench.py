#!/usr/bin/env python
"""Headline benchmark: committed log entries/sec simulating 10k MultiPaxos
acceptors (BASELINE.json: target >= 1M/sec on TPU, metric "committed log
entries/sec @ 10k replicas; p50 commit latency (sim ticks)").

Prints exactly ONE JSON line to stdout:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}

Robustness contract (the driver records this script's stdout verbatim):
the orchestrating process imports no jax. It probes the TPU backend in a
subprocess with a hard timeout; if the probe fails or the TPU run dies, it
re-runs the measurement on the CPU backend in a clean environment (the
sitecustomize gated on PALLAS_AXON_POOL_IPS would otherwise import the TPU
plugin at interpreter start). Every path ends in a one-line JSON on stdout
and exit code 0, with an honest "device" field.

``--live-only`` disables the last-known-good replay: the headline is
whatever ran live this invocation, never a stale TPU capture. The JSON
also carries a "memory" block (device peak_bytes_in_use when the
backend's allocator reports it — process peak RSS labeled source="rss"
otherwise — plus the dtype-policy state footprint).

``--telemetry`` additionally measures the device-side metric ring's
overhead head-to-head (default ring vs zero-width ring) against the <2%
ticks/sec budget, and embeds the captured ring (renderable by
``python -m frankenpaxos_tpu.monitoring.dashboard <result.json>``).

``--faults`` measures degraded mode: the same flagship config healthy vs
under the standard fault plan (tpu/faults.py; extra drops + duplication
+ jitter + crash/revive driving on-device elections), reporting both
ticks/sec and committed/sec plus the faulty run's telemetry ring capture
(drops/retries/leader_changes actually injected). Evidence file:
results/fault_overhead_r08.json.

``--workload`` is a SEPARATE mode: the latency-vs-offered-load matrix
of the flagship under the in-graph workload engine (tpu/workload.py).
It anchors the offered-load scale at the measured saturation rate,
then sweeps 0.25x/0.5x/0.9x/1.1x of it through ONE compiled program
(the offered rate is a traced state scalar; the jit cache is asserted
not to grow), reporting committed/sec, p50/p99 commit latency, queue
depth/wait, and shed per leg — plus a p99-under-partition+burst leg
and a closed-loop (outstanding-window) leg. Capture artifact:
WORKLOAD_r01.json.

``--serve`` is a SEPARATE mode: the continuous serve loop
(harness/serve.py) at the flagship shape — steady-state ticks/sec vs
the batch-mode ``run_ticks`` baseline at the same chunk lengths (the
double-buffered non-blocking telemetry drain's overhead, budgeted
< 2%), the Perfetto trace export (device lifecycle spans + host
dispatch spans), and a fault-injected SLO leg (shaped load + degraded
FaultPlan -> queue-wait p99 breach -> alarm -> admission clamp via the
traced rate -> p99 recovery). Capture artifact: SERVE_r01.json.

``--checkpoint`` is a SEPARATE mode: the crash-tolerance budget — the
serve loop at the same flagship shape with async alias-free
checkpointing (tpu/checkpoint.py: the State copy enqueues behind chunk
i, the disk write rides a writer thread overlapping later chunks),
overhead vs the no-checkpoint serve budgeted < 2%; plus a recovery-evidence leg
(bit-exact resume vs the uninterrupted twin, corrupt-newest-checkpoint
fallback). Capture artifact: CHECKPOINT_r01.json.

``--multichip`` is a SEPARATE mode: it measures the multi-chip GSPMD
scaling matrix of the compartmentalized backend
(tpu/compartmentalized_batched.py sharded via parallel/sharding.py) on
1/2/4/8 simulated host devices (clean subprocess with
``--xla_force_host_platform_device_count=8``), prints one JSON line,
and records per-leg ``n_devices``/``mesh_shape``/``collective_bytes``
plus an HLO collective census verifying the group-local write path.
Since the fleet PR it also carries per-mesh-size OFFERED-LOAD matrices
(``shaped_load_matrix``): the traced rate swept through one compiled
program per mesh size, so every scaling row has latency-vs-load, not
just committed/tick. Simulated-domain throughput (committed entries
per tick at fixed per-device load) is the scaling headline on a CPU
host — wall-clock columns are honest about the host's physical core
count, and the real-TPU leg is flagged ``pending_tpu_remeasure``.
Capture artifact: MULTICHIP_r08.json.

``--fleet`` is a SEPARATE mode: the fleet-axis capacity planner
(parallel/sharding.py two-axis ``('fleet', 'groups')`` mesh). It maps
the full [offered-load x fault-rate] saturation surface of the
flagship in ONE compiled executable per mesh — every cell is a fleet
instance whose traced offered rate and traced Bernoulli fault rates
are state, so the whole surface is one ``run_ticks_fleet`` call
(per-cell committed/sec + p99 commit latency + queue-wait p99 + shed;
the runner's jit cache is asserted flat and the kernels-engaged
lowering's per-device autotune block resolutions are recorded) — plus
the device-rate fuzzing leg: ``simtest.run_fleet`` packs a whole
[seeds x schedules] brick into one executable and is timed against
the sequential per-config loop (one compile per schedule — the cost
the fleet axis amortizes). Capture artifact: FLEET_r01.json.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

_REPO = os.path.dirname(os.path.abspath(__file__))
TARGET = 1_000_000.0  # committed entries/sec (BASELINE.json north star)
METRIC = "committed log entries/sec @ 10k simulated MultiPaxos acceptors"
UNIT = "entries/sec"

_PROBE_CODE = (
    "import jax, jax.numpy as jnp; "
    "x = jnp.ones((256, 256), jnp.float32); "
    "jax.block_until_ready(x @ x); "
    "print('PROBE_OK', jax.devices()[0].platform)"
)


def _inner_main() -> None:
    """The actual measurement; runs in a subprocess with jax importable.

    Soft deadline: the subprocess has a 900s hard timeout, after which
    the WHOLE run (headline included) is lost. On slow machines the
    secondary variants (read modes, SMR) can push past it, so each
    checks a soft budget first and is skipped — recorded honestly in
    the JSON — rather than silently destroying the headline."""
    import dataclasses
    import time

    import jax

    from frankenpaxos_tpu.tpu import BatchedMultiPaxosConfig, TpuSimTransport

    inner_start = time.perf_counter()
    soft_budget = float(os.environ.get("BENCH_INNER_BUDGET_S", "700"))

    def over_budget() -> bool:
        return time.perf_counter() - inner_start > soft_budget

    def make_cfg(K: int, W: int) -> BatchedMultiPaxosConfig:
        # 3334 groups x 3 acceptors = 10,002 simulated acceptors (f=1).
        return BatchedMultiPaxosConfig(
            f=1,
            num_groups=3334,
            window=W,
            slots_per_tick=K,
            lat_min=1,
            lat_max=3,
            drop_rate=0.0,
            retry_timeout=16,
            thrifty=True,
        )

    # Calibrate over (K, W): ticks/s is set by the window-sized fusions
    # (W), not the proposal rate (K), so committed/s rises with K until
    # K * commit-latency exceeds W (results/tpu_perf_analysis_r03.md).
    # The best point differs between backends (VPU vs host SIMD), so
    # measure a short segment per candidate and keep the winner warm.
    candidates = [(8, 64), (16, 128), (32, 256)]
    calib_rows = []
    best = None  # (rate, K, W, sim)
    for K, W in candidates:
        c_sim = TpuSimTransport(make_cfg(K, W), seed=0)
        c_sim.run(150)  # compile + ramp the pipeline
        c_sim.block_until_ready()
        c0 = c_sim.committed()
        c_start = time.perf_counter()
        c_sim.run(150)
        c_sim.block_until_ready()
        c_dt = time.perf_counter() - c_start
        rate = (c_sim.committed() - c0) / c_dt
        calib_rows.append(
            {"K": K, "W": W, "committed_per_sec": round(rate, 1)}
        )
        if best is None or rate > best[0]:
            best = (rate, K, W, c_sim)
    _, bK, bW, sim = best
    cfg = make_cfg(bK, bW)

    # Size the measured run to a sane wall-clock budget on any backend
    # (TPU ticks are ~5ms at this model size; a CPU fallback is ~50ms).
    ticks_per_segment = 500
    sim.run(ticks_per_segment)
    sim.block_until_ready()
    t0 = time.perf_counter()
    sim.run(ticks_per_segment)
    sim.block_until_ready()
    probe = time.perf_counter() - t0
    budget_s = 30.0
    segments = max(1, min(12, int(budget_s / max(probe, 1e-3))))

    committed0 = sim.committed()
    start = time.perf_counter()
    for _ in range(segments):
        sim.run(ticks_per_segment)
    sim.block_until_ready()
    elapsed = time.perf_counter() - start
    committed = sim.committed() - committed0

    stats = sim.stats()
    throughput = committed / elapsed
    ticks = segments * ticks_per_segment
    # Device memory accounting for the HBM-bandwidth pass: peak bytes in
    # use as the device runtime reports them, plus the dtype-policy state
    # footprint computed from the live state. Backends without an
    # allocator stats API (CPU) fall back to the process's peak RSS so
    # CPU runs report a real number — labeled by source ("xla" vs "rss";
    # RSS covers the whole process, not just simulation state, so the
    # two are comparable only within a source).
    mem_stats = jax.devices()[0].memory_stats() or {}
    from frankenpaxos_tpu.tpu.common import state_nbytes

    peak = mem_stats.get("peak_bytes_in_use")
    if peak is not None:
        mem_source = "xla"
    else:
        import resource

        # ru_maxrss is KiB on Linux (bytes on macOS — not this box).
        peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
        mem_source = "rss"
    # Packed-plane accounting (tpu/common.PACKED_PLANES via
    # tpu/packing.py): what the headline config's hot narrow planes
    # cost as stored vs bit-packed, regardless of whether this run
    # packed them — the saved-bytes column of the memory story.
    from frankenpaxos_tpu.harness.microbench import _packed_plane_bytes
    from frankenpaxos_tpu.tpu import multipaxos_batched as _mp

    _pp = {
        case: _packed_plane_bytes(
            _mp.init_state(dataclasses.replace(cfg, pack_planes=packed))
        )
        for case, packed in (("unpacked", False), ("packed", True))
    }
    memory = {
        "peak_bytes_in_use": peak,
        "source": mem_source,
        "bytes_in_use": mem_stats.get("bytes_in_use"),
        "state_bytes": state_nbytes(sim.state),
        "packed_planes": {
            "enabled": bool(cfg.pack_planes),
            "plane_bytes": _pp,
            "bytes_saved": sum(_pp["unpacked"].values())
            - sum(_pp["packed"].values()),
        },
    }
    result = {
        "metric": METRIC,
        "value": round(throughput, 1),
        "unit": UNIT,
        "vs_baseline": round(throughput / TARGET, 3),
        "p50_commit_latency_ticks": stats["commit_latency_p50_ticks"],
        "num_acceptors": cfg.num_acceptors,
        "ticks": ticks,
        "ticks_per_sec": round(ticks / elapsed, 1),
        "wall_seconds": round(elapsed, 3),
        "device": str(jax.devices()[0]),
        "config": {"K": bK, "W": bW, "num_groups": cfg.num_groups},
        "calibration": calib_rows,
        "memory": memory,
    }

    # Kernel-layer accounting (ops/registry.py): the headline config's
    # effective KernelPolicy, the per-plane implementation it resolved
    # to on THIS backend (pallas / interpret / reference), and the
    # registry's backend -> fused-plane coverage map.
    from frankenpaxos_tpu.ops import registry as _registry

    _pol = _registry.policy_of(cfg)
    result["kernel_policy"] = {
        "mode": _pol.mode,
        "block": _pol.block,
        "disable": list(_pol.disable),
        "resolved": {
            name: _registry.resolve_mode(name, cfg)
            for name, plane in _registry.PLANES.items()
            if plane.backend == "multipaxos"
        },
        # The whole-tick megakernel's resolution, surfaced separately:
        # "pallas" here means the flagship tick runs as ONE fused grid
        # program (no per-plane HBM round trips); "reference" means the
        # pure-jnp multi-plane path (the CPU fallback's fastest mode).
        "fused_tick": _registry.resolve_mode(
            "multipaxos_fused_tick", cfg
        ),
    }
    result["kernel_coverage"] = {
        backend: list(planes)
        for backend, planes in _registry.coverage().items()
    }

    # Static-analysis provenance (frankenpaxos_tpu/analysis): which
    # contract-rule registry version, and how many rules, were in force
    # when this artifact was captured — so a future reader knows what a
    # "clean" repo meant at capture time.
    from frankenpaxos_tpu import analysis as _analysis

    result["analysis"] = {
        "version": _analysis.ANALYSIS_VERSION,
        "rule_count": _analysis.rule_count(),
    }

    # Telemetry overhead budget (--telemetry): the device-side metric
    # ring (tpu/telemetry.py) must cost <2% ticks/sec on this flagship
    # config. Measured head-to-head: the shipped default ring vs a
    # ZERO-WIDTH ring (record() no-ops at trace time, so XLA removes
    # every telemetry computation — the true without-telemetry
    # baseline). Both numbers land in the results JSON; the budget
    # verdict is `overhead_ok` (ticks/sec with >= 0.98x without). The
    # hard `assert` is opt-in via BENCH_STRICT_TELEMETRY=1 because this
    # script's stdout contract ("every path ends in a one-line JSON,
    # exit 0") outranks failing the whole bench on a noisy-box blip.
    if "--telemetry" in sys.argv:
        if over_budget():
            result.setdefault("skipped_variants", []).append(
                f"telemetry (soft budget {soft_budget:.0f}s exceeded)"
            )
        else:
            from frankenpaxos_tpu.harness.microbench import (
                measure_telemetry_overhead,
            )

            measured = measure_telemetry_overhead(cfg, ticks=300)
            ratio = measured["ratio"]
            result["telemetry"] = {
                "ticks_per_sec_with": round(measured["rates"]["ring_on"], 1),
                "ticks_per_sec_without": round(
                    measured["rates"]["ring_off"], 1
                ),
                "ratio": round(ratio, 4),
                "overhead_ok": ratio >= 0.98,
                # The captured ring: feed this JSON straight to
                # `python -m frankenpaxos_tpu.monitoring.dashboard`.
                **measured["sim_on"].telemetry_dict(),
            }
            if ratio < 0.98:
                print(
                    f"warning: telemetry overhead budget MISSED "
                    f"(ratio {ratio:.4f} < 0.98)",
                    file=sys.stderr,
                )
            if os.environ.get("BENCH_STRICT_TELEMETRY"):
                assert ratio >= 0.98, (
                    f"telemetry overhead over budget: {ratio:.4f} < 0.98"
                )

    # Degraded-mode benchmark (--faults): healthy vs faulty ticks/sec on
    # the winning flagship config under the standard degraded plan, with
    # the faulty run's telemetry ring embedded so the injected
    # drops/retries/leader_changes are visible in the artifact.
    if "--faults" in sys.argv:
        if over_budget():
            result.setdefault("skipped_variants", []).append(
                f"faults (soft budget {soft_budget:.0f}s exceeded)"
            )
        else:
            from frankenpaxos_tpu.harness.microbench import (
                measure_fault_overhead,
            )
            from frankenpaxos_tpu.tpu.telemetry import COL

            measured = measure_fault_overhead(cfg, ticks=300)
            tel = measured["sim_faulty"].telemetry()
            result["faults"] = {
                "plan": measured["plan"],
                "ticks_per_sec_healthy": round(
                    measured["rates"]["healthy"], 1
                ),
                "ticks_per_sec_faulty": round(
                    measured["rates"]["faulty"], 1
                ),
                "slowdown_ratio": round(measured["ratio"], 4),
                "committed_healthy": measured["committed"]["healthy"],
                "committed_faulty": measured["committed"]["faulty"],
                "drops_total": int(tel.totals[COL["drops"]]),
                "retries_total": int(tel.totals[COL["retries"]]),
                "leader_changes_total": int(
                    tel.totals[COL["leader_changes"]]
                ),
                "invariants_ok": all(
                    measured["sim_faulty"].check_invariants().values()
                ),
                # The captured ring (dashboard interchange format).
                **measured["sim_faulty"].telemetry_dict(),
            }

    # Secondary: the same cluster serving reads alongside writes through
    # the device-resident ReadBatchers (ReadBatcher.scala:239-338;
    # read_rate=1 means one read per group per tick — read load scales
    # with the cluster, the way the reference adds ReadBatcher nodes).
    # All three consistency modes are measured; "linearizable" is the
    # headline read_variant.
    for mode in ("linearizable", "sequential", "eventual"):
        if over_budget():
            result.setdefault("skipped_variants", []).append(
                f"read_{mode} (soft budget {soft_budget:.0f}s exceeded)"
            )
            continue
        rcfg = dataclasses.replace(
            cfg, read_rate=8, read_window=32, read_mode=mode
        )
        # The headline lin row gets a full segment; seq/eventual only
        # need the consistency-mode ordering, so shorter segments keep
        # the whole inner run well inside its subprocess timeout.
        r_ticks = (
            ticks_per_segment if mode == "linearizable"
            else max(150, ticks_per_segment // 3)
        )
        rsim = TpuSimTransport(rcfg, seed=0)
        rsim.run(r_ticks)
        rsim.block_until_ready()
        rc0, rr0 = rsim.committed(), int(rsim.state.reads_done)
        r_start = time.perf_counter()
        rsim.run(r_ticks)
        rsim.block_until_ready()
        r_elapsed = time.perf_counter() - r_start
        rstats = rsim.stats()
        row = {
            "mode": mode,
            # Offered load: read_rate reads per group per tick (the
            # per-group ReadBatcher model — reads_per_sec scales with
            # num_groups, unlike the pre-r05 fixed global ring).
            "read_rate": rcfg.read_rate,
            "read_window": rcfg.read_window,
            "committed_per_sec": round(
                (rsim.committed() - rc0) / r_elapsed, 1
            ),
            "reads_per_sec": round(
                (int(rsim.state.reads_done) - rr0) / r_elapsed, 1
            ),
            "read_latency_p50_ticks": rstats["read_latency_p50_ticks"],
            "reads_shed": rstats["reads_shed"],
            "invariants_ok": all(rsim.check_invariants().values()),
        }
        if mode == "linearizable":
            result["read_variant"] = row
        else:
            result.setdefault("read_modes", {})[mode] = row

    # Tertiary: the FULL replicated-state-machine pipeline — writes +
    # device-side KV state machine + exactly-once client table with
    # injected client re-sends (Replica.executeCommand,
    # Replica.scala:305-344) — i.e. commands ACTUALLY EXECUTING, not just
    # committing.
    if over_budget():
        result.setdefault("skipped_variants", []).append(
            f"smr (soft budget {soft_budget:.0f}s exceeded)"
        )
        print("BENCH_JSON " + json.dumps(result))
        return
    scfg = dataclasses.replace(
        cfg, state_machine="kv", kv_keys=64, num_clients=8, dup_rate=0.02
    )
    ssim = TpuSimTransport(scfg, seed=0)
    ssim.run(ticks_per_segment)
    ssim.block_until_ready()
    sc0, sa0 = ssim.committed(), int(ssim.state.sm_applied)
    s_start = time.perf_counter()
    ssim.run(ticks_per_segment)
    ssim.block_until_ready()
    s_elapsed = time.perf_counter() - s_start
    result["smr_variant"] = {
        "committed_per_sec": round((ssim.committed() - sc0) / s_elapsed, 1),
        "sm_applied_per_sec": round(
            (int(ssim.state.sm_applied) - sa0) / s_elapsed, 1
        ),
        "dups_filtered": int(ssim.state.dups_filtered),
        "invariants_ok": all(ssim.check_invariants().values()),
    }
    print("BENCH_JSON " + json.dumps(result))


_SIGNED_COLLECTIVES = (
    "all-reduce", "all-gather", "all-to-all", "collective-permute",
    "reduce-scatter",
)
_DTYPE_BYTES = {"s8": 1, "u8": 1, "s16": 2, "u16": 2, "s32": 4, "u32": 4,
                "pred": 1}


def _collective_census(hlo_text: str) -> dict:
    """Census of the collectives XLA's SPMD partitioner emitted: total
    payload bytes, split signed/pred (simulation state + stat
    reductions) vs unsigned (threefry PRNG-sweep assembly artifacts),
    plus the largest signed collective — the number that must stay at
    stat-reduction scale for the group-local claim to hold.

    Result shapes are parsed from the segment between '=' and the
    collective's op name, and EVERY shape there is counted: XLA's
    all-reduce combiner merges several reductions into one tuple-shaped
    op, so reading only the first element would let a large state
    reduction hide behind a combined scalar."""
    import re

    shape_re = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")
    signed_bytes = unsigned_bytes = 0
    signed_ops = unsigned_ops = 0
    max_signed_elems = 0
    for line in hlo_text.splitlines():
        op_at = [
            line.index(tok)
            for op in _SIGNED_COLLECTIVES
            for tok in (op + "(", op + "-start(")
            if tok in line
        ]
        eq_at = line.find("=")
        if not op_at or eq_at < 0:
            continue
        result_part = line[eq_at: min(op_at)]
        shapes = shape_re.findall(result_part)
        if not shapes:
            continue
        any_signed = False
        for dtype, dims in shapes:
            elems = 1
            for d in dims.split(","):
                if d:
                    elems *= int(d)
            nbytes = elems * _DTYPE_BYTES.get(dtype, 4)
            if dtype.startswith("u"):
                unsigned_bytes += nbytes
            else:
                any_signed = True
                signed_bytes += nbytes
                max_signed_elems = max(max_signed_elems, elems)
        if any_signed:
            signed_ops += 1
        else:
            unsigned_ops += 1
    return {
        "state_collective_ops": signed_ops,
        "state_collective_bytes": signed_bytes,
        "prng_collective_ops": unsigned_ops,
        "prng_collective_bytes": unsigned_bytes,
        "max_state_collective_elems": max_signed_elems,
        # Stat reductions (scalars + LAT_BINS=64 histograms) only.
        "group_local_ok": max_signed_elems <= 64,
    }


def _multichip_inner() -> None:
    """The multichip scaling measurement; runs in a subprocess with 8
    virtual CPU devices. One JSON line on stdout (BENCH_JSON ...)."""
    import time

    import jax
    import jax.numpy as jnp

    from frankenpaxos_tpu.parallel import sharding as sh
    from frankenpaxos_tpu.tpu import compartmentalized_batched as cbk

    devices = jax.devices()
    assert len(devices) >= 8, (
        f"need 8 virtual devices, have {len(devices)}"
    )
    G_PER_DEV = 3125  # x (2x2 grid) = 12,500 simulated acceptors/device

    def make_cfg(G: int, **kw) -> "cbk.BatchedCompartmentalizedConfig":
        return cbk.BatchedCompartmentalizedConfig(
            num_groups=G, grid_rows=2, grid_cols=2,
            num_proxy_leaders=8, num_batchers=2, num_unbatchers=2,
            num_replicas=3, window=32, batch_size=8,
            arrivals_per_tick=4, lat_min=1, lat_max=3, retry_timeout=16,
            **kw,
        )

    def leg_census(cfg, mesh) -> dict:
        """Collective census of THIS leg's own lowered program — every
        row carries bytes measured at its own mesh size, not a copy of
        the 8-device number."""
        st = sh.shard_state("compartmentalized", cbk.init_state(cfg), mesh)
        hlo = sh.lower_sharded(
            "compartmentalized", cfg, mesh, st,
            jnp.zeros((), jnp.int32), 4, jax.random.PRNGKey(0),
        ).compile().as_text()
        return _collective_census(hlo)

    def kernel_fields(cfg) -> dict:
        """Per-row kernel accounting: the policy mode + the per-plane
        resolution on THIS backend — so a leg where the kernels stayed
        off says `kernels_engaged: false` explicitly instead of
        staying silent."""
        from frankenpaxos_tpu.ops import registry as reg

        pol = reg.policy_of(cfg)
        resolved = {
            n: reg.resolve_mode(n, cfg)
            for n, p in reg.PLANES.items()
            if p.backend == "compartmentalized"
        }
        return {
            "kernel_policy": {
                "mode": pol.mode,
                "block": pol.block,
                "resolved": resolved,
            },
            "kernels_engaged": any(
                m != "reference" for m in resolved.values()
            ),
        }

    def measure(
        n_dev: int, G: int, warm: int = 60, ticks: int = 60,
        kernels_mode: "str | None" = None,
    ):
        import dataclasses as _dc

        from frankenpaxos_tpu.ops.registry import KernelPolicy

        cfg = make_cfg(G)
        if kernels_mode is not None:
            cfg = _dc.replace(cfg, kernels=KernelPolicy(mode=kernels_mode))
        mesh = sh.make_mesh(devices[:n_dev])
        census = leg_census(cfg, mesh)
        state = sh.shard_state("compartmentalized",
                               cbk.init_state(cfg), mesh)
        key = jax.random.PRNGKey(0)
        state, t = sh.run_ticks_sharded(
            "compartmentalized", cfg, mesh, state,
            jnp.zeros((), jnp.int32), warm, key,
        )
        jax.block_until_ready(state)  # compile + ramp to steady state
        c0 = int(state.committed)
        start = time.perf_counter()
        state, t = sh.run_ticks_sharded(
            "compartmentalized", cfg, mesh, state, t, ticks,
            jax.random.fold_in(key, 1),
        )
        jax.block_until_ready(state)
        dt = time.perf_counter() - start
        committed = int(state.committed) - c0
        inv_ok = all(
            bool(v)
            for v in cbk.check_invariants(cfg, state, t).values()
        )
        return {
            "n_devices": n_dev,
            "mesh_shape": [n_dev],
            "num_groups": G,
            "num_acceptors": cfg.num_acceptors,
            "ticks": ticks,
            "committed_entries": committed,
            "committed_per_tick": round(committed / ticks, 1),
            "ticks_per_sec": round(ticks / dt, 2),
            "committed_per_sec": round(committed / dt, 1),
            "invariants_ok": inv_ok,
            # This leg's own census (4-tick program at THIS mesh size).
            "collective_bytes": census["state_collective_bytes"],
            "group_local_ok": census["group_local_ok"],
            **kernel_fields(cfg),
        }

    # Weak scaling: fixed per-device load (the scale-out axis the
    # compartmentalization paper adds nodes along) — 12.5k simulated
    # acceptors per device, 100k at the full 8-device mesh.
    weak = [measure(d, G_PER_DEV * d) for d in (1, 2, 4, 8)]
    # Strong scaling: the SAME 100k-acceptor model on 1 vs 8 devices
    # (fixed total work; on a CPU host this isolates partitioning
    # overhead rather than speedup).
    strong = [measure(d, G_PER_DEV * 8, warm=40, ticks=40)
              for d in (1, 8)]
    # Kernels-ON legs per mesh size: the same simulation with the
    # grid-vote plane ENGAGED, shard_map-lowered per device (interpret
    # mode on this CPU host — the actual kernel path, priced by the
    # Pallas interpreter, so these rows measure COMPOSITION not speed;
    # the compiled wall clock is the reserved TPU leg). Short ticks:
    # the interpreter costs ~2 orders of magnitude per tick.
    kernels_on = [
        measure(d, G_PER_DEV * d, warm=8, ticks=8,
                kernels_mode="interpret")
        for d in (1, 2, 4, 8)
    ]
    # Cross-check at the full mesh: the kernels-on leg must commit
    # EXACTLY what the reference program commits over the same
    # (seed, ticks) history — sharded kernels == sharded reference.
    ref_check = measure(8, G_PER_DEV * 8, warm=8, ticks=8)
    kernels_match = (
        kernels_on[-1]["committed_entries"] == ref_check["committed_entries"]
    )

    # Shaped-load legs (ROADMAP PR 9 follow-up (b)): per-mesh-size
    # offered-load matrices. Each mesh size anchors the rate scale at
    # its own measured saturation (the weak-scaling row), then sweeps
    # 0.5x/0.9x/1.1x of it as the TRACED state-side rate — every leg of
    # a mesh size replays ONE compiled program, so the scaling rows
    # carry latency-vs-load, not just committed/tick.
    from frankenpaxos_tpu.monitoring.slo import hist_p99
    from frankenpaxos_tpu.tpu import workload as wl_mod
    from frankenpaxos_tpu.tpu.workload import WorkloadPlan

    import dataclasses as _dcl

    def shaped_matrix(n_dev: int, sat_row: dict, warm=30, ticks=30):
        G = sat_row["num_groups"]
        sat_lane = sat_row["committed_per_tick"] / G
        cfg = make_cfg(
            G,
            workload=WorkloadPlan(
                arrival="constant", rate=sat_lane, backlog_cap=256
            ),
        )
        mesh = sh.make_mesh(devices[:n_dev])
        rows = []
        cache_before = None
        for frac in (0.5, 0.9, 1.1):
            state = sh.shard_state(
                "compartmentalized", cbk.init_state(cfg), mesh
            )
            state = _dcl.replace(
                state,
                workload=wl_mod.set_rate(
                    state.workload, frac * sat_lane
                ),
            )
            key = jax.random.PRNGKey(int(frac * 100))
            state, t = sh.run_ticks_sharded(
                "compartmentalized", cfg, mesh, state,
                jnp.zeros((), jnp.int32), warm, key,
            )
            jax.block_until_ready(state.committed)
            c0 = int(state.committed)
            lat0 = jax.device_get(state.lat_hist)
            wait0 = jax.device_get(state.workload.wait_hist)
            start = time.perf_counter()
            state, t = sh.run_ticks_sharded(
                "compartmentalized", cfg, mesh, state, t, ticks,
                jax.random.fold_in(key, 1),
            )
            jax.block_until_ready(state.committed)
            dt = time.perf_counter() - start
            lat_d = jax.device_get(state.lat_hist) - lat0
            wait_d = jax.device_get(state.workload.wait_hist) - wait0
            summ = wl_mod.summary(cfg.workload, state.workload)
            rows.append({
                "load_fraction": frac,
                "offered_rate_per_lane": round(frac * sat_lane, 4),
                "committed": int(state.committed) - c0,
                "committed_per_tick": round(
                    (int(state.committed) - c0) / ticks, 1
                ),
                "ticks_per_sec": round(ticks / dt, 2),
                "p99_commit_latency_ticks": hist_p99(lat_d, 0.99),
                "queue_wait_p99_ticks": hist_p99(wait_d, 0.99),
                "shed_total": summ["shed"],
                "invariants_ok": all(
                    bool(v)
                    for v in cbk.check_invariants(cfg, state, t).values()
                ),
            })
            if cache_before is None:
                # After the first leg the program is compiled; the
                # remaining rate legs must hit the same executable.
                cache_before = sh._runner(
                    "compartmentalized",
                    sh._wrap_mesh("compartmentalized", cfg, mesh),
                )._cache_size()
        cache_after = sh._runner(
            "compartmentalized",
            sh._wrap_mesh("compartmentalized", cfg, mesh),
        )._cache_size()
        return {
            "n_devices": n_dev,
            "num_groups": G,
            "saturation_rate_per_lane_per_tick": round(sat_lane, 4),
            "legs": rows,
            "one_compile_per_mesh_size": cache_after == cache_before,
        }

    shaped_load = [
        shaped_matrix(d, row)
        for d, row in zip((1, 2, 4, 8), weak)
    ]

    # Headline census: the full 8-device, 100k-acceptor program — the
    # group-local-write-path claim as a compile-time fact.
    census = leg_census(make_cfg(G_PER_DEV * 8), sh.make_mesh(devices[:8]))

    base = weak[0]
    top = weak[-1]
    result = {
        "metric": (
            "compartmentalized committed entries/sec scaling, "
            "1 -> 8 devices"
        ),
        "backend": "compartmentalized",
        "device": str(devices[0]),
        "n_devices": 8,
        "mesh_shape": [8],
        "host_physical_cores": os.cpu_count(),
        "weak_scaling": weak,
        "strong_scaling_100k": strong,
        # The kernels x mesh legs (PR 8): grid-vote plane engaged under
        # shard_map at every mesh size, plus the bit-exactness
        # cross-check against the reference program.
        "kernels_on_matrix": kernels_on,
        "kernels_vs_reference_committed_match": kernels_match,
        # Per-mesh-size offered-load matrices (traced-rate sweeps, one
        # compile per mesh size): latency-vs-load at every scale.
        "shaped_load_matrix": shaped_load,
        "collective_census_8dev_100k": census,
        "scaling": {
            "basis": (
                "committed entries per tick at fixed per-device load "
                "(12.5k simulated acceptors per device; 100k at 8 "
                "devices) — the simulated-domain throughput a "
                "group-local program sustains per added device"
            ),
            "x_at_8_devices": round(
                top["committed_per_tick"] / base["committed_per_tick"], 2
            ),
            "wallclock_x_at_8_devices": round(
                top["committed_per_sec"] / base["committed_per_sec"], 2
            ),
            "wallclock_note": (
                "virtual 8-device mesh shares this host's physical "
                "cores, so wall-clock scaling is bounded by the core "
                "count; real-chip wall-clock scaling is the reserved "
                "TPU leg (group-locality verified by the collective "
                "census above)"
            ),
            "group_local_ok": census["group_local_ok"],
        },
        "invariants_ok": all(
            r["invariants_ok"] for r in weak + strong
        ),
        # Real-hardware leg reserved: this capture is a virtual-mesh
        # (CPU) measurement.
        "measured_live": True,
        "pending_tpu_remeasure": True,
    }
    print("BENCH_JSON " + json.dumps(result))


def _fleet_inner() -> None:
    """The fleet-axis measurement (``--fleet``); runs in a subprocess
    with 8 virtual CPU devices. Two legs (module docstring): the
    one-compile-per-mesh [offered-load x fault-rate] saturation
    surface, and the simtest fleet fuzzer timed against the sequential
    per-config loop — plus the fleet OBSERVABILITY legs (drain
    overhead vs the drain-off brick, hostile-instance straggler
    detection + per-instance clamp). One JSON line on stdout
    (BENCH_JSON ...). Capture artifacts: FLEET_r01.json (pre-
    observability), FLEET_r02.json (with the telemetry legs)."""
    import dataclasses
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from frankenpaxos_tpu.harness import simtest
    from frankenpaxos_tpu.monitoring.slo import hist_p99
    from frankenpaxos_tpu.ops.registry import KernelPolicy
    from frankenpaxos_tpu.parallel import sharding as sh
    from frankenpaxos_tpu.tpu import multipaxos_batched as mp
    from frankenpaxos_tpu.tpu.faults import FaultPlan
    from frankenpaxos_tpu.tpu.workload import WorkloadPlan

    # Multi-host entry: a no-op on this single-process virtual mesh,
    # the jax.distributed init + barrier on a real pod (the same code
    # path runs both — the T5X pattern parallel/sharding.py documents).
    sh_multihost = sh.maybe_init_distributed()
    sh.host_sync("fleet-bench-start")

    devices = jax.devices()
    assert len(devices) >= 8, f"need 8 virtual devices, have {len(devices)}"
    G, W, K = 512, 32, 4
    WARM, MEAS = 60, 120
    key = jax.random.PRNGKey(0)
    t0 = jnp.zeros((), jnp.int32)

    def base_cfg(**kw) -> "mp.BatchedMultiPaxosConfig":
        return mp.BatchedMultiPaxosConfig(
            f=1, num_groups=G, window=W, slots_per_tick=K,
            lat_min=1, lat_max=3, retry_timeout=16, thrifty=True, **kw
        )

    # 1. Saturation anchor (single instance, none plan): fixes the
    # offered-load scale for the surface, exactly as --workload does.
    cfg0 = base_cfg()
    st = mp.init_state(cfg0)
    st, t = mp.run_ticks(cfg0, st, t0, WARM, key)
    jax.block_until_ready(st.committed)
    c0 = int(st.committed)
    start = time.perf_counter()
    st, t = mp.run_ticks(cfg0, st, t, MEAS, jax.random.fold_in(key, 1))
    jax.block_until_ready(st.committed)
    sat_dt = time.perf_counter() - start
    sat_committed = int(st.committed) - c0
    sat_rate_lane = sat_committed / MEAS / G

    # 2. The saturation surface: [offered-load x fault-rate] as ONE
    # fleet brick — each cell an instance with its own traced offered
    # rate and traced drop rate, the whole surface one executable.
    loads = (0.25, 0.5, 0.9, 1.1)
    drops = (0.0, 0.05, 0.15, 0.3)
    cells = [(ld, dr) for ld in loads for dr in drops]
    F = len(cells)
    cfg = base_cfg(
        workload=WorkloadPlan(
            arrival="constant", rate=sat_rate_lane, backlog_cap=256
        ),
        faults=FaultPlan(traced=True),
    )
    mesh = sh.make_fleet_mesh(fleet=2)
    rates = [ld * sat_rate_lane for ld, _ in cells]
    frates = [[dr, 0.0, 0.0, 0.0] for _, dr in cells]
    states = sh.shard_fleet_state(
        "multipaxos",
        sh.fleet_states("multipaxos", cfg, F, rates=rates,
                        fault_rates=frates),
        mesh,
    )
    keys = sh.fleet_keys(range(F))
    # Warm and measure share ONE static tick count, so the whole
    # surface — warm-up included — is one compiled executable.
    SWEEP = 100
    states, tf = sh.run_ticks_fleet(
        "multipaxos", cfg, mesh, states, t0, SWEEP, keys
    )
    jax.block_until_ready(states.committed)
    c0s = np.asarray(states.committed).copy()
    lat0 = np.asarray(states.lat_hist).copy()
    wait0 = np.asarray(states.workload.wait_hist).copy()
    shed0 = np.asarray(states.workload.shed).copy()
    start = time.perf_counter()
    # Fresh per-segment keys (run_ticks folds the scan index, not the
    # absolute tick): the measured window draws an independent random
    # stream instead of replaying the warm-up's, same executable.
    keys2 = jax.vmap(lambda k: jax.random.fold_in(k, 1))(keys)
    states, tf = sh.run_ticks_fleet(
        "multipaxos", cfg, mesh, states, tf, SWEEP, keys2
    )
    jax.block_until_ready(states.committed)
    dt = time.perf_counter() - start
    committed = np.asarray(states.committed) - c0s
    lat_d = np.asarray(states.lat_hist) - lat0
    wait_d = np.asarray(states.workload.wait_hist) - wait0
    shed_d = np.asarray(states.workload.shed) - shed0
    inv = jax.device_get(
        jax.jit(
            jax.vmap(lambda s, tt: mp.check_invariants(cfg, s, tt))
        )(states, tf)
    )
    surface = []
    for i, (ld, dr) in enumerate(cells):
        surface.append({
            "load_fraction": ld,
            "drop_rate": dr,
            "committed": int(committed[i]),
            "committed_per_tick": round(float(committed[i]) / SWEEP, 2),
            "committed_per_sec": round(float(committed[i]) / dt, 1),
            "p99_commit_latency_ticks": hist_p99(lat_d[i], 0.99),
            "queue_wait_p99_ticks": hist_p99(wait_d[i], 0.99),
            "shed": int(shed_d[i]),
            "invariants_ok": all(bool(inv[k][i]) for k in inv),
        })
    wrap = sh._fleet_wrap_mesh("multipaxos", cfg, mesh)
    runner = sh._fleet_runner("multipaxos", mesh, wrap)
    one_compile = runner._cache_size() == 1

    # Kernels-engaged LOWERING of the same brick: populates the
    # registry's per-device block resolutions (the autotune table keyed
    # at the true per-device shape under the product mesh) for the
    # JSON record; the compiled-wall-clock kernels leg stays on the
    # TPU-hardware-debt list.
    cfg_k = dataclasses.replace(cfg, kernels=KernelPolicy(mode="interpret"))
    states_k = sh.fleet_states(
        "multipaxos", cfg_k, F, rates=rates, fault_rates=frates
    )
    states_k = sh.shard_fleet_state("multipaxos", states_k, mesh)
    sh.lower_fleet("multipaxos", cfg_k, mesh, states_k, t0, 2, keys)
    resolved_blocks = sh.fleet_block_plan("multipaxos", cfg_k, mesh)

    # 3. Device-rate fuzzing: a [seeds x schedules] brick through ONE
    # executable (simtest.run_fleet on a second, (2, 2) mesh — its own
    # cached program) vs the sequential per-config loop (one compile
    # per schedule: static rates, the pre-fleet cost model).
    import random as _random

    spec = simtest.SPECS["multipaxos"]
    n_sched, n_seeds, ticks = 16, 2, 80
    rng = _random.Random(0)
    fuzz_cells = [
        simtest.random_rate_cell(rng, spec) for _ in range(n_sched)
    ]
    # Brick on the default device: on this 1-core host the product
    # mesh only adds partitioning overhead (all virtual devices share
    # the core), so the fuzzer's headline is the unmeshed brick; the
    # meshed brick is timed alongside it for the composition record.
    start = time.perf_counter()
    fleet_res = simtest.run_fleet(
        spec, cells=fuzz_cells, seeds_per_schedule=n_seeds, ticks=ticks,
    )
    fleet_dt = time.perf_counter() - start
    fuzz_mesh = sh.make_fleet_mesh(fleet=2, devices=devices[:4])
    start = time.perf_counter()
    fleet_mesh_res = simtest.run_fleet(
        spec, cells=fuzz_cells, seeds_per_schedule=n_seeds,
        ticks=ticks, mesh=fuzz_mesh,
    )
    fleet_mesh_dt = time.perf_counter() - start
    start = time.perf_counter()
    seq_ok = True
    for cell in fuzz_cells:
        plan = FaultPlan(
            drop_rate=cell["drop"], dup_rate=cell["dup"],
            crash_rate=cell["crash"], revive_rate=cell["revive"],
        )
        wplan = WorkloadPlan(arrival="constant", rate=cell["rate"])
        res = simtest.run_many_seeds(
            spec, plan, list(range(n_seeds)), ticks, workload=wplan
        )
        seq_ok = seq_ok and res["ok"]
    seq_dt = time.perf_counter() - start
    n_runs = n_sched * n_seeds
    fuzz = {
        "schedules": n_sched,
        "seeds_per_schedule": n_seeds,
        "ticks": ticks,
        "instances": n_runs,
        "fleet_seconds": round(fleet_dt, 2),
        "fleet_mesh": [int(s) for s in dict(fuzz_mesh.shape).values()],
        "fleet_mesh_seconds": round(fleet_mesh_dt, 2),
        "sequential_seconds": round(seq_dt, 2),
        "fleet_schedules_per_sec": round(n_runs / fleet_dt, 1),
        "sequential_schedules_per_sec": round(n_runs / seq_dt, 1),
        # Wall-clock INCLUDING compiles on both sides: the sequential
        # loop pays one compile per schedule (static rates), the fleet
        # brick pays one total — exactly the cost the fleet amortizes.
        "speedup_x": round(seq_dt / fleet_dt, 2),
        "speedup_x_meshed": round(seq_dt / fleet_mesh_dt, 2),
        "fleet_ok": fleet_res["ok"] and fleet_mesh_res["ok"],
        "sequential_ok": seq_ok,
        "verdicts_match_across_meshes": (
            fleet_res["per_instance_ok"]
            == fleet_mesh_res["per_instance_ok"]
        ),
        "host_physical_cores": os.cpu_count(),
        "note": (
            "single-physical-core host: the virtual-device mesh adds "
            "partitioning overhead without parallelism, so the "
            "default-device brick is the throughput headline; real "
            "multi-chip meshes multiply it (pending_tpu_remeasure)"
        ),
    }

    # 4. Telemetry-engaged legs (the fleet observability plane,
    # harness/serve.FleetServeLoop). (a) Drain overhead: the
    # double-buffered non-blocking fleet drain (snapshot + in-graph
    # fleet_summary + per-instance DrainCursor) raced against the
    # drain-OFF brick — same compiled run_ticks_fleet, same chunking,
    # interleaved best-of-N, <2% budget. (b) The straggler-detection
    # demo: a homogeneous fleet below saturation with ONE instance on
    # a hostile traced drop rate — the per-instance summary flags it,
    # the per-instance SLO clamps it, and its siblings' p99 stays
    # flat (the differential-failure loop the fleet plane exists for).
    from frankenpaxos_tpu.harness.serve import (
        FleetServeConfig, FleetServeLoop, _fleet_snap_fn,
    )
    from frankenpaxos_tpu.monitoring.slo import SloPolicy
    from frankenpaxos_tpu.tpu import telemetry as telemetry_mod
    from frankenpaxos_tpu.harness import serve as serve_harness

    DF, CHUNK, CHUNKS, REPS = 8, 32, 8, 3
    drain_cfg = base_cfg(
        workload=WorkloadPlan(
            arrival="constant", rate=0.9 * sat_rate_lane,
            backlog_cap=256,
        ),
        faults=FaultPlan(traced=True),
    )
    d_rates = [0.9 * sat_rate_lane] * DF
    d_frates = [[0.0, 0.0, 0.0, 0.0]] * DF
    snap_fn = _fleet_snap_fn(4, 0, True)

    def fresh_states():
        return sh.fleet_states(
            "multipaxos", drain_cfg, DF, rates=d_rates,
            fault_rates=d_frates,
        )

    snap_sum = _fleet_snap_fn(4, 0, False)
    d_keys = sh.fleet_keys(range(DF))

    def run_leg(mode: str):
        """One bounded fleet run. "off" = the drain-off brick (same
        compiled chunks, no snapshot/drain); "rings" = the full
        exact-drain discipline (snapshot + per-instance DrainCursor);
        "summary" = the O(F)-scalars summary-only drain."""
        st, tt = fresh_states(), t0
        cur = telemetry_mod.DrainCursor()
        prev = None
        for c in range(CHUNKS):
            kk = jax.vmap(jax.random.fold_in, in_axes=(0, None))(
                d_keys, c
            )
            st, tt = sh.run_ticks_fleet(
                "multipaxos", drain_cfg, None, st, tt, CHUNK, kk
            )
            if mode == "off":
                continue
            fn = snap_fn if mode == "rings" else snap_sum
            snap = fn(serve_harness.snapshot_leaves(st))
            if prev is not None:
                host = jax.device_get(prev)
                if mode == "rings":
                    cur.drain(host["telemetry"])
            prev = snap
        if prev is not None:
            host = jax.device_get(prev)
            if mode == "rings":
                cur.drain(host["telemetry"])
        jax.block_until_ready(st.committed)

    import gc

    modes = ("off", "rings", "summary")
    for _ in range(2):  # warm compiles + allocator on every path
        for mode in modes:
            run_leg(mode)
    gc.collect()
    best = {m: float("inf") for m in modes}
    for _ in range(REPS):  # fully interleaved best-of-N
        for mode in modes:
            start = time.perf_counter()
            run_leg(mode)
            best[mode] = min(best[mode], time.perf_counter() - start)
    drain_overhead = {
        "instances": DF,
        "chunks": CHUNKS,
        "chunk_ticks": CHUNK,
        "reps_interleaved_best_of": REPS,
        "drain_off_seconds": round(best["off"], 4),
        "drain_rings_seconds": round(best["rings"], 4),
        "drain_summary_seconds": round(best["summary"], 4),
        "overhead_fraction_rings": round(
            best["rings"] / best["off"] - 1.0, 4
        ),
        "overhead_fraction_summary": round(
            best["summary"] / best["off"] - 1.0, 4
        ),
        "budget_fraction": 0.02,
        "within_budget": (
            best["rings"] / best["off"] - 1.0 < 0.02
        ),
    }

    HOSTILE = 5
    demo_frates = [[0.0, 0.0, 0.0, 0.0] for _ in range(DF)]
    demo_frates[HOSTILE][0] = 0.6
    demo_loop = FleetServeLoop(
        "multipaxos", drain_cfg,
        FleetServeConfig(
            chunk_ticks=CHUNK, telemetry_window=2 * CHUNK,
            slo=SloPolicy(p99_target_ticks=8, source="queue_wait"),
            max_chunks=10,
        ),
        DF,
        rates=d_rates,
        fault_rates=demo_frates,
    )
    wrap0 = sh._fleet_wrap_mesh("multipaxos", drain_cfg, None)
    demo_runner = sh._fleet_runner("multipaxos", None, wrap0)
    # Delta-based cache pin: the demo's own ring shape may add AT MOST
    # one entry (its first chunk's compile); every SLO clamp inside the
    # run must add none.
    demo_cache0 = demo_runner._cache_size()
    demo_rep = demo_loop.run()
    flagged = demo_rep["stragglers_flagged"]
    scales = demo_rep["slo"]["scales"]
    sibling_p99 = [
        row["p99_queue_wait"]
        for i, row in enumerate(demo_rep["summary"])
        if i != HOSTILE
    ]
    straggler_demo = {
        "instances": DF,
        "hostile_instance": HOSTILE,
        "hostile_drop_rate": 0.6,
        "flagged": flagged,
        "only_hostile_flagged": flagged == [HOSTILE],
        "scales": scales,
        "only_hostile_clamped": all(
            (s < 1.0) == (i == HOSTILE) for i, s in enumerate(scales)
        ),
        "hostile_p99_queue_wait": (
            demo_rep["summary"][HOSTILE]["p99_queue_wait"]
        ),
        "sibling_p99_queue_wait_max": max(sibling_p99),
        "sibling_p99_flat": max(sibling_p99) <= 8,
        "dropped_ticks": demo_rep["dropped_ticks"],
        "jit_cache_flat": (
            demo_runner._cache_size() <= demo_cache0 + 1
        ),
        "markers": demo_rep["markers"][:8],
    }
    assert straggler_demo["only_hostile_flagged"], straggler_demo
    assert straggler_demo["only_hostile_clamped"], straggler_demo
    assert straggler_demo["sibling_p99_flat"], straggler_demo

    result = {
        "metric": (
            "fleet-axis capacity surface + device-rate fuzzing "
            "throughput (one compiled executable per mesh)"
        ),
        "backend": "multipaxos",
        "device": str(devices[0]),
        "n_devices": len(devices[:8]),
        "mesh_shape": [int(s) for s in dict(mesh.shape).values()],
        "num_groups": G,
        "saturation": {
            "committed_per_tick": round(sat_committed / MEAS, 2),
            "committed_per_sec": round(sat_committed / sat_dt, 1),
            "rate_per_lane_per_tick": round(sat_rate_lane, 4),
        },
        "surface_cells": F,
        "surface_ticks": SWEEP,
        "surface_wall_seconds": round(dt, 2),
        "saturation_surface": surface,
        "one_compile_per_mesh": one_compile,
        "resolved_blocks": resolved_blocks,
        "fuzz": fuzz,
        # Fleet observability legs (harness/serve.FleetServeLoop):
        # the non-blocking fleet drain's cost vs the drain-off brick,
        # and the hostile-instance differential-detection demo.
        "telemetry_drain_overhead": drain_overhead,
        "straggler_demo": straggler_demo,
        "invariants_ok": all(r["invariants_ok"] for r in surface),
        "multi_host": sh_multihost,
        "measured_live": True,
        "pending_tpu_remeasure": True,
    }
    sh.host_sync("fleet-bench-done")
    print("BENCH_JSON " + json.dumps(result))


def _workload_inner() -> None:
    """The latency-vs-offered-load measurement (``--workload``): the
    flagship under the in-graph workload engine (tpu/workload.py).
    Legs at 0.25x/0.5x/0.9x/1.1x of the measured saturation rate all
    replay ONE compiled program (the offered rate is a traced state
    scalar — the jit cache is asserted not to grow across the sweep),
    plus a p99-under-partition+burst leg and a closed-loop leg. One
    JSON line on stdout (BENCH_JSON ...). Capture artifact:
    WORKLOAD_r01.json."""
    import dataclasses
    import time

    import jax
    import jax.numpy as jnp

    from frankenpaxos_tpu.tpu import multipaxos_batched as mp
    from frankenpaxos_tpu.tpu import workload as wl_mod
    from frankenpaxos_tpu.tpu.faults import FaultPlan
    from frankenpaxos_tpu.tpu.workload import WorkloadPlan

    G, W, K = 3334, 64, 8
    WARM, MEAS = 100, 250

    def base_cfg(**kw) -> "mp.BatchedMultiPaxosConfig":
        return mp.BatchedMultiPaxosConfig(
            f=1, num_groups=G, window=W, slots_per_tick=K,
            lat_min=1, lat_max=3, retry_timeout=16, thrifty=True, **kw
        )

    def hist_pct(hist_delta, q):
        return wl_mod.hist_percentile(hist_delta, q)

    def run_leg(cfg, state, key, label):
        """Warm WARM ticks, measure MEAS ticks; return the leg row
        with commit-latency / queue-wait percentiles computed from the
        MEASURED WINDOW's histogram deltas."""
        t0 = jnp.zeros((), jnp.int32)
        state, t = mp.run_ticks(cfg, state, t0, WARM, key)
        jax.block_until_ready(state.committed)
        c0 = int(state.committed)
        lat0 = jax.device_get(state.lat_hist)
        shaped = cfg.workload.shaped
        wait0 = jax.device_get(state.workload.wait_hist) if shaped else 0
        start = time.perf_counter()
        state, t = mp.run_ticks(
            cfg, state, t, MEAS, jax.random.fold_in(key, 1)
        )
        jax.block_until_ready(state.committed)
        dt = time.perf_counter() - start
        committed = int(state.committed) - c0
        lat_d = jax.device_get(state.lat_hist) - lat0
        inv = mp.check_invariants(cfg, state, t)
        row = {
            "leg": label,
            "ticks": MEAS,
            "committed": committed,
            "committed_per_tick": round(committed / MEAS, 2),
            "committed_per_sec": round(committed / dt, 1),
            "ticks_per_sec": round(MEAS / dt, 2),
            "p50_commit_latency_ticks": hist_pct(lat_d, 0.50),
            "p99_commit_latency_ticks": hist_pct(lat_d, 0.99),
            "invariants_ok": all(bool(v) for v in inv.values()),
        }
        if shaped:
            wait_d = jax.device_get(state.workload.wait_hist) - wait0
            summ = wl_mod.summary(cfg.workload, state.workload)
            wait_p99 = hist_pct(wait_d, 0.99)
            row.update(
                offered_rate_per_lane=round(
                    float(state.workload.rate), 4
                ),
                queue_depth_end=summ["queue_depth"],
                queue_wait_p50_ticks=hist_pct(wait_d, 0.50),
                queue_wait_p99_ticks=wait_p99,
                # Client-visible latency decomposes as queue wait
                # (arrival -> admission) + commit (admission ->
                # chosen); the p99 sum is the conservative roll-up the
                # monotonicity claim is gated on.
                p99_client_latency_ticks=(
                    max(wait_p99, 0) + max(
                        row["p99_commit_latency_ticks"], 0
                    )
                ),
                shed_total=summ["shed"],
                offered_total=summ["offered"],
                admitted_total=summ["admitted"],
            )
        if cfg.workload.closed:
            summ = wl_mod.summary(cfg.workload, state.workload)
            row.update(
                closed_window=cfg.workload.closed_window,
                in_flight_end=summ["in_flight"],
            )
        return row

    key = jax.random.PRNGKey(0)

    # 1. Saturation anchor: the none-plan flagship (today's headline
    # behavior) fixes the offered-load scale.
    cfg0 = base_cfg()
    sat = run_leg(cfg0, mp.init_state(cfg0), key, "saturation")
    sat_rate_lane = sat["committed_per_tick"] / G

    # 2. The offered-load matrix: ONE shaped config, the rate swept as
    # a traced state scalar — every leg replays the same compile.
    # Arrivals are UNIFORM across lanes here (zipf_s=0): at G=3334 a
    # Zipfian hot lane draws tens of times the mean rate and saturates
    # at every load fraction, which is its own (separate) leg below.
    plan = WorkloadPlan(
        arrival="constant", rate=sat_rate_lane, backlog_cap=256,
    )
    wcfg = base_cfg(workload=plan)
    matrix = []
    cache_before = None
    for frac in (0.25, 0.5, 0.9, 1.1):
        st = mp.init_state(wcfg)
        st = dataclasses.replace(
            st,
            workload=wl_mod.set_rate(
                st.workload, frac * sat_rate_lane
            ),
        )
        row = run_leg(
            wcfg, st, jax.random.fold_in(key, int(frac * 100)),
            f"{frac}x_saturation",
        )
        row["load_fraction"] = frac
        matrix.append(row)
        if cache_before is None:
            cache_before = mp.run_ticks._cache_size()
    retrace_clean = mp.run_ticks._cache_size() == cache_before
    p99s = [r["p99_client_latency_ticks"] for r in matrix]
    p99_monotone = all(a <= b for a, b in zip(p99s, p99s[1:])) and (
        p99s[-1] > p99s[0]
    )

    # 2b. Hot-key leg: the same 0.5x mean load, Zipf-skewed — the hot
    # lanes run past their lane-local saturation while the cold tail
    # idles (the key-skew story; a separate compile, zipf is static).
    hk_cfg = base_cfg(
        workload=WorkloadPlan(
            arrival="constant", rate=0.5 * sat_rate_lane, zipf_s=0.6,
            backlog_cap=256,
        )
    )
    hot_key = run_leg(
        hk_cfg, mp.init_state(hk_cfg), jax.random.fold_in(key, 5),
        "0.5x_hotkey_zipf0.6",
    )

    # 3. p99 under partition + burst: a minority acceptor cut through
    # the middle of the measured window while arrivals burst 3x.
    pb_cfg = base_cfg(
        workload=WorkloadPlan(
            arrival="bursty", rate=0.5 * sat_rate_lane,
            burst_every=64, burst_len=16, burst_mult=3.0,
            zipf_s=0.6, backlog_cap=256,
        ),
        faults=FaultPlan(
            partition=(0, 0, 1), partition_start=WARM + 50,
            partition_heal=WARM + 180,
        ),
    )
    pb = run_leg(
        pb_cfg, mp.init_state(pb_cfg), jax.random.fold_in(key, 7),
        "partition_plus_burst",
    )

    # 4. Closed loop: W_c clients per group, 4-tick think time — the
    # interactive-session shape (latency ~ protocol floor, throughput
    # window-bound).
    cl_cfg = base_cfg(
        workload=WorkloadPlan(closed_window=8, think_time=4)
    )
    cl = run_leg(
        cl_cfg, mp.init_state(cl_cfg), jax.random.fold_in(key, 9),
        "closed_loop",
    )

    # Predicted-vs-observed: the roofline model's pre-run saturation
    # forecast (ops/costmodel.py) recorded next to the measurement —
    # the observatory's ground-truth anchor. On the CPU host the
    # acceptance bar is within-2x; TPU exactness is hardware debt.
    from frankenpaxos_tpu.ops import costmodel

    on_tpu = jax.default_backend() in ("tpu", "axon")
    predicted = costmodel.predict_saturation(
        G, W, K, lat_min=cfg0.lat_min, lat_max=cfg0.lat_max,
        params=costmodel.TPU_V5E if on_tpu else costmodel.CPU_JIT,
    )
    predicted_vs_observed = {
        "predicted": predicted,
        "observed_committed_per_tick": sat["committed_per_tick"],
        "observed_committed_per_sec": sat["committed_per_sec"],
        "per_tick_ratio": round(
            sat["committed_per_tick"]
            / max(predicted["committed_per_tick"], 1e-9), 4
        ),
        "per_sec_ratio": round(
            sat["committed_per_sec"]
            / max(predicted["committed_per_sec"], 1e-9), 4
        ),
        "constants_version": costmodel.CONSTANTS_VERSION,
        "tpu_exactness_is_hardware_debt": on_tpu,
    }

    result = {
        "metric": (
            "flagship latency vs offered load under the in-graph "
            "workload engine"
        ),
        "backend": "multipaxos",
        "device": str(jax.devices()[0]),
        "num_acceptors": cfg0.num_acceptors,
        "saturation": sat,
        "predicted_saturation": predicted_vs_observed,
        "saturation_rate_per_lane_per_tick": round(sat_rate_lane, 4),
        "arrival_process": plan.arrival,
        "offered_load_matrix": matrix,
        "one_compile_per_mesh": retrace_clean,
        "p99_monotone_toward_saturation": p99_monotone,
        "hot_key_leg": hot_key,
        "partition_plus_burst": pb,
        "closed_loop": cl,
        "invariants_ok": all(
            r["invariants_ok"]
            for r in [sat, hot_key, pb, cl] + matrix
        ),
        "measured_live": True,
    }
    print("BENCH_JSON " + json.dumps(result))


def _serve_inner() -> None:
    """The serve-mode measurement (``--serve``): the flagship under the
    continuous serve loop (harness/serve.py — chunked dispatch with the
    double-buffered non-blocking telemetry drain). Three legs:

      1. batch baseline: plain back-to-back ``run_ticks`` segments at
         the same shape/chunk length (one sync at the end);
      2. serve steady state: the same ticks through ServeLoop with the
         drain + span sampler + scrape CSV live — drain overhead is the
         ticks/sec gap, budgeted < 2%;
      3. fault-injected SLO leg: shaped load near saturation + a
         degraded FaultPlan; the queue-wait p99 breaches the target,
         the SLO alarm fires, the control plane clamps admission
         through the traced rate, and the windowed p99 recovers.

    One JSON line on stdout (BENCH_JSON ...). Capture artifact:
    SERVE_r01.json."""
    import time

    import jax
    import jax.numpy as jnp

    from frankenpaxos_tpu.harness.serve import ServeConfig, ServeLoop
    from frankenpaxos_tpu.monitoring.slo import SloPolicy
    from frankenpaxos_tpu.monitoring import traceviz
    from frankenpaxos_tpu.tpu import multipaxos_batched as mp
    from frankenpaxos_tpu.tpu.faults import FaultPlan
    from frankenpaxos_tpu.tpu.workload import WorkloadPlan

    G, W, K = 3334, 64, 8
    CHUNK, CHUNKS, WARM_CHUNKS = 25, 10, 2

    def base_cfg(**kw) -> "mp.BatchedMultiPaxosConfig":
        return mp.BatchedMultiPaxosConfig(
            f=1, num_groups=G, window=W, slots_per_tick=K,
            lat_min=1, lat_max=3, retry_timeout=16, thrifty=True, **kw
        )

    # ---- 1. Batch baseline: the same chunked segment lengths,
    # back-to-back, one sync at the end — the pre-serve dispatch shape.
    cfg = base_cfg()
    key = jax.random.PRNGKey(0)
    state = mp.init_state(cfg)
    t = jnp.zeros((), jnp.int32)
    for i in range(WARM_CHUNKS):  # compile + steady-state warmup
        state, t = mp.run_ticks(cfg, state, t, CHUNK, jax.random.fold_in(key, i))
    jax.block_until_ready(state.committed)
    start = time.perf_counter()
    for i in range(CHUNKS):
        state, t = mp.run_ticks(
            cfg, state, t, CHUNK, jax.random.fold_in(key, 100 + i)
        )
    jax.block_until_ready(state.committed)
    batch_dt = time.perf_counter() - start
    batch_tps = CHUNKS * CHUNK / batch_dt

    # ---- 2a. Serve steady state (the drain-overhead budget leg): same
    # shape + chunk through the serve loop — the compiled program is
    # IDENTICAL to the batch baseline (spans=0: the sampler, like the
    # telemetry ring, is a feature knob with its own in-graph cost),
    # so the gap isolates the serve machinery itself: the per-chunk
    # snapshot copy, the double-buffered device_get, and the cursor
    # bookkeeping. The scrape CSV + span sampler ride the full-stack
    # leg below.
    serve_cfg = ServeConfig(
        chunk_ticks=CHUNK,
        telemetry_window=max(2 * CHUNK, 128),
        spans=0,
        max_chunks=WARM_CHUNKS + CHUNKS,
    )
    loop = ServeLoop(mp, cfg, serve_cfg, seed=0)
    report = loop.run()
    warm_ticks = WARM_CHUNKS * CHUNK
    serve_ticks = report["ticks"] - warm_ticks
    dspans = [s for s in loop.host_spans if s["name"] == "dispatch"]
    drains = [s for s in loop.host_spans if s["name"] == "drain"]
    # Steady-state ticks/sec: measure from the wall clock spanning the
    # post-warmup chunks (dispatch i completes during drain i, so the
    # chunk stream's envelope is dispatch start -> last drain end; the
    # warmup chunks absorb the XLA compile).
    t0 = dspans[WARM_CHUNKS]["start_unix"]
    t1 = drains[-1]["start_unix"] + drains[-1]["duration_s"]
    serve_tps = serve_ticks / max(t1 - t0, 1e-9)
    drain_overhead = 1.0 - serve_tps / batch_tps

    # ---- 2b. Full streaming stack (export evidence): a shorter run
    # with the scrape CSV + Perfetto trace export live; asserts the
    # trace carries BOTH device lifecycle spans and host dispatch
    # spans (the acceptance artifact).
    out_dir = os.path.join(_REPO, "results", "serve_bench")
    os.makedirs(out_dir, exist_ok=True)
    csv_path = os.path.join(out_dir, "serve_metrics.csv")
    trace_path = os.path.join(out_dir, "serve_trace.json")
    if os.path.exists(csv_path):
        os.remove(csv_path)
    full_cfg = ServeConfig(
        chunk_ticks=CHUNK,
        telemetry_window=max(2 * CHUNK, 128),
        spans=16,
        scrape_csv=csv_path,
        trace_path=trace_path,
        max_chunks=6,
    )
    full_loop = ServeLoop(mp, cfg, full_cfg, seed=0)
    full_report = full_loop.run()
    tr = traceviz.load_chrome_trace(trace_path)
    has_device = any(
        e.get("pid") == traceviz.DEVICE_PID and e.get("ph") == "X"
        for e in tr["traceEvents"]
    )
    has_host = any(
        e.get("pid") == traceviz.HOST_PID and e.get("ph") == "X"
        for e in tr["traceEvents"]
    )

    # ---- 3. Fault-injected SLO leg: shaped load near the saturation
    # rate + a degraded fault plan (drops + jitter eat throughput), so
    # the queue backs up and the windowed queue-wait p99 breaches the
    # target; the alarm clamps admission via the traced rate and the
    # p99 recovers.
    sat_rate_lane = float(jax.device_get(state.committed)) / (
        float(jax.device_get(t)) * G
    )
    slo_cfg = base_cfg(
        workload=WorkloadPlan(
            arrival="constant", rate=0.9 * sat_rate_lane,
            backlog_cap=512,
        ),
        faults=FaultPlan(drop_rate=0.3, jitter=2),
    )
    slo_serve = ServeConfig(
        chunk_ticks=CHUNK,
        telemetry_window=max(2 * CHUNK, 128),
        spans=0,
        slo=SloPolicy(
            p99_target_ticks=8, source="queue_wait",
            window_chunks=2, clear_after=2,
        ),
        max_chunks=40,
    )
    slo_loop = ServeLoop(mp, slo_cfg, slo_serve, seed=1)
    slo_report = slo_loop.run()
    hist = slo_loop.slo.history
    p99s = [h["p99"] for h in hist]
    fired_at = next(
        (i for i, h in enumerate(hist) if h["fired"]), None
    )
    p99_peak = max(p99s) if p99s else -1
    p99_final = p99s[-1] if p99s else -1
    # Recovery = after the first alarm, the clamp drove the windowed
    # p99 back to (or under) the target and the alarm CLEARED. The
    # controller keeps probing upward afterwards (multiplicative
    # recovery), so the FINAL sample may sit in a later probe cycle —
    # the claim is alarm -> clamp -> recovery, not a one-way lockdown.
    target = slo_serve.slo.p99_target_ticks
    recovered = fired_at is not None and any(
        h["cleared"] and p <= target  # -1 = queue fully drained
        for h, p in zip(hist[fired_at + 1:], p99s[fired_at + 1:])
    )
    result = {
        "metric": "flagship serve mode: chunked dispatch with "
        "non-blocking telemetry drain",
        "backend": "multipaxos",
        "device": str(jax.devices()[0]),
        "num_acceptors": cfg.num_acceptors,
        "chunk_ticks": CHUNK,
        "batch_ticks_per_sec": round(batch_tps, 2),
        "serve_ticks_per_sec": round(serve_tps, 2),
        "drain_overhead_fraction": round(drain_overhead, 4),
        "drain_overhead_under_2pct": drain_overhead < 0.02,
        "serve_report": {
            k: v for k, v in report.items() if k != "totals"
        },
        "committed_total": int(report["totals"]["commits"]),
        "dropped_ticks": report["dropped_ticks"],
        # The span sampler + scrape CSV leg (its own in-graph cost —
        # informational, like the telemetry-ring budget in --telemetry).
        "full_stack_leg": {
            k: v for k, v in full_report.items() if k != "totals"
        },
        "spans_exported": full_report["spans_exported"],
        "trace_has_device_spans": has_device,
        "trace_has_host_spans": has_host,
        "slo_leg": {
            "plan_rate_per_lane": round(0.9 * sat_rate_lane, 4),
            "fault_plan": {"drop_rate": 0.3, "jitter": 2},
            "p99_target_ticks": slo_serve.slo.p99_target_ticks,
            "alarm_fired": fired_at is not None,
            "fired_at_drain": fired_at,
            "alarms_fired": slo_loop.slo.alarms_fired,
            "clamps_applied": slo_loop.slo.clamps_applied,
            "p99_peak": p99_peak,
            "p99_final": p99_final,
            "p99_recovered_under_target": recovered,
            "final_scale": slo_loop.slo.scale,
            "p99_timeline": p99s,
            "scale_timeline": [h["scale"] for h in hist],
            "clean_shutdown": slo_report["clean_shutdown"],
        },
        "ok": (
            drain_overhead < 0.02
            and has_device
            and has_host
            and fired_at is not None
            and recovered
            and report["dropped_ticks"] == 0
            and full_report["dropped_ticks"] == 0
            and full_report["spans_exported"] > 0
        ),
        "measured_live": True,
    }
    print("BENCH_JSON " + json.dumps(result))


def _checkpoint_inner() -> None:
    """The crash-tolerance measurement (``--checkpoint``): the flagship
    under the serve loop with async checkpointing (tpu/checkpoint.py).
    Three legs:

      1. no-checkpoint serve baseline: ServeLoop at the 10k-acceptor
         flagship shape, checkpointing off;
      2. checkpointed serve: the same ticks with an async on-disk
         checkpoint at a production cadence (the alias-free snapshot
         enqueues behind chunk i; the device_get + serialization +
         disk write ride a writer thread overlapping later chunks) —
         checkpoint overhead is the ticks/sec gap, budgeted < 2%;
      3. recovery-evidence leg (small shape): an interrupted run
         resumes from its checkpoint and replays the uninterrupted
         twin sha256-identically, and a corrupted NEWEST checkpoint
         falls back to the previous valid one.

    One JSON line on stdout (BENCH_JSON ...). Capture artifact:
    CHECKPOINT_r01.json."""
    import shutil
    import tempfile
    import time

    import jax

    from frankenpaxos_tpu.harness.serve import ServeConfig, ServeLoop
    from frankenpaxos_tpu.tpu import checkpoint as checkpoint_mod
    from frankenpaxos_tpu.tpu import multipaxos_batched as mp
    from frankenpaxos_tpu.tpu.workload import WorkloadPlan

    G, W, K = 3334, 64, 8
    CHUNK, CHUNKS, WARM_CHUNKS = 25, 30, 2
    EVERY = 10  # chunks per checkpoint (~6 s of serve at this shape)
    cfg = mp.BatchedMultiPaxosConfig(
        f=1, num_groups=G, window=W, slots_per_tick=K,
        lat_min=1, lat_max=3, retry_timeout=16, thrifty=True,
    )

    def timed_serve(ckpt_dir, every):
        serve = ServeConfig(
            chunk_ticks=CHUNK,
            telemetry_window=max(2 * CHUNK, 128),
            spans=0,
            max_chunks=WARM_CHUNKS + CHUNKS,
            checkpoint_dir=ckpt_dir,
            checkpoint_every=every,
        )
        loop = ServeLoop(mp, cfg, serve, seed=0)
        report = loop.run()
        dspans = [s for s in loop.host_spans if s["name"] == "dispatch"]
        drains = [s for s in loop.host_spans if s["name"] == "drain"]
        t0 = dspans[WARM_CHUNKS]["start_unix"]
        t1 = drains[-1]["start_unix"] + drains[-1]["duration_s"]
        ticks = report["ticks"] - WARM_CHUNKS * CHUNK
        return report, ticks / max(t1 - t0, 1e-9)

    # ---- 1+2. Overhead: no-checkpoint vs checkpointed serve at a
    # production cadence (one durable snapshot every ~6 s at this
    # shape; the alias-free copy is the only device-side cost, the
    # serialization + disk write rides the writer thread).
    base_report, base_tps = timed_serve(None, 0)
    ck_dir = tempfile.mkdtemp(prefix="fpx_ckpt_bench_")
    ck_report, ck_tps = timed_serve(ck_dir, EVERY)
    overhead = 1.0 - ck_tps / base_tps
    state_bytes = sum(
        os.path.getsize(os.path.join(ck_dir, fn))
        for fn in os.listdir(ck_dir)
    )
    steps_on_disk = len(os.listdir(ck_dir)) // 2
    shutil.rmtree(ck_dir, ignore_errors=True)

    # ---- 3. Recovery evidence at a small shape: bit-exact resume +
    # corrupt-newest fallback (the same assertions the tier-1 tests
    # pin; repeated here so the capture artifact carries them).
    small = mp.BatchedMultiPaxosConfig(
        f=1, num_groups=8, window=16, slots_per_tick=2, retry_timeout=8,
        workload=WorkloadPlan(arrival="constant", rate=1.5),
    )
    d = tempfile.mkdtemp(prefix="fpx_ckpt_rec_")
    try:
        sv = dict(chunk_ticks=10, telemetry_window=32)
        twin = ServeLoop(
            mp, small,
            ServeConfig(max_chunks=8, **sv), seed=1,
        )
        twin.run()
        twin_digest = checkpoint_mod.state_digest(twin.state)
        ck2 = os.path.join(d, "ck")
        a = ServeLoop(
            mp, small,
            ServeConfig(
                max_chunks=5, checkpoint_dir=ck2, checkpoint_every=2,
                **sv,
            ),
            seed=1,
        )
        a.run()
        b = ServeLoop.resume(
            mp, small,
            ServeConfig(
                max_chunks=8, checkpoint_dir=ck2, checkpoint_every=2,
                **sv,
            ),
        )
        b.run()
        bit_exact = checkpoint_mod.state_digest(b.state) == twin_digest
        # Corrupt the newest checkpoint: flip bytes mid-npz; the loader
        # must fall back to the previous valid step.
        steps = checkpoint_mod.list_steps(ck2)
        newest = os.path.join(d, "ck", f"ckpt_{steps[-1]:08d}.npz")
        blob = bytearray(open(newest, "rb").read())
        blob[len(blob) // 2] ^= 0xFF
        open(newest, "wb").write(bytes(blob))
        found = checkpoint_mod.latest_valid(
            ck2, config_hash=checkpoint_mod.config_fingerprint(mp, small)
        )
        fallback_ok = (
            found is not None
            and found[0]["step"] == steps[-2]
            and found[0].get("skipped")
        )
    finally:
        shutil.rmtree(d, ignore_errors=True)

    result = {
        "metric": "flagship serve mode: async checkpoint overhead + "
        "bit-exact crash recovery",
        "backend": "multipaxos",
        "device": str(jax.devices()[0]),
        "num_acceptors": cfg.num_acceptors,
        "chunk_ticks": CHUNK,
        "checkpoint_every_chunks": EVERY,
        "checkpoint_period_s": round(EVERY * CHUNK / base_tps, 2),
        "base_ticks_per_sec": round(base_tps, 2),
        "checkpoint_ticks_per_sec": round(ck_tps, 2),
        "checkpoint_overhead_fraction": round(overhead, 4),
        "checkpoint_overhead_under_2pct": overhead < 0.02,
        "checkpoints_written": ck_report["checkpoints_written"],
        "checkpoint_steps_retained": steps_on_disk,
        "checkpoint_bytes_on_disk": state_bytes,
        "dropped_ticks": ck_report["dropped_ticks"],
        "recovery_leg": {
            "bit_exact_resume": bool(bit_exact),
            "corrupt_newest_falls_back": bool(fallback_ok),
        },
        "ok": (
            overhead < 0.02
            and bool(bit_exact)
            and bool(fallback_ok)
            and ck_report["dropped_ticks"] == 0
            and ck_report["checkpoints_written"] >= 3
        ),
        "measured_live": True,
    }
    print("BENCH_JSON " + json.dumps(result))


def _lifecycle_inner() -> None:
    """The production-lifecycle measurement (``--lifecycle``): the
    flagship under tpu/lifecycle.py. Three legs:

      1. unbounded-horizon leg: a run crossing >= 20x the slot-window
         length with window rotation on — the slot horizon (max head)
         stays bounded by one rotation quantum + W and the state byte
         footprint is flat across every segment, while the protocol
         history stays BIT-IDENTICAL to the unrotated twin (rebased);
      2. overhead leg: rotation + session table engaged vs
         LifecyclePlan.none() at the same shape — budget < 2%;
      3. reconfiguration leg: a mid-serve acceptor swap through the
         traced epoch axis — per-chunk commit throughput dips and
         recovers, with the jit cache pinned flat across both epoch
         changes.

    One JSON line on stdout (BENCH_JSON ...). Capture artifact:
    LIFECYCLE_r01.json."""
    import dataclasses
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from frankenpaxos_tpu.tpu import lifecycle as lifecycle_mod
    from frankenpaxos_tpu.tpu import multipaxos_batched as mp
    from frankenpaxos_tpu.tpu.common import state_nbytes
    from frankenpaxos_tpu.tpu.lifecycle import LifecyclePlan

    G, W, K = 256, 32, 4
    CHUNK, CHUNKS, WARM = 25, 8, 2
    ROT = 2 * W  # rotation quantum (align = W)

    def base_cfg(**kw) -> "mp.BatchedMultiPaxosConfig":
        return mp.BatchedMultiPaxosConfig(
            f=1, num_groups=G, window=W, slots_per_tick=K,
            lat_min=1, lat_max=3, retry_timeout=16, thrifty=True, **kw
        )

    def run_segments(cfg, n_chunks, seed=0, state=None, t=None,
                     per_chunk=None):
        key = jax.random.PRNGKey(seed)
        state = mp.init_state(cfg) if state is None else state
        t = jnp.zeros((), jnp.int32) if t is None else t
        for i in range(n_chunks):
            state, t = mp.run_ticks(
                cfg, state, t, CHUNK, jax.random.fold_in(key, i)
            )
            if per_chunk is not None:
                per_chunk(state)
        return state, t

    # ---- 1. Unbounded-horizon leg: >= 20x the window in constant
    # horizon + flat bytes, bit-identical to the unrotated twin.
    plan = LifecyclePlan(rotate_every=ROT, sessions=8,
                         resubmit_rate=0.05)
    cfg_l = base_cfg(lifecycle=plan)
    cfg_n = base_cfg()
    horizon_chunks = 26  # 650 ticks: next_slot crosses ~20x W easily
    max_heads, live_series = [], []

    def probe(st):
        max_heads.append(int(jax.device_get(jnp.max(st.head))))
        # LIVE process-wide buffer bytes (jax.live_arrays): the state
        # shapes are static, so the real constant-memory claim is that
        # nothing accumulates across rotations — donation keeps the
        # state single-buffered and no roll materializes extra
        # buffers. (state_nbytes alone is shape-derived and could
        # never vary.)
        live_series.append(
            sum(int(x.nbytes) for x in jax.live_arrays())
        )

    st_l, t_l = run_segments(cfg_l, horizon_chunks, seed=0,
                             per_chunk=probe)
    st_n, _ = run_segments(cfg_n, horizon_chunks, seed=0)
    base = int(jax.device_get(st_l.lifecycle.rot_base))
    twin_next = int(jax.device_get(jnp.max(st_n.next_slot)))
    # Bit-identity modulo the rebase, on the headline planes.
    ident = (
        bool(np.array_equal(
            jax.device_get(st_l.head) + base, jax.device_get(st_n.head)
        ))
        and bool(np.array_equal(
            jax.device_get(st_l.status), jax.device_get(st_n.status)
        ))
        and int(st_l.committed) == int(st_n.committed)
        and bool(np.array_equal(
            jax.device_get(st_l.lat_hist), jax.device_get(st_n.lat_hist)
        ))
    )
    inv = {
        k: bool(v)
        for k, v in mp.check_invariants(cfg_l, st_l, t_l).items()
    }
    horizon_leg = {
        "ticks": horizon_chunks * CHUNK,
        "window": W,
        "rotate_every": ROT,
        "rotations": int(jax.device_get(st_l.lifecycle.rot_count)),
        "rotated_slots": base,
        "slots_allocated_x_window": round(twin_next / W, 1),
        "max_head_rotated": max(max_heads),
        "horizon_bound": ROT + 2 * W,
        "horizon_constant": max(max_heads) < ROT + 2 * W,
        "state_bytes": state_nbytes(st_l),
        "live_bytes_first": live_series[0],
        "live_bytes_peak": max(live_series),
        # Flat = no growth across rotations beyond transient slack
        # (keys/probe scalars); a rotation path that materialized
        # extra buffers per roll would trip this.
        "device_bytes_flat": max(live_series)
        <= int(1.25 * live_series[0]),
        "bit_identical_to_unrotated_twin": ident,
        "session_cache_hits": int(
            jax.device_get(st_l.lifecycle.cache_hits)
        ),
        "invariants_ok": all(inv.values()),
    }

    # ---- 2. Overhead leg: lifecycle engaged vs none at the FLAGSHIP
    # shape (the budget is a serve-deployment claim — at toy shapes
    # the subsystem's fixed per-tick scalars dominate a sub-ms tick
    # and the fraction is meaningless). The rotation quantum is the
    # production-ish 8x window (the horizon leg above stresses an
    # aggressive 2x quantum — ~80 rolls in 650 ticks — to pin
    # exactness; the lax.cond rebase costs only on roll ticks).
    FG = 3334  # the bench.py flagship group count (10k acceptors)
    overhead_plan = LifecyclePlan(
        rotate_every=8 * W, sessions=8, resubmit_rate=0.05
    )

    def fcfg(**kw):
        return mp.BatchedMultiPaxosConfig(
            f=1, num_groups=FG, window=W, slots_per_tick=K,
            lat_min=1, lat_max=3, retry_timeout=16, thrifty=True, **kw
        )

    def warm(cfg, seed):
        key = jax.random.PRNGKey(seed)
        state = mp.init_state(cfg)
        t = jnp.zeros((), jnp.int32)
        for i in range(WARM):  # compile + steady-state warmup
            state, t = mp.run_ticks(
                cfg, state, t, CHUNK, jax.random.fold_in(key, i)
            )
        jax.block_until_ready(state.committed)
        return key, state, t

    def timed_pass(cfg, run, rep):
        key, state, t = run
        start = time.perf_counter()
        for i in range(4):
            state, t = mp.run_ticks(
                cfg, state, t, CHUNK,
                jax.random.fold_in(key, 10 + 4 * rep + i),
            )
        jax.block_until_ready(state.committed)
        return time.perf_counter() - start, (key, state, t)

    # INTERLEAVED best-of-5: the two configs' timed passes alternate,
    # so slow drift on a small shared-CPU host hits both columns
    # instead of biasing whichever ran second, and the min-of-5
    # converges on each program's true floor (run-to-run noise on this
    # box is on the order of the budget itself).
    cfg_none, cfg_lc = fcfg(), fcfg(lifecycle=overhead_plan)
    run_none = warm(cfg_none, seed=10)
    run_lc = warm(cfg_lc, seed=10)
    best_none = best_lc = float("inf")
    for rep in range(5):
        dt, run_none = timed_pass(cfg_none, run_none, rep)
        best_none = min(best_none, dt)
        dt, run_lc = timed_pass(cfg_lc, run_lc, rep)
        best_lc = min(best_lc, dt)
    none_tps = 4 * CHUNK / best_none
    lc_tps = 4 * CHUNK / best_lc
    overhead = 1.0 - lc_tps / none_tps

    # ---- 3. Reconfiguration leg: mid-serve acceptor swap, zero
    # recompiles, throughput dips and recovers.
    cfg_r = base_cfg(
        lifecycle=LifecyclePlan(rotate_every=ROT, reconfig=True)
    )
    st, t = run_segments(cfg_r, WARM, seed=20)
    cache0 = mp.run_ticks._cache_size()

    def commits_over(n, seed, state, t):
        c0 = int(jax.device_get(state.committed))
        state, t = run_segments(cfg_r, n, seed=seed, state=state, t=t)
        return (
            (int(jax.device_get(state.committed)) - c0) / (n * CHUNK),
            state, t,
        )

    healthy, st, t = commits_over(4, 21, st, t)
    st = dataclasses.replace(
        st, lifecycle=lifecycle_mod.swap_acceptor(st.lifecycle, 1)
    )
    degraded, st, t = commits_over(2, 22, st, t)
    st = dataclasses.replace(
        st, lifecycle=lifecycle_mod.set_membership(st.lifecycle, True)
    )
    recovered, st, t = commits_over(4, 23, st, t)
    cache_flat = mp.run_ticks._cache_size() == cache0
    inv_r = {
        k: bool(v)
        for k, v in mp.check_invariants(cfg_r, st, t).items()
    }
    reconfig_leg = {
        "healthy_commits_per_tick": round(healthy, 2),
        "swapped_commits_per_tick": round(degraded, 2),
        "recovered_commits_per_tick": round(recovered, 2),
        "dipped": degraded < healthy,
        "recovered": recovered > 0.9 * healthy,
        "jit_cache_flat_across_epochs": cache_flat,
        "epochs_applied": int(jax.device_get(st.lifecycle.applied)),
        "old_epochs_gcd": int(jax.device_get(st.lifecycle.epochs_gcd)),
        "invariants_ok": all(inv_r.values()),
    }

    result = {
        "metric": "flagship production lifecycle: window rotation + "
        "session table + traced reconfiguration",
        "backend": "multipaxos",
        "device": str(jax.devices()[0]),
        "num_acceptors": cfg_l.num_acceptors,
        "horizon_leg": horizon_leg,
        "overhead_leg": {
            "num_groups": FG,
            "rotate_every": overhead_plan.rotate_every,
            "sessions": overhead_plan.sessions,
            "none_ticks_per_sec": round(none_tps, 2),
            "lifecycle_ticks_per_sec": round(lc_tps, 2),
            "overhead_fraction": round(overhead, 4),
            "overhead_under_2pct": overhead < 0.02,
        },
        "reconfig_leg": reconfig_leg,
        "ok": (
            horizon_leg["horizon_constant"]
            and horizon_leg["device_bytes_flat"]
            and horizon_leg["bit_identical_to_unrotated_twin"]
            and horizon_leg["slots_allocated_x_window"] >= 20
            and horizon_leg["invariants_ok"]
            and overhead < 0.02
            and reconfig_leg["dipped"]
            and reconfig_leg["recovered"]
            and reconfig_leg["jit_cache_flat_across_epochs"]
            and reconfig_leg["invariants_ok"]
        ),
        "measured_live": True,
    }
    print("BENCH_JSON " + json.dumps(result))


def _sessions_inner() -> None:
    """The million-session serving measurement (``--sessions``): one
    flagship brick at the [L=1024 lanes x S=1024 sessions] shape —
    1,048,576 distinct session-table slots — with bit-packed planes
    (tpu/packing.py) and the trace-driven open-loop arrival source.
    Four legs:

      1. headline trace leg: a recorded 1,048,576-event trace replays
         through ONE compiled brick — every event admitted exactly
         once (offered == cursor == trace_len), >= 1e6 DISTINCT
         sessions live at drain, duplicate re-submissions answered
         from the cache, the conservation books exact (lifecycle_ok),
         at measured entries/sec;
      2. packing leg: packed vs unpacked twins interleave-timed at the
         same shape — per-plane stored bytes (packed / unpacked /
         widened int32 reference) + the throughput ratio, committed
         counts equal (the bit-identity spot check);
      3. saturation matrix: the traced-rate axis swept on the SAME
         executable (workload.set_rate — zero recompiles) — offered
         vs committed per tick at the 1M-session shape;
      4. sharded leg (8-virtual-device 'groups' mesh): the session
         table partitions P('groups') instead of replicating, a
         mid-run checkpoint/restore (PR 13) replays the uninterrupted
         sharded twin bit-exactly.

    One JSON line on stdout (BENCH_JSON ...). Capture artifact:
    SESSIONS_r01.json."""
    import dataclasses
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from frankenpaxos_tpu.harness.microbench import (
        _packed_plane_bytes,
        measure_packing_overhead,
    )
    from frankenpaxos_tpu.tpu import checkpoint as ck
    from frankenpaxos_tpu.tpu import lifecycle as lifecycle_mod
    from frankenpaxos_tpu.tpu import multipaxos_batched as mp
    from frankenpaxos_tpu.tpu import packing
    from frankenpaxos_tpu.tpu import workload as workload_mod
    from frankenpaxos_tpu.tpu.lifecycle import LifecyclePlan
    from frankenpaxos_tpu.tpu.workload import WorkloadPlan

    L, S = 1024, 1024  # lanes x sessions = 1,048,576 distinct slots
    N = L * S  # one trace event per session slot
    CHUNK = 1024  # trace decode chunk (the validate() ceiling)

    def base_cfg(**kw):
        return mp.BatchedMultiPaxosConfig(
            f=1, num_groups=L, window=16, slots_per_tick=2,
            lat_min=1, lat_max=3, retry_timeout=16, thrifty=True,
            pack_planes=True, **kw
        )

    # ---- 1. Headline: the 1M-event trace through one brick.
    # Arrivals spread at CHUNK/tick (the decode-chunk ceiling);
    # admission at 2/lane/tick outpaces them, so the drain tail is
    # short. Lane ids round-robin so every lane receives exactly S
    # events == S distinct sessions.
    ev = np.arange(N, dtype=np.int64)
    words = packing.encode_trace(ev // CHUNK, ev % L)
    plan = WorkloadPlan(
        arrival="trace", trace_len=N, trace_chunk=CHUNK
    )
    cfg = base_cfg(
        workload=plan,
        lifecycle=LifecyclePlan(sessions=S, resubmit_rate=0.02),
    )
    st = mp.init_state(cfg)
    st = dataclasses.replace(
        st, workload=workload_mod.load_trace(st.workload, words)
    )
    t = jnp.zeros((), jnp.int32)
    key = jax.random.PRNGKey(0)
    seg = 120
    st, t = mp.run_ticks(cfg, st, t, seg, key)  # compile + first leg
    jax.block_until_ready(st.committed)
    cache0 = mp.run_ticks._cache_size()
    start = time.perf_counter()
    ticks = seg
    # Drain criterion: the whole trace fired AND every one of the N
    # commands committed into its session record.
    while (
        int(jax.device_get(st.workload.trace_cursor)) < N
        or int(jax.device_get(jnp.sum(st.lifecycle.sess_total))) < N
    ) and ticks < 4096:
        st, t = mp.run_ticks(
            cfg, st, t, seg, jax.random.fold_in(key, ticks)
        )
        ticks += seg
    jax.block_until_ready(st.committed)
    elapsed = time.perf_counter() - start
    inv = {
        k: bool(v) for k, v in mp.check_invariants(cfg, st, t).items()
    }
    distinct = int(
        jax.device_get(lifecycle_mod.live_sessions(cfg.lifecycle,
                                                   st.lifecycle))
    )
    trace_leg = {
        "lanes": L,
        "sessions_per_lane": S,
        "trace_events": N,
        "ticks": ticks,
        "entries_per_sec": round(N / elapsed, 1),
        "offered": int(jax.device_get(st.workload.offered)),
        "trace_cursor": int(jax.device_get(st.workload.trace_cursor)),
        "distinct_sessions_live": distinct,
        "cache_hits": int(jax.device_get(st.lifecycle.cache_hits)),
        "resubmits": int(jax.device_get(st.lifecycle.resubmits)),
        "books_reconciled": int(
            jax.device_get(jnp.sum(st.lifecycle.sess_total))
        ) == int(jax.device_get(st.committed)),
        "exactly_once": (
            int(jax.device_get(st.workload.offered)) == N
            and int(jax.device_get(st.workload.trace_cursor)) == N
            and int(jax.device_get(jnp.sum(st.workload.adm_total))) == N
        ),
        "one_compile_per_mesh": mp.run_ticks._cache_size() == cache0,
        "invariants_ok": all(inv.values()),
    }

    # ---- 2. Packing leg: packed vs unpacked twins, plus the widened
    # int32 reference the dtype policy debugs against.
    cfg_pk = base_cfg(
        lifecycle=LifecyclePlan(sessions=S, resubmit_rate=0.02)
    )
    pk = measure_packing_overhead(cfg_pk, 60, rounds=3)
    st_w = mp.init_state(dataclasses.replace(cfg_pk, pack_planes=False))
    # The widened reference: every logical element stored as int32 (the
    # dtype a naive lane-major layout would pick).
    widened = {
        "status": int(st_w.status.size) * 4,
        "rb_status": int(st_w.rb_status.size) * 4,
        "sess_occ": L * S * 4,
    }
    packing_leg = {
        "ticks_per_sec": {
            c: round(r, 2) for c, r in pk["rates"].items()
        },
        "throughput_ratio": round(pk["ratio"], 4),
        "plane_bytes": {**pk["plane_bytes"], "widened": widened},
        "bytes_saved_vs_unpacked": pk["bytes_saved"],
        "bytes_saved_vs_widened": sum(widened.values())
        - sum(pk["plane_bytes"]["packed"].values()),
        "committed_equal": pk["committed"]["packed"]
        == pk["committed"]["unpacked"],
    }

    # ---- 3. Saturation matrix: traced-rate sweep, one executable.
    cfg_m = base_cfg(
        workload=WorkloadPlan(arrival="constant", rate=1.0, zipf_s=0.8),
        lifecycle=LifecyclePlan(sessions=S, resubmit_rate=0.02),
    )
    st_m = mp.init_state(cfg_m)
    t_m = jnp.zeros((), jnp.int32)
    # Warm with the SAME static segment length the sweep uses —
    # num_ticks is a static arg, so a different length is a recompile.
    st_m, t_m = mp.run_ticks(cfg_m, st_m, t_m, 60, key)
    cache_m = mp.run_ticks._cache_size()
    matrix = []
    for rate in (0.5, 1.0, 2.0, 4.0):
        st_m = dataclasses.replace(
            st_m,
            workload=workload_mod.set_rate(st_m.workload, rate),
        )
        c0 = int(jax.device_get(st_m.committed))
        o0 = int(jax.device_get(st_m.workload.offered))
        st_m, t_m = mp.run_ticks(
            cfg_m, st_m, t_m, 60, jax.random.fold_in(key, int(rate * 8))
        )
        matrix.append({
            "rate_per_lane": rate,
            "offered_per_tick": round((int(
                jax.device_get(st_m.workload.offered)) - o0) / 60, 1),
            "committed_per_tick": round((int(
                jax.device_get(st_m.committed)) - c0) / 60, 1),
        })
    matrix_leg = {
        "rows": matrix,
        "one_compile_per_mesh": mp.run_ticks._cache_size() == cache_m,
        # Saturation: the highest swept rate runs into the admission
        # ceiling (committed/tick stops tracking offered/tick).
        "saturated": matrix[-1]["committed_per_tick"]
        < matrix[-1]["offered_per_tick"],
    }

    # ---- 4. Sharded leg: groups-partitioned session table +
    # checkpoint/resume == the uninterrupted sharded twin.
    sharded_leg = {"devices": jax.device_count()}
    if jax.device_count() >= 2:
        import tempfile

        from frankenpaxos_tpu.parallel import sharding as sh

        mesh = sh.make_mesh(jax.devices())
        seg_s = 60

        def fresh():
            s0 = mp.init_state(cfg)
            s0 = dataclasses.replace(
                s0,
                workload=workload_mod.load_trace(s0.workload, words),
            )
            return sh.shard_state("multipaxos", s0, mesh)

        tw, tt = sh.run_ticks_sharded(
            "multipaxos", cfg, mesh, fresh(), jnp.zeros((), jnp.int32),
            seg_s, key,
        )
        tw, tt = sh.run_ticks_sharded(
            "multipaxos", cfg, mesh, tw, tt, seg_s, key
        )
        s1, t1 = sh.run_ticks_sharded(
            "multipaxos", cfg, mesh, fresh(), jnp.zeros((), jnp.int32),
            seg_s, key,
        )
        with tempfile.TemporaryDirectory() as d:
            ck.save_state(d, mp, cfg, s1, t1, step=0)
            s2, t2, _ = ck.restore_state(d, mp, cfg, fresh())
        s2 = sh.shard_state("multipaxos", s2, mesh)
        s2, t2 = sh.run_ticks_sharded(
            "multipaxos", cfg, mesh, s2, t2, seg_s, key
        )
        occ = sh.shard_state("multipaxos", mp.init_state(cfg), mesh)
        sharded_leg.update({
            "session_table_partitioned": sh.GROUP_AXIS in tuple(
                occ.lifecycle.sess_occ.sharding.spec
            ),
            "resume_bit_exact": ck.state_digest(s2)
            == ck.state_digest(tw),
            "resume_tick_equal": int(t2) == int(tt),
        })

    result = {
        "metric": "million-session serving: packed planes + "
        "group-sharded session table + trace-driven open loop",
        "backend": "multipaxos",
        "device": str(jax.devices()[0]),
        "num_acceptors": cfg.num_acceptors,
        "trace_leg": trace_leg,
        "packing_leg": packing_leg,
        "saturation_matrix": matrix_leg,
        "sharded_leg": sharded_leg,
        "ok": (
            trace_leg["distinct_sessions_live"] >= 1_000_000
            and trace_leg["exactly_once"]
            and trace_leg["books_reconciled"]
            and trace_leg["cache_hits"] > 0
            and trace_leg["one_compile_per_mesh"]
            and trace_leg["invariants_ok"]
            and packing_leg["committed_equal"]
            and packing_leg["bytes_saved_vs_widened"] > 0
            and matrix_leg["one_compile_per_mesh"]
            and sharded_leg.get("resume_bit_exact", True)
            and sharded_leg.get("session_table_partitioned", True)
        ),
        "measured_live": True,
    }
    print("BENCH_JSON " + json.dumps(result))


def _elastic_inner() -> None:
    """The elastic-capacity measurement (``--elastic``): the serve
    loop under the SLO-driven autoscaler ladder (tpu/elastic.py +
    monitoring/autoscaler.py). Two legs over the flagship with a
    padded 8-group elastic plane and the session-table lifecycle on:

      1. diurnal leg: a 24h-compressed day (night trough -> morning
         ramp -> midday saturating burst -> evening trough) served
         with role counts seeded at the floor — the burst's p99 alarm
         GROWS active groups (traced resize verbs, zero recompiles),
         the evening trough drains and shrinks them back, p99 returns
         under target, and the exactly-once session books stay exact
         across every resize;
      2. fault leg: a degraded FaultPlan eats protocol capacity
         mid-run — the ladder first absorbs the breach by scaling out
         (what a clamp alone could not: admission is never refused
         while padded capacity remains), engages the admission clamp
         only once the role plane is exhausted, and on recovery
         releases the clamp BEFORE giving capacity back.

    One JSON line on stdout (BENCH_JSON ...). Capture artifact:
    results/ELASTIC_r01.json."""
    import dataclasses

    import jax

    from frankenpaxos_tpu.harness import serve as serve_mod
    from frankenpaxos_tpu.monitoring.autoscaler import AutoscalerPolicy
    from frankenpaxos_tpu.monitoring.slo import SloPolicy
    from frankenpaxos_tpu.tpu import multipaxos_batched as mp
    from frankenpaxos_tpu.tpu.elastic import ElasticPlan
    from frankenpaxos_tpu.tpu.faults import FaultPlan
    from frankenpaxos_tpu.tpu.lifecycle import LifecyclePlan
    from frankenpaxos_tpu.tpu.workload import WorkloadPlan

    G, CAP, FLOOR = 8, 8, 2
    P99_TARGET = 12

    def build_loop(seed, faults=None, out_tag="diurnal"):
        cfg = mp.BatchedMultiPaxosConfig(
            f=1, num_groups=G, window=16, slots_per_tick=2,
            retry_timeout=16,
            workload=WorkloadPlan(
                arrival="constant", rate=0.5, backlog_cap=256
            ),
            elastic=ElasticPlan(roles=(("groups", CAP, FLOOR),)),
            lifecycle=LifecyclePlan(sessions=64, resubmit_rate=0.02),
            **({"faults": faults} if faults is not None else {}),
        )
        serve_cfg = serve_mod.ServeConfig(
            chunk_ticks=16, telemetry_window=64,
            max_chunks=1,  # run_phase extends this per phase
            slo=SloPolicy(
                p99_target_ticks=P99_TARGET, source="queue_wait"
            ),
            autoscaler=AutoscalerPolicy(
                cooldown_drains=0, trough_after=3
            ),
            scrape_csv=os.path.join(
                _REPO, "results", f"elastic_{out_tag}_metrics.csv"
            ),
        )
        try:
            os.remove(serve_cfg.scrape_csv)
        except OSError:
            pass
        return serve_mod.ServeLoop(
            mp, cfg, serve_cfg, seed=seed,
            elastic_initial={"groups": FLOOR},
        )

    def run_phase(loop, chunks, rate):
        loop.set_base_rate(rate)
        loop.serve = dataclasses.replace(
            loop.serve, max_chunks=loop._chunks + chunks
        )
        return loop.run()

    def drains_of(loop, n_last):
        return loop.drains[-n_last:]

    # ---- 1. Diurnal leg: the fleet breathes with the compressed day.
    # Total offered load is rate x G lanes rerouted onto the ACTIVE
    # groups, so the burst (1.75 x 8 = 14/tick) saturates 2 groups
    # (admission cap 2/lane/tick) but fits 8 comfortably.
    loop = build_loop(seed=0)
    run_phase(loop, 6, 0.4)  # 00-06h: night trough at the floor
    cache_after_warm = mp.run_ticks._cache_size()
    run_phase(loop, 6, 1.0)   # 06-12h: morning ramp
    run_phase(loop, 14, 1.75)  # 12-18h: saturating burst
    burst_tail = [
        d["slo"]["p99"] for d in drains_of(loop, 3)
    ]
    report = run_phase(loop, 14, 0.4)  # 18-24h: evening trough
    trough_tail = [
        d["slo"]["p99"] for d in drains_of(loop, 3)
    ]
    cache_at_end = mp.run_ticks._cache_size()
    asum = report["autoscaler"]
    inv = {
        k: bool(v)
        for k, v in jax.device_get(
            mp.check_invariants(loop.cfg, loop.state, loop.t)
        ).items()
    }
    diurnal_leg = {
        "phases_hours": [[0, 6, 0.4], [6, 12, 1.0], [12, 18, 1.75],
                         [18, 24, 0.4]],
        "scale_up_events": asum["scale_up_events"],
        "scale_down_events": asum["scale_down_events"],
        "events": asum["events"],
        "elastic": report["elastic"],
        "p99_target_ticks": P99_TARGET,
        "burst_steady_p99": burst_tail,
        "trough_steady_p99": trough_tail,
        "p99_under_target_steady": all(
            0 <= p <= P99_TARGET for p in burst_tail + trough_tail
        ),
        "one_compile_per_mesh": (
            cache_after_warm == cache_at_end == 1
        ),
        "invariants": inv,
        "session_books_exact": bool(
            inv.get("lifecycle_ok", False)
            and inv.get("elastic_ok", False)
            and inv.get("workload_ok", False)
        ),
        "lifecycle": report.get("lifecycle", {}),
        "slo": report["slo"],
    }

    # ---- 2. Fault leg: the ladder in order. Drop faults eat protocol
    # capacity mid-burst; scale-out absorbs what it can, the clamp
    # binds only at capacity exhaustion, release precedes shrink.
    loop = build_loop(seed=1, faults=FaultPlan(traced=True),
                      out_tag="fault")
    run_phase(loop, 6, 1.0)  # healthy warmup below target
    loop.set_fault_rates(drop=0.5)  # the injected degradation
    run_phase(loop, 18, 1.75)  # burst under faults: grow, then clamp
    loop.set_fault_rates(drop=0.0)  # fault clears
    report_f = run_phase(loop, 18, 0.4)  # recovery + trough
    fsum = report_f["autoscaler"]
    kinds = [e["kind"] for e in fsum["events"]]

    def first(kind):
        return kinds.index(kind) if kind in kinds else None

    def last(kind):
        return (
            len(kinds) - 1 - kinds[::-1].index(kind)
            if kind in kinds
            else None
        )

    ladder_in_order = (
        first("scale_up") is not None
        and first("clamp_engage") is not None
        and first("clamp_release") is not None
        and first("scale_up") < first("clamp_engage")
        # The clamp binds only after the role plane is exhausted: every
        # scale-up that precedes the first engage happened first.
        and all(
            k != "scale_up" or i < first("clamp_engage")
            for i, k in enumerate(kinds[: first("clamp_engage")])
        )
        and (
            first("scale_down") is None
            or first("clamp_release") < first("scale_down")
        )
    )
    fault_leg = {
        "events": fsum["events"],
        "event_kinds": kinds,
        "scale_up_events": fsum["scale_up_events"],
        "clamp_engagements": fsum["clamp_engagements"],
        "clamp_releases": fsum["clamp_releases"],
        "scale_down_events": fsum["scale_down_events"],
        "ladder_in_order": ladder_in_order,
        "clamp_alone_could_not": (
            # Capacity the clamp cannot create: the scale-outs that
            # absorbed load before ANY admission was refused.
            first("scale_up") is not None
            and first("clamp_engage") is not None
            and fsum["scale_up_events"] > 0
        ),
        "elastic": report_f["elastic"],
        "slo": report_f["slo"],
    }

    result = {
        "metric": "elastic capacity: SLO-driven live resize of role "
        "planes (scale out under duress, clamp as last resort)",
        "backend": "multipaxos",
        "device": str(jax.devices()[0]),
        "elastic_plan": {"groups": {"capacity": CAP, "floor": FLOOR}},
        "diurnal_leg": diurnal_leg,
        "fault_leg": fault_leg,
        "ok": (
            diurnal_leg["scale_up_events"] >= 2
            and diurnal_leg["scale_down_events"] >= 2
            and diurnal_leg["p99_under_target_steady"]
            and diurnal_leg["one_compile_per_mesh"]
            and diurnal_leg["session_books_exact"]
            and all(diurnal_leg["invariants"].values())
            and fault_leg["ladder_in_order"]
        ),
        "measured_live": True,
    }
    with open(
        os.path.join(_REPO, "results", "ELASTIC_r01.json"), "w"
    ) as f:
        json.dump(result, f, indent=1)
    print("BENCH_JSON " + json.dumps(result))


def _depgraph_inner() -> None:
    """The dependency-graph measurement (``--depgraph``): the
    XLA-native bitmask SCC executor (ops/depgraph.py) in two legs.

      1. executor leg: batched bitmask closure vs the sequential
         pointer-walk twin at the flagship window shape
         (harness/microbench.bench_depgraph — interleaved best-of-N,
         bit-identity asserted against the host Tarjan oracle before
         any timing; the ISSUE floor is a 1.3x CPU speedup, the TPU
         number stays on the hardware-debt list);
      2. surface leg: the [conflict x Zipf] density surface on the
         bpaxos backend — conflict_rate rides WorkloadState as a
         traced scalar, so the whole conflict axis replays ONE
         compiled program per Zipf level (set_conflict_rate, no
         retrace), and the executed/co-executed totals show dense
         graphs batching into SCC closures instead of stalling.

    One JSON line on stdout (BENCH_JSON ...). Capture artifact:
    results/DEPGRAPH_r01.json."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from frankenpaxos_tpu.harness import microbench
    from frankenpaxos_tpu.tpu import bpaxos_batched as bp
    from frankenpaxos_tpu.tpu import workload as workload_mod
    from frankenpaxos_tpu.tpu.workload import WorkloadPlan

    # ---- 1. Executor leg (flagship shape: batch 208 x window 64).
    rows = microbench.bench_depgraph()
    by_case = {r["case"]: r for r in rows if r["name"] == "depgraph"}
    closure = by_case["bitmask_closure"]["ops_per_sec"]
    walk = by_case["pointer_walk"]["ops_per_sec"]
    speedup = closure / walk
    executor_leg = {
        "rows": rows,
        "closure_ops_per_sec": closure,
        "pointer_walk_ops_per_sec": walk,
        "speedup": round(speedup, 4),
        "floor": 1.3,
        "bit_identity": "asserted in bench_depgraph before timing",
    }

    # ---- 2. Surface leg: [conflict x Zipf] on bpaxos. Zipf skew is a
    # trace-time plan constant (one compile per level); the conflict
    # axis is traced state (zero recompiles along it).
    CONFLICTS = (0.0, 0.25, 0.5, 0.75, 1.0)
    ZIPFS = (0.0, 1.0)
    TICKS = 200
    surface = []
    one_compile_per_zipf = True
    for zipf_s in ZIPFS:
        plan = WorkloadPlan(
            arrival="constant", rate=1.5, zipf_s=zipf_s,
            conflict_rate=CONFLICTS[0],
        )
        cfg = bp.analysis_config(workload=plan)
        cache0 = bp.run_ticks._cache_size()
        for conflict in CONFLICTS:
            st = bp.init_state(cfg)
            st = dataclasses.replace(
                st,
                workload=workload_mod.set_conflict_rate(
                    st.workload, conflict
                ),
            )
            st, t = bp.run_ticks(
                cfg, st, jnp.zeros((), jnp.int32), TICKS,
                jax.random.PRNGKey(7),
            )
            inv = bp.check_invariants(cfg, st, t)
            surface.append({
                "zipf_s": zipf_s,
                "conflict_rate": conflict,
                "committed": int(st.committed_total),
                "executed": int(st.executed_total),
                "coexecuted": int(st.coexecuted),
                "retired": int(st.retired_total),
                "invariants_ok": all(bool(v) for v in inv.values()),
            })
        one_compile_per_zipf &= (
            bp.run_ticks._cache_size() == cache0 + 1
        )

    def cell(zipf_s, conflict):
        return next(
            r for r in surface
            if r["zipf_s"] == zipf_s and r["conflict_rate"] == conflict
        )

    density_ordered = all(
        cell(z, 0.0)["executed"] > cell(z, 1.0)["executed"] > 0
        for z in ZIPFS
    )
    scc_fires_when_dense = all(
        cell(z, 1.0)["coexecuted"] > cell(z, 0.0)["coexecuted"]
        for z in ZIPFS
    )
    surface_leg = {
        "backend": "bpaxos",
        "ticks_per_cell": TICKS,
        "cells": surface,
        "one_compile_per_zipf_level": one_compile_per_zipf,
        "density_ordered": density_ordered,
        "scc_fires_when_dense": scc_fires_when_dense,
    }

    result = {
        "metric": "depgraph: batched bitmask SCC closure vs "
        "sequential pointer walk + the [conflict x Zipf] surface",
        "device": str(jax.devices()[0]),
        "executor_leg": executor_leg,
        "surface_leg": surface_leg,
        "ok": (
            speedup >= 1.3
            and all(r["invariants_ok"] for r in surface)
            and density_ordered
            and scc_fires_when_dense
            and one_compile_per_zipf
        ),
        "measured_live": True,
    }
    with open(
        os.path.join(_REPO, "results", "DEPGRAPH_r01.json"), "w"
    ) as f:
        json.dump(result, f, indent=1)
    print("BENCH_JSON " + json.dumps(result))


def _subprocess_mode_main(inner_flag: str, metric: str, env: dict) -> None:
    """Shared orchestrator for the standalone bench modes (--workload,
    --multichip): run this script's inner mode in a clean subprocess,
    scrape the last BENCH_JSON line, print exactly one JSON line (a
    failure row with the stderr tail otherwise), exit 0."""
    argv = [sys.executable, os.path.abspath(__file__), inner_flag]
    try:
        proc = subprocess.run(
            argv, env=env, cwd=_REPO, capture_output=True, text=True,
            timeout=1800.0,
        )
    except subprocess.TimeoutExpired:
        print(json.dumps({
            "metric": metric, "ok": False, "notes": "timeout after 1800s",
        }))
        sys.exit(0)
    for line in reversed(proc.stdout.splitlines()):
        if line.startswith("BENCH_JSON "):
            print(line[len("BENCH_JSON "):])
            sys.exit(0)
    tail = (proc.stderr or proc.stdout or "").strip().splitlines()[-6:]
    print(json.dumps({
        "metric": metric,
        "ok": False,
        "notes": f"rc={proc.returncode}: " + " | ".join(tail),
    }))
    sys.exit(0)


def _workload_main() -> None:
    """Orchestrate the workload measurement in a clean CPU subprocess;
    print exactly one JSON line, exit 0."""
    _subprocess_mode_main(
        "--inner-workload", "flagship latency vs offered load", _cpu_env()
    )


def _serve_main() -> None:
    """Orchestrate the serve measurement in a clean CPU subprocess;
    print exactly one JSON line, exit 0."""
    _subprocess_mode_main(
        "--inner-serve",
        "flagship serve mode: chunked dispatch with non-blocking "
        "telemetry drain",
        _cpu_env(),
    )


def _checkpoint_main() -> None:
    """Orchestrate the checkpoint measurement in a clean CPU
    subprocess; print exactly one JSON line, exit 0."""
    _subprocess_mode_main(
        "--inner-checkpoint",
        "flagship serve mode: async checkpoint overhead + bit-exact "
        "crash recovery",
        _cpu_env(),
    )


def _lifecycle_main() -> None:
    """Orchestrate the lifecycle measurement in a clean CPU subprocess;
    print exactly one JSON line, exit 0."""
    _subprocess_mode_main(
        "--inner-lifecycle",
        "flagship production lifecycle: window rotation + session "
        "table + traced reconfiguration",
        _cpu_env(),
    )


def _multichip_main() -> None:
    """Orchestrate the multichip measurement in a clean 8-virtual-device
    CPU subprocess; print exactly one JSON line, exit 0."""
    env = _cpu_env()
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()
    _subprocess_mode_main(
        "--inner-multichip", "compartmentalized multichip scaling", env
    )


def _fleet_main() -> None:
    """Orchestrate the fleet measurement in a clean 8-virtual-device
    CPU subprocess; print exactly one JSON line, exit 0."""
    env = _cpu_env()
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()
    _subprocess_mode_main(
        "--inner-fleet",
        "fleet-axis capacity surface + device-rate fuzzing throughput",
        env,
    )


def _elastic_main() -> None:
    """Orchestrate the elastic-capacity measurement in a clean CPU
    subprocess; print exactly one JSON line, exit 0."""
    _subprocess_mode_main(
        "--inner-elastic",
        "elastic capacity: SLO-driven live resize of role planes "
        "(scale out under duress, clamp as last resort)",
        _cpu_env(),
    )


def _depgraph_main() -> None:
    """Orchestrate the depgraph measurement in a clean CPU subprocess;
    print exactly one JSON line, exit 0."""
    _subprocess_mode_main(
        "--inner-depgraph",
        "depgraph: batched bitmask SCC closure vs sequential pointer "
        "walk + the [conflict x Zipf] surface",
        _cpu_env(),
    )


def _sessions_main() -> None:
    """Orchestrate the million-session measurement in a clean
    8-virtual-device CPU subprocess (the sharded leg needs a 'groups'
    mesh); print exactly one JSON line, exit 0."""
    env = _cpu_env()
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()
    _subprocess_mode_main(
        "--inner-sessions",
        "million-session serving: packed planes + group-sharded "
        "session table + trace-driven open loop",
        env,
    )


def _cpu_env() -> dict:
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("PALLAS_AXON_POOL_IPS", "JAX_PLATFORMS", "XLA_FLAGS")
    }
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _tpu_env() -> dict:
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _probe_tpu(timeout: float = 60.0) -> bool:
    """True iff the ambient (TPU) backend can run a tiny matmul in time."""
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _PROBE_CODE],
            env=_tpu_env(),
            cwd=_REPO,
            capture_output=True,
            text=True,
            timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        return False
    if proc.returncode != 0:
        return False
    # The accelerator platform on this box registers as "axon", not "tpu";
    # accept any non-CPU platform so a healthy tunnel is actually used.
    for line in proc.stdout.splitlines():
        if line.startswith("PROBE_OK "):
            return line.split()[1].lower() not in ("cpu", "")
    return False


def _run_inner(env: dict, timeout: float):
    """Run the measurement subprocess; return (result dict | None, note).
    Pass-through flags (--telemetry) ride along to the inner process."""
    argv = [sys.executable, os.path.abspath(__file__), "--inner"]
    for flag in ("--telemetry", "--faults"):
        if flag in sys.argv:
            argv.append(flag)
    try:
        proc = subprocess.run(
            argv,
            env=env,
            cwd=_REPO,
            capture_output=True,
            text=True,
            timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        return None, f"timeout after {timeout:.0f}s"
    for line in reversed(proc.stdout.splitlines()):
        if line.startswith("BENCH_JSON "):
            try:
                return json.loads(line[len("BENCH_JSON "):]), ""
            except json.JSONDecodeError:
                break
    tail = (proc.stderr or proc.stdout or "").strip().splitlines()[-6:]
    return None, f"rc={proc.returncode}: " + " | ".join(tail)


_LAST_GOOD = os.path.join(_REPO, "results", "bench_tpu_last_good.json")


def _is_tpu_result(result: dict) -> bool:
    dev = str(result.get("device", "")).lower()
    return bool(dev) and "cpu" not in dev and dev != "none"


def _invariants_ok(result: dict) -> bool:
    """True iff no attached variant reported a failed invariant check
    (including rows nested one level deeper, e.g. read_modes.*)."""

    def walk(node) -> bool:
        if not isinstance(node, dict):
            return True
        if node.get("invariants_ok") is False:
            return False
        return all(walk(v) for v in node.values())

    return walk(result)


def _save_last_good(result: dict) -> None:
    """Persist a live-TPU capture so later CPU-fallback runs can still
    report a real-TPU headline (with honest staleness). Temp-file + mv:
    a crash mid-write must never truncate an earlier good capture.
    A run whose variants failed invariants is never persisted — it must
    not be replayed as the real-TPU headline by later invocations."""
    import datetime

    if not _invariants_ok(result):
        print(
            "warning: live TPU run had failed invariants; "
            "not persisting as last-known-good",
            file=sys.stderr,
        )
        return

    payload = dict(result)
    payload["captured_at"] = datetime.datetime.now(
        datetime.timezone.utc
    ).isoformat(timespec="seconds")
    tmp = _LAST_GOOD + ".tmp"
    try:
        with open(tmp, "w") as f:
            json.dump(payload, f, indent=1)
        os.replace(tmp, _LAST_GOOD)
    except OSError as e:
        # stdout must stay one JSON line; a silent failure here would
        # quietly disable the whole last-known-good mechanism.
        print(f"warning: could not persist last-good capture: {e}",
              file=sys.stderr)


def _load_last_good() -> dict | None:
    try:
        with open(_LAST_GOOD) as f:
            payload = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    # Only a capture of THIS benchmark may become the headline: a stale
    # or hand-seeded capture of a different metric must not be promoted,
    # and neither may a capture (e.g. written by an older bench.py) whose
    # variants failed invariants.
    if payload.get("metric") != METRIC or not _invariants_ok(payload):
        return None
    return payload if _is_tpu_result(payload) else None


def _staleness_hours(captured_at: str) -> float:
    import datetime

    try:
        then = datetime.datetime.fromisoformat(captured_at)
        if then.tzinfo is None:  # older/hand-edited captures: assume UTC
            then = then.replace(tzinfo=datetime.timezone.utc)
        now = datetime.datetime.now(datetime.timezone.utc)
        return round((now - then).total_seconds() / 3600.0, 2)
    except (ValueError, TypeError):
        return -1.0


def _prefer_last_good(cpu_live: dict, notes: list) -> dict:
    """A live CPU measurement is in hand. If a real-TPU capture of this
    same benchmark exists from earlier (the tunnel wedges for hours at a
    time), report THAT as the headline — honestly labelled with when it
    was captured and how stale it is — with the live CPU number attached."""
    last_good = _load_last_good()
    if last_good is None:
        return cpu_live
    result = dict(last_good)
    result["measured_live"] = False
    # Explicit machine-readable staleness flag (not just the free-text
    # note): the headline is a replayed TPU capture that predates the
    # current code and must be re-measured on hardware.
    result["pending_tpu_remeasure"] = True
    result["staleness_hours"] = _staleness_hours(
        result.get("captured_at", "")
    )
    # Model plausibility check (ops/costmodel.py): the promoted
    # headline gets an explicit ``model_flagged`` provenance field
    # when its rate is implausible against the roofline's predicted
    # saturation for the capture's device class — e.g. the
    # pre-kernel-layer BENCH_r05 4.0M entries/sec TPU capture sits
    # ~50x under the hardware ceiling the model predicts for the
    # current tree, so it surfaces flagged, never silently.
    from frankenpaxos_tpu.ops import costmodel

    costmodel.flag_capture(result)
    if result.get("model_flagged"):
        notes.append(result["model_flag_reason"])
    result["live_cpu_fallback"] = {
        "value": cpu_live.get("value"),
        "unit": cpu_live.get("unit"),
        "device": cpu_live.get("device"),
        "p50_commit_latency_ticks": cpu_live.get(
            "p50_commit_latency_ticks"
        ),
        "config": cpu_live.get("config"),
        # The live run's secondary measurements (read path lin/seq/
        # eventual, SMR, telemetry overhead, degraded-mode faults)
        # travel with the fallback record so the artifact always
        # carries them even when the headline is a stale capture.
        "read_variant": cpu_live.get("read_variant"),
        "read_modes": cpu_live.get("read_modes"),
        "smr_variant": cpu_live.get("smr_variant"),
        "telemetry": cpu_live.get("telemetry"),
        "faults": cpu_live.get("faults"),
        "kernel_policy": cpu_live.get("kernel_policy"),
        "kernel_coverage": cpu_live.get("kernel_coverage"),
        "analysis": cpu_live.get("analysis"),
    }
    notes.append(
        "headline is the last-known-good real-TPU capture; "
        "live run this invocation was the attached CPU fallback"
    )
    return result


def main() -> None:
    # --live-only: this invocation must measure, not replay. A stale
    # last-known-good TPU capture is never promoted to the headline;
    # whatever ran live THIS invocation (TPU or the honest CPU fallback)
    # is the result, with a note recording that the replay was refused.
    live_only = "--live-only" in sys.argv
    notes = []
    result = None

    if _probe_tpu():
        result, note = _run_inner(_tpu_env(), timeout=900.0)
        if result is None:
            notes.append(f"tpu run failed ({note})")
        elif _is_tpu_result(result):
            result["measured_live"] = True
            if not _invariants_ok(result):
                notes.append(
                    "live run reported FAILED invariants (see variant "
                    "fields); not persisted as last-known-good"
                )
            _save_last_good(result)
        else:
            # The probe saw the accelerator but JAX inside the inner run
            # landed on CPU (the tunnel wedged in between): this is a CPU
            # fallback, not a TPU headline.
            notes.append(
                "tpu probe ok but the measurement ran on "
                f"{result.get('device')}; treating as cpu fallback"
            )
            if live_only:
                result["measured_live"] = True
                notes.append(
                    "--live-only: refusing to headline a stale "
                    "last-known-good TPU capture"
                )
            else:
                result = _prefer_last_good(result, notes)
    else:
        notes.append("tpu probe failed or timed out; falling back to cpu")

    if result is None:
        result, note = _run_inner(_cpu_env(), timeout=900.0)
        if result is None:
            notes.append(f"cpu run failed ({note})")
        elif live_only:
            result["measured_live"] = True
            notes.append(
                "--live-only: refusing to headline a stale "
                "last-known-good TPU capture"
            )
        else:
            result = _prefer_last_good(result, notes)

    if result is None:
        result = {
            "metric": METRIC,
            "value": 0.0,
            "unit": UNIT,
            "vs_baseline": 0.0,
            "device": "none",
        }
    if notes:
        result["notes"] = "; ".join(notes)
    print(json.dumps(result))
    sys.exit(0)


if __name__ == "__main__":
    if "--inner-multichip" in sys.argv:
        _multichip_inner()
    elif "--inner-fleet" in sys.argv:
        _fleet_inner()
    elif "--inner-workload" in sys.argv:
        _workload_inner()
    elif "--inner-serve" in sys.argv:
        _serve_inner()
    elif "--inner-checkpoint" in sys.argv:
        _checkpoint_inner()
    elif "--inner-lifecycle" in sys.argv:
        _lifecycle_inner()
    elif "--inner-sessions" in sys.argv:
        _sessions_inner()
    elif "--inner-elastic" in sys.argv:
        _elastic_inner()
    elif "--inner-depgraph" in sys.argv:
        _depgraph_inner()
    elif "--inner" in sys.argv:
        _inner_main()
    elif "--multichip" in sys.argv:
        _multichip_main()
    elif "--fleet" in sys.argv:
        _fleet_main()
    elif "--workload" in sys.argv:
        _workload_main()
    elif "--serve" in sys.argv:
        _serve_main()
    elif "--checkpoint" in sys.argv:
        _checkpoint_main()
    elif "--lifecycle" in sys.argv:
        _lifecycle_main()
    elif "--sessions" in sys.argv:
        _sessions_main()
    elif "--elastic" in sys.argv:
        _elastic_main()
    elif "--depgraph" in sys.argv:
        _depgraph_main()
    else:
        main()
