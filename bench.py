#!/usr/bin/env python
"""Headline benchmark: committed log entries/sec simulating 10k MultiPaxos
acceptors (BASELINE.json: target >= 1M/sec on TPU, metric "committed log
entries/sec @ 10k replicas; p50 commit latency (sim ticks)").

Prints exactly ONE JSON line to stdout:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}
"""

from __future__ import annotations

import json
import sys
import time

import jax

from frankenpaxos_tpu.tpu import BatchedMultiPaxosConfig, TpuSimTransport

TARGET = 1_000_000.0  # committed entries/sec (BASELINE.json north star)


def main() -> None:
    # 3334 groups x 3 acceptors = 10,002 simulated acceptors (f=1).
    cfg = BatchedMultiPaxosConfig(
        f=1,
        num_groups=3334,
        window=64,
        slots_per_tick=8,
        lat_min=1,
        lat_max=3,
        drop_rate=0.0,
        retry_timeout=16,
        thrifty=True,
    )
    sim = TpuSimTransport(cfg, seed=0)

    # Warmup + calibration: compile the segment program, ramp the pipeline,
    # and size the measured run to a sane wall-clock budget on any backend
    # (TPU ticks are microseconds; a CPU fallback is ~50ms/tick).
    ticks_per_segment = 500
    sim.run(ticks_per_segment)
    sim.block_until_ready()
    t0 = time.perf_counter()
    sim.run(ticks_per_segment)
    sim.block_until_ready()
    probe = time.perf_counter() - t0
    budget_s = 30.0
    segments = max(1, min(12, int(budget_s / max(probe, 1e-3))))

    committed0 = sim.committed()
    start = time.perf_counter()
    for _ in range(segments):
        sim.run(ticks_per_segment)
    sim.block_until_ready()
    elapsed = time.perf_counter() - start
    committed = sim.committed() - committed0

    stats = sim.stats()
    throughput = committed / elapsed
    ticks = segments * ticks_per_segment
    result = {
        "metric": "committed log entries/sec @ 10k simulated MultiPaxos acceptors",
        "value": round(throughput, 1),
        "unit": "entries/sec",
        "vs_baseline": round(throughput / TARGET, 3),
        "p50_commit_latency_ticks": stats["commit_latency_p50_ticks"],
        "num_acceptors": cfg.num_acceptors,
        "ticks": ticks,
        "ticks_per_sec": round(ticks / elapsed, 1),
        "wall_seconds": round(elapsed, 3),
        "device": str(jax.devices()[0]),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
