#!/usr/bin/env python
"""Refresh results/batched_backends_cpu.json: per-family throughput
snapshots of every batched backend (one in-session process, so numbers
are conservative vs bench.py's clean-subprocess measurement). Warmup
segments use the SAME tick count as measured segments: run_ticks
specializes on num_ticks, so a different length would recompile inside
the timed region."""
import json
import time

import jax
import jax.numpy as jnp

from frankenpaxos_tpu.tpu import (
    BatchedCasPaxosConfig,
    BatchedCraqConfig,
    BatchedEPaxosConfig,
    BatchedFastPaxosConfig,
    BatchedMenciusConfig,
    BatchedMultiPaxosConfig,
    TpuSimTransport,
    caspaxos_batched,
    craq_batched,
    epaxos_batched,
    fastpaxos_batched,
    mencius_batched,
    scalog_batched,
    unreplicated_batched,
)
from frankenpaxos_tpu.tpu.unreplicated_batched import (
    BatchedUnreplicatedConfig,
)
from frankenpaxos_tpu.tpu.scalog_batched import BatchedScalogConfig

out = {
    "device": str(jax.devices()[0]),
    "note": "per-family batched-backend throughput snapshots",
}


# MultiPaxos @ 10k acceptors (write path only, the bench.py headline).
mp = TpuSimTransport(
    BatchedMultiPaxosConfig(
        f=1, num_groups=3334, window=64, slots_per_tick=8,
        lat_min=1, lat_max=3, retry_timeout=16,
    ),
    seed=0,
)
mp.run(400); mp.block_until_ready()
c0 = mp.committed()
t0 = time.perf_counter()
mp.run(400); mp.block_until_ready()
dt = time.perf_counter() - t0
out["multipaxos_10k_acceptors"] = {
    "committed_per_sec": int((mp.committed() - c0) / dt),
    "ticks_per_sec": round(400 / dt, 1),
}

# Unreplicated ceiling at the same scale (the eurosys-fig1 framing:
# consensus throughput as a fraction of the no-replication ceiling).
ucfg = BatchedUnreplicatedConfig(
    num_servers=3334, window=64, ops_per_tick=8, lat_min=1, lat_max=3
)
ustate = unreplicated_batched.init_state(ucfg)
ustate, ut = unreplicated_batched.run_ticks(
    ucfg, ustate, jnp.int32(0), 400, jax.random.PRNGKey(0)
)
jax.block_until_ready(ustate)
u0 = int(ustate.done)
t0 = time.perf_counter()
ustate, ut = unreplicated_batched.run_ticks(
    ucfg, ustate, ut, 400, jax.random.PRNGKey(1)
)
jax.block_until_ready(ustate)
dt = time.perf_counter() - t0
ceiling = int((int(ustate.done) - u0) / dt)
out["unreplicated_ceiling_3334_servers"] = {"ops_per_sec": ceiling}
out["multipaxos_10k_acceptors"]["ceiling_fraction"] = round(
    out["multipaxos_10k_acceptors"]["committed_per_sec"] / max(1, ceiling), 3
)

# MultiPaxos + device-side SM + client table (the full SMR pipeline).
sm = TpuSimTransport(
    BatchedMultiPaxosConfig(
        f=1, num_groups=3334, window=64, slots_per_tick=8,
        lat_min=1, lat_max=3, retry_timeout=16,
        state_machine="kv", kv_keys=64, num_clients=8, dup_rate=0.02,
    ),
    seed=0,
)
sm.run(400); sm.block_until_ready()
a0 = int(sm.state.sm_applied)
t0 = time.perf_counter()
sm.run(400); sm.block_until_ready()
dt = time.perf_counter() - t0
out["multipaxos_10k_acceptors_with_smr"] = {
    "sm_applied_per_sec": int((int(sm.state.sm_applied) - a0) / dt),
    "dups_filtered": int(sm.state.dups_filtered),
}

# EPaxos @ 64 and 1024 columns (the factored-dependency closure scales
# past the round-3 backend's 64-column ceiling).
for ecols, ekw in [
    (64, dict()),
    (1024, dict(window=64, instances_per_tick=4, frontier_history=128)),
]:
    ecfg = BatchedEPaxosConfig(num_columns=ecols, **ekw)
    estate = epaxos_batched.init_state(ecfg)
    estate, _ = epaxos_batched.run_ticks(
        ecfg, estate, jnp.int32(0), 200, jax.random.PRNGKey(0)
    )
    jax.block_until_ready(estate)
    e0 = int(estate.executed_total)
    t0 = time.perf_counter()
    estate, _ = epaxos_batched.run_ticks(
        ecfg, estate, jnp.int32(200), 200, jax.random.PRNGKey(1)
    )
    jax.block_until_ready(estate)
    dt = time.perf_counter() - t0
    inv = epaxos_batched.check_invariants(ecfg, estate, jnp.int32(400))
    out[f"epaxos_{ecols}_columns"] = {
        "executed_per_sec": int((int(estate.executed_total) - e0) / dt),
        "invariants_ok": all(bool(v) for v in inv.values()),
    }

# Mencius @ 256 leaders.
mcfg = BatchedMenciusConfig(
    f=1, num_leaders=256, window=32, slots_per_tick=4, num_idle_leaders=64
)
mstate = mencius_batched.init_state(mcfg)
mstate, _ = mencius_batched.run_ticks(
    mcfg, mstate, jnp.int32(0), 200, jax.random.PRNGKey(0)
)
jax.block_until_ready(mstate)
m0 = int(mstate.executed_global)
t0 = time.perf_counter()
mstate, _ = mencius_batched.run_ticks(
    mcfg, mstate, jnp.int32(200), 200, jax.random.PRNGKey(1)
)
jax.block_until_ready(mstate)
dt = time.perf_counter() - t0
out["mencius_256_leaders"] = {
    "globally_executed_per_sec": int((int(mstate.executed_global) - m0) / dt),
    "skips": int(mstate.skips),
}

# Scalog @ 256 shards.
scfg = BatchedScalogConfig(num_shards=256, appends_per_tick=8)
sstate = scalog_batched.init_state(scfg)
sstate, _ = scalog_batched.run_ticks(
    scfg, sstate, jnp.int32(0), 200, jax.random.PRNGKey(0)
)
jax.block_until_ready(sstate)
g0 = int(sstate.global_len)
t0 = time.perf_counter()
sstate, _ = scalog_batched.run_ticks(
    scfg, sstate, jnp.int32(200), 200, jax.random.PRNGKey(1)
)
jax.block_until_ready(sstate)
dt = time.perf_counter() - t0
out["scalog_256_shards"] = {
    "ordered_records_per_sec": int((int(sstate.global_len) - g0) / dt),
    "mean_ordering_lag_ticks": round(
        float(sstate.lat_sum) / max(1, int(sstate.lat_count)), 2
    ),
}

# CRAQ @ 256 chains of 4 (apportioned reads).
ccfg = BatchedCraqConfig(
    num_chains=256, chain_len=4, num_keys=64, window=16,
    writes_per_tick=2, reads_per_tick=4, read_window=32,
)
cstate = craq_batched.init_state(ccfg)
cstate, ct = craq_batched.run_ticks(
    ccfg, cstate, jnp.int32(0), 200, jax.random.PRNGKey(0)
)
jax.block_until_ready(cstate)
w0, r0 = int(cstate.writes_done), int(cstate.reads_done)
t0 = time.perf_counter()
cstate, ct = craq_batched.run_ticks(
    ccfg, cstate, ct, 200, jax.random.PRNGKey(1)
)
jax.block_until_ready(cstate)
dt = time.perf_counter() - t0
cs = craq_batched.stats(ccfg, cstate, ct)
out["craq_256_chains_of_4"] = {
    "writes_per_sec": int((int(cstate.writes_done) - w0) / dt),
    "reads_per_sec": int((int(cstate.reads_done) - r0) / dt),
    "clean_read_fraction": round(cs["clean_fraction"], 3),
}

# Fast Paxos @ 512 groups (fast path + O4 recovery under conflicts).
fcfg = BatchedFastPaxosConfig(
    f=1, num_groups=512, window=16, instances_per_tick=2,
    conflict_rate=0.2, lat_min=1, lat_max=3, recovery_timeout=8,
)
fstate = fastpaxos_batched.init_state(fcfg)
fstate, ft = fastpaxos_batched.run_ticks(
    fcfg, fstate, jnp.int32(0), 200, jax.random.PRNGKey(0)
)
jax.block_until_ready(fstate)
f0 = int(fstate.chosen_total)
t0 = time.perf_counter()
fstate, ft = fastpaxos_batched.run_ticks(
    fcfg, fstate, ft, 200, jax.random.PRNGKey(1)
)
jax.block_until_ready(fstate)
dt = time.perf_counter() - t0
fs = fastpaxos_batched.stats(fcfg, fstate, ft)
out["fastpaxos_512_groups"] = {
    "chosen_per_sec": int((int(fstate.chosen_total) - f0) / dt),
    "fast_fraction": round(fs["fast_fraction"], 3),
    "safety_violations": fs["safety_violations"],
}

# CASPaxos @ 1024 registers, 2 contending leaders each.
cscfg = BatchedCasPaxosConfig(
    f=1, num_registers=1024, num_leaders=2, op_rate=0.3,
    lat_min=1, lat_max=3, backoff_min=2, backoff_max=8,
)
csstate = caspaxos_batched.init_state(cscfg)
csstate, cst = caspaxos_batched.run_ticks(
    cscfg, csstate, jnp.int32(0), 200, jax.random.PRNGKey(0)
)
jax.block_until_ready(csstate)
cs0 = int(csstate.commits)
t0 = time.perf_counter()
csstate, cst = caspaxos_batched.run_ticks(
    cscfg, csstate, cst, 200, jax.random.PRNGKey(1)
)
jax.block_until_ready(csstate)
dt = time.perf_counter() - t0
css = caspaxos_batched.stats(cscfg, csstate, cst)
out["caspaxos_1024_registers"] = {
    "commits_per_sec": int((int(csstate.commits) - cs0) / dt),
    "nacks": css["nacks"],
    "chain_violations": css["chain_violations"],
}

# Horizontal @ 128 groups with config-as-log-value churn.
from frankenpaxos_tpu.tpu import horizontal_batched
hcfg = horizontal_batched.BatchedHorizontalConfig(
    f=1, num_groups=128, window=32, slots_per_tick=2, alpha=16,
    reconfigure_every=50,
)
hstate = horizontal_batched.init_state(hcfg)
hstate, ht = horizontal_batched.run_ticks(
    hcfg, hstate, jnp.int32(0), 200, jax.random.PRNGKey(0)
)
jax.block_until_ready(hstate)
h0 = int(hstate.committed)
t0 = time.perf_counter()
hstate, ht = horizontal_batched.run_ticks(
    hcfg, hstate, ht, 200, jax.random.PRNGKey(1)
)
jax.block_until_ready(hstate)
dt = time.perf_counter() - t0
hs = horizontal_batched.stats(hcfg, hstate, ht)
hinv = horizontal_batched.check_invariants(hcfg, hstate, ht)
out["horizontal_128_groups_churning"] = {
    "committed_per_sec": int((int(hstate.committed) - h0) / dt),
    "reconfigs_done": hs["reconfigs_done"],
    "invariants_ok": all(bool(v) for v in hinv.values()),
}

# Vanilla Mencius @ 64 servers with failure churn + revocation.
from frankenpaxos_tpu.tpu import vanillamencius_batched
vmcfg = vanillamencius_batched.BatchedVanillaMenciusConfig(
    f=1, num_servers=64, window=32, slots_per_tick=2,
    fail_rate=0.005, revive_rate=0.1, revoke_threshold=8,
)
vmstate = vanillamencius_batched.init_state(vmcfg)
vmstate, vmt = vanillamencius_batched.run_ticks(
    vmcfg, vmstate, jnp.int32(0), 200, jax.random.PRNGKey(0)
)
jax.block_until_ready(vmstate)
vm0 = int(vmstate.committed_real)
t0 = time.perf_counter()
vmstate, vmt = vanillamencius_batched.run_ticks(
    vmcfg, vmstate, vmt, 200, jax.random.PRNGKey(1)
)
jax.block_until_ready(vmstate)
dt = time.perf_counter() - t0
vms = vanillamencius_batched.stats(vmcfg, vmstate, vmt)
vminv = vanillamencius_batched.check_invariants(vmcfg, vmstate, vmt)
out["vanillamencius_64_servers_churning"] = {
    "committed_real_per_sec": int((int(vmstate.committed_real) - vm0) / dt),
    "revocations": vms["revocations"],
    "invariants_ok": all(bool(v) for v in vminv.values()),
}

# Faster Paxos @ 64 groups with delegate churn.
from frankenpaxos_tpu.tpu import fasterpaxos_batched
fpcfg = fasterpaxos_batched.BatchedFasterPaxosConfig(
    f=1, num_groups=64, window=16, slots_per_tick=2,
    fail_rate=0.005, revive_rate=0.15, detect_timeout=4,
)
fpstate = fasterpaxos_batched.init_state(fpcfg)
fpstate, fpt = fasterpaxos_batched.run_ticks(
    fpcfg, fpstate, jnp.int32(0), 200, jax.random.PRNGKey(0)
)
jax.block_until_ready(fpstate)
fp0 = int(fpstate.committed_real)
t0 = time.perf_counter()
fpstate, fpt = fasterpaxos_batched.run_ticks(
    fpcfg, fpstate, fpt, 200, jax.random.PRNGKey(1)
)
jax.block_until_ready(fpstate)
dt = time.perf_counter() - t0
fps = fasterpaxos_batched.stats(fpcfg, fpstate, fpt)
fpinv = fasterpaxos_batched.check_invariants(fpcfg, fpstate, fpt)
out["fasterpaxos_64_groups_churning"] = {
    "committed_real_per_sec": int(
        (int(fpstate.committed_real) - fp0) / dt
    ),
    "leader_changes": fps["leader_changes"],
    "invariants_ok": all(bool(v) for v in fpinv.values()),
}

# Fast MultiPaxos @ 64 groups (log-structured fast rounds).
from frankenpaxos_tpu.tpu import fastmultipaxos_batched
fmcfg = fastmultipaxos_batched.BatchedFastMultiPaxosConfig(
    f=1, num_groups=64, window=32, cmd_window=16, cmds_per_tick=2,
    lat_min=2, lat_max=2, jitter=1,
)
fmstate = fastmultipaxos_batched.init_state(fmcfg)
fmstate, fmt = fastmultipaxos_batched.run_ticks(
    fmcfg, fmstate, jnp.int32(0), 200, jax.random.PRNGKey(0)
)
jax.block_until_ready(fmstate)
fm0 = int(fmstate.cmds_done)
t0 = time.perf_counter()
fmstate, fmt = fastmultipaxos_batched.run_ticks(
    fmcfg, fmstate, fmt, 200, jax.random.PRNGKey(1)
)
jax.block_until_ready(fmstate)
dt = time.perf_counter() - t0
fms = fastmultipaxos_batched.stats(fmcfg, fmstate, fmt)
fminv = fastmultipaxos_batched.check_invariants(fmcfg, fmstate, fmt)
out["fastmultipaxos_64_groups"] = {
    "cmds_done_per_sec": int((int(fmstate.cmds_done) - fm0) / dt),
    "fast_fraction": round(fms["fast_fraction"], 3),
    "invariants_ok": all(bool(v) for v in fminv.values()),
}

with open("results/batched_backends_cpu.json", "w") as f:
    json.dump(out, f, indent=2)
print(json.dumps(out, indent=2))
