#!/bin/bash
# Bounded serve smoke (CI): a ~10 s LIVE serve run of the flagship
# backend through harness/serve.py on CPU, asserting
#   1. clean shutdown (final drain + block_until_ready + report),
#   2. a non-empty Perfetto-loadable trace export carrying BOTH device
#      lifecycle spans and host dispatch spans,
#   3. a non-empty scrape CSV (the live-dashboard feed),
#   4. a SIGKILL-mid-serve leg (harness/recovery.py): the serve worker
#      is killed at a randomized chunk boundary, restarts from the
#      latest checkpoint, and must recover — invariants + exactly-once
#      session books hold and the final state is sha256-identical to
#      the uninterrupted twin, and
#   5. static analysis exiting 0 with the trace-serve-nosync,
#      checkpoint-alias-free, and trace-checkpoint-restore rules
#      registered (the chunked dispatch path stays free of blocking
#      transfers; the checkpoint snapshot aliases nothing; restore
#      never recompiles).
#
# Usage: scripts/serve_smoke.sh [out_dir]   (SERVE_SMOKE_SECONDS=10)
set -euo pipefail
cd "$(dirname "$0")/.."
OUT=${1:-/tmp/fpx_serve_smoke}
rm -rf "$OUT"
mkdir -p "$OUT"

# The lifecycle legs ride the smoke: window rotation keeps the run's
# slot horizon constant and the session table answers duplicate
# re-submissions from cache (tpu/lifecycle.py; both asserted below).
JAX_PLATFORMS=cpu python -m frankenpaxos_tpu.harness.serve \
  --seconds "${SERVE_SMOKE_SECONDS:-10}" --out-dir "$OUT" \
  --groups 64 --chunk 32 --spans 16 --rate-x 1.1 --slo-p99 24 \
  --rotate-every 64 --sessions 8 --resubmit-rate 0.05 \
  > "$OUT/report_line.json"

JAX_PLATFORMS=cpu python - "$OUT" <<'EOF'
import json, os, sys

out = sys.argv[1]
report = json.load(open(os.path.join(out, "serve_report.json")))
assert report["clean_shutdown"], report
assert report["ticks"] > 0, report
assert report["dropped_ticks"] == 0, report
lc = report["lifecycle"]
assert lc["rotations"] >= 1, lc  # the window rolled at least once
assert lc["cache_hits"] > 0, lc  # duplicates answered from the table

from frankenpaxos_tpu.monitoring import traceviz

tr = traceviz.load_chrome_trace(os.path.join(out, "serve_trace.json"))
xs = [e for e in tr["traceEvents"] if e.get("ph") == "X"]
assert any(e["pid"] == traceviz.DEVICE_PID for e in xs), "no device spans"
assert any(e["pid"] == traceviz.HOST_PID for e in xs), "no host spans"
assert os.path.getsize(os.path.join(out, "serve_metrics.csv")) > 0
print(
    "serve smoke OK:", report["ticks"], "ticks,",
    report["spans_exported"], "device spans,",
    len(xs), "trace events"
)
EOF

# Fleet-serve leg: a bounded 4-instance FLEET brick through
# FleetServeLoop (per-instance telemetry drains, in-graph straggler
# flags, per-instance SLO clamps) with one hostile instance — clean
# shutdown, non-empty per-instance scrape rows, the straggler column
# present, and only the hostile instance flagged.
JAX_PLATFORMS=cpu python -m frankenpaxos_tpu.harness.serve \
  --fleet 4 --seconds "${SERVE_SMOKE_SECONDS:-10}" \
  --out-dir "$OUT/fleet" --chunk 16 --rate-x 0.9 --slo-p99 8 \
  --hostile-instance 2 --hostile-drop 0.6 \
  > "$OUT/fleet_report_line.json"

JAX_PLATFORMS=cpu python - "$OUT/fleet" <<'EOF'
import csv, json, os, sys

out = sys.argv[1]
report = json.load(open(os.path.join(out, "fleet_report.json")))
assert report["clean_shutdown"], report
assert report["ticks"] > 0, report
assert report["dropped_ticks"] == 0, report
assert report["stragglers_flagged"] == [2], report["stragglers_flagged"]
with open(os.path.join(out, "fleet_metrics.csv")) as f:
    rows = list(csv.DictReader(f))
insts = {r["instance"] for r in rows if r["job"] == "fleet"}
assert insts == {"0", "1", "2", "3"}, insts  # per-instance rows
strag = [r for r in rows if r["name"] == "fpx_fleet_straggler"]
assert strag, "straggler column missing from the scrape CSV"
assert any(
    float(r["value"]) == 1.0 and r["instance"] == "2" for r in strag
), "hostile instance never hit the straggler lane"
print(
    "fleet smoke OK:", report["ticks"], "ticks,",
    len(strag), "straggler samples, scales", report["slo"]["scales"]
)
EOF

# Kill-and-recover leg: SIGKILL the serve worker mid-run at a
# randomized chunk boundary, restart from the newest valid checkpoint,
# and verify liveness + invariants + exactly-once books + a final
# state digest bit-identical to the uninterrupted twin.
JAX_PLATFORMS=cpu python -m frankenpaxos_tpu.harness.recovery \
  --smoke --out-dir "$OUT/recovery" --chunks 10 --every 2 \
  --chunk-ticks 8

# The full registry must exit 0 and know the serve + checkpoint rules.
# (grep WITHOUT -q: -q exits at first match and the listing dies on
# EPIPE under pipefail once the registry outgrew the pipe buffer.)
RULES=$(python -m frankenpaxos_tpu.analysis --list)
echo "$RULES" | grep trace-serve-nosync >/dev/null
echo "$RULES" | grep checkpoint-alias-free >/dev/null
echo "$RULES" | grep trace-checkpoint-restore >/dev/null
echo "$RULES" | grep trace-fleet-drain-nosync >/dev/null
# lint.sh forces the 8-virtual-device product mesh, so the fleet rule
# runs its full census here even on single-device hosts.
scripts/lint.sh --rule trace-serve-nosync \
  --rule checkpoint-alias-free --rule trace-checkpoint-restore \
  --rule trace-fleet-drain-nosync
echo "serve_smoke: PASS"
