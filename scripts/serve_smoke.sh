#!/bin/bash
# Bounded serve smoke (CI): a ~10 s LIVE serve run of the flagship
# backend through harness/serve.py on CPU, asserting
#   1. clean shutdown (final drain + block_until_ready + report),
#   2. a non-empty Perfetto-loadable trace export carrying BOTH device
#      lifecycle spans and host dispatch spans,
#   3. a non-empty scrape CSV (the live-dashboard feed),
#   4. a SIGKILL-mid-serve leg (harness/recovery.py): the serve worker
#      is killed at a randomized chunk boundary, restarts from the
#      latest checkpoint, and must recover — invariants + exactly-once
#      session books hold and the final state is sha256-identical to
#      the uninterrupted twin, and
#   5. an elastic-capacity leg (tpu/elastic.py + the autoscaler
#      ladder): a compressed trough->burst->trough day over a padded
#      role plane must scale OUT at least once under the burst's p99
#      alarm and scale back IN on the trough, shut down cleanly, and
#      never recompile across the resizes, and
#   6. static analysis exiting 0 with the trace-serve-nosync,
#      checkpoint-alias-free, trace-checkpoint-restore, elastic-noop,
#      and trace-elastic-retrace rules registered (the chunked
#      dispatch path stays free of blocking transfers; the checkpoint
#      snapshot aliases nothing; restore never recompiles; resizes
#      ride traced membership scalars).
#
# Usage: scripts/serve_smoke.sh [out_dir]   (SERVE_SMOKE_SECONDS=10)
set -euo pipefail
cd "$(dirname "$0")/.."
OUT=${1:-/tmp/fpx_serve_smoke}
rm -rf "$OUT"
mkdir -p "$OUT"

# The lifecycle legs ride the smoke: window rotation keeps the run's
# slot horizon constant and the session table answers duplicate
# re-submissions from cache (tpu/lifecycle.py; both asserted below).
JAX_PLATFORMS=cpu python -m frankenpaxos_tpu.harness.serve \
  --seconds "${SERVE_SMOKE_SECONDS:-10}" --out-dir "$OUT" \
  --groups 64 --chunk 32 --spans 16 --rate-x 1.1 --slo-p99 24 \
  --rotate-every 64 --sessions 8 --resubmit-rate 0.05 \
  > "$OUT/report_line.json"

JAX_PLATFORMS=cpu python - "$OUT" <<'EOF'
import json, os, sys

out = sys.argv[1]
report = json.load(open(os.path.join(out, "serve_report.json")))
assert report["clean_shutdown"], report
assert report["ticks"] > 0, report
assert report["dropped_ticks"] == 0, report
lc = report["lifecycle"]
assert lc["rotations"] >= 1, lc  # the window rolled at least once
assert lc["cache_hits"] > 0, lc  # duplicates answered from the table

from frankenpaxos_tpu.monitoring import traceviz

tr = traceviz.load_chrome_trace(os.path.join(out, "serve_trace.json"))
xs = [e for e in tr["traceEvents"] if e.get("ph") == "X"]
assert any(e["pid"] == traceviz.DEVICE_PID for e in xs), "no device spans"
assert any(e["pid"] == traceviz.HOST_PID for e in xs), "no host spans"
assert os.path.getsize(os.path.join(out, "serve_metrics.csv")) > 0
print(
    "serve smoke OK:", report["ticks"], "ticks,",
    report["spans_exported"], "device spans,",
    len(xs), "trace events"
)
EOF

# Fleet-serve leg: a bounded 4-instance FLEET brick through
# FleetServeLoop (per-instance telemetry drains, in-graph straggler
# flags, per-instance SLO clamps) with one hostile instance — clean
# shutdown, non-empty per-instance scrape rows, the straggler column
# present, and only the hostile instance flagged.
JAX_PLATFORMS=cpu python -m frankenpaxos_tpu.harness.serve \
  --fleet 4 --seconds "${SERVE_SMOKE_SECONDS:-10}" \
  --out-dir "$OUT/fleet" --chunk 16 --rate-x 0.9 --slo-p99 8 \
  --hostile-instance 2 --hostile-drop 0.6 \
  > "$OUT/fleet_report_line.json"

JAX_PLATFORMS=cpu python - "$OUT/fleet" <<'EOF'
import csv, json, os, sys

out = sys.argv[1]
report = json.load(open(os.path.join(out, "fleet_report.json")))
assert report["clean_shutdown"], report
assert report["ticks"] > 0, report
assert report["dropped_ticks"] == 0, report
assert report["stragglers_flagged"] == [2], report["stragglers_flagged"]
with open(os.path.join(out, "fleet_metrics.csv")) as f:
    rows = list(csv.DictReader(f))
insts = {r["instance"] for r in rows if r["job"] == "fleet"}
assert insts == {"0", "1", "2", "3"}, insts  # per-instance rows
strag = [r for r in rows if r["name"] == "fpx_fleet_straggler"]
assert strag, "straggler column missing from the scrape CSV"
assert any(
    float(r["value"]) == 1.0 and r["instance"] == "2" for r in strag
), "hostile instance never hit the straggler lane"
print(
    "fleet smoke OK:", report["ticks"], "ticks,",
    len(strag), "straggler samples, scales", report["slo"]["scales"]
)
EOF

# Elastic-capacity leg: a compressed trough->burst->trough day over a
# padded 4-group role plane with the SLO-driven autoscaler ladder on.
# The burst's p99 alarm must GROW the active group count (traced
# resize verbs — the jit cache stays flat), the trough must SHRINK it
# back (drain-then-deactivate), and the exactly-once books must hold
# across every resize.
JAX_PLATFORMS=cpu python - "$OUT/elastic" <<'EOF'
import dataclasses, json, os, sys

import jax

from frankenpaxos_tpu.harness import serve as serve_mod
from frankenpaxos_tpu.monitoring.autoscaler import AutoscalerPolicy
from frankenpaxos_tpu.monitoring.slo import SloPolicy
from frankenpaxos_tpu.tpu import multipaxos_batched as mp
from frankenpaxos_tpu.tpu.elastic import ElasticPlan
from frankenpaxos_tpu.tpu.workload import WorkloadPlan

out = sys.argv[1]
os.makedirs(out, exist_ok=True)
cfg = mp.BatchedMultiPaxosConfig(
    f=1, num_groups=4, window=16, slots_per_tick=2, retry_timeout=16,
    workload=WorkloadPlan(arrival="constant", rate=0.4, backlog_cap=64),
    elastic=ElasticPlan(roles=(("groups", 4, 1),)),
)
serve_cfg = serve_mod.ServeConfig(
    chunk_ticks=16, telemetry_window=64, max_chunks=1,
    slo=SloPolicy(p99_target_ticks=12, source="queue_wait"),
    autoscaler=AutoscalerPolicy(cooldown_drains=0, trough_after=3),
    scrape_csv=os.path.join(out, "elastic_metrics.csv"),
)
loop = serve_mod.ServeLoop(
    mp, cfg, serve_cfg, seed=0, elastic_initial={"groups": 1}
)

def phase(chunks, rate):
    loop.set_base_rate(rate)
    loop.serve = dataclasses.replace(
        loop.serve, max_chunks=loop._chunks + chunks
    )
    return loop.run()

phase(3, 0.3)                      # night trough at the floor
cache_after_warm = mp.run_ticks._cache_size()
phase(6, 1.6)                      # saturating burst: scale out
report = phase(16, 0.2)            # evening trough: scale back in
assert report["clean_shutdown"], report
asum = report["autoscaler"]
assert asum["scale_up_events"] >= 1, asum
assert asum["scale_down_events"] >= 1, asum
assert mp.run_ticks._cache_size() == cache_after_warm == 1, (
    "a resize recompiled"
)
inv = {
    k: bool(v)
    for k, v in jax.device_get(
        mp.check_invariants(loop.cfg, loop.state, loop.t)
    ).items()
}
assert inv.get("elastic_ok") and inv.get("workload_ok"), inv
assert os.path.getsize(serve_cfg.scrape_csv) > 0
print(
    "elastic smoke OK:", asum["scale_up_events"], "ups,",
    asum["scale_down_events"], "downs, roles",
    json.dumps(report["elastic"]["roles"]),
)
EOF

# Kill-and-recover leg: SIGKILL the serve worker mid-run at a
# randomized chunk boundary, restart from the newest valid checkpoint,
# and verify liveness + invariants + exactly-once books + a final
# state digest bit-identical to the uninterrupted twin.
JAX_PLATFORMS=cpu python -m frankenpaxos_tpu.harness.recovery \
  --smoke --out-dir "$OUT/recovery" --chunks 10 --every 2 \
  --chunk-ticks 8

# The full registry must exit 0 and know the serve + checkpoint rules.
# (grep WITHOUT -q: -q exits at first match and the listing dies on
# EPIPE under pipefail once the registry outgrew the pipe buffer.)
RULES=$(python -m frankenpaxos_tpu.analysis --list)
echo "$RULES" | grep trace-serve-nosync >/dev/null
echo "$RULES" | grep checkpoint-alias-free >/dev/null
echo "$RULES" | grep trace-checkpoint-restore >/dev/null
echo "$RULES" | grep trace-fleet-drain-nosync >/dev/null
echo "$RULES" | grep elastic-noop >/dev/null
echo "$RULES" | grep trace-elastic-retrace >/dev/null
# lint.sh forces the 8-virtual-device product mesh, so the fleet rule
# runs its full census here even on single-device hosts.
scripts/lint.sh --rule trace-serve-nosync \
  --rule checkpoint-alias-free --rule trace-checkpoint-restore \
  --rule trace-fleet-drain-nosync --rule elastic-noop \
  --rule trace-elastic-retrace
echo "serve_smoke: PASS"
