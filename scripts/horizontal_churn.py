#!/usr/bin/env python
"""Horizontal reconfiguration churn timeline: committed entries per
segment for a churn-free run vs runs reconfiguring every R ticks via
config-as-log-value chunks (tpu/horizontal_batched.py), at two alpha
pipeline bounds — the knob that decides whether the old chunk's runway
covers the new bank's phase 1 (big alpha: no dip) or not (small alpha:
visible boundary stall). Writes results/horizontal_churn_device.json
and results/horizontal_churn_timeline.png.

Reference figure analog: horizontal/Leader.scala's chunk pipeline;
the vldb21 horizontal-reconfiguration experiments."""
import json

import jax
import jax.numpy as jnp

from frankenpaxos_tpu.tpu import horizontal_batched as hb

SEG = 25
SEGS = 24
BASE = dict(
    f=1, num_groups=64, window=32, slots_per_tick=2,
    lat_min=1, lat_max=3,
)


def run(reconfigure_every, alpha):
    cfg = hb.BatchedHorizontalConfig(
        reconfigure_every=reconfigure_every, alpha=alpha, **BASE
    )
    key = jax.random.PRNGKey(0)
    state = hb.init_state(cfg)
    t = jnp.int32(0)
    timeline = []
    for seg in range(SEGS):
        # Fresh key per segment: run_ticks folds by loop index starting
        # at 0, so reusing one key would replay identical random streams
        # every segment.
        before = int(state.committed)
        state, t = hb.run_ticks(
            cfg, state, t, SEG, jax.random.fold_in(key, seg)
        )
        timeline.append(int(state.committed) - before)
    s = hb.stats(cfg, state, t)
    inv = hb.check_invariants(cfg, state, t)
    assert all(bool(v) for v in inv.values()), inv
    return {
        "alpha": alpha,
        "reconfigure_every": reconfigure_every,
        "timeline_committed_per_segment": timeline,
        "stats": s,
    }


rows = {
    "churn_free": run(0, 16),
    "churn_alpha16": run(50, 16),
    "churn_alpha4": run(50, 4),
}
free_total = sum(rows["churn_free"]["timeline_committed_per_segment"][4:])
for k in ("churn_alpha16", "churn_alpha4"):
    total = sum(rows[k]["timeline_committed_per_segment"][4:])
    rows[k]["throughput_retained"] = round(total / free_total, 4)

with open("results/horizontal_churn_device.json", "w") as f:
    json.dump(rows, f, indent=1)

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt

x = range(1, SEGS + 1)
fig, ax = plt.subplots(figsize=(7.0, 3.2), dpi=150)
ax.plot(
    x, rows["churn_free"]["timeline_committed_per_segment"],
    marker="o", ms=3, lw=1.2, label="churn-free",
)
ax.plot(
    x, rows["churn_alpha16"]["timeline_committed_per_segment"],
    marker="s", ms=3, lw=1.2,
    label=f"reconfig/50 ticks, alpha=16 "
    f"({rows['churn_alpha16']['throughput_retained']:.0%} retained)",
)
ax.plot(
    x, rows["churn_alpha4"]["timeline_committed_per_segment"],
    marker="^", ms=3, lw=1.2,
    label=f"reconfig/50 ticks, alpha=4 "
    f"({rows['churn_alpha4']['throughput_retained']:.0%} retained)",
)
ax.set_xlabel(f"{SEG}-tick segment")
ax.set_ylabel("committed entries / segment")
ax.set_title("Horizontal config-as-log-value reconfiguration churn")
ax.grid(True, alpha=0.3)
ax.legend(frameon=False, fontsize=8)
ax.set_ylim(bottom=0)
fig.tight_layout()
out = "results/horizontal_churn_timeline.png"
fig.savefig(out)
print(out)
print(json.dumps({k: rows[k].get("throughput_retained") for k in rows}))
