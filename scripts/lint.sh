#!/bin/bash
# Repo-wide static analysis: runs the full frankenpaxos_tpu.analysis
# rule registry (AST contract rules + jaxpr/HLO trace rules) and exits
# with the finding count — 0 means every contract from PRs 1-4 holds in
# both the source and what XLA actually compiles. This is the one-shot
# CI entry point; `pytest -m lint` enforces the same registry per-rule.
#
# Usage:
#   scripts/lint.sh              # human-readable findings, exit = count
#   scripts/lint.sh --json       # structured report on stdout
#   scripts/lint.sh --rule ID    # any frankenpaxos_tpu.analysis flag
set -u
cd "$(dirname "$0")/.."
exec python -m frankenpaxos_tpu.analysis "$@"
