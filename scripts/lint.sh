#!/bin/bash
# Repo-wide static analysis: runs the full frankenpaxos_tpu.analysis
# rule registry (AST contract rules + jaxpr/HLO trace rules) and exits
# with the finding count — 0 means every contract from PRs 1-4 holds in
# both the source and what XLA actually compiles. This is the one-shot
# CI entry point; `pytest -m lint` enforces the same registry per-rule.
#
# Usage:
#   scripts/lint.sh              # human-readable findings, exit = count
#   scripts/lint.sh --json       # structured report on stdout
#   scripts/lint.sh --rule ID    # any frankenpaxos_tpu.analysis flag
set -u
cd "$(dirname "$0")/.."
# The trace-shardmap-kernel rule compiles sharded wrappers and the
# trace-fleet-onecompile rule compiles whole fleet bricks on a 2-row
# ('fleet', 'groups') PRODUCT mesh: give the CLI the same
# 8-virtual-device CPU mesh the pytest conftest uses, so the
# kernels x mesh and fleet-axis contracts are checked on
# single-device hosts too.
if [[ "${XLA_FLAGS:-}" != *xla_force_host_platform_device_count* ]]; then
  export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8"
fi
# Fast pre-step: the per-rule lint suite (fixture teeth + rule
# wrappers, seconds not minutes) fails fast before the full-registry
# CLI compiles trace rules. Report goes to stderr so `--json` stdout
# stays machine-parseable.
if [[ "${LINT_SKIP_PYTEST:-0}" != 1 ]]; then
  python -m pytest tests/ -m lint -q -p no:cacheprovider 1>&2 || exit $?
fi
exec python -m frankenpaxos_tpu.analysis "$@"
