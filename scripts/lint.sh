#!/bin/bash
# Repo-wide static analysis: runs the full frankenpaxos_tpu.analysis
# rule registry (AST contract rules + jaxpr/HLO trace rules + jaxpr
# DATAFLOW rules: PRNG stream lineage, salt disjointness, reachability
# dead writes, donation hazards) and exits with the finding count — 0
# means every contract from PRs 1-4 + 20 holds in both the source and
# what XLA actually compiles. This is the one-shot CI entry point;
# `pytest -m lint` enforces the same registry per-rule.
#
# Usage:
#   scripts/lint.sh              # human-readable findings, exit = count
#   scripts/lint.sh --json       # structured report on stdout
#   scripts/lint.sh --rule ID    # any frankenpaxos_tpu.analysis flag
#   LINT_BUDGET=45 scripts/lint.sh
#                                # opt-in EXTRA leg: re-run the trace +
#                                # dataflow layers at flagship shapes
#                                # under a 45s wall-clock budget
#                                # (per-rule timings + skipped-rules
#                                # report), never the default path
set -u
cd "$(dirname "$0")/.."
# The trace-shardmap-kernel rule compiles sharded wrappers and the
# trace-fleet-onecompile rule compiles whole fleet bricks on a 2-row
# ('fleet', 'groups') PRODUCT mesh: give the CLI the same
# 8-virtual-device CPU mesh the pytest conftest uses, so the
# kernels x mesh and fleet-axis contracts are checked on
# single-device hosts too.
if [[ "${XLA_FLAGS:-}" != *xla_force_host_platform_device_count* ]]; then
  export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8"
fi
# Fast pre-step: the per-rule lint suite (fixture teeth + rule
# wrappers, seconds not minutes) fails fast before the full-registry
# CLI compiles trace rules. Report goes to stderr so `--json` stdout
# stays machine-parseable.
if [[ "${LINT_SKIP_PYTEST:-0}" != 1 ]]; then
  python -m pytest tests/ -m lint -q -p no:cacheprovider 1>&2 || exit $?
fi
# Opt-in flagship-shape leg: a wall-clock budget (seconds) re-runs the
# trace + dataflow layers with every backend resized to its bench-scale
# flagship config. Runs BEFORE the default all-layer pass so its
# findings fail fast; it never replaces the default leg.
if [[ "${LINT_BUDGET:-}" != "" ]]; then
  python -m frankenpaxos_tpu.analysis --budget "${LINT_BUDGET}" || exit $?
fi
# Default fail-fast leg: all three layers (ast + trace + dataflow).
exec python -m frankenpaxos_tpu.analysis "$@"
