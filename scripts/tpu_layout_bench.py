#!/usr/bin/env python
"""One-off: measure the acceptor-major layout + Pallas kernel + the
HBM-bandwidth pass (dtype narrowing + buffer donation) on the live TPU
at the headline config. Appends rows to results/tpu_layout_r03.json.

Each row carries ticks/sec AND the memory side of the story:
``state_bytes`` (the dtype-policy footprint), ``bytes_per_tick`` (the
2 x state elementwise-sweep bound), and XLA's compiled memory analysis
(argument/output/temp/alias bytes — donation shows up as alias bytes,
and ``peak_bytes`` = arg + out + temp - alias is the measured peak the
acceptance criteria track). The measurement logic itself lives in
frankenpaxos_tpu.harness.microbench (compiled_memory_stats / bench_hbm)
so this script and the CPU tier-1 bench cannot drift apart.
"""
import json
import time

import jax

from frankenpaxos_tpu.harness.microbench import (
    bench_hbm,
    compiled_memory_stats,
)
from frankenpaxos_tpu.tpu import BatchedMultiPaxosConfig, TpuSimTransport
from frankenpaxos_tpu.tpu import multipaxos_batched as mb
from frankenpaxos_tpu.tpu.common import state_nbytes

rows = []
for name, kw in [
    ("xla_W64", dict(window=64, use_pallas=False)),
    ("xla_W128", dict(window=128, use_pallas=False)),
    ("pallas_W64", dict(window=64, use_pallas=True)),
    ("pallas_W128", dict(window=128, use_pallas=True)),
]:
    cfg = BatchedMultiPaxosConfig(
        f=1, num_groups=3334, slots_per_tick=8,
        lat_min=1, lat_max=3, drop_rate=0.0, retry_timeout=16, thrifty=True,
        **kw,
    )
    try:
        sim = TpuSimTransport(cfg, seed=0)
        sim.run(200); sim.block_until_ready()
        c0 = sim.committed()
        t0 = time.perf_counter()
        sim.run(1000); sim.block_until_ready()
        dt = time.perf_counter() - t0
        state0 = mb.init_state(cfg)
        row = {
            "variant": name, "ticks_per_sec": round(1000 / dt, 1),
            "committed_per_sec": round((sim.committed() - c0) / dt, 1),
            "p50_ticks": sim.stats()["commit_latency_p50_ticks"],
            "invariants_ok": all(sim.check_invariants().values()),
            "state_bytes": state_nbytes(state0),
            "bytes_per_tick": 2 * state_nbytes(state0),
        }
        row.update(compiled_memory_stats(mb.run_ticks, cfg, state0, 1000))
    except Exception as e:  # record compile failures instead of dying
        row = {"variant": name, "error": repr(e)[:500]}
    print(row, flush=True)
    rows.append(row)

# The HBM pass isolated at the W64 XLA config: before (int32, no
# donation) vs after (narrow dtypes, donated state) — the same
# measurement the CPU tier-1 microbench records, at TPU scale.
try:
    for r in bench_hbm(
        num_groups=3334, window=64, slots_per_tick=8, ticks=1000,
        cases=("int32_nodonate", "narrow_donate"),
    ):
        label = {
            "int32_nodonate": "hbm_before_int32_nodonate",
            "narrow_donate": "hbm_after_narrow_donate",
        }[r["case"]]
        row = dict(r, variant=label)
        print(row, flush=True)
        rows.append(row)
except Exception as e:
    rows.append({"variant": "hbm_before_after", "error": repr(e)[:500]})

with open("results/tpu_layout_r03.json", "w") as f:
    json.dump({"device": str(jax.devices()[0]), "rows": rows}, f, indent=1)
