#!/usr/bin/env python
"""One-off: measure the acceptor-major layout + Pallas kernel on the live
TPU at the headline config. Appends rows to results/tpu_layout_r03.json."""
import json
import time

import jax

from frankenpaxos_tpu.tpu import BatchedMultiPaxosConfig, TpuSimTransport

rows = []
for name, kw in [
    ("xla_W64", dict(window=64, use_pallas=False)),
    ("xla_W128", dict(window=128, use_pallas=False)),
    ("pallas_W64", dict(window=64, use_pallas=True)),
    ("pallas_W128", dict(window=128, use_pallas=True)),
]:
    cfg = BatchedMultiPaxosConfig(
        f=1, num_groups=3334, slots_per_tick=8,
        lat_min=1, lat_max=3, drop_rate=0.0, retry_timeout=16, thrifty=True,
        **kw,
    )
    try:
        sim = TpuSimTransport(cfg, seed=0)
        sim.run(200); sim.block_until_ready()
        c0 = sim.committed()
        t0 = time.perf_counter()
        sim.run(1000); sim.block_until_ready()
        dt = time.perf_counter() - t0
        row = {
            "variant": name, "ticks_per_sec": round(1000 / dt, 1),
            "committed_per_sec": round((sim.committed() - c0) / dt, 1),
            "p50_ticks": sim.stats()["commit_latency_p50_ticks"],
            "invariants_ok": all(sim.check_invariants().values()),
        }
    except Exception as e:  # record compile failures instead of dying
        row = {"variant": name, "error": repr(e)[:500]}
    print(row, flush=True)
    rows.append(row)

with open("results/tpu_layout_r03.json", "w") as f:
    json.dump({"device": str(jax.devices()[0]), "rows": rows}, f, indent=1)
