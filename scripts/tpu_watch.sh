#!/bin/bash
# Poll the axon TPU tunnel all round; whenever it is up, refresh the
# last-known-good TPU bench capture so the end-of-round bench.py always
# has a recent real-TPU artifact even if the tunnel wedges again.
# One status line per event in results/tpu_watch_r05.log.
cd /root/repo
LOG=results/tpu_watch_r05.log
log() { echo "$(date -u +%H:%M:%S) $*" >>"$LOG"; }
while true; do
  if timeout 90 python -c "
import jax, jax.numpy as jnp
d = jax.devices()[0]
assert 'cpu' not in str(d).lower(), d
x = jnp.ones((256, 256))
(x @ x).block_until_ready()
print(d)
" >>"$LOG" 2>&1; then
    log "PROBE OK"
    # K sweep once per round (cash the ~8M/s prediction). The sweep
    # refuses CPU fallbacks (exit 2) and resumes completed rows, so
    # gating the marker on exit 0 is exact.
    if [ ! -f results/.tpu_k_sweep_r05.done ]; then
      if timeout 3000 python scripts/tpu_k_sweep.py >>"$LOG" 2>&1; then
        touch results/.tpu_k_sweep_r05.done
        log "k sweep complete"
      else
        log "k sweep incomplete (rc=$?)"
      fi
    fi
    # Calibrated bench capture; bench.py itself persists the
    # last-known-good TPU artifact (results/bench_tpu_last_good.json)
    # on every successful live-TPU run.
    if timeout 1800 python bench.py >results/.bench_tpu_tmp.json 2>>"$LOG"; then
      mv results/.bench_tpu_tmp.json results/bench_tpu_recovered_r05.json
      log "bench captured"
    else
      rm -f results/.bench_tpu_tmp.json
      log "bench failed"
    fi
    # Keep refreshing every ~45 min while the tunnel stays up.
    sleep 2700
  else
    log "probe failed/hung"
    sleep 600
  fi
done
