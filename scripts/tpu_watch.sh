#!/bin/bash
# Poll the axon TPU tunnel; when it comes back, run the queued perf work.
# Writes a status line per probe to results/tpu_watch_r03.log and exits
# after the sweep completes (or keeps polling on failure).
cd /root/repo
LOG=results/tpu_watch_r03.log
while true; do
  ts=$(date -u +%H:%M:%S)
  if timeout 90 python -c "
import jax, jax.numpy as jnp
d = jax.devices()[0]
assert 'cpu' not in str(d).lower(), d
x = jnp.ones((256, 256))
(x @ x).block_until_ready()
print(d)
" >>"$LOG" 2>&1; then
    echo "$ts PROBE OK - running k sweep" >>"$LOG"
    timeout 3000 python scripts/tpu_k_sweep.py >>"$LOG" 2>&1
    rc=$?
    echo "$ts k sweep rc=$rc" >>"$LOG"
    # Also capture a full calibrated bench on the live chip, so a TPU
    # number exists even if the tunnel wedges again before round end.
    # Write via a temp file: a mid-bench tunnel drop must never truncate
    # an earlier good capture.
    if timeout 1800 python bench.py >results/.bench_tpu_tmp.json 2>>"$LOG"; then
      mv results/.bench_tpu_tmp.json results/bench_tpu_recovered_r03.json
      echo "$ts bench captured" >>"$LOG"
    else
      rm -f results/.bench_tpu_tmp.json
      echo "$ts bench failed" >>"$LOG"
    fi
    # Only stop once the sweep actually completed; a tunnel drop
    # mid-sweep goes back to polling.
    [ "$rc" -eq 0 ] && exit 0
  else
    echo "$ts probe failed/hung" >>"$LOG"
  fi
  sleep 600
done
