#!/usr/bin/env python
"""BASELINE config 5 at scale, properly: grid vs majority flexible
quorums at 100k acceptors (316 x 316 grid), >= 2k ticks, with a loss
sweep — the regime where the two quorum systems DIFFERENTIATE
(multipaxos/Config.scala:19-25 flexible quorum claims):

  * message economics: a grid write quorum is one row + one column
    (~631 of 100k acceptors) vs a majority of 50,001 — msgs_per_commit
    differs by ~2 orders of magnitude;
  * retry economics under loss: exact quorums have zero loss margin,
    and the grid's small quorums retry cheaply while majority retries
    re-broadcast to half the cluster.

Writes results/config5_flexible_quorum_scale_r05.json. CPU fallback is
honest (device recorded); reruns on TPU when the tunnel returns.
"""
import json

import jax

from frankenpaxos_tpu.tpu import grid_batched as gb

ROWS = COLS = 316  # 99,856 acceptors
TICKS = 2000

configs = [
    gb.GridBatchedConfig(
        rows=ROWS, cols=COLS, mode=mode, window=16, slots_per_tick=2,
        lat_min=1, lat_max=3, drop_rate=drop, retry_timeout=12,
    )
    for mode in ("grid", "majority")
    for drop in (0.0, 0.01, 0.03)
]

rows = []
for cfg in configs:
    (r,) = gb.sweep([cfg], num_ticks=TICKS, seed=0)
    r["invariants"] = {k: bool(v) for k, v in r["invariants"].items()}
    rows.append(r)
    print(r, flush=True)

out = {
    "device": str(jax.devices()[0]),
    "note": (
        "grid vs majority at ~100k acceptors over 2k ticks with a loss "
        "sweep; differentiation = msgs_per_commit (quorum size) and "
        "latency/commit collapse under loss (retry economics)"
    ),
    "ticks": TICKS,
    "points": rows,
}
with open("results/config5_flexible_quorum_scale_r05.json", "w") as f:
    json.dump(out, f, indent=1)
print("written results/config5_flexible_quorum_scale_r05.json")
