#!/usr/bin/env python
"""Proposal-rate sweep on the live TPU: cash the round-3 prediction that
committed/sec rises ~linearly in K at fixed ticks/s until the in-flight
window saturates (results/tpu_perf_analysis_r03.md: K=16/W=128 ~ 8M/s).

Refuses to run on a CPU fallback (exit 2) so the watcher never marks a
CPU sweep as the round's TPU sweep. Resumes from the incremental JSON:
rows completed by an earlier partial run are kept and their points
skipped, so a tunnel drop mid-sweep never loses measured rows."""
import json
import os
import sys
import time

import jax

OUT = "results/tpu_k_sweep_r05.json"

device = jax.devices()[0]
if "cpu" in str(device).lower():
    print(f"refusing to sweep on {device}; this sweep is TPU-only")
    sys.exit(2)

rows = []
if os.path.exists(OUT):
    # The exit-2 guard above means any existing file is TPU-measured.
    try:
        with open(OUT) as f:
            rows = json.load(f).get("rows", [])
    except (OSError, json.JSONDecodeError):
        rows = []
done = {(r["K"], r["W"], r.get("read_rate", r.get("reads_per_tick", 0))) for r in rows}


def save():
    tmp = OUT + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"device": str(device), "rows": rows}, f, indent=1)
    os.replace(tmp, OUT)


from frankenpaxos_tpu.tpu import BatchedMultiPaxosConfig, TpuSimTransport

# (K, W, reads): the r03 baseline point first (reproducibility anchor),
# then the predicted optimum K=16/W=128 and its neighbours, then the
# saturation probes.
POINTS = [
    (8, 64, 0),
    (16, 128, 0),
    (16, 96, 0),
    (24, 128, 0),
    (32, 128, 0),
    (32, 256, 0),
    (16, 128, 1),  # 1 read per group per tick = G reads/tick
]

for K, W, reads in POINTS:
    if (K, W, reads) in done:
        print(f"skip completed ({K}, {W}, {reads})", flush=True)
        continue
    cfg = BatchedMultiPaxosConfig(
        f=1, num_groups=3334, window=W, slots_per_tick=K,
        lat_min=1, lat_max=3, drop_rate=0.0, retry_timeout=16, thrifty=True,
        read_rate=reads, read_window=16 if reads else 0,
    )
    sim = TpuSimTransport(cfg, seed=0)
    sim.run(200); sim.block_until_ready()
    c0 = sim.committed()
    r0 = int(sim.state.reads_done) if reads else 0
    t0 = time.perf_counter()
    sim.run(600); sim.block_until_ready()
    dt = time.perf_counter() - t0
    row = {
        "K": K, "W": W, "read_rate": reads,
        "ticks_per_sec": round(600 / dt, 1),
        "committed_per_sec": round((sim.committed() - c0) / dt, 1),
        "p50_ticks": sim.stats()["commit_latency_p50_ticks"],
        "invariants_ok": all(sim.check_invariants().values()),
    }
    if reads:
        row["reads_per_sec"] = round((int(sim.state.reads_done) - r0) / dt, 1)
        row["read_p50_ticks"] = sim.stats()["read_latency_p50_ticks"]
    print(row, flush=True)
    rows.append(row)
    save()
