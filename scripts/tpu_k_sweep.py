#!/usr/bin/env python
"""One-off: proposal-rate sweep on the live TPU — is committed/sec limited
by bandwidth-per-window (flat in K) or fixed overheads (rises with K)?
Writes results/tpu_k_sweep_r03.json incrementally after each row."""
import json
import time

import jax

from frankenpaxos_tpu.tpu import BatchedMultiPaxosConfig, TpuSimTransport

rows = []


def save():
    with open("results/tpu_k_sweep_r03.json", "w") as f:
        json.dump({"device": str(jax.devices()[0]), "rows": rows}, f, indent=1)


for K, W, reads in [(8, 64, 0), (16, 128, 0), (32, 256, 0), (8, 64, 2)]:
    cfg = BatchedMultiPaxosConfig(
        f=1, num_groups=3334, window=W, slots_per_tick=K,
        lat_min=1, lat_max=3, drop_rate=0.0, retry_timeout=16, thrifty=True,
        reads_per_tick=reads, read_window=4 * reads,
    )
    sim = TpuSimTransport(cfg, seed=0)
    sim.run(200); sim.block_until_ready()
    c0 = sim.committed()
    r0 = int(sim.state.reads_done) if reads else 0
    t0 = time.perf_counter()
    sim.run(600); sim.block_until_ready()
    dt = time.perf_counter() - t0
    row = {
        "K": K, "W": W, "reads_per_tick": reads,
        "ticks_per_sec": round(600 / dt, 1),
        "committed_per_sec": round((sim.committed() - c0) / dt, 1),
        "p50_ticks": sim.stats()["commit_latency_p50_ticks"],
        "invariants_ok": all(sim.check_invariants().values()),
    }
    if reads:
        row["reads_per_sec"] = round((int(sim.state.reads_done) - r0) / dt, 1)
        row["read_p50_ticks"] = sim.stats()["read_latency_p50_ticks"]
    print(row, flush=True)
    rows.append(row)
    save()
