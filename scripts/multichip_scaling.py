#!/usr/bin/env python
"""Multi-device scaling measurement on the virtual CPU mesh: fixed
total work, D in {1, 2, 4, 8} devices, three paths —

  * flagship write path (group-local: XLA partitions the scan with no
    cross-device traffic beyond scalar stat reductions),
  * flagship + device-side ReadBatcher reads (adds the wave's
    cross-device max/min reductions — the ICI-analog cost),
  * grid quorums (quorums SPAN devices: cross-device reductions on the
    hot path).

This box exposes ONE physical core, so a virtual mesh cannot show
wall-clock speedup; what the curve measures honestly is the SPMD
PARTITIONING + COLLECTIVE OVERHEAD of each path — ticks/s at D devices
relative to D=1 for identical total work. Group-local paths should hold
near 1.0 (partitioning is ~free, validating the sharding design);
collective-bearing paths pay for their reductions. Real-chip speedup is
the TPU watcher's job when the tunnel cooperates; correctness of the
same sharded program is pinned by tests/test_hlo_sharding.py and the
driver's dryrun_multichip.

Run with:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8
Writes results/multichip_scaling_r05.json + results/multichip_scaling_r05.png.
"""
import json
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from frankenpaxos_tpu.parallel import make_mesh, run_ticks_sharded, shard_state
from frankenpaxos_tpu.tpu import BatchedMultiPaxosConfig, init_state
from frankenpaxos_tpu.tpu import grid_batched as gb

devices = jax.devices()
assert len(devices) >= 8, (
    "need 8 virtual devices: set JAX_PLATFORMS=cpu "
    "XLA_FLAGS=--xla_force_host_platform_device_count=8"
)

G_TOTAL = 512  # divisible by 8; fixed TOTAL work at every D
TICKS = 200
DS = (1, 2, 4, 8)


def measure_flagship(n_dev, reads):
    cfg = BatchedMultiPaxosConfig(
        f=1, num_groups=G_TOTAL, window=32, slots_per_tick=4,
        lat_min=1, lat_max=3, retry_timeout=16,
        read_rate=8 if reads else 0, read_window=32 if reads else 0,
    )
    mesh = make_mesh(devices[:n_dev])
    state = shard_state(init_state(cfg), mesh)
    key = jax.random.PRNGKey(0)
    t0j = jnp.zeros((), jnp.int32)
    state, t = run_ticks_sharded(cfg, mesh, state, t0j, TICKS, key)
    jax.block_until_ready(state)  # compile + ramp
    c0 = int(state.committed)
    r0 = int(state.reads_done) if reads else 0
    t0 = time.perf_counter()
    # Fresh key: run_ticks folds by loop index from 0, so reusing the
    # warmup key would replay its random stream in the timed segment.
    state, t = run_ticks_sharded(
        cfg, mesh, state, t, TICKS, jax.random.fold_in(key, 1)
    )
    jax.block_until_ready(state)
    dt = time.perf_counter() - t0
    row = {
        "devices": n_dev,
        "ticks_per_sec": round(TICKS / dt, 2),
        "committed_per_sec": round((int(state.committed) - c0) / dt, 1),
    }
    if reads:
        row["reads_per_sec"] = round((int(state.reads_done) - r0) / dt, 1)
    return row


def measure_grid(n_dev):
    cfg = gb.GridBatchedConfig(
        rows=8, cols=4, mode="majority", window=8, slots_per_tick=2
    )
    mesh = make_mesh(devices[:n_dev])
    state = gb.init_state(cfg)
    specs = {"p2a_arrival": P(None, "groups", None),
             "p2b_arrival": P(None, "groups", None)}
    import dataclasses as dc

    placed = {}
    for f_ in dc.fields(state):
        arr = getattr(state, f_.name)
        spec = specs.get(f_.name, P())
        placed[f_.name] = jax.device_put(arr, NamedSharding(mesh, spec))
    state = type(state)(**placed)
    key = jax.random.PRNGKey(0)
    run = jax.jit(gb.run_ticks.__wrapped__, static_argnums=(0, 3))
    state, t = run(cfg, state, jnp.zeros((), jnp.int32), TICKS, key)
    jax.block_until_ready(state)
    c0 = int(state.committed)
    t0 = time.perf_counter()
    state, t = run(cfg, state, t, TICKS, jax.random.fold_in(key, 1))
    jax.block_until_ready(state)
    dt = time.perf_counter() - t0
    return {
        "devices": n_dev,
        "ticks_per_sec": round(TICKS / dt, 2),
        "committed_per_sec": round(
            (int(state.committed) - c0) / dt, 1
        ),
    }


out = {
    "device": str(devices[0]),
    "physical_cores": 1,
    "note": (
        "fixed total work, virtual mesh on one physical core: the curve "
        "measures SPMD partitioning + collective overhead (ticks/s vs "
        "D=1), not speedup — see module docstring"
    ),
    "write_path": [],
    "read_path": [],
    "grid": [],
}
for d in DS:
    out["write_path"].append(measure_flagship(d, reads=False))
    print("write", out["write_path"][-1], flush=True)
for d in DS:
    out["read_path"].append(measure_flagship(d, reads=True))
    print("read", out["read_path"][-1], flush=True)
for d in DS:
    out["grid"].append(measure_grid(d))
    print("grid", out["grid"][-1], flush=True)

for series in ("write_path", "read_path", "grid"):
    base = out[series][0]["ticks_per_sec"]
    for row in out[series]:
        row["efficiency_vs_1dev"] = round(row["ticks_per_sec"] / base, 3)

with open("results/multichip_scaling_r05.json", "w") as f:
    json.dump(out, f, indent=1)

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt

fig, ax = plt.subplots(figsize=(6.4, 3.4), dpi=150)
for series, label, marker in [
    ("write_path", "write path (group-local)", "o"),
    ("read_path", "write + batched reads (wave collectives)", "s"),
    ("grid", "grid quorums (cross-device quorums)", "^"),
]:
    xs = [r["devices"] for r in out[series]]
    ys = [r["efficiency_vs_1dev"] for r in out[series]]
    ax.plot(xs, ys, marker=marker, ms=4, lw=1.3, label=label)
ax.axhline(1.0, color="gray", lw=0.8, ls="--", alpha=0.6)
ax.set_xscale("log", base=2)
ax.set_xticks(list(DS))
ax.set_xticklabels([str(d) for d in DS])
ax.set_xlabel("devices (virtual 8-CPU mesh, 1 physical core)")
ax.set_ylabel("ticks/s vs 1 device")
ax.set_title("SPMD partitioning overhead, fixed total work")
ax.grid(True, alpha=0.3)
ax.legend(frameon=False, fontsize=8)
fig.tight_layout()
fig.savefig("results/multichip_scaling_r05.png")
print("results/multichip_scaling_r05.png")
