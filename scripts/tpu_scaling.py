#!/usr/bin/env python
"""One-off: ticks/sec scaling sweep G in {334, 3334, 33334} on the live TPU,
plus a jax.profiler trace at the headline config. Writes
results/tpu_scaling_r03.json and results/tpu_trace_r03/."""
import json
import time

import jax

from frankenpaxos_tpu.tpu import BatchedMultiPaxosConfig, TpuSimTransport

out = {"device": str(jax.devices()[0]), "sweep": []}
for G in (334, 3334, 33334):
    cfg = BatchedMultiPaxosConfig(
        f=1, num_groups=G, window=64, slots_per_tick=8,
        lat_min=1, lat_max=3, drop_rate=0.0, retry_timeout=16, thrifty=True,
    )
    sim = TpuSimTransport(cfg, seed=0)
    sim.run(200); sim.block_until_ready()  # compile + ramp
    c0 = sim.committed()
    t0 = time.perf_counter()
    sim.run(500); sim.block_until_ready()
    dt = time.perf_counter() - t0
    committed = sim.committed() - c0
    row = {
        "num_groups": G, "num_acceptors": cfg.num_acceptors,
        "ticks_per_sec": round(500 / dt, 1),
        "committed_per_sec": round(committed / dt, 1),
        "wall_seconds": round(dt, 3),
    }
    print(row)
    out["sweep"].append(row)

# Profile the headline config.
cfg = BatchedMultiPaxosConfig(
    f=1, num_groups=3334, window=64, slots_per_tick=8,
    lat_min=1, lat_max=3, drop_rate=0.0, retry_timeout=16, thrifty=True,
)
sim = TpuSimTransport(cfg, seed=0)
sim.profile(500, "results/tpu_trace_r03")
print("trace written")

with open("results/tpu_scaling_r03.json", "w") as f:
    json.dump(out, f, indent=1)
