#!/usr/bin/env python
"""Render the Matchmaker churn timeline figure from the recorded
config-4 run (results/config4_matchmaker_churn_device.json) — the
analog of the reference's vldb20_matchmaker latency/throughput figure:
committed entries per segment, churn-free vs with periodic device-side
reconfigurations, the dips landing on the reconfiguration waves."""
import json

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt

with open("results/config4_matchmaker_churn_device.json") as f:
    d = json.load(f)

free = d["churn_free"]["timeline_committed_per_segment"]
churn = d["with_churn"]["timeline_committed_per_segment"]
x = range(1, len(free) + 1)

fig, ax = plt.subplots(figsize=(7.0, 3.2), dpi=150)
ax.plot(x, free, marker="o", ms=3, lw=1.2, label="churn-free")
ax.plot(
    x, churn, marker="s", ms=3, lw=1.2,
    label="reconfiguration every 100 ticks",
)
ax.set_xlabel("25-tick segment")
ax.set_ylabel("committed entries / segment")
ax.set_title(
    "Device-side Matchmaker reconfiguration churn "
    f"({d['throughput_retained']:.0%} throughput retained)"
)
ax.grid(True, alpha=0.3)
ax.legend(frameon=False, fontsize=8)
ax.set_ylim(bottom=0)
fig.tight_layout()
out = "results/config4_churn_timeline.png"
fig.savefig(out)
print(out)
