#!/usr/bin/env python
"""The eurosys-fig1 analog (reference
``benchmarks/eurosys/fig1_batched_multipaxos_results.csv``): throughput
vs offered load for COUPLED MultiPaxos, COMPARTMENTALIZED MultiPaxos,
and the unreplicated ceiling, at the 10k-acceptor headline scale.

In the reference, compartmentalization decouples the leader from
batching/broadcast so more commands can be in flight; the batched
model's analog of that decoupling is the in-flight window W (a coupled
leader's pipeline is shallow — W=8 slots; proxy leaders/batchers deepen
it to W=256). Offered load is K (proposals per group per tick); a
coupled leader ADMITS at most W/2 per tick (its pipeline bound), which
is exactly how it saturates. Throughput is measured in MODELED time
(committed entries per tick, aggregated over all groups): wall-clock
sim rates would conflate array-size compute cost with protocol
behavior. The figure shows the coupled pipeline flat-lining at its
window/latency bound while the compartmentalized one tracks the
unreplicated ceiling — the claim fig1 makes.

Writes results/eurosys_fig1.csv + results/eurosys_fig1.png.
"""
import csv

import jax
import jax.numpy as jnp

from frankenpaxos_tpu.tpu import BatchedMultiPaxosConfig, TpuSimTransport
from frankenpaxos_tpu.tpu import unreplicated_batched as ub

G = 3334
KS = (1, 2, 4, 8, 16, 32)
COUPLED_W = 8  # shallow leader pipeline (no proxy decoupling)
DECOUPLED_W = 256  # compartmentalized in-flight depth
TICKS = 300


def measure_multipaxos(K, W):
    admitted = min(K, W // 2)  # the pipeline admission bound
    cfg = BatchedMultiPaxosConfig(
        f=1, num_groups=G, window=W, slots_per_tick=admitted,
        lat_min=1, lat_max=3, retry_timeout=16, thrifty=True,
    )
    sim = TpuSimTransport(cfg, seed=0)
    sim.run(100)  # ramp
    c0 = sim.committed()
    sim.run(TICKS)
    sim.block_until_ready()
    s = sim.stats()
    return {
        "per_tick": round((sim.committed() - c0) / TICKS, 1),
        "p50_latency_ticks": s["commit_latency_p50_ticks"],
    }


def measure_ceiling(K):
    cfg = ub.BatchedUnreplicatedConfig(
        num_servers=G, window=DECOUPLED_W, ops_per_tick=K,
        lat_min=1, lat_max=3,
    )
    state = ub.init_state(cfg)
    state, t = ub.run_ticks(
        cfg, state, jnp.int32(0), 100, jax.random.PRNGKey(0)
    )
    d0 = int(state.done)
    state, t = ub.run_ticks(cfg, state, t, TICKS, jax.random.PRNGKey(1))
    jax.block_until_ready(state)
    return {"per_tick": round((int(state.done) - d0) / TICKS, 1)}


rows = []
for K in KS:
    coupled = measure_multipaxos(K, COUPLED_W)
    decoupled = measure_multipaxos(K, DECOUPLED_W)
    ceiling = measure_ceiling(K)
    rows.append(
        {
            "offered_load_K": K,
            "offered_entries_per_tick": K * G,
            "coupled_per_tick": coupled["per_tick"],
            "coupled_p50_ticks": coupled["p50_latency_ticks"],
            "compartmentalized_per_tick": decoupled["per_tick"],
            "compartmentalized_p50_ticks": decoupled["p50_latency_ticks"],
            "unreplicated_per_tick": ceiling["per_tick"],
        }
    )
    print(rows[-1], flush=True)

with open("results/eurosys_fig1.csv", "w", newline="") as f:
    w = csv.DictWriter(f, fieldnames=list(rows[0].keys()))
    w.writeheader()
    w.writerows(rows)

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt

xs = [r["offered_entries_per_tick"] / 1e3 for r in rows]
fig, ax = plt.subplots(figsize=(6.6, 3.4), dpi=150)
ax.plot(
    xs, [r["unreplicated_per_tick"] / 1e3 for r in rows],
    marker="^", ms=4, lw=1.3, color="gray", label="unreplicated ceiling",
)
ax.plot(
    xs, [r["compartmentalized_per_tick"] / 1e3 for r in rows],
    marker="s", ms=4, lw=1.3,
    label=f"compartmentalized MultiPaxos (W={DECOUPLED_W})",
)
ax.plot(
    xs, [r["coupled_per_tick"] / 1e3 for r in rows],
    marker="o", ms=4, lw=1.3,
    label=f"coupled MultiPaxos (W={COUPLED_W})",
)
ax.set_xscale("log", base=2)
ax.set_xlabel("offered load (K entries/tick, 10k acceptors)")
ax.set_ylabel("committed (K entries/tick)")
ax.set_title("Coupled vs compartmentalized MultiPaxos vs ceiling")
ax.grid(True, alpha=0.3)
ax.legend(frameon=False, fontsize=8)
ax.set_ylim(bottom=0)
fig.tight_layout()
fig.savefig("results/eurosys_fig1.png")
print("results/eurosys_fig1.png")
