"""Read-write quorum systems for Flexible Paxos.

Capability parity with the reference ``quorums`` package
(``shared/src/main/scala/frankenpaxos/quorums/QuorumSystem.scala:16-24``):
``SimpleMajority`` (``SimpleMajority.scala:19-56``), ``UnanimousWrites``
(``UnanimousWrites.scala:17-``), and ``Grid`` (rows are read quorums, one
element per row is a write quorum; ``Grid.scala:5-57``), plus wire
round-tripping (the analog of ``QuorumSystem.toProto/fromProto``,
``QuorumSystem.scala:26-61``).

A read-write quorum system over a node set X is two families R, W of
subsets of X such that every r in R intersects every w in W. MultiPaxos
needs only this (Flexible Paxos); simple majorities are the special case.
"""

from __future__ import annotations

import dataclasses
import random
from typing import FrozenSet, Generic, List, Sequence, Set, Tuple, TypeVar

from frankenpaxos_tpu.core import wire

T = TypeVar("T")


class QuorumSystem(Generic[T]):
    def nodes(self) -> FrozenSet[T]:
        raise NotImplementedError

    def random_read_quorum(self) -> Set[T]:
        raise NotImplementedError

    def random_write_quorum(self) -> Set[T]:
        raise NotImplementedError

    def is_read_quorum(self, xs: Set[T]) -> bool:
        raise NotImplementedError

    def is_write_quorum(self, xs: Set[T]) -> bool:
        raise NotImplementedError

    def is_superset_of_read_quorum(self, xs: Set[T]) -> bool:
        raise NotImplementedError

    def is_superset_of_write_quorum(self, xs: Set[T]) -> bool:
        raise NotImplementedError


class SimpleMajority(QuorumSystem[T]):
    """Every majority is both a read and a write quorum."""

    def __init__(self, members: Set[T], seed: int = 0):
        if not members:
            raise ValueError("SimpleMajority requires at least one member")
        self.members = frozenset(members)
        self._rand = random.Random(seed)
        self.quorum_size = len(self.members) // 2 + 1

    def __repr__(self) -> str:
        return f"SimpleMajority(members={sorted(self.members)})"

    def nodes(self) -> FrozenSet[T]:
        return self.members

    def random_read_quorum(self) -> Set[T]:
        return set(self._rand.sample(sorted(self.members), self.quorum_size))

    def random_write_quorum(self) -> Set[T]:
        return self.random_read_quorum()

    def is_read_quorum(self, xs: Set[T]) -> bool:
        if not xs <= self.members:
            raise ValueError(f"{xs} is not a subset of {self.members}")
        return len(xs) >= self.quorum_size

    def is_write_quorum(self, xs: Set[T]) -> bool:
        return self.is_read_quorum(xs)

    def is_superset_of_read_quorum(self, xs: Set[T]) -> bool:
        return len(xs & self.members) >= self.quorum_size

    def is_superset_of_write_quorum(self, xs: Set[T]) -> bool:
        return self.is_superset_of_read_quorum(xs)


class UnanimousWrites(QuorumSystem[T]):
    """The single write quorum is all members; any non-empty subset reads."""

    def __init__(self, members: Set[T], seed: int = 0):
        if not members:
            raise ValueError("UnanimousWrites requires at least one member")
        self.members = frozenset(members)
        self._rand = random.Random(seed)

    def __repr__(self) -> str:
        return f"UnanimousWrites(members={sorted(self.members)})"

    def nodes(self) -> FrozenSet[T]:
        return self.members

    def random_read_quorum(self) -> Set[T]:
        return {self._rand.choice(sorted(self.members))}

    def random_write_quorum(self) -> Set[T]:
        return set(self.members)

    def is_read_quorum(self, xs: Set[T]) -> bool:
        if not xs <= self.members:
            raise ValueError(f"{xs} is not a subset of {self.members}")
        return len(xs) > 0

    def is_write_quorum(self, xs: Set[T]) -> bool:
        if not xs <= self.members:
            raise ValueError(f"{xs} is not a subset of {self.members}")
        return xs == self.members

    def is_superset_of_read_quorum(self, xs: Set[T]) -> bool:
        return bool(xs & self.members)

    def is_superset_of_write_quorum(self, xs: Set[T]) -> bool:
        return self.members <= xs


class Grid(QuorumSystem[T]):
    """Nodes in an n x m grid; each row is a read quorum, each one-per-row
    transversal (in practice, each column) is a write quorum."""

    def __init__(self, grid: Sequence[Sequence[T]], seed: int = 0):
        if not grid:
            raise ValueError("Grid requires a non-empty grid")
        if any(len(row) != len(grid[0]) for row in grid):
            raise ValueError("Grid requires equal-sized rows")
        self.grid: List[List[T]] = [list(row) for row in grid]
        self._rows = [frozenset(row) for row in self.grid]
        self._rand = random.Random(seed)
        self._nodes = frozenset(x for row in self._rows for x in row)

    def __repr__(self) -> str:
        return f"Grid(grid={self.grid})"

    def nodes(self) -> FrozenSet[T]:
        return self._nodes

    def random_read_quorum(self) -> Set[T]:
        return set(self.grid[self._rand.randrange(len(self.grid))])

    def random_write_quorum(self) -> Set[T]:
        i = self._rand.randrange(len(self.grid[0]))
        return {row[i] for row in self.grid}

    def is_read_quorum(self, xs: Set[T]) -> bool:
        if not xs <= self._nodes:
            raise ValueError(f"{xs} is not a subset of {self._nodes}")
        return any(row <= xs for row in self._rows)

    def is_write_quorum(self, xs: Set[T]) -> bool:
        if not xs <= self._nodes:
            raise ValueError(f"{xs} is not a subset of {self._nodes}")
        return all(row & xs for row in self._rows)

    def is_superset_of_read_quorum(self, xs: Set[T]) -> bool:
        return any(row <= xs for row in self._rows)

    def is_superset_of_write_quorum(self, xs: Set[T]) -> bool:
        return all(row & xs for row in self._rows)


# -- Wire round-tripping (QuorumSystem.scala:26-61) --------------------------


@wire.message
@dataclasses.dataclass(frozen=True)
class QuorumSystemProto:
    kind: str  # "simple_majority" | "unanimous_writes" | "grid"
    members: tuple  # flat members, or row tuples for grid
    num_cols: int


def to_proto(qs: QuorumSystem[int]) -> QuorumSystemProto:
    if isinstance(qs, SimpleMajority):
        return QuorumSystemProto("simple_majority", tuple(sorted(qs.members)), 0)
    if isinstance(qs, UnanimousWrites):
        return QuorumSystemProto("unanimous_writes", tuple(sorted(qs.members)), 0)
    if isinstance(qs, Grid):
        flat = tuple(x for row in qs.grid for x in row)
        return QuorumSystemProto("grid", flat, len(qs.grid[0]))
    raise TypeError(f"unserializable quorum system {qs!r}")


def from_proto(proto: QuorumSystemProto, seed: int = 0) -> QuorumSystem[int]:
    if proto.kind == "simple_majority":
        return SimpleMajority(set(proto.members), seed)
    if proto.kind == "unanimous_writes":
        return UnanimousWrites(set(proto.members), seed)
    if proto.kind == "grid":
        m = proto.num_cols
        rows = [
            list(proto.members[i : i + m]) for i in range(0, len(proto.members), m)
        ]
        return Grid(rows, seed)
    raise ValueError(f"unknown quorum system kind {proto.kind!r}")
