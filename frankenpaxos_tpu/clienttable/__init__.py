"""Exactly-once semantics for out-of-order executors.

Capability parity with the reference ``clienttable`` package
(``clienttable/ClientTable.scala:9-110``). Protocols like EPaxos/BPaxos may
execute a client's commands out of client-id order, so a simple
largest-id-per-client table is wrong. This table caches the output of the
*largest* executed id per client and an :class:`IntPrefixSet` of *all*
executed ids, so "was id i executed?" is exact while old outputs can be
dropped. Serializable (the analog of ``ClientTable.proto``) because
reconfiguration/state-transfer paths ship it between replicas.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Generic, Optional, Tuple, TypeVar

from frankenpaxos_tpu.compact import IntPrefixSet, IntPrefixSetProto
from frankenpaxos_tpu.core import wire

ClientAddress = TypeVar("ClientAddress")
Output = TypeVar("Output")


class NotExecuted:
    def __repr__(self) -> str:
        return "NotExecuted"

    def __eq__(self, other) -> bool:
        return isinstance(other, NotExecuted)

    def __hash__(self):
        return hash("NotExecuted")


@dataclasses.dataclass(frozen=True)
class Executed(Generic[Output]):
    """The command was executed; ``output`` is cached only if it was the
    client's latest command."""

    output: Optional[Output]


@dataclasses.dataclass
class ClientState(Generic[Output]):
    largest_id: int
    largest_output: Output
    executed_ids: IntPrefixSet


@wire.message
@dataclasses.dataclass(frozen=True)
class ClientTableProto:
    entries: tuple  # of (address_bytes, largest_id, output_bytes, prefix_proto)


class ClientTable(Generic[ClientAddress, Output]):
    def __init__(self) -> None:
        self.states: Dict[ClientAddress, ClientState[Output]] = {}

    def __repr__(self) -> str:
        return f"ClientTable({self.states!r})"

    def executed(self, client: ClientAddress, client_id: int):
        """NotExecuted | Executed(Some(output)) | Executed(None)
        (ClientTable.scala:60-85)."""
        state = self.states.get(client)
        if state is None or not state.executed_ids.contains(client_id):
            return NotExecuted()
        if client_id == state.largest_id:
            return Executed(state.largest_output)
        return Executed(None)

    def execute(self, client: ClientAddress, client_id: int, output: Output) -> None:
        """Record that ``client_id`` was executed with ``output``
        (ClientTable.scala:87-110). Must not already be executed."""
        state = self.states.get(client)
        if state is None:
            state = ClientState(
                largest_id=client_id,
                largest_output=output,
                executed_ids=IntPrefixSet(),
            )
            self.states[client] = state
        if state.executed_ids.contains(client_id):
            raise ValueError(f"client {client!r} id {client_id} executed twice")
        state.executed_ids.add(client_id)
        if client_id >= state.largest_id:
            state.largest_id = client_id
            state.largest_output = output

    # -- Serialization (ClientTable.proto analog) ---------------------------

    def to_proto(self, address_to_bytes, output_to_bytes) -> ClientTableProto:
        entries = []
        for client, state in sorted(
            self.states.items(), key=lambda kv: address_to_bytes(kv[0])
        ):
            entries.append(
                (
                    address_to_bytes(client),
                    state.largest_id,
                    output_to_bytes(state.largest_output),
                    state.executed_ids.to_proto(),
                )
            )
        return ClientTableProto(tuple(entries))

    @staticmethod
    def from_proto(
        proto: ClientTableProto, address_from_bytes, output_from_bytes
    ) -> "ClientTable":
        table: ClientTable = ClientTable()
        for addr_bytes, largest_id, output_bytes, prefix in proto.entries:
            table.states[address_from_bytes(addr_bytes)] = ClientState(
                largest_id=largest_id,
                largest_output=output_from_bytes(output_bytes),
                executed_ids=IntPrefixSet.from_proto(prefix),
            )
        return table
