"""Actor base class, mirroring the reference ``Actor[Transport]``
(``shared/src/main/scala/frankenpaxos/Actor.scala:7-51``): constructed with
(address, transport, logger), self-registers, declares a serializer, and
implements ``receive(src, msg)``. Outbound communication via typed ``chan``s
or raw ``send``/``send_no_flush``/``flush``; timers via ``timer``.

Protocol roles subclass this for the Python execution backends (sim + TCP).
The TPU backend does not use this class: there, roles are pure step
functions over batched array state (see ``frankenpaxos_tpu.tpu``);
``tests/test_tpu_cross_validation.py`` checks that the two produce the
same per-slot chosen values on aligned scenarios.
"""

from __future__ import annotations

from typing import Any, Callable

from frankenpaxos_tpu.core.address import Address
from frankenpaxos_tpu.core.channel import Chan
from frankenpaxos_tpu.core.logger import Logger
from frankenpaxos_tpu.core.serializer import Serializer, WireSerializer
from frankenpaxos_tpu.core.timer import Timer
from frankenpaxos_tpu.core.transport import Transport

_WIRE = WireSerializer()


class Actor:
    serializer: Serializer = _WIRE

    def __init__(self, address: Address, transport: Transport, logger: Logger):
        self.address = address
        self.transport = transport
        self.logger = logger
        transport.register(address, self)  # Actor.scala:19-20

    def receive(self, src: Address, msg: Any) -> None:
        raise NotImplementedError

    def enable_metrics(self, collectors, role: str) -> None:
        """Instrument this actor with per-message-type request counts and
        handler-latency summaries — the analog of the reference's per-role
        Metrics classes and its ``timed(label){...}`` handler wrapper
        (``multipaxos/Acceptor.scala:107-119``), applied at the actor
        boundary so every role of every protocol gets the same
        observability without hand-rolled Metrics classes. Called by the
        deployment mains after construction; roles with richer
        domain-specific metrics (e.g. multipaxos) add them on top."""
        import time as _time

        requests_total = collectors.counter(
            f"{role}_requests_total",
            f"Total messages received by {role}, by type.",
            labels=("type",),
        )
        latency = collectors.summary(
            f"{role}_handler_latency_seconds",
            f"Receive-handler latency of {role}, by message type.",
            labels=("type",),
        )
        inner = self.receive

        def timed_receive(src: Address, msg: Any) -> None:
            label = type(msg).__name__
            t0 = _time.perf_counter()
            inner(src, msg)
            elapsed = _time.perf_counter() - t0
            requests_total.labels(label).inc()
            latency.labels(label).observe(elapsed)

        # Instance attribute shadows the method for transport dispatch.
        self.receive = timed_receive

    def chan(self, dst: Address, serializer: Serializer = _WIRE) -> Chan:
        return Chan(self.transport, self.address, dst, serializer)

    def send(self, dst: Address, data: bytes) -> None:
        self.transport.send(self.address, dst, data)

    def send_no_flush(self, dst: Address, data: bytes) -> None:
        self.transport.send_no_flush(self.address, dst, data)

    def flush(self, dst: Address) -> None:
        self.transport.flush(self.address, dst)

    def timer(self, name: str, delay: float, f: Callable[[], None]) -> Timer:
        return self.transport.timer(self.address, name, delay, f)
