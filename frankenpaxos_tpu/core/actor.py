"""Actor base class, mirroring the reference ``Actor[Transport]``
(``shared/src/main/scala/frankenpaxos/Actor.scala:7-51``): constructed with
(address, transport, logger), self-registers, declares a serializer, and
implements ``receive(src, msg)``. Outbound communication via typed ``chan``s
or raw ``send``/``send_no_flush``/``flush``; timers via ``timer``.

Protocol roles subclass this for the Python execution backends (sim + TCP).
The TPU backend does not use this class: there, roles are pure step
functions over batched array state (see ``frankenpaxos_tpu.tpu``);
``tests/test_tpu_cross_validation.py`` checks that the two produce the
same per-slot chosen values on aligned scenarios.
"""

from __future__ import annotations

from typing import Any, Callable

from frankenpaxos_tpu.core.address import Address
from frankenpaxos_tpu.core.channel import Chan
from frankenpaxos_tpu.core.logger import Logger
from frankenpaxos_tpu.core.serializer import Serializer, WireSerializer
from frankenpaxos_tpu.core.timer import Timer
from frankenpaxos_tpu.core.transport import Transport

_WIRE = WireSerializer()


class Actor:
    serializer: Serializer = _WIRE

    def __init__(self, address: Address, transport: Transport, logger: Logger):
        self.address = address
        self.transport = transport
        self.logger = logger
        transport.register(address, self)  # Actor.scala:19-20

    def receive(self, src: Address, msg: Any) -> None:
        raise NotImplementedError

    def chan(self, dst: Address, serializer: Serializer = _WIRE) -> Chan:
        return Chan(self.transport, self.address, dst, serializer)

    def send(self, dst: Address, data: bytes) -> None:
        self.transport.send(self.address, dst, data)

    def send_no_flush(self, dst: Address, data: bytes) -> None:
        self.transport.send_no_flush(self.address, dst, data)

    def flush(self, dst: Address) -> None:
        self.transport.flush(self.address, dst)

    def timer(self, name: str, delay: float, f: Callable[[], None]) -> Timer:
        return self.transport.timer(self.address, name, delay, f)
