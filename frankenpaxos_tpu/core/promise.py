"""A tiny single-threaded future/promise.

The reference's client APIs return Scala ``Future``s resolved on the
transport's event loop (``multipaxos/Client.scala:1035-1069``). Since every
transport here is single-threaded, a minimal callback future suffices."""

from __future__ import annotations

from typing import Any, Callable, List, Optional


class Promise:
    def __init__(self) -> None:
        self.done = False
        self.value: Any = None
        self.exception: Optional[BaseException] = None
        self._callbacks: List[Callable[["Promise"], None]] = []

    def success(self, value: Any) -> None:
        if self.done:
            raise RuntimeError("promise already completed")
        self.done = True
        self.value = value
        for cb in self._callbacks:
            cb(self)

    def failure(self, exception: BaseException) -> None:
        if self.done:
            raise RuntimeError("promise already completed")
        self.done = True
        self.exception = exception
        for cb in self._callbacks:
            cb(self)

    def on_complete(self, cb: Callable[["Promise"], None]) -> None:
        if self.done:
            cb(self)
        else:
            self._callbacks.append(cb)

    def result(self) -> Any:
        if not self.done:
            raise RuntimeError("promise not completed")
        if self.exception is not None:
            raise self.exception
        return self.value
