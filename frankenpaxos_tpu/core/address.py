"""Transport addresses.

Mirrors the reference's ``Address`` marker trait
(``shared/src/main/scala/frankenpaxos/Address.scala:1-3``) and the Netty
transport's host/port addresses (``NettyTcpTransport.scala:39-41``).
Addresses must be hashable and totally ordered so deterministic simulations
can sort actors.
"""

from __future__ import annotations

import dataclasses


class Address:
    """Marker base class for transport addresses."""


@dataclasses.dataclass(frozen=True, order=True)
class SimAddress(Address):
    """A string address used by simulated transports (cf. JsTransport's
    string addresses, ``JsTransport.scala:10``)."""

    name: str

    def __str__(self) -> str:
        return self.name


@dataclasses.dataclass(frozen=True, order=True)
class HostPort(Address):
    """A (host, port) address used by the TCP deployment transport
    (cf. ``NettyTcpTransport.scala:39-41`` / ``NettyTcpTransport.proto``)."""

    host: str
    port: int

    def __str__(self) -> str:
        return f"{self.host}:{self.port}"
