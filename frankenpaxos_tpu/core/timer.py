"""Named restartable one-shot timer, mirroring the reference ``Timer``
(``shared/src/main/scala/frankenpaxos/Timer.scala:23-42``): ``start``,
``stop``, ``reset`` (= stop; start). Concrete transports subclass and
implement the scheduling."""

from __future__ import annotations

from typing import Callable


class Timer:
    def __init__(self, name: str, delay: float, f: Callable[[], None]):
        self._name = name
        self.delay = delay
        self.f = f

    def name(self) -> str:
        return self._name

    def start(self) -> None:
        raise NotImplementedError

    def stop(self) -> None:
        raise NotImplementedError

    def reset(self) -> None:
        self.stop()
        self.start()
