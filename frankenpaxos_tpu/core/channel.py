"""Typed outbound channel, mirroring ``Chan[Transport, DstActor]``
(``shared/src/main/scala/frankenpaxos/Chan.scala:3-17``): serializes the
destination actor's inbound message type and forwards to the transport."""

from __future__ import annotations

from typing import Any

from frankenpaxos_tpu.core.address import Address
from frankenpaxos_tpu.core.serializer import Serializer
from frankenpaxos_tpu.core.transport import Transport


class Chan:
    def __init__(
        self,
        transport: Transport,
        src: Address,
        dst: Address,
        serializer: Serializer,
    ):
        self.transport = transport
        self.src = src
        self.dst = dst
        self.serializer = serializer

    def send(self, msg: Any) -> None:
        self.transport.send(self.src, self.dst, self.serializer.to_bytes(msg))

    def send_no_flush(self, msg: Any) -> None:
        self.transport.send_no_flush(
            self.src, self.dst, self.serializer.to_bytes(msg)
        )

    def flush(self) -> None:
        self.transport.flush(self.src, self.dst)
