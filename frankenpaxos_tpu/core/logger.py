"""Leveled logging with fatal-checked assertions.

Mirrors the reference ``Logger``
(``shared/src/main/scala/frankenpaxos/Logger.scala:5-118``): five levels,
lazy message arguments, and ``check*`` helpers that raise on violation
(the reference's ``fatal`` returns ``Nothing``; ours raises
``FatalError``). Implementations mirror ``PrintLogger``, ``FileLogger``,
``JsLogger`` (ring buffer, for the viz), and ``FakeLogger`` (tests).
"""

from __future__ import annotations

import collections
import enum
import sys
import time
from typing import Any, Callable, List, Optional, Union

LazyMsg = Union[str, Callable[[], str]]


def _force(msg: LazyMsg) -> str:
    return msg() if callable(msg) else msg


class LogLevel(enum.IntEnum):
    DEBUG = 0
    INFO = 1
    WARN = 2
    ERROR = 3
    FATAL = 4


class FatalError(AssertionError):
    """Raised by Logger.fatal; the sim harness treats it as an invariant
    violation, deployment mains exit."""


class Logger:
    def __init__(self, level: LogLevel = LogLevel.DEBUG):
        self.level = level

    # Subclass hook.
    def emit(self, level: LogLevel, message: str) -> None:
        raise NotImplementedError

    def _log(self, level: LogLevel, message: LazyMsg) -> None:
        if level >= self.level:
            self.emit(level, _force(message))

    def debug(self, message: LazyMsg) -> None:
        self._log(LogLevel.DEBUG, message)

    def info(self, message: LazyMsg) -> None:
        self._log(LogLevel.INFO, message)

    def warn(self, message: LazyMsg) -> None:
        self._log(LogLevel.WARN, message)

    def error(self, message: LazyMsg) -> None:
        self._log(LogLevel.ERROR, message)

    def fatal(self, message: LazyMsg) -> "NoReturn":  # noqa: F821
        text = _force(message)
        self.emit(LogLevel.FATAL, text)
        raise FatalError(text)

    # Assertion helpers (Logger.scala:77-117).
    def check(self, condition: bool, message: LazyMsg = "check failed") -> None:
        if not condition:
            self.fatal(message)

    def check_eq(self, a: Any, b: Any, message: Optional[LazyMsg] = None) -> None:
        if a != b:
            self.fatal(message or (lambda: f"check_eq failed: {a!r} != {b!r}"))

    def check_ne(self, a: Any, b: Any, message: Optional[LazyMsg] = None) -> None:
        if a == b:
            self.fatal(message or (lambda: f"check_ne failed: {a!r} == {b!r}"))

    def check_lt(self, a: Any, b: Any, message: Optional[LazyMsg] = None) -> None:
        if not a < b:
            self.fatal(message or (lambda: f"check_lt failed: {a!r} >= {b!r}"))

    def check_le(self, a: Any, b: Any, message: Optional[LazyMsg] = None) -> None:
        if not a <= b:
            self.fatal(message or (lambda: f"check_le failed: {a!r} > {b!r}"))

    def check_gt(self, a: Any, b: Any, message: Optional[LazyMsg] = None) -> None:
        if not a > b:
            self.fatal(message or (lambda: f"check_gt failed: {a!r} <= {b!r}"))

    def check_ge(self, a: Any, b: Any, message: Optional[LazyMsg] = None) -> None:
        if not a >= b:
            self.fatal(message or (lambda: f"check_ge failed: {a!r} < {b!r}"))


class PrintLogger(Logger):
    def __init__(self, level: LogLevel = LogLevel.DEBUG, prefix: str = ""):
        super().__init__(level)
        self.prefix = prefix

    def emit(self, level: LogLevel, message: str) -> None:
        ts = time.strftime("%H:%M:%S")
        print(f"[{level.name:5s}] {ts} {self.prefix}{message}", file=sys.stderr)


class FileLogger(Logger):
    def __init__(self, path: str, level: LogLevel = LogLevel.DEBUG):
        super().__init__(level)
        self._f = open(path, "a")

    def emit(self, level: LogLevel, message: str) -> None:
        self._f.write(f"[{level.name}] {message}\n")
        self._f.flush()


class RingLogger(Logger):
    """Keeps the last ``capacity`` records; used by the interactive viz
    (cf. JsLogger's ring buffer, ``JsLogger.scala``)."""

    def __init__(self, capacity: int = 1000, level: LogLevel = LogLevel.DEBUG):
        super().__init__(level)
        self.records: collections.deque = collections.deque(maxlen=capacity)

    def emit(self, level: LogLevel, message: str) -> None:
        self.records.append((level, message))


class FakeLogger(Logger):
    """Records everything; silent. For tests (cf. FakeLogger.scala)."""

    def __init__(self, level: LogLevel = LogLevel.DEBUG):
        super().__init__(level)
        self.records: List[tuple] = []

    def emit(self, level: LogLevel, message: str) -> None:
        self.records.append((level, message))
