"""The transport abstraction.

Mirrors the reference's ``Transport[Self]`` trait
(``shared/src/main/scala/frankenpaxos/Transport.scala:44-99``): actor
registration, point-to-point sends with optional flush batching, and named
one-shot timers.

THE LOAD-BEARING CONTRACT (Transport.scala:37-39): every transport is a
single-threaded event loop. ``Actor.receive`` calls and timer callbacks run
serially, never concurrently. Protocol code therefore needs no locks, the
sim transport is deterministic, and — the point of this project — each
``receive`` is a pure-ish state transition that the TPU backend can batch
and ``jax.vmap``.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Callable, Optional

from frankenpaxos_tpu.core.address import Address

if TYPE_CHECKING:
    from frankenpaxos_tpu.core.actor import Actor
    from frankenpaxos_tpu.core.timer import Timer


class Transport:
    def register(self, address: Address, actor: "Actor") -> None:
        """Register an actor at an address (Transport.scala:58-61). At most
        one actor per address."""
        raise NotImplementedError

    def send(self, src: Address, dst: Address, data: bytes) -> None:
        """Send bytes from src to dst and flush (Transport.scala:65-69)."""
        raise NotImplementedError

    def send_no_flush(self, src: Address, dst: Address, data: bytes) -> None:
        """Buffer bytes for dst without flushing (Transport.scala:71-78).
        Transports without write batching may treat this as send."""
        self.send(src, dst, data)

    def flush(self, src: Address, dst: Address) -> None:
        """Flush buffered messages to dst (Transport.scala:80-84)."""

    def timer(
        self,
        address: Address,
        name: str,
        delay: float,
        f: Callable[[], None],
    ) -> "Timer":
        """Create a stopped one-shot timer owned by the actor at ``address``
        (Transport.scala:88-93). ``delay`` is in seconds; the sim transports
        interpret it as relative priority only. Names are non-unique; they
        exist for debugging and test addressing (Timer.scala:1-22)."""
        raise NotImplementedError

    def shutdown(self) -> None:
        """Stop event loops / close sockets (NettyTcpTransport.scala:502)."""

    # Address serialization (the analog of the reference's
    # transport.addressSerializer, used to embed client addresses in
    # CommandIds so any node can open a channel back to the client).

    def address_to_bytes(self, address: Address) -> bytes:
        raise NotImplementedError

    def address_from_bytes(self, data: bytes) -> Address:
        raise NotImplementedError
