"""A tiny self-describing binary wire format for protocol messages.

The reference serializes every message with protobuf (ScalaPB,
``Serializer.scala:5-10`` / ``ProtoSerializer.scala``). We keep the same
*capability* — every protocol message round-trips to bytes with structural
equality, so the sim transport can treat messages as values
(``FakeTransport.scala:54-62``) and the TCP transport can frame them — but
implement it as a dependency-free tagged binary codec over frozen
dataclasses.

Usage::

    @wire.message
    @dataclasses.dataclass(frozen=True)
    class ClientRequest:
        command_id: int
        command: bytes

``wire.encode(msg) -> bytes`` and ``wire.decode(data) -> msg``. Message
classes are registered under their qualified name; the registry is global
and collision-checked.
"""

from __future__ import annotations

import dataclasses
import struct
import zlib
from typing import Any, Dict, Tuple, Type

# Type tags.
_NONE = 0
_FALSE = 1
_TRUE = 2
_INT = 3  # 8-byte signed big-endian
_FLOAT = 4  # 8-byte IEEE double
_STR = 5  # u32 length + utf-8
_BYTES = 6  # u32 length + raw
_LIST = 7  # u32 count + items
_TUPLE = 8  # u32 count + items
_DICT = 9  # u32 count + alternating key/value
_MSG = 10  # u16 registry id + u16 name hash + u32 field count + field values
_BIGINT = 11  # u32 length + signed big-endian bytes (ints beyond 64 bits)
_FROZENSET = 12  # u32 count + items (sorted for determinism)

_registry_by_name: Dict[str, Type[Any]] = {}
_registry_by_id: Dict[int, Type[Any]] = {}
_ids_by_type: Dict[Type[Any], int] = {}


def message(cls: Type[Any]) -> Type[Any]:
    """Class decorator registering a dataclass as a wire message."""
    if not dataclasses.is_dataclass(cls):
        raise TypeError(f"@wire.message requires a dataclass, got {cls!r}")
    name = f"{cls.__module__}.{cls.__qualname__}"
    if name in _registry_by_name:
        raise ValueError(f"duplicate wire message registration: {name}")
    # Stable ids: assigned in registration order. All processes must import
    # protocol modules in the same order; registration happens at module
    # import, and modules register messages top-to-bottom, so any two
    # processes importing the same protocol module agree. Because two
    # processes with different import sets could still map the same id to
    # different classes, every _MSG header also carries a 16-bit hash of the
    # qualified class name, verified on decode.
    msg_id = len(_registry_by_id)
    _registry_by_name[name] = cls
    _registry_by_id[msg_id] = cls
    _ids_by_type[cls] = msg_id
    cls.__wire_name__ = name
    cls.__wire_id__ = msg_id
    cls.__wire_hash__ = zlib.crc32(name.encode("utf-8")) & 0xFFFF
    cls.__wire_fields__ = tuple(f.name for f in dataclasses.fields(cls))
    return cls


def _encode_value(value: Any, out: bytearray) -> None:
    if value is None:
        out.append(_NONE)
    elif value is False:
        out.append(_FALSE)
    elif value is True:
        out.append(_TRUE)
    elif isinstance(value, int):
        if -(2**63) <= value < 2**63:
            out.append(_INT)
            out += struct.pack(">q", value)
        else:
            raw = value.to_bytes((value.bit_length() + 8) // 8, "big", signed=True)
            out.append(_BIGINT)
            out += struct.pack(">I", len(raw))
            out += raw
    elif isinstance(value, float):
        out.append(_FLOAT)
        out += struct.pack(">d", value)
    elif isinstance(value, str):
        raw = value.encode("utf-8")
        out.append(_STR)
        out += struct.pack(">I", len(raw))
        out += raw
    elif isinstance(value, (bytes, bytearray)):
        out.append(_BYTES)
        out += struct.pack(">I", len(value))
        out += value
    elif type(value) in _ids_by_type:
        out.append(_MSG)
        out += struct.pack(
            ">HHI",
            _ids_by_type[type(value)],
            value.__wire_hash__,
            len(value.__wire_fields__),
        )
        for fname in value.__wire_fields__:
            _encode_value(getattr(value, fname), out)
    elif isinstance(value, list):
        out.append(_LIST)
        out += struct.pack(">I", len(value))
        for item in value:
            _encode_value(item, out)
    elif isinstance(value, tuple):
        out.append(_TUPLE)
        out += struct.pack(">I", len(value))
        for item in value:
            _encode_value(item, out)
    elif isinstance(value, dict):
        out.append(_DICT)
        out += struct.pack(">I", len(value))
        for k in sorted(value):
            _encode_value(k, out)
            _encode_value(value[k], out)
    elif isinstance(value, frozenset):
        out.append(_FROZENSET)
        out += struct.pack(">I", len(value))
        for item in sorted(value):
            _encode_value(item, out)
    else:
        raise TypeError(f"unencodable value of type {type(value)!r}: {value!r}")


def _decode_value(data: bytes, pos: int) -> Tuple[Any, int]:
    tag = data[pos]
    pos += 1
    if tag == _NONE:
        return None, pos
    if tag == _FALSE:
        return False, pos
    if tag == _TRUE:
        return True, pos
    if tag == _INT:
        return struct.unpack_from(">q", data, pos)[0], pos + 8
    if tag == _BIGINT:
        (n,) = struct.unpack_from(">I", data, pos)
        pos += 4
        return int.from_bytes(data[pos : pos + n], "big", signed=True), pos + n
    if tag == _FLOAT:
        return struct.unpack_from(">d", data, pos)[0], pos + 8
    if tag == _STR:
        (n,) = struct.unpack_from(">I", data, pos)
        pos += 4
        return data[pos : pos + n].decode("utf-8"), pos + n
    if tag == _BYTES:
        (n,) = struct.unpack_from(">I", data, pos)
        pos += 4
        return bytes(data[pos : pos + n]), pos + n
    if tag in (_LIST, _TUPLE, _FROZENSET):
        (n,) = struct.unpack_from(">I", data, pos)
        pos += 4
        items = []
        for _ in range(n):
            item, pos = _decode_value(data, pos)
            items.append(item)
        if tag == _LIST:
            return items, pos
        if tag == _TUPLE:
            return tuple(items), pos
        return frozenset(items), pos
    if tag == _DICT:
        (n,) = struct.unpack_from(">I", data, pos)
        pos += 4
        d = {}
        for _ in range(n):
            k, pos = _decode_value(data, pos)
            v, pos = _decode_value(data, pos)
            d[k] = v
        return d, pos
    if tag == _MSG:
        msg_id, name_hash, nfields = struct.unpack_from(">HHI", data, pos)
        pos += 8
        cls = _registry_by_id.get(msg_id)
        if cls is None:
            raise ValueError(f"unknown wire message id {msg_id}")
        if name_hash != cls.__wire_hash__:
            raise ValueError(
                f"wire name-hash mismatch for id {msg_id}: local class "
                f"{cls.__wire_name__} (hash {cls.__wire_hash__:#06x}) vs "
                f"wire hash {name_hash:#06x}; the peer registered a "
                f"different message under this id (import-order skew?)"
            )
        if nfields != len(cls.__wire_fields__):
            raise ValueError(
                f"field count mismatch for {cls.__wire_name__}: "
                f"wire={nfields} local={len(cls.__wire_fields__)}"
            )
        values = []
        for _ in range(nfields):
            v, pos = _decode_value(data, pos)
            values.append(v)
        return cls(*values), pos
    raise ValueError(f"unknown wire tag {tag} at offset {pos - 1}")


def encode(value: Any) -> bytes:
    out = bytearray()
    _encode_value(value, out)
    return bytes(out)


def decode(data: bytes) -> Any:
    value, pos = _decode_value(data, 0)
    if pos != len(data):
        raise ValueError(f"trailing bytes: consumed {pos} of {len(data)}")
    return value


def pretty(value: Any) -> str:
    return repr(value)
