"""Byte-serialization typeclass.

Mirrors the reference's ``Serializer[A]`` trait
(``shared/src/main/scala/frankenpaxos/Serializer.scala:5-10``) with
``to_bytes``/``from_bytes``/``to_pretty_string`` and the standard instances
(int/string/bytes, ``Serializer.scala:12-53``). ``WireSerializer`` plays the
role of ``ProtoSerializer`` (``ProtoSerializer.scala:1-11``): the default
serializer for protocol messages, backed by the :mod:`wire` codec.
"""

from __future__ import annotations

import struct
from typing import Any, Generic, TypeVar

from frankenpaxos_tpu.core import wire

A = TypeVar("A")


class Serializer(Generic[A]):
    def to_bytes(self, x: A) -> bytes:
        raise NotImplementedError

    def from_bytes(self, data: bytes) -> A:
        raise NotImplementedError

    def to_pretty_string(self, x: A) -> str:
        return repr(x)


class WireSerializer(Serializer[Any]):
    """Serializer for any @wire.message dataclass (the ProtoSerializer
    analog). A single instance serializes every registered message type, so
    role ``InboundMessage`` wrapper types are just unions of message
    classes."""

    def to_bytes(self, x: Any) -> bytes:
        return wire.encode(x)

    def from_bytes(self, data: bytes) -> Any:
        return wire.decode(data)


class IntSerializer(Serializer[int]):
    def to_bytes(self, x: int) -> bytes:
        return struct.pack(">q", x)

    def from_bytes(self, data: bytes) -> int:
        return struct.unpack(">q", data)[0]


class StringSerializer(Serializer[str]):
    def to_bytes(self, x: str) -> bytes:
        return x.encode("utf-8")

    def from_bytes(self, data: bytes) -> str:
        return data.decode("utf-8")


class BytesSerializer(Serializer[bytes]):
    def to_bytes(self, x: bytes) -> bytes:
        return x

    def from_bytes(self, data: bytes) -> bytes:
        return data
