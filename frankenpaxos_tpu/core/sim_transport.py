"""Deterministic in-process simulation transport.

This is the reference's ``FakeTransport``
(``shared/src/main/scala/frankenpaxos/FakeTransport.scala:64-240``) merged
with the interactive capabilities of ``JsTransport``
(``JsTransport.scala:60-299``):

  * every ``send`` queues a :class:`QueuedMessage`; nothing is delivered
    until the driver (a test, the simulator, or the viz) says so;
  * timers never fire on their own; the driver triggers them;
  * messages can be delivered, dropped, or duplicated in any order, and
    actors can be partitioned (inbound+outbound drops) — message loss and
    delay are therefore implicit in the scheduling model
    (SURVEY.md §4: delivery can be postponed indefinitely);
  * the full command history is recorded so an interactive session can be
    exported as a regression test (cf. ``JsTransport.scala:260-298``).

Commands (:class:`DeliverMessage` / :class:`TriggerTimer`) mirror the
``FakeTransport.Command`` ADT (``FakeTransport.scala:185-193``). Messages
hold bytes, so command equality is structural and delivery-by-value is
well-defined under shrinking (``FakeTransport.scala:54-62``): delivering a
message that is no longer pending is a no-op.
"""

from __future__ import annotations

import dataclasses
import random
from typing import Any, Callable, Dict, List, Optional, Set, Tuple, Union

from frankenpaxos_tpu.core.address import Address
from frankenpaxos_tpu.core.logger import Logger, PrintLogger
from frankenpaxos_tpu.core.timer import Timer
from frankenpaxos_tpu.core.transport import Transport


@dataclasses.dataclass(frozen=True)
class QueuedMessage:
    src: Address
    dst: Address
    data: bytes


class SimTimer(Timer):
    def __init__(
        self,
        transport: "SimTransport",
        address: Address,
        name: str,
        delay: float,
        f: Callable[[], None],
    ):
        super().__init__(name, delay, f)
        self.transport = transport
        self.address = address
        self.running = False

    def start(self) -> None:
        if not self.running:
            self.running = True
            self.transport._running_timers.append(self)

    def stop(self) -> None:
        if self.running:
            self.running = False
            self.transport._running_timers.remove(self)

    def run(self) -> None:
        # Mirrors FakeTransport timer semantics: a timer stops itself before
        # running its callback so the callback can restart it.
        if self.running:
            self.stop()
            self.f()


@dataclasses.dataclass(frozen=True)
class DeliverMessage:
    msg: QueuedMessage


@dataclasses.dataclass(frozen=True)
class TriggerTimer:
    address: Address
    name: str
    # Which of the running timers sharing (address, name) to fire; an
    # actor may run several timers under one name (per-op retries).
    occurrence: int = 0


@dataclasses.dataclass(frozen=True)
class DropMessage:
    msg: QueuedMessage


@dataclasses.dataclass(frozen=True)
class DuplicateMessage:
    msg: QueuedMessage


@dataclasses.dataclass(frozen=True)
class PartitionActor:
    address: Address


@dataclasses.dataclass(frozen=True)
class UnpartitionActor:
    address: Address


SimCommand = Union[
    DeliverMessage,
    TriggerTimer,
    DropMessage,
    DuplicateMessage,
    PartitionActor,
    UnpartitionActor,
]


class SimTransport(Transport):
    def __init__(self, logger: Optional[Logger] = None):
        self.logger = logger or PrintLogger()
        self.actors: Dict[Address, Any] = {}
        self.messages: List[QueuedMessage] = []
        # Only RUNNING timers are tracked (timers register themselves on
        # start and deregister on stop/fire). Protocol clients create one
        # fresh timer per request; tracking stopped timers would leak them
        # and make every scheduling step O(total timers ever created).
        self._running_timers: List[SimTimer] = []
        self.partitioned: Set[Address] = set()
        self.history: List[SimCommand] = []
        # Per-(src,dst) buffers for send_no_flush/flush batching semantics.
        self._unflushed: Dict[Tuple[Address, Address], List[bytes]] = {}

    # -- Transport interface -------------------------------------------------

    def register(self, address: Address, actor: Any) -> None:
        if address in self.actors:
            self.logger.fatal(f"duplicate actor registration at {address}")
        self.actors[address] = actor

    def send(self, src: Address, dst: Address, data: bytes) -> None:
        self.send_no_flush(src, dst, data)
        self.flush(src, dst)

    def send_no_flush(self, src: Address, dst: Address, data: bytes) -> None:
        if src in self.partitioned or dst in self.partitioned:
            return
        self._unflushed.setdefault((src, dst), []).append(data)

    def flush(self, src: Address, dst: Address) -> None:
        for data in self._unflushed.pop((src, dst), []):
            self.messages.append(QueuedMessage(src, dst, data))

    def flush_all(self) -> None:
        for (src, dst) in list(self._unflushed):
            self.flush(src, dst)

    def timer(
        self, address: Address, name: str, delay: float, f: Callable[[], None]
    ) -> SimTimer:
        return SimTimer(self, address, name, delay, f)

    def address_to_bytes(self, address: Address) -> bytes:
        return address.name.encode("utf-8")

    def address_from_bytes(self, data: bytes) -> Address:
        from frankenpaxos_tpu.core.address import SimAddress

        return SimAddress(data.decode("utf-8"))

    # -- Driver interface ----------------------------------------------------

    def running_timers(self) -> List[SimTimer]:
        return list(self._running_timers)

    def timer_occurrence(self, i: int) -> int:
        """Occurrence ordinal of the i-th running timer among earlier
        running timers sharing its (address, name) — an actor may run
        several timers under one name (per-op retries). The single
        source of truth for occurrence numbering (command generation,
        the Stepper, and replay all use it)."""
        running = self.running_timers()
        timer = running[i]
        return sum(
            1
            for u in running[:i]
            if u.address == timer.address and u._name == timer._name
        )

    def deliver_message(self, msg: QueuedMessage, record: bool = True) -> None:
        """Deliver (and remove) the first pending message structurally equal
        to ``msg`` (FakeTransport.scala:142-159). No-op if absent or if an
        endpoint is partitioned — no-op semantics make command histories
        shrinkable."""
        if record:
            self.history.append(DeliverMessage(msg))
        try:
            self.messages.remove(msg)
        except ValueError:
            return
        if msg.src in self.partitioned or msg.dst in self.partitioned:
            return
        actor = self.actors.get(msg.dst)
        if actor is None:
            return
        actor.receive(msg.src, actor.serializer.from_bytes(msg.data))
        self.flush_all()

    def drop_message(self, msg: QueuedMessage, record: bool = True) -> None:
        if record:
            self.history.append(DropMessage(msg))
        try:
            self.messages.remove(msg)
        except ValueError:
            pass

    def duplicate_message(self, msg: QueuedMessage, record: bool = True) -> None:
        if record:
            self.history.append(DuplicateMessage(msg))
        if msg in self.messages:
            self.messages.append(msg)

    def trigger_timer(
        self,
        address: Address,
        name: str,
        record: bool = True,
        occurrence: int = 0,
    ) -> None:
        """Fire the ``occurrence``-th running timer with this
        (address, name) (FakeTransport.scala:161-179; an actor may run
        several timers under one name, e.g. per-op retry timers). No-op
        if none is running at that occurrence."""
        if record:
            self.history.append(TriggerTimer(address, name, occurrence))
        if address in self.partitioned:
            return
        seen = 0
        for t in list(self._running_timers):
            if t.address == address and t._name == name:
                if seen == occurrence:
                    t.run()
                    self.flush_all()
                    return
                seen += 1

    def partition_actor(self, address: Address, record: bool = True) -> None:
        """Drop all traffic to/from ``address`` and all its pending messages
        (JsTransport.scala:246-258)."""
        if record:
            self.history.append(PartitionActor(address))
        self.partitioned.add(address)
        self.messages = [
            m
            for m in self.messages
            if m.src != address and m.dst != address
        ]

    def unpartition_actor(self, address: Address, record: bool = True) -> None:
        if record:
            self.history.append(UnpartitionActor(address))
        self.partitioned.discard(address)

    # -- Random command generation (FakeTransport.scala:196-231) -------------

    def generate_command(self, rng: random.Random) -> Optional[SimCommand]:
        """Pick a random pending message or running timer, weighted by
        count — this IS the network-nondeterminism model for property
        testing."""
        n_msgs = len(self.messages)
        running = self.running_timers()
        total = n_msgs + len(running)
        if total == 0:
            return None
        i = rng.randrange(total)
        if i < n_msgs:
            return DeliverMessage(self.messages[i])
        t = running[i - n_msgs]
        return TriggerTimer(
            t.address, t._name, self.timer_occurrence(i - n_msgs)
        )

    def run_command(self, cmd: SimCommand, record: bool = True) -> None:
        if isinstance(cmd, DeliverMessage):
            self.deliver_message(cmd.msg, record=record)
        elif isinstance(cmd, TriggerTimer):
            self.trigger_timer(
                cmd.address, cmd.name, record=record,
                occurrence=cmd.occurrence,
            )
        elif isinstance(cmd, DropMessage):
            self.drop_message(cmd.msg, record=record)
        elif isinstance(cmd, DuplicateMessage):
            self.duplicate_message(cmd.msg, record=record)
        elif isinstance(cmd, PartitionActor):
            self.partition_actor(cmd.address, record=record)
        elif isinstance(cmd, UnpartitionActor):
            self.unpartition_actor(cmd.address, record=record)
        else:
            raise TypeError(f"unknown sim command {cmd!r}")
