from frankenpaxos_tpu.core.address import Address, HostPort, SimAddress
from frankenpaxos_tpu.core.actor import Actor
from frankenpaxos_tpu.core.channel import Chan
from frankenpaxos_tpu.core.logger import (
    FakeLogger,
    FileLogger,
    LogLevel,
    Logger,
    PrintLogger,
    RingLogger,
)
from frankenpaxos_tpu.core.serializer import (
    BytesSerializer,
    IntSerializer,
    Serializer,
    StringSerializer,
    WireSerializer,
)
from frankenpaxos_tpu.core.sim_transport import (
    DeliverMessage,
    QueuedMessage,
    SimCommand,
    SimTimer,
    SimTransport,
    TriggerTimer,
)
from frankenpaxos_tpu.core.timer import Timer
from frankenpaxos_tpu.core.transport import Transport
from frankenpaxos_tpu.core import wire

__all__ = [
    "Actor",
    "Address",
    "BytesSerializer",
    "Chan",
    "DeliverMessage",
    "FakeLogger",
    "FileLogger",
    "HostPort",
    "IntSerializer",
    "LogLevel",
    "Logger",
    "PrintLogger",
    "QueuedMessage",
    "RingLogger",
    "Serializer",
    "SimAddress",
    "SimCommand",
    "SimTimer",
    "SimTransport",
    "StringSerializer",
    "Timer",
    "Transport",
    "TriggerTimer",
    "WireSerializer",
    "wire",
]
