"""Real-deployment transport over asyncio TCP.

The capability analog of the reference's ``NettyTcpTransport``
(``shared/src/main/scala/frankenpaxos/NettyTcpTransport.scala:124-505``):

  * a single-threaded event loop (one asyncio loop; the reference uses a
    single ``NioEventLoopGroup(1)`` thread, ``NettyTcpTransport.scala:240``);
  * one TCP server socket per registered actor
    (``NettyTcpTransport.scala:335-369``);
  * a per-(local, remote) connection cache with lazy connect and buffering
    of messages while the connection is pending
    (``NettyTcpTransport.scala:242-272, 375-450``);
  * 4-byte length-prefixed framing with a 10 MiB max frame
    (``NettyTcpTransport.scala:353-358``);
  * timers are scheduled callbacks on the same loop
    (``NettyTcpTransport.scala:78-122``).

Wire protocol per connection: the initiator first sends one frame containing
its own registered listening address (host, port) so the receiver can
attribute inbound messages to a canonical address; every subsequent frame is
a message payload dispatched as ``actor.receive(remote, serializer.from_bytes(payload))``.
"""

from __future__ import annotations

import asyncio
import struct
from typing import Any, Callable, Dict, List, Optional, Tuple

from frankenpaxos_tpu.core.address import Address, HostPort
from frankenpaxos_tpu.core.logger import Logger, PrintLogger
from frankenpaxos_tpu.core.timer import Timer
from frankenpaxos_tpu.core.transport import Transport

MAX_FRAME = 10 * 1024 * 1024  # NettyTcpTransport.scala:353


def _frame(data: bytes) -> bytes:
    if len(data) > MAX_FRAME:
        raise ValueError(f"frame too large: {len(data)}")
    return struct.pack(">I", len(data)) + data


class TcpTimer(Timer):
    def __init__(
        self,
        transport: "TcpTransport",
        name: str,
        delay: float,
        f: Callable[[], None],
    ):
        super().__init__(name, delay, f)
        self.transport = transport
        self._handle: Optional[asyncio.TimerHandle] = None

    def start(self) -> None:
        if self._handle is None:
            self._handle = self.transport.loop.call_later(self.delay, self._fire)

    def stop(self) -> None:
        if self._handle is not None:
            self._handle.cancel()
            self._handle = None

    def _fire(self) -> None:
        self._handle = None
        self.f()


class _Conn:
    """A lazily-connected outbound channel to one remote address, buffering
    writes while connecting (NettyTcpTransport's Pending/Chan states,
    NettyTcpTransport.scala:242-272)."""

    def __init__(self) -> None:
        self.writer: Optional[asyncio.StreamWriter] = None
        self.pending: List[bytes] = []
        self.connecting = False


class TcpTransport(Transport):
    def __init__(self, logger: Optional[Logger] = None):
        self.logger = logger or PrintLogger()
        self.loop = asyncio.new_event_loop()
        self.actors: Dict[HostPort, Any] = {}
        self.servers: Dict[HostPort, asyncio.AbstractServer] = {}
        # Connection cache keyed by (local, remote) like the reference's
        # channels map (NettyTcpTransport.scala:242).
        self.conns: Dict[Tuple[HostPort, HostPort], _Conn] = {}
        self._unflushed: Dict[Tuple[HostPort, HostPort], List[bytes]] = {}
        self._started = False
        self._stopping = False

    # -- Transport interface -------------------------------------------------

    def register(self, address: Address, actor: Any) -> None:
        assert isinstance(address, HostPort), address
        if address in self.actors:
            self.logger.fatal(f"duplicate actor registration at {address}")
        self.actors[address] = actor
        if self._started:
            self.loop.create_task(self._start_server(address))

    def send(self, src: Address, dst: Address, data: bytes) -> None:
        self.send_no_flush(src, dst, data)
        self.flush(src, dst)

    def send_no_flush(self, src: Address, dst: Address, data: bytes) -> None:
        self._unflushed.setdefault((src, dst), []).append(data)

    def flush(self, src: Address, dst: Address) -> None:
        msgs = self._unflushed.pop((src, dst), [])
        if not msgs:
            return
        conn = self.conns.get((src, dst))
        if conn is None:
            conn = _Conn()
            self.conns[(src, dst)] = conn
        if conn.writer is not None:
            for m in msgs:
                conn.writer.write(_frame(m))
        else:
            conn.pending.extend(msgs)
            if not conn.connecting:
                conn.connecting = True
                self.loop.create_task(self._connect(src, dst, conn))

    def timer(
        self, address: Address, name: str, delay: float, f: Callable[[], None]
    ) -> TcpTimer:
        return TcpTimer(self, name, delay, f)

    def address_to_bytes(self, address: Address) -> bytes:
        from frankenpaxos_tpu.core import wire

        return wire.encode((address.host, address.port))

    def address_from_bytes(self, data: bytes) -> Address:
        from frankenpaxos_tpu.core import wire

        host, port = wire.decode(data)
        return HostPort(host, port)

    def shutdown(self) -> None:
        self._stopping = True
        self.loop.call_soon(self.loop.stop)

    # -- Event loop ----------------------------------------------------------

    def run(self, on_start: Optional[Callable[[], None]] = None) -> None:
        """Bind all servers and run the event loop until ``shutdown``."""
        asyncio.set_event_loop(self.loop)
        self._started = True
        for address in list(self.actors):
            self.loop.run_until_complete(self._start_server(address))
        if on_start is not None:
            self.loop.call_soon(on_start)
        try:
            self.loop.run_forever()
        finally:
            for server in self.servers.values():
                server.close()
            for conn in self.conns.values():
                if conn.writer is not None:
                    conn.writer.close()

    async def _start_server(self, address: HostPort) -> None:
        server = await asyncio.start_server(
            lambda r, w: self._handle_inbound(address, r, w),
            host=address.host,
            port=address.port,
        )
        self.servers[address] = server

    async def _connect(self, src: HostPort, dst: HostPort, conn: _Conn) -> None:
        try:
            reader, writer = await asyncio.open_connection(dst.host, dst.port)
        except OSError as e:
            self.logger.warn(f"connect {src}->{dst} failed: {e}")
            self.conns.pop((src, dst), None)
            return
        # Handshake: announce our canonical (listening) address.
        from frankenpaxos_tpu.core import wire

        writer.write(_frame(wire.encode((src.host, src.port))))
        for m in conn.pending:
            writer.write(_frame(m))
        conn.pending = []
        conn.writer = writer
        conn.connecting = False
        # Inbound messages can also arrive on an outbound connection.
        self.loop.create_task(self._read_frames(src, dst, reader, writer))

    async def _handle_inbound(
        self,
        local: HostPort,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
    ) -> None:
        from frankenpaxos_tpu.core import wire

        try:
            hello = await self._read_frame(reader)
            if hello is None:
                return
            host, port = wire.decode(hello)
            remote = HostPort(host, port)
        except (ValueError, asyncio.IncompleteReadError):
            writer.close()
            return
        # Cache the reverse channel so replies reuse this connection.
        conn = self.conns.get((local, remote))
        if conn is None or conn.writer is None:
            conn = _Conn()
            conn.writer = writer
            self.conns[(local, remote)] = conn
        await self._read_frames(local, remote, reader, writer)

    async def _read_frame(self, reader: asyncio.StreamReader) -> Optional[bytes]:
        try:
            header = await reader.readexactly(4)
        except (asyncio.IncompleteReadError, ConnectionError):
            return None
        (n,) = struct.unpack(">I", header)
        if n > MAX_FRAME:
            raise ValueError(f"frame too large: {n}")
        try:
            return await reader.readexactly(n)
        except (asyncio.IncompleteReadError, ConnectionError):
            return None

    async def _read_frames(
        self,
        local: HostPort,
        remote: HostPort,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
    ) -> None:
        while not self._stopping:
            payload = await self._read_frame(reader)
            if payload is None:
                break
            actor = self.actors.get(local)
            if actor is None:
                continue
            try:
                actor.receive(remote, actor.serializer.from_bytes(payload))
            except Exception as e:  # noqa: BLE001 — isolate actor faults
                self.logger.error(f"receive failed at {local} from {remote}: {e!r}")
                raise
        conn = self.conns.get((local, remote))
        if conn is not None and conn.writer is writer:
            self.conns.pop((local, remote), None)
