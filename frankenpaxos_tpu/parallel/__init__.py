"""Device-mesh sharding for the batched simulation.

The replica axis of the simulation (acceptor groups, axis ``G``) shards
across devices: slots are partitioned ``slot % G`` (ProxyLeader.scala:190),
so the entire write path is group-local — each device simulates its own
contiguous block of acceptor groups with NO cross-device traffic. The only
global quantity is the executed-watermark/commit statistics, which XLA
reduces over ICI when read. This is the map of SURVEY.md §2.7's
"scale-out by role decoupling" onto a TPU mesh.

The machinery lives in :mod:`frankenpaxos_tpu.parallel.sharding` — a
GENERIC per-backend registry of mesh specs + sharded ``run_ticks``
wrappers (donation preserved, kernel-policy validation under a mesh).
This module keeps the original flagship/EPaxos-specific names as thin
wrappers over that registry, so existing callers (``__graft_entry__``,
``scripts/multichip_scaling.py``, the HLO tests) are unchanged; new
code — including the compartmentalized backend — should call the
registry API directly with a backend name.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
from jax.sharding import Mesh

from frankenpaxos_tpu.parallel.sharding import (  # noqa: F401
    GROUP_AXIS,
    SHARDINGS,
    ShardingSpec,
    lower_sharded,
    make_mesh,
    register_sharding,
    validate_policy,
)
from frankenpaxos_tpu.parallel import sharding as _sharding
from frankenpaxos_tpu.tpu.multipaxos_batched import (
    BatchedMultiPaxosConfig,
    BatchedMultiPaxosState,
)


def state_shardings(mesh: Mesh) -> dict:
    """A pytree of NamedShardings for the flagship: every [G, ...]
    array shards along G; scalars and the latency histogram replicate
    (legacy wrapper: ``sharding.state_shardings("multipaxos", mesh)``)."""
    return _sharding.state_shardings("multipaxos", mesh)


def shard_state(
    state: BatchedMultiPaxosState, mesh: Mesh
) -> BatchedMultiPaxosState:
    """Place the flagship state on the mesh with the group axis sharded."""
    return _sharding.shard_state("multipaxos", state, mesh)


def run_ticks_sharded(
    cfg: BatchedMultiPaxosConfig,
    mesh: Mesh,
    state: BatchedMultiPaxosState,
    t0,
    num_ticks: int,
    key,
) -> Tuple[BatchedMultiPaxosState, jnp.ndarray]:
    """Sharded flagship run. ``state`` is donated (single-buffered per
    shard): callers rebind the returned state and must not reuse the
    argument. The write path partitions group-locally; only the
    scalar/ring stat and read-wave reductions cross devices (pinned by
    tests/test_hlo_sharding.py)."""
    return _sharding.run_ticks_sharded(
        "multipaxos", cfg, mesh, state, t0, num_ticks, key
    )


def epaxos_shardings(mesh: Mesh) -> dict:
    """NamedShardings for the batched EPaxos state (legacy wrapper:
    ``sharding.state_shardings("epaxos", mesh)``)."""
    return _sharding.state_shardings("epaxos", mesh)


def shard_epaxos_state(state, mesh: Mesh):
    """Place batched EPaxos state on the mesh, columns sharded."""
    return _sharding.shard_state("epaxos", state, mesh)


def run_epaxos_ticks_sharded(cfg, mesh, state, t0, num_ticks: int, key):
    """Sharded batched-EPaxos run (GSPMD propagation from the input
    shardings, like run_ticks_sharded for the flagship). ``state`` is
    donated; rebind the result, never reuse the argument."""
    return _sharding.run_ticks_sharded(
        "epaxos", cfg, mesh, state, t0, num_ticks, key
    )
