"""Device-mesh sharding for the batched simulation.

The replica axis of the simulation (acceptor groups, axis ``G``) shards
across devices: slots are partitioned ``slot % G`` (ProxyLeader.scala:190),
so the entire write path is group-local — each device simulates its own
contiguous block of acceptor groups with NO cross-device traffic. The only
global quantity is the executed-watermark/commit statistics, which XLA
reduces over ICI when read. This is the map of SURVEY.md §2.7's
"scale-out by role decoupling" onto a TPU mesh.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from frankenpaxos_tpu.tpu.multipaxos_batched import (
    BatchedMultiPaxosConfig,
    BatchedMultiPaxosState,
    run_ticks,
)

GROUP_AXIS = "groups"


def make_mesh(devices=None, axis_name: str = GROUP_AXIS) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.asarray(devices).reshape(-1), (axis_name,))


def state_shardings(mesh: Mesh) -> BatchedMultiPaxosState:
    """A pytree of NamedShardings: every [G, ...] array shards along G;
    scalars and the latency histogram replicate."""

    def spec_for(leaf_name: str):
        # Scalars, stats, and the shared wave clock ([NW] wave_issue —
        # one probe wave per tick is global by construction). The
        # per-group batcher rings (rb_*: [G, NW]) and the wave's
        # per-acceptor request/response arrays ([A, G, NW]) SHARD with
        # the group axis: read state lives with the groups it serves.
        scalar_or_global = {
            "committed", "retired", "lat_sum", "lat_hist",
            "max_chosen_global", "client_watermark", "wave_issue",
            "reads_done", "reads_shed", "read_lat_sum", "read_lat_hist",
            "read_lin_violations", "elections", "reconfigs", "configs_gcd",
            "sm_applied", "dups_filtered", "dups_seen",
            # The telemetry ring holds cluster-wide per-tick reductions
            # ([K, NUM_COLS] + histograms) — replicated; device_put
            # broadcasts the spec over the nested pytree's leaves.
            "telemetry",
        }
        # Acceptor-major arrays ([A, G, W] / [A, G] / [A, G, RW]) carry
        # the group axis second; everything else ([G, W] / [G]) first.
        acceptor_major = {
            "acc_round", "p2a_arrival", "p2b_arrival", "vote_round",
            "vote_value", "acc_max_slot", "req_arrival", "resp_slot",
            "resp_arrival", "leader_alive",  # [C, G] candidates
            # [M, G] matchmakers / [A, G] old-config phase-1 exchanges.
            "mm_epoch", "matcha_arrival", "matchb_arrival",
            "rc_p1a_arrival", "rc_p1b_arrival",
        }
        if leaf_name in scalar_or_global:
            return NamedSharding(mesh, P())
        if leaf_name in acceptor_major:
            return NamedSharding(mesh, P(None, GROUP_AXIS))
        return NamedSharding(mesh, P(GROUP_AXIS))

    import dataclasses as _dc

    from frankenpaxos_tpu.tpu import multipaxos_batched as mb

    fields = [f.name for f in _dc.fields(mb.BatchedMultiPaxosState)]
    return {name: spec_for(name) for name in fields}


def _shard_dataclass(state, specs, mesh: Mesh, axis_len: int, what: str):
    """Place a struct-of-arrays state dataclass on the mesh per-field;
    the sharded axis length must divide evenly over the devices."""
    import dataclasses as _dc

    n_devices = mesh.devices.size
    if axis_len % n_devices != 0:
        raise ValueError(
            f"{what} ({axis_len}) must be divisible by the mesh size "
            f"({n_devices}) to shard that axis; pick a multiple of the "
            f"device count."
        )
    out = {}
    for f in _dc.fields(state):
        out[f.name] = jax.device_put(getattr(state, f.name), specs[f.name])
    return type(state)(**out)


def shard_state(
    state: BatchedMultiPaxosState, mesh: Mesh
) -> BatchedMultiPaxosState:
    """Place the state on the mesh with the group axis sharded."""
    return _shard_dataclass(
        state, state_shardings(mesh), mesh,
        state.leader_round.shape[-1], "num_groups",
    )


@functools.partial(jax.jit, static_argnums=(0, 1, 4), donate_argnums=(2,))
def _run_ticks_sharded(
    cfg: BatchedMultiPaxosConfig,
    mesh: Mesh,
    state: BatchedMultiPaxosState,
    t0: jnp.ndarray,
    num_ticks: int,
    key: jnp.ndarray,
):
    # ``state`` is donated (single-buffered per shard), mirroring
    # run_ticks: callers rebind the returned state and must not reuse
    # the argument.
    # The write path is elementwise over groups; with the G axis sharded,
    # XLA partitions the whole scan and the only cross-device traffic is
    # scalar/ring-stat reductions (psum over ICI): commit stats, and —
    # when reads are enabled — the read path's global reductions (the
    # executed-watermark min over G, the bind max over (A, G), and the
    # chosen-floor max), all of which land on the replicated [RW]/scalar
    # read arrays. We rely on GSPMD propagation from the input shardings
    # rather than hand-writing shard_map: every contraction either stays
    # within a group or reduces to a replicated scalar/ring, so
    # propagation is exact (test_reads_sharded_matches_unsharded pins
    # bit-identity).
    return run_ticks.__wrapped__(cfg, state, t0, num_ticks, key)


def run_ticks_sharded(
    cfg: BatchedMultiPaxosConfig,
    mesh: Mesh,
    state: BatchedMultiPaxosState,
    t0,
    num_ticks: int,
    key,
) -> Tuple[BatchedMultiPaxosState, jnp.ndarray]:
    return _run_ticks_sharded(cfg, mesh, state, t0, num_ticks, key)


def epaxos_shardings(mesh: Mesh):
    """NamedShardings for the batched EPaxos state: every [C, ...] array
    shards along the column axis (the docstring's "shardable over a
    device mesh along C"); the frontier history ([H, C]) and per-replica
    GC watermarks ([R, C]) shard on their SECOND axis; scalars and the
    latency histogram replicate. The closure's only cross-device traffic
    is the [H]-sized tick scores and scalar stats (all-reduces over the
    column axis)."""
    import dataclasses as _dc

    from frankenpaxos_tpu.tpu import epaxos_batched as eb

    second_axis = {"fpre", "fpost", "rep_exec"}
    replicated = {
        "committed_total", "fast_path_total", "executed_total",
        "retired_total", "coexecuted", "lat_sum", "lat_hist",
        "snapshots_served", "rep_crashes", "rep_down", "telemetry",
    }
    specs = {}
    for f in _dc.fields(eb.BatchedEPaxosState):
        if f.name in replicated:
            specs[f.name] = NamedSharding(mesh, P())
        elif f.name in second_axis:
            specs[f.name] = NamedSharding(mesh, P(None, GROUP_AXIS))
        else:
            specs[f.name] = NamedSharding(mesh, P(GROUP_AXIS))
    return specs


def shard_epaxos_state(state, mesh: Mesh):
    """Place batched EPaxos state on the mesh, columns sharded."""
    return _shard_dataclass(
        state, epaxos_shardings(mesh), mesh,
        state.head.shape[0], "num_columns",
    )


@functools.partial(jax.jit, static_argnums=(0, 1, 4), donate_argnums=(2,))
def _run_epaxos_sharded(cfg, mesh, state, t0, num_ticks, key):
    # ``state`` is donated; rebind the result, never reuse the argument.
    from frankenpaxos_tpu.tpu import epaxos_batched as eb

    return eb.run_ticks.__wrapped__(cfg, state, t0, num_ticks, key)


def run_epaxos_ticks_sharded(cfg, mesh, state, t0, num_ticks: int, key):
    """Sharded batched-EPaxos run (GSPMD propagation from the input
    shardings, like run_ticks_sharded for the flagship)."""
    return _run_epaxos_sharded(cfg, mesh, state, t0, num_ticks, key)
