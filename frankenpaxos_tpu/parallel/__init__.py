"""Device-mesh sharding for the batched simulation.

The replica axis of the simulation (acceptor groups, axis ``G``) shards
across devices: slots are partitioned ``slot % G`` (ProxyLeader.scala:190),
so the entire write path is group-local — each device simulates its own
contiguous block of acceptor groups with NO cross-device traffic. The only
global quantity is the executed-watermark/commit statistics, which XLA
reduces over ICI when read. This is the map of SURVEY.md §2.7's
"scale-out by role decoupling" onto a TPU mesh.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from frankenpaxos_tpu.tpu.multipaxos_batched import (
    BatchedMultiPaxosConfig,
    BatchedMultiPaxosState,
    run_ticks,
)

GROUP_AXIS = "groups"


def make_mesh(devices=None, axis_name: str = GROUP_AXIS) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.asarray(devices).reshape(-1), (axis_name,))


def state_shardings(mesh: Mesh) -> BatchedMultiPaxosState:
    """A pytree of NamedShardings: every [G, ...] array shards along G;
    scalars and the latency histogram replicate."""

    def spec_for(leaf_name: str):
        scalar_or_global = {"committed", "retired", "lat_sum", "lat_hist"}
        if leaf_name in scalar_or_global:
            return NamedSharding(mesh, P())
        return NamedSharding(mesh, P(GROUP_AXIS))

    import dataclasses as _dc

    from frankenpaxos_tpu.tpu import multipaxos_batched as mb

    fields = [f.name for f in _dc.fields(mb.BatchedMultiPaxosState)]
    return {name: spec_for(name) for name in fields}


def shard_state(
    state: BatchedMultiPaxosState, mesh: Mesh
) -> BatchedMultiPaxosState:
    """Place the state on the mesh with the group axis sharded."""
    import dataclasses as _dc

    num_groups = state.leader_round.shape[-1]
    n_devices = mesh.devices.size
    if num_groups % n_devices != 0:
        raise ValueError(
            f"num_groups ({num_groups}) must be divisible by the mesh size "
            f"({n_devices}) to shard the group axis; pick num_groups as a "
            f"multiple of the device count."
        )
    specs = state_shardings(mesh)
    out = {}
    for f in _dc.fields(state):
        out[f.name] = jax.device_put(getattr(state, f.name), specs[f.name])
    return type(state)(**out)


@functools.partial(jax.jit, static_argnums=(0, 1, 4))
def _run_ticks_sharded(
    cfg: BatchedMultiPaxosConfig,
    mesh: Mesh,
    state: BatchedMultiPaxosState,
    t0: jnp.ndarray,
    num_ticks: int,
    key: jnp.ndarray,
):
    # The tick is elementwise over groups; with the G axis sharded, XLA
    # partitions the whole scan with no communication except the scalar
    # stat reductions (psum over ICI). We rely on GSPMD propagation from
    # the input shardings rather than hand-writing shard_map: the program
    # has no cross-group contractions, so propagation is exact.
    return run_ticks.__wrapped__(cfg, state, t0, num_ticks, key)


def run_ticks_sharded(
    cfg: BatchedMultiPaxosConfig,
    mesh: Mesh,
    state: BatchedMultiPaxosState,
    t0,
    num_ticks: int,
    key,
) -> Tuple[BatchedMultiPaxosState, jnp.ndarray]:
    return _run_ticks_sharded(cfg, mesh, state, t0, num_ticks, key)
