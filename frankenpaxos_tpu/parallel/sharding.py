"""Generic multi-chip GSPMD layer: a per-backend registry of mesh
sharding specs + sharded ``run_ticks`` wrappers.

Every batched backend whose simulation carries a data-parallel
group/column axis registers a :class:`ShardingSpec` here: which axis of
each State field is the shard axis (``axis_pos``), which fields
replicate (``replicated``), and how long the sharded axis is. The layer
then provides, uniformly for every registered backend:

  * :func:`state_shardings` — the ``NamedSharding`` pytree for a mesh,
  * :func:`shard_state` — place a state on the mesh (with an axis
    divisibility check),
  * :func:`run_ticks_sharded` — a jitted multi-tick runner with
    ``donate_argnums`` preserved per shard (single-buffered state on
    every device), and
  * :func:`lower_sharded` — the lowering hook the static-analysis
    ``trace-donation-alias`` rule compiles to verify the HLO
    ``input_output_alias`` table under a mesh.

Partitioning model: the wrappers run the backend's OWN ``run_ticks``
body under input ``NamedSharding``s and let XLA's SPMD partitioner
propagate — the GSPMD equivalent of a hand-written ``shard_map`` over
the group axis, with the collectives inserted exactly where the tick's
reductions demand them. This is deliberate: the tick bodies compute
global quantities (commit counters, watermark minima, histogram
accumulations) inline, and under GSPMD each becomes one small psum over
ICI while every ``[..., G/n, ...]`` elementwise sweep stays group-local
— hand-writing shard_map would mean re-deriving every reduction site
per backend. The group-locality claim is pinned as a compile-time fact
by ``tests/test_multichip.py`` / ``tests/test_hlo_sharding.py`` (no
all-gather/all-to-all of signed state, stat reductions bounded by
``LAT_BINS`` elements) and re-checked by ``bench.py --multichip``'s
collective census. All simulation state is integer, and integer psums
are associative exactly, so sharded runs are BIT-IDENTICAL to
unsharded runs at any mesh size (also pinned by the tests).

Kernel policy x mesh: Pallas planes have no SPMD partitioning rule, so
a config whose :class:`KernelPolicy` resolves any plane off the
reference path under a mesh of >1 devices would silently mis-lower (the
kernel runs replicated or partitions wrong). :func:`validate_policy`
rejects that combination with a ``ValueError`` instead; at mesh size 1
any policy is allowed (sharded-vs-unsharded bit-identity with the
kernels engaged is pinned by ``tests/test_multichip.py``). On CPU the
default ``auto`` policy already resolves every plane to its reference
twin, so sharded CPU runs need no config change.
"""

from __future__ import annotations

import dataclasses
import functools
import importlib
from typing import Callable, Dict, Mapping, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

GROUP_AXIS = "groups"


def make_mesh(devices=None, axis_name: str = GROUP_AXIS) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.asarray(devices).reshape(-1), (axis_name,))


@dataclasses.dataclass(frozen=True)
class ShardingSpec:
    """One backend's entry in the sharding registry.

    ``axis_pos`` maps State field name -> index of the sharded
    (group/column) axis in that field's shape; fields in ``replicated``
    replicate on every device; any field in neither defaults to axis 0.
    ``axis_len`` reads the sharded-axis extent off a live state (for
    the divisibility check); ``planes_backend`` names the kernel
    registry backend whose planes :func:`validate_policy` must check
    (None = no registered planes can apply).
    """

    backend: str
    module: str  # dotted module path of the tpu/*_batched.py backend
    state_class: str  # the module's State dataclass name
    replicated: frozenset
    axis_pos: Mapping[str, int]
    axis_len: Callable[[object], int]
    axis_desc: str  # e.g. "num_groups" — for error messages
    planes_backend: Optional[str] = None

    def mod(self):
        return importlib.import_module(self.module)

    def spec_for(self, field: str) -> P:
        if field in self.replicated:
            return P()
        pos = self.axis_pos.get(field, 0)
        return P(*([None] * pos + [GROUP_AXIS]))


SHARDINGS: Dict[str, ShardingSpec] = {}


def register_sharding(spec: ShardingSpec) -> ShardingSpec:
    assert spec.backend not in SHARDINGS, f"duplicate {spec.backend}"
    SHARDINGS[spec.backend] = spec
    return spec


def state_shardings(backend: str, mesh: Mesh) -> Dict[str, NamedSharding]:
    """field name -> NamedSharding for the backend's State dataclass."""
    spec = SHARDINGS[backend]
    state_cls = getattr(spec.mod(), spec.state_class)
    assert dataclasses.is_dataclass(state_cls), spec.state_class
    return {
        f.name: NamedSharding(mesh, spec.spec_for(f.name))
        for f in dataclasses.fields(state_cls)
    }


def shard_state(backend: str, state, mesh: Mesh):
    """Place a state dataclass on the mesh per the backend's spec; the
    sharded axis must divide evenly over the devices."""
    spec = SHARDINGS[backend]
    n_devices = mesh.devices.size
    axis_len = spec.axis_len(state)
    if axis_len % n_devices != 0:
        raise ValueError(
            f"{spec.axis_desc} ({axis_len}) must be divisible by the "
            f"mesh size ({n_devices}) to shard that axis; pick a "
            "multiple of the device count."
        )
    shardings = state_shardings(backend, mesh)
    out = {}
    for f in dataclasses.fields(state):
        out[f.name] = jax.device_put(getattr(state, f.name), shardings[f.name])
    return type(state)(**out)


def validate_policy(backend: str, cfg, mesh: Mesh) -> None:
    """Reject kernel policies that would silently mis-lower under a
    real mesh: with >1 devices, every registered plane of the backend
    must resolve to its reference twin (Pallas has no SPMD partitioning
    rule). Mesh size 1 allows any policy."""
    if mesh.devices.size <= 1:
        return
    spec = SHARDINGS[backend]
    if spec.planes_backend is None:
        return
    from frankenpaxos_tpu.ops import registry

    offending = {
        name: registry.resolve_mode(name, cfg)
        for name, plane in registry.PLANES.items()
        if plane.backend == spec.planes_backend
        and registry.resolve_mode(name, cfg) != "reference"
    }
    if offending:
        raise ValueError(
            f"KernelPolicy resolves plane(s) {offending} off the "
            f"reference path under a {mesh.devices.size}-device mesh — "
            "Pallas kernels have no SPMD partitioning rule, so the "
            "sharded program would silently mis-lower. Use "
            "kernels=KernelPolicy.reference() (or mode='auto' on a "
            "non-TPU backend) for sharded runs."
        )


@functools.lru_cache(maxsize=None)
def _runner(backend: str):
    """The jitted sharded multi-tick runner for one backend. The
    backend's own ``run_ticks`` body runs under the input shardings
    (GSPMD propagation, module docstring); ``state`` is DONATED —
    single-buffered per shard — so callers rebind the returned state
    and must not reuse the argument."""
    mod = SHARDINGS[backend].mod()

    @functools.partial(jax.jit, static_argnums=(0, 3), donate_argnums=(1,))
    def run(cfg, state, t0, num_ticks: int, key):
        return mod.run_ticks.__wrapped__(cfg, state, t0, num_ticks, key)

    return run


def run_ticks_sharded(
    backend: str, cfg, mesh: Mesh, state, t0, num_ticks: int, key
) -> Tuple[object, jnp.ndarray]:
    """Run ``num_ticks`` of the backend's simulation with the state
    sharded per the registry spec (see :func:`shard_state`). The mesh
    argument is used for policy validation; the partitioning itself
    rides the state's shardings."""
    validate_policy(backend, cfg, mesh)
    return _runner(backend)(cfg, state, t0, num_ticks, key)


def lower_sharded(
    backend: str, cfg, mesh: Mesh, state, t0, num_ticks: int, key
):
    """Lower (don't run) the sharded runner — the static-analysis
    ``trace-donation-alias`` rule compiles this to check that every
    donated State leaf is aliased in the HLO under a mesh."""
    validate_policy(backend, cfg, mesh)
    return _runner(backend).lower(cfg, state, t0, num_ticks, key)


# ---------------------------------------------------------------------------
# Registry entries
# ---------------------------------------------------------------------------

# Flagship batched MultiPaxos: every [G, ...] array shards along G;
# scalars, stats, the shared read wave, and the telemetry ring
# replicate. Acceptor-major arrays ([A, G, W] / [A, G] / [M, G] /
# [A, G, RW]) carry the group axis SECOND.
register_sharding(
    ShardingSpec(
        backend="multipaxos",
        module="frankenpaxos_tpu.tpu.multipaxos_batched",
        state_class="BatchedMultiPaxosState",
        replicated=frozenset({
            "committed", "retired", "lat_sum", "lat_hist",
            "max_chosen_global", "client_watermark", "wave_issue",
            "reads_done", "reads_shed", "read_lat_sum", "read_lat_hist",
            "read_lin_violations", "elections", "reconfigs", "configs_gcd",
            "sm_applied", "dups_filtered", "dups_seen",
            # The telemetry ring holds cluster-wide per-tick reductions
            # ([K, NUM_COLS] + histograms) — replicated; device_put
            # broadcasts the spec over the nested pytree's leaves.
            "telemetry",
        }),
        axis_pos={
            name: 1
            for name in (
                "acc_round", "p2a_arrival", "p2b_arrival", "vote_round",
                "vote_value", "acc_max_slot", "req_arrival", "resp_slot",
                "resp_arrival", "leader_alive",  # [C, G] candidates
                # [M, G] matchmakers / [A, G] old-config phase-1.
                "mm_epoch", "matcha_arrival", "matchb_arrival",
                "rc_p1a_arrival", "rc_p1b_arrival",
            )
        },
        axis_len=lambda st: st.leader_round.shape[-1],
        axis_desc="num_groups",
        planes_backend="multipaxos",
    )
)

# Batched EPaxos: every [C, ...] array shards along the column axis;
# the frontier history ([H, C]) and per-replica GC watermarks ([R, C])
# shard on their SECOND axis; scalars and histograms replicate. The
# closure's only cross-device traffic is the [H]-sized tick scores and
# scalar stats.
register_sharding(
    ShardingSpec(
        backend="epaxos",
        module="frankenpaxos_tpu.tpu.epaxos_batched",
        state_class="BatchedEPaxosState",
        replicated=frozenset({
            "committed_total", "fast_path_total", "executed_total",
            "retired_total", "coexecuted", "lat_sum", "lat_hist",
            "snapshots_served", "rep_crashes", "rep_down", "telemetry",
        }),
        axis_pos={name: 1 for name in ("fpre", "fpost", "rep_exec")},
        axis_len=lambda st: st.head.shape[0],
        axis_desc="num_columns",
        planes_backend=None,
    )
)

# Compartmentalized MultiPaxos: role-major planes with (G, W) minor.
# Grid planes ([R, C, G, W]) carry the group axis THIRD, replica planes
# ([NR, G, W] / [NR, G] / [NR, G, RW]) SECOND, everything else
# ([G, ...]) first; scalar stats, histograms, and the telemetry ring
# replicate. The whole write path (batchers -> leader -> proxies ->
# grid -> replicas -> unbatchers) is group-local; only the commit/
# watermark/histogram reductions cross devices.
register_sharding(
    ShardingSpec(
        backend="compartmentalized",
        module="frankenpaxos_tpu.tpu.compartmentalized_batched",
        state_class="BatchedCompartmentalizedState",
        replicated=frozenset({
            "bat_shed", "committed", "batches_committed", "retired",
            "writes_done", "lat_sum", "lat_hist", "reads_done",
            "reads_shed", "read_lat_sum", "read_lat_hist", "telemetry",
        }),
        axis_pos={
            **{name: 2 for name in ("p2a_arrival", "p2b_arrival")},
            **{
                name: 1
                for name in (
                    "rep_arrival", "rep_exec", "rd_issue", "rd_bound",
                    "rd_count", "rd_probe", "rd_row",
                )
            },
        },
        axis_len=lambda st: st.head.shape[0],
        axis_desc="num_groups",
        planes_backend="compartmentalized",
    )
)
