"""Generic multi-chip GSPMD layer: a per-backend registry of mesh
sharding specs + sharded ``run_ticks`` wrappers.

Every batched backend whose simulation carries a data-parallel
group/column axis registers a :class:`ShardingSpec` here: which axis of
each State field is the shard axis (``axis_pos``), which fields
replicate (``replicated``), and how long the sharded axis is. The layer
then provides, uniformly for every registered backend:

  * :func:`state_shardings` — the ``NamedSharding`` pytree for a mesh,
  * :func:`shard_state` — place a state on the mesh (with an axis
    divisibility check),
  * :func:`run_ticks_sharded` — a jitted multi-tick runner with
    ``donate_argnums`` preserved per shard (single-buffered state on
    every device), and
  * :func:`lower_sharded` — the lowering hook the static-analysis
    ``trace-donation-alias`` rule compiles to verify the HLO
    ``input_output_alias`` table under a mesh.

Partitioning model: the wrappers run the backend's OWN ``run_ticks``
body under input ``NamedSharding``s and let XLA's SPMD partitioner
propagate — the GSPMD equivalent of a hand-written ``shard_map`` over
the group axis, with the collectives inserted exactly where the tick's
reductions demand them. This is deliberate: the tick bodies compute
global quantities (commit counters, watermark minima, histogram
accumulations) inline, and under GSPMD each becomes one small psum over
ICI while every ``[..., G/n, ...]`` elementwise sweep stays group-local
— hand-writing shard_map would mean re-deriving every reduction site
per backend. The group-locality claim is pinned as a compile-time fact
by ``tests/test_multichip.py`` / ``tests/test_hlo_sharding.py`` (no
all-gather/all-to-all of signed state, stat reductions bounded by
``LAT_BINS`` elements) and re-checked by ``bench.py --multichip``'s
collective census. All simulation state is integer, and integer psums
are associative exactly, so sharded runs are BIT-IDENTICAL to
unsharded runs at any mesh size (also pinned by the tests).

Kernel policy x mesh — the kernels x mesh COMPOSITION layer: Pallas
planes have no SPMD partitioning rule, so GSPMD alone cannot partition
an engaged kernel. Instead of rejecting the combination, the sharded
runners trace under ``ops.registry.shard_lowering(mesh)``: every
engaged plane that declares a ``ShardSpec`` (all planes are group-local
— no cross-group dataflow) lowers through ``jax.shard_map`` over the
group axis, so each device runs the kernel on its local ``[*, G/D, *]``
shard with the block size autotuned for the PER-DEVICE shape (the
table's nearest-G fallback). Sharded+kernels runs are BIT-IDENTICAL to
unsharded+kernels and to the reference (pinned 3-seed by
``tests/test_multichip.py``; the ``trace-shardmap-kernel`` analysis
rule pins the lowering shape). :func:`validate_policy` still raises,
but only for planes whose registration declares them NON-shardable
(``shard=None`` — e.g. a future cross-group reduction that would need
in-kernel collectives); at mesh size 1 nothing wraps and any policy is
allowed. On CPU the default ``auto`` policy resolves every plane to
its reference twin, so sharded CPU runs engage kernels only when a
policy asks for them (mode="interpret"/"on").
"""

from __future__ import annotations

import dataclasses
import functools
import importlib
from typing import Callable, Dict, Mapping, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

GROUP_AXIS = "groups"


def make_mesh(devices=None, axis_name: str = GROUP_AXIS) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.asarray(devices).reshape(-1), (axis_name,))


@dataclasses.dataclass(frozen=True)
class ShardingSpec:
    """One backend's entry in the sharding registry.

    ``axis_pos`` maps State field name -> index of the sharded
    (group/column) axis in that field's shape; fields in ``replicated``
    replicate on every device; any field in neither defaults to axis 0.
    ``axis_len`` reads the sharded-axis extent off a live state (for
    the divisibility check); ``planes_backend`` names the kernel
    registry backend whose planes :func:`validate_policy` must check
    (None = no registered planes can apply).
    """

    backend: str
    module: str  # dotted module path of the tpu/*_batched.py backend
    state_class: str  # the module's State dataclass name
    replicated: frozenset
    axis_pos: Mapping[str, int]
    axis_len: Callable[[object], int]
    axis_desc: str  # e.g. "num_groups" — for error messages
    planes_backend: Optional[str] = None

    def mod(self):
        return importlib.import_module(self.module)

    def spec_for(self, field: str) -> P:
        if field in self.replicated:
            return P()
        pos = self.axis_pos.get(field, 0)
        return P(*([None] * pos + [GROUP_AXIS]))


SHARDINGS: Dict[str, ShardingSpec] = {}


def register_sharding(spec: ShardingSpec) -> ShardingSpec:
    assert spec.backend not in SHARDINGS, f"duplicate {spec.backend}"
    SHARDINGS[spec.backend] = spec
    return spec


def state_shardings(backend: str, mesh: Mesh) -> Dict[str, NamedSharding]:
    """field name -> NamedSharding for the backend's State dataclass."""
    spec = SHARDINGS[backend]
    state_cls = getattr(spec.mod(), spec.state_class)
    assert dataclasses.is_dataclass(state_cls), spec.state_class
    return {
        f.name: NamedSharding(mesh, spec.spec_for(f.name))
        for f in dataclasses.fields(state_cls)
    }


def shard_state(backend: str, state, mesh: Mesh):
    """Place a state dataclass on the mesh per the backend's spec; the
    sharded axis must divide evenly over the devices."""
    spec = SHARDINGS[backend]
    n_devices = mesh.devices.size
    axis_len = spec.axis_len(state)
    if axis_len % n_devices != 0:
        raise ValueError(
            f"{spec.axis_desc} ({axis_len}) must be divisible by the "
            f"mesh size ({n_devices}) to shard that axis; pick a "
            "multiple of the device count."
        )
    shardings = state_shardings(backend, mesh)
    out = {}
    for f in dataclasses.fields(state):
        out[f.name] = jax.device_put(getattr(state, f.name), shardings[f.name])
    return type(state)(**out)


def _engaged_planes(backend: str, cfg) -> Dict[str, str]:
    """Registered planes of ``backend`` the policy resolves OFF the
    reference path on the current jax backend: name -> mode."""
    spec = SHARDINGS[backend]
    if spec.planes_backend is None:
        return {}
    from frankenpaxos_tpu.ops import registry

    return {
        name: registry.resolve_mode(name, cfg)
        for name, plane in registry.PLANES.items()
        if plane.backend == spec.planes_backend
        and registry.resolve_mode(name, cfg) != "reference"
    }


def validate_policy(backend: str, cfg, mesh: Mesh) -> None:
    """Validate the KernelPolicy x mesh combination. Engaged planes
    with a :class:`registry.ShardSpec` lower per-device via
    ``jax.shard_map`` (module docstring) — allowed at any mesh size.
    Engaged planes WITHOUT one (declared non-shardable: they would need
    in-kernel collectives) raise a ``ValueError`` at mesh > 1 instead
    of silently mis-lowering. Mesh size 1 allows any policy."""
    if mesh.devices.size <= 1:
        return
    from frankenpaxos_tpu.ops import registry

    unshardable = {
        name: mode
        for name, mode in _engaged_planes(backend, cfg).items()
        if registry.PLANES[name].shard is None
    }
    if unshardable:
        raise ValueError(
            f"KernelPolicy resolves non-shardable plane(s) {unshardable} "
            f"off the reference path under a {mesh.devices.size}-device "
            "mesh — these planes declare no ShardSpec (they would need "
            "in-kernel collectives), so shard_map cannot lower them "
            "per-device. Use kernels=KernelPolicy.reference() or "
            "disable=(...) for sharded runs."
        )


def _wrap_mesh(backend: str, cfg, mesh: Mesh) -> Optional[Mesh]:
    """The mesh the runner must trace its kernels under: the real mesh
    when any plane is engaged at >1 devices (shard_map lowering), else
    None (plain GSPMD propagation — the reference path partitions
    itself, and a 1-device mesh needs no wrapping)."""
    if mesh.devices.size <= 1:
        return None
    return mesh if _engaged_planes(backend, cfg) else None


@functools.lru_cache(maxsize=None)
def _runner(backend: str, wrap_mesh: Optional[Mesh] = None):
    """The jitted sharded multi-tick runner for one backend. The
    backend's own ``run_ticks`` body runs under the input shardings
    (GSPMD propagation, module docstring); with ``wrap_mesh`` set, the
    trace additionally runs under ``registry.shard_lowering`` so every
    engaged kernel plane lowers through ``jax.shard_map`` on that mesh
    (one jitted runner per mesh — a cached executable never leaks
    across meshes). ``state`` is DONATED — single-buffered per shard —
    so callers rebind the returned state and must not reuse the
    argument."""
    from frankenpaxos_tpu.ops import registry

    mod = SHARDINGS[backend].mod()

    @functools.partial(jax.jit, static_argnums=(0, 3), donate_argnums=(1,))
    def run(cfg, state, t0, num_ticks: int, key):
        with registry.shard_lowering(wrap_mesh, GROUP_AXIS):
            return mod.run_ticks.__wrapped__(cfg, state, t0, num_ticks, key)

    return run


def run_ticks_sharded(
    backend: str, cfg, mesh: Mesh, state, t0, num_ticks: int, key
) -> Tuple[object, jnp.ndarray]:
    """Run ``num_ticks`` of the backend's simulation with the state
    sharded per the registry spec (see :func:`shard_state`). The mesh
    argument drives policy validation and the shard_map lowering of any
    engaged kernel planes; the GSPMD partitioning itself rides the
    state's shardings."""
    validate_policy(backend, cfg, mesh)
    wrap = _wrap_mesh(backend, cfg, mesh)
    return _runner(backend, wrap)(cfg, state, t0, num_ticks, key)


def lower_sharded(
    backend: str, cfg, mesh: Mesh, state, t0, num_ticks: int, key
):
    """Lower (don't run) the sharded runner — the static-analysis
    ``trace-donation-alias`` / ``trace-shardmap-kernel`` rules compile
    this to check aliasing and kernel lowering under a mesh."""
    validate_policy(backend, cfg, mesh)
    wrap = _wrap_mesh(backend, cfg, mesh)
    return _runner(backend, wrap).lower(cfg, state, t0, num_ticks, key)


# ---------------------------------------------------------------------------
# Registry entries
# ---------------------------------------------------------------------------

# Flagship batched MultiPaxos: every [G, ...] array shards along G;
# scalars, stats, the shared read wave, and the telemetry ring
# replicate. Acceptor-major arrays ([A, G, W] / [A, G] / [M, G] /
# [A, G, RW]) carry the group axis SECOND.
register_sharding(
    ShardingSpec(
        backend="multipaxos",
        module="frankenpaxos_tpu.tpu.multipaxos_batched",
        state_class="BatchedMultiPaxosState",
        replicated=frozenset({
            "committed", "retired", "lat_sum", "lat_hist",
            "max_chosen_global", "client_watermark", "wave_issue",
            "reads_done", "reads_shed", "read_lat_sum", "read_lat_hist",
            "read_lin_violations", "elections", "reconfigs", "configs_gcd",
            "sm_applied", "dups_filtered", "dups_seen",
            # The telemetry ring holds cluster-wide per-tick reductions
            # ([K, NUM_COLS] + histograms) — replicated; device_put
            # broadcasts the spec over the nested pytree's leaves. The
            # workload shaping state replicates the same way (all-empty
            # under WorkloadPlan.none(); tiny [G]-sized bookkeeping
            # otherwise), as does the lifecycle state (all-empty under
            # LifecyclePlan.none(); rotation scalars + the [G, S]
            # session table + the [A, G] membership mask otherwise —
            # the rotation predicate's min-head reduction is the only
            # cross-device traffic it adds, a scalar).
            "telemetry", "workload", "lifecycle",
        }),
        axis_pos={
            name: 1
            for name in (
                "acc_round", "p2a_arrival", "p2b_arrival", "vote_round",
                "vote_value", "acc_max_slot", "req_arrival", "resp_slot",
                "resp_arrival", "leader_alive",  # [C, G] candidates
                # [M, G] matchmakers / [A, G] old-config phase-1.
                "mm_epoch", "matcha_arrival", "matchb_arrival",
                "rc_p1a_arrival", "rc_p1b_arrival",
            )
        },
        axis_len=lambda st: st.leader_round.shape[-1],
        axis_desc="num_groups",
        planes_backend="multipaxos",
    )
)

# Batched EPaxos: every [C, ...] array shards along the column axis;
# the frontier history ([H, C]) and per-replica GC watermarks ([R, C])
# shard on their SECOND axis; scalars and histograms replicate. The
# closure's only cross-device traffic is the [H]-sized tick scores and
# scalar stats.
register_sharding(
    ShardingSpec(
        backend="epaxos",
        module="frankenpaxos_tpu.tpu.epaxos_batched",
        state_class="BatchedEPaxosState",
        replicated=frozenset({
            "committed_total", "fast_path_total", "executed_total",
            "retired_total", "coexecuted", "lat_sum", "lat_hist",
            "snapshots_served", "rep_crashes", "rep_down", "telemetry",
            "workload",
        }),
        axis_pos={name: 1 for name in ("fpre", "fpost", "rep_exec")},
        axis_len=lambda st: st.head.shape[0],
        axis_desc="num_columns",
        planes_backend=None,
    )
)

# Compartmentalized MultiPaxos: role-major planes with (G, W) minor.
# Grid planes ([R, C, G, W]) carry the group axis THIRD, replica planes
# ([NR, G, W] / [NR, G] / [NR, G, RW]) SECOND, everything else
# ([G, ...]) first; scalar stats, histograms, and the telemetry ring
# replicate. The whole write path (batchers -> leader -> proxies ->
# grid -> replicas -> unbatchers) is group-local; only the commit/
# watermark/histogram reductions cross devices.
register_sharding(
    ShardingSpec(
        backend="compartmentalized",
        module="frankenpaxos_tpu.tpu.compartmentalized_batched",
        state_class="BatchedCompartmentalizedState",
        replicated=frozenset({
            "bat_shed", "committed", "batches_committed", "retired",
            "writes_done", "lat_sum", "lat_hist", "reads_done",
            "reads_shed", "read_lat_sum", "read_lat_hist", "telemetry",
            "workload", "lifecycle",
        }),
        axis_pos={
            **{name: 2 for name in ("p2a_arrival", "p2b_arrival")},
            **{
                name: 1
                for name in (
                    "rep_arrival", "rep_exec", "rd_issue", "rd_bound",
                    "rd_count", "rd_probe", "rd_row",
                )
            },
        },
        axis_len=lambda st: st.head.shape[0],
        axis_desc="num_groups",
        planes_backend="compartmentalized",
    )
)
