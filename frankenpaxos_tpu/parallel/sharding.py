"""Generic multi-chip GSPMD layer: a per-backend registry of mesh
sharding specs + sharded ``run_ticks`` wrappers.

Every batched backend whose simulation carries a data-parallel
group/column axis registers a :class:`ShardingSpec` here: which axis of
each State field is the shard axis (``axis_pos``), which fields
replicate (``replicated``), and how long the sharded axis is. The layer
then provides, uniformly for every registered backend:

  * :func:`state_shardings` — the ``NamedSharding`` pytree for a mesh,
  * :func:`shard_state` — place a state on the mesh (with an axis
    divisibility check),
  * :func:`run_ticks_sharded` — a jitted multi-tick runner with
    ``donate_argnums`` preserved per shard (single-buffered state on
    every device), and
  * :func:`lower_sharded` — the lowering hook the static-analysis
    ``trace-donation-alias`` rule compiles to verify the HLO
    ``input_output_alias`` table under a mesh.

Partitioning model: the wrappers run the backend's OWN ``run_ticks``
body under input ``NamedSharding``s and let XLA's SPMD partitioner
propagate — the GSPMD equivalent of a hand-written ``shard_map`` over
the group axis, with the collectives inserted exactly where the tick's
reductions demand them. This is deliberate: the tick bodies compute
global quantities (commit counters, watermark minima, histogram
accumulations) inline, and under GSPMD each becomes one small psum over
ICI while every ``[..., G/n, ...]`` elementwise sweep stays group-local
— hand-writing shard_map would mean re-deriving every reduction site
per backend. The group-locality claim is pinned as a compile-time fact
by ``tests/test_multichip.py`` / ``tests/test_hlo_sharding.py`` (no
all-gather/all-to-all of signed state, stat reductions bounded by
``LAT_BINS`` elements) and re-checked by ``bench.py --multichip``'s
collective census. All simulation state is integer, and integer psums
are associative exactly, so sharded runs are BIT-IDENTICAL to
unsharded runs at any mesh size (also pinned by the tests).

Kernel policy x mesh — the kernels x mesh COMPOSITION layer: Pallas
planes have no SPMD partitioning rule, so GSPMD alone cannot partition
an engaged kernel. Instead of rejecting the combination, the sharded
runners trace under ``ops.registry.shard_lowering(mesh)``: every
engaged plane that declares a ``ShardSpec`` (all planes are group-local
— no cross-group dataflow) lowers through ``jax.shard_map`` over the
group axis, so each device runs the kernel on its local ``[*, G/D, *]``
shard with the block size autotuned for the PER-DEVICE shape (the
table's nearest-G fallback). Sharded+kernels runs are BIT-IDENTICAL to
unsharded+kernels and to the reference (pinned 3-seed by
``tests/test_multichip.py``; the ``trace-shardmap-kernel`` analysis
rule pins the lowering shape). :func:`validate_policy` still raises,
but only for planes whose registration declares them NON-shardable
(``shard=None`` — e.g. a future cross-group reduction that would need
in-kernel collectives); at mesh size 1 nothing wraps and any policy is
allowed. On CPU the default ``auto`` policy resolves every plane to
its reference twin, so sharded CPU runs engage kernels only when a
policy asks for them (mode="interpret"/"on").

FLEET axis — the two-axis product mesh (``('fleet', 'groups')``): the
whole layer is MESH-SHAPE-AGNOSTIC. The group axis keeps sharding one
protocol instance's group/column planes exactly as above (a 2-D mesh
with a trivial fleet axis behaves identically to the old 1-D mesh);
the NEW fleet axis data-parallels INDEPENDENT protocol instances —
whole clusters are embarrassingly parallel along it (the
compartmentalization thesis applied one level up: nothing ever crosses
the fleet axis, pinned by the ``trace-fleet-onecompile`` rule's
replica-group census). Fleet states carry one LEADING instance axis on
every State leaf (:func:`fleet_states`): per-instance PRNG seeds,
per-instance traced ``WorkloadState.rate`` offered loads, and
per-instance ``FaultPlan(traced=True)`` Bernoulli rates all enter as
fleet-sharded arrays, so a whole [seeds x workload x fault] brick is
ONE compiled executable per mesh (:func:`run_ticks_fleet` — jit of
``vmap(run_ticks)`` with ``spmd_axis_name=FLEET_AXIS``, donation
preserved). Engaged kernel planes still lower through ``jax.shard_map``
over the GROUP axis; the vmap batching rule maps the instance axis onto
the fleet mesh axis via ``spmd_axis_name``, and the autotune lookup
resolves at the true PER-DEVICE shape (the group-axis mesh extent, not
the total device count — a product mesh changes the divisor).

Multi-host: :func:`maybe_init_distributed` initializes
``jax.distributed`` from the standard env/args and
:func:`make_fleet_mesh` builds the product mesh via
``mesh_utils.create_hybrid_device_mesh`` when more than one process is
attached (the T5X partitioner pattern — ICI-adjacent devices land on
the group axis, the slower DCN links carry only the fleet axis, which
moves NO data), with :func:`host_sync` (``multihost_utils``) as the
cross-host barrier. On a single process it degrades to a plain reshape
of the local devices, which is how the 8-virtual-device CPU CI runs
it; the real-pod leg stays on the hardware-debt list.
"""

from __future__ import annotations

import dataclasses
import functools
import importlib
from typing import Callable, Dict, Mapping, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

GROUP_AXIS = "groups"
FLEET_AXIS = "fleet"


def make_mesh(devices=None, axis_name: str = GROUP_AXIS) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.asarray(devices).reshape(-1), (axis_name,))


def group_size(mesh: Mesh) -> int:
    """Extent of the group axis — mesh-shape-agnostic (a 1-D group
    mesh, the 2-D product mesh, and a degenerate fleet-only mesh all
    answer correctly)."""
    return dict(mesh.shape).get(GROUP_AXIS, 1)


def fleet_size(mesh: Mesh) -> int:
    return dict(mesh.shape).get(FLEET_AXIS, 1)


def maybe_init_distributed(**kwargs) -> bool:
    """Initialize ``jax.distributed`` for a multi-host fleet when the
    standard coordination env is present (``JAX_COORDINATOR_ADDRESS``
    or explicit kwargs — the same contract ``jax.distributed
    .initialize`` reads). Single-host runs (CI's virtual-device mesh)
    are a no-op returning False; calling twice is harmless. Returns
    True when a multi-process runtime is attached.

    Order matters: ``initialize`` must run before ANYTHING touches the
    jax backend (including ``jax.process_count()``), so the env check
    gates first and only genuinely-already-initialized errors are
    swallowed — a bad coordinator address or a too-late call stays
    loud instead of silently degrading a pod to N disconnected
    hosts."""
    import os

    if not (kwargs or os.environ.get("JAX_COORDINATOR_ADDRESS")):
        # No coordination config: single-host, or a launcher already
        # initialized the runtime before importing us.
        return jax.process_count() > 1
    try:
        jax.distributed.initialize(**kwargs)
    except RuntimeError as e:
        if "already" not in str(e).lower():
            raise  # misconfiguration / called after backend init
    return jax.process_count() > 1


def host_sync(tag: str) -> None:
    """Cross-host barrier (``multihost_utils.sync_global_devices``):
    fleet consumers call it around checkpoint/bench boundaries so every
    host observes the same brick. No-op on a single process, so the
    call sites stay portable down to the CPU CI mesh."""
    if jax.process_count() <= 1:
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(tag)


def make_fleet_mesh(fleet: int = 1, devices=None) -> Mesh:
    """The two-axis product mesh: ``fleet`` rows of independent
    protocol instances x the group axis sharding each instance. The
    device count must divide into ``fleet`` evenly; ``fleet=1``
    degenerates to the old single-axis behavior (with the axis present,
    so one code path serves every mesh shape).

    Multi-host: with >1 jax processes attached (see
    :func:`maybe_init_distributed`), the mesh comes from
    ``mesh_utils.create_hybrid_device_mesh`` so the group axis stays
    ICI-local per slice and only the data-parallel fleet axis — which
    carries zero protocol traffic — crosses DCN."""
    if devices is None and jax.process_count() > 1:
        from jax.experimental import mesh_utils

        nproc = jax.process_count()
        n_local = jax.local_device_count()
        # The fleet axis factors as (hosts x rows-per-host): whole rows
        # never straddle DCN, and each host's ICI-local devices carry
        # its rows' group shards. Both divisibility constraints are
        # asserted HERE (a violation inside create_hybrid_device_mesh
        # surfaces as an opaque reshape error).
        assert fleet % nproc == 0, (
            f"fleet rows ({fleet}) must divide over the {nproc} hosts "
            "(whole rows never straddle DCN)"
        )
        rows_per_host = fleet // nproc
        assert n_local % rows_per_host == 0, (
            f"{n_local} local devices do not divide into "
            f"{rows_per_host} fleet rows per host"
        )
        dev_grid = mesh_utils.create_hybrid_device_mesh(
            mesh_shape=(rows_per_host, n_local // rows_per_host),
            dcn_mesh_shape=(nproc, 1),
        )
        return Mesh(dev_grid, (FLEET_AXIS, GROUP_AXIS))
    devices = devices if devices is not None else jax.devices()
    arr = np.asarray(devices)
    assert arr.size % fleet == 0, (
        f"{arr.size} devices do not divide into a {fleet}-row fleet axis"
    )
    return Mesh(arr.reshape(fleet, -1), (FLEET_AXIS, GROUP_AXIS))


@dataclasses.dataclass(frozen=True)
class ShardingSpec:
    """One backend's entry in the sharding registry.

    ``axis_pos`` maps State field name -> index of the sharded
    (group/column) axis in that field's shape; fields in ``replicated``
    replicate on every device; any field in neither defaults to axis 0.
    ``axis_len`` reads the sharded-axis extent off a live state (for
    the divisibility check); ``planes_backend`` names the kernel
    registry backend whose planes :func:`validate_policy` must check
    (None = no registered planes can apply).
    """

    backend: str
    module: str  # dotted module path of the tpu/*_batched.py backend
    state_class: str  # the module's State dataclass name
    replicated: frozenset
    axis_pos: Mapping[str, int]
    axis_len: Callable[[object], int]
    axis_desc: str  # e.g. "num_groups" — for error messages
    planes_backend: Optional[str] = None

    def mod(self):
        return importlib.import_module(self.module)

    def spec_for(self, field: str, fleet: bool = False) -> P:
        """The field's PartitionSpec. ``fleet=True`` is the fleet-state
        layout: every leaf gains a LEADING instance axis sharded over
        ``FLEET_AXIS``, and the group axis (where the field has one)
        shifts one position right. Single-instance specs on a 2-D mesh
        simply replicate over the fleet axis — mesh-shape-agnostic."""
        lead = [FLEET_AXIS] if fleet else []
        if field in self.replicated:
            return P(*lead)
        pos = self.axis_pos.get(field, 0)
        return P(*(lead + [None] * pos + [GROUP_AXIS]))


SHARDINGS: Dict[str, ShardingSpec] = {}


def register_sharding(spec: ShardingSpec) -> ShardingSpec:
    assert spec.backend not in SHARDINGS, f"duplicate {spec.backend}"
    SHARDINGS[spec.backend] = spec
    return spec


def state_shardings(
    backend: str, mesh: Mesh, fleet: bool = False
) -> Dict[str, NamedSharding]:
    """field name -> NamedSharding for the backend's State dataclass."""
    spec = SHARDINGS[backend]
    state_cls = getattr(spec.mod(), spec.state_class)
    assert dataclasses.is_dataclass(state_cls), spec.state_class
    return {
        f.name: NamedSharding(mesh, spec.spec_for(f.name, fleet=fleet))
        for f in dataclasses.fields(state_cls)
    }


def _reject_fleet_axis(mesh: Mesh) -> None:
    """Single-INSTANCE wrappers only ride the group axis. A >1 fleet
    axis under a single instance is rejected loudly: with the repo's
    non-partitionable threefry (the golden-pinned PRNG), XLA's SPMD
    partitioner makes an unbatched PRNG sweep's VALUES depend on how
    the spare mesh axis tiles it — a silent bit-drift, demonstrated by
    the guard test in tests/test_fleet.py. Fleet instances go through
    :func:`fleet_states` / :func:`run_ticks_fleet`, whose explicit
    instance axis (vmap + ``spmd_axis_name``) is pinned bit-identical
    across mesh shapes."""
    if fleet_size(mesh) > 1:
        raise ValueError(
            f"mesh {dict(mesh.shape)} has a >1 fleet axis: "
            "single-instance states shard the group axis only — use "
            "the fleet API (fleet_states/shard_fleet_state/"
            "run_ticks_fleet) for data-parallel instances"
        )


def shard_state(backend: str, state, mesh: Mesh):
    """Place a state dataclass on the mesh per the backend's spec; the
    sharded axis must divide evenly over the GROUP-axis extent. Meshes
    with a >1 fleet axis are rejected (:func:`_reject_fleet_axis`)."""
    spec = SHARDINGS[backend]
    _reject_fleet_axis(mesh)
    n_group = group_size(mesh)
    axis_len = spec.axis_len(state)
    if axis_len % n_group != 0:
        raise ValueError(
            f"{spec.axis_desc} ({axis_len}) must be divisible by the "
            f"mesh size ({n_group}) to shard that axis; pick a "
            "multiple of the device count."
        )
    out = {}
    for f in dataclasses.fields(state):
        value = getattr(state, f.name)
        out[f.name] = jax.device_put(
            value,
            _nested_field_sharding(
                spec, f.name, value, mesh, axis_len, fleet=False
            ),
        )
    return type(state)(**out)


def _engaged_planes(backend: str, cfg) -> Dict[str, str]:
    """Registered planes of ``backend`` the policy resolves OFF the
    reference path on the current jax backend: name -> mode."""
    spec = SHARDINGS[backend]
    if spec.planes_backend is None:
        return {}
    from frankenpaxos_tpu.ops import registry

    return {
        name: registry.resolve_mode(name, cfg)
        for name, plane in registry.PLANES.items()
        if plane.backend == spec.planes_backend
        and registry.resolve_mode(name, cfg) != "reference"
    }


def validate_policy(backend: str, cfg, mesh: Mesh) -> None:
    """Validate the KernelPolicy x mesh combination. Engaged planes
    with a :class:`registry.ShardSpec` lower per-device via
    ``jax.shard_map`` (module docstring) — allowed at any mesh size.
    Engaged planes WITHOUT one (declared non-shardable: they would need
    in-kernel collectives) raise a ``ValueError`` at mesh > 1 instead
    of silently mis-lowering. Mesh size 1 allows any policy."""
    if mesh.devices.size <= 1:
        return
    from frankenpaxos_tpu.ops import registry

    unshardable = {
        name: mode
        for name, mode in _engaged_planes(backend, cfg).items()
        if registry.PLANES[name].shard is None
    }
    if unshardable:
        raise ValueError(
            f"KernelPolicy resolves non-shardable plane(s) {unshardable} "
            f"off the reference path under a {mesh.devices.size}-device "
            "mesh — these planes declare no ShardSpec (they would need "
            "in-kernel collectives), so shard_map cannot lower them "
            "per-device. Use kernels=KernelPolicy.reference() or "
            "disable=(...) for sharded runs."
        )


def _wrap_mesh(backend: str, cfg, mesh: Mesh) -> Optional[Mesh]:
    """The mesh the runner must trace its kernels under: the real mesh
    when any plane is engaged at >1 devices (shard_map lowering), else
    None (plain GSPMD propagation — the reference path partitions
    itself, and a 1-device mesh needs no wrapping)."""
    if mesh.devices.size <= 1:
        return None
    return mesh if _engaged_planes(backend, cfg) else None


def _constrain_client_out(backend: str, mesh: Mesh, state):
    """Pin the single-instance runner's client-plane OUTPUT shardings
    (the nested workload/lifecycle subtrees, per leaf) to the layout
    :func:`shard_state` placed the inputs in. Without this, XLA may
    assign feature-off (zero-sized) leaves a different output sharding
    than the input's, so rebinding segment 1's result into segment 2
    presents new input shardings and recompiles — and a re-replicated
    session table would break the donation alias on the [L, S] planes.
    Only the nested client subtrees are constrained; every protocol
    plane keeps pure GSPMD propagation (the HLO the census rules pin)."""
    spec = SHARDINGS[backend]
    lanes = spec.axis_len(state)
    out = {}
    for f in dataclasses.fields(state):
        v = getattr(state, f.name)
        if f.name not in _NESTED_LANE_FIELDS or not dataclasses.is_dataclass(v):
            out[f.name] = v
            continue
        sharding = _nested_field_sharding(
            spec, f.name, v, mesh, lanes, fleet=False
        )
        out[f.name] = type(v)(**{
            g.name: jax.lax.with_sharding_constraint(
                getattr(v, g.name), getattr(sharding, g.name)
            )
            for g in dataclasses.fields(v)
        })
    return type(state)(**out)


@functools.lru_cache(maxsize=None)
def _runner(
    backend: str,
    wrap_mesh: Optional[Mesh] = None,
    mesh: Optional[Mesh] = None,
):
    """The jitted sharded multi-tick runner for one backend. The
    backend's own ``run_ticks`` body runs under the input shardings
    (GSPMD propagation, module docstring); with ``wrap_mesh`` set, the
    trace additionally runs under ``registry.shard_lowering`` so every
    engaged kernel plane lowers through ``jax.shard_map`` on that mesh
    (one jitted runner per mesh — a cached executable never leaks
    across meshes). With ``mesh`` set (any >1-device run), the client
    planes' output shardings are pinned (:func:`_constrain_client_out`)
    so segmented runs stay on one executable. ``state`` is DONATED —
    single-buffered per shard — so callers rebind the returned state
    and must not reuse the argument."""
    from frankenpaxos_tpu.ops import registry

    mod = SHARDINGS[backend].mod()

    @functools.partial(jax.jit, static_argnums=(0, 3), donate_argnums=(1,))
    def run(cfg, state, t0, num_ticks: int, key):
        with registry.shard_lowering(wrap_mesh, GROUP_AXIS):
            state, t = mod.run_ticks.__wrapped__(
                cfg, state, t0, num_ticks, key
            )
        if mesh is not None:
            state = _constrain_client_out(backend, mesh, state)
        return state, t

    return run


def _constrain_mesh(mesh: Mesh) -> Optional[Mesh]:
    """The mesh :func:`_constrain_client_out` pins outputs on: any real
    multi-device mesh. Single-device runs skip the constraints (nothing
    to pin, and the unsharded HLO stays byte-stable)."""
    return mesh if mesh.devices.size > 1 else None


def run_ticks_sharded(
    backend: str, cfg, mesh: Mesh, state, t0, num_ticks: int, key
) -> Tuple[object, jnp.ndarray]:
    """Run ``num_ticks`` of the backend's simulation with the state
    sharded per the registry spec (see :func:`shard_state`). The mesh
    argument drives policy validation and the shard_map lowering of any
    engaged kernel planes; the GSPMD partitioning itself rides the
    state's shardings."""
    _reject_fleet_axis(mesh)
    validate_policy(backend, cfg, mesh)
    wrap = _wrap_mesh(backend, cfg, mesh)
    return _runner(backend, wrap, _constrain_mesh(mesh))(
        cfg, state, t0, num_ticks, key
    )


def lower_sharded(
    backend: str, cfg, mesh: Mesh, state, t0, num_ticks: int, key
):
    """Lower (don't run) the sharded runner — the static-analysis
    ``trace-donation-alias`` / ``trace-shardmap-kernel`` rules compile
    this to check aliasing and kernel lowering under a mesh."""
    _reject_fleet_axis(mesh)
    validate_policy(backend, cfg, mesh)
    wrap = _wrap_mesh(backend, cfg, mesh)
    return _runner(backend, wrap, _constrain_mesh(mesh)).lower(
        cfg, state, t0, num_ticks, key
    )


# ---------------------------------------------------------------------------
# Fleet execution: the seed/replica data-parallel axis
# ---------------------------------------------------------------------------


def fleet_states(
    backend: str,
    cfg,
    n: int,
    rates=None,
    fault_rates=None,
    module=None,
    base=None,
):
    """``n`` independent instances of the backend's fresh state as ONE
    pytree with a leading instance axis on every leaf (the fleet-state
    layout :func:`ShardingSpec.spec_for` shards).

    ``rates`` ([n] floats) seeds each instance's TRACED offered load
    (needs a shaped ``WorkloadPlan``); ``fault_rates`` ([n, 4] floats,
    ``[drop, dup, crash, revive]`` per row) seeds each instance's
    traced Bernoulli fault rates (needs ``FaultPlan(traced=True)``).
    Both are state-side, so a whole brick of distinct (workload, fault)
    cells shares one compiled executable.

    ``module`` overrides the sharding-registry lookup with an explicit
    ``tpu/*_batched`` module — how ``simtest.run_fleet`` builds bricks
    for backends outside the registry (mesh=None runs need no specs).
    ``base`` overrides the fresh ``init_state(cfg)`` template — how the
    fleet serve loop installs a SIZED telemetry ring (and span
    reservoir) on every instance before broadcasting."""
    mod = module if module is not None else SHARDINGS[backend].mod()
    base = base if base is not None else mod.init_state(cfg)
    states = jax.tree_util.tree_map(
        lambda a: jnp.broadcast_to(a[None], (n,) + a.shape), base
    )
    wls = getattr(states, "workload", None)
    if rates is not None:
        rates = jnp.asarray(rates, jnp.float32)
        assert wls is not None and wls.rate.shape == (n,), (
            "per-instance rates need a shaped WorkloadPlan "
            "(arrival != 'saturate') on the config"
        )
        assert rates.shape == (n,), (rates.shape, n)
        wls = dataclasses.replace(wls, rate=rates)
    if fault_rates is not None:
        fault_rates = jnp.asarray(fault_rates, jnp.float32)
        assert wls is not None and wls.fault_rates.shape == (n, 4), (
            "per-instance fault rates need FaultPlan(traced=True) "
            "on the config"
        )
        assert fault_rates.shape == (n, 4), (fault_rates.shape, n)
        wls = dataclasses.replace(wls, fault_rates=fault_rates)
    if wls is not None:
        states = dataclasses.replace(states, workload=wls)
    return states


def fleet_keys(seeds) -> jnp.ndarray:
    """[n, 2] per-instance PRNG keys from a sequence of integer seeds —
    instance i of the fleet replays EXACTLY the program a sequential
    run of seed i replays (the bit-identity contract of
    ``tests/test_fleet.py``)."""
    return jax.vmap(jax.random.PRNGKey)(
        jnp.asarray(list(seeds), jnp.uint32)
    )


# Client-plane fields whose leading (post-instance) axis is the
# backend's LANE axis — the same axis the group sharding splits, since
# every registered backend's lanes are its groups/columns. These shard
# over the GROUP axis (and the fleet axis, in the fleet layout) instead
# of replicating: GSPMD propagation re-shards them that way anyway (the
# admission cap clamps group-sharded propose planes elementwise, the
# session table joins group-sharded completion counts), and placing
# them pre-sharded keeps the donation aliases intact (a resharded input
# cannot alias its output). At production cardinality the session table
# ([L, S] — a million sessions) is the client plane that MUST partition.
_WORKLOAD_LANE_FIELDS = frozenset({
    "acc", "racc", "backlog", "cum_ring", "adm_total",
    "in_flight", "idle", "ready_ring",
})
_LIFECYCLE_LANE_FIELDS = frozenset({
    "sess_total", "sess_last", "sess_res", "sess_occ",
    "gc_watermark", "old_live",
})
# Nested State fields that get PER-LEAF shardings (everything else in
# them — traced sweep scalars, counters, the arrival trace, the
# acceptor-axis membership masks — replicates).
_NESTED_LANE_FIELDS = {
    "workload": _WORKLOAD_LANE_FIELDS,
    "lifecycle": _LIFECYCLE_LANE_FIELDS,
}


def _nested_field_sharding(
    spec, field: str, value, mesh: Mesh, lanes: int, fleet: bool
):
    """The sharding of one State field — a single NamedSharding, except
    the nested workload/lifecycle pytrees, which get per-leaf shardings
    so their lane-axis client state (per-lane bookkeeping, the [L, S]
    session table) rides the group axis instead of replicating."""
    lane_fields = _NESTED_LANE_FIELDS.get(field)
    if lane_fields is None or not dataclasses.is_dataclass(value):
        return NamedSharding(mesh, spec.spec_for(field, fleet=fleet))
    pos = 1 if fleet else 0  # lane axis, past any leading instance axis
    lead = [FLEET_AXIS] if fleet else []

    def leaf_spec(name: str, leaf) -> NamedSharding:
        lane_sharded = (
            name in lane_fields
            and leaf.ndim >= pos + 1
            and leaf.shape[pos] == lanes
            and lanes % group_size(mesh) == 0
        )
        p = P(*(lead + [GROUP_AXIS])) if lane_sharded else P(*lead)
        return NamedSharding(mesh, p)

    return type(value)(**{
        f.name: leaf_spec(f.name, getattr(value, f.name))
        for f in dataclasses.fields(value)
    })


def _fleet_field_sharding(spec, field: str, value, mesh: Mesh, lanes: int):
    return _nested_field_sharding(spec, field, value, mesh, lanes, True)


def shard_fleet_state(backend: str, states, mesh: Mesh):
    """Place a fleet-state pytree on the product mesh: the leading
    instance axis shards over ``FLEET_AXIS``, the group axis over
    ``GROUP_AXIS`` (both must divide their mesh extents)."""
    spec = SHARDINGS[backend]
    n = jax.tree_util.tree_leaves(states)[0].shape[0]
    n_fleet = fleet_size(mesh)
    if n % n_fleet != 0:
        raise ValueError(
            f"{n} fleet instances must divide over the fleet axis "
            f"({n_fleet} rows); pick a multiple."
        )
    # axis_len reads the group extent off a single instance's shapes:
    # peel the leading instance axis with a shape-only view.
    one = jax.tree_util.tree_map(lambda a: a[0], states)
    axis_len = spec.axis_len(one)
    n_group = group_size(mesh)
    if axis_len % n_group != 0:
        raise ValueError(
            f"{spec.axis_desc} ({axis_len}) must be divisible by the "
            f"group-axis extent ({n_group}); pick a multiple."
        )
    out = {}
    for f in dataclasses.fields(states):
        value = getattr(states, f.name)
        out[f.name] = jax.device_put(
            value,
            _fleet_field_sharding(spec, f.name, value, mesh, axis_len),
        )
    return type(states)(**out)


def _constrain_fleet_out(backend: str, mesh: Mesh, states, t):
    """Pin the fleet runner's OUTPUT shardings to the canonical fleet
    layout (``with_sharding_constraint`` per field, the workload
    subtree per leaf). Without this, XLA assigns zero-sized and
    feature-off leaves a fully-replicated output sharding, so feeding
    segment 1's result into segment 2 presents DIFFERENT input
    shardings and recompiles — the constraint keeps every segment on
    ONE executable (the ``trace-fleet-onecompile`` contract)."""
    spec = SHARDINGS[backend]
    shapes = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape[1:], a.dtype), states
    )
    lanes = spec.axis_len(shapes)
    out = {}
    for f in dataclasses.fields(states):
        v = getattr(states, f.name)
        sharding = _fleet_field_sharding(spec, f.name, v, mesh, lanes)
        if dataclasses.is_dataclass(sharding):
            v = type(v)(**{
                g.name: jax.lax.with_sharding_constraint(
                    getattr(v, g.name), getattr(sharding, g.name)
                )
                for g in dataclasses.fields(v)
            })
        else:
            v = jax.tree_util.tree_map(
                lambda x: jax.lax.with_sharding_constraint(x, sharding),
                v,
            )
        out[f.name] = v
    t = jax.lax.with_sharding_constraint(
        t, NamedSharding(mesh, P(FLEET_AXIS))
    )
    return type(states)(**out), t


@functools.lru_cache(maxsize=None)
def _fleet_runner(backend: str, mesh: Mesh, wrap: Optional[Mesh]):
    """The jitted fleet runner for one (backend, mesh): ``vmap`` over
    the leading instance axis of ``run_ticks``'s own body, jitted with
    the states DONATED. ``spmd_axis_name=FLEET_AXIS`` maps the vmapped
    instance axis onto the fleet mesh axis, so every collective and
    every ``shard_map``-lowered kernel plane (the ``wrap`` mesh, pushed
    while tracing exactly as :func:`_runner` does) partitions inside
    one fleet row — instances never talk across the fleet axis, and a
    whole [seeds x workload x fault] brick is ONE executable for this
    mesh. Keyed per (backend, mesh): a cached runner (and its jit
    cache) never leaks across fleet shapes — the isolation the
    ``trace-fleet-onecompile`` rule and ``tests/test_fleet.py`` spy
    pin."""
    from frankenpaxos_tpu.ops import registry

    mod = SHARDINGS[backend].mod()

    @functools.partial(jax.jit, static_argnums=(0, 3), donate_argnums=(1,))
    def run(cfg, states, t0s, num_ticks: int, keys):
        def one(state, t0, key):
            with registry.shard_lowering(wrap, GROUP_AXIS):
                return mod.run_ticks.__wrapped__(
                    cfg, state, t0, num_ticks, key
                )

        out, t = jax.vmap(one, spmd_axis_name=FLEET_AXIS)(
            states, t0s, keys
        )
        if mesh is not None:
            out, t = _constrain_fleet_out(backend, mesh, out, t)
        return out, t

    return run


def _fleet_wrap_mesh(backend: str, cfg, mesh: Optional[Mesh]):
    """The mesh engaged kernel planes must shard_map-lower under in a
    fleet run: the product mesh whenever any plane is engaged on a >1
    device mesh (even a 1-wide group axis — the fleet axis still needs
    ``spmd_axis_name`` routing through shard_map's batching rule), else
    None (pure GSPMD propagation / single device)."""
    if mesh is None or mesh.devices.size <= 1:
        return None
    return mesh if _engaged_planes(backend, cfg) else None


def _fleet_t0s(states, t0, mesh: Optional[Mesh]) -> jnp.ndarray:
    """Per-instance tick counters: a scalar ``t0`` broadcasts over the
    fleet (a fresh brick), a ``[n]`` vector (the ``t`` a previous fleet
    call returned) passes through — segmented fleet runs just rebind
    ``states, t = run_ticks_fleet(...)`` like every other runner. On a
    mesh the vector is placed fleet-sharded either way, so segment 1
    (host-built t0s) and segment 2 (the device vector segment 1
    returned) present the SAME input sharding — one executable serves
    every segment."""
    n = jax.tree_util.tree_leaves(states)[0].shape[0]
    t0 = jnp.asarray(t0, jnp.int32)
    t0s = jnp.broadcast_to(t0, (n,)) if t0.ndim == 0 else t0
    if mesh is not None:
        t0s = jax.device_put(t0s, NamedSharding(mesh, P(FLEET_AXIS)))
    return t0s


def place_fleet_keys(keys, mesh: Optional[Mesh]):
    """Fleet-shard a ``[n, 2]`` key array on the product mesh (no-op
    without a mesh): keys ride the instance axis like every state
    leaf."""
    if mesh is None:
        return keys
    return jax.device_put(keys, NamedSharding(mesh, P(FLEET_AXIS)))


def set_fleet_rates(states, rates, mesh: Optional[Mesh] = None):
    """Per-instance admission control for a fleet brick: install a new
    ``[n]`` vector of traced offered rates (the fleet-sharded twin of
    ``workload.set_rate``) — clamping instance i's admission never
    touches its siblings, and because the rate is STATE the same
    compiled fleet executable keeps running (the jit-cache-flat
    contract the ``trace-fleet-drain-nosync`` rule pins). Under a
    product mesh the vector is placed fleet-sharded so the next
    ``run_ticks_fleet`` call presents the SAME input sharding (a
    replicated host array would silently recompile)."""
    wls = getattr(states, "workload", None)
    assert wls is not None and wls.rate.ndim == 1, (
        "set_fleet_rates needs a fleet state with per-instance traced "
        "rates (a shaped WorkloadPlan)"
    )
    n = wls.rate.shape[0]
    arr = jnp.asarray(rates, jnp.float32)
    assert arr.shape == (n,), (arr.shape, n)
    if mesh is not None:
        arr = jax.device_put(arr, NamedSharding(mesh, P(FLEET_AXIS)))
    return dataclasses.replace(
        states, workload=dataclasses.replace(wls, rate=arr)
    )


def run_ticks_fleet(
    backend: str, cfg, mesh: Optional[Mesh], states, t0, num_ticks: int,
    keys,
):
    """Run ``num_ticks`` of EVERY fleet instance (leading axis of
    ``states`` / ``keys``) in one compiled call. ``t0`` is a scalar
    (fresh brick) or the per-instance ``[n]`` vector a previous call
    returned. Per-tick keys fold the SCAN index (``run_ticks``
    semantics), so segmented runs must pass fresh per-segment keys
    (``vmap(fold_in)`` the previous ones) or the next segment replays
    the same random stream. ``mesh=None`` runs the brick on the
    default device (pure vmap — the small-host path); otherwise the
    states should be placed via :func:`shard_fleet_state` first.
    States are DONATED — rebind the result."""
    if mesh is not None:
        validate_policy(backend, cfg, mesh)
    wrap = _fleet_wrap_mesh(backend, cfg, mesh)
    return _fleet_runner(backend, mesh, wrap)(
        cfg, states, _fleet_t0s(states, t0, mesh), num_ticks,
        place_fleet_keys(keys, mesh),
    )


def lower_fleet(
    backend: str, cfg, mesh: Optional[Mesh], states, t0, num_ticks: int,
    keys,
):
    """Lower (don't run) the fleet runner — the
    ``trace-fleet-onecompile`` analysis rule compiles this to census
    the collectives (nothing may cross the fleet axis) and the
    donation aliases under the product mesh."""
    if mesh is not None:
        validate_policy(backend, cfg, mesh)
    wrap = _fleet_wrap_mesh(backend, cfg, mesh)
    return _fleet_runner(backend, mesh, wrap).lower(
        cfg, states, _fleet_t0s(states, t0, mesh), num_ticks,
        place_fleet_keys(keys, mesh),
    )


def fleet_block_plan(backend: str, cfg, mesh: Mesh) -> dict:
    """plane -> {mode, block resolution} for a fleet run on ``mesh`` —
    the bench JSON's record of WHICH autotuned block each engaged plane
    resolved at the true per-device shape (``ops.registry`` stashes the
    resolution in ``RESOLVED_BLOCKS`` while the shard_map wrapper
    traces). A stashed resolution is reported only when its recorded
    mesh axes match ``mesh`` — a stale entry from some other mesh's
    lowering never masquerades as this one's. Planes that resolved to
    the reference, never dispatched (e.g. subsumed by the megakernel),
    or last resolved under a different mesh report ``block=None``."""
    from frankenpaxos_tpu.ops import registry

    spec = SHARDINGS[backend]
    mesh_axes = {str(a): int(s) for a, s in dict(mesh.shape).items()}
    out = {}
    for name, plane in registry.PLANES.items():
        if plane.backend != spec.planes_backend:
            continue
        mode = registry.resolve_mode(name, cfg)
        row = {"mode": mode, "block": None, "per_device_key": None}
        resolved = registry.RESOLVED_BLOCKS.get(name)
        if (
            mode != "reference"
            and resolved is not None
            and resolved.get("mesh_axes") == mesh_axes
        ):
            row.update(resolved)
        out[name] = row
    return out


# ---------------------------------------------------------------------------
# Registry entries
# ---------------------------------------------------------------------------

# Flagship batched MultiPaxos: every [G, ...] array shards along G;
# scalars, stats, the shared read wave, and the telemetry ring
# replicate. Acceptor-major arrays ([A, G, W] / [A, G] / [M, G] /
# [A, G, RW]) carry the group axis SECOND.
register_sharding(
    ShardingSpec(
        backend="multipaxos",
        module="frankenpaxos_tpu.tpu.multipaxos_batched",
        state_class="BatchedMultiPaxosState",
        replicated=frozenset({
            "committed", "retired", "lat_sum", "lat_hist",
            "max_chosen_global", "client_watermark", "wave_issue",
            "reads_done", "reads_shed", "read_lat_sum", "read_lat_hist",
            "read_lin_violations", "elections", "reconfigs", "configs_gcd",
            "sm_applied", "dups_filtered", "dups_seen",
            # The telemetry ring holds cluster-wide per-tick reductions
            # ([K, NUM_COLS] + histograms) — replicated; device_put
            # broadcasts the spec over the nested pytree's leaves.
            # "workload"/"lifecycle" here is the DEFAULT for their
            # non-lane leaves only (traced sweep scalars, counters, the
            # arrival trace, the [A, G] membership masks): the nested
            # client planes — per-lane shaping bookkeeping and the
            # [G, S] session table — shard over the group axis per
            # _NESTED_LANE_FIELDS (production session cardinality
            # cannot replicate per device).
            # The elastic membership counts ([R] role-count scalars,
            # tpu/elastic.py) are control-plane state every device
            # reads — replicated, like the lifecycle masks.
            "telemetry", "workload", "lifecycle", "elastic",
        }),
        axis_pos={
            name: 1
            for name in (
                "acc_round", "p2a_arrival", "p2b_arrival", "vote_round",
                "vote_value", "acc_max_slot", "req_arrival", "resp_slot",
                "resp_arrival", "leader_alive",  # [C, G] candidates
                # [M, G] matchmakers / [A, G] old-config phase-1.
                "mm_epoch", "matcha_arrival", "matchb_arrival",
                "rc_p1a_arrival", "rc_p1b_arrival",
            )
        },
        axis_len=lambda st: st.leader_round.shape[-1],
        axis_desc="num_groups",
        planes_backend="multipaxos",
    )
)

# Batched EPaxos: every [C, ...] array shards along the column axis;
# the frontier history ([H, C]) and per-replica GC watermarks ([R, C])
# shard on their SECOND axis; scalars and histograms replicate. The
# closure's only cross-device traffic is the [H]-sized tick scores and
# scalar stats.
register_sharding(
    ShardingSpec(
        backend="epaxos",
        module="frankenpaxos_tpu.tpu.epaxos_batched",
        state_class="BatchedEPaxosState",
        replicated=frozenset({
            "committed_total", "fast_path_total", "executed_total",
            "retired_total", "coexecuted", "lat_sum", "lat_hist",
            "snapshots_served", "rep_crashes", "rep_down", "telemetry",
            "workload",
        }),
        axis_pos={name: 1 for name in ("fpre", "fpost", "rep_exec")},
        axis_len=lambda st: st.head.shape[0],
        axis_desc="num_columns",
        planes_backend=None,
    )
)

# Batched BPaxos: LANE-sharded. Every [L, ...] lane ring shards along
# its leader axis, the per-replica views ([R, L] watermarks, [R, L, W]
# commit visibility) shard on their SECOND axis — the replica axis is
# a small fixed fan-out (every device holds all R views of ITS lanes),
# while the leader axis is the one production scales — and the packed
# adjacency ([V, VW], V = L*W with vertex id = lane * W + slot, i.e.
# lane-major) shards on its row axis, which divides exactly when L
# does. Scalar stats, the latency histogram, and the telemetry ring
# replicate; the workload client planes ride the lane axis through
# _NESTED_LANE_FIELDS as everywhere else. Cross-device traffic is the
# dependency closure's column reads (a vertex may depend on another
# lane's rows), the [L]-sized gc_head minimum, and the scalar stat
# reductions. planes_backend stays None like epaxos: kernel shard_map
# lowering needs the lifecycle-threaded fleet contract the
# client-plane backends carry; CPU/lint runs resolve the plane to its
# reference twin either way.
register_sharding(
    ShardingSpec(
        backend="bpaxos",
        module="frankenpaxos_tpu.tpu.bpaxos_batched",
        state_class="BatchedBPaxosState",
        replicated=frozenset({
            "committed_total", "executed_total", "retired_total",
            "coexecuted", "lat_sum", "lat_hist", "workload",
            "telemetry",
        }),
        axis_pos={"head_r": 1, "rep_commit_tick": 1},
        axis_len=lambda st: st.next_cmd.shape[0],
        axis_desc="num_leaders",
        planes_backend=None,
    )
)

# Compartmentalized MultiPaxos: role-major planes with (G, W) minor.
# Grid planes ([R, C, G, W]) carry the group axis THIRD, replica planes
# ([NR, G, W] / [NR, G] / [NR, G, RW]) SECOND, everything else
# ([G, ...]) first; scalar stats, histograms, and the telemetry ring
# replicate. The whole write path (batchers -> leader -> proxies ->
# grid -> replicas -> unbatchers) is group-local; only the commit/
# watermark/histogram reductions cross devices.
register_sharding(
    ShardingSpec(
        backend="compartmentalized",
        module="frankenpaxos_tpu.tpu.compartmentalized_batched",
        state_class="BatchedCompartmentalizedState",
        replicated=frozenset({
            "bat_shed", "committed", "batches_committed", "retired",
            "writes_done", "lat_sum", "lat_hist", "reads_done",
            "reads_shed", "read_lat_sum", "read_lat_hist", "telemetry",
            "workload", "lifecycle",
            # Elastic role-count state replicates (see multipaxos).
            "elastic",
        }),
        axis_pos={
            **{name: 2 for name in ("p2a_arrival", "p2b_arrival")},
            **{
                name: 1
                for name in (
                    "rep_arrival", "rep_exec", "rd_issue", "rd_bound",
                    "rd_count", "rd_probe", "rd_row",
                )
            },
        },
        axis_len=lambda st: st.head.shape[0],
        axis_desc="num_groups",
        planes_backend="compartmentalized",
    )
)
