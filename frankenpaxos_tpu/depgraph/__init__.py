"""Dependency graphs for commit-then-execute protocols (EPaxos/BPaxos).

Capability parity with the reference ``depgraph`` package
(``depgraph/DependencyGraph.scala:8-193``): protocols commit vertices
(commands) with sequence numbers and dependency sets; execution returns
strongly connected components of *eligible* vertices in reverse
topological order, deterministically ordered within a component by
(sequence number, key). A vertex is eligible iff every vertex it
transitively depends on is committed. ``execute`` never returns a vertex
twice; ``update_executed`` teaches the graph about externally executed
vertices (e.g. from a snapshot).

Implementations:

  * :class:`TarjanDependencyGraph` — the reference's fast implementation
    (``TarjanDependencyGraph.scala:149-``): Tarjan SCC with eligibility
    short-circuiting and blocker reporting.
  * :class:`ZigzagTarjanDependencyGraph` — the GC'd, leader-striped
    variant (``ZigzagTarjanDependencyGraph.scala:135-``): vertices live
    in per-leader BufferMaps, execution zigzags across the leaders'
    watermark frontiers, and executed prefixes are compacted and
    garbage collected — bounded memory for long-running deployments.
  * :class:`NaiveDependencyGraph` — an oracle built from DIFFERENT
    algorithms (Kosaraju SCC + Kahn toposort + BFS eligibility), the
    analog of the reference's library-backed Jgrapht/ScalaGraph
    implementations: slow but obviously correct, used to cross-check
    the fast ones.
"""

from __future__ import annotations

from typing import Dict, Generic, List, Optional, Sequence, Set, Tuple, TypeVar

Key = TypeVar("Key")
Seq = TypeVar("Seq")


class DependencyGraph(Generic[Key, Seq]):
    def commit(self, key: Key, sequence_number: Seq, dependencies: Set[Key]) -> None:
        raise NotImplementedError

    def execute(
        self, num_blockers: Optional[int] = None
    ) -> Tuple[List[Key], Set[Key]]:
        components, blockers = self.execute_by_component(num_blockers)
        return [k for comp in components for k in comp], blockers

    def execute_by_component(
        self, num_blockers: Optional[int] = None
    ) -> Tuple[List[List[Key]], Set[Key]]:
        raise NotImplementedError

    def update_executed(self, keys: Set[Key]) -> None:
        raise NotImplementedError

    @property
    def num_vertices(self) -> int:
        raise NotImplementedError


class _Vertex:
    __slots__ = ("key", "sequence_number", "dependencies")

    def __init__(self, key, sequence_number, dependencies):
        self.key = key
        self.sequence_number = sequence_number
        self.dependencies = dependencies


class _Meta:
    __slots__ = ("number", "low_link", "stack_index", "eligible")

    def __init__(self, number, stack_index):
        self.number = number
        self.low_link = number
        self.stack_index = stack_index
        self.eligible = True


class TarjanDependencyGraph(DependencyGraph[Key, Seq]):
    """Tarjan SCC with eligibility pruning (TarjanDependencyGraph.scala).
    An iterative DFS (explicit stack) so deep dependency chains don't hit
    Python's recursion limit."""

    def __init__(self) -> None:
        self.vertices: Dict[Key, _Vertex] = {}
        self.executed: Set[Key] = set()

    def commit(self, key, sequence_number, dependencies) -> None:
        if key in self.vertices or key in self.executed:
            return
        self.vertices[key] = _Vertex(key, sequence_number, set(dependencies))

    def update_executed(self, keys) -> None:
        self.executed |= set(keys)
        for key in list(self.vertices):
            if key in self.executed:
                del self.vertices[key]

    @property
    def num_vertices(self) -> int:
        return len(self.vertices)

    def execute_by_component(self, num_blockers=None):
        metadatas: Dict[Key, _Meta] = {}
        stack: List[Key] = []
        components: List[List[Key]] = []
        blockers: Set[Key] = set()

        for root in list(self.vertices):
            if root in metadatas:
                continue
            self._strong_connect(root, metadatas, stack, components, blockers)
            if not metadatas[root].eligible:
                # Abandon the root's stack WITHOUT resetting stack_index
                # (mirrors TarjanDependencyGraph.scala clearing only the
                # stack): vertices closed under this root may still be
                # eligible=True but must look "on stack" to later roots so
                # their low-links keep those roots' components open —
                # resetting stack_index here would let a later root execute
                # a vertex that transitively depends on an uncommitted one.
                stack.clear()
            if num_blockers is not None and len(blockers) >= num_blockers:
                break

        for component in components:
            for key in component:
                del self.vertices[key]
                self.executed.add(key)
        return components, blockers

    def _strong_connect(self, root, metadatas, stack, components, blockers):
        # Iterative DFS. Each frame is [vertex, iterator over remaining
        # dependency children].
        def open_frame(v):
            metadatas[v] = _Meta(number=len(metadatas), stack_index=len(stack))
            stack.append(v)
            deps = self.vertices[v].dependencies
            return [v, iter([d for d in deps if d not in self.executed])]

        frames = [open_frame(root)]
        while frames:
            v, children = frames[-1]
            mv = metadatas[v]
            advanced = False
            for w in children:
                if w not in self.vertices:
                    # Uncommitted dependency: v (and its ancestors) are not
                    # eligible; w is a blocker.
                    mv.eligible = False
                    blockers.add(w)
                    break
                mw = metadatas.get(w)
                if mw is None:
                    frames.append(open_frame(w))
                    advanced = True
                    break
                if not mw.eligible:
                    mv.eligible = False
                    break
                if mw.stack_index != -1:
                    mv.low_link = min(mv.low_link, mw.number)
                # Off-stack eligible child: nothing to do.
            else:
                # All children processed: close the frame.
                self._close_frame(v, metadatas, stack, components)
                frames.pop()
                if frames:
                    parent_meta = metadatas[frames[-1][0]]
                    parent_meta.low_link = min(parent_meta.low_link, mv.low_link)
                    parent_meta.eligible = parent_meta.eligible and mv.eligible
                continue
            if advanced:
                continue
            # A child made v ineligible: propagate up without closing SCCs.
            frames.pop()
            if frames:
                metadatas[frames[-1][0]].eligible = False
            # Unwind remaining frames, marking them ineligible.
            while frames:
                u, _ = frames.pop()
                metadatas[u].eligible = False
                if frames:
                    metadatas[frames[-1][0]].eligible = False

    def _close_frame(self, v, metadatas, stack, components):
        mv = metadatas[v]
        if mv.low_link != mv.number:
            return
        if not mv.eligible:
            return
        if mv.stack_index == len(stack) - 1:
            component = [stack.pop()]
            metadatas[component[0]].stack_index = -1
        else:
            component = stack[mv.stack_index :]
            del stack[mv.stack_index :]
            for w in component:
                metadatas[w].stack_index = -1
            component.sort(
                key=lambda k: (self.vertices[k].sequence_number, k)
            )
        components.append(component)


class NaiveDependencyGraph(DependencyGraph[Key, Seq]):
    """Obviously-correct oracle: BFS eligibility closure, Kosaraju SCC,
    Kahn topological order of the condensation — deliberately different
    algorithms from the Tarjan implementations so tests cross-check them
    (the role of JgraphtDependencyGraph/ScalaGraphDependencyGraph)."""

    def __init__(self) -> None:
        self.vertices: Dict[Key, _Vertex] = {}
        self.executed: Set[Key] = set()

    def commit(self, key, sequence_number, dependencies) -> None:
        if key in self.vertices or key in self.executed:
            return
        self.vertices[key] = _Vertex(key, sequence_number, set(dependencies))

    def update_executed(self, keys) -> None:
        self.executed |= set(keys)
        for key in list(self.vertices):
            if key in self.executed:
                del self.vertices[key]

    @property
    def num_vertices(self) -> int:
        return len(self.vertices)

    def execute_by_component(self, num_blockers=None):
        # 1. Eligibility: a vertex is INELIGIBLE iff it can reach an
        #    uncommitted dependency. Find them by reverse BFS from the
        #    uncommitted frontier.
        blockers: Set[Key] = set()
        reverse: Dict[Key, Set[Key]] = {}
        for key, vertex in self.vertices.items():
            for dep in vertex.dependencies:
                if dep in self.executed:
                    continue
                if dep not in self.vertices:
                    blockers.add(dep)
                reverse.setdefault(dep, set()).add(key)
        ineligible: Set[Key] = set()
        frontier = list(blockers)
        while frontier:
            missing = frontier.pop()
            for parent in reverse.get(missing, ()):
                if parent not in ineligible:
                    ineligible.add(parent)
                    frontier.append(parent)
        eligible = {
            k for k in self.vertices if k not in ineligible
        }

        # 2. Kosaraju SCC on the eligible subgraph.
        order: List[Key] = []
        seen: Set[Key] = set()
        for start in sorted(eligible):
            if start in seen:
                continue
            stack = [(start, iter(self._deps(start, eligible)))]
            seen.add(start)
            while stack:
                node, it = stack[-1]
                for child in it:
                    if child not in seen:
                        seen.add(child)
                        stack.append(
                            (child, iter(self._deps(child, eligible)))
                        )
                        break
                else:
                    order.append(node)
                    stack.pop()
        reverse_eligible: Dict[Key, List[Key]] = {}
        for key in eligible:
            for dep in self._deps(key, eligible):
                reverse_eligible.setdefault(dep, []).append(key)
        component_of: Dict[Key, int] = {}
        components: List[List[Key]] = []
        for start in reversed(order):
            if start in component_of:
                continue
            component = []
            stack2 = [start]
            component_of[start] = len(components)
            while stack2:
                node = stack2.pop()
                component.append(node)
                for parent in reverse_eligible.get(node, ()):
                    if parent not in component_of:
                        component_of[parent] = len(components)
                        stack2.append(parent)
            components.append(component)

        # 3. Kahn toposort of the condensation: dependencies first.
        edges: Dict[int, Set[int]] = {i: set() for i in range(len(components))}
        indegree = [0] * len(components)
        for key in eligible:
            for dep in self._deps(key, eligible):
                a, b = component_of[dep], component_of[key]
                if a != b and b not in edges[a]:
                    edges[a].add(b)
                    indegree[b] += 1
        ready = sorted(i for i in range(len(components)) if indegree[i] == 0)
        ordered: List[List[Key]] = []
        while ready:
            i = ready.pop(0)
            component = components[i]
            component.sort(
                key=lambda k: (self.vertices[k].sequence_number, k)
            )
            ordered.append(component)
            for j in sorted(edges[i]):
                indegree[j] -= 1
                if indegree[j] == 0:
                    ready.append(j)
        for component in ordered:
            for key in component:
                del self.vertices[key]
                self.executed.add(key)
        return ordered, blockers

    def _deps(self, key, eligible):
        return sorted(
            d for d in self.vertices[key].dependencies
            if d in eligible and d not in self.executed
        )


class ZigzagTarjanDependencyGraph(DependencyGraph[tuple, Seq]):
    """GC'd, leader-striped Tarjan (ZigzagTarjanDependencyGraph.scala):
    keys are (leader_index, id) with ids contiguous per leader. Vertices
    live in per-leader BufferMaps; execution walks the per-leader
    watermark frontiers round-robin ("zigzag"), and executed prefixes
    compact into per-leader IntPrefixSets whose watermarks drive
    BufferMap garbage collection — memory stays bounded by the frontier,
    not by history."""

    def __init__(self, num_leaders: int, vertices_grow_size: int = 1000,
                 garbage_collect_every_n_commands: int = 1000):
        from frankenpaxos_tpu.compact import IntPrefixSet
        from frankenpaxos_tpu.util import BufferMap

        self.num_leaders = num_leaders
        self.gc_every = garbage_collect_every_n_commands
        self.vertices = [
            BufferMap(vertices_grow_size) for _ in range(num_leaders)
        ]
        self.executed_watermark = [0] * num_leaders
        self.executed = [IntPrefixSet() for _ in range(num_leaders)]
        self._count = 0
        self._since_gc = 0

    def _get(self, key):
        return self.vertices[key[0]].get(key[1])

    def _executed_contains(self, key) -> bool:
        return self.executed[key[0]].contains(key[1])

    def _executed_add(self, key) -> None:
        self.executed[key[0]].add(key[1])

    def commit(self, key, sequence_number, dependencies) -> None:
        if self._get(key) is not None or self._executed_contains(key):
            return
        self.vertices[key[0]].put(
            key[1], _Vertex(key, sequence_number, set(dependencies))
        )
        self._count += 1

    def update_executed(self, keys) -> None:
        for key in keys:
            if not self._executed_contains(key):
                self._executed_add(key)
                if self._get(key) is not None:
                    # Evict the now-dead vertex (BufferMap treats a None
                    # value as absent) and let GC reclaim the prefix.
                    self.vertices[key[0]].put(key[1], None)
                    self._count -= 1
                    self._since_gc += 1

    @property
    def num_vertices(self) -> int:
        return self._count

    def execute_by_component(self, num_blockers=None):
        metadatas: Dict[tuple, _Meta] = {}
        stack: List[tuple] = []
        components: List[List[tuple]] = []
        blockers: Set[tuple] = set()

        columns = list(range(self.num_leaders))
        index = 0
        while columns:
            leader = columns[index]
            key = (leader, self.executed_watermark[leader])
            if self._execute_key(
                key, metadatas, stack, components, blockers
            ):
                self.executed_watermark[leader] = max(
                    self.executed_watermark[leader] + 1,
                    self.executed[leader].watermark,
                )
                index += 1
                if index >= len(columns):
                    index = 0
            else:
                columns.pop(index)
                if index >= len(columns):
                    index = 0
            if num_blockers is not None and len(blockers) >= num_blockers:
                break

        executed_count = sum(len(c) for c in components)
        self._count -= executed_count
        self._since_gc += executed_count
        if self._since_gc >= self.gc_every:
            for i in range(self.num_leaders):
                self.vertices[i].garbage_collect(self.executed[i].watermark)
            self._since_gc = 0
        return components, blockers

    def _execute_key(self, key, metadatas, stack, components,
                     blockers) -> bool:
        vertex = self._get(key)
        if vertex is None:
            if not self._executed_contains(key):
                blockers.add(key)
                return False
            return True  # executed in an earlier invocation
        if self._executed_contains(key):
            return True
        meta = metadatas.get(key)
        if meta is not None:
            return meta.eligible
        meta = self._strong_connect(
            key, vertex, metadatas, stack, components, blockers
        )
        if not meta.eligible:
            # Abandon the stack: everything on it is ineligible this
            # round (ZigzagTarjanDependencyGraph.scala:385-393).
            for v in stack:
                metadatas[v].eligible = False
                metadatas[v].stack_index = -1
            stack.clear()
            return False
        return True

    def _strong_connect(self, root_key, root_vertex, metadatas, stack,
                        components, blockers):
        def open_frame(key, vertex):
            meta = _Meta(number=len(metadatas), stack_index=len(stack))
            metadatas[key] = meta
            stack.append(key)
            children = iter(sorted(
                d for d in vertex.dependencies
                if not self._executed_contains(d)
            ))
            return [key, children]

        frames = [open_frame(root_key, root_vertex)]
        while frames:
            key, children = frames[-1]
            meta = metadatas[key]
            advanced = False
            failed = False
            for w in children:
                wertex = self._get(w)
                if wertex is None:
                    meta.eligible = False
                    meta.stack_index = -1
                    blockers.add(w)
                    failed = True
                    break
                wm = metadatas.get(w)
                if wm is None:
                    frames.append(open_frame(w, wertex))
                    advanced = True
                    break
                if not wm.eligible:
                    meta.eligible = False
                    meta.stack_index = -1
                    failed = True
                    break
                if wm.stack_index != -1:
                    meta.low_link = min(meta.low_link, wm.number)
            else:
                frames.pop()
                if meta.low_link == meta.number and meta.stack_index != -1:
                    component = stack[meta.stack_index:]
                    del stack[meta.stack_index:]
                    for w in component:
                        metadatas[w].stack_index = -1
                        self._executed_add(w)
                    if len(component) > 1:
                        component.sort(key=lambda k: (
                            self._get(k).sequence_number, k
                        ))
                    components.append(component)
                if frames:
                    parent = metadatas[frames[-1][0]]
                    parent.low_link = min(parent.low_link, meta.low_link)
                continue
            if advanced:
                continue
            if failed:
                frames.pop()
                while frames:
                    k2, _ = frames.pop()
                    m2 = metadatas[k2]
                    m2.eligible = False
                    m2.stack_index = -1
        return metadatas[root_key]


class _IncMeta:
    __slots__ = ("number", "low_link", "on_stack", "current_dependency")

    def __init__(self, number):
        self.number = number
        self.low_link = number
        self.on_stack = True
        self.current_dependency = 0


class IncrementalTarjanDependencyGraph(DependencyGraph[Key, Seq]):
    """Incremental, pausable Tarjan
    (IncrementalTarjanDependencyGraph.scala:29): unlike
    TarjanDependencyGraph — which re-runs the whole algorithm every
    execute() — the DFS state (call stack, SCC stack, vertex metadata)
    persists across calls. Hitting an uncommitted dependency PAUSES the
    pass, reporting that single vertex as the blocker, and a later
    execute() resumes exactly where it stopped. No redundant
    re-traversal, at the cost of sometimes delaying eligible commands
    (the reference documents it as neither strictly better nor worse
    than the from-scratch variant)."""

    def __init__(self) -> None:
        self.vertices: Dict[Key, _Vertex] = {}
        self.executed: Set[Key] = set()
        self.callstack: List[Key] = []
        self.stack: List[Key] = []
        self.metadatas: Dict[Key, _IncMeta] = {}
        self.executables: List[List[Key]] = []
        self.blocker: Optional[Key] = None
        # Monotonic DFS numbering: numbers must stay unique across passes
        # because executed vertices' metadata is pruned eagerly (below)
        # while a suspended pass may span many calls.
        self._next_number = 0

    def commit(self, key, sequence_number, dependencies) -> None:
        if key in self.vertices or key in self.executed:
            return
        # Executed dependencies are dropped; committed dependencies come
        # FIRST so a pass runs as far as possible before pausing on an
        # uncommitted one (commit, :96-109).
        live = [d for d in dependencies if d not in self.executed]
        committed = [d for d in live if d in self.vertices]
        uncommitted = [d for d in live if d not in self.vertices]
        self.vertices[key] = _Vertex(
            key, sequence_number, committed + uncommitted
        )

    def update_executed(self, keys) -> None:
        # The reference leaves this wholly unimplemented (:110-116: pruning
        # mid-pass would corrupt the suspended DFS). Between passes it is
        # safe, so support that much.
        if self.callstack:
            raise NotImplementedError(
                "cannot prune while a Tarjan pass is suspended"
            )
        self.executed |= set(keys)
        for key in list(self.vertices):
            if key in self.executed:
                del self.vertices[key]

    @property
    def num_vertices(self) -> int:
        return len(self.vertices)

    def _collect_executables(self) -> List[List[Key]]:
        for component in self.executables:
            for key in component:
                del self.vertices[key]
                self.executed.add(key)
                # Dead metadata: the dep-loop checks `w in executed`
                # before any metadata lookup, and executed vertices are
                # off both stacks — prune eagerly, or in a steady state
                # of always-paused passes (some command always in
                # flight) metadatas would grow with TOTAL commands.
                self.metadatas.pop(key, None)
        out = self.executables
        self.executables = []
        return out

    def _take_blocker(self) -> Set[Key]:
        b = {self.blocker} if self.blocker is not None else set()
        self.blocker = None
        return b

    def execute_by_component(self, num_blockers=None):
        # Resume a suspended pass first (:125-135).
        if self.callstack and self._strong_connect() == "paused":
            return self._collect_executables(), self._take_blocker()
        for key in list(self.vertices):
            if key not in self.metadatas:
                self.callstack.append(key)
                if self._strong_connect() == "paused":
                    return self._collect_executables(), self._take_blocker()
        # A full pass finished: safe to start fresh next time (:149-154).
        assert not self.callstack
        self.metadatas.clear()
        assert not self.stack
        return self._collect_executables(), self._take_blocker()

    def _strong_connect(self) -> str:
        """The manually-stacked, resumable DFS (strongConnect, :172-264).
        Returns "paused" on an uncommitted dependency, else "success"."""
        while self.callstack:
            v = self.callstack[-1]
            mv = self.metadatas.get(v)
            if mv is None:
                mv = _IncMeta(number=self._next_number)
                self._next_number += 1
                self.metadatas[v] = mv
                self.stack.append(v)
            deps = self.vertices[v].dependencies
            recursed = False
            while mv.current_dependency < len(deps):
                w = deps[mv.current_dependency]
                if w in self.executed:
                    pass  # already executed: no edge to follow
                elif w not in self.vertices:
                    # Uncommitted: suspend with everything in place; the
                    # resume re-examines this same dependency (:195-199).
                    self.blocker = w
                    return "paused"
                elif w not in self.metadatas:
                    self.callstack.append(w)  # "recurse" (:200-209)
                    recursed = True
                    break
                else:
                    mw = self.metadatas[w]
                    if mw.on_stack:
                        mv.low_link = min(mv.low_link, mw.number)
                mv.current_dependency += 1
            if recursed:
                continue
            # All dependencies processed: v may root a component (:229-251).
            if mv.low_link == mv.number:
                component = []
                while self.stack[-1] != v:
                    w = self.stack.pop()
                    self.metadatas[w].on_stack = False
                    component.append(w)
                self.stack.pop()
                mv.on_stack = False
                component.append(v)
                component.sort(
                    key=lambda k: (self.vertices[k].sequence_number, k)
                )
                self.executables.append(component)
            # Return to the parent frame, merging low-links (:253-261).
            self.callstack.pop()
            if self.callstack:
                parent = self.metadatas[self.callstack[-1]]
                parent.low_link = min(parent.low_link, mv.low_link)
        return "success"


# Registry mirroring DependencyGraph.scala's DependencyGraphType.
REGISTRY = {
    "Tarjan": TarjanDependencyGraph,
    "IncrementalTarjan": IncrementalTarjanDependencyGraph,
    "Naive": NaiveDependencyGraph,
}


def from_name(name: str) -> DependencyGraph:
    try:
        return REGISTRY[name]()
    except KeyError:
        raise ValueError(
            f"{name} is not one of {', '.join(sorted(REGISTRY))}."
        ) from None
