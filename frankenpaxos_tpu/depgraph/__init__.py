"""Dependency graphs for commit-then-execute protocols (EPaxos/BPaxos).

Capability parity with the reference ``depgraph`` package
(``depgraph/DependencyGraph.scala:8-193``): protocols commit vertices
(commands) with sequence numbers and dependency sets; execution returns
strongly connected components of *eligible* vertices in reverse
topological order, deterministically ordered within a component by
(sequence number, key). A vertex is eligible iff every vertex it
transitively depends on is committed. ``execute`` never returns a vertex
twice; ``update_executed`` teaches the graph about externally executed
vertices (e.g. from a snapshot).

Implementations: :class:`TarjanDependencyGraph` — the reference's fast
implementation (``TarjanDependencyGraph.scala:149-``, a Tarjan SCC variant
with eligibility short-circuiting and blocker reporting). The reference's
Jgrapht/ScalaGraph/Incremental/Zigzag variants exist for JVM-library
comparison and GC-striping; here one canonical implementation plus the
same test battery covers the capability.
"""

from __future__ import annotations

from typing import Dict, Generic, List, Optional, Sequence, Set, Tuple, TypeVar

Key = TypeVar("Key")
Seq = TypeVar("Seq")


class DependencyGraph(Generic[Key, Seq]):
    def commit(self, key: Key, sequence_number: Seq, dependencies: Set[Key]) -> None:
        raise NotImplementedError

    def execute(
        self, num_blockers: Optional[int] = None
    ) -> Tuple[List[Key], Set[Key]]:
        components, blockers = self.execute_by_component(num_blockers)
        return [k for comp in components for k in comp], blockers

    def execute_by_component(
        self, num_blockers: Optional[int] = None
    ) -> Tuple[List[List[Key]], Set[Key]]:
        raise NotImplementedError

    def update_executed(self, keys: Set[Key]) -> None:
        raise NotImplementedError

    @property
    def num_vertices(self) -> int:
        raise NotImplementedError


class _Vertex:
    __slots__ = ("key", "sequence_number", "dependencies")

    def __init__(self, key, sequence_number, dependencies):
        self.key = key
        self.sequence_number = sequence_number
        self.dependencies = dependencies


class _Meta:
    __slots__ = ("number", "low_link", "stack_index", "eligible")

    def __init__(self, number, stack_index):
        self.number = number
        self.low_link = number
        self.stack_index = stack_index
        self.eligible = True


class TarjanDependencyGraph(DependencyGraph[Key, Seq]):
    """Tarjan SCC with eligibility pruning (TarjanDependencyGraph.scala).
    An iterative DFS (explicit stack) so deep dependency chains don't hit
    Python's recursion limit."""

    def __init__(self) -> None:
        self.vertices: Dict[Key, _Vertex] = {}
        self.executed: Set[Key] = set()

    def commit(self, key, sequence_number, dependencies) -> None:
        if key in self.vertices or key in self.executed:
            return
        self.vertices[key] = _Vertex(key, sequence_number, set(dependencies))

    def update_executed(self, keys) -> None:
        self.executed |= set(keys)
        for key in list(self.vertices):
            if key in self.executed:
                del self.vertices[key]

    @property
    def num_vertices(self) -> int:
        return len(self.vertices)

    def execute_by_component(self, num_blockers=None):
        metadatas: Dict[Key, _Meta] = {}
        stack: List[Key] = []
        components: List[List[Key]] = []
        blockers: Set[Key] = set()

        for root in list(self.vertices):
            if root in metadatas:
                continue
            self._strong_connect(root, metadatas, stack, components, blockers)
            if not metadatas[root].eligible:
                # Abandon the root's stack WITHOUT resetting stack_index
                # (mirrors TarjanDependencyGraph.scala clearing only the
                # stack): vertices closed under this root may still be
                # eligible=True but must look "on stack" to later roots so
                # their low-links keep those roots' components open —
                # resetting stack_index here would let a later root execute
                # a vertex that transitively depends on an uncommitted one.
                stack.clear()
            if num_blockers is not None and len(blockers) >= num_blockers:
                break

        for component in components:
            for key in component:
                del self.vertices[key]
                self.executed.add(key)
        return components, blockers

    def _strong_connect(self, root, metadatas, stack, components, blockers):
        # Iterative DFS. Each frame is [vertex, iterator over remaining
        # dependency children].
        def open_frame(v):
            metadatas[v] = _Meta(number=len(metadatas), stack_index=len(stack))
            stack.append(v)
            deps = self.vertices[v].dependencies
            return [v, iter([d for d in deps if d not in self.executed])]

        frames = [open_frame(root)]
        while frames:
            v, children = frames[-1]
            mv = metadatas[v]
            advanced = False
            for w in children:
                if w not in self.vertices:
                    # Uncommitted dependency: v (and its ancestors) are not
                    # eligible; w is a blocker.
                    mv.eligible = False
                    blockers.add(w)
                    break
                mw = metadatas.get(w)
                if mw is None:
                    frames.append(open_frame(w))
                    advanced = True
                    break
                if not mw.eligible:
                    mv.eligible = False
                    break
                if mw.stack_index != -1:
                    mv.low_link = min(mv.low_link, mw.number)
                # Off-stack eligible child: nothing to do.
            else:
                # All children processed: close the frame.
                self._close_frame(v, metadatas, stack, components)
                frames.pop()
                if frames:
                    parent_meta = metadatas[frames[-1][0]]
                    parent_meta.low_link = min(parent_meta.low_link, mv.low_link)
                    parent_meta.eligible = parent_meta.eligible and mv.eligible
                continue
            if advanced:
                continue
            # A child made v ineligible: propagate up without closing SCCs.
            frames.pop()
            if frames:
                metadatas[frames[-1][0]].eligible = False
            # Unwind remaining frames, marking them ineligible.
            while frames:
                u, _ = frames.pop()
                metadatas[u].eligible = False
                if frames:
                    metadatas[frames[-1][0]].eligible = False

    def _close_frame(self, v, metadatas, stack, components):
        mv = metadatas[v]
        if mv.low_link != mv.number:
            return
        if not mv.eligible:
            return
        if mv.stack_index == len(stack) - 1:
            component = [stack.pop()]
            metadatas[component[0]].stack_index = -1
        else:
            component = stack[mv.stack_index :]
            del stack[mv.stack_index :]
            for w in component:
                metadatas[w].stack_index = -1
            component.sort(
                key=lambda k: (self.vertices[k].sequence_number, k)
            )
        components.append(component)


# Registry mirroring DependencyGraph.scala's DependencyGraphType.
REGISTRY = {
    "Tarjan": TarjanDependencyGraph,
}


def from_name(name: str) -> DependencyGraph:
    try:
        return REGISTRY[name]()
    except KeyError:
        raise ValueError(
            f"{name} is not one of {', '.join(sorted(REGISTRY))}."
        ) from None
