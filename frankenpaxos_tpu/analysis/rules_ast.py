"""AST-layer rules: the four repo-wide contracts the old lint test
files enforced (donation, telemetry, faults, kernel containment), each
now a registry rule with structured findings — plus the new checks the
ad-hoc lints never had: a TRANSITIVE host-sync purity walk (a sync
smuggled into a helper called from ``tick`` is caught, not just a sync
written inline), and a State-field dead-write detector.

Rules parse source only; nothing here executes backend code (the two
registry-introspection kernel rules import ``ops.registry``, which is
why they skip on non-importable fixture trees).
"""

from __future__ import annotations

import ast
import pathlib
from typing import Dict, List, Set, Tuple

from frankenpaxos_tpu.analysis import astutil
from frankenpaxos_tpu.analysis.core import Context, Finding, rule

# ---------------------------------------------------------------------------
# Inventory
# ---------------------------------------------------------------------------


@rule(
    "backend-inventory",
    "ast",
    "tpu/ holds at least the expected number of *_batched.py backends",
)
def check_backend_inventory(ctx: Context) -> List[Finding]:
    files = astutil.batched_files(ctx.root)
    if len(files) < ctx.min_backends:
        return [
            Finding(
                rule="backend-inventory",
                path=str((ctx.root / "tpu").relative_to(ctx.repo))
                if ctx.root.is_relative_to(ctx.repo)
                else str(ctx.root / "tpu"),
                line=0,
                message=(
                    f"expected >= {ctx.min_backends} batched backends, "
                    f"found {len(files)}: {[f.name for f in files]}"
                ),
                key="count",
            )
        ]
    return []


def _rel(ctx: Context, path: pathlib.Path) -> str:
    try:
        return str(path.relative_to(ctx.repo))
    except ValueError:
        return str(path.relative_to(ctx.root.parent))


# ---------------------------------------------------------------------------
# Donation (PR 1 contract)
# ---------------------------------------------------------------------------


@rule(
    "donation-jit",
    "ast",
    "every jitted *State-threading entry point in tpu/ donates its "
    "state buffers (single-buffer HBM contract)",
)
def check_donation(ctx: Context) -> List[Finding]:
    out: List[Finding] = []
    for path in astutil.py_files(ctx.root / "tpu"):
        tree = astutil.parse_file(path)
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            jitted = donated = False
            for dec in node.decorator_list:
                is_jit, has_donate = astutil.jit_decorator_info(dec)
                jitted = jitted or is_jit
                donated = donated or has_donate
            if not jitted or not astutil.threads_state(node):
                continue
            if donated:
                continue
            out.append(
                Finding(
                    rule="donation-jit",
                    path=_rel(ctx, path),
                    line=node.lineno,
                    message=(
                        f"jitted state-threading entry point "
                        f"{node.name!r} lacks donate_argnums/"
                        "donate_argnames — the cluster state "
                        "double-buffers in device memory (see "
                        "tpu/common.py donation policy)"
                    ),
                    key=f"{path.name}:{node.name}",
                )
            )
    return out


# ---------------------------------------------------------------------------
# Telemetry (PR 2 contract)
# ---------------------------------------------------------------------------


@rule(
    "telemetry-state-carry",
    "ast",
    "every batched *State dataclass threads a `telemetry: Telemetry` "
    "field through the scan carry",
)
def check_telemetry_state(ctx: Context) -> List[Finding]:
    out: List[Finding] = []
    for path in astutil.batched_files(ctx.root):
        tree = astutil.parse_file(path)
        classes = astutil.classes_with_suffix(tree, "State")
        if not classes:
            out.append(
                Finding(
                    rule="telemetry-state-carry",
                    path=_rel(ctx, path),
                    line=0,
                    message="no *State dataclass found",
                    key=f"{path.name}:<missing>",
                )
            )
            continue
        for cls in classes:
            ann = astutil.ann_fields(cls).get("telemetry")
            if ann is None or "Telemetry" not in ann:
                out.append(
                    Finding(
                        rule="telemetry-state-carry",
                        path=_rel(ctx, path),
                        line=cls.lineno,
                        message=(
                            f"{cls.name} lacks a `telemetry: Telemetry` "
                            "field (tpu/telemetry.py carry contract)"
                        ),
                        key=f"{path.name}:{cls.name}",
                    )
                )
    return out


@rule(
    "telemetry-tick-records",
    "ast",
    "every batched backend's tick calls telemetry record() — no dead "
    "metric rings",
)
def check_tick_records(ctx: Context) -> List[Finding]:
    out: List[Finding] = []
    for path in astutil.batched_files(ctx.root):
        tree = astutil.parse_file(path)
        ticks = astutil.functions_named(tree, ("tick",))
        if not ticks:
            out.append(
                Finding(
                    rule="telemetry-tick-records",
                    path=_rel(ctx, path),
                    line=0,
                    message="no tick function found",
                    key=f"{path.name}:<missing>",
                )
            )
            continue
        for func in ticks:
            calls_record = any(
                isinstance(n, ast.Call)
                and (
                    (isinstance(n.func, ast.Name) and n.func.id == "record")
                    or (
                        isinstance(n.func, ast.Attribute)
                        and n.func.attr == "record"
                    )
                )
                for n in ast.walk(func)
            )
            if not calls_record:
                out.append(
                    Finding(
                        rule="telemetry-tick-records",
                        path=_rel(ctx, path),
                        line=func.lineno,
                        message=(
                            "tick never calls telemetry record() — a "
                            "dead ring ships no observability"
                        ),
                        key=path.name,
                    )
                )
    return out


# ---------------------------------------------------------------------------
# Host-sync / trace purity (generalized + transitive)
# ---------------------------------------------------------------------------

# Attribute/name references that serialize the compiled loop against
# the host. `asarray` is special-cased below: numpy's blocks, jnp's is
# traced.
_SYNC_NAMES = (
    "block_until_ready",
    "device_get",
    "pure_callback",
    "io_callback",
    "debug_callback",
)

_NUMPY_BASES = ("np", "numpy", "onp")
_JNP_BASES = ("jnp", "jaxnp")


def _sync_offenses_in(func: ast.AST) -> List[Tuple[str, int]]:
    """(primitive, line) pairs for host-sync constructs inside ``func``
    (nested defs included)."""
    out: List[Tuple[str, int]] = []
    for node in ast.walk(func):
        if isinstance(node, ast.Attribute):
            if node.attr in _SYNC_NAMES:
                out.append((node.attr, node.lineno))
            elif node.attr == "asarray":
                base = (
                    node.value.id
                    if isinstance(node.value, ast.Name)
                    else None
                )
                # jnp.asarray is traced; numpy's (or an unknown base,
                # conservatively) materializes on the host.
                if base not in _JNP_BASES:
                    out.append(("asarray", node.lineno))
        elif isinstance(node, ast.Name) and node.id in _SYNC_NAMES + (
            "asarray",
        ):
            # A bare `asarray` name is a from-import of numpy's (jnp
            # users write jnp.asarray by repo convention) — host
            # materialization either way.
            out.append((node.id, node.lineno))
        elif (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "item"
        ):
            out.append(("item", node.lineno))
    return out


def _module_index(ctx: Context) -> Dict[str, dict]:
    """dotted module name -> {path, tree, functions, aliases} for every
    module under tpu/ and ops/ (the in-graph universe)."""
    index: Dict[str, dict] = {}
    pkg = ctx.root.name
    for sub in ("tpu", "ops"):
        base = ctx.root / sub
        if not base.exists():
            continue
        for path in astutil.py_files(base):
            tree = astutil.parse_file(path)
            dotted = f"{pkg}.{sub}.{path.stem}"
            index[dotted] = {
                "path": path,
                "tree": tree,
                "functions": astutil.module_functions(tree),
                "aliases": astutil.import_aliases(tree),
                "tables": astutil.dispatch_tables(tree),
            }
    return index


def _resolve_call(
    index: Dict[str, dict], mod: str, base: str, name: str
):
    """Resolve a ``base.name(...)`` / ``name(...)`` call made inside
    module ``mod`` to a (module, function-name) pair inside the index,
    or None for externals (jax, jnp, stdlib, methods)."""
    entry = index[mod]
    if base == "":
        if name in entry["functions"]:
            return (mod, name)
        target = entry["aliases"].get(name)
        if target and "." in target:
            tmod, tname = target.rsplit(".", 1)
            if tmod in index and tname in index[tmod]["functions"]:
                return (tmod, tname)
        return None
    target = entry["aliases"].get(base)
    if target and target in index and name in index[target]["functions"]:
        return (target, name)
    # METHOD calls (``driver.helper(...)`` / ``self.helper(...)``):
    # the base is an object, not a module alias, but the walk can still
    # follow a def with that name in the SAME module —
    # module_functions() indexes class bodies, so methods resolve like
    # any other def. Array/stdlib method names (.sum(), .astype(), ...)
    # match no local def and fall through to None exactly as before.
    if name in entry["functions"]:
        return (mod, name)
    return None


@rule(
    "host-sync-purity",
    "ast",
    "no host-sync primitive is reachable from any tick/run_ticks/step "
    "body — transitively, through helpers in tpu/ and ops/, including "
    "method calls and dict switch-table dispatch",
)
def check_host_sync(ctx: Context) -> List[Finding]:
    index = _module_index(ctx)
    # Roots: every in-graph function in every tpu module.
    queue: List[Tuple[str, str, ast.AST]] = []
    seen: Set[Tuple[str, str]] = set()
    for mod, entry in index.items():
        if entry["path"].parent.name != "tpu":
            continue
        for func in astutil.functions_named(
            entry["tree"], astutil.IN_GRAPH_FUNCS
        ):
            if (mod, func.name) not in seen:
                seen.add((mod, func.name))
                queue.append((mod, func.name, func))

    out: List[Finding] = []
    emitted: Set[str] = set()
    while queue:
        mod, fname, func = queue.pop()
        entry = index[mod]
        for prim, line in _sync_offenses_in(func):
            key = f"{entry['path'].name}:{fname}:{prim}"
            if key in emitted:
                continue
            emitted.add(key)
            out.append(
                Finding(
                    rule="host-sync-purity",
                    path=_rel(ctx, entry["path"]),
                    line=line,
                    message=(
                        f"host-sync primitive {prim!r} in {fname!r}, "
                        "which is reachable from a compiled "
                        "tick/run_ticks body — it serializes the scan "
                        "against the host (use the telemetry ring / "
                        "post-hoc stats instead)"
                    ),
                    key=key,
                )
            )
        callees = set(astutil.called_names(func))
        # SWITCH TABLES: a read of a module/class-level dict of function
        # refs inside a walked body dispatches to every function in the
        # table (HANDLERS[kind](x) — the call edge the direct walk
        # cannot see); all its entries join the frontier.
        tables = entry["tables"]
        if tables:
            for node in ast.walk(func):
                if (
                    isinstance(node, ast.Name)
                    and isinstance(node.ctx, ast.Load)
                    and node.id in tables
                ):
                    callees.update(tables[node.id])
        for base, name in callees:
            resolved = _resolve_call(index, mod, base, name)
            if resolved and resolved not in seen:
                seen.add(resolved)
                tmod, tname = resolved
                queue.append(
                    (tmod, tname, index[tmod]["functions"][tname])
                )
    return sorted(out, key=lambda f: f.key)


# ---------------------------------------------------------------------------
# Faults (PR 3 contract)
# ---------------------------------------------------------------------------


@rule(
    "fault-config-field",
    "ast",
    "every batched *Config accepts a `faults: FaultPlan` field",
)
def check_fault_config(ctx: Context) -> List[Finding]:
    out: List[Finding] = []
    for path in astutil.batched_files(ctx.root):
        tree = astutil.parse_file(path)
        classes = astutil.classes_with_suffix(tree, "Config")
        if not classes:
            out.append(
                Finding(
                    rule="fault-config-field",
                    path=_rel(ctx, path),
                    line=0,
                    message="no *Config dataclass found",
                    key=f"{path.name}:<missing>",
                )
            )
            continue
        for cls in classes:
            ann = astutil.ann_fields(cls).get("faults")
            if ann is None or "FaultPlan" not in ann:
                out.append(
                    Finding(
                        rule="fault-config-field",
                        path=_rel(ctx, path),
                        line=cls.lineno,
                        message=(
                            f"{cls.name} lacks a `faults: FaultPlan` "
                            "field (tpu/faults.py contract)"
                        ),
                        key=f"{path.name}:{cls.name}",
                    )
                )
    return out


@rule(
    "fault-validate",
    "ast",
    "every batched *Config.__post_init__ calls faults.validate(...) "
    "so malformed plans fail at config time",
)
def check_fault_validate(ctx: Context) -> List[Finding]:
    out: List[Finding] = []
    for path in astutil.batched_files(ctx.root):
        tree = astutil.parse_file(path)
        for cls in astutil.classes_with_suffix(tree, "Config"):
            post = [
                n
                for n in cls.body
                if isinstance(n, ast.FunctionDef)
                and n.name == "__post_init__"
            ]
            if not post:
                out.append(
                    Finding(
                        rule="fault-validate",
                        path=_rel(ctx, path),
                        line=cls.lineno,
                        message=f"{cls.name} has no __post_init__",
                        key=f"{path.name}:{cls.name}",
                    )
                )
                continue
            calls_validate = any(
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr == "validate"
                and "faults" in ast.unparse(n.func.value)
                for n in ast.walk(post[0])
            )
            if not calls_validate:
                out.append(
                    Finding(
                        rule="fault-validate",
                        path=_rel(ctx, path),
                        line=post[0].lineno,
                        message=(
                            f"{cls.name}.__post_init__ never calls "
                            "self.faults.validate(...)"
                        ),
                        key=f"{path.name}:{cls.name}",
                    )
                )
    return out


def _tick_applies_faults(func: ast.FunctionDef) -> bool:
    for node in ast.walk(func):
        if isinstance(node, ast.Attribute) and node.attr == "faults":
            return True
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id in ("faults_mod", "faults")
        ):
            return True
    return False


@rule(
    "fault-apply",
    "ast",
    "every batched tick actually applies the configured FaultPlan",
)
def check_fault_apply(ctx: Context) -> List[Finding]:
    out: List[Finding] = []
    for path in astutil.batched_files(ctx.root):
        tree = astutil.parse_file(path)
        for func in astutil.functions_named(tree, ("tick",)):
            if not _tick_applies_faults(func):
                out.append(
                    Finding(
                        rule="fault-apply",
                        path=_rel(ctx, path),
                        line=func.lineno,
                        message=(
                            "tick accepts a FaultPlan via config but "
                            "never applies it"
                        ),
                        key=path.name,
                    )
                )
    return out


@rule(
    "fault-rate-validated",
    "ast",
    "every float *_rate config field is range-checked in __post_init__",
)
def check_rate_validated(ctx: Context) -> List[Finding]:
    out: List[Finding] = []
    for path in astutil.batched_files(ctx.root):
        tree = astutil.parse_file(path)
        for cls in astutil.classes_with_suffix(tree, "Config"):
            rate_fields = [
                name
                for name, ann in astutil.ann_fields(cls).items()
                if name.endswith("_rate") and "float" in ann
            ]
            post = [
                n
                for n in cls.body
                if isinstance(n, ast.FunctionDef)
                and n.name == "__post_init__"
            ]
            body_src = ast.unparse(post[0]) if post else ""
            for name in rate_fields:
                if f"self.{name}" not in body_src:
                    out.append(
                        Finding(
                            rule="fault-rate-validated",
                            path=_rel(ctx, path),
                            line=cls.lineno,
                            message=(
                                f"{cls.name}.{name} is never "
                                "range-checked in __post_init__ — an "
                                "out-of-range rate simulates a "
                                "different protocol regime"
                            ),
                            key=f"{path.name}:{cls.name}:{name}",
                        )
                    )
    return out


# ---------------------------------------------------------------------------
# Workload engine (the faults contracts mirrored for tpu/workload.py)
# ---------------------------------------------------------------------------


@rule(
    "workload-config-field",
    "ast",
    "every batched *Config accepts a `workload: WorkloadPlan` field",
)
def check_workload_config(ctx: Context) -> List[Finding]:
    out: List[Finding] = []
    for path in astutil.batched_files(ctx.root):
        tree = astutil.parse_file(path)
        classes = astutil.classes_with_suffix(tree, "Config")
        if not classes:
            out.append(
                Finding(
                    rule="workload-config-field",
                    path=_rel(ctx, path),
                    line=0,
                    message="no *Config dataclass found",
                    key=f"{path.name}:<missing>",
                )
            )
            continue
        for cls in classes:
            ann = astutil.ann_fields(cls).get("workload")
            if ann is None or "WorkloadPlan" not in ann:
                out.append(
                    Finding(
                        rule="workload-config-field",
                        path=_rel(ctx, path),
                        line=cls.lineno,
                        message=(
                            f"{cls.name} lacks a `workload: WorkloadPlan`"
                            " field (tpu/workload.py contract)"
                        ),
                        key=f"{path.name}:{cls.name}",
                    )
                )
    return out


@rule(
    "workload-validate",
    "ast",
    "every batched *Config.__post_init__ calls workload.validate(...) "
    "so malformed traffic shapes fail at config time",
)
def check_workload_validate(ctx: Context) -> List[Finding]:
    out: List[Finding] = []
    for path in astutil.batched_files(ctx.root):
        tree = astutil.parse_file(path)
        for cls in astutil.classes_with_suffix(tree, "Config"):
            post = [
                n
                for n in cls.body
                if isinstance(n, ast.FunctionDef)
                and n.name == "__post_init__"
            ]
            if not post:
                out.append(
                    Finding(
                        rule="workload-validate",
                        path=_rel(ctx, path),
                        line=cls.lineno,
                        message=f"{cls.name} has no __post_init__",
                        key=f"{path.name}:{cls.name}",
                    )
                )
                continue
            calls_validate = any(
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr == "validate"
                and "workload" in ast.unparse(n.func.value)
                for n in ast.walk(post[0])
            )
            if not calls_validate:
                out.append(
                    Finding(
                        rule="workload-validate",
                        path=_rel(ctx, path),
                        line=post[0].lineno,
                        message=(
                            f"{cls.name}.__post_init__ never calls "
                            "self.workload.validate(...)"
                        ),
                        key=f"{path.name}:{cls.name}",
                    )
                )
    return out


def _tick_applies_workload(func: ast.FunctionDef) -> bool:
    for node in ast.walk(func):
        if isinstance(node, ast.Attribute) and node.attr == "workload":
            return True
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id in ("workload_mod", "workload")
        ):
            return True
    return False


@rule(
    "workload-apply",
    "ast",
    "every batched tick actually applies the configured WorkloadPlan "
    "(admission gates its propose path)",
)
def check_workload_apply(ctx: Context) -> List[Finding]:
    out: List[Finding] = []
    for path in astutil.batched_files(ctx.root):
        tree = astutil.parse_file(path)
        for func in astutil.functions_named(tree, ("tick",)):
            if not _tick_applies_workload(func):
                out.append(
                    Finding(
                        rule="workload-apply",
                        path=_rel(ctx, path),
                        line=func.lineno,
                        message=(
                            "tick accepts a WorkloadPlan via config but "
                            "never applies it"
                        ),
                        key=path.name,
                    )
                )
    return out


@rule(
    "workload-rate-validated",
    "ast",
    "every float field of the WorkloadPlan dataclass is range-checked "
    "in its validate() body",
)
def check_workload_rate_validated(ctx: Context) -> List[Finding]:
    path = ctx.root / "tpu" / "workload.py"
    if not path.exists():
        return [
            Finding(
                rule="workload-rate-validated",
                path="tpu/workload.py",
                line=0,
                message=(
                    "no tpu/workload.py module found — the workload "
                    "engine is missing"
                ),
                key="workload.py:<missing>",
            )
        ]
    out: List[Finding] = []
    tree = astutil.parse_file(path)
    for cls in astutil.classes_with_suffix(tree, "Plan"):
        float_fields = [
            name
            for name, ann in astutil.ann_fields(cls).items()
            if "float" in ann
        ]
        validate = next(
            (
                n
                for n in cls.body
                if isinstance(n, ast.FunctionDef) and n.name == "validate"
            ),
            None,
        )
        body_src = ast.unparse(validate) if validate else ""
        for name in float_fields:
            if f"self.{name}" not in body_src:
                out.append(
                    Finding(
                        rule="workload-rate-validated",
                        path=_rel(ctx, path),
                        line=cls.lineno,
                        message=(
                            f"{cls.name}.{name} is never range-checked "
                            "in validate() — an out-of-range rate "
                            "shapes a different traffic regime"
                        ),
                        key=f"{path.name}:{cls.name}:{name}",
                    )
                )
    return out


# ---------------------------------------------------------------------------
# Production lifecycle (the fault/workload contracts mirrored for
# tpu/lifecycle.py). Scoped to the backends that thread the subsystem —
# the plan rolls out flagship-first, so the contract names its coverage
# explicitly instead of demanding all backends at once.
# ---------------------------------------------------------------------------

LIFECYCLE_BACKEND_FILES = (
    "multipaxos_batched.py",
    "compartmentalized_batched.py",
)


def _lifecycle_files(ctx: Context) -> List[pathlib.Path]:
    return [
        p
        for p in astutil.batched_files(ctx.root)
        if p.name in LIFECYCLE_BACKEND_FILES
    ]


@rule(
    "lifecycle-config-field",
    "ast",
    "every lifecycle-threaded batched *Config accepts a "
    "`lifecycle: LifecyclePlan` field",
)
def check_lifecycle_config(ctx: Context) -> List[Finding]:
    out: List[Finding] = []
    for path in _lifecycle_files(ctx):
        tree = astutil.parse_file(path)
        for cls in astutil.classes_with_suffix(tree, "Config"):
            ann = astutil.ann_fields(cls).get("lifecycle")
            if ann is None or "LifecyclePlan" not in ann:
                out.append(
                    Finding(
                        rule="lifecycle-config-field",
                        path=_rel(ctx, path),
                        line=cls.lineno,
                        message=(
                            f"{cls.name} lacks a `lifecycle: "
                            "LifecyclePlan` field (tpu/lifecycle.py "
                            "contract)"
                        ),
                        key=f"{path.name}:{cls.name}",
                    )
                )
    return out


@rule(
    "lifecycle-validate",
    "ast",
    "every lifecycle-threaded *Config.__post_init__ calls "
    "lifecycle.validate(...) so malformed plans (misaligned rotation "
    "quanta, cacheless resubmit rates) fail at config time",
)
def check_lifecycle_validate(ctx: Context) -> List[Finding]:
    out: List[Finding] = []
    for path in _lifecycle_files(ctx):
        tree = astutil.parse_file(path)
        for cls in astutil.classes_with_suffix(tree, "Config"):
            post = [
                n
                for n in cls.body
                if isinstance(n, ast.FunctionDef)
                and n.name == "__post_init__"
            ]
            calls_validate = post and any(
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr == "validate"
                and "lifecycle" in ast.unparse(n.func.value)
                for n in ast.walk(post[0])
            )
            if not calls_validate:
                out.append(
                    Finding(
                        rule="lifecycle-validate",
                        path=_rel(ctx, path),
                        line=cls.lineno,
                        message=(
                            f"{cls.name}.__post_init__ never calls "
                            "self.lifecycle.validate(...)"
                        ),
                        key=f"{path.name}:{cls.name}",
                    )
                )
    return out


@rule(
    "lifecycle-apply",
    "ast",
    "every lifecycle-threaded tick actually applies the configured "
    "LifecyclePlan (rotation/sessions/reconfig legs reachable)",
)
def check_lifecycle_apply(ctx: Context) -> List[Finding]:
    out: List[Finding] = []
    for path in _lifecycle_files(ctx):
        tree = astutil.parse_file(path)
        for func in astutil.functions_named(tree, ("tick",)):
            applies = any(
                (
                    isinstance(n, ast.Attribute)
                    and n.attr == "lifecycle"
                )
                or (
                    isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and isinstance(n.func.value, ast.Name)
                    and n.func.value.id in ("lifecycle_mod", "lifecycle")
                )
                for n in ast.walk(func)
            )
            if not applies:
                out.append(
                    Finding(
                        rule="lifecycle-apply",
                        path=_rel(ctx, path),
                        line=func.lineno,
                        message=(
                            "tick accepts a LifecyclePlan via config "
                            "but never applies it"
                        ),
                        key=path.name,
                    )
                )
    return out


# ---------------------------------------------------------------------------
# Kernel layer (PR 4 contract)
# ---------------------------------------------------------------------------


@rule(
    "kernel-pallas-containment",
    "ast",
    "pallas_call appears only inside ops/ — the registry is the single "
    "kernel dispatch point",
)
def check_pallas_containment(ctx: Context) -> List[Finding]:
    out: List[Finding] = []
    for path in astutil.py_files(ctx.root):
        rel = path.relative_to(ctx.root)
        if rel.parts and rel.parts[0] == "ops":
            continue
        tree = astutil.parse_file(path)
        lines = []
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Attribute)
                and node.attr == "pallas_call"
            ) or (
                isinstance(node, ast.Name) and node.id == "pallas_call"
            ):
                lines.append(node.lineno)
        if lines:
            out.append(
                Finding(
                    rule="kernel-pallas-containment",
                    path=_rel(ctx, path),
                    line=lines[0],
                    message=(
                        f"pallas_call outside ops/ at line(s) {lines} "
                        "— route the plane through "
                        "ops.registry.dispatch instead"
                    ),
                    key=str(rel),
                )
            )
    return out


@rule(
    "kernel-dispatch-coverage",
    "ast",
    "every registered kernel plane is dispatched by its backend's tick "
    "(and nothing dispatches an unregistered plane)",
)
def check_dispatch_coverage(ctx: Context) -> List[Finding]:
    if not (ctx.importable and ctx.is_real_tree()):
        return []
    from frankenpaxos_tpu.ops import registry

    out: List[Finding] = []
    for backend, planes in registry.coverage().items():
        module = ctx.root / "tpu" / f"{backend}_batched.py"
        if not module.exists():
            out.append(
                Finding(
                    rule="kernel-dispatch-coverage",
                    path=f"frankenpaxos_tpu/tpu/{backend}_batched.py",
                    line=0,
                    message=(
                        f"registry covers backend {backend!r} but no "
                        "such batched module exists"
                    ),
                    key=f"{backend}:<missing>",
                )
            )
            continue
        dispatched = astutil.dispatched_plane_names(
            astutil.parse_file(module)
        )
        for plane in set(planes) - dispatched:
            out.append(
                Finding(
                    rule="kernel-dispatch-coverage",
                    path=_rel(ctx, module),
                    line=0,
                    message=(
                        f"registered plane {plane!r} is never "
                        "dispatched by this backend — dead kernel"
                    ),
                    key=f"{backend}:{plane}",
                )
            )
        for plane in dispatched - set(registry.PLANES):
            out.append(
                Finding(
                    rule="kernel-dispatch-coverage",
                    path=_rel(ctx, module),
                    line=0,
                    message=(
                        f"dispatches unregistered plane {plane!r} — "
                        "KeyError at trace time"
                    ),
                    key=f"{backend}:{plane}:unregistered",
                )
            )
    return out


@rule(
    "kernel-reference-twin",
    "ast",
    "every registered kernel has a reference_* twin with the same "
    "positional signature (plus block/interpret)",
)
def check_reference_twin(ctx: Context) -> List[Finding]:
    if not (ctx.importable and ctx.is_real_tree()):
        return []
    import inspect

    from frankenpaxos_tpu.ops import registry

    out: List[Finding] = []
    for name, plane in registry.PLANES.items():
        if not plane.reference.__name__.startswith("reference_"):
            out.append(
                Finding(
                    rule="kernel-reference-twin",
                    path="frankenpaxos_tpu/ops/registry.py",
                    line=0,
                    message=(
                        f"plane {name!r}: reference twin "
                        f"{plane.reference.__name__!r} is not named "
                        "reference_*"
                    ),
                    key=f"{name}:name",
                )
            )
        ref_params = list(
            inspect.signature(plane.reference).parameters
        )
        ker_params = [
            p
            for p in inspect.signature(plane.kernel).parameters
            if p not in ("block", "interpret")
        ]
        if ker_params != ref_params:
            out.append(
                Finding(
                    rule="kernel-reference-twin",
                    path="frankenpaxos_tpu/ops/registry.py",
                    line=0,
                    message=(
                        f"plane {name!r}: kernel signature must be the "
                        f"reference's plus block/interpret (got "
                        f"{ker_params} vs {ref_params})"
                    ),
                    key=f"{name}:signature",
                )
            )
    return out


@rule(
    "kernel-policy-knob",
    "ast",
    "every kernel-covered backend's *Config carries a validated "
    "`kernels: KernelPolicy` knob",
)
def check_policy_knob(ctx: Context) -> List[Finding]:
    if not (ctx.importable and ctx.is_real_tree()):
        return []
    from frankenpaxos_tpu.ops import registry

    out: List[Finding] = []
    for backend in registry.coverage():
        module = ctx.root / "tpu" / f"{backend}_batched.py"
        if not module.exists():
            continue  # kernel-dispatch-coverage already reports this
        tree = astutil.parse_file(module)
        for cls in astutil.classes_with_suffix(tree, "Config"):
            fields = astutil.ann_fields(cls)
            if "kernels" not in fields:
                out.append(
                    Finding(
                        rule="kernel-policy-knob",
                        path=_rel(ctx, module),
                        line=cls.lineno,
                        message=f"{cls.name} lacks a `kernels` field",
                        key=f"{module.name}:{cls.name}:field",
                    )
                )
                continue
            post = next(
                (
                    stmt
                    for stmt in cls.body
                    if isinstance(stmt, ast.FunctionDef)
                    and stmt.name == "__post_init__"
                ),
                None,
            )
            validates = post is not None and any(
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "validate"
                and isinstance(node.func.value, ast.Attribute)
                and node.func.value.attr == "kernels"
                for node in ast.walk(post)
            )
            if not validates:
                out.append(
                    Finding(
                        rule="kernel-policy-knob",
                        path=_rel(ctx, module),
                        line=cls.lineno,
                        message=(
                            f"{cls.name}.__post_init__ must call "
                            "self.kernels.validate()"
                        ),
                        key=f"{module.name}:{cls.name}:validate",
                    )
                )
    return out


# ---------------------------------------------------------------------------
# State-field dead writes — RETIRED (ANALYSIS_VERSION 2.4)
# ---------------------------------------------------------------------------
# The AST-approximate `state-dead-write` rule (any attribute read
# anywhere in the package counted as consumption, with a replace()
# self-feed exclusion) is replaced by the dataflow layer's
# `state-dead-write-reachable` (rules_dataflow.py): reaching
# definitions over the traced tick jaxpr, where a leaf is live only
# if some dataflow path — across any number of ticks — carries it to
# telemetry, a traced invariant, or a host-read output. The jaxpr
# rule is strictly stronger: a field whose value only ever feeds
# itself is dead no matter how the Python spells the update.


# ---------------------------------------------------------------------------
# Packing layer (PR 16 contract)
# ---------------------------------------------------------------------------

# Keys of tpu/common.PACKED_PLANES — the planes a backend may store
# bit-packed. Mirrored here as literals: the analysis layer parses the
# tree without importing it (fixtures are parse-only).
_PACKED_PLANE_ATTRS = frozenset({"status", "rb_status", "sess_occ"})
_BIT_OPS = (ast.BitOr, ast.BitAnd, ast.BitXor, ast.LShift, ast.RShift)


@rule(
    "packing-containment",
    "ast",
    "raw bit-twiddling on packed planes (tpu/common.PACKED_PLANES) "
    "lives only in tpu/packing.py — backends route through the "
    "pack/unpack helpers",
)
def check_packing_containment(ctx: Context) -> List[Finding]:
    """A packed plane is an opaque word array outside tpu/packing.py:
    shifting or masking ``<x>.status`` / ``<x>.rb_status`` /
    ``<x>.sess_occ`` inline re-implements the codec and silently
    diverges from the pinned bit layout the twin tests certify.
    Only a DIRECT operand counts (modulo subscripting): a plane
    nested in a comparison (``(state.status == CHOSEN) & live`` —
    boolean mask logic on the unpacked view) or handed to a helper
    call (``cached & packing.occ_get(...)``) is not twiddling the
    stored words."""

    def _packed_operand(expr: ast.expr) -> bool:
        while isinstance(expr, ast.Subscript):
            expr = expr.value
        return (
            isinstance(expr, ast.Attribute)
            and expr.attr in _PACKED_PLANE_ATTRS
        )

    out: List[Finding] = []
    for path in astutil.py_files(ctx.root):
        rel = path.relative_to(ctx.root)
        if rel.parts[-1] == "packing.py":
            continue
        tree = astutil.parse_file(path)
        hits: List[int] = []
        for node in ast.walk(tree):
            if isinstance(node, ast.BinOp) and isinstance(node.op, _BIT_OPS):
                operands = (node.left, node.right)
            elif isinstance(node, ast.AugAssign) and isinstance(
                node.op, _BIT_OPS
            ):
                operands = (node.target, node.value)
            else:
                continue
            if any(_packed_operand(op) for op in operands):
                hits.append(node.lineno)
        if hits:
            out.append(
                Finding(
                    rule="packing-containment",
                    path=_rel(ctx, path),
                    line=hits[0],
                    message=(
                        f"bitwise op on a packed plane at line(s) {hits} "
                        "— use the tpu/packing.py helpers "
                        "(pack/unpack/occ_set/occ_clear/occ_get)"
                    ),
                    key=str(rel),
                )
            )
    return out


# Packed dependency-graph planes (ops/depgraph.py adjacency layout):
# [V, ceil(V/32)] uint32 rows, little-endian lanes — the layout the
# kernel/reference/oracle bit-identity tests certify.
_DEPGRAPH_ATTRS = frozenset({"adj"})


@rule(
    "depgraph-containment",
    "ast",
    "raw bit-twiddling on the packed dependency-graph adjacency "
    "(State.adj) lives only in ops/depgraph.py — consumers route "
    "through its pack/clear/subset helpers",
)
def check_depgraph_containment(ctx: Context) -> List[Finding]:
    """The packed adjacency is an opaque word array outside
    ops/depgraph.py: shifting or masking ``<x>.adj`` inline
    re-implements the bitmask layout (lane order, padding-word
    hygiene) and silently diverges from the closure the
    kernel-vs-oracle tests certify. Same operand discipline as
    packing-containment: only a DIRECT ``.adj`` operand (modulo
    subscripting) of a bitwise op counts — comparisons against it and
    helper calls over it are reads of the opaque value, and local
    word arrays a helper returned are the helper's business."""

    def _adj_operand(expr: ast.expr) -> bool:
        while isinstance(expr, ast.Subscript):
            expr = expr.value
        return (
            isinstance(expr, ast.Attribute)
            and expr.attr in _DEPGRAPH_ATTRS
        )

    out: List[Finding] = []
    for path in astutil.py_files(ctx.root):
        rel = path.relative_to(ctx.root)
        if rel.parts[-1] == "depgraph.py":
            continue
        tree = astutil.parse_file(path)
        hits: List[int] = []
        for node in ast.walk(tree):
            if isinstance(node, ast.BinOp) and isinstance(node.op, _BIT_OPS):
                operands = (node.left, node.right)
            elif isinstance(node, ast.AugAssign) and isinstance(
                node.op, _BIT_OPS
            ):
                operands = (node.target, node.value)
            else:
                continue
            if any(_adj_operand(op) for op in operands):
                hits.append(node.lineno)
        if hits:
            out.append(
                Finding(
                    rule="depgraph-containment",
                    path=_rel(ctx, path),
                    line=hits[0],
                    message=(
                        f"bitwise op on the packed adjacency at line(s) "
                        f"{hits} — use the ops/depgraph.py helpers "
                        "(pack_mask/unpack_mask/clear_vertices/"
                        "rows_subset)"
                    ),
                    key=str(rel),
                )
            )
    return out


@rule(
    "costmodel-coverage",
    "ast",
    "every registered kernel plane (and every PACKED_PLANES entry, and "
    "the unfused reference tick) has a cost-model entry with stated "
    "byte/FLOP terms (ops/costmodel.py)",
)
def check_costmodel_coverage(ctx: Context) -> List[Finding]:
    if not (ctx.importable and ctx.is_real_tree()):
        return []
    from frankenpaxos_tpu.ops import costmodel, registry
    from frankenpaxos_tpu.tpu import common

    PATH = "frankenpaxos_tpu/ops/costmodel.py"
    out: List[Finding] = []
    required = sorted(set(registry.PLANES) | {"multipaxos_unfused_tick"})
    for name in required:
        model = costmodel.MODELS.get(name)
        if model is None:
            out.append(
                Finding(
                    rule="costmodel-coverage",
                    path=PATH,
                    line=0,
                    message=(
                        f"plane {name!r} has no cost-model entry — "
                        "state its byte/FLOP terms in costmodel.MODELS"
                    ),
                    key=name,
                )
            )
            continue
        key = costmodel.CAPTURE_KEYS.get(name)
        if key is None and name in registry.PLANES:
            out.append(
                Finding(
                    rule="costmodel-coverage",
                    path=PATH,
                    line=0,
                    message=(
                        f"plane {name!r} has no CAPTURE_KEYS flagship "
                        "shape — microbench captures of it cannot be "
                        "validated"
                    ),
                    key=f"{name}:capture-key",
                )
            )
        # The stated terms must be live at SOME shape: the flagship
        # capture key when recorded, else a synthetic small key of the
        # right arity (probed via the model's own input spec).
        if key is None:
            key = costmodel.CAPTURE_KEYS["multipaxos_fused_tick"]
        try:
            ok = (
                costmodel.bytes_moved(name, key) > 0
                and costmodel.flops(name, key) > 0
            )
        except Exception as e:  # stated terms crash = no coverage
            ok = False
            out.append(
                Finding(
                    rule="costmodel-coverage",
                    path=PATH,
                    line=0,
                    message=(
                        f"plane {name!r}: byte/FLOP terms raise at key "
                        f"{key}: {e}"
                    ),
                    key=f"{name}:raises",
                )
            )
        if ok is False and not any(f.key.startswith(name) for f in out):
            out.append(
                Finding(
                    rule="costmodel-coverage",
                    path=PATH,
                    line=0,
                    message=(
                        f"plane {name!r}: stated byte/FLOP terms are "
                        f"non-positive at key {key}"
                    ),
                    key=f"{name}:terms",
                )
            )
    for pname, bits in sorted(common.PACKED_PLANES.items()):
        pm = costmodel.PACKED_MODELS.get(pname)
        if pm is None:
            out.append(
                Finding(
                    rule="costmodel-coverage",
                    path=PATH,
                    line=0,
                    message=(
                        f"packed plane {pname!r} (common.PACKED_PLANES) "
                        "has no PACKED_MODELS entry"
                    ),
                    key=f"packed:{pname}",
                )
            )
        elif pm.bits != bits:
            out.append(
                Finding(
                    rule="costmodel-coverage",
                    path=PATH,
                    line=0,
                    message=(
                        f"packed plane {pname!r}: model states "
                        f"{pm.bits}-bit packing but common.PACKED_PLANES "
                        f"says {bits} — byte terms are wrong"
                    ),
                    key=f"packed:{pname}:bits",
                )
            )
    return out


@rule(
    "costmodel-drift",
    "ast",
    "every recorded kernel microbench capture sits inside the cost "
    "model's measured/predicted envelope, no capture's ratio regressed "
    "vs the previous round, and the committed envelope artifact is "
    "fresh (results/costmodel_envelope.json)",
)
def check_costmodel_drift(ctx: Context) -> List[Finding]:
    if not (ctx.importable and ctx.is_real_tree()):
        return []
    import json

    from frankenpaxos_tpu.ops import costmodel

    results = ctx.repo / "results"
    out: List[Finding] = []
    labeled = []
    for path in sorted(results.glob("kernel_microbench_*.json")):
        try:
            labeled.append((path.name, json.loads(path.read_text())))
        except (OSError, json.JSONDecodeError) as e:
            out.append(
                Finding(
                    rule="costmodel-drift",
                    path=f"results/{path.name}",
                    line=0,
                    message=f"unreadable capture: {e}",
                    key=f"{path.name}:unreadable",
                )
            )
    for f in costmodel.drift_findings(labeled):
        out.append(
            Finding(
                rule="costmodel-drift",
                path=f"results/{f['capture']}",
                line=0,
                message=f["message"],
                key=f"{f['capture']}:{f['plane']}:{f['kind']}",
            )
        )
    # Envelope artifact freshness: the committed verdict file must
    # exist and match the model constants that live in the tree —
    # a refit without a regenerated artifact (or vice versa) is drift.
    env_path = results / "costmodel_envelope.json"
    ENV = "results/costmodel_envelope.json"
    try:
        payload = json.loads(env_path.read_text())
    except (OSError, json.JSONDecodeError) as e:
        out.append(
            Finding(
                rule="costmodel-drift",
                path=ENV,
                line=0,
                message=(
                    f"missing/unreadable envelope artifact ({e}) — "
                    "regenerate: FPX_WRITE_ENVELOPE=1 python -m "
                    "frankenpaxos_tpu.harness.microbench costmodel"
                ),
                key="envelope:missing",
            )
        )
        return out
    stale = []
    if payload.get("constants_version") != costmodel.CONSTANTS_VERSION:
        stale.append(
            f"constants_version {payload.get('constants_version')} != "
            f"model {costmodel.CONSTANTS_VERSION}"
        )
    if payload.get("envelope") != list(costmodel.ENVELOPE):
        stale.append(
            f"envelope {payload.get('envelope')} != model "
            f"{list(costmodel.ENVELOPE)}"
        )
    if payload.get("regression_factor") != costmodel.REGRESSION_FACTOR:
        stale.append("regression_factor mismatch")
    if not payload.get("bytes_exact", False):
        stale.append("recorded byte terms were not exact")
    if payload.get("uncovered_planes"):
        stale.append(
            f"recorded uncovered planes {payload['uncovered_planes']}"
        )
    if payload.get("drift_findings"):
        stale.append(
            f"{len(payload['drift_findings'])} drift finding(s) "
            "recorded in the artifact"
        )
    for reason in stale:
        out.append(
            Finding(
                rule="costmodel-drift",
                path=ENV,
                line=0,
                message=(
                    f"stale envelope artifact: {reason} — regenerate: "
                    "FPX_WRITE_ENVELOPE=1 python -m "
                    "frankenpaxos_tpu.harness.microbench costmodel"
                ),
                key=f"envelope:{reason[:40]}",
            )
        )
    return out
