"""Flagship-shape analysis under a wall-clock budget (``--budget``).

The default lint leg runs every rule at each backend's tiny
``analysis_config()`` shape — deterministic and fast, but some
contracts are worth re-checking at the shapes bench.py actually
serves. ``run_budget(seconds)`` re-points the shared tick-trace
caches (``rules_trace.CFG_FACTORY``) at per-backend FLAGSHIP shapes
and runs the trace + dataflow layers rule by rule, with:

* per-rule wall-clock accounting (printed and in the JSON report);
* a hard start-gate: a rule only STARTS while budget remains, and
  every rule that never started is listed in the skipped-rules
  report (cheap pure-graph dataflow rules run first, compile-heavy
  trace rules last, so small budgets still buy real coverage);
* no allowlist hygiene: the budget leg applies suppressions but does
  not emit ``allowlist-stale`` findings — the default leg owns
  hygiene, and a flagship re-run must not double-report it.

Shape-calibrated rules are excluded (see ``EXCLUDE``):
``trace-dtype-policy`` pins exact widening counts at the analysis
shapes, and ``donation-hazard``'s control-plane size exemption is
calibrated there too — running either at flagship shapes would
report calibration drift that is really shape drift. Everything
else in the trace/dataflow layers runs
unmodified — rules that trace through the shared caches see flagship
jaxprs; rules that build their own configs keep their own shapes.

This is opt-in (CLI ``--budget SECONDS``, ``LINT_BUDGET=N`` in
scripts/lint.sh) and never part of the default fail-fast path.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional, Sequence

# Per-backend flagship size overrides, applied with dataclasses.replace
# on top of analysis_config(**plans) — plan structure (traced faults,
# shaped workload, lifecycle) comes from the caller, shapes from here.
# multipaxos matches the bench.py flagship (10k simulated acceptors:
# f=1 -> 3 acceptors x 3334 groups); the others scale their primary
# lane axis into the thousands with serving-sized windows.
FLAGSHIP: Dict[str, dict] = {
    "bpaxos": dict(num_leaders=64, window=64, cmds_per_tick=8),
    "caspaxos": dict(num_registers=1024, num_leaders=3),
    "compartmentalized": dict(
        num_groups=64, num_proxy_leaders=16, num_batchers=8,
        num_unbatchers=8, window=32, batch_size=4,
        arrivals_per_tick=4,
    ),
    "craq": dict(
        num_chains=256, num_keys=64, window=16, writes_per_tick=4,
        reads_per_tick=4,
    ),
    "epaxos": dict(num_columns=64, window=64, instances_per_tick=8),
    "fasterpaxos": dict(num_groups=1024, window=32, slots_per_tick=8),
    "fastmultipaxos": dict(
        num_groups=1024, window=32, cmd_window=32, cmds_per_tick=8,
    ),
    "fastpaxos": dict(num_groups=1024, window=32, instances_per_tick=8),
    "grid": dict(rows=16, cols=16, window=32, slots_per_tick=8),
    "horizontal": dict(
        num_groups=1024, window=32, slots_per_tick=8, alpha=16,
    ),
    "mencius": dict(num_leaders=64, window=64, slots_per_tick=8),
    "multipaxos": dict(
        num_groups=3334, window=64, slots_per_tick=8, retry_timeout=16,
    ),
    "scalog": dict(num_shards=4096),
    "unreplicated": dict(num_servers=4096, window=32, ops_per_tick=8),
    "vanillamencius": dict(num_servers=64, window=64, slots_per_tick=8),
}

# Rules whose semantics are calibrated to the analysis shapes: running
# them at flagship would report calibration drift, not new facts.
# trace-dtype-policy: DTYPE_WIDENING pins are count-exact at the
# analysis shapes. donation-hazard: its control-plane size exemption
# (DONATION_MIN_ELEMS) is likewise calibrated at the analysis shapes —
# at flagship sizes the repo-wide delta-read idiom (telemetry/
# accounting deltas computed from the pre-update value after the
# update exists, which XLA's buffer assigner orders safely) crosses
# the threshold and reports idiom, not hazard.
EXCLUDE = ("trace-dtype-policy", "donation-hazard")

# Rules that COMPILE (jit caches, HLO, checkpoint replay, meshes) —
# scheduled last so a small budget spends itself on the cheap
# trace-the-jaxpr rules first.
COMPILE_HEAVY = (
    "trace-retrace-guard",
    "trace-workload-retrace",
    "trace-elastic-retrace",
    "trace-checkpoint-restore",
    "trace-shardmap-kernel",
    "trace-donation-alias",
    "trace-fleet-onecompile",
)


def flagship_config(backend: str, **plan_kwargs):
    """analysis_config(**plans) resized to the backend's flagship
    shape — the CFG_FACTORY the budget leg installs."""
    from frankenpaxos_tpu.analysis import rules_trace as _rt

    cfg = _rt._module(backend).analysis_config(**plan_kwargs)
    return dataclasses.replace(cfg, **FLAGSHIP.get(backend, {}))


def _schedule(layers: Sequence[str]) -> List[str]:
    from frankenpaxos_tpu.analysis import core

    ids = sorted(
        r.id for r in core.RULES.values()
        if r.layer in layers and r.id not in EXCLUDE
    )
    df = [i for i in ids if core.RULES[i].layer == "dataflow"]
    cheap = [
        i for i in ids
        if core.RULES[i].layer != "dataflow" and i not in COMPILE_HEAVY
    ]
    heavy = [i for i in COMPILE_HEAVY if i in ids]
    return df + cheap + heavy


def run_budget(
    seconds: float,
    backends: Optional[Sequence[str]] = None,
    json_out: bool = False,
) -> int:
    """Run the trace + dataflow layers at flagship shapes until the
    budget is spent. Returns the exit code (finding count, capped)."""
    import json as _json
    import sys

    from frankenpaxos_tpu.analysis import (
        allowlists,
        cli,
        core,
        rules_dataflow,
        rules_trace,
    )

    ctx = core.Context()
    if backends:
        ctx.backends = tuple(backends)
    order = _schedule(("trace", "dataflow"))
    deadline = time.monotonic() + float(seconds)

    findings = []
    rows = []  # (rule_id, status, elapsed, n_findings)
    rules_trace.CFG_FACTORY = flagship_config
    rules_trace._TICK_TRACE_CACHE.clear()
    rules_dataflow.clear_cache()
    try:
        for rid in order:
            if time.monotonic() >= deadline:
                rows.append((rid, "skipped", None, None))
                continue
            t0 = time.monotonic()
            try:
                raw = core.RULES[rid].check(ctx)
            except Exception as e:  # a flagship shape a rule rejects
                rows.append((rid, f"error: {e}", time.monotonic() - t0,
                             None))
                continue
            allow = allowlists.suppressions(rid)
            kept = [f for f in raw if f.key not in allow]
            findings.extend(kept)
            rows.append((rid, "ok", time.monotonic() - t0, len(kept)))
    finally:
        rules_trace.CFG_FACTORY = None
        rules_trace._TICK_TRACE_CACHE.clear()
        rules_dataflow.clear_cache()

    ran = [r for r in rows if r[1] == "ok"]
    skipped = [r for r in rows if r[1] == "skipped"]
    if json_out:
        print(_json.dumps({
            "version": core.ANALYSIS_VERSION,
            "mode": "budget",
            "budget_seconds": float(seconds),
            "rules": [
                {
                    "rule": rid, "status": status,
                    "seconds": None if dt is None else round(dt, 3),
                    "findings": n,
                }
                for rid, status, dt, n in rows
            ],
            "finding_count": len(findings),
            "findings": [f.to_dict() for f in findings],
        }, indent=1))
    else:
        for rid, status, dt, n in rows:
            clock = "      -" if dt is None else f"{dt:7.2f}s"
            extra = "" if n is None else f"  {n} finding(s)"
            print(f"{rid:30s} {status:8s} {clock}{extra}")
        for f in findings:
            print(f"{f.rule}: {f.location()}: {f.message}")
        print(
            f"budget {float(seconds):.0f}s: {len(ran)} rule(s) ran, "
            f"{len(skipped)} skipped, {len(findings)} finding(s) at "
            f"flagship shapes, analysis version "
            f"{core.ANALYSIS_VERSION}",
            file=sys.stderr,
        )
        if skipped:
            print(
                "skipped (budget exhausted): "
                + ", ".join(r[0] for r in skipped),
                file=sys.stderr,
            )
    return min(len(findings), cli.EXIT_CAP)
