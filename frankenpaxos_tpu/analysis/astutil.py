"""Shared AST-walking helpers for the static-analysis rules.

Every matcher that used to be copy-pasted across the four lint test
files (``tests/test_donation_lint.py``, ``test_telemetry_lint.py``,
``test_fault_lint.py``, ``test_kernel_lint.py``) lives here exactly
once: file enumeration, ``@jax.jit`` decorator recognition, ``*State``
parameter detection, dataclass field extraction, and the
``dispatch("plane", ...)`` literal scraper. The rule modules
(``rules_ast.py`` / ``rules_trace.py``) and the thin test wrappers all
import from here.
"""

from __future__ import annotations

import ast
import functools
import pathlib
from typing import Dict, Iterable, List, Sequence, Set, Tuple

PKG_ROOT = pathlib.Path(__file__).resolve().parent.parent
REPO_ROOT = PKG_ROOT.parent

# Function names whose bodies run INSIDE the compiled scan (subject to
# trace-purity constraints).
IN_GRAPH_FUNCS = ("tick", "run_ticks", "step")


def py_files(base: pathlib.Path) -> List[pathlib.Path]:
    """All ``*.py`` files under ``base``, excluding ``__pycache__``."""
    return sorted(
        p for p in base.rglob("*.py") if "__pycache__" not in p.parts
    )


def batched_files(root: pathlib.Path) -> List[pathlib.Path]:
    """The ``tpu/*_batched.py`` backend modules under a package root."""
    return sorted((root / "tpu").glob("*_batched.py"))


@functools.lru_cache(maxsize=None)
def _parse_cached(path: str, mtime: float) -> ast.Module:
    p = pathlib.Path(path)
    return ast.parse(p.read_text(), filename=path)


def parse_file(path: pathlib.Path) -> ast.Module:
    """Parse ``path``, cached on (path, mtime) so one CLI run parses
    each file once even when many rules visit it."""
    return _parse_cached(str(path), path.stat().st_mtime)


def is_jax_jit(node: ast.AST) -> bool:
    """Matches the ``jax.jit`` attribute expression."""
    return (
        isinstance(node, ast.Attribute)
        and node.attr == "jit"
        and isinstance(node.value, ast.Name)
        and node.value.id == "jax"
    )


def jit_decorator_info(dec: ast.AST) -> Tuple[bool, bool]:
    """(is_jit, has_donate) for one decorator expression, matching
    ``@jax.jit``, ``@functools.partial(jax.jit, ...)`` /
    ``@partial(jax.jit, ...)``, and ``@jax.jit(...)`` shapes."""
    if is_jax_jit(dec):
        return True, False
    if isinstance(dec, ast.Call):
        callee = dec.func
        is_partial = (
            isinstance(callee, ast.Attribute) and callee.attr == "partial"
        ) or (isinstance(callee, ast.Name) and callee.id == "partial")
        if is_partial and dec.args and is_jax_jit(dec.args[0]):
            has_donate = any(
                kw.arg in ("donate_argnums", "donate_argnames")
                for kw in dec.keywords
            )
            return True, has_donate
        if is_jax_jit(callee):
            has_donate = any(
                kw.arg in ("donate_argnums", "donate_argnames")
                for kw in dec.keywords
            )
            return True, has_donate
    return False, False


def threads_state(func: ast.FunctionDef) -> bool:
    """True iff some parameter annotation names a ``*State`` dataclass
    (or, for unannotated entry points, the repo-wide convention names
    the threaded parameter ``state``)."""
    for arg in func.args.args + func.args.posonlyargs + func.args.kwonlyargs:
        ann = arg.annotation
        if ann is None:
            continue
        if "State" in ast.unparse(ann):
            return True
    return any(
        a.arg == "state" for a in func.args.args + func.args.posonlyargs
    )


def classes_with_suffix(
    tree: ast.Module, suffix: str
) -> List[ast.ClassDef]:
    """ClassDef nodes whose names end with ``suffix`` (``"State"`` /
    ``"Config"`` dataclasses by repo convention)."""
    return [
        node
        for node in ast.walk(tree)
        if isinstance(node, ast.ClassDef) and node.name.endswith(suffix)
    ]


def ann_fields(cls: ast.ClassDef) -> Dict[str, str]:
    """Annotated dataclass fields of ``cls``: name -> unparsed
    annotation text."""
    return {
        stmt.target.id: ast.unparse(stmt.annotation)
        for stmt in cls.body
        if isinstance(stmt, ast.AnnAssign)
        and isinstance(stmt.target, ast.Name)
    }


def functions_named(
    tree: ast.Module, names: Sequence[str]
) -> List[ast.FunctionDef]:
    """All (possibly nested) FunctionDefs in ``tree`` with a name in
    ``names``."""
    return [
        n
        for n in ast.walk(tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        and n.name in names
    ]


def module_functions(tree: ast.Module) -> Dict[str, ast.FunctionDef]:
    """name -> FunctionDef for every function defined in ``tree``
    (nested defs included; later definitions win, matching runtime
    shadowing)."""
    out: Dict[str, ast.FunctionDef] = {}
    for n in ast.walk(tree):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out[n.name] = n
    return out


def import_aliases(tree: ast.Module) -> Dict[str, str]:
    """Local alias -> dotted module path for every module import
    (``import x.y as z`` and ``from x import y [as z]`` both map the
    bound name to the module/attribute path)."""
    aliases: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                aliases[a.asname or a.name.split(".")[0]] = a.name
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                aliases[a.asname or a.name] = f"{node.module}.{a.name}"
    return aliases


def called_names(func: ast.AST) -> Set[Tuple[str, str]]:
    """(base, name) pairs for every call inside ``func``'s body: a bare
    ``helper(...)`` call yields ``("", "helper")``; ``mod.helper(...)``
    yields ``("mod", "helper")``. Deeper attribute chains keep only the
    innermost base name."""
    out: Set[Tuple[str, str]] = set()
    for node in ast.walk(func):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Name):
            out.add(("", f.id))
        elif isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
            out.add((f.value.id, f.attr))
    return out


def dispatch_tables(tree: ast.Module) -> Dict[str, List[Tuple[str, str]]]:
    """SWITCH TABLES: dict literals bound at module or class level
    whose values reference functions — the ``HANDLERS = {...}`` /
    ``HANDLERS[kind](x)`` dispatch idiom a call-graph walk cannot see
    through a direct call edge. Returns table name -> list of
    ``(base, name)`` callee refs (the :func:`called_names` shape) for
    every Name / ``mod.attr`` value in the dict; non-reference values
    (literals, lambdas, comprehensions) are skipped. Only module- and
    class-level bindings count — a dict local to one function is that
    function's business, and matching it repo-wide by bare name would
    drag unreachable helpers into the host-sync frontier."""
    out: Dict[str, List[Tuple[str, str]]] = {}
    scopes = [tree.body] + [
        n.body for n in tree.body if isinstance(n, ast.ClassDef)
    ]
    for node in (stmt for body in scopes for stmt in body):
        if isinstance(node, ast.AnnAssign):
            targets = [node.target]
            value = node.value
        elif isinstance(node, ast.Assign):
            targets = node.targets
            value = node.value
        else:
            continue
        if not isinstance(value, ast.Dict):
            continue
        refs: List[Tuple[str, str]] = []
        for v in value.values:
            if isinstance(v, ast.Name):
                refs.append(("", v.id))
            elif isinstance(v, ast.Attribute) and isinstance(
                v.value, ast.Name
            ):
                refs.append((v.value.id, v.attr))
        if not refs:
            continue
        for target in targets:
            if isinstance(target, ast.Name):
                out.setdefault(target.id, []).extend(refs)
    return out


def dispatched_plane_names(tree: ast.Module) -> Set[str]:
    """Literal plane names passed to a ``*.dispatch(...)`` call."""
    names: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        is_dispatch = (
            isinstance(func, ast.Attribute) and func.attr == "dispatch"
        ) or (isinstance(func, ast.Name) and func.id == "dispatch")
        if not is_dispatch or not node.args:
            continue
        first = node.args[0]
        if isinstance(first, ast.Constant) and isinstance(first.value, str):
            names.add(first.value)
    return names


def attribute_reads(trees: Iterable[ast.Module]) -> Set[str]:
    """Every attribute name read (Load context) across ``trees``."""
    reads: Set[str] = set()
    for tree in trees:
        for node in ast.walk(tree):
            if isinstance(node, ast.Attribute) and isinstance(
                node.ctx, ast.Load
            ):
                reads.add(node.attr)
    return reads


def consumed_attribute_reads(trees: Iterable[ast.Module]) -> Set[str]:
    """Attribute names that are genuinely CONSUMED somewhere in
    ``trees`` — like :func:`attribute_reads`, except that a read of
    field ``f`` appearing inside the ``f=...`` keyword of a
    ``replace(...)`` / ``*State(...)`` update does not count: a field
    that only ever feeds its own next value (``replace(state,
    acc=state.acc + 1)``) is a dead write nobody observes."""
    excluded: Set[int] = set()
    for tree in trees:
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            is_update = (
                (isinstance(f, ast.Attribute) and f.attr == "replace")
                or (isinstance(f, ast.Name) and f.id == "replace")
                or (
                    isinstance(f, ast.Name) and f.id.endswith("State")
                )
            )
            if not is_update:
                continue
            for kw in node.keywords:
                if kw.arg is None:
                    continue
                for sub in ast.walk(kw.value):
                    if (
                        isinstance(sub, ast.Attribute)
                        and sub.attr == kw.arg
                    ):
                        excluded.add(id(sub))
    reads: Set[str] = set()
    for tree in trees:
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Attribute)
                and isinstance(node.ctx, ast.Load)
                and id(node) not in excluded
            ):
                reads.add(node.attr)
    return reads
