"""Dataflow-layer rules: semantic checks over the traced tick jaxpr.

Four rules built on :mod:`frankenpaxos_tpu.analysis.dataflow`:

* ``prng-stream-lineage`` — every random draw inside a tick descends
  from exactly one declared salt family (fault / workload / lifecycle
  / backend), no key value feeds two independent draws, and no key is
  minted from non-key data.
* ``prng-salt-disjoint`` — the declared salt-family constants are
  pairwise disjoint under the fold-in arithmetic ACTUALLY traced: the
  observed fold constants each land inside exactly one family's
  private interval.
* ``state-dead-write-reachable`` — reaching definitions over State
  leaves: a leaf the tick writes that no jaxpr path carries (across
  any number of ticks) to a telemetry feed, a traced invariant, or a
  host-read output is dead HBM traffic.
* ``donation-hazard`` — a donated input State leaf consumed after its
  aliased output has been produced is a latent use-after-donate.

All four trace each backend's tick ONCE (with the fault / workload /
lifecycle plans structurally active, so the salt folds appear in the
jaxpr) and share the linearized graph; the work is pure Python graph
walking, cheap enough for the default lint leg. Engine tests and the
dataflow teeth tests point the rules at fixture modules via
``Context.dataflow_targets`` instead of the real backend registry.
"""

from __future__ import annotations

import ast
import dataclasses
import inspect
from typing import Dict, List, Optional, Sequence, Tuple

from frankenpaxos_tpu.analysis import astutil, dataflow
from frankenpaxos_tpu.analysis.core import Context, Finding, rule
from frankenpaxos_tpu.analysis import rules_trace as _rt

# Declared salt families: name -> base constant. The "backend" family
# is implicit — a draw with NO family marker on its fold path belongs
# to the backend's own per-plane stream (small fold constants below
# dataflow.FAMILY_MIN).
def declared_families() -> Dict[str, int]:
    from frankenpaxos_tpu.tpu import faults, lifecycle, workload

    return {
        "fault": faults.FAULT_SALT,
        "workload": workload.WORKLOAD_SALT,
        "lifecycle": lifecycle.LIFECYCLE_SALT,
    }


# Donated leaves smaller than this (elements) are control-plane
# scalars / histograms / per-register rings whose post-production
# reads are delta computations (``lat_hist - state.lat_hist``, the
# telemetry-delta idiom every backend uses) on tiny buffers; the
# hazard the rule hunts is a LARGE donated data plane consumed after
# its replacement exists. 256 clears the repo-wide idioms (lat_hist
# is 64 bins, the caspaxos bit-issue ring is G x 32 = 128) while any
# real [G, W] protocol plane is thousands of elements.
DONATION_MIN_ELEMS = 256


# ---------------------------------------------------------------------------
# Shared per-target trace cache
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class _Traced:
    name: str
    mod: object
    cfg: object
    graph: dataflow.Graph
    leaf_names: List[str]
    leaf_sizes: List[int]
    leaf_in_ids: List[int]
    leaf_out_ids: List[int]
    key_id: int
    draws: List[dataflow.Draw]
    prov: Dict[int, dataflow.KeyProv]


_GRAPH_CACHE: Dict[Tuple[str, int], _Traced] = {}


def _plan_kwargs(mod) -> dict:
    """Plans structurally active so the salt-family folds (and the
    workload/lifecycle state planes) appear in the traced jaxpr."""
    from frankenpaxos_tpu.tpu.faults import FaultPlan
    from frankenpaxos_tpu.tpu.workload import WorkloadPlan

    kw: dict = {}
    params = inspect.signature(mod.analysis_config).parameters
    if "faults" in params:
        kw["faults"] = FaultPlan(traced=True)
    if "workload" in params:
        kw["workload"] = WorkloadPlan(arrival="constant", rate=1.0)
    if "lifecycle" in params:
        from frankenpaxos_tpu.tpu.lifecycle import LifecyclePlan

        kw["lifecycle"] = LifecyclePlan(sessions=8, resubmit_rate=0.1)
    return kw


def _targets(ctx: Context) -> List[Tuple[str, object]]:
    if ctx.dataflow_targets is not None:
        out = []
        for entry in ctx.dataflow_targets:
            if isinstance(entry, tuple):
                out.append(entry)
            else:
                out.append(
                    (entry.__name__.rsplit(".", 1)[-1], entry)
                )
        return out
    if not ctx.importable:
        return []
    return [(b, _rt._module(b)) for b in _rt._selected(ctx)]


def _traced(name: str, mod) -> _Traced:
    ck = (name, id(mod))
    if ck in _GRAPH_CACHE:
        return _GRAPH_CACHE[ck]
    _rt._jax_cache_setup()
    import jax
    import jax.numpy as jnp

    kw = _plan_kwargs(mod)
    if _rt.CFG_FACTORY is not None and name in _rt.BACKENDS:
        cfg = _rt.CFG_FACTORY(name, **kw)
    else:
        cfg = mod.analysis_config(**kw)
    state = mod.init_state(cfg)
    leaves_kp = jax.tree_util.tree_flatten_with_path(state)[0]
    leaf_names = [
        jax.tree_util.keystr(kp).lstrip(".") for kp, _ in leaves_kp
    ]
    leaf_sizes = [int(getattr(v, "size", 1)) for _, v in leaves_kp]
    closed = jax.make_jaxpr(
        lambda s, t, k: mod.tick(cfg, s, t, k)
    )(state, jnp.zeros((), jnp.int32), jax.random.PRNGKey(0))
    g = dataflow.linearize(closed)
    n = len(leaf_names)
    assert len(g.outvar_ids) == n, (
        f"{name}: tick must return exactly the State "
        f"({n} leaves, traced {len(g.outvar_ids)} outputs)"
    )
    key_id = g.invar_ids[n + 1]
    draws, prov = dataflow.key_lineage(g, key_id)
    t = _Traced(
        name=name, mod=mod, cfg=cfg, graph=g, leaf_names=leaf_names,
        leaf_sizes=leaf_sizes, leaf_in_ids=list(g.invar_ids[:n]),
        leaf_out_ids=list(g.outvar_ids), key_id=key_id, draws=draws,
        prov=prov,
    )
    _GRAPH_CACHE[ck] = t
    return t


def clear_cache() -> None:
    """Budget mode swaps the config factory: drop memoized graphs."""
    _GRAPH_CACHE.clear()


def _family_of(c: int, fams: Dict[str, int]) -> Optional[str]:
    for fam, base in fams.items():
        if base <= c < base + dataflow.FAMILY_SPAN:
            return fam
    return None


# ---------------------------------------------------------------------------
# Rule: prng-stream-lineage
# ---------------------------------------------------------------------------


@rule(
    "prng-stream-lineage",
    "dataflow",
    "every traced random draw descends from exactly one declared salt "
    "family, no key value feeds two independent draws, and no key is "
    "minted from non-key data inside the tick",
)
def check_prng_lineage(ctx: Context) -> List[Finding]:
    fams = declared_families()
    out: List[Finding] = []
    for name, mod in _targets(ctx):
        tr = _traced(name, mod)
        foreign_n = 0
        for d in tr.draws:
            if d.prov.foreign:
                out.append(Finding(
                    rule="prng-stream-lineage", path=name, line=0,
                    message=(
                        "a random draw uses a key minted inside the "
                        "tick from non-key data (not derived from the "
                        "tick's key argument) — its stream is fixed "
                        "across seeds and correlated with nothing the "
                        "harness controls"
                    ),
                    key=f"{name}:foreign:{foreign_n}",
                ))
                foreign_n += 1
                continue
            d_fams = sorted({
                f for f in (
                    _family_of(c, fams) for c in d.prov.markers
                ) if f
            })
            undeclared = sorted(
                c for c in d.prov.markers
                if _family_of(c, fams) is None
            )
            if len(d_fams) >= 2:
                out.append(Finding(
                    rule="prng-stream-lineage", path=name, line=0,
                    message=(
                        f"draw at {d.prov.describe()} folds salts "
                        f"from {len(d_fams)} families "
                        f"({', '.join(d_fams)}) — a stream must "
                        "belong to exactly one"
                    ),
                    key=f"{name}:mixed:{d.prov.describe()}",
                ))
            for c in undeclared:
                out.append(Finding(
                    rule="prng-stream-lineage", path=name, line=0,
                    message=(
                        f"draw at {d.prov.describe()} folds "
                        f"{c:#x}, a family-sized salt that belongs "
                        "to no declared family (fault/workload/"
                        "lifecycle) — declare it or fold a "
                        "family base first"
                    ),
                    key=f"{name}:undeclared:{c:#x}",
                ))
        # Stream reuse: the same exact key value feeding two draws
        # that can both execute.
        by_id: Dict[tuple, List[dataflow.Draw]] = {}
        for d in tr.draws:
            if d.prov.widened or d.prov.foreign:
                continue
            by_id.setdefault(d.prov.identity(), []).append(d)
        for ident, group in sorted(by_id.items(), key=str):
            if len(group) < 2:
                continue
            live_pairs = [
                (a, b)
                for i, a in enumerate(group)
                for b in group[i + 1:]
                if not dataflow.branches_exclusive(a.branch, b.branch)
            ]
            if live_pairs:
                p = group[0].prov
                out.append(Finding(
                    rule="prng-stream-lineage", path=name, line=0,
                    message=(
                        f"key {p.describe()} feeds {len(group)} "
                        "independent draws — stream reuse makes "
                        '"independent" randomness correlated '
                        "(split or fold a fresh salt per draw)"
                    ),
                    key=f"{name}:reuse:{p.describe()}",
                ))
    return out


# ---------------------------------------------------------------------------
# Rule: prng-salt-disjoint
# ---------------------------------------------------------------------------


@rule(
    "prng-salt-disjoint",
    "dataflow",
    "the declared salt-family constants are pairwise disjoint under "
    "the fold-in arithmetic actually traced (every observed "
    "family-sized fold constant lands inside exactly one family's "
    "private interval)",
)
def check_salt_disjoint(ctx: Context) -> List[Finding]:
    fams = declared_families()
    out: List[Finding] = []
    span = dataflow.FAMILY_SPAN
    # Declared intervals pairwise disjoint — from the constants the
    # modules export, not their comments.
    items = sorted(fams.items(), key=lambda kv: kv[1])
    for (fa, ba), (fb, bb) in zip(items, items[1:]):
        if ba + span > bb:
            out.append(Finding(
                rule="prng-salt-disjoint",
                path="frankenpaxos_tpu/tpu", line=0,
                message=(
                    f"declared salt families overlap: {fa} "
                    f"[{ba:#x}, {ba + span:#x}) reaches into {fb} "
                    f"base {bb:#x}"
                ),
                key=f"declared:{fa}:{fb}",
            ))
    # Observed fold constants: every literal random_fold_in operand in
    # every traced tick. Family-sized constants must sit inside one
    # declared interval; an offset escaping its family's span can
    # collide with the next family.
    for name, mod in _targets(ctx):
        tr = _traced(name, mod)
        seen = set()
        for n in tr.graph.nodes:
            if n.prim != "random_fold_in" or len(n.invars) < 2:
                continue
            lit = tr.graph.literals.get(n.invars[1])
            if lit is None:
                continue
            c = int(lit)
            if c < dataflow.FAMILY_MIN or c in seen:
                continue
            seen.add(c)
            fam = _family_of(c, fams)
            if fam is None:
                below = [
                    (f, b) for f, b in fams.items() if b <= c
                ]
                if below:
                    f, b = max(below, key=lambda kv: kv[1])
                    out.append(Finding(
                        rule="prng-salt-disjoint", path=name, line=0,
                        message=(
                            f"traced fold constant {c:#x} is "
                            f"{c - b} past the {f} family base "
                            f"{b:#x} — offsets must stay below the "
                            f"family span ({span}) or streams from "
                            "adjacent families collide"
                        ),
                        key=f"{name}:escape:{c:#x}",
                    ))
                else:
                    out.append(Finding(
                        rule="prng-salt-disjoint", path=name, line=0,
                        message=(
                            f"traced fold constant {c:#x} is "
                            "family-sized but below every declared "
                            "family base — declare the family"
                        ),
                        key=f"{name}:undeclared:{c:#x}",
                    ))
    return out


# ---------------------------------------------------------------------------
# Rule: state-dead-write-reachable
# ---------------------------------------------------------------------------

# Host-side surfaces whose attribute reads count as observation sinks.
# Deliberately EXCLUDES the tpu/ package itself: in-graph consumption
# is what the jaxpr reachability below computes exactly, and counting
# a tick's own reads would re-admit the self-feed blind spot the
# retired AST rule had.
_HOST_GLOBS = (
    ("", "bench.py"),
    ("scripts", "*.py"),
    ("frankenpaxos_tpu/harness", "*.py"),
    ("frankenpaxos_tpu/monitoring", "*.py"),
    ("frankenpaxos_tpu/viz", "*.py"),
)

# Host-facing functions INSIDE the tpu package: ``stats`` (backend
# bench summaries) and ``summary`` (the workload/lifecycle host
# roll-ups) run in Python on fetched state, so their reads are real
# sinks even though their modules otherwise hold in-graph code.
_HOST_FUNCS = ("stats", "summary")

_HOST_READS_CACHE: Dict[str, frozenset] = {}


def _host_reads(ctx: Context) -> frozenset:
    key = str(ctx.repo)
    if key in _HOST_READS_CACHE:
        return _HOST_READS_CACHE[key]
    trees = []
    for sub, pat in _HOST_GLOBS:
        base = ctx.repo / sub if sub else ctx.repo
        if not base.exists():
            continue
        paths = [base] if base.is_file() else sorted(base.glob(pat))
        for p in paths:
            if p.suffix == ".py" and p.exists():
                trees.append(astutil.parse_file(p))
    for p in astutil.py_files(ctx.root):
        tree = astutil.parse_file(p)
        host_fns = [
            n for n in ast.walk(tree)
            if isinstance(n, ast.FunctionDef) and n.name in _HOST_FUNCS
        ]
        if host_fns:
            trees.append(ast.Module(body=host_fns, type_ignores=[]))
    reads = frozenset(astutil.consumed_attribute_reads(trees))
    _HOST_READS_CACHE[key] = reads
    return reads


def _invariant_leaves(tr: _Traced) -> int:
    """Bitmask of State-leaf indices the backend's traced
    ``check_invariants`` actually consumes."""
    if not hasattr(tr.mod, "check_invariants"):
        return 0
    import jax
    import jax.numpy as jnp

    state = tr.mod.init_state(tr.cfg)
    try:
        closed = jax.make_jaxpr(
            lambda s, t: tr.mod.check_invariants(tr.cfg, s, t)
        )(state, jnp.zeros((), jnp.int32))
    except Exception:
        return 0
    g = dataflow.linearize(closed)
    n = len(tr.leaf_names)
    consumed = g.consumers()
    outs = set(g.outvar_ids)
    mask = 0
    for j in range(n):
        vid = g.invar_ids[j]
        if consumed.get(vid) or vid in outs:
            mask |= 1 << j
    return mask


@rule(
    "state-dead-write-reachable",
    "dataflow",
    "reaching definitions over State leaves: a leaf the tick writes "
    "that no jaxpr path carries (across ticks) to telemetry, a traced "
    "invariant, or a host-read output is dead HBM traffic",
)
def check_dead_write_reachable(ctx: Context) -> List[Finding]:
    out: List[Finding] = []
    for name, mod in _targets(ctx):
        tr = _traced(name, mod)
        n = len(tr.leaf_names)
        src = dataflow.reach_analysis(tr.graph, tr.leaf_in_ids)
        adj = {
            j: src.get(tr.leaf_out_ids[j], 0) for j in range(n)
        }
        live = _invariant_leaves(tr)
        host = (
            _host_reads(ctx)
            if ctx.is_real_tree() and ctx.dataflow_targets is None
            else frozenset()
        )
        for j, lname in enumerate(tr.leaf_names):
            parts = lname.replace("[", ".").replace("]", "").split(".")
            top, last = parts[0], parts[-1]
            # Telemetry is drained by the host scrape every chunk;
            # the whole subtree is an observation sink.
            if top == "telemetry":
                live |= 1 << j
            elif last in host or top in (host & {"checkpoint"}):
                live |= 1 << j
        live = dataflow.closure(adj, live, n)
        for j, lname in enumerate(tr.leaf_names):
            if live >> j & 1:
                continue
            if tr.leaf_sizes[j] == 0:
                continue  # structurally-off plan leaves
            if tr.leaf_out_ids[j] == tr.leaf_in_ids[j]:
                continue  # pass-through, never written
            out.append(Finding(
                rule="state-dead-write-reachable", path=name, line=0,
                message=(
                    f"State leaf {lname!r} is written every tick but "
                    "no dataflow path carries it to telemetry, a "
                    "traced invariant, or any host-read output — "
                    "dead HBM traffic on every bandwidth-bound sweep "
                    "(drop it, or read it)"
                ),
                key=f"{name}:{lname}",
            ))
    return out


# ---------------------------------------------------------------------------
# Rule: donation-hazard
# ---------------------------------------------------------------------------


@rule(
    "donation-hazard",
    "dataflow",
    "no donated input State leaf is consumed after its aliased output "
    "has been produced within the tick (latent use-after-donate once "
    "XLA reuses the buffer in place)",
)
def check_donation_hazard(ctx: Context) -> List[Finding]:
    out: List[Finding] = []
    for name, mod in _targets(ctx):
        tr = _traced(name, mod)
        producers = tr.graph.producers()
        consumers = tr.graph.consumers()
        for j, lname in enumerate(tr.leaf_names):
            a, o = tr.leaf_in_ids[j], tr.leaf_out_ids[j]
            if a == o:
                continue  # pass-through: no fresh buffer to alias
            if tr.leaf_sizes[j] < DONATION_MIN_ELEMS:
                continue  # control-plane scalars/rings (see const)
            p = producers.get(o)
            if p is None:
                continue
            late = [u for u in consumers.get(a, ()) if u > p]
            if late:
                prim = tr.graph.nodes[late[-1]].prim
                out.append(Finding(
                    rule="donation-hazard", path=name, line=0,
                    message=(
                        f"donated State leaf {lname!r} "
                        f"({tr.leaf_sizes[j]} elems) is consumed by "
                        f"{len(late)} equation(s) (last: {prim}) "
                        "AFTER its aliased output is produced — a "
                        "latent use-after-donate once XLA writes the "
                        "output in place (reorder the update so every "
                        "read of the old value precedes the new one)"
                    ),
                    key=f"{name}:{lname}",
                ))
    return out
