"""Unified static-analysis subsystem for the batched backends.

Three layers behind one rule registry (``core.RULES``):

* **AST layer** (``rules_ast.py``) — the repo-wide source contracts:
  buffer donation on every jitted ``*State`` entry point, the telemetry
  carry/record contract, the FaultPlan accept/validate/apply contract,
  Pallas containment + kernel-registry coverage, and transitive
  host-sync purity of every tick body.
* **Trace layer** (``rules_trace.py``) — jits every backend at its
  ``analysis_config()`` and inspects the artifact: jaxpr dtype-policy
  (no unallowlisted narrow->wide conversions), compiled-HLO donation
  effectiveness (``input_output_alias`` covers the State buffers), and
  a retrace guard (equal configs hit the jit cache).
* **Dataflow layer** (``rules_dataflow.py`` over ``dataflow.py``'s
  abstract interpreter) — semantic facts inside the traced tick
  jaxpr: PRNG key lineage (one declared salt family per draw, no
  stream reuse, salt disjointness under the traced fold arithmetic),
  reaching-definitions dead-write detection over State leaves, and
  donation use-after-alias ordering.

Diagnostics are structured (:class:`~.core.Finding`: rule id,
file:line, message, stable allowlist key); every exemption lives in
``allowlists.py`` with a mandatory reason, and stale entries are
findings themselves. CLI::

    python -m frankenpaxos_tpu.analysis [--rule ID]
        [--layer ast|trace|dataflow] [--backends a,b] [--json]
        [--list] [--budget SECONDS]

Exit code = finding count. The tier-1 lint tests
(``tests/test_*_lint.py``) are thin wrappers invoking rules by id, so
``pytest -m lint`` and the CLI enforce the same registry.
"""

from frankenpaxos_tpu.analysis.core import (  # noqa: F401
    ANALYSIS_VERSION,
    Context,
    Finding,
    Report,
    Rule,
    RULES,
    run,
)


def rule_count() -> int:
    """Number of registered rules (imports the rule modules)."""
    from frankenpaxos_tpu.analysis import (  # noqa: F401
        rules_ast,
        rules_dataflow,
        rules_trace,
    )

    return len(RULES)
