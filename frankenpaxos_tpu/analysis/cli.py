"""Command-line entry point: ``python -m frankenpaxos_tpu.analysis``.

Runs the full rule registry (or a ``--rule`` / ``--layer`` /
``--backends`` selection) over the repository and exits with the
finding count (0 = clean; capped at 100 so the code never wraps mod
256). ``--json`` emits the structured report on stdout for CI
artifacts; ``scripts/lint.sh`` is a thin wrapper around this module.

``--budget SECONDS`` is a separate opt-in mode (never part of the
default path): it re-runs the trace + dataflow layers at each
backend's FLAGSHIP shape with per-rule wall-clock accounting and a
skipped-rules report — see ``analysis/budget.py``.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

EXIT_CAP = 100


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m frankenpaxos_tpu.analysis",
        description=(
            "Static analysis for the batched backends: AST contract "
            "rules, jaxpr/HLO trace rules, and jaxpr dataflow rules. "
            "Exit code = finding count."
        ),
    )
    parser.add_argument(
        "--rule",
        action="append",
        metavar="ID",
        help="run only this rule id (repeatable; see --list)",
    )
    parser.add_argument(
        "--layer",
        choices=("ast", "trace", "dataflow"),
        action="append",
        help="run only this layer (repeatable; default: all three)",
    )
    parser.add_argument(
        "--backends",
        metavar="A,B,...",
        help="comma-separated backend subset for the trace layer",
    )
    parser.add_argument(
        "--json",
        action="store_true",
        help="emit the structured report as JSON on stdout",
    )
    parser.add_argument(
        "--list",
        action="store_true",
        help="list registered rules (grouped by layer) and exit 0",
    )
    parser.add_argument(
        "--budget",
        type=float,
        metavar="SECONDS",
        help=(
            "opt-in flagship-shape mode: run the trace + dataflow "
            "layers at production shapes under this wall-clock "
            "budget, with per-rule timings and a skipped-rules "
            "report (bypasses the default lint path entirely)"
        ),
    )
    args = parser.parse_args(argv)

    from frankenpaxos_tpu.analysis import core

    # Import for side effects: rule registration (before --list).
    from frankenpaxos_tpu.analysis import (  # noqa: F401
        rules_ast,
        rules_dataflow,
        rules_trace,
    )

    if args.list:
        for layer in ("ast", "trace", "dataflow"):
            rules = sorted(
                (r for r in core.RULES.values() if r.layer == layer),
                key=lambda r: r.id,
            )
            print(f"[{layer}] ({len(rules)} rules)")
            for r in rules:
                print(f"  {r.id:28s} {r.doc}")
        return 0

    if args.budget is not None:
        from frankenpaxos_tpu.analysis import budget

        backends = None
        if args.backends:
            backends = tuple(
                b.strip() for b in args.backends.split(",") if b.strip()
            )
        return budget.run_budget(
            args.budget, backends=backends, json_out=args.json
        )

    ctx = core.Context()
    if args.backends:
        ctx.backends = tuple(
            b.strip() for b in args.backends.split(",") if b.strip()
        )
    layers = (
        tuple(args.layer) if args.layer else ("ast", "trace", "dataflow")
    )
    try:
        report = core.run(rule_ids=args.rule, layers=layers, ctx=ctx)
    except KeyError as e:
        parser.error(str(e))  # unknown rule/backend: usage error, exit 2

    if args.json:
        print(json.dumps(report.to_dict(), indent=1))
    else:
        if report.findings:
            print(report.format())
        print(
            f"{len(report.findings)} finding(s) from "
            f"{len(report.rules_run)} rule(s) "
            f"({len(report.allowlisted)} allowlisted), analysis "
            f"version {report.version}",
            file=sys.stderr,
        )
    return min(len(report.findings), EXIT_CAP)


if __name__ == "__main__":
    sys.exit(main())
