"""Command-line entry point: ``python -m frankenpaxos_tpu.analysis``.

Runs the full rule registry (or a ``--rule`` / ``--layer`` /
``--backends`` selection) over the repository and exits with the
finding count (0 = clean; capped at 100 so the code never wraps mod
256). ``--json`` emits the structured report on stdout for CI
artifacts; ``scripts/lint.sh`` is a thin wrapper around this module.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

EXIT_CAP = 100


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m frankenpaxos_tpu.analysis",
        description=(
            "Static analysis for the batched backends: AST contract "
            "rules + jaxpr/HLO trace rules. Exit code = finding count."
        ),
    )
    parser.add_argument(
        "--rule",
        action="append",
        metavar="ID",
        help="run only this rule id (repeatable; see --list)",
    )
    parser.add_argument(
        "--layer",
        choices=("ast", "trace"),
        action="append",
        help="run only this layer (repeatable; default: both)",
    )
    parser.add_argument(
        "--backends",
        metavar="A,B,...",
        help="comma-separated backend subset for the trace layer",
    )
    parser.add_argument(
        "--json",
        action="store_true",
        help="emit the structured report as JSON on stdout",
    )
    parser.add_argument(
        "--list",
        action="store_true",
        help="list registered rules and exit 0",
    )
    args = parser.parse_args(argv)

    from frankenpaxos_tpu.analysis import core

    # Import for side effects: rule registration (before --list).
    from frankenpaxos_tpu.analysis import rules_ast, rules_trace  # noqa: F401

    if args.list:
        for r in sorted(core.RULES.values(), key=lambda r: (r.layer, r.id)):
            print(f"{r.id:28s} [{r.layer}]  {r.doc}")
        return 0

    ctx = core.Context()
    if args.backends:
        ctx.backends = tuple(
            b.strip() for b in args.backends.split(",") if b.strip()
        )
    layers = tuple(args.layer) if args.layer else ("ast", "trace")
    try:
        report = core.run(rule_ids=args.rule, layers=layers, ctx=ctx)
    except KeyError as e:
        parser.error(str(e))  # unknown rule/backend: usage error, exit 2

    if args.json:
        print(json.dumps(report.to_dict(), indent=1))
    else:
        if report.findings:
            print(report.format())
        print(
            f"{len(report.findings)} finding(s) from "
            f"{len(report.rules_run)} rule(s) "
            f"({len(report.allowlisted)} allowlisted), analysis "
            f"version {report.version}",
            file=sys.stderr,
        )
    return min(len(report.findings), EXIT_CAP)


if __name__ == "__main__":
    sys.exit(main())
