"""Rule registry, structured diagnostics, and the analysis engine.

A :class:`Rule` is a named check over the repository: AST-layer rules
parse source files, trace-layer rules jit the batched backends at a
small config and inspect what XLA actually compiles. Every violation is
a :class:`Finding` with a stable ``key``; allowlists
(``allowlists.py``) suppress findings BY KEY and must carry a reason —
and the engine rejects stale entries (an allowlist key matching no
current raw finding becomes an ``allowlist-stale`` finding itself, so a
typo'd or outdated exemption can never silently exempt nothing).
"""

from __future__ import annotations

import dataclasses
import pathlib
from typing import Callable, Dict, List, Optional, Sequence

from frankenpaxos_tpu.analysis import astutil

# Bumped whenever a rule is added/removed or a rule's semantics change;
# recorded by bench.py for artifact provenance. 1.2: trace-donation-alias
# also compiles the sharded run_ticks wrappers (parallel/sharding.py
# registry) and requires alias coverage under a mesh; the backend
# inventory floor rose to 14 (compartmentalized). 1.4: the workload
# engine contracts — four AST rules mirroring the fault contracts
# (workload-config-field/validate/apply + workload-rate-validated on
# the plan itself) and two trace rules (trace-workload-noop: the none
# plan is all-empty state feeding zero tick equations;
# trace-workload-retrace: the traced [rate x fault-rate] sweep never
# grows the jit cache). 1.7: the crash-tolerance contracts —
# checkpoint-alias-free (the serve loop's jitted full-State snapshot
# aliases no input and carries no host callback) and
# trace-checkpoint-restore (save -> load -> restore is bit-exact and
# replays the existing compiled run_ticks with a flat jit cache).
# 1.8: trace-fleet-onecompile — a [seeds x workload x fault] fleet
# brick is one compiled executable per product mesh (flat jit cache
# across traced-rate re-sweeps) and no signed collective crosses the
# fleet axis (replica-group census) or moves state at all.
# 2.1: the performance-observatory gates — costmodel-coverage (every
# registered plane, every PACKED_PLANES entry, and the unfused
# reference tick carry stated byte/FLOP terms in ops/costmodel.py)
# and costmodel-drift (every recorded kernel microbench capture sits
# inside the model's measured/predicted envelope, no round-over-round
# ratio regression, and results/costmodel_envelope.json matches the
# in-tree model constants).
# 2.2: the elastic-capacity gates — elastic-noop (ElasticPlan.none()
# is a structural no-op: zero-sized State leaves feeding no tick
# equation) and trace-elastic-retrace (role-count resizes ride the
# traced membership scalars, so every autoscaler scale-up/down
# replays ONE compiled program; the jit cache stays flat).
# 2.3: the dependency-graph gates — depgraph-containment (packed
# adjacency bit twiddling stays inside ops/depgraph.py; consumers go
# through its helpers or jnp.where writes) and the backend-inventory
# floor rises to 15 with the bpaxos backend (the depgraph_execute
# plane's home).
# 2.4: the dataflow layer (rules_dataflow.py over dataflow.py's
# abstract interpreter): prng-stream-lineage + prng-salt-disjoint
# (key provenance through fold_in/split/random_bits — one declared
# salt family per draw, no stream reuse, declared salts disjoint
# under the traced fold arithmetic), state-dead-write-reachable
# (reaching definitions over State leaves; RETIRES the AST
# state-dead-write rule and its self-feed heuristic), and
# donation-hazard (no donated input consumed after its aliased
# output exists). The CLI gains --budget SECONDS (flagship-shape
# trace+dataflow leg with per-rule wall clocks, analysis/budget.py).
ANALYSIS_VERSION = "2.4"

# Rule id reserved for the engine's own stale-allowlist findings.
STALE_RULE = "allowlist-stale"


@dataclasses.dataclass(frozen=True)
class Finding:
    """One structured diagnostic."""

    rule: str  # rule id that produced it
    path: str  # repo-relative file (or backend name for trace rules)
    line: int  # 1-based line, 0 when the finding is not line-anchored
    message: str
    key: str  # stable id allowlist entries match against

    def location(self) -> str:
        return f"{self.path}:{self.line}" if self.line else self.path

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass(frozen=True)
class Rule:
    """One registered check. ``check(ctx)`` returns RAW findings — the
    engine applies the rule's allowlist afterwards."""

    id: str
    layer: str  # "ast" | "trace" | "dataflow"
    doc: str  # one-line description (CLI --list, README table)
    check: Callable[["Context"], List[Finding]]


@dataclasses.dataclass
class Context:
    """What rules run against. ``root`` is the package directory the
    AST rules scan (the real ``frankenpaxos_tpu/`` in production, a
    synthetic fixture tree in the engine's own tests)."""

    root: pathlib.Path = astutil.PKG_ROOT
    repo: pathlib.Path = astutil.REPO_ROOT
    # Backends the trace layer runs (None = all registered). Names must
    # match rules_trace.BACKENDS.
    backends: Optional[Sequence[str]] = None
    # Floor the backend-inventory rule enforces; fixture trees override.
    min_backends: int = 15
    # Fixture trees are not importable packages: rules that must import
    # repo modules (kernel registry introspection) skip when False.
    importable: bool = True
    # Dataflow-layer targets: None = the real backend registry; the
    # engine's own tests point the rules at importable fixture modules
    # (entries are modules, or (name, module) pairs).
    dataflow_targets: Optional[Sequence] = None

    def is_real_tree(self) -> bool:
        return self.root == astutil.PKG_ROOT


RULES: Dict[str, Rule] = {}


def rule(id: str, layer: str, doc: str):
    """Decorator registering a check function as a :class:`Rule`."""

    def register(fn: Callable[[Context], List[Finding]]):
        assert id not in RULES, f"duplicate rule id {id}"
        RULES[id] = Rule(id=id, layer=layer, doc=doc, check=fn)
        return fn

    return register


@dataclasses.dataclass
class Report:
    """Engine output: surviving findings + suppressed-by-allowlist
    findings (kept for transparency) + the rules that ran."""

    findings: List[Finding]
    allowlisted: List[dict]  # finding dict + its allowlist reason
    rules_run: List[str]
    version: str = ANALYSIS_VERSION

    def to_dict(self) -> dict:
        return {
            "version": self.version,
            "rules_run": self.rules_run,
            "finding_count": len(self.findings),
            "findings": [f.to_dict() for f in self.findings],
            "allowlisted": self.allowlisted,
        }

    def format(self) -> str:
        lines = []
        for f in self.findings:
            lines.append(f"{f.rule}: {f.location()}: {f.message}")
        return "\n".join(lines)


def run(
    rule_ids: Optional[Sequence[str]] = None,
    layers: Sequence[str] = ("ast", "trace", "dataflow"),
    ctx: Optional[Context] = None,
) -> Report:
    """Run the selected rules and apply/validate their allowlists.

    ``rule_ids=None`` runs every registered rule in ``layers``. Unknown
    rule ids raise (a CI invocation of a renamed rule must fail loudly,
    not silently check nothing). The default layer set includes
    ``dataflow`` so stale allowlist entries for dataflow-layer rules
    are examined (and rejected) by exactly the same walk as every
    other layer's.
    """
    # Import for side effects: rule registration.
    from frankenpaxos_tpu.analysis import (  # noqa: F401
        rules_ast,
        rules_dataflow,
        rules_trace,
    )
    from frankenpaxos_tpu.analysis import allowlists

    ctx = ctx or Context()
    if rule_ids is None:
        selected = [r for r in RULES.values() if r.layer in layers]
    else:
        unknown = [rid for rid in rule_ids if rid not in RULES]
        if unknown:
            raise KeyError(
                f"unknown rule id(s) {unknown}; known: {sorted(RULES)}"
            )
        selected = [RULES[rid] for rid in rule_ids]

    findings: List[Finding] = []
    suppressed: List[dict] = []
    for r in selected:
        raw = r.check(ctx)
        assert all(f.rule == r.id for f in raw), (
            f"rule {r.id} emitted findings under a different rule id"
        )
        allow = allowlists.suppressions(r.id)
        matched = set()
        for f in raw:
            if f.key in allow:
                matched.add(f.key)
                suppressed.append(
                    {**f.to_dict(), "reason": allow[f.key]}
                )
            else:
                findings.append(f)
        # Stale-allowlist hygiene: an entry that matches no raw finding
        # exempts nothing — it is a typo or a leftover from removed
        # code, and keeping it around silently erodes coverage.
        for key in sorted(set(allow) - matched):
            findings.append(
                Finding(
                    rule=STALE_RULE,
                    path="frankenpaxos_tpu/analysis/allowlists.py",
                    line=0,
                    message=(
                        f"allowlist entry {key!r} for rule {r.id!r} "
                        "matches no current finding — remove it (stale "
                        "entries silently exempt nothing)"
                    ),
                    key=f"{r.id}:{key}",
                )
            )
    # A SUPPRESS block keyed by a rule id that is not registered at all
    # (typo, or the rule was renamed) would otherwise never be examined
    # — the intended exemption doesn't apply AND nothing flags it.
    for rid in sorted(set(allowlists.SUPPRESS) - set(RULES)):
        findings.append(
            Finding(
                rule=STALE_RULE,
                path="frankenpaxos_tpu/analysis/allowlists.py",
                line=0,
                message=(
                    f"SUPPRESS block for unknown rule id {rid!r} — "
                    f"no such rule is registered (known: "
                    f"{sorted(RULES)})"
                ),
                key=f"{rid}:<unknown-rule>",
            )
        )
    return Report(
        findings=findings,
        allowlisted=suppressed,
        rules_run=[r.id for r in selected],
    )
