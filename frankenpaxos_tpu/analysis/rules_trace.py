"""Trace-layer rules: jit every batched backend at its canonical small
config (``<backend>_batched.analysis_config()``) and inspect what JAX
actually traces and XLA actually compiles — the contract surface the
AST layer structurally cannot see.

* ``trace-dtype-policy`` — walks the tick jaxpr's
  ``convert_element_type``/``iota`` equations and pins the exact
  multiset of narrow->wide signed-integer conversions per backend
  against ``allowlists.DTYPE_WIDENING`` (64-bit conversions are never
  allowed: x64 is off repo-wide). A silent int16->int32 upcast eats the
  HBM-bandwidth pass even though every AST lint still passes.
* ``trace-donation-alias`` — compiles ``run_ticks`` and checks the HLO
  ``input_output_alias`` table actually aliases every State buffer: a
  donation that fails to alias double-buffers the cluster state.
* ``trace-retrace-guard`` — calls ``run_ticks`` twice with fresh but
  EQUAL configs/states and asserts the second call hits the jit cache
  (hashability/`__eq__`/static-argnum regressions recompile every
  segment in production).
* ``trace-fused-tick`` — traces the FLAGSHIP-shaped MultiPaxos tick
  with the kernel policy engaged and asserts the hot path is exactly
  ONE ``pallas_call`` (the whole-tick megakernel): a second call means
  the tick regressed to per-plane dispatch (an HBM round trip between
  planes); zero means the megakernel silently fell back to the
  reference. The reference-mode trace is asserted pallas-free.
* ``trace-shardmap-kernel`` — the kernels x mesh composition contract
  (parallel/sharding.py): for every sharding-registry backend with
  registered planes, the SHARDED wrapper traced with the kernel policy
  engaged must contain the Pallas call(s) (shard_map actually lowered
  the kernels — zero means a silent reference fallback), the compiled
  kernels-engaged program must introduce NO signed-state collective
  beyond the <=64-element stat reductions tests/test_multichip.py
  already allowlists (a bigger one means a ShardSpec mis-declared an
  axis and shard_map is gathering state), and the reference-mode trace
  must stay pallas-free. Needs >=2 devices (scripts/lint.sh forces an
  8-virtual-device CPU host; pytest's conftest does the same).
* ``trace-fleet-onecompile`` — the fleet-axis contract
  (parallel/sharding.py product mesh): a whole [seeds x workload x
  fault] brick — per-instance traced offered rates + traced Bernoulli
  fault rates — compiles to exactly ONE executable per mesh (a
  traced-rate re-sweep keeps the fleet runner's jit cache flat), and
  the compiled program's signed collectives all stay INSIDE one fleet
  row (replica-group census over both the explicit and iota HLO
  formats) with no signed state-moving collective at all — protocol
  instances are provably independent along the fleet axis. Needs >=4
  devices (the 2-row product mesh); scripts/lint.sh forces the same
  8-virtual-device host as the pytest conftest, which covers it.

All jax imports live inside the checks so the AST layer stays
importable without jax.
"""

from __future__ import annotations

import collections
import importlib
import re
from typing import Dict, List

from frankenpaxos_tpu.analysis.core import Context, Finding, rule

# backend name -> tpu module stem. The trace layer runs each backend's
# analysis_config(); adding a backend here (and its analysis_config)
# is the entire integration cost.
BACKENDS = (
    "bpaxos",
    "caspaxos",
    "compartmentalized",
    "craq",
    "epaxos",
    "fasterpaxos",
    "fastmultipaxos",
    "fastpaxos",
    "grid",
    "horizontal",
    "mencius",
    "multipaxos",
    "scalog",
    "unreplicated",
    "vanillamencius",
)

_TICKS = 2  # run_ticks horizon for the compiled-artifact rules


def _jax_cache_setup() -> None:
    """Enable the persistent XLA compilation cache (same knob as
    tests/conftest.py) so repeated CLI/CI runs skip the backend
    compiles the donation/retrace rules trigger."""
    import os

    import jax

    cache_dir = os.environ.get(
        "FRANKENPAXOS_JAX_CACHE", "/tmp/frankenpaxos_jax_cache"
    )
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", 0.5
        )
    except Exception:
        pass  # older jax without the persistent cache: run uncached


def _module(backend: str):
    return importlib.import_module(
        f"frankenpaxos_tpu.tpu.{backend}_batched"
    )


def _selected(ctx: Context) -> List[str]:
    if ctx.backends is None:
        return list(BACKENDS)
    unknown = [b for b in ctx.backends if b not in BACKENDS]
    if unknown:
        raise KeyError(
            f"unknown backend(s) {unknown}; known: {sorted(BACKENDS)}"
        )
    return list(ctx.backends)


def _walk_eqns(jaxpr, out: list) -> None:
    """All equations of ``jaxpr`` including every nested sub-jaxpr
    (pjit/scan/while/cond bodies)."""
    for eqn in jaxpr.eqns:
        out.append(eqn)
        for v in eqn.params.values():
            vals = v if isinstance(v, (list, tuple)) else [v]
            for item in vals:
                sub = getattr(item, "jaxpr", None)
                if sub is not None:
                    _walk_eqns(sub, out)
                elif hasattr(item, "eqns"):
                    _walk_eqns(item, out)


# Default-config tick traces are shared across trace rules in one
# process (trace-dtype-policy and trace-workload-noop both want the
# SAME analysis_config() jaxpr; re-tracing a big tick body costs
# seconds per backend on a small host). Keyed by backend name; rules
# that trace a NON-default config bypass the cache.
_TICK_TRACE_CACHE: Dict[str, tuple] = {}

# Analysis-config factory override: ``analysis/budget.py`` installs a
# flagship-shape factory here (signature ``(backend, **plan_kwargs) ->
# config``) so the shared tick-trace caches — this one and the
# dataflow layer's — re-trace at production shapes during a --budget
# run. None = each backend's own analysis_config(). Installers must
# clear both caches around install/uninstall.
CFG_FACTORY = None


def _tick_closed(backend: str):
    """(closed_jaxpr, state) of ``tick`` at the backend's default
    analysis_config() (or CFG_FACTORY's shape), memoized per
    process."""
    if backend not in _TICK_TRACE_CACHE:
        import jax
        import jax.numpy as jnp

        mod = _module(backend)
        cfg = (
            CFG_FACTORY(backend)
            if CFG_FACTORY is not None
            else mod.analysis_config()
        )
        state = mod.init_state(cfg)
        closed = jax.make_jaxpr(
            lambda s, t, k: mod.tick(cfg, s, t, k)
        )(state, jnp.zeros((), jnp.int32), jax.random.PRNGKey(0))
        _TICK_TRACE_CACHE[backend] = (closed, state)
    return _TICK_TRACE_CACHE[backend]


def _tick_eqns(backend: str, cfg=None) -> list:
    import jax
    import jax.numpy as jnp

    if cfg is None:
        closed, _ = _tick_closed(backend)
    else:
        mod = _module(backend)
        state = mod.init_state(cfg)
        closed = jax.make_jaxpr(
            lambda s, t, k: mod.tick(cfg, s, t, k)
        )(state, jnp.zeros((), jnp.int32), jax.random.PRNGKey(0))
    eqns: list = []
    _walk_eqns(closed.jaxpr, eqns)
    return eqns


@rule(
    "trace-dtype-policy",
    "trace",
    "the compiled tick contains exactly the allowlisted narrow->wide "
    "integer conversions, and no 64-bit conversions/iotas at all",
)
def check_dtype_policy(ctx: Context) -> List[Finding]:
    _jax_cache_setup()
    import jax.numpy as jnp

    from frankenpaxos_tpu.analysis.allowlists import DTYPE_WIDENING

    out: List[Finding] = []
    # A pin keyed by a backend name that does not exist at all is a
    # typo or a leftover from a renamed/deleted backend — it can never
    # match any trace, so it silently exempts nothing (pins for real
    # backends simply not selected this run are fine).
    for b, conv in sorted(set(DTYPE_WIDENING) - {
        (b, c) for (b, c) in DTYPE_WIDENING if b in BACKENDS
    }):
        out.append(
            Finding(
                rule="trace-dtype-policy",
                path="frankenpaxos_tpu/analysis/allowlists.py",
                line=0,
                message=(
                    f"DTYPE_WIDENING pin ({b!r}, {conv!r}) names an "
                    "unknown backend — remove or fix it (known: "
                    f"{sorted(BACKENDS)})"
                ),
                key=f"{b}:{conv}:unknown-backend",
            )
        )
    for backend in _selected(ctx):
        observed: Dict[str, int] = collections.Counter()
        for eqn in _tick_eqns(backend):
            name = eqn.primitive.name
            if name == "convert_element_type":
                src = eqn.invars[0].aval.dtype
                dst = jnp.dtype(eqn.params["new_dtype"])
                if dst.itemsize > 4:
                    out.append(
                        Finding(
                            rule="trace-dtype-policy",
                            path=backend,
                            line=0,
                            message=(
                                f"tick jaxpr converts {src} -> "
                                f"{dst.name} (64-bit is never allowed; "
                                "x64 must stay off)"
                            ),
                            key=f"{backend}:{src}->{dst.name}:64bit",
                        )
                    )
                elif (
                    jnp.issubdtype(src, jnp.signedinteger)
                    and jnp.issubdtype(dst, jnp.signedinteger)
                    and dst.itemsize > src.itemsize
                ):
                    observed[f"{src}->{dst.name}"] += 1
            elif name == "iota":
                d = jnp.dtype(eqn.params["dtype"])
                if d.itemsize > 4:
                    out.append(
                        Finding(
                            rule="trace-dtype-policy",
                            path=backend,
                            line=0,
                            message=f"tick jaxpr builds a {d.name} iota",
                            key=f"{backend}:iota:{d.name}",
                        )
                    )
        expected = {
            conv: spec
            for (b, conv), spec in DTYPE_WIDENING.items()
            if b == backend
        }
        for conv in sorted(set(observed) | set(expected)):
            got = observed.get(conv, 0)
            want = expected.get(conv, (0, ""))[0]
            if got != want:
                out.append(
                    Finding(
                        rule="trace-dtype-policy",
                        path=backend,
                        line=0,
                        message=(
                            f"tick jaxpr has {got} {conv} widening "
                            f"conversion(s), allowlist pins {want} — "
                            "a new widening is a silent HBM "
                            "regression; a removed one must shrink "
                            "the DTYPE_WIDENING pin (allowlists.py) "
                            "so the budget can't absorb a future "
                            "regression"
                        ),
                        key=f"{backend}:{conv}",
                    )
                )
    # Pins for backends this run never traced are NOT stale — only
    # flag pins whose backend ran and whose conversion never appeared
    # in either direction (handled above via want != got == 0).
    return out


def _alias_param_indices(hlo_text: str) -> set:
    """Parameter numbers that appear as alias SOURCES in the compiled
    module's ``input_output_alias={ {out}: (param, {}, kind), ... }``
    table (balanced-brace scan: the table nests ``{}`` index paths)."""
    start = hlo_text.find("input_output_alias={")
    if start < 0:
        return set()
    i = hlo_text.index("{", start)
    depth = 0
    for j in range(i, len(hlo_text)):
        if hlo_text[j] == "{":
            depth += 1
        elif hlo_text[j] == "}":
            depth -= 1
            if depth == 0:
                break
    table = hlo_text[i : j + 1]
    return {int(p) for p in re.findall(r":\s*\((\d+),", table)}


@rule(
    "trace-donation-alias",
    "trace",
    "the compiled run_ticks HLO input_output_alias table aliases every "
    "State buffer (donation actually took effect) — both unsharded and, "
    "for backends in the sharding registry, under a device mesh",
)
def check_donation_alias(ctx: Context) -> List[Finding]:
    _jax_cache_setup()
    import jax
    import jax.numpy as jnp

    out: List[Finding] = []

    def check_alias(
        backend: str, hlo: str, n_leaves: int, where: str, key: str
    ):
        aliased = _alias_param_indices(hlo)
        # jit flattens (state, t0, key) in order, so the donated state
        # leaves are exactly parameters [0, n_leaves).
        missing = sorted(set(range(n_leaves)) - aliased)
        if missing:
            out.append(
                Finding(
                    rule="trace-donation-alias",
                    path=backend,
                    line=0,
                    message=(
                        f"{len(missing)} of {n_leaves} donated State "
                        f"buffers are NOT aliased in the compiled "
                        f"{where} HLO (parameter indices "
                        f"{missing[:8]}...) — donation silently fell "
                        "back to double-buffering"
                    ),
                    key=key,
                )
            )

    selected = _selected(ctx)
    for backend in selected:
        mod = _module(backend)
        cfg = mod.analysis_config()
        state = mod.init_state(cfg)
        n_leaves = len(jax.tree_util.tree_leaves(state))
        lowered = mod.run_ticks.lower(
            cfg,
            state,
            jnp.zeros((), jnp.int32),
            _TICKS,
            jax.random.PRNGKey(0),
        )
        check_alias(backend, lowered.compile().as_text(), n_leaves,
                    "run_ticks", backend)

    # The sharded wrappers (parallel/sharding.py registry): donation
    # must survive GSPMD partitioning too — a sharded run that
    # double-buffers pays 2x HBM on EVERY device. Compiled under the
    # widest mesh the host's devices allow for the analysis shape.
    from frankenpaxos_tpu.parallel import sharding as _sharding

    for backend, spec in sorted(_sharding.SHARDINGS.items()):
        if backend not in selected:
            continue
        mod = _module(backend)
        # Engage the client planes the registry lane-shards
        # (_NESTED_LANE_FIELDS): per-lane workload bookkeeping and the
        # [L, S] session table must keep their donation aliases under
        # the group-sharded layout too (a replicated->sharded reshard
        # would silently double-buffer the million-session plane).
        import inspect as _inspect

        _params = _inspect.signature(mod.analysis_config).parameters
        _kw = {}
        if "workload" in _params:
            from frankenpaxos_tpu.tpu.workload import WorkloadPlan

            _kw["workload"] = WorkloadPlan(arrival="constant", rate=1.0)
        if "lifecycle" in _params:
            from frankenpaxos_tpu.tpu.lifecycle import LifecyclePlan

            _kw["lifecycle"] = LifecyclePlan(
                sessions=8, resubmit_rate=0.1
            )
        cfg = mod.analysis_config(**_kw)
        # Pin the kernel policy to the reference twins: the donation
        # contract must hold on the plain-GSPMD program independent of
        # the shard_map kernel lowering (whose own contract is
        # trace-shardmap-kernel's job; donation under kernels-engaged
        # meshes is pinned by tests/test_multichip.py).
        if hasattr(cfg, "kernels"):
            import dataclasses as _dc

            from frankenpaxos_tpu.ops.registry import KernelPolicy

            cfg = _dc.replace(cfg, kernels=KernelPolicy.reference())
        state = mod.init_state(cfg)
        n_leaves = len(jax.tree_util.tree_leaves(state))
        axis_len = spec.axis_len(state)
        # A 2-device mesh is the cheapest configuration that makes
        # aliasing non-trivial under GSPMD (wider meshes only grow the
        # compile bill; tests/test_multichip.py covers the full mesh).
        n_dev = 1
        for d in range(min(len(jax.devices()), axis_len, 2), 0, -1):
            if axis_len % d == 0:
                n_dev = d
                break
        mesh = _sharding.make_mesh(jax.devices()[:n_dev])
        sharded = _sharding.shard_state(backend, state, mesh)
        lowered = _sharding.lower_sharded(
            backend, cfg, mesh, sharded, jnp.zeros((), jnp.int32),
            _TICKS, jax.random.PRNGKey(0),
        )
        check_alias(
            backend, lowered.compile().as_text(), n_leaves,
            f"sharded[{n_dev}dev]", f"{backend}:sharded",
        )
    return out


def _count_pallas_calls(eqns) -> int:
    return sum(1 for e in eqns if e.primitive.name == "pallas_call")


@rule(
    "trace-fused-tick",
    "trace",
    "the flagship MultiPaxos tick with the kernel policy engaged "
    "compiles its hot path to exactly ONE pallas_call (the whole-tick "
    "megakernel, no per-plane HBM round trips); reference mode to none",
)
def check_fused_tick(ctx: Context) -> List[Finding]:
    if ctx.backends is not None and "multipaxos" not in ctx.backends:
        return []
    _jax_cache_setup()
    from frankenpaxos_tpu.ops.registry import KernelPolicy
    from frankenpaxos_tpu.tpu import multipaxos_batched as mb

    out: List[Finding] = []
    # The bench.py flagship shape (10k simulated acceptors). Tracing is
    # shape-cheap: make_jaxpr never materializes the arrays.
    flagship = dict(
        f=1, num_groups=3334, window=64, slots_per_tick=8,
        lat_min=1, lat_max=3, retry_timeout=16, thrifty=True,
    )
    cfg_on = mb.BatchedMultiPaxosConfig(
        **flagship, kernels=KernelPolicy(mode="interpret")
    )
    n_on = _count_pallas_calls(_tick_eqns("multipaxos", cfg_on))
    if n_on != 1:
        out.append(
            Finding(
                rule="trace-fused-tick",
                path="multipaxos",
                line=0,
                message=(
                    f"flagship tick with the kernel policy engaged "
                    f"traces {n_on} pallas_call(s), expected exactly 1 "
                    "(the whole-tick megakernel): >1 means the tick "
                    "regressed to per-plane dispatch (an HBM round "
                    "trip between planes), 0 means the megakernel "
                    "silently fell back to the reference path"
                ),
                key=f"multipaxos:on:{n_on}",
            )
        )
    cfg_ref = mb.BatchedMultiPaxosConfig(
        **flagship, kernels=KernelPolicy.reference()
    )
    n_ref = _count_pallas_calls(_tick_eqns("multipaxos", cfg_ref))
    if n_ref != 0:
        out.append(
            Finding(
                rule="trace-fused-tick",
                path="multipaxos",
                line=0,
                message=(
                    f"flagship tick in reference mode traces {n_ref} "
                    "pallas_call(s), expected none — the reference "
                    "path must stay pure jnp"
                ),
                key=f"multipaxos:reference:{n_ref}",
            )
        )
    return out


def _sharded_wrapper_eqns(backend: str, cfg, mesh) -> list:
    """Jaxpr equations of the backend's run_ticks body traced exactly
    as ``parallel.sharding.run_ticks_sharded`` traces it: under the
    registry's shard_lowering context, so engaged kernel planes lower
    through jax.shard_map (tracing is shape-only — no device memory)."""
    import jax
    import jax.numpy as jnp

    from frankenpaxos_tpu.ops import registry as _registry
    from frankenpaxos_tpu.parallel import sharding as _sh

    mod = _module(backend)
    state = mod.init_state(cfg)
    wrap = _sh._wrap_mesh(backend, cfg, mesh)

    def run(s, t0, k):
        with _registry.shard_lowering(wrap, _sh.GROUP_AXIS):
            return mod.run_ticks.__wrapped__(cfg, s, t0, _TICKS, k)

    closed = jax.make_jaxpr(run)(
        state, jnp.zeros((), jnp.int32), jax.random.PRNGKey(0)
    )
    eqns: list = []
    _walk_eqns(closed.jaxpr, eqns)
    return eqns


_COLLECTIVE_TOKENS = (
    "all-reduce", "all-gather", "all-to-all", "collective-permute",
    "reduce-scatter",
)


_SHAPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")


def _collective_line_shapes(line: str):
    """``(dtype, elems)`` result shapes of one HLO collective line, or
    None when the line is not a collective / has no parseable result
    shapes. Every shape of a combined tuple-shaped collective is
    returned — XLA's combiner can hide a large reduction behind a
    scalar first element. ONE scanner shared by the multichip-era
    signed-size census and the fleet replica-group census, so a parser
    fix never has to land twice."""
    op_at = [
        line.index(tok + suffix)
        for tok in _COLLECTIVE_TOKENS
        for suffix in ("(", "-start(")
        if (tok + suffix) in line
    ]
    eq_at = line.find("=")
    if not op_at or eq_at < 0:
        return None
    shapes = []
    for dtype, dims in _SHAPE_RE.findall(line[eq_at: min(op_at)]):
        elems = 1
        for d in dims.split(","):
            if d:
                elems *= int(d)
        shapes.append((dtype, elems))
    return shapes or None


def _max_signed_collective_elems(hlo_text: str) -> int:
    """Largest signed/pred result element count across the compiled
    module's collectives (unsigned u32 shapes are threefry PRNG-sweep
    assembly, counted by the multichip tests separately)."""
    worst = 0
    for line in hlo_text.splitlines():
        for dtype, elems in _collective_line_shapes(line) or ():
            if not dtype.startswith("u"):
                worst = max(worst, elems)
    return worst


@rule(
    "trace-shardmap-kernel",
    "trace",
    "sharded wrappers with the kernel policy engaged lower their "
    "planes through shard_map (pallas_call present, no signed-state "
    "collective beyond the <=64-element stat reductions); reference "
    "mode stays pallas-free",
)
def check_shardmap_kernel(ctx: Context) -> List[Finding]:
    _jax_cache_setup()
    import dataclasses as _dc

    import jax
    import jax.numpy as jnp

    from frankenpaxos_tpu.ops.registry import KernelPolicy
    from frankenpaxos_tpu.parallel import sharding as _sh

    out: List[Finding] = []
    if len(jax.devices()) < 2:
        # Single-device host: shard_map lowering never engages, so the
        # contract is untestable here. scripts/lint.sh and the pytest
        # conftest both force an 8-virtual-device CPU mesh, so the
        # standard entry points always run the full check — but say so
        # loudly when skipping, so a pre-set 1-device XLA_FLAGS can't
        # silently disable the rule.
        import sys

        print(
            "trace-shardmap-kernel: SKIPPED (needs >=2 jax devices; "
            "set XLA_FLAGS=--xla_force_host_platform_device_count=8 "
            "or run via scripts/lint.sh)",
            file=sys.stderr,
        )
        return out
    selected = _selected(ctx)
    for backend, spec in sorted(_sh.SHARDINGS.items()):
        if backend not in selected or spec.planes_backend is None:
            continue
        mod = _module(backend)
        base = mod.analysis_config()
        state = mod.init_state(base)
        axis_len = spec.axis_len(state)
        n_dev = max(
            (
                d
                for d in range(1, min(len(jax.devices()), axis_len) + 1)
                if axis_len % d == 0
            ),
            default=1,
        )
        if n_dev < 2:
            continue
        mesh = _sh.make_mesh(jax.devices()[:n_dev])

        cfg_on = _dc.replace(base, kernels=KernelPolicy(mode="interpret"))
        n_on = _count_pallas_calls(
            _sharded_wrapper_eqns(backend, cfg_on, mesh)
        )
        if n_on < 1:
            out.append(
                Finding(
                    rule="trace-shardmap-kernel",
                    path=backend,
                    line=0,
                    message=(
                        f"sharded {n_dev}-device wrapper with the "
                        "kernel policy engaged traces 0 pallas_calls — "
                        "the kernels silently fell back to the "
                        "reference path instead of shard_map-lowering"
                    ),
                    key=f"{backend}:on:none",
                )
            )
        # The compiled kernels-engaged program: no signed-state
        # collective beyond the stat reductions (a bigger one means a
        # ShardSpec axis is wrong and shard_map is moving state).
        sharded = _sh.shard_state(backend, mod.init_state(cfg_on), mesh)
        hlo = _sh.lower_sharded(
            backend, cfg_on, mesh, sharded, jnp.zeros((), jnp.int32),
            _TICKS, jax.random.PRNGKey(0),
        ).compile().as_text()
        worst = _max_signed_collective_elems(hlo)
        if worst > 64:
            out.append(
                Finding(
                    rule="trace-shardmap-kernel",
                    path=backend,
                    line=0,
                    message=(
                        f"kernels-engaged sharded program emits a "
                        f"{worst}-element signed collective (allowed: "
                        "<=64-element stat reductions) — a ShardSpec "
                        "axis is mis-declared and shard_map is "
                        "gathering simulation state"
                    ),
                    key=f"{backend}:collective:{worst}",
                )
            )
        cfg_ref = _dc.replace(base, kernels=KernelPolicy.reference())
        n_ref = _count_pallas_calls(
            _sharded_wrapper_eqns(backend, cfg_ref, mesh)
        )
        if n_ref != 0:
            out.append(
                Finding(
                    rule="trace-shardmap-kernel",
                    path=backend,
                    line=0,
                    message=(
                        f"sharded reference-mode wrapper traces {n_ref} "
                        "pallas_call(s) — the reference path must stay "
                        "pure jnp"
                    ),
                    key=f"{backend}:reference:{n_ref}",
                )
            )
    return out


@rule(
    "trace-workload-noop",
    "trace",
    "under WorkloadPlan.none() every workload State leaf is zero-sized "
    "and feeds no tick equation — the structural no-op contract that "
    "keeps default runs bit-identical to the pre-workload program",
)
def check_workload_noop(ctx: Context) -> List[Finding]:
    _jax_cache_setup()
    import jax
    import jax.numpy as jnp

    out: List[Finding] = []
    for backend in _selected(ctx):
        # Shared with trace-dtype-policy: ONE default-config tick trace
        # per backend per process (_tick_closed).
        closed, state = _tick_closed(backend)
        # (a) Structure: an all-empty shaping state under the default
        # none plan — a sized leaf is carried HBM bytes on every tick.
        flat, _ = jax.tree_util.tree_flatten_with_path(state)
        wl_idx = [
            i
            for i, (path, leaf) in enumerate(flat)
            if path
            and getattr(path[0], "name", None) == "workload"
        ]
        if not wl_idx:
            out.append(
                Finding(
                    rule="trace-workload-noop",
                    path=backend,
                    line=0,
                    message=(
                        "State carries no workload field — the engine "
                        "is not threaded through this backend"
                    ),
                    key=f"{backend}:missing",
                )
            )
            continue
        sized = [
            flat[i][1].size for i in wl_idx if flat[i][1].size != 0
        ]
        if sized:
            out.append(
                Finding(
                    rule="trace-workload-noop",
                    path=backend,
                    line=0,
                    message=(
                        f"WorkloadPlan.none() state carries "
                        f"{len(sized)} NON-empty leaf/leaves — the "
                        "none plan must be structurally empty"
                    ),
                    key=f"{backend}:sized",
                )
            )
        # (b) Zero ops: no tick equation may consume a workload leaf —
        # they must pass straight through the carry untouched.
        invars = closed.jaxpr.invars
        wl_vars = {id(invars[i]) for i in wl_idx}
        consumed = sum(
            1
            for eqn in closed.jaxpr.eqns
            for v in eqn.invars
            if id(v) in wl_vars
        )
        if consumed:
            out.append(
                Finding(
                    rule="trace-workload-noop",
                    path=backend,
                    line=0,
                    message=(
                        f"{consumed} tick equation input(s) consume a "
                        "workload leaf under WorkloadPlan.none() — the "
                        "none plan must add ZERO ops (XLA cannot DCE a "
                        "consumed carry)"
                    ),
                    key=f"{backend}:consumed",
                )
            )
    return out


# Backends that thread the production-lifecycle subsystem
# (tpu/lifecycle.py); the lifecycle-noop / trace-lifecycle-retrace
# rules cover exactly these (the subsystem rolls out flagship-first).
LIFECYCLE_BACKENDS = ("multipaxos", "compartmentalized")


@rule(
    "lifecycle-noop",
    "trace",
    "under LifecyclePlan.none() every lifecycle State leaf is "
    "zero-sized and feeds no tick equation — the structural no-op "
    "contract that keeps default runs bit-identical to the "
    "pre-lifecycle program",
)
def check_lifecycle_noop(ctx: Context) -> List[Finding]:
    _jax_cache_setup()
    import jax

    out: List[Finding] = []
    for backend in _selected(ctx):
        if backend not in LIFECYCLE_BACKENDS:
            continue
        # Shared with trace-dtype-policy / trace-workload-noop: ONE
        # default-config tick trace per backend per process.
        closed, state = _tick_closed(backend)
        flat, _ = jax.tree_util.tree_flatten_with_path(state)
        lc_idx = [
            i
            for i, (path, leaf) in enumerate(flat)
            if path and getattr(path[0], "name", None) == "lifecycle"
        ]
        if not lc_idx:
            out.append(
                Finding(
                    rule="lifecycle-noop",
                    path=backend,
                    line=0,
                    message=(
                        "State carries no lifecycle field — the "
                        "subsystem is not threaded through this backend"
                    ),
                    key=f"{backend}:missing",
                )
            )
            continue
        sized = [
            flat[i][1].size for i in lc_idx if flat[i][1].size != 0
        ]
        if sized:
            out.append(
                Finding(
                    rule="lifecycle-noop",
                    path=backend,
                    line=0,
                    message=(
                        f"LifecyclePlan.none() state carries "
                        f"{len(sized)} NON-empty leaf/leaves — the "
                        "none plan must be structurally empty"
                    ),
                    key=f"{backend}:sized",
                )
            )
        invars = closed.jaxpr.invars
        lc_vars = {id(invars[i]) for i in lc_idx}
        consumed = sum(
            1
            for eqn in closed.jaxpr.eqns
            for v in eqn.invars
            if id(v) in lc_vars
        )
        if consumed:
            out.append(
                Finding(
                    rule="lifecycle-noop",
                    path=backend,
                    line=0,
                    message=(
                        f"{consumed} tick equation input(s) consume a "
                        "lifecycle leaf under LifecyclePlan.none() — "
                        "the none plan must add ZERO ops"
                    ),
                    key=f"{backend}:consumed",
                )
            )
    return out


@rule(
    "trace-lifecycle-retrace",
    "trace",
    "acceptor reconfiguration is recompile-free: swapping membership "
    "and bumping the traced epoch (plus a force-rotation latch) "
    "between run_ticks segments replays ONE compiled program — the "
    "jit cache stays flat across epoch changes",
)
def check_lifecycle_retrace(ctx: Context) -> List[Finding]:
    _jax_cache_setup()
    import dataclasses as _dc

    import jax
    import jax.numpy as jnp

    from frankenpaxos_tpu.tpu import lifecycle as _lifecycle
    from frankenpaxos_tpu.tpu.lifecycle import LifecyclePlan

    out: List[Finding] = []
    for backend in _selected(ctx):
        if backend not in LIFECYCLE_BACKENDS:
            continue
        mod = _module(backend)
        cfg = mod.analysis_config(
            lifecycle=LifecyclePlan(
                rotate_every=16, sessions=4, resubmit_rate=0.1,
                reconfig=True,
            )
        )

        def run(st):
            st, t = mod.run_ticks(
                cfg, st, jnp.zeros((), jnp.int32), _TICKS,
                jax.random.PRNGKey(0),
            )
            jax.block_until_ready(t)

        run(mod.init_state(cfg))
        before = mod.run_ticks._cache_size()
        steered = mod.init_state(cfg)
        # Mask one acceptor CELL out (shape-generic: flat element 0 —
        # swap_acceptor is the flat-[A, G]-axis convenience and
        # rejects grid-shaped axes by design).
        shape = steered.lifecycle.acc_mask.shape
        mask = (
            jnp.ones(shape, bool).ravel().at[0].set(False).reshape(shape)
        )
        steered = _dc.replace(
            steered,
            lifecycle=_lifecycle.request_rotation(
                _lifecycle.set_membership(steered.lifecycle, mask)
            ),
        )
        run(steered)
        after = mod.run_ticks._cache_size()
        if after > before:
            out.append(
                Finding(
                    rule="trace-lifecycle-retrace",
                    path=backend,
                    line=0,
                    message=(
                        "a membership swap + epoch bump missed the jit "
                        f"cache ({before} -> {after} entries) — the "
                        "membership/epoch landed in a static argument "
                        "and every reconfiguration recompiles the "
                        "serve loop"
                    ),
                    key=backend,
                )
            )
    return out


# Backends that thread the elastic-capacity subsystem
# (tpu/elastic.py); the elastic-noop / trace-elastic-retrace rules
# cover exactly these (padded role planes roll out flagship +
# compartmentalized first — the two backends the autoscaler ladder
# serves).
ELASTIC_BACKENDS = ("multipaxos", "compartmentalized")


def _elastic_plan_for(backend: str):
    """An ElasticPlan matching the backend's analysis_config axes."""
    from frankenpaxos_tpu.tpu.elastic import ElasticPlan

    if backend == "multipaxos":
        return ElasticPlan(roles=(("groups", 4, 1),))
    return ElasticPlan(roles=(
        ("proxies", 4, 1), ("batchers", 2, 1),
        ("unbatchers", 2, 1), ("replicas", 3, 1),
    ))


@rule(
    "elastic-noop",
    "trace",
    "under ElasticPlan.none() every elastic State leaf is zero-sized "
    "and feeds no tick equation — the structural no-op contract that "
    "keeps default runs bit-identical to the pre-elastic program",
)
def check_elastic_noop(ctx: Context) -> List[Finding]:
    _jax_cache_setup()
    import jax

    out: List[Finding] = []
    for backend in _selected(ctx):
        if backend not in ELASTIC_BACKENDS:
            continue
        # Shared with trace-dtype-policy / trace-workload-noop: ONE
        # default-config tick trace per backend per process.
        closed, state = _tick_closed(backend)
        flat, _ = jax.tree_util.tree_flatten_with_path(state)
        el_idx = [
            i
            for i, (path, leaf) in enumerate(flat)
            if path and getattr(path[0], "name", None) == "elastic"
        ]
        if not el_idx:
            out.append(
                Finding(
                    rule="elastic-noop",
                    path=backend,
                    line=0,
                    message=(
                        "State carries no elastic field — the "
                        "subsystem is not threaded through this backend"
                    ),
                    key=f"{backend}:missing",
                )
            )
            continue
        sized = [
            flat[i][1].size for i in el_idx if flat[i][1].size != 0
        ]
        if sized:
            out.append(
                Finding(
                    rule="elastic-noop",
                    path=backend,
                    line=0,
                    message=(
                        f"ElasticPlan.none() state carries "
                        f"{len(sized)} NON-empty leaf/leaves — the "
                        "none plan must be structurally empty"
                    ),
                    key=f"{backend}:sized",
                )
            )
        invars = closed.jaxpr.invars
        el_vars = {id(invars[i]) for i in el_idx}
        consumed = sum(
            1
            for eqn in closed.jaxpr.eqns
            for v in eqn.invars
            if id(v) in el_vars
        )
        if consumed:
            out.append(
                Finding(
                    rule="elastic-noop",
                    path=backend,
                    line=0,
                    message=(
                        f"{consumed} tick equation input(s) consume an "
                        "elastic leaf under ElasticPlan.none() — the "
                        "none plan must add ZERO ops"
                    ),
                    key=f"{backend}:consumed",
                )
            )
    return out


@rule(
    "trace-elastic-retrace",
    "trace",
    "live resize is recompile-free: steering the traced role-count "
    "targets (ServeLoop.resize -> elastic.set_target) between "
    "run_ticks segments replays ONE compiled program — the serve/"
    "fleet jit caches stay FLAT across every scale-up and scale-down",
)
def check_elastic_retrace(ctx: Context) -> List[Finding]:
    _jax_cache_setup()
    import dataclasses as _dc

    import jax
    import jax.numpy as jnp

    from frankenpaxos_tpu.tpu import elastic as _elastic

    out: List[Finding] = []
    for backend in _selected(ctx):
        if backend not in ELASTIC_BACKENDS:
            continue
        mod = _module(backend)
        plan = _elastic_plan_for(backend)
        cfg = mod.analysis_config(elastic=plan)

        def run(st):
            st, t = mod.run_ticks(
                cfg, st, jnp.zeros((), jnp.int32), _TICKS,
                jax.random.PRNGKey(0),
            )
            jax.block_until_ready(t)
            return st

        st = run(mod.init_state(cfg))
        before = mod.run_ticks._cache_size()
        # Shrink every role toward its floor, run a segment, grow back
        # to capacity, run again — two resize generations through the
        # same executable.
        es = st.elastic
        for name in plan.names:
            es = _elastic.set_target(plan, es, name, plan.floor_of(name))
        st = run(_dc.replace(st, elastic=es))
        es = st.elastic
        for name in plan.names:
            es = _elastic.set_target(
                plan, es, name, plan.capacity_of(name)
            )
        run(_dc.replace(st, elastic=es))
        after = mod.run_ticks._cache_size()
        if after > before:
            out.append(
                Finding(
                    rule="trace-elastic-retrace",
                    path=backend,
                    line=0,
                    message=(
                        "a role-count resize missed the jit cache "
                        f"({before} -> {after} entries) — a target "
                        "count landed in a static argument and every "
                        "autoscaler action recompiles the serve loop"
                    ),
                    key=backend,
                )
            )
    return out


# Backends whose traced sweep gets the COMPILE-backed jit-cache check
# (the XLA-compile half of the retrace rule). The cheap trace-only
# coverage below still runs for every backend — the traced-rate
# plumbing is the shared faults.py helper surface, and the helpers'
# own "rates= required" assert fires at TRACE time for any backend
# that missed the threading; compiling all 14 would only re-prove the
# cache behavior the representative set already pins, at ~10 extra
# XLA compiles per lint run.
RETRACE_COMPILE_BACKENDS = (
    "compartmentalized", "craq", "multipaxos", "unreplicated",
)


@rule(
    "trace-workload-retrace",
    "trace",
    "sweeping the traced offered rate AND the traced FaultPlan rates "
    "replays ONE compiled program — every backend traces the "
    "[workload x fault-rate] config cleanly, and the representative "
    "set's jit cache must not grow across the grid",
)
def check_workload_retrace(ctx: Context) -> List[Finding]:
    _jax_cache_setup()
    import dataclasses as _dc

    import jax
    import jax.numpy as jnp

    from frankenpaxos_tpu.tpu import workload as _workload
    from frankenpaxos_tpu.tpu.faults import FaultPlan
    from frankenpaxos_tpu.tpu.workload import WorkloadPlan

    out: List[Finding] = []
    for backend in _selected(ctx):
        mod = _module(backend)
        cfg = mod.analysis_config(
            faults=FaultPlan(traced=True),
            workload=WorkloadPlan(arrival="constant", rate=1.0),
        )
        # (a) Every backend: the traced [workload x fault] config must
        # TRACE cleanly — the fault helpers assert rates= was threaded
        # (tpu/faults.py _rate), so a backend that accepted a traced
        # plan but never passed its rate state fails right here, no
        # compile needed.
        try:
            state = mod.init_state(cfg)
            jax.make_jaxpr(lambda s, t, k: mod.tick(cfg, s, t, k))(
                state, jnp.zeros((), jnp.int32), jax.random.PRNGKey(0)
            )
        except AssertionError as e:
            out.append(
                Finding(
                    rule="trace-workload-retrace",
                    path=backend,
                    line=0,
                    message=(
                        "tick failed to trace the traced [workload x "
                        f"fault-rate] config: {e}"
                    ),
                    key=f"{backend}:trace",
                )
            )
            continue
        if backend not in RETRACE_COMPILE_BACKENDS:
            continue
        # (b) Representative set: the compile-backed cache check.

        def run(st):
            st, t = mod.run_ticks(
                cfg, st, jnp.zeros((), jnp.int32), _TICKS,
                jax.random.PRNGKey(0),
            )
            jax.block_until_ready(t)

        run(mod.init_state(cfg))
        before = mod.run_ticks._cache_size()
        swept = mod.init_state(cfg)
        swept = _dc.replace(
            swept,
            workload=_workload.set_fault_rates(
                _workload.set_rate(swept.workload, 2.5),
                drop=0.2, dup=0.1, crash=0.01, revive=0.2,
            ),
        )
        run(swept)
        after = mod.run_ticks._cache_size()
        if after > before:
            out.append(
                Finding(
                    rule="trace-workload-retrace",
                    path=backend,
                    line=0,
                    message=(
                        "sweeping the traced offered rate + fault "
                        f"rates missed the jit cache ({before} -> "
                        f"{after} entries) — a rate landed in a static "
                        "argument and the grid recompiles per point"
                    ),
                    key=backend,
                )
            )
    return out


@rule(
    "trace-retrace-guard",
    "trace",
    "a second run_ticks call with a fresh but equal config hits the "
    "jit cache — no hashability/static-arg retrace regressions",
)
def check_retrace_guard(ctx: Context) -> List[Finding]:
    _jax_cache_setup()
    import jax
    import jax.numpy as jnp

    out: List[Finding] = []
    for backend in _selected(ctx):
        mod = _module(backend)

        def call():
            cfg = mod.analysis_config()  # fresh object each call
            state = mod.init_state(cfg)
            st, t = mod.run_ticks(
                cfg,
                state,
                jnp.zeros((), jnp.int32),
                _TICKS,
                jax.random.PRNGKey(0),
            )
            jax.block_until_ready(t)

        try:
            call()
        except TypeError as e:
            out.append(
                Finding(
                    rule="trace-retrace-guard",
                    path=backend,
                    line=0,
                    message=(
                        f"run_ticks rejected its analysis_config as a "
                        f"static argument (unhashable?): {e}"
                    ),
                    key=f"{backend}:unhashable",
                )
            )
            continue
        before = mod.run_ticks._cache_size()
        call()
        after = mod.run_ticks._cache_size()
        if after > before:
            out.append(
                Finding(
                    rule="trace-retrace-guard",
                    path=backend,
                    line=0,
                    message=(
                        "a second run_ticks call with an EQUAL config "
                        f"missed the jit cache ({before} -> {after} "
                        "entries) — the config's __eq__/__hash__ or a "
                        "non-hashable field retraces every segment"
                    ),
                    key=backend,
                )
            )
    return out


def _blocking_hlo_hits(hlo_text: str):
    """``(1-based line, description)`` for every host-rendezvous
    construct in a compiled hot-path artifact — ONE scanner shared by
    the serve/checkpoint/fleet nosync rules, so a detection fix never
    has to land three times. Matched per-line so variable names in
    metadata (last_send ...) can't false-positive: callbacks lower to
    custom-calls whose TARGET names a python/host callback;
    infeed/outfeed appear as the op itself."""
    hits = []
    for i, line in enumerate(hlo_text.splitlines()):
        lowered = line.lower()
        if "custom-call" in lowered and (
            "callback" in lowered or "host_compute" in lowered
        ):
            hits.append((i + 1, "host callback custom-call"))
        elif " infeed(" in lowered or " outfeed(" in lowered:
            hits.append((i + 1, "infeed/outfeed"))
    return hits


@rule(
    "trace-serve-nosync",
    "trace",
    "the serve loop's chunked-dispatch hot path (run_ticks + the "
    "telemetry snapshot, harness/serve.py) compiles free of blocking "
    "host transfers — no host callbacks/infeed/outfeed, and the "
    "snapshot COPIES (aliases nothing), so draining it after the next "
    "chunk donates the state never reads donated buffers",
)
def check_serve_nosync(ctx: Context) -> List[Finding]:
    _jax_cache_setup()
    import dataclasses as _dc

    from frankenpaxos_tpu.harness import serve as serve_mod
    from frankenpaxos_tpu.tpu import telemetry as telemetry_mod

    backend = "multipaxos"  # the flagship serve target
    if ctx.backends is not None and backend not in ctx.backends:
        return []
    out: List[Finding] = []

    def scan_blocking(hlo: str, where: str):
        for line_no, hit in _blocking_hlo_hits(hlo):
            out.append(
                Finding(
                    rule="trace-serve-nosync",
                    path=backend,
                    line=line_no,
                    message=(
                        f"{hit} in the compiled {where} — the "
                        "serve hot path would block on the host "
                        "every chunk"
                    ),
                    key=f"{backend}:{where}:{hit}",
                )
            )

    mod = _module(backend)
    cfg = mod.analysis_config()
    # Two legs: the plain serve state, and a span-sampler-enabled state
    # (the reservoir + completion ring must not smuggle a callback or
    # break the snapshot-copies contract either).
    for label, spans in (("", 0), ("spans", 4)):
        state = mod.init_state(cfg)
        state = _dc.replace(
            state,
            telemetry=telemetry_mod.make_telemetry(
                telemetry_mod.TELEM_WINDOW, spans=spans
            ),
        )
        run_lowered, snap_lowered = serve_mod.lower_chunk_path(
            mod, cfg, state=state
        )
        where_run = f"run_ticks{('+' + label) if label else ''}"
        where_snap = f"snapshot{('+' + label) if label else ''}"
        scan_blocking(run_lowered.compile().as_text(), where_run)
        snap_hlo = snap_lowered.compile().as_text()
        scan_blocking(snap_hlo, where_snap)
        aliased = _alias_param_indices(snap_hlo)
        if aliased:
            out.append(
                Finding(
                    rule="trace-serve-nosync",
                    path=backend,
                    line=0,
                    message=(
                        f"the compiled telemetry snapshot ALIASES "
                        f"{len(aliased)} input buffer(s) — the serve "
                        "drain would read buffers the next chunk's "
                        "donation already reused; the snapshot must "
                        "copy"
                    ),
                    key=f"{backend}:{where_snap}:aliased",
                )
            )
    return out


@rule(
    "checkpoint-alias-free",
    "trace",
    "the crash-tolerance snapshot (tpu/checkpoint.py snapshot_tree: "
    "the jitted full-State copy the serve loop enqueues every N "
    "chunks) compiles alias-free — no output aliases an input buffer "
    "(the next chunk's donation would reuse it while the disk drain "
    "still reads it) and no host callback rides the hot path",
)
def check_checkpoint_alias_free(ctx: Context) -> List[Finding]:
    _jax_cache_setup()
    import jax.numpy as jnp

    from frankenpaxos_tpu.tpu import checkpoint as checkpoint_mod

    backend = "multipaxos"  # the flagship serve target
    if ctx.backends is not None and backend not in ctx.backends:
        return []
    out: List[Finding] = []
    mod = _module(backend)
    cfg = mod.analysis_config()
    tree = {"state": mod.init_state(cfg), "t": jnp.zeros((), jnp.int32)}
    hlo = checkpoint_mod.lower_snapshot(tree).compile().as_text()
    aliased = _alias_param_indices(hlo)
    if aliased:
        out.append(
            Finding(
                rule="checkpoint-alias-free",
                path=backend,
                line=0,
                message=(
                    f"the compiled checkpoint snapshot ALIASES "
                    f"{len(aliased)} input buffer(s) — the disk drain "
                    "would read buffers the next chunk's donation "
                    "already reused; the snapshot must copy"
                ),
                key=f"{backend}:aliased",
            )
        )
    for line_no, hit in _blocking_hlo_hits(hlo):
        out.append(
            Finding(
                rule="checkpoint-alias-free",
                path=backend,
                line=line_no,
                message=(
                    f"{hit} in the compiled checkpoint snapshot — "
                    "the serve hot path would block on the host "
                    "every checkpoint"
                ),
                key=f"{backend}:{hit}",
            )
        )
    return out


@rule(
    "trace-checkpoint-restore",
    "trace",
    "checkpoint restore is recompile-free: a State saved to disk "
    "(tpu/checkpoint.py), loaded back, and rebuilt onto a fresh "
    "template replays the EXISTING compiled run_ticks — the restore "
    "path preserves every leaf's dtype/shape/commitment so the jit "
    "cache stays flat (no cold recompile beyond process start)",
)
def check_checkpoint_restore(ctx: Context) -> List[Finding]:
    _jax_cache_setup()
    import tempfile

    import jax
    import jax.numpy as jnp

    from frankenpaxos_tpu.tpu import checkpoint as checkpoint_mod

    backend = "multipaxos"  # the flagship serve target
    if ctx.backends is not None and backend not in ctx.backends:
        return []
    out: List[Finding] = []
    mod = _module(backend)
    cfg = mod.analysis_config()

    def run(st, t0):
        st, t = mod.run_ticks(
            cfg, st, t0, _TICKS, jax.random.PRNGKey(0)
        )
        jax.block_until_ready(t)
        return st, t

    state, t = run(mod.init_state(cfg), jnp.zeros((), jnp.int32))
    before = mod.run_ticks._cache_size()
    with tempfile.TemporaryDirectory() as d:
        checkpoint_mod.save_state(d, mod, cfg, state, t, step=0)
        restored, t_r, manifest = checkpoint_mod.restore_state(
            d, mod, cfg, mod.init_state(cfg)
        )
    if checkpoint_mod.state_digest(restored) != (
        checkpoint_mod.state_digest(state)
    ):
        out.append(
            Finding(
                rule="trace-checkpoint-restore",
                path=backend,
                line=0,
                message=(
                    "save -> load -> restore is not bit-exact: the "
                    "restored State's digest differs from the saved "
                    "one"
                ),
                key=f"{backend}:digest",
            )
        )
    run(restored, t_r)
    after = mod.run_ticks._cache_size()
    if after > before:
        out.append(
            Finding(
                rule="trace-checkpoint-restore",
                path=backend,
                line=0,
                message=(
                    "run_ticks on a RESTORED state missed the jit "
                    f"cache ({before} -> {after} entries) — the "
                    "restore path changed a leaf's dtype/shape/weak "
                    "type and every crash recovery recompiles the "
                    "serve loop"
                ),
                key=backend,
            )
        )
    return out


_RG_EXPLICIT = re.compile(r"replica_groups=\{(\{[0-9,{} ]*\})\}")
_RG_IOTA = re.compile(
    r"replica_groups=\[(\d+),(\d+)\]<=\[([0-9,]+)\](?:T\(([0-9,]+)\))?"
)


def _collective_groups(line: str):
    """The replica groups of one HLO collective line as a list of
    device-id lists, handling the explicit ``{{0,1},{2,3}}`` form and
    the iota ``[2,4]<=[8]`` / ``[4,2]<=[2,4]T(1,0)`` forms. Returns
    None when the format is unrecognized (the caller treats
    unparseable as a finding — never a silent pass)."""
    m = _RG_EXPLICIT.search(line)
    if m:
        groups = []
        for grp in re.findall(r"\{([0-9, ]*)\}", m.group(1)):
            ids = [int(x) for x in grp.replace(" ", "").split(",") if x]
            groups.append(ids)
        return groups
    m = _RG_IOTA.search(line)
    if m:
        import numpy as np

        n_groups, group_size = int(m.group(1)), int(m.group(2))
        dims = [int(d) for d in m.group(3).split(",")]
        ids = np.arange(int(np.prod(dims))).reshape(dims)
        if m.group(4):  # iota with a transpose: reshape + permute
            perm = [int(d) for d in m.group(4).split(",")]
            ids = ids.transpose(perm)
        ids = [int(x) for x in ids.ravel()]
        if len(ids) != n_groups * group_size:
            return None
        return [
            ids[i * group_size: (i + 1) * group_size]
            for i in range(n_groups)
        ]
    if "replica_groups=" in line:
        return None
    return [[0]]  # no groups attribute: a degenerate single-device op


def _fleet_rows(n_fleet: int, n_group: int):
    """Device-id rows of a ``(fleet, group)`` product mesh built from
    the devices in order (``parallel.sharding.make_fleet_mesh``): row i
    owns flat ids [i*n_group, (i+1)*n_group) — the sets no protocol
    collective may cross."""
    return [
        set(range(i * n_group, (i + 1) * n_group))
        for i in range(n_fleet)
    ]


@rule(
    "trace-fleet-onecompile",
    "trace",
    "a whole [seeds x workload x fault] fleet brick is ONE compiled "
    "executable per mesh (a traced-rate re-sweep keeps the fleet "
    "runner's jit cache flat), and the compiled program's collectives "
    "never cross the fleet axis: every signed-state replica group "
    "stays inside one fleet row, with no signed all-gather/"
    "all-to-all/collective-permute of state at all",
)
def check_fleet_onecompile(ctx: Context) -> List[Finding]:
    _jax_cache_setup()
    import dataclasses as _dc

    import jax
    import jax.numpy as jnp

    from frankenpaxos_tpu.parallel import sharding as _sh
    from frankenpaxos_tpu.tpu.faults import FaultPlan
    from frankenpaxos_tpu.tpu.workload import WorkloadPlan

    out: List[Finding] = []
    if len(jax.devices()) < 4:
        import sys

        print(
            "trace-fleet-onecompile: SKIPPED (needs >=4 jax devices "
            "for a 2x2 product mesh; set XLA_FLAGS="
            "--xla_force_host_platform_device_count=8 or run via "
            "scripts/lint.sh)",
            file=sys.stderr,
        )
        return out
    selected = _selected(ctx)
    for backend, spec in sorted(_sh.SHARDINGS.items()):
        if backend not in selected or spec.planes_backend is None:
            continue
        mod = _module(backend)
        from frankenpaxos_tpu.tpu.lifecycle import LifecyclePlan

        # Sessions engaged: the [L, S] session table (group+fleet
        # sharded client state) joins the collective census — exactly-
        # once bookkeeping must stay inside one fleet row too.
        base = mod.analysis_config(
            faults=FaultPlan(traced=True),
            workload=WorkloadPlan(arrival="constant", rate=1.0),
            lifecycle=LifecyclePlan(sessions=8, resubmit_rate=0.1),
        )
        state = mod.init_state(base)
        axis_len = spec.axis_len(state)
        n_group = max(
            (
                d
                for d in range(
                    1, min(len(jax.devices()) // 2, axis_len) + 1
                )
                if axis_len % d == 0
            ),
            default=1,
        )
        mesh = _sh.make_fleet_mesh(
            fleet=2, devices=jax.devices()[: 2 * n_group]
        )
        F = 4
        rates_a = [0.5, 1.0, 1.5, 2.0]
        frates_a = [[0.05 * i, 0.0, 0.0, 0.0] for i in range(F)]
        keys = _sh.fleet_keys(range(F))
        t0 = jnp.zeros((), jnp.int32)

        def brick(rates, frates):
            states = _sh.fleet_states(
                backend, base, F, rates=rates, fault_rates=frates
            )
            return _sh.shard_fleet_state(backend, states, mesh)

        wrap = _sh._fleet_wrap_mesh(backend, base, mesh)
        runner = _sh._fleet_runner(backend, mesh, wrap)
        before = runner._cache_size()
        sts, _ = _sh.run_ticks_fleet(
            backend, base, mesh, brick(rates_a, frates_a), t0, _TICKS,
            keys,
        )
        jax.block_until_ready(jax.tree_util.tree_leaves(sts)[0])
        after_first = runner._cache_size()
        # The re-sweep: new traced rates through the SAME executable.
        sts2, _ = _sh.run_ticks_fleet(
            backend, base, mesh,
            brick([2.0, 0.25, 0.75, 1.25], [[0.2, 0.1, 0.0, 0.0]] * F),
            t0, _TICKS, keys,
        )
        jax.block_until_ready(jax.tree_util.tree_leaves(sts2)[0])
        # A pre-warmed runner (another brick of this process already
        # compiled this signature) legally starts at a cache hit; the
        # contract is: at most ONE compile for the first brick, and the
        # re-sweep NEVER compiles.
        if runner._cache_size() != after_first or (
            after_first > before + 1
        ):
            out.append(
                Finding(
                    rule="trace-fleet-onecompile",
                    path=backend,
                    line=0,
                    message=(
                        "the [seeds x workload x fault] brick is not "
                        "one executable per mesh: fleet-runner jit "
                        f"cache went {before} -> {after_first} -> "
                        f"{runner._cache_size()} across a traced-rate "
                        "re-sweep (a rate or fault knob regressed to "
                        "compile-time static)"
                    ),
                    key=f"{backend}:cache",
                )
            )
        # Collective census of the compiled brick: nothing crosses the
        # fleet axis, and no signed state moves at all.
        hlo = _sh.lower_fleet(
            backend, base, mesh, brick(rates_a, frates_a), t0, _TICKS,
            keys,
        ).compile().as_text()
        rows = _fleet_rows(2, n_group)
        for line in hlo.splitlines():
            # Signed/pred shapes only: u32 collectives are threefry
            # PRNG-sweep assembly (bounded separately by the multichip
            # census); protocol state is all signed/pred.
            shapes = _collective_line_shapes(line)
            if not shapes or all(d.startswith("u") for d, _ in shapes):
                continue
            big = [
                tok
                for tok in (
                    "all-gather", "all-to-all", "collective-permute"
                )
                if tok + "(" in line or tok + "-start(" in line
            ]
            if big:
                out.append(
                    Finding(
                        rule="trace-fleet-onecompile",
                        path=backend,
                        line=0,
                        message=(
                            f"signed {big[0]} in the compiled fleet "
                            "brick — simulation state is moving "
                            "between devices (allowed: all-reduce "
                            "stat reductions only)"
                        ),
                        key=f"{backend}:move:{big[0]}",
                    )
                )
            groups = _collective_groups(line)
            if groups is None:
                out.append(
                    Finding(
                        rule="trace-fleet-onecompile",
                        path=backend,
                        line=0,
                        message=(
                            "unparseable replica_groups on a signed "
                            f"collective: {line.strip()[:160]}"
                        ),
                        key=f"{backend}:unparseable",
                    )
                )
                continue
            for grp in groups:
                if not any(set(grp) <= row for row in rows):
                    out.append(
                        Finding(
                            rule="trace-fleet-onecompile",
                            path=backend,
                            line=0,
                            message=(
                                f"signed collective spans fleet rows "
                                f"{sorted(grp)} (rows are "
                                f"{[sorted(r) for r in rows]}) — "
                                "protocol state is crossing the fleet "
                                "axis; instances are no longer "
                                "independent"
                            ),
                            key=f"{backend}:crossfleet",
                        )
                    )
                    break
    return out


# The largest signed collective the FLEET SNAPSHOT program may emit:
# the in-graph fleet_summary's median/MAD sorts move [F]-sized summary
# columns across fleet rows (a legitimate tiny cross-row stat), never
# ring blocks or protocol state. 256 elements is ~25x the widest
# summary column at the rule's brick width and ~3 orders of magnitude
# under the smallest per-instance ring block.
_FLEET_SNAP_COLLECTIVE_MAX = 256


@rule(
    "trace-fleet-drain-nosync",
    "trace",
    "the fleet serve hot path (run_ticks_fleet + the jitted fleet "
    "snapshot with the in-graph summary, harness/serve.py) compiles "
    "free of host callbacks/infeed/outfeed, the snapshot COPIES "
    "(aliases nothing), the summary reduction moves no signed state "
    "across the fleet axis (collectives bounded at summary size), and "
    "a per-instance SLO clamp re-entry keeps the fleet runner's jit "
    "cache flat",
)
def check_fleet_drain_nosync(ctx: Context) -> List[Finding]:
    _jax_cache_setup()
    import jax
    import jax.numpy as jnp

    from frankenpaxos_tpu.harness import serve as serve_mod
    from frankenpaxos_tpu.parallel import sharding as _sh
    from frankenpaxos_tpu.tpu.faults import FaultPlan
    from frankenpaxos_tpu.tpu.workload import WorkloadPlan

    backend = "multipaxos"  # the flagship fleet serve target
    if ctx.backends is not None and backend not in ctx.backends:
        return []
    out: List[Finding] = []
    if len(jax.devices()) < 4:
        import sys

        print(
            "trace-fleet-drain-nosync: SKIPPED (needs >=4 jax devices "
            "for a 2x2 product mesh; set XLA_FLAGS="
            "--xla_force_host_platform_device_count=8 or run via "
            "scripts/lint.sh)",
            file=sys.stderr,
        )
        return out

    mod = _module(backend)
    cfg = mod.analysis_config(
        faults=FaultPlan(traced=True),
        workload=WorkloadPlan(arrival="constant", rate=1.0),
    )
    spec = _sh.SHARDINGS[backend]
    state = mod.init_state(cfg)
    axis_len = spec.axis_len(state)
    n_group = max(
        (
            d
            for d in range(1, min(len(jax.devices()) // 2, axis_len) + 1)
            if axis_len % d == 0
        ),
        default=1,
    )
    mesh = _sh.make_fleet_mesh(
        fleet=2, devices=jax.devices()[: 2 * n_group]
    )
    F = 4
    rates = [0.5, 1.0, 1.5, 2.0]
    frates = [[0.05 * i, 0.0, 0.0, 0.0] for i in range(F)]

    def scan_blocking(hlo: str, where: str):
        for line_no, hit in _blocking_hlo_hits(hlo):
            out.append(
                Finding(
                    rule="trace-fleet-drain-nosync",
                    path=backend,
                    line=line_no,
                    message=(
                        f"{hit} in the compiled fleet {where} — "
                        "the fleet serve hot path would block on "
                        "the host every chunk"
                    ),
                    key=f"{backend}:{where}:{hit}",
                )
            )

    run_lowered, snap_lowered = serve_mod.lower_fleet_chunk_path(
        backend, cfg, mesh, n=F, rates=rates, fault_rates=frates
    )
    scan_blocking(run_lowered.compile().as_text(), "run_ticks_fleet")
    snap_hlo = snap_lowered.compile().as_text()
    scan_blocking(snap_hlo, "snapshot")

    # (a) The snapshot must COPY: draining it after the next chunk
    # donates the fleet state must never read reused buffers.
    aliased = _alias_param_indices(snap_hlo)
    if aliased:
        out.append(
            Finding(
                rule="trace-fleet-drain-nosync",
                path=backend,
                line=0,
                message=(
                    f"the compiled fleet snapshot ALIASES {len(aliased)} "
                    "input buffer(s) — the fleet drain would read "
                    "buffers the next chunk's donation already reused; "
                    "the snapshot must copy"
                ),
                key=f"{backend}:snapshot:aliased",
            )
        )
    # (b) Summary-reduction census (the PR 14 replica-group machinery
    # reused): the in-graph fleet_summary may sort tiny summary
    # columns across fleet rows (median/MAD), but any signed
    # collective above summary size means the snapshot is moving ring
    # blocks or protocol state between instances.
    for line in snap_hlo.splitlines():
        shapes = _collective_line_shapes(line)
        if not shapes or all(d.startswith("u") for d, _ in shapes):
            continue
        worst = max(e for d, e in shapes if not d.startswith("u"))
        if worst > _FLEET_SNAP_COLLECTIVE_MAX:
            out.append(
                Finding(
                    rule="trace-fleet-drain-nosync",
                    path=backend,
                    line=0,
                    message=(
                        f"the compiled fleet snapshot emits a "
                        f"{worst}-element signed collective (allowed: "
                        f"<={_FLEET_SNAP_COLLECTIVE_MAX}-element "
                        "summary stats) — the summary reduction is "
                        "moving per-instance state across the fleet "
                        "axis"
                    ),
                    key=f"{backend}:snapshot:collective:{worst}",
                )
            )
        if _collective_groups(line) is None:
            out.append(
                Finding(
                    rule="trace-fleet-drain-nosync",
                    path=backend,
                    line=0,
                    message=(
                        "unparseable replica_groups on a signed "
                        f"snapshot collective: {line.strip()[:160]}"
                    ),
                    key=f"{backend}:snapshot:unparseable",
                )
            )

    # (c) Clamp re-entry is recompile-free: run a chunk, steer the
    # per-instance traced rates (the SLO control plane's verb —
    # sharding.set_fleet_rates), run another chunk — the fleet
    # runner's jit cache must not grow.
    wrap = _sh._fleet_wrap_mesh(backend, cfg, mesh)
    runner = _sh._fleet_runner(backend, mesh, wrap)
    states = _sh.shard_fleet_state(
        backend,
        _sh.fleet_states(
            backend, cfg, F, rates=rates, fault_rates=frates
        ),
        mesh,
    )
    keys = _sh.place_fleet_keys(_sh.fleet_keys(range(F)), mesh)
    states, t = _sh.run_ticks_fleet(
        backend, cfg, mesh, states, jnp.zeros((), jnp.int32), _TICKS,
        keys,
    )
    jax.block_until_ready(jax.tree_util.tree_leaves(states)[0])
    before = runner._cache_size()
    clamped = [r * s for r, s in zip(rates, (1.0, 0.05, 1.0, 1.0))]
    states = _sh.set_fleet_rates(states, clamped, mesh)
    states, t = _sh.run_ticks_fleet(
        backend, cfg, mesh, states, t, _TICKS,
        jax.vmap(jax.random.fold_in, in_axes=(0, None))(keys, 1),
    )
    jax.block_until_ready(jax.tree_util.tree_leaves(states)[0])
    after = runner._cache_size()
    if after > before:
        out.append(
            Finding(
                rule="trace-fleet-drain-nosync",
                path=backend,
                line=0,
                message=(
                    "a per-instance SLO clamp (set_fleet_rates between "
                    f"chunks) missed the jit cache ({before} -> {after} "
                    "entries) — the clamp vector landed in a static or "
                    "re-sharded argument and every control-plane action "
                    "recompiles the fleet serve loop"
                ),
                key=f"{backend}:clamp-retrace",
            )
        )
    return out
