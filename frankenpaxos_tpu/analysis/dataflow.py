"""Dataflow analysis over closed jaxprs.

The AST layer sees source text and the trace layer sees whole-artifact
facts (alias maps, cache sizes, equation censuses); neither can answer
*dataflow* questions about the compiled tick — "which PRNG stream does
this draw descend from?", "does this State leaf ever reach an output
anyone reads?", "is a donated input consumed after its aliased output
exists?". This module is the shared machinery the ``dataflow`` rule
layer (``rules_dataflow.py``) stands on:

* :func:`linearize` flattens a closed jaxpr into a single ordered list
  of :class:`Node` records, inlining every sub-jaxpr it meets —
  ``pjit``/call bodies verbatim, ``scan``/``while`` bodies ONCE with
  explicit phi nodes modelling the carry feedback edge, and **every**
  ``cond`` branch tagged with a branch context so mutually-exclusive
  paths stay distinguishable. Values get dense integer ids; known
  scalar literals (fold-in salts!) are kept in a side table and
  propagated through dtype/shape-preserving ops.

* :func:`key_lineage` abstractly interprets the linearized program
  over a key-provenance lattice (:class:`KeyProv`): a provenance is a
  root id plus the exact ``fold_in``/``split`` path applied to it,
  with fold constants >= :data:`FAMILY_MIN` recorded as salt-family
  markers. Loop-carried keys are *widened* (fresh root, markers kept)
  so one inlined iteration never fabricates equalities across
  iterations; keys built from non-key data are *foreign*. Every
  ``random_bits`` draw is collected with its provenance and branch
  context.

* :func:`reach_analysis` computes forward reachability from the tick's
  input State leaves to every value (bitmasks over leaf indices,
  iterated to fixpoint across phi feedback), plus per-value producer
  and consumer node indices. ``rules_dataflow`` turns that into
  reaching-definitions over State leaves (dead-write detection) and
  donation-hazard ordering checks.

Everything here is pure graph walking over already-traced jaxprs — no
compilation, no device work — so it is cheap enough to run against all
fifteen backends inside the default lint leg.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

# Fold-in constants at or above this value are salt-FAMILY markers
# (FAULT_SALT = 0x5EED, WORKLOAD_SALT = 0x10AD, LIFECYCLE_SALT =
# 0x11FE all clear it); smaller constants are per-plane or per-sweep
# offsets folded INSIDE a family (``fault_key(key, salt=2)``,
# ``fold_in(key, lane)``) and never establish family membership.
FAMILY_MIN = 4096

# Width of one salt family's private offset interval: a family base B
# owns [B, B + FAMILY_SPAN). Plane salts folded on top of a family
# base must stay below this, or two families' effective fold constants
# could collide (the prng-salt-disjoint rule enforces both halves).
FAMILY_SPAN = 256

# Primitives whose output carries its (single key-ish) input's
# provenance unchanged: pure dtype/layout plumbing the PRNG helpers
# thread keys through (``random_unwrap`` -> u32[2] -> ``random_wrap``
# round trips, scalar converts ahead of fold_in).
_TRANSPARENT = frozenset({
    "squeeze",
    "reshape",
    "broadcast_in_dim",
    "convert_element_type",
    "transpose",
    "copy",
    "rev",
    "stop_gradient",
    "device_put",
})

# Call-like primitives whose single sub-jaxpr is inlined verbatim.
_CALL_PRIMS = frozenset({
    "pjit",
    "closed_call",
    "core_call",
    "xla_call",
    "remat",
    "remat2",
    "checkpoint",
    "custom_jvp_call",
    "custom_vjp_call",
    "custom_jvp_call_jaxpr",
    "custom_vjp_call_jaxpr",
})


@dataclasses.dataclass(frozen=True)
class Node:
    """One linearized equation (or synthetic merge point)."""

    idx: int  # position in program order
    prim: str  # primitive name; synthetic: "phi" | "cond_merge" | ...
    invars: Tuple[int, ...]  # value ids consumed
    outvars: Tuple[int, ...]  # value ids produced
    params: dict  # primitive params (sub-jaxprs stripped)
    branch: Tuple[Tuple[int, int], ...]  # ((cond_uid, branch_idx), ...)


@dataclasses.dataclass
class Graph:
    """Linearized program + side tables."""

    nodes: List[Node]
    invar_ids: List[int]  # value ids of the closed jaxpr's inputs
    outvar_ids: List[int]  # value ids of its outputs
    literals: Dict[int, object]  # value id -> known Python scalar
    # Phi feedback edges: (phi_id, init_id, loopback_id). The phi NODE
    # only lists init_id as an invar (program order); reachability
    # iterates the loopback edge to fixpoint separately.
    phis: List[Tuple[int, int, int]]
    nvals: int

    def producers(self) -> Dict[int, int]:
        """value id -> index of the node producing it."""
        out: Dict[int, int] = {}
        for n in self.nodes:
            for v in n.outvars:
                out[v] = n.idx
        return out

    def consumers(self) -> Dict[int, List[int]]:
        """value id -> indices of nodes consuming it."""
        out: Dict[int, List[int]] = {}
        for n in self.nodes:
            for v in n.invars:
                out.setdefault(v, []).append(n.idx)
        return out


def _scalar_of(val) -> Optional[object]:
    """``val`` as a Python int/float if it is a known scalar."""
    try:
        import numpy as np

        arr = np.asarray(val)
        if arr.ndim == 0 and arr.dtype.kind in "iuf":
            return arr.item()
    except Exception:
        pass
    return None


def linearize(closed) -> Graph:
    """Flatten ``closed`` (a ClosedJaxpr) into one ordered node list.

    Sub-jaxprs are inlined: calls verbatim; ``scan``/``while`` bodies
    once with phi nodes feeding the carry (init -> phi in program
    order, carry-out -> phi as a recorded feedback edge); every
    ``cond`` branch with a per-branch context tag, merged afterwards
    by a synthetic ``cond_merge`` node.
    """
    g = Graph(
        nodes=[], invar_ids=[], outvar_ids=[], literals={}, phis=[],
        nvals=0,
    )
    cond_uids = [0]

    def fresh() -> int:
        g.nvals += 1
        return g.nvals - 1

    def add(prim, invars, n_out, params, branch) -> List[int]:
        outs = [fresh() for _ in range(n_out)]
        g.nodes.append(Node(
            idx=len(g.nodes), prim=prim, invars=tuple(invars),
            outvars=tuple(outs), params=params, branch=branch,
        ))
        return outs

    def atom_id(v, env) -> int:
        # Literal operands get their own id + recorded value; variables
        # resolve through the current environment.
        if hasattr(v, "val") and not hasattr(v, "count"):
            i = fresh()
            s = _scalar_of(v.val)
            if s is not None:
                g.literals[i] = s
            return i
        return env[v]

    def strip(params: dict) -> dict:
        return {
            k: v for k, v in params.items()
            if not hasattr(v, "jaxpr") and not hasattr(v, "eqns")
            and not (
                isinstance(v, (list, tuple))
                and any(hasattr(x, "jaxpr") for x in v)
            )
        }

    def inline(jaxpr, consts, arg_ids, branch) -> List[int]:
        env: Dict[object, int] = {}
        for cv, cval in zip(jaxpr.constvars, consts):
            i = fresh()
            s = _scalar_of(cval)
            if s is not None:
                g.literals[i] = s
            env[cv] = i
        for v, a in zip(jaxpr.invars, arg_ids):
            env[v] = a
        for eqn in jaxpr.eqns:
            handle(eqn, env, branch)
        return [atom_id(v, env) for v in jaxpr.outvars]

    def handle(eqn, env, branch) -> None:
        name = eqn.primitive.name
        in_ids = [atom_id(v, env) for v in eqn.invars]
        params = eqn.params

        if name in _CALL_PRIMS and "jaxpr" in params:
            sub = params["jaxpr"]
            inner = getattr(sub, "jaxpr", sub)
            consts = getattr(sub, "consts", ())
            outs = inline(inner, consts, in_ids, branch)
            for v, o in zip(eqn.outvars, outs):
                env[v] = o
            return

        if name == "scan":
            sub = params["jaxpr"]
            inner, consts = sub.jaxpr, sub.consts
            nc = params.get("num_consts", 0)
            ncar = params.get("num_carry", 0)
            const_ids = in_ids[:nc]
            init_ids = in_ids[nc:nc + ncar]
            xs_ids = in_ids[nc + ncar:]
            # Per-iteration xs element: a slice of the stacked input.
            elt_ids = [
                add("scan_slice", [x], 1, {}, branch)[0] for x in xs_ids
            ]
            phi_ids = []
            for init in init_ids:
                (p,) = add("phi", [init], 1, {}, branch)
                phi_ids.append(p)
            outs = inline(
                inner, consts, const_ids + phi_ids + elt_ids, branch
            )
            carry_out, ys = outs[:ncar], outs[ncar:]
            for p, init, co in zip(phi_ids, init_ids, carry_out):
                g.phis.append((p, init, co))
            stacked = [
                add("scan_stack", [y], 1, {}, branch)[0] for y in ys
            ]
            for v, o in zip(eqn.outvars, carry_out + stacked):
                env[v] = o
            return

        if name == "while":
            cond_j = params["cond_jaxpr"]
            body_j = params["body_jaxpr"]
            cn = params.get("cond_nconsts", 0)
            bn = params.get("body_nconsts", 0)
            c_const = in_ids[:cn]
            b_const = in_ids[cn:cn + bn]
            init_ids = in_ids[cn + bn:]
            phi_ids = []
            for init in init_ids:
                (p,) = add("phi", [init], 1, {}, branch)
                phi_ids.append(p)
            inline(cond_j.jaxpr, cond_j.consts, c_const + phi_ids,
                   branch)
            outs = inline(body_j.jaxpr, body_j.consts,
                          b_const + phi_ids, branch)
            for p, init, co in zip(phi_ids, init_ids, outs):
                g.phis.append((p, init, co))
            # The loop's outputs ARE the (widened) carries.
            for v, p in zip(eqn.outvars, phi_ids):
                env[v] = p
            return

        if name == "cond":
            uid = cond_uids[0]
            cond_uids[0] += 1
            idx_id, op_ids = in_ids[0], in_ids[1:]
            branch_outs = []
            for bi, bj in enumerate(params["branches"]):
                branch_outs.append(inline(
                    bj.jaxpr, bj.consts, op_ids,
                    branch + ((uid, bi),),
                ))
            for k, v in enumerate(eqn.outvars):
                ins = [idx_id] + [outs[k] for outs in branch_outs]
                (m,) = add("cond_merge", ins, 1, {}, branch)
                env[v] = m
            return

        outs = add(name, in_ids, len(eqn.outvars), strip(params),
                   branch)
        # Constant-fold scalar plumbing so fold_in salts that pass
        # through a convert_element_type stay visible as literals.
        if (
            name in _TRANSPARENT
            and len(in_ids) == 1
            and in_ids[0] in g.literals
        ):
            g.literals[outs[0]] = g.literals[in_ids[0]]
        for v, o in zip(eqn.outvars, outs):
            env[v] = o

    jaxpr = closed.jaxpr
    env: Dict[object, int] = {}
    for cv, cval in zip(jaxpr.constvars, closed.consts):
        i = fresh()
        s = _scalar_of(cval)
        if s is not None:
            g.literals[i] = s
        env[cv] = i
        g.invar_ids.append(i)  # consts count as inputs for reach
    n_consts = len(jaxpr.constvars)
    for v in jaxpr.invars:
        i = fresh()
        env[v] = i
        g.invar_ids.append(i)
    for eqn in jaxpr.eqns:
        handle(eqn, env, ())
    g.outvar_ids = [atom_id(v, env) for v in jaxpr.outvars]
    # Real (non-const) inputs come FIRST for callers indexing by the
    # traced function's argument order.
    g.invar_ids = g.invar_ids[n_consts:] + g.invar_ids[:n_consts]
    return g


# ---------------------------------------------------------------------------
# PRNG key lineage
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class KeyProv:
    """Provenance of one key value: a root plus the exact derivation
    path. Two keys with equal (root, path) hold the SAME key value —
    drawing from both is stream reuse. ``markers`` are the >=
    :data:`FAMILY_MIN` fold constants seen on the path (salt-family
    membership); ``widened`` keys crossed a loop-carry merge (identity
    no longer exact); ``foreign`` keys were built from non-key data
    inside the tick (a constant ``PRNGKey(0)`` smuggled past the
    declared key argument)."""

    root: int
    path: Tuple[Tuple[str, object], ...] = ()
    markers: frozenset = frozenset()
    pending_split: bool = False
    widened: bool = False
    foreign: bool = False

    def fold(self, const: Optional[int], var_id: Optional[int]):
        if const is not None:
            ev = ("fold", int(const))
            marks = (
                self.markers | {int(const)}
                if int(const) >= FAMILY_MIN else self.markers
            )
        else:
            ev = ("fold", ("var", var_id))
            marks = self.markers
        return dataclasses.replace(
            self, path=self.path + (ev,), markers=marks,
            pending_split=False,
        )

    def split_child(self, i: object):
        return dataclasses.replace(
            self, path=self.path + (("split", i),), pending_split=False,
        )

    def identity(self) -> Tuple:
        return (self.root, self.path, self.pending_split)

    def describe(self) -> str:
        bits = []
        for kind, arg in self.path:
            if kind == "fold":
                bits.append(
                    f"fold({arg:#x})" if isinstance(arg, int)
                    else "fold(<traced>)"
                )
            else:
                bits.append(
                    f"split[{arg}]" if isinstance(arg, int)
                    else "split[<traced>]"
                )
        head = "key" if self.root == 0 else f"key#{self.root}"
        return ".".join([head] + bits) if bits else head


@dataclasses.dataclass(frozen=True)
class Draw:
    """One ``random_bits`` site."""

    node: int
    prov: KeyProv
    branch: Tuple[Tuple[int, int], ...]
    shape: Tuple[int, ...]


def branches_exclusive(
    a: Tuple[Tuple[int, int], ...], b: Tuple[Tuple[int, int], ...]
) -> bool:
    """True when the two branch contexts cannot both execute: they
    disagree on the branch index of at least one shared cond."""
    da, db = dict(a), dict(b)
    return any(
        uid in db and db[uid] != bi for uid, bi in da.items()
    )


def key_lineage(
    g: Graph, key_id: int
) -> Tuple[List[Draw], Dict[int, KeyProv]]:
    """Abstractly interpret ``g`` over the key-provenance lattice.

    ``key_id`` is the value id of the tick's declared key argument
    (root 0). Returns every draw site plus the final provenance map.
    """
    prov: Dict[int, KeyProv] = {key_id: KeyProv(root=0)}
    draws: List[Draw] = []
    next_root = [1]

    def fresh_prov(**kw) -> KeyProv:
        r = next_root[0]
        next_root[0] += 1
        return KeyProv(root=r, **kw)

    for node in g.nodes:
        name = node.prim
        ins = node.invars
        p0 = prov.get(ins[0]) if ins else None

        if name in ("random_wrap", "random_unwrap"):
            if p0 is not None:
                prov[node.outvars[0]] = p0
            elif name == "random_wrap":
                # A key minted from raw uint32 data that never came
                # from the declared key argument.
                prov[node.outvars[0]] = fresh_prov(foreign=True)
        elif name == "random_split":
            if p0 is not None:
                prov[node.outvars[0]] = dataclasses.replace(
                    p0, pending_split=True
                )
        elif name == "random_fold_in":
            if p0 is not None:
                const = g.literals.get(ins[1]) if len(ins) > 1 else None
                const = int(const) if isinstance(const, int) else (
                    None if const is None else int(const)
                )
                var_id = ins[1] if len(ins) > 1 else None
                prov[node.outvars[0]] = p0.fold(
                    const if const is not None else None,
                    var_id,
                )
        elif name in ("random_bits", "threefry2x32"):
            kp = None
            for i in ins:
                if i in prov:
                    kp = prov[i]
                    break
            if kp is None:
                kp = fresh_prov(foreign=True)
            shape = tuple(node.params.get("shape", ()) or ())
            draws.append(Draw(
                node=node.idx, prov=kp, branch=node.branch, shape=shape,
            ))
            if name == "threefry2x32" and node.outvars:
                prov[node.outvars[0]] = kp
        elif name in ("slice", "dynamic_slice") and p0 is not None:
            if p0.pending_split:
                start = None
                if name == "slice":
                    si = node.params.get("start_indices", ())
                    start = int(si[0]) if si else None
                else:
                    lit = (
                        g.literals.get(ins[1]) if len(ins) > 1 else None
                    )
                    start = int(lit) if lit is not None else None
                prov[node.outvars[0]] = p0.split_child(
                    start if start is not None else ("var", node.idx)
                )
            else:
                prov[node.outvars[0]] = p0
        elif name == "phi":
            pi = prov.get(node.invars[0])
            if pi is not None:
                # A key threaded through a loop carry: widen. Markers
                # survive (family membership is path-stable), exact
                # identity does not.
                prov[node.outvars[0]] = dataclasses.replace(
                    fresh_prov(), markers=pi.markers, widened=True,
                    foreign=pi.foreign,
                )
        elif name == "cond_merge":
            ps = [prov[i] for i in ins[1:] if i in prov]
            if ps:
                if all(p == ps[0] for p in ps) and len(ps) == len(
                    ins
                ) - 1:
                    prov[node.outvars[0]] = ps[0]
                else:
                    marks = frozenset().union(
                        *[p.markers for p in ps]
                    )
                    prov[node.outvars[0]] = dataclasses.replace(
                        fresh_prov(), markers=marks, widened=True,
                        foreign=all(p.foreign for p in ps),
                    )
        elif name in _TRANSPARENT or name in (
            "scan_slice", "scan_stack"
        ):
            if p0 is not None and len(ins) >= 1:
                prov[node.outvars[0]] = p0
        else:
            # Any other primitive consuming a key-tracked value
            # produces data, not a key — no propagation. But a
            # MULTI-key-input op (concatenate of keys, select between
            # keys) yields an unknown key: widen defensively so a
            # later draw is not misattributed.
            keyish = [i for i in ins if i in prov]
            if keyish and name in ("concatenate", "select_n", "gather",
                                   "dynamic_slice", "add", "xor",
                                   "pad"):
                marks = frozenset().union(
                    *[prov[i].markers for i in keyish]
                )
                for o in node.outvars:
                    prov[o] = dataclasses.replace(
                        fresh_prov(), markers=marks, widened=True,
                        foreign=all(prov[i].foreign for i in keyish),
                    )
    return draws, prov


# ---------------------------------------------------------------------------
# Reachability (reaching definitions over input leaves)
# ---------------------------------------------------------------------------


def reach_analysis(
    g: Graph, source_ids: Sequence[int]
) -> Dict[int, int]:
    """Forward reachability: for every value id, a bitmask over
    ``source_ids`` indices of the sources with a dataflow path to it.
    Phi feedback edges are iterated to fixpoint, so a leaf that feeds
    another leaf only via the NEXT loop iteration still reaches it.
    """
    src: Dict[int, int] = {}
    for bit, vid in enumerate(source_ids):
        src[vid] = src.get(vid, 0) | (1 << bit)

    feedback = {p: co for p, _init, co in g.phis}

    def sweep() -> bool:
        changed = False
        for n in g.nodes:
            acc = 0
            for i in n.invars:
                acc |= src.get(i, 0)
            if n.prim == "phi":
                co = feedback.get(n.outvars[0])
                if co is not None:
                    acc |= src.get(co, 0)
            for o in n.outvars:
                base = src.get(o, 0)
                if base | acc != base:
                    src[o] = base | acc
                    changed = True
        return changed

    # One pass reaches everything acyclic; feedback needs fixpoint.
    for _ in range(len(g.phis) + 2):
        if not sweep():
            break
    return src


def closure(adjacency: Dict[int, int], live: int, n: int) -> int:
    """Backward closure of a liveness bitmask over a one-step leaf
    adjacency (``adjacency[j]`` = mask of leaves feeding leaf ``j``):
    a leaf feeding a live leaf is live, across any number of ticks."""
    changed = True
    while changed:
        changed = False
        for j in range(n):
            if live >> j & 1:
                feed = adjacency.get(j, 0)
                if live | feed != live:
                    live |= feed
                    changed = True
    return live
