"""Every intentional exemption from a static-analysis rule, in one
place, each with a mandatory human reason.

Two shapes:

* ``SUPPRESS[rule_id][finding_key] = reason`` — suppresses a finding by
  its stable key. The engine REJECTS stale entries: a key matching no
  current raw finding becomes an ``allowlist-stale`` finding (see
  ``core.run``), so a typo'd or outdated entry can never silently
  exempt nothing.
* ``DTYPE_WIDENING[(backend, conversion)] = (count, reason)`` — the
  trace-layer dtype-policy rule pins the EXACT number of narrow->wide
  integer conversions each backend's compiled tick may contain. Any
  drift in either direction (a new silent upcast, or a removed widening
  leaving budget for a future one) is a finding telling you to update
  the pin. Widening is legitimate ONLY at accumulation/indexing points
  per the dtype policy in ``tpu/common.py``.
"""

from __future__ import annotations

from typing import Dict, Tuple

SUPPRESS: Dict[str, Dict[str, str]] = {
    # rule id -> {finding key -> reason}.
    # Example:
    # "donation-jit": {
    #     "foo_batched.py:replay_ticks":
    #         "replay keeps the input state for post-hoc divergence "
    #         "dumps",
    # },
    "state-dead-write-reachable": {
        # Leaves below carry real protocol observability that today is
        # read only by the test suites (or by a plan-gated path the
        # analysis-config trace structurally omits) — they are kept
        # deliberately, not dead by accident. Surfacing them through
        # stats()/telemetry removes the entry.
        "compartmentalized:rd_row":
            "read-path partition-defer plane: consumed by the grid-row "
            "re-probe only when the fault plan carries an active "
            "partition cut, which the analysis trace (partition=()) "
            "structurally omits",
        "craq:crashes":
            "crash census pinned by the checkpoint/restore suite "
            "(tests/test_checkpoint.py); not yet surfaced in stats()",
        "craq:resyncs":
            "tail-resync census pinned by the checkpoint/restore suite "
            "(tests/test_checkpoint.py); not yet surfaced in stats()",
        "epaxos:snapshots_served":
            "snapshot-read census cross-validated by "
            "tests/test_tpu_epaxos.py; not yet surfaced in a host "
            "summary",
        "epaxos:fast_path_total":
            "fast-path commit census cross-validated by "
            "tests/test_tpu_epaxos.py; not yet surfaced in a host "
            "summary",
        "fastpaxos:chosen_fast":
            "fast-round commit census pinned by "
            "tests/test_tpu_fastpaxos.py; not yet surfaced in a host "
            "summary",
        "grid:chosen_tick":
            "per-slot quorum-formation tick read by the randomized-"
            "family and cross-validation suites to check commit "
            "ordering; not yet surfaced in a host summary",
        "mencius:chosen_tick":
            "per-slot quorum-formation tick read by the randomized-"
            "family and cross-validation suites to check commit "
            "ordering; not yet surfaced in a host summary",
        "multipaxos:chosen_tick":
            "per-slot quorum-formation tick read by the randomized-"
            "family and cross-validation suites to check commit "
            "ordering; not yet surfaced in a host summary",
    },
}

# (backend, "src->dst") -> (exact count, reason). Counts are taken at
# the backend's analysis_config() — the same deterministic small config
# the trace layer jits.
DTYPE_WIDENING: Dict[Tuple[str, str], Tuple[int, str]] = {
    ("fasterpaxos", "int16->int32"): (
        5,
        "int16 seat/ballot epochs feed jnp.mod + take_along_axis "
        "delegate-seating index math ([G,1]-scale control plane, "
        "_seat_server/seating_ok) — index arithmetic widens at the "
        "consumption point per the tpu/common.py dtype policy",
    ),
    ("horizontal", "int16->int32"): (
        5,
        "int16 config epochs feed jnp.mod bank-parity compares against "
        "the int32 row iota ([P,G]/[P,G,W] masks in tick steps 5-6) — "
        "tiny control planes widened at the compare, not state storage",
    ),
}


def suppressions(rule_id: str) -> Dict[str, str]:
    return SUPPRESS.get(rule_id, {})
