import sys

from frankenpaxos_tpu.analysis.cli import main

sys.exit(main())
