"""Watermark-compressed add-only sets.

Capability parity with the reference ``compact`` package:
``CompactSet`` trait (``compact/CompactSet.scala:24-80``) and
``IntPrefixSet`` (``compact/IntPrefixSet.scala``) — an add-only set of
non-negative ints represented as a watermark plus an overflow set: the set
is {x | 0 <= x < watermark} ∪ values. Also ``FakeCompactSet`` for tests.
Proto round-tripping mirrors ``IntPrefixSet.toProto/fromProto``.
"""

from __future__ import annotations

import dataclasses
from typing import FrozenSet, Iterable, Iterator, Set

from frankenpaxos_tpu.core import wire


class CompactSet:
    """Add-only set with best-effort O(1) compaction (CompactSet.scala:24-80)."""

    def add(self, x) -> bool:
        """Add x; returns True if x was newly added."""
        raise NotImplementedError

    def contains(self, x) -> bool:
        raise NotImplementedError

    def union(self, other: "CompactSet") -> "CompactSet":
        raise NotImplementedError

    def diff(self, other: "CompactSet") -> "CompactSet":
        raise NotImplementedError

    def diff_iterator(self, other: "CompactSet") -> Iterator:
        return iter(self.diff(other).materialize())

    def add_all(self, other: "CompactSet") -> "CompactSet":
        raise NotImplementedError

    def subtract_all(self, other: "CompactSet") -> "CompactSet":
        raise NotImplementedError

    def subtract_one(self, x) -> "CompactSet":
        raise NotImplementedError

    @property
    def size(self) -> int:
        raise NotImplementedError

    @property
    def uncompacted_size(self) -> int:
        raise NotImplementedError

    def subset(self) -> "CompactSet":
        """A monotone, especially-compact subset of self."""
        raise NotImplementedError

    def materialize(self) -> Set:
        raise NotImplementedError


@wire.message
@dataclasses.dataclass(frozen=True)
class IntPrefixSetProto:
    watermark: int
    values: tuple


class IntPrefixSet(CompactSet):
    """{0..watermark-1} ∪ values, with values kept disjoint from the prefix
    and the watermark advanced greedily on add (IntPrefixSet.scala)."""

    def __init__(self, watermark: int = 0, values: Iterable[int] = ()):
        self.watermark = watermark
        self.values: Set[int] = {x for x in values if x >= watermark}
        self._compact()

    @staticmethod
    def from_watermark(watermark: int) -> "IntPrefixSet":
        return IntPrefixSet(watermark)

    @staticmethod
    def from_set(values: Iterable[int]) -> "IntPrefixSet":
        return IntPrefixSet(0, values)

    def _compact(self) -> None:
        while self.watermark in self.values:
            self.values.discard(self.watermark)
            self.watermark += 1

    def __repr__(self) -> str:
        return f"IntPrefixSet(watermark={self.watermark}, values={sorted(self.values)})"

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, IntPrefixSet)
            and self.watermark == other.watermark
            and self.values == other.values
        )

    def __hash__(self):
        return hash((self.watermark, frozenset(self.values)))

    def add(self, x: int) -> bool:
        if x < 0:
            raise ValueError(f"IntPrefixSet holds non-negative ints, got {x}")
        if self.contains(x):
            return False
        self.values.add(x)
        self._compact()
        return True

    def contains(self, x: int) -> bool:
        return x < self.watermark or x in self.values

    def union(self, other: "IntPrefixSet") -> "IntPrefixSet":
        w = max(self.watermark, other.watermark)
        return IntPrefixSet(w, self.values | other.values)

    def diff(self, other: "IntPrefixSet") -> "IntPrefixSet":
        """Set difference; the result is a plain overflow set (watermark 0
        unless 0 is in the result, then compaction applies)."""
        mine = self.materialize()
        return IntPrefixSet(0, {x for x in mine if not other.contains(x)})

    def materialized_diff(self, other: "IntPrefixSet") -> Iterable[int]:
        for x in range(other.watermark if other.watermark < self.watermark else 0,
                       self.watermark):
            if not other.contains(x):
                yield x
        for x in sorted(self.values):
            if not other.contains(x):
                yield x

    def diff_iterator(self, other: "IntPrefixSet") -> Iterator[int]:
        return iter(self.materialized_diff(other))

    def add_all(self, other: "IntPrefixSet") -> "IntPrefixSet":
        self.watermark = max(self.watermark, other.watermark)
        self.values |= other.values
        self.values = {x for x in self.values if x >= self.watermark}
        self._compact()
        return self

    def subtract_all(self, other: "IntPrefixSet") -> "IntPrefixSet":
        result = self.diff(other)
        self.watermark = result.watermark
        self.values = result.values
        return self

    def subtract_one(self, x: int) -> "IntPrefixSet":
        if x >= self.watermark:
            self.values.discard(x)
            return self
        # Un-compact the prefix, drop x, re-compact.
        self.values.update(range(self.watermark))
        self.watermark = 0
        self.values.discard(x)
        self._compact()
        return self

    @property
    def size(self) -> int:
        return self.watermark + len(self.values)

    @property
    def uncompacted_size(self) -> int:
        return len(self.values)

    def subset(self) -> "IntPrefixSet":
        # The especially-compact monotone subset: the watermark prefix.
        return IntPrefixSet(self.watermark)

    def materialize(self) -> Set[int]:
        return set(range(self.watermark)) | self.values

    # -- proto ---------------------------------------------------------------

    def to_proto(self) -> IntPrefixSetProto:
        return IntPrefixSetProto(self.watermark, tuple(sorted(self.values)))

    @staticmethod
    def from_proto(proto: IntPrefixSetProto) -> "IntPrefixSet":
        return IntPrefixSet(proto.watermark, set(proto.values))


class FakeCompactSet(CompactSet):
    """An uncompacted CompactSet for tests (FakeCompactSet.scala)."""

    def __init__(self, values: Iterable = ()):
        self._values: Set = set(values)

    def __repr__(self) -> str:
        return f"FakeCompactSet({sorted(self._values)})"

    def __eq__(self, other):
        return isinstance(other, FakeCompactSet) and self._values == other._values

    def __hash__(self):
        return hash(frozenset(self._values))

    def add(self, x) -> bool:
        if x in self._values:
            return False
        self._values.add(x)
        return True

    def contains(self, x) -> bool:
        return x in self._values

    def union(self, other: "FakeCompactSet") -> "FakeCompactSet":
        return FakeCompactSet(self._values | other._values)

    def diff(self, other: "FakeCompactSet") -> "FakeCompactSet":
        return FakeCompactSet(self._values - other._values)

    def add_all(self, other: "FakeCompactSet") -> "FakeCompactSet":
        self._values |= other._values
        return self

    def subtract_all(self, other: "FakeCompactSet") -> "FakeCompactSet":
        self._values -= other._values
        return self

    def subtract_one(self, x) -> "FakeCompactSet":
        self._values.discard(x)
        return self

    @property
    def size(self) -> int:
        return len(self._values)

    @property
    def uncompacted_size(self) -> int:
        return len(self._values)

    def subset(self) -> "FakeCompactSet":
        return FakeCompactSet(self._values)

    def materialize(self) -> Set:
        return set(self._values)
