"""All-pairs heartbeat failure detector.

Capability parity with ``heartbeat/Participant.scala:39-209``: every
participant pings every other; a missing pong within ``fail_period``
triggers a retry; ``num_retries`` consecutive misses mark the peer dead; a
pong revives it and feeds an EWMA estimate of one-way network delay.
Options mimic TCP keepalive (:39-60). ``unsafe_alive()`` /
``unsafe_network_delay()`` must only be called from the same transport's
event loop (:189-208).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Dict, List, Optional, Sequence, Set

from frankenpaxos_tpu.core import Actor, Address, Logger, Transport, wire

INFINITE_DELAY = float("inf")


@wire.message
@dataclasses.dataclass(frozen=True)
class HeartbeatPing:
    index: int  # the destination's index in the *sender's* address list
    clock: float  # sender's clock at send time


@wire.message
@dataclasses.dataclass(frozen=True)
class HeartbeatPong:
    index: int
    clock: float  # echoed


@dataclasses.dataclass(frozen=True)
class HeartbeatOptions:
    fail_period: float = 5.0
    success_period: float = 10.0
    num_retries: int = 3
    network_delay_alpha: float = 0.9


class Participant(Actor):
    def __init__(
        self,
        address: Address,
        transport: Transport,
        logger: Logger,
        addresses: Sequence[Address],
        options: HeartbeatOptions = HeartbeatOptions(),
        clock: Callable[[], float] = time.monotonic,
    ):
        super().__init__(address, transport, logger)
        logger.check_le(0, options.network_delay_alpha)
        logger.check_le(options.network_delay_alpha, 1)
        self.addresses = list(addresses)
        self.options = options
        self.clock = clock
        self.chans = [self.chan(a) for a in self.addresses]
        self.fail_timers = [
            self.timer(f"failTimer{a}", options.fail_period, self._fail_fn(i))
            for i, a in enumerate(self.addresses)
        ]
        self.success_timers = [
            self.timer(f"successTimer{a}", options.success_period, self._succeed_fn(i))
            for i, a in enumerate(self.addresses)
        ]
        self.num_retries: List[int] = [0] * len(self.addresses)
        self.network_delay: Dict[int, float] = {}
        self.alive: Set[Address] = set(self.addresses)
        for i, ch in enumerate(self.chans):
            ch.send(HeartbeatPing(index=i, clock=self.clock()))
            self.fail_timers[i].start()

    def _fail_fn(self, index: int) -> Callable[[], None]:
        def fail() -> None:
            self.num_retries[index] += 1
            if self.num_retries[index] >= self.options.num_retries:
                self.alive.discard(self.addresses[index])
            self.chans[index].send(HeartbeatPing(index=index, clock=self.clock()))
            self.fail_timers[index].start()

        return fail

    def _succeed_fn(self, index: int) -> Callable[[], None]:
        def succeed() -> None:
            self.chans[index].send(HeartbeatPing(index=index, clock=self.clock()))
            self.fail_timers[index].start()

        return succeed

    def receive(self, src: Address, msg) -> None:
        if isinstance(msg, HeartbeatPing):
            self.chan(src).send(HeartbeatPong(index=msg.index, clock=msg.clock))
        elif isinstance(msg, HeartbeatPong):
            self._handle_pong(msg)
        else:
            self.logger.fatal(f"unknown heartbeat message {msg!r}")

    def _handle_pong(self, pong: HeartbeatPong) -> None:
        delay = (self.clock() - pong.clock) / 2
        alpha = self.options.network_delay_alpha
        prev = self.network_delay.get(pong.index)
        self.network_delay[pong.index] = (
            delay if prev is None else alpha * delay + (1 - alpha) * prev
        )
        self.alive.add(self.addresses[pong.index])
        self.num_retries[pong.index] = 0
        self.fail_timers[pong.index].stop()
        self.success_timers[pong.index].start()

    # -- Same-transport-only accessors (Participant.scala:189-208) -----------

    def unsafe_alive(self) -> Set[Address]:
        return set(self.alive)

    def unsafe_network_delay(self) -> Dict[Address, float]:
        out = {}
        for i, a in enumerate(self.addresses):
            if a in self.alive and i in self.network_delay:
                out[a] = self.network_delay[i]
            else:
                out[a] = INFINITE_DELAY
        return out
