"""Cluster topology files (the analog of ``benchmarks/cluster.py``): a JSON
file maps role -> str(f) -> list of host addresses; ``.f(n)`` selects the
sub-cluster for a given fault tolerance."""

from __future__ import annotations

import json
from typing import Dict, List


class Cluster:
    def __init__(self, mapping: Dict[str, Dict[str, List[str]]]):
        self._mapping = mapping

    @staticmethod
    def from_json_file(path: str) -> "Cluster":
        with open(path) as f:
            return Cluster(json.load(f))

    @staticmethod
    def from_json(data: Dict) -> "Cluster":
        return Cluster(data)

    def f(self, n: int) -> "SubCluster":
        return SubCluster(
            {
                role: by_f[str(n)]
                for role, by_f in self._mapping.items()
                if str(n) in by_f
            }
        )

    def roles(self) -> List[str]:
        return sorted(self._mapping)


class SubCluster:
    def __init__(self, mapping: Dict[str, List[str]]):
        self._mapping = mapping

    def __getitem__(self, role: str) -> List[str]:
        return self._mapping[role]

    def get(self, role: str, default=None):
        return self._mapping.get(role, default)

    def roles(self) -> List[str]:
        return sorted(self._mapping)
