"""Workload generators (the analog of ``jvm/.../Workload.scala`` and
``benchmarks/workload.py``): each workload produces state-machine command
bytes; parsed from JSON dicts the way the reference parses pbtxt."""

from __future__ import annotations

import dataclasses
import random
import string
from typing import Dict

from frankenpaxos_tpu.statemachine import kv_get, kv_set


@dataclasses.dataclass
class StringWorkload:
    """Random strings of a given size for AppendLog-style SMs."""

    size_mean: int = 8
    size_std: int = 0

    def get(self, rng: random.Random) -> bytes:
        n = max(1, int(rng.gauss(self.size_mean, self.size_std)))
        return "".join(
            rng.choice(string.ascii_lowercase) for _ in range(n)
        ).encode()

    def to_dict(self) -> Dict:
        return {
            "type": "string",
            "size_mean": self.size_mean,
            "size_std": self.size_std,
        }


@dataclasses.dataclass
class UniformSingleKeyWorkload:
    """KV sets over a uniform choice of num_keys keys."""

    num_keys: int = 100
    size_mean: int = 8

    def get(self, rng: random.Random) -> bytes:
        key = f"k{rng.randrange(self.num_keys)}"
        value = "".join(
            rng.choice(string.ascii_lowercase) for _ in range(self.size_mean)
        )
        return kv_set((key, value))

    def to_dict(self) -> Dict:
        return {
            "type": "uniform_single_key",
            "num_keys": self.num_keys,
            "size_mean": self.size_mean,
        }


@dataclasses.dataclass
class BernoulliSingleKeyWorkload:
    """With probability conflict_rate touch a single hot key, else a fresh
    key (the reference's conflict-rate knob for EPaxos-style protocols)."""

    conflict_rate: float = 0.1
    size_mean: int = 8

    def __post_init__(self):
        self._fresh = 0

    def get(self, rng: random.Random) -> bytes:
        if rng.random() < self.conflict_rate:
            key = "hot"
        else:
            self._fresh += 1
            key = f"fresh{self._fresh}"
        return kv_set((key, "x" * self.size_mean))

    def to_dict(self) -> Dict:
        return {
            "type": "bernoulli_single_key",
            "conflict_rate": self.conflict_rate,
            "size_mean": self.size_mean,
        }


@dataclasses.dataclass
class ReadWriteWorkload:
    """Mixed reads/writes with a fixed read fraction over num_keys keys
    (the analog of multipaxos/ReadWriteWorkload.scala)."""

    read_fraction: float = 0.5
    num_keys: int = 100
    size_mean: int = 8

    def get(self, rng: random.Random) -> bytes:
        key = f"k{rng.randrange(self.num_keys)}"
        if rng.random() < self.read_fraction:
            return kv_get(key)
        return kv_set((key, "x" * self.size_mean))

    def is_read(self, command: bytes) -> bool:
        from frankenpaxos_tpu.core import wire
        from frankenpaxos_tpu.statemachine import KVGetRequest

        return isinstance(wire.decode(command), KVGetRequest)

    def to_dict(self) -> Dict:
        return {
            "type": "read_write",
            "read_fraction": self.read_fraction,
            "num_keys": self.num_keys,
            "size_mean": self.size_mean,
        }


def workload_from_dict(data: Dict):
    kind = data.get("type")
    data = {k: v for k, v in data.items() if k != "type"}
    if kind == "string":
        return StringWorkload(**data)
    if kind == "uniform_single_key":
        return UniformSingleKeyWorkload(**data)
    if kind == "bernoulli_single_key":
        return BernoulliSingleKeyWorkload(**data)
    if kind == "read_write":
        return ReadWriteWorkload(**data)
    raise ValueError(f"unknown workload type {kind!r}")
