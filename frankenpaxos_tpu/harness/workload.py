"""Workload generators (the analog of ``jvm/.../Workload.scala`` and
``benchmarks/workload.py``): each workload produces state-machine command
bytes; parsed from JSON dicts the way the reference parses pbtxt.

ONE config surface with the device engine: every generator here and the
in-graph :class:`frankenpaxos_tpu.tpu.workload.WorkloadPlan` share the
same ``{"type": ..., ...}`` JSON dict schema and round-trip through
:func:`workload_from_dict` (a ``"device_plan"`` dict deserializes to
the device plan), and the skewed generators draw their key weights from
the SAME :func:`frankenpaxos_tpu.tpu.workload.zipf_weights` vector the
device engine skews its lane arrivals with — so a host command-byte
workload and a device traffic shape describing the same experiment are
one JSON document apart, not two vocabularies."""

from __future__ import annotations

import dataclasses
import random
import string
from typing import Dict

from frankenpaxos_tpu.statemachine import kv_get, kv_set
from frankenpaxos_tpu.tpu.workload import WorkloadPlan, zipf_weights


@dataclasses.dataclass
class StringWorkload:
    """Random strings of a given size for AppendLog-style SMs."""

    size_mean: int = 8
    size_std: int = 0

    def get(self, rng: random.Random) -> bytes:
        n = max(1, int(rng.gauss(self.size_mean, self.size_std)))
        return "".join(
            rng.choice(string.ascii_lowercase) for _ in range(n)
        ).encode()

    def to_dict(self) -> Dict:
        return {
            "type": "string",
            "size_mean": self.size_mean,
            "size_std": self.size_std,
        }


@dataclasses.dataclass
class UniformSingleKeyWorkload:
    """KV sets over a uniform choice of num_keys keys."""

    num_keys: int = 100
    size_mean: int = 8

    def get(self, rng: random.Random) -> bytes:
        key = f"k{rng.randrange(self.num_keys)}"
        value = "".join(
            rng.choice(string.ascii_lowercase) for _ in range(self.size_mean)
        )
        return kv_set((key, value))

    def to_dict(self) -> Dict:
        return {
            "type": "uniform_single_key",
            "num_keys": self.num_keys,
            "size_mean": self.size_mean,
        }


@dataclasses.dataclass
class BernoulliSingleKeyWorkload:
    """With probability conflict_rate touch a single hot key, else a fresh
    key (the reference's conflict-rate knob for EPaxos-style protocols)."""

    conflict_rate: float = 0.1
    size_mean: int = 8

    def __post_init__(self):
        self._fresh = 0

    def get(self, rng: random.Random) -> bytes:
        if rng.random() < self.conflict_rate:
            key = "hot"
        else:
            self._fresh += 1
            key = f"fresh{self._fresh}"
        return kv_set((key, "x" * self.size_mean))

    def to_dict(self) -> Dict:
        return {
            "type": "bernoulli_single_key",
            "conflict_rate": self.conflict_rate,
            "size_mean": self.size_mean,
        }


@dataclasses.dataclass
class ReadWriteWorkload:
    """Mixed reads/writes with a fixed read fraction over num_keys keys
    (the analog of multipaxos/ReadWriteWorkload.scala)."""

    read_fraction: float = 0.5
    num_keys: int = 100
    size_mean: int = 8

    def get(self, rng: random.Random) -> bytes:
        key = f"k{rng.randrange(self.num_keys)}"
        if rng.random() < self.read_fraction:
            return kv_get(key)
        return kv_set((key, "x" * self.size_mean))

    def is_read(self, command: bytes) -> bool:
        from frankenpaxos_tpu.core import wire
        from frankenpaxos_tpu.statemachine import KVGetRequest

        return isinstance(wire.decode(command), KVGetRequest)

    def to_dict(self) -> Dict:
        return {
            "type": "read_write",
            "read_fraction": self.read_fraction,
            "num_keys": self.num_keys,
            "size_mean": self.size_mean,
        }


@dataclasses.dataclass
class ZipfSingleKeyWorkload:
    """KV sets over a Zipf-skewed choice of num_keys keys — the host
    command-byte twin of the device engine's hot-key axis: the key
    weights are exactly ``tpu.workload.zipf_weights(num_keys, zipf_s)``
    (key 0 is the hot key), so a host run and a device ``WorkloadPlan``
    with the same ``zipf_s`` skew the same distribution."""

    num_keys: int = 100
    zipf_s: float = 1.0
    size_mean: int = 8

    def __post_init__(self):
        self._weights = list(zipf_weights(self.num_keys, self.zipf_s))

    def get(self, rng: random.Random) -> bytes:
        key = f"k{rng.choices(range(self.num_keys), self._weights)[0]}"
        value = "".join(
            rng.choice(string.ascii_lowercase) for _ in range(self.size_mean)
        )
        return kv_set((key, value))

    def to_dict(self) -> Dict:
        return {
            "type": "zipf_single_key",
            "num_keys": self.num_keys,
            "zipf_s": self.zipf_s,
            "size_mean": self.size_mean,
        }


def workload_from_dict(data: Dict):
    """The shared deserializer: host command-byte generators AND the
    device :class:`WorkloadPlan` (``type: "device_plan"``) come back
    from the same JSON dict schema."""
    kind = data.get("type")
    if kind == "device_plan":
        return WorkloadPlan.from_dict(data)
    data = {k: v for k, v in data.items() if k != "type"}
    if kind == "string":
        return StringWorkload(**data)
    if kind == "uniform_single_key":
        return UniformSingleKeyWorkload(**data)
    if kind == "bernoulli_single_key":
        return BernoulliSingleKeyWorkload(**data)
    if kind == "zipf_single_key":
        return ZipfSingleKeyWorkload(**data)
    if kind == "read_write":
        return ReadWriteWorkload(**data)
    raise ValueError(f"unknown workload type {kind!r}")
