"""The analysis layer (L6): recorder CSVs -> pandas -> summaries/plots.

The reference's benchmark results are analyzed with a small pandas
toolbox (``benchmarks/pd_util.py``: concatenated CSV loading, outlier
pruning, rolling-window throughput, counter rates) feeding matplotlib
plot scripts (``benchmarks/plot_latency_and_throughput.py`` and the
per-paper figure directories). This module provides the same capability
surface over this framework's recorder CSVs (``start,stop,
latency_nanos,label`` rows with unix-epoch float timestamps, written by
the closed-loop client mains) and over Suite ``results.csv`` tables.
"""

from __future__ import annotations

import os
from typing import Dict, Iterable, List, Optional, Sequence

import numpy as np
import pandas as pd


def read_recorder_csvs(paths: Iterable[str]) -> pd.DataFrame:
    """Load one or more recorder CSVs into a single frame indexed by start
    time (datetime), with a ``latency_ms`` column (pd_util.read_csvs)."""
    frames = [pd.read_csv(p, header=0) for p in paths]
    df = pd.concat(frames, ignore_index=True)
    df["start"] = pd.to_datetime(df["start"], unit="s")
    df["stop"] = pd.to_datetime(df["stop"], unit="s")
    df["latency_ms"] = df["latency_nanos"] / 1e6
    df = df.sort_values("start")
    df.index = df["start"]
    return df


def outliers(s: pd.Series, n_std: float) -> pd.Series:
    """Boolean mask of values >= n_std standard deviations from the mean
    (pd_util.outliers); prune with ``s[~outliers(s, n)]``."""
    return (s - s.mean()).abs() >= n_std * s.std()


def rolling_throughput(
    timestamps: pd.Series, window_ms: float = 1000.0, trim: bool = True
) -> pd.Series:
    """Events/second over rolling windows whose right edges are the given
    timestamps (pd_util.throughput). ``trim`` drops the first window,
    whose left edge precedes the data."""
    ticks = pd.Series(1.0, index=pd.DatetimeIndex(timestamps).sort_values())
    tp = ticks.rolling(f"{int(window_ms)}ms").count() / (window_ms / 1000.0)
    if trim and len(tp):
        cutoff = tp.index[0] + pd.Timedelta(milliseconds=window_ms)
        tp = tp[tp.index >= cutoff]
    return tp


def weighted_throughput(
    counts: pd.Series, window_ms: float = 1000.0
) -> pd.Series:
    """Like rolling_throughput but each timestamped measurement carries a
    count (pd_util.weighted_throughput) — e.g. batch sizes."""
    counts = counts.sort_index()
    tp = counts.rolling(f"{int(window_ms)}ms").sum() / (window_ms / 1000.0)
    if len(tp):
        cutoff = tp.index[0] + pd.Timedelta(milliseconds=window_ms)
        tp = tp[tp.index >= cutoff]
    return tp


def rate(s: pd.Series, window_ms: float = 1000.0) -> pd.Series:
    """Rate of change of a monotone counter over rolling windows
    (pd_util.rate; the PromQL ``rate()`` analog for scraped counters)."""

    def dxdt(win: pd.Series) -> float:
        dt = (win.index[-1] - win.index[0]).total_seconds()
        if dt == 0:
            return np.nan
        return (win.iloc[-1] - win.iloc[0]) / dt

    return s.sort_index().rolling(f"{int(window_ms)}ms", min_periods=2).apply(
        dxdt, raw=False
    )


def rolling_latency_quantiles(
    df: pd.DataFrame,
    window_ms: float = 500.0,
    quantiles: Sequence[float] = (0.5, 0.9, 0.99),
) -> Dict[float, pd.Series]:
    """Per-quantile rolling latency series from a recorder frame."""
    lat = df["latency_ms"]
    return {
        q: lat.rolling(f"{int(window_ms)}ms").quantile(q) for q in quantiles
    }


def summarize(df: pd.DataFrame, drop_seconds: float = 0.0) -> dict:
    """One-row summary of a recorder frame: count, duration, mean
    throughput, latency percentiles (benchmark.py's percentile
    summarization, as a DataFrame-level operation)."""
    if drop_seconds and len(df):
        cutoff = df.index[0] + pd.Timedelta(seconds=drop_seconds)
        df = df[df.index >= cutoff]
    if not len(df):
        return {"count": 0}
    duration_s = (df["stop"].max() - df["start"].min()).total_seconds()
    lat = df["latency_ms"]
    return {
        "count": int(len(df)),
        "duration_s": round(duration_s, 3),
        "throughput_per_s": (
            round(len(df) / duration_s, 1) if duration_s > 0 else float("nan")
        ),
        "latency_mean_ms": round(float(lat.mean()), 3),
        "latency_p50_ms": round(float(lat.quantile(0.5)), 3),
        "latency_p90_ms": round(float(lat.quantile(0.9)), 3),
        "latency_p99_ms": round(float(lat.quantile(0.99)), 3),
        "latency_max_ms": round(float(lat.max()), 3),
    }


def suite_results(suite_dir: str) -> pd.DataFrame:
    """Load a Suite directory's ``results.csv`` (one row per benchmark,
    flattened input/output columns) into a DataFrame."""
    return pd.read_csv(os.path.join(suite_dir, "results.csv"), header=0)


def plot_latency_and_throughput(
    df: pd.DataFrame,
    output: str,
    drop_seconds: float = 0.0,
    window_ms: float = 500.0,
    tp_window_ms: float = 1000.0,
) -> str:
    """The plot_latency_and_throughput.py analog: a two-panel figure of
    rolling latency quantiles and rolling start/stop throughput."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    if drop_seconds and len(df):
        cutoff = df.index[0] + pd.Timedelta(seconds=drop_seconds)
        df = df[df.index >= cutoff]

    fig, (ax_lat, ax_tp) = plt.subplots(2, 1, figsize=(6.4, 9.6))
    for q, series in rolling_latency_quantiles(df, window_ms).items():
        ax_lat.plot(series.index, series.values, label=f"p{int(q * 100)}")
    ax_lat.set_title(f"Latency (rolling {int(window_ms)}ms)")
    ax_lat.set_ylabel("latency (ms)")

    tp_start = rolling_throughput(df["start"], tp_window_ms)
    tp_stop = rolling_throughput(df["stop"], tp_window_ms)
    ax_tp.plot(tp_start.index, tp_start.values, label="start")
    ax_tp.plot(tp_stop.index, tp_stop.values, label="stop", alpha=0.7)
    ax_tp.set_title(f"Throughput (rolling {int(tp_window_ms)}ms)")
    ax_tp.set_ylabel("ops/s")

    for ax in (ax_lat, ax_tp):
        ax.grid(True)
        ax.legend(loc="best")
        for label in ax.get_xticklabels():
            label.set_rotation(20)
            label.set_ha("right")
    fig.tight_layout()
    fig.savefig(output)
    plt.close(fig)
    return output


def analyze_benchmark_dir(
    bench_dir: str, output: Optional[str] = None, drop_seconds: float = 0.0
) -> dict:
    """One command for one benchmark directory: find recorder CSVs, write
    the latency/throughput plot next to them, return the summary."""
    recorders: List[str] = []
    for name in sorted(os.listdir(bench_dir)):
        if name.endswith(".csv") and "recorder" in name:
            recorders.append(os.path.join(bench_dir, name))
    if not recorders:
        raise FileNotFoundError(f"no recorder CSVs in {bench_dir}")
    df = read_recorder_csvs(recorders)
    output = output or os.path.join(bench_dir, "latency_and_throughput.png")
    plot_latency_and_throughput(df, output, drop_seconds=drop_seconds)
    summary = summarize(df, drop_seconds=drop_seconds)
    summary["plot"] = output
    metrics_csv = os.path.join(bench_dir, "metrics.csv")
    if os.path.exists(metrics_csv):
        from frankenpaxos_tpu.monitoring.dashboard import render_dashboard
        from frankenpaxos_tpu.monitoring.scrape import MetricsCapture

        dash = render_dashboard(
            MetricsCapture(metrics_csv),
            os.path.join(bench_dir, "dashboard.png"),
        )
        if dash:
            summary["dashboard"] = dash
    return summary
