"""Device-scale simulation testing: randomized fault schedules against
property checks, batched on-device.

This is the ``FakeTransport`` bad-history workflow (SimulatedSystem-style
tests in the reference) rebuilt for the batched backends: a
:class:`SimSpec` registry names every ``tpu/*_batched.py`` backend with a
small config factory, a progress (liveness) counter, and its partition
axis; the harness then

  * draws randomized :class:`FaultPlan` schedules (:func:`random_plan` —
    deterministic from a ``random.Random`` seed) and, JOINTLY, randomized
    :class:`WorkloadPlan` traffic shapes (:func:`random_workload`:
    open-loop arrival processes with Zipf skew, read/write mixes where
    the backend has a read path, and closed-loop client windows — the
    [workload x fault] axis of tpu/workload.py),
  * runs them while checking ``check_invariants`` after every segment
    (:func:`run_schedule`),
  * fans the SEED axis out on-device: one compiled scan, vmapped over
    any number of PRNG seeds, returning per-seed invariant verdicts
    (:func:`run_many_seeds` — the "thousands of randomized schedules
    per compiled scan" axis; a schedule's rates are static, its
    randomness is the seed),
  * packs a whole [seeds x schedules] BRICK into ONE compiled
    executable (:func:`run_fleet` — the fleet axis of
    ``parallel/sharding.py``): with ``FaultPlan(traced=True)`` and a
    shaped workload, every Bernoulli fault rate and the offered load
    are per-instance STATE, so N randomized schedules x M seeds are
    N*M fleet instances of one program — device-rate fuzzing at
    thousands of schedules/sec instead of one python loop iteration
    per config, invariants reduced per-instance in-graph. Runs on the
    default device (``mesh=None``) or any ``('fleet', 'groups')``
    product mesh, one executable per mesh,
  * asserts liveness resumes after a scheduled partition heal
    (:func:`check_liveness_after_heal`), and
  * greedily SHRINKS a failing plan to a minimized reproducer dumped as
    JSON (:func:`shrink` / :func:`dump_reproducer` /
    :func:`load_reproducer`) — the counterexample-minimization loop of
    the reference's simulation tests.

CLI::

    python -m frankenpaxos_tpu.harness.simtest \
        --backends multipaxos,mencius --schedules 16 --seeds 4 \
        --out results/simtest_sweep.json
"""

from __future__ import annotations

import dataclasses
import functools
import json
import random as _random
import zlib
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from frankenpaxos_tpu.tpu import (
    bpaxos_batched,
    caspaxos_batched,
    compartmentalized_batched,
    craq_batched,
    epaxos_batched,
    fasterpaxos_batched,
    fastmultipaxos_batched,
    fastpaxos_batched,
    grid_batched,
    horizontal_batched,
    mencius_batched,
    multipaxos_batched,
    scalog_batched,
    unreplicated_batched,
    vanillamencius_batched,
)
from frankenpaxos_tpu.tpu import elastic as elastic_mod
from frankenpaxos_tpu.tpu import lifecycle as lifecycle_mod
from frankenpaxos_tpu.tpu.elastic import ElasticPlan
from frankenpaxos_tpu.tpu.faults import FaultPlan
from frankenpaxos_tpu.tpu.lifecycle import LifecyclePlan
from frankenpaxos_tpu.tpu.workload import WorkloadPlan

# Segment grid: schedule boundaries (partition start/heal) snap to
# multiples of this so run_schedule's per-segment compiles are reused
# across schedules (run_ticks specializes on the tick count).
SEGMENT = 40


@dataclasses.dataclass(frozen=True)
class SimSpec:
    """One backend's entry in the simulation-testing registry."""

    name: str
    module: object  # the tpu/*_batched.py module
    make_config: Callable[[FaultPlan], object]
    progress: Callable[[object], jnp.ndarray]  # liveness counter (traced ok)
    partition_axis: int  # side-bit count random_plan must produce
    crash_ok: bool = True  # the backend reacts to crash/revive knobs
    # Liveness-after-heal is asserted only where a healed partition is
    # guaranteed to resume progress within a recovery segment.
    liveness: bool = True
    # Longest partition window random_plan may draw (ticks); None = the
    # horizon. Backends with ring-residency bounds set this (epaxos: a
    # cut column's instances must still fit the frontier-history ring
    # at the heal tick, or its config assertion fires).
    max_partition_span: Optional[int] = None
    # The backend's analysis config has a device read path, so
    # random_workload may draw a read/write mix for it.
    read_mix_ok: bool = False
    # The backend consumes the traced conflict-density knob
    # (WorkloadPlan.conflict_rate -> WorkloadState.conflict), so
    # random_workload may draw a conflict rate — the [faults x
    # conflict] joint axis of the dependency-graph backends.
    conflict_ok: bool = False
    # The backend threads the production-lifecycle subsystem
    # (tpu/lifecycle.py), so the reconfiguration-epoch axis
    # (run_reconfig_schedule / random_lifecycle) applies.
    lifecycle_ok: bool = False
    # The backend threads the elastic-capacity subsystem
    # (tpu/elastic.py), so the [faults x resize] churn axis
    # (run_elastic_schedule / random_elastic) applies.
    elastic_ok: bool = False


def _specs() -> Dict[str, SimSpec]:
    cz = compartmentalized_batched
    mp = multipaxos_batched
    me = mencius_batched
    vm = vanillamencius_batched
    fx = fasterpaxos_batched
    hz = horizontal_batched
    gr = grid_batched
    fm = fastmultipaxos_batched
    fpx = fastpaxos_batched
    cp = caspaxos_batched
    cr = craq_batched
    ep = epaxos_batched
    sc = scalog_batched
    ur = unreplicated_batched
    entries = [
        SimSpec(
            "multipaxos", mp,
            mp.analysis_config,
            lambda st: st.committed, partition_axis=3,
            lifecycle_ok=True, elastic_ok=True,
        ),
        SimSpec(
            "mencius", me,
            me.analysis_config,
            lambda st: st.committed, partition_axis=3,
            # A crashed mencius leader pins the global watermark (plain
            # Mencius has no revocation); commits still advance, but a
            # crash landing near the end of a run can legitimately hold
            # the post-heal delta at zero.
            liveness=False,
        ),
        SimSpec(
            "vanillamencius", vm,
            vm.analysis_config,
            lambda st: st.committed, partition_axis=3,
        ),
        SimSpec(
            "fasterpaxos", fx,
            fx.analysis_config,
            lambda st: st.committed, partition_axis=3,
        ),
        SimSpec(
            "horizontal", hz,
            hz.analysis_config,
            lambda st: st.committed, partition_axis=6,
        ),
        SimSpec(
            "grid", gr,
            gr.analysis_config,
            lambda st: st.committed, partition_axis=9, crash_ok=False,
        ),
        SimSpec(
            # Crash/revive drives the per-group proposer: a dead
            # proposer admits nothing and re-sends nothing; a revival
            # triggers the recovery election (instant re-broadcast of
            # every pending command), so commits resume after revival
            # — the liveness-after-revive schedule in
            # tests/test_tpu_fastmultipaxos.py pins exactly that.
            "fastmultipaxos", fm,
            fm.analysis_config,
            lambda st: st.committed_slots, partition_axis=3,
        ),
        SimSpec(
            # Crash/revive drives the per-group round-0 proposer pair
            # (the vote-counting client role): dead proposers issue and
            # observe nothing; revival resumes the gated transitions
            # and the recovery timeout rescues starved instances — the
            # liveness-after-revive schedule in
            # tests/test_tpu_fastpaxos.py pins exactly that.
            "fastpaxos", fpx,
            fpx.analysis_config,
            lambda st: st.chosen_total, partition_axis=3,
        ),
        SimSpec(
            "caspaxos", cp,
            cp.analysis_config,
            lambda st: st.commits, partition_axis=3, crash_ok=False,
            # CASPaxos leaders stall while a quorum is cut and their
            # exchanges buffer to the heal tick; commits resume, but a
            # backoff can straddle the final segment.
            liveness=False,
        ),
        SimSpec(
            # Crash/revive drives the chain's MIDDLE nodes (head/tail
            # pinned — chain-membership replacement is the coordination
            # service's job): the chain re-stitches around dead nodes
            # in-tick, acks buffer to a dead member and re-propagate on
            # revive, and revived nodes catch up from the tail before
            # serving clean reads (tpu/craq_batched.py crash axis —
            # the carried PR 3 (b) gap, closed).
            "craq", cr,
            cr.analysis_config,
            lambda st: st.writes_done, partition_axis=3,
            read_mix_ok=True,
        ),
        SimSpec(
            "epaxos", ep,
            ep.analysis_config,
            lambda st: st.committed_total, partition_axis=5,
            # frontier_history=256, lat_max=3: span + 24 < 256.
            max_partition_span=200,
            conflict_ok=True,
        ),
        SimSpec(
            # TRUE EPaxos execution: the factored snapshot MATERIALIZED
            # into the packed adjacency and executed through the
            # depgraph_execute plane (general_deps=True). Same shape
            # and liveness envelope as "epaxos"; the dep_safety_ok
            # invariant (no instance executes before its committed
            # dependencies, checked against the live bitmask) joins
            # every boundary check.
            "epaxos_dg", ep,
            ep.analysis_config_general,
            lambda st: st.committed_total, partition_axis=5,
            max_partition_span=200,
            conflict_ok=True,
        ),
        SimSpec(
            # Leaderless BPaxos over the explicit dependency-graph
            # plane: a cut leader lane's consensus rounds defer to the
            # heal tick, and every dependency chain through its
            # vertices stalls with it — liveness resumes after heal
            # once the deferred commits land and the closure drains.
            # dep_safety_ok asserts per-replica execution order against
            # the live adjacency at every segment boundary; the traced
            # conflict knob (conflict_ok) randomizes graph density per
            # schedule without recompiling.
            "bpaxos", bpaxos_batched,
            bpaxos_batched.analysis_config,
            lambda st: st.committed_total, partition_axis=3,
            crash_ok=False,
            conflict_ok=True,
        ),
        SimSpec(
            "scalog", sc,
            sc.analysis_config,
            lambda st: st.committed_cuts, partition_axis=4,
        ),
        SimSpec(
            "unreplicated", ur,
            ur.analysis_config,
            lambda st: st.done, partition_axis=4, crash_ok=False,
        ),
        SimSpec(
            # Partition cuts cells of the per-group 2x2 acceptor grid
            # (the leader's full-grid retries restore liveness after
            # heal); crash/revive drives the proxy-leader plane. The
            # progress counter sums writes AND reads, so the
            # liveness-after-heal assertion also covers the read
            # replicas' probe path (reads defer across a cut row).
            "compartmentalized", cz,
            cz.analysis_config,
            lambda st: st.committed + st.reads_done, partition_axis=4,
            read_mix_ok=True, lifecycle_ok=True, elastic_ok=True,
        ),
    ]
    return {s.name: s for s in entries}


SPECS: Dict[str, SimSpec] = _specs()


# ---------------------------------------------------------------------------
# Randomized schedules
# ---------------------------------------------------------------------------


def _draw_drop_dup(rng: _random.Random) -> Tuple[float, float]:
    """The fuzz distribution of the drop/dup Bernoulli rates — ONE
    definition shared by :func:`random_plan` (static plans) and
    :func:`random_rate_cell` (traced fleet bricks), so retuning the
    ranges keeps both fuzzers sampling the same space. 0.0 = knob off."""
    drop = (
        round(rng.uniform(0.02, 0.25), 3) if rng.random() < 0.7 else 0.0
    )
    dup = (
        round(rng.uniform(0.02, 0.2), 3) if rng.random() < 0.4 else 0.0
    )
    return drop, dup


def _draw_crash(
    rng: _random.Random, spec: SimSpec
) -> Tuple[float, float]:
    """The shared crash/revive fuzz distribution ((0, 0) = off; always
    off for ``crash_ok=False`` backends)."""
    if spec.crash_ok and rng.random() < 0.35:
        return (
            round(rng.uniform(0.005, 0.05), 3),
            round(rng.uniform(0.1, 0.3), 3),
        )
    return 0.0, 0.0


def random_plan(
    rng: _random.Random, spec: SimSpec, horizon: int
) -> FaultPlan:
    """One randomized fault schedule, deterministic from ``rng``'s state.
    Partition heals always land on the SEGMENT grid inside the horizon,
    so every schedule's liveness-after-heal is checkable and the
    per-segment compiles are shared across schedules."""
    kw: dict = {}
    drop, dup = _draw_drop_dup(rng)
    if drop:
        kw["drop_rate"] = drop
    if dup:
        kw["dup_rate"] = dup
    if rng.random() < 0.5:
        kw["jitter"] = rng.randint(1, 3)
    crash, revive = _draw_crash(rng, spec)
    if crash:
        kw["crash_rate"] = crash
        kw["revive_rate"] = revive
    if rng.random() < 0.5:
        n = spec.partition_axis
        # Cut a strict minority of the replica axis (side 1).
        cut = rng.sample(range(n), rng.randint(1, max(1, (n - 1) // 2)))
        side = tuple(1 if i in cut else 0 for i in range(n))
        n_seg = max(2, horizon // SEGMENT)
        heal_seg = rng.randint(1, n_seg - 1)
        heal = heal_seg * SEGMENT
        start = rng.randint(0, heal - 1)
        if (
            spec.max_partition_span is not None
            and heal - start > spec.max_partition_span
        ):
            start = heal - spec.max_partition_span
        kw["partition"] = side
        kw["partition_heal"] = heal
        kw["partition_start"] = start
    return FaultPlan(**kw)


def random_workload(
    rng: _random.Random, spec: SimSpec, horizon: int
) -> WorkloadPlan:
    """One randomized traffic shape, deterministic from ``rng``'s
    state — the workload half of the joint [workload x fault]
    randomization. ~30% saturation (the pre-workload behavior), ~15%
    pure closed loop, else an open-loop arrival process with optional
    Zipf skew, closed window, and (where the backend has a read path)
    a read/write mix. Rates are sized for the SMALL analysis configs
    (1-3 proposals per lane per tick). Dependency-graph backends
    (``conflict_ok``) additionally draw a traced conflict density most
    of the time — the [faults x conflict-rate] joint axis — riding the
    same plan (one compile per schedule shape, the rate is state)."""
    plan = _random_workload_shape(rng, spec, horizon)
    if spec.conflict_ok and rng.random() < 0.65:
        plan = dataclasses.replace(
            plan, conflict_rate=round(rng.uniform(0.0, 0.9), 3)
        )
    return plan


def _random_workload_shape(
    rng: _random.Random, spec: SimSpec, horizon: int
) -> WorkloadPlan:
    r = rng.random()
    if r < 0.30:
        return WorkloadPlan.none()
    if r < 0.45:  # pure closed loop (admission gated on completions)
        return WorkloadPlan(
            closed_window=rng.randint(2, 8),
            think_time=rng.randint(0, 3),
        )
    kw: dict = {
        "arrival": rng.choice(
            ["constant", "poisson", "bursty", "diurnal"]
        ),
        "rate": round(rng.uniform(0.3, 2.5), 2),
    }
    if kw["arrival"] == "bursty":
        kw["burst_every"] = rng.choice([16, 32, 64])
        kw["burst_len"] = rng.randint(2, 8)
        kw["burst_mult"] = round(rng.uniform(2.0, 5.0), 1)
    elif kw["arrival"] == "diurnal":
        kw["phases"] = tuple(
            round(rng.uniform(0.3, 3.0), 2)
            for _ in range(rng.randint(2, 4))
        )
        kw["phase_len"] = rng.choice([8, 16, 32])
    if rng.random() < 0.5:
        kw["zipf_s"] = round(rng.uniform(0.3, 1.2), 2)
    if spec.read_mix_ok and rng.random() < 0.4:
        kw["read_fraction"] = round(rng.uniform(0.1, 0.5), 2)
    if rng.random() < 0.35:
        kw["closed_window"] = rng.randint(2, 8)
        kw["think_time"] = rng.randint(0, 3)
    return WorkloadPlan(**kw)


def random_lifecycle(
    rng: _random.Random, spec: SimSpec, horizon: int
) -> LifecyclePlan:
    """One randomized lifecycle shape for a lifecycle-threaded backend
    (deterministic from ``rng``): the reconfiguration axis is always
    armed (it is what :func:`run_reconfig_schedule` churns), window
    rotation and the session table ride along ~half the time. Rotation
    quanta are sized against the HORIZON (the analysis configs retire
    roughly a slot per lane-tick, align 16), so a drawn rotation leg
    actually fires within the schedule instead of being dead weight."""
    if not spec.lifecycle_ok:
        return LifecyclePlan.none()
    kw: dict = {"reconfig": True}
    if rng.random() < 0.7 and horizon >= 80:
        kw["rotate_every"] = 16 * rng.randint(
            1, max(1, min(4, horizon // 80))
        )
    if rng.random() < 0.5:
        kw["sessions"] = rng.choice([2, 4, 8])
        kw["resubmit_rate"] = round(rng.uniform(0.05, 0.3), 3)
    return LifecyclePlan(**kw)


# Padded-capacity axes per elastic-threaded backend, matching the
# analysis_config shapes (the capacity IS the structural count — the
# plan pads nothing extra at analysis scale; floors of 1 leave every
# role shrinkable).
_ELASTIC_AXES: Dict[str, Tuple[Tuple[str, int, int], ...]] = {
    "multipaxos": (("groups", 4, 1),),
    "compartmentalized": (
        ("proxies", 4, 1), ("batchers", 2, 1),
        ("unbatchers", 2, 1), ("replicas", 3, 1),
    ),
}


def random_elastic(rng: _random.Random, spec: SimSpec) -> ElasticPlan:
    """One randomized elastic shape for an elastic-threaded backend
    (deterministic from ``rng``): the full role set half the time, a
    random non-empty subset otherwise — the subset draw exercises
    configs where only SOME roles are resizable while the rest stay
    structural."""
    if not spec.elastic_ok:
        return ElasticPlan.none()
    axes = _ELASTIC_AXES[spec.name]
    if rng.random() < 0.5 or len(axes) == 1:
        return ElasticPlan(roles=axes)
    keep = [a for a in axes if rng.random() < 0.6]
    return ElasticPlan(roles=tuple(keep) if keep else (axes[0],))


def random_rate_cell(rng: _random.Random, spec: SimSpec) -> dict:
    """One randomized TRACED-rate cell of a fleet brick, deterministic
    from ``rng``: the workload offered rate plus the four Bernoulli
    fault rates. These are exactly the knobs that are per-instance
    STATE under ``FaultPlan(traced=True)`` + a shaped plan, so every
    drawn cell replays the same compiled program (:func:`run_fleet`);
    the structural knobs (partition windows, jitter, arrival kind)
    stay compile-time static and ride :func:`random_plan` instead."""
    rate = round(rng.uniform(0.3, 2.5), 2)
    drop, dup = _draw_drop_dup(rng)
    crash, revive = _draw_crash(rng, spec)
    return {
        "rate": rate, "drop": drop, "dup": dup,
        "crash": crash, "revive": revive,
    }


def _random_membership(rng: _random.Random, shape):
    """A SAFE random membership mask over the backend's acceptor axis:
    cut one acceptor row of a [A, G] flagship mask (a strict minority
    at f=1) or one cell of a [R, C, G] grid mask (every row keeps a
    live cell), so quorums can still form and liveness is recoverable."""
    import numpy as np

    m = np.ones(shape, bool)
    if len(shape) == 2:
        m[rng.randrange(shape[0])] = False
    else:
        m[rng.randrange(shape[0]), rng.randrange(shape[1])] = False
    return m


# ---------------------------------------------------------------------------
# Running schedules
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnums=(0, 1, 5))
def _run_segment(mod, cfg, state, t0, start, n: int, key):
    """One scan segment whose per-tick keys fold the GLOBAL tick index
    (``start + i``) into one run-level key — so a (plan, seed) schedule
    replays the exact same fault history whether it runs as one vmapped
    scan (:func:`run_many_seeds`) or as invariant-checked segments
    (:func:`run_schedule`, :func:`check_liveness_after_heal`). ``start``
    is traced, so every segment of a given length shares one compile."""

    def step(carry, i):
        st, t = carry
        st = mod.tick(cfg, st, t, jax.random.fold_in(key, start + i))
        return (st, t + 1), ()

    (state, t), _ = jax.lax.scan(
        step, (state, t0), jnp.arange(n)
    )
    return state, t


def run_schedule(
    spec: SimSpec,
    plan: FaultPlan,
    seed: int,
    ticks: int = 3 * SEGMENT,
    segment: int = SEGMENT,
    workload: WorkloadPlan = WorkloadPlan.none(),
) -> dict:
    """Run one (plan, seed) schedule in segments, checking invariants at
    every segment boundary. Per-tick keys fold the global tick index, so
    the history is IDENTICAL to a :func:`run_many_seeds` run of the same
    (plan, seed) — found counterexamples replay and shrink here 1:1.
    Returns ``{"ok", "violations", "progress", "plan", "seed",
    "ticks"}``; ``violations`` maps each failed check to the FIRST
    segment-end tick it was seen at; ``progress`` is the liveness
    counter at each boundary."""
    mod = spec.module
    cfg = spec.make_config(plan, workload=workload)
    state = mod.init_state(cfg)
    t = jnp.zeros((), jnp.int32)
    key = jax.random.PRNGKey(seed)
    violations: Dict[str, int] = {}
    progress: List[int] = []
    done = 0
    while done < ticks:
        n = min(segment, ticks - done)
        state, t = _run_segment(
            mod, cfg, state, t, jnp.int32(done), n, key
        )
        done += n
        inv = mod.check_invariants(cfg, state, t)
        for k, v in inv.items():
            if not bool(v):
                violations.setdefault(k, done)
        progress.append(int(spec.progress(state)))
    return {
        "backend": spec.name,
        "ok": not violations,
        "violations": violations,  # first-seen segment-end tick per check
        "progress": progress,
        "plan": plan.to_dict(),
        "workload": workload.to_dict(),
        "seed": seed,
        "ticks": ticks,
    }


def run_reconfig_schedule(
    spec: SimSpec,
    plan: FaultPlan,
    seed: int,
    ticks: int = 4 * SEGMENT,
    segment: int = SEGMENT,
    workload: WorkloadPlan = WorkloadPlan.none(),
    lifecycle: Optional[LifecyclePlan] = None,
    epoch_seed: int = 0,
) -> dict:
    """The reconfiguration-epoch axis of simulation testing: one
    (fault plan, seed) schedule run in segments with RANDOMIZED
    membership churn at the segment boundaries — the serve control
    plane's ``set_membership`` verb driven by a deterministic rng, so
    traced epoch switches interleave the crash/partition schedule
    in-graph. Invariants check at every boundary; before the FINAL
    segment full membership is restored (the heal), and the schedule
    passes only if progress strictly resumes across that recovery
    segment — liveness-after-heal under [faults x epochs] churn.

    The compiled program never changes across epochs: every segment of
    a given length reuses ONE jitted ``_run_segment`` (membership and
    epoch are traced state), which is itself the recompile-free
    contract the ``trace-lifecycle-retrace`` rule pins."""
    assert spec.lifecycle_ok, spec.name
    lifecycle = lifecycle if lifecycle is not None else LifecyclePlan(
        reconfig=True
    )
    assert lifecycle.reconfig
    mod = spec.module
    cfg = spec.make_config(plan, workload=workload, lifecycle=lifecycle)
    state = mod.init_state(cfg)
    t = jnp.zeros((), jnp.int32)
    key = jax.random.PRNGKey(seed)
    rng = _random.Random(epoch_seed * 7919 + seed)
    mask_shape = state.lifecycle.acc_mask.shape
    violations: Dict[str, int] = {}
    progress: List[int] = []
    epochs = 0
    done = 0
    while done < ticks:
        n = min(segment, ticks - done)
        state, t = _run_segment(
            mod, cfg, state, t, jnp.int32(done), n, key
        )
        done += n
        inv = mod.check_invariants(cfg, state, t)
        for k, v in inv.items():
            if not bool(v):
                violations.setdefault(k, done)
        progress.append(int(spec.progress(state)))
        remaining = ticks - done
        if remaining > segment and rng.random() < 0.6:
            # Churn: swap one acceptor/cell out, or restore everyone.
            mask = (
                _random_membership(rng, mask_shape)
                if rng.random() < 0.6
                else True
            )
            state = dataclasses.replace(
                state,
                lifecycle=lifecycle_mod.set_membership(
                    state.lifecycle, mask
                ),
            )
            epochs += 1
        elif 0 < remaining <= segment:
            # The heal before the recovery segment: full membership.
            state = dataclasses.replace(
                state,
                lifecycle=lifecycle_mod.set_membership(
                    state.lifecycle, True
                ),
            )
            epochs += 1
    resumed = len(progress) >= 2 and progress[-1] > progress[-2]
    return {
        "backend": spec.name,
        "ok": not violations and resumed,
        "violations": violations,
        "progress": progress,
        "epochs": epochs,
        "resumed": resumed,
        "plan": plan.to_dict(),
        "workload": workload.to_dict(),
        "lifecycle": lifecycle.to_dict(),
        "seed": seed,
        "ticks": ticks,
    }


def run_elastic_schedule(
    spec: SimSpec,
    plan: FaultPlan,
    seed: int,
    ticks: int = 4 * SEGMENT,
    segment: int = SEGMENT,
    workload: WorkloadPlan = WorkloadPlan.none(),
    elastic: Optional[ElasticPlan] = None,
    churn_seed: int = 0,
) -> dict:
    """The elastic-capacity axis of simulation testing: one (fault
    plan, seed) schedule run in segments with RANDOMIZED role resizes
    at the segment boundaries — the serve control plane's ``resize``
    verb (``elastic.set_target``) driven by a deterministic rng, so
    traced role-count churn interleaves the crash/partition schedule
    in-graph. Invariants (including the elastic books and workload
    conservation) check at every boundary; before the FINAL segment
    every role is pinned to its FLOOR (the deepest scale-down), and
    the schedule passes only if progress strictly resumes across that
    recovery segment — liveness-after-scale-down under
    [faults x resize] churn.

    The compiled program never changes across resizes: every segment
    of a given length reuses ONE jitted ``_run_segment`` (the role
    counts are traced state), which is itself the recompile-free
    contract the ``trace-elastic-retrace`` rule pins."""
    assert spec.elastic_ok, spec.name
    elastic = (
        elastic if elastic is not None
        else ElasticPlan(roles=_ELASTIC_AXES[spec.name])
    )
    assert elastic.active
    mod = spec.module
    cfg = spec.make_config(plan, workload=workload, elastic=elastic)
    state = mod.init_state(cfg)
    t = jnp.zeros((), jnp.int32)
    key = jax.random.PRNGKey(seed)
    rng = _random.Random(churn_seed * 6271 + seed)
    violations: Dict[str, int] = {}
    progress: List[int] = []
    resizes = 0
    done = 0
    while done < ticks:
        n = min(segment, ticks - done)
        state, t = _run_segment(
            mod, cfg, state, t, jnp.int32(done), n, key
        )
        done += n
        inv = mod.check_invariants(cfg, state, t)
        for k, v in inv.items():
            if not bool(v):
                violations.setdefault(k, done)
        if not bool(elastic_mod.invariants_ok(elastic, state.elastic)):
            violations.setdefault("elastic_books", done)
        progress.append(int(spec.progress(state)))
        remaining = ticks - done
        if remaining > segment and rng.random() < 0.7:
            # Churn: retarget one role anywhere in [floor, capacity].
            name = rng.choice(elastic.names)
            to = rng.randint(
                elastic.floor_of(name), elastic.capacity_of(name)
            )
            state = dataclasses.replace(
                state,
                elastic=elastic_mod.set_target(
                    elastic, state.elastic, name, to
                ),
            )
            resizes += 1
        elif 0 < remaining <= segment:
            # The deepest scale-down before the recovery segment:
            # every role at its floor — progress must still resume.
            es = state.elastic
            for name in elastic.names:
                es = elastic_mod.set_target(
                    elastic, es, name, elastic.floor_of(name)
                )
            state = dataclasses.replace(state, elastic=es)
            resizes += 1
    resumed = len(progress) >= 2 and progress[-1] > progress[-2]
    return {
        "backend": spec.name,
        "ok": not violations and resumed,
        "violations": violations,
        "progress": progress,
        "resizes": resizes,
        "resumed": resumed,
        # Final ACTIVE counts (drain-then-deactivate may still be
        # draining a lane) and the pinned TARGETS (the floors).
        "counts": elastic_mod.counts(elastic, state.elastic),
        "targets": {
            name: int(tgt)
            for name, tgt in zip(
                elastic.names, jax.device_get(state.elastic.target)
            )
        },
        "plan": plan.to_dict(),
        "workload": workload.to_dict(),
        "elastic": elastic.to_dict(),
        "seed": seed,
        "ticks": ticks,
    }


def run_crash_restart_schedule(
    spec: SimSpec,
    plan: FaultPlan,
    seed: int,
    ticks: int = 4 * SEGMENT,
    segment: int = SEGMENT,
    workload: WorkloadPlan = WorkloadPlan.none(),
    lifecycle: Optional[LifecyclePlan] = None,
    crash_seed: int = 0,
    checkpoint_every: int = 1,
    max_crashes: int = 4,
) -> dict:
    """The HOST-crash schedule axis of simulation testing: one
    (plan, seed) schedule run in segments with randomized KILL-RESTART
    events at segment boundaries. Every ``checkpoint_every`` segments a
    checkpoint of the full State is taken (a host-side alias-free copy
    — the in-memory twin of ``tpu/checkpoint.py``'s on-disk format);
    at boundaries drawn from a deterministic rng the run "crashes":
    everything since the last checkpoint is discarded and the run
    restarts from it. Because the PRNG is counter-based and fully
    in-state, the restarted run re-executes the lost ticks
    IDENTICALLY, so the schedule asserts the whole crash-tolerance
    contract in-graph:

      * liveness — the run reaches the full horizon despite crashes;
      * invariants hold at every boundary (including re-executed ones);
      * BIT-EXACT recovery — the final state's digest equals the
        never-crashed twin's (sha256 over every leaf);
      * zero duplicate client effects — with a session-table lifecycle
        plan, exactly-once accounting reconciles via ``lifecycle_ok``
        exactly as in the twin.
    """
    from frankenpaxos_tpu.tpu import checkpoint as checkpoint_mod

    mod = spec.module
    kw = {"workload": workload}
    if lifecycle is not None:
        assert spec.lifecycle_ok, spec.name
        kw["lifecycle"] = lifecycle
    cfg = spec.make_config(plan, **kw)
    key = jax.random.PRNGKey(seed)
    rng = _random.Random(crash_seed * 6121 + seed)

    def fresh():
        return mod.init_state(cfg), jnp.zeros((), jnp.int32)

    def host_copy(state, t, done):
        # OWNED host copies (np.array, not the zero-copy views
        # device_get returns on CPU): the checkpoint must outlive the
        # device buffers it was taken from.
        import numpy as np

        return (
            jax.tree_util.tree_map(
                lambda a: np.array(a, copy=True),
                jax.device_get((state, t)),
            ),
            done,
        )

    state, t = fresh()
    ckpt = host_copy(state, t, 0)
    violations: Dict[str, int] = {}
    progress: List[int] = []
    crashes: List[int] = []
    done = 0
    seg_i = 0
    while done < ticks:
        n = min(segment, ticks - done)
        state, t = _run_segment(
            mod, cfg, state, t, jnp.int32(done), n, key
        )
        done += n
        seg_i += 1
        inv = mod.check_invariants(cfg, state, t)
        for k, v in inv.items():
            if not bool(v):
                violations.setdefault(k, done)
        progress.append(int(spec.progress(state)))
        if seg_i % checkpoint_every == 0:
            ckpt = host_copy(state, t, done)
        if (
            len(crashes) < max_crashes
            and done < ticks
            and rng.random() < 0.4
        ):
            # SIGKILL: lose everything since the last checkpoint and
            # restart from it (the lost ticks re-execute bit-identically
            # — counter-based PRNG, keys fold the global tick index).
            crashes.append(done)
            (host_state, host_t), done = ckpt
            # XLA-owned device copies (jnp.copy, not bare asarray —
            # the CPU backend would alias the checkpoint's numpy
            # memory; see tpu/checkpoint.restore_leaves).
            state = jax.tree_util.tree_map(
                lambda a: jnp.copy(jnp.asarray(a)), host_state
            )
            t = jnp.copy(jnp.asarray(host_t))
    digest = checkpoint_mod.state_digest(state)

    # The never-crashed twin, same (plan, seed) — final state must be
    # sha256-identical.
    state2, t2 = fresh()
    done2 = 0
    while done2 < ticks:
        n = min(segment, ticks - done2)
        state2, t2 = _run_segment(
            mod, cfg, state2, t2, jnp.int32(done2), n, key
        )
        done2 += n
    twin_digest = checkpoint_mod.state_digest(state2)
    return {
        "backend": spec.name,
        "ok": not violations and digest == twin_digest,
        "violations": violations,
        "progress": progress,
        "crashes": crashes,
        "bit_exact": digest == twin_digest,
        "digest": digest,
        "plan": plan.to_dict(),
        "workload": workload.to_dict(),
        "seed": seed,
        "ticks": ticks,
    }


def run_many_seeds(
    spec: SimSpec,
    plan: FaultPlan,
    seeds: Sequence[int],
    ticks: int = 2 * SEGMENT,
    workload: WorkloadPlan = WorkloadPlan.none(),
) -> dict:
    """The device-scale axis: ONE compiled scan, vmapped over the seed
    axis, returning per-seed invariant verdicts and progress counters.
    The plan's rates are compile-time static; the schedule realization
    (which messages drop, when crashes hit, who duplicates) is entirely
    seed-driven, so N seeds are N distinct fault histories for one
    compile."""
    mod = spec.module
    cfg = spec.make_config(plan, workload=workload)

    def one(key):
        def step(carry, i):
            st, t = carry
            st = mod.tick(cfg, st, t, jax.random.fold_in(key, i))
            return (st, t + 1), ()

        (st, t), _ = jax.lax.scan(
            step,
            (mod.init_state(cfg), jnp.zeros((), jnp.int32)),
            jnp.arange(ticks),
        )
        inv = mod.check_invariants(cfg, st, t)
        return (
            {k: jnp.asarray(v) for k, v in inv.items()},
            jnp.asarray(spec.progress(st)),
        )

    keys = jax.vmap(jax.random.PRNGKey)(
        jnp.asarray(list(seeds), jnp.uint32)
    )
    invs, progress = jax.jit(jax.vmap(one))(keys)
    invs = jax.device_get(invs)
    progress = jax.device_get(progress)
    per_seed_ok = [
        all(bool(invs[k][i]) for k in invs) for i in range(len(seeds))
    ]
    return {
        "backend": spec.name,
        "plan": plan.to_dict(),
        "workload": workload.to_dict(),
        "seeds": list(seeds),
        "ticks": ticks,
        "ok": all(per_seed_ok),
        "per_seed_ok": per_seed_ok,
        "failing_seeds": [
            s for s, ok in zip(seeds, per_seed_ok) if not ok
        ],
        "progress": [int(p) for p in progress],
    }


@functools.lru_cache(maxsize=None)
def _fleet_program(name: str, mesh, wrap):
    """The ONE compiled executable a whole [seeds x schedules] brick
    runs through for a given (backend, mesh): jit of the vmapped
    (scan + in-graph invariant reduction) body. ``spmd_axis_name``
    maps the instance axis onto the fleet mesh axis and ``wrap``
    shard_map-lowers any engaged kernel planes over the group axis,
    exactly as ``parallel.sharding._fleet_runner`` does. Outputs are
    the per-instance verdicts only (states never leave the device, so
    nothing to donate into — the state-returning fleet runner with
    donation lives in ``parallel/sharding.py``). Keyed per mesh — a
    cached program never leaks across fleet shapes (the jit-cache
    isolation ``tests/test_fleet.py`` spies on, and the flat-cache
    contract the ``trace-fleet-onecompile`` rule pins)."""
    from frankenpaxos_tpu.ops import registry
    from frankenpaxos_tpu.parallel import sharding

    spec = SPECS[name]
    mod = spec.module

    @functools.partial(jax.jit, static_argnums=(0, 3))
    def run(cfg, states, t0, num_ticks: int, keys):
        def one(state, key):
            with registry.shard_lowering(wrap, sharding.GROUP_AXIS):
                st, t = mod.run_ticks.__wrapped__(
                    cfg, state, t0, num_ticks, key
                )
            inv = mod.check_invariants(cfg, st, t)
            return (
                {k: jnp.asarray(v) for k, v in inv.items()},
                jnp.asarray(spec.progress(st)),
            )

        return jax.vmap(one, spmd_axis_name=sharding.FLEET_AXIS)(
            states, keys
        )

    return run


def _brick_states(name: str, mod, cfg, cells, seeds_per_schedule: int):
    """The brick's fleet-state pytree: one fresh instance per
    (cell, seed) with that cell's traced offered rate and Bernoulli
    fault-rate vector installed per instance — the sharding layer's
    ``fleet_states`` with the module passed explicitly, so backends
    outside the sharding registry brick up too (mesh=None runs)."""
    from frankenpaxos_tpu.parallel import sharding

    return sharding.fleet_states(
        name,
        cfg,
        len(cells) * seeds_per_schedule,
        rates=[
            c["rate"] for c in cells for _ in range(seeds_per_schedule)
        ],
        fault_rates=[
            [c["drop"], c["dup"], c["crash"], c["revive"]]
            for c in cells
            for _ in range(seeds_per_schedule)
        ],
        module=mod,
    )


def run_fleet(
    spec: SimSpec,
    cells: Optional[Sequence[dict]] = None,
    schedules: int = 8,
    seeds_per_schedule: int = 4,
    ticks: int = 2 * SEGMENT,
    base_seed: int = 0,
    mesh=None,
    arrival: str = "constant",
    kernels=None,
) -> dict:
    """The FLEET axis of simulation testing: one compiled executable
    runs an entire [schedules x seeds] brick of randomized traced-rate
    schedules (:func:`random_rate_cell`) as data-parallel instances —
    per-instance PRNG seeds, per-instance offered loads, per-instance
    fault-rate vectors — and reduces every backend invariant
    PER-INSTANCE in-graph. Schedule (c, s) is bit-identical to a
    sequential single-instance run of the same traced config with that
    cell's rates installed (``tests/test_fleet.py``).

    ``mesh=None`` runs the brick on the default device (pure vmap);
    a ``('fleet', 'groups')`` product mesh shards instances over the
    fleet axis and each instance's group axis over the group axis
    (the backend must be in the sharding registry). ``kernels``
    optionally installs a :class:`ops.registry.KernelPolicy` (fleet x
    kernels composition). Returns per-instance verdicts plus the
    failing (cell, seed) pairs, ``sweep``-style."""
    from frankenpaxos_tpu.parallel import sharding

    if cells is None:
        rng = _random.Random(
            base_seed * 7919 + zlib.crc32(spec.name.encode())
        )
        cells = [random_rate_cell(rng, spec) for _ in range(schedules)]
    cells = list(cells)
    plan = FaultPlan(traced=True)
    wplan = WorkloadPlan(arrival=arrival, rate=1.0)
    cfg = spec.make_config(plan, workload=wplan)
    if kernels is not None:
        cfg = dataclasses.replace(cfg, kernels=kernels)
    mod = spec.module
    n = len(cells) * seeds_per_schedule
    states = _brick_states(spec.name, mod, cfg, cells, seeds_per_schedule)
    seeds = [
        base_seed + c * seeds_per_schedule + s
        for c in range(len(cells))
        for s in range(seeds_per_schedule)
    ]
    keys = sharding.fleet_keys(seeds)
    wrap = None
    if mesh is not None:
        if spec.name not in sharding.SHARDINGS:
            raise ValueError(
                f"backend {spec.name!r} is not in the sharding "
                "registry; run its brick with mesh=None"
            )
        sharding.validate_policy(spec.name, cfg, mesh)
        states = sharding.shard_fleet_state(spec.name, states, mesh)
        keys = sharding.place_fleet_keys(keys, mesh)
        wrap = sharding._fleet_wrap_mesh(spec.name, cfg, mesh)
    invs, progress = _fleet_program(spec.name, mesh, wrap)(
        cfg, states, jnp.zeros((), jnp.int32), ticks, keys
    )
    invs = jax.device_get(invs)
    progress = jax.device_get(progress)
    per_ok = [all(bool(invs[k][i]) for k in invs) for i in range(n)]
    failures = []
    for i, ok in enumerate(per_ok):
        if not ok:
            c, s = divmod(i, seeds_per_schedule)
            failures.append({
                "cell": cells[c],
                "seed": seeds[i],
                "failed_checks": sorted(
                    k for k in invs if not bool(invs[k][i])
                ),
            })
    return {
        "backend": spec.name,
        "cells": cells,
        "seeds_per_schedule": seeds_per_schedule,
        "instances": n,
        "ticks": ticks,
        "mesh": None if mesh is None else [
            int(s) for s in dict(mesh.shape).values()
        ],
        "kernels": None if kernels is None else kernels.mode,
        "ok": all(per_ok),
        "per_instance_ok": per_ok,
        "failures": failures,
        "progress": [int(p) for p in progress],
    }


def check_liveness_after_heal(
    spec: SimSpec,
    plan: FaultPlan,
    seed: int,
    recovery: int = 2 * SEGMENT,
    workload: WorkloadPlan = WorkloadPlan.none(),
) -> dict:
    """For a plan with a scheduled heal: progress measured at the heal
    tick must strictly grow over the recovery window after it."""
    assert plan.has_partition and plan.partition_heal >= 0, plan
    mod = spec.module
    cfg = spec.make_config(plan, workload=workload)
    state = mod.init_state(cfg)
    t = jnp.zeros((), jnp.int32)
    key = jax.random.PRNGKey(seed)
    done = 0
    while done < plan.partition_heal:
        n = min(SEGMENT, plan.partition_heal - done)
        state, t = _run_segment(
            mod, cfg, state, t, jnp.int32(done), n, key
        )
        done += n
    at_heal = int(spec.progress(state))
    state, t = _run_segment(
        mod, cfg, state, t, jnp.int32(done), recovery, key
    )
    after = int(spec.progress(state))
    inv = {k: bool(v) for k, v in mod.check_invariants(cfg, state, t).items()}
    return {
        "backend": spec.name,
        "at_heal": at_heal,
        "after_recovery": after,
        "resumed": after > at_heal,
        "invariants_ok": all(inv.values()),
    }


# ---------------------------------------------------------------------------
# Shrinking
# ---------------------------------------------------------------------------


def _quantize(rate: float) -> float:
    return 0.0 if rate < 0.004 else round(rate, 3)


def _candidates(plan: FaultPlan) -> List[FaultPlan]:
    """Ordered simplification candidates: whole-knob removals first
    (biggest steps), then halvings and partition-window shrinks."""
    out: List[FaultPlan] = []

    def repl(**kw):
        cand = dataclasses.replace(plan, **kw)
        if cand != plan:
            out.append(cand)

    # Remove whole knobs.
    repl(drop_rate=0.0)
    repl(dup_rate=0.0)
    repl(jitter=0)
    repl(crash_rate=0.0, revive_rate=0.0)
    repl(partition=(), partition_start=0, partition_heal=-1)
    # Halve rates / jitter.
    repl(drop_rate=_quantize(plan.drop_rate / 2))
    repl(dup_rate=_quantize(plan.dup_rate / 2))
    repl(crash_rate=_quantize(plan.crash_rate / 2))
    if plan.jitter > 0:
        repl(jitter=plan.jitter // 2)
    # Shrink the partition: fewer cut replicas, narrower window.
    if plan.has_partition:
        ones = [i for i, s in enumerate(plan.partition) if s]
        if len(ones) > 1:
            smaller = list(plan.partition)
            smaller[ones[-1]] = 0
            repl(partition=tuple(smaller))
        if plan.partition_heal >= 0:
            span = plan.partition_heal - plan.partition_start
            # Halve the cut window (floor 8 ticks)...
            if span > 8:
                repl(
                    partition_heal=plan.partition_start + max(8, span // 2)
                )
            # ...and slide the whole window toward t=0, span preserved.
            if plan.partition_start > 0:
                ns = plan.partition_start // 2
                repl(partition_start=ns, partition_heal=ns + span)
        elif plan.partition_start > 0:
            repl(partition_start=plan.partition_start // 2)
    return out


def _with_cut(plan: FaultPlan, subset) -> FaultPlan:
    """The plan with its partition cut replaced by exactly ``subset``
    (replica indices on the cut side)."""
    side = [0] * len(plan.partition)
    for i in subset:
        side[i] = 1
    return dataclasses.replace(plan, partition=tuple(side))


def _ddmin_partition(
    plan: FaultPlan,
    failing: Callable[[FaultPlan], bool],
    budget: int,
) -> Tuple[FaultPlan, int]:
    """Delta debugging (Zeller's ddmin) over the partition SIDE-BIT SET.

    The greedy candidate list only ever drops the LAST cut replica, so
    a multi-replica cut like {0, 1, 2} where only {0, 2} matters stops
    shrinking the moment dropping replica 2 alone passes. ddmin instead
    splits the cut set into n chunks and tests each chunk AND each
    complement, re-splitting finer on failure, which converges to a
    1-MINIMAL cut (no single replica can be removed) in O(k^2) runs
    worst case for a k-replica cut.

    Returns ``(plan, tests_used)``; the input plan must fail."""
    cut = [i for i, s in enumerate(plan.partition) if s]
    tests = 0
    if len(cut) <= 1:
        return plan, tests
    n = 2
    while len(cut) >= 2 and tests < budget:
        bounds = [len(cut) * i // n for i in range(n + 1)]
        chunks = [
            cut[bounds[i] : bounds[i + 1]]
            for i in range(n)
            if bounds[i] < bounds[i + 1]
        ]
        reduced = False
        for chunk in chunks:  # reduce to subset
            tests += 1
            if failing(_with_cut(plan, chunk)):
                cut, n, reduced = chunk, 2, True
                break
            if tests >= budget:
                break
        if not reduced and tests < budget:
            for chunk in chunks:  # reduce to complement
                comp = [i for i in cut if i not in chunk]
                if not comp or len(comp) == len(cut):
                    continue
                tests += 1
                if failing(_with_cut(plan, comp)):
                    cut, n, reduced = comp, max(n - 1, 2), True
                    break
                if tests >= budget:
                    break
        if not reduced:
            if n >= len(cut):
                break  # 1-minimal: no chunk or complement still fails
            n = min(len(cut), 2 * n)  # split finer
    return _with_cut(plan, cut), tests


def shrink(
    spec: SimSpec,
    plan: FaultPlan,
    seed: int,
    ticks: int = 3 * SEGMENT,
    failing: Optional[Callable[[FaultPlan], bool]] = None,
    max_steps: int = 64,
) -> FaultPlan:
    """Schedule minimization: a greedy first-improvement pass over
    :func:`_candidates` (whole-knob removals, halvings, window shrinks)
    interleaved with DELTA DEBUGGING over the partition side-bit set
    (:func:`_ddmin_partition`) until a joint fixpoint — greedy strips
    the knobs and the window, ddmin minimizes WHICH replicas the cut
    needs, and each can unlock further steps for the other (a smaller
    cut can make a narrower window sufficient and vice versa). The
    default failure predicate is "run_schedule reports an invariant
    violation"; tests inject their own (e.g. a deliberately-broken
    invariant) to pin the loop's behavior. ``plan`` must fail."""
    if failing is None:
        def failing(p: FaultPlan) -> bool:
            return not run_schedule(spec, p, seed, ticks)["ok"]

    assert failing(plan), "shrink() needs a failing plan to start from"
    steps = 0
    improved = True
    while improved and steps < max_steps:
        improved = False
        for cand in _candidates(plan):
            steps += 1
            if failing(cand):
                plan = cand
                improved = True
                break
            if steps >= max_steps:
                break
        if not improved and plan.has_partition and steps < max_steps:
            smaller, used = _ddmin_partition(plan, failing, max_steps - steps)
            steps += used
            if smaller != plan:
                plan = smaller
                improved = True  # ddmin may unlock further greedy steps
    return plan


def dump_reproducer(
    path: str,
    spec: SimSpec,
    plan: FaultPlan,
    seed: int,
    ticks: int,
    note: str = "",
    workload: WorkloadPlan = WorkloadPlan.none(),
    elastic: ElasticPlan = ElasticPlan.none(),
    churn_seed: int = 0,
) -> dict:
    """Write a minimized reproducer as JSON (the bad-history artifact):
    backend + seed + tick horizon + the shrunk FaultPlan (+ the
    workload shape the failure was found under; shrinking minimizes
    the FAULT knobs — the workload rides along verbatim). An elastic
    schedule's artifact also records the ElasticPlan and the churn
    seed, so the exact [faults x resize] interleaving replays through
    :func:`run_elastic_schedule`."""
    payload = {
        "backend": spec.name,
        "seed": seed,
        "ticks": ticks,
        "fault_plan": plan.to_dict(),
        "note": note,
    }
    if workload.active:
        payload["workload_plan"] = workload.to_dict()
    if elastic.active:
        payload["elastic_plan"] = elastic.to_dict()
        payload["churn_seed"] = int(churn_seed)
    with open(path, "w") as f:
        json.dump(payload, f, indent=1)
    return payload


def load_reproducer(path: str):
    """Load a reproducer JSON: returns ``(spec, plan, seed, ticks)``
    (+ a 5th ``workload`` element when the artifact recorded an ACTIVE
    workload shape) — feed straight back into :func:`run_schedule`.
    An elastic artifact instead returns ``(spec, plan, seed, ticks,
    workload, elastic, churn_seed)`` for
    :func:`run_elastic_schedule`."""
    with open(path) as f:
        payload = json.load(f)
    spec = SPECS[payload["backend"]]
    plan = FaultPlan.from_dict(payload["fault_plan"])
    base = (spec, plan, int(payload["seed"]), int(payload["ticks"]))
    if "elastic_plan" in payload:
        workload = (
            WorkloadPlan.from_dict(payload["workload_plan"])
            if "workload_plan" in payload
            else WorkloadPlan.none()
        )
        return base + (
            workload,
            ElasticPlan.from_dict(payload["elastic_plan"]),
            int(payload.get("churn_seed", 0)),
        )
    if "workload_plan" in payload:
        return base + (
            WorkloadPlan.from_dict(payload["workload_plan"]),
        )
    return base


# ---------------------------------------------------------------------------
# Sweeps
# ---------------------------------------------------------------------------


def sweep(
    backends: Optional[Sequence[str]] = None,
    schedules: int = 16,
    seeds_per_schedule: int = 4,
    ticks: int = 3 * SEGMENT,
    base_seed: int = 0,
    check_liveness: bool = True,
) -> dict:
    """Randomized JOINT [workload x fault] sweep over the registry:
    per backend, ``schedules`` random (FaultPlan, WorkloadPlan) pairs x
    ``seeds_per_schedule`` vmapped seeds, invariants (incl. the
    workload window-conservation check) on every run; plans with a
    scheduled heal also get a liveness-after-heal assertion (where the
    spec supports it; asserted under the drawn workload too — shaped
    rates are sized so progress always resumes). Returns a JSON-ready
    summary with every failure's (plan, workload, seed)."""
    names = list(backends) if backends else list(SPECS)
    out: dict = {"schedules": schedules, "seeds_per_schedule":
                 seeds_per_schedule, "ticks": ticks, "backends": {}}
    for name in names:
        spec = SPECS[name]
        # crc32, not hash(): Python string hashing is process-randomized
        # and would make identical sweep invocations non-reproducible.
        rng = _random.Random(
            base_seed * 7919 + zlib.crc32(name.encode())
        )
        failures: List[dict] = []
        liveness_rows: List[dict] = []
        ran = 0
        for i in range(schedules):
            plan = random_plan(rng, spec, ticks)
            wplan = random_workload(rng, spec, ticks)
            seeds = [base_seed + i * seeds_per_schedule + j
                     for j in range(seeds_per_schedule)]
            res = run_many_seeds(spec, plan, seeds, ticks, workload=wplan)
            ran += len(seeds)
            if not res["ok"]:
                failures.append(
                    {"plan": plan.to_dict(),
                     "workload": wplan.to_dict(),
                     "failing_seeds": res["failing_seeds"]}
                )
            if (
                check_liveness
                and spec.liveness
                and plan.has_partition
                and plan.partition_heal >= 0
                and not plan.has_crash
            ):
                lv = check_liveness_after_heal(
                    spec, plan, seeds[0], workload=wplan
                )
                liveness_rows.append(lv)
        resumed = sum(r["resumed"] for r in liveness_rows)
        out["backends"][name] = {
            "schedules": schedules,
            "runs": ran,
            "failures": failures,
            # A backend is green only if invariants held on every run
            # AND every checked heal actually resumed progress.
            "ok": not failures and resumed == len(liveness_rows),
            "liveness_checked": len(liveness_rows),
            "liveness_resumed": resumed,
        }
    out["ok"] = all(b["ok"] for b in out["backends"].values())
    return out


def main() -> None:
    import argparse

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--backends", default="",
                   help="comma-separated (default: all)")
    p.add_argument("--schedules", type=int, default=16)
    p.add_argument("--seeds", type=int, default=4)
    p.add_argument("--ticks", type=int, default=3 * SEGMENT)
    p.add_argument("--base-seed", type=int, default=0)
    p.add_argument("--fleet", type=int, default=0, metavar="ROWS",
                   help="run one-compile [seeds x schedules] fleet "
                   "bricks instead of the per-config sweep; ROWS is "
                   "the fleet-axis extent (0 = sweep mode, 1 = brick "
                   "on the default device)")
    p.add_argument("--out", default="")
    args = p.parse_args()
    backends = (
        [b for b in args.backends.split(",") if b] or None
    )
    if args.fleet:
        import jax

        from frankenpaxos_tpu.parallel import sharding as _sh

        # Product mesh only when everything divides (device count by
        # fleet rows, brick instances by fleet rows); otherwise the
        # brick falls back to the default device instead of dying on a
        # divisibility assert mid-sweep.
        n_inst = args.schedules * args.seeds
        mesh = (
            _sh.make_fleet_mesh(fleet=args.fleet)
            if args.fleet > 1
            and len(jax.devices()) % args.fleet == 0
            and n_inst % args.fleet == 0
            else None
        )
        def fleet_one(name: str) -> dict:
            m = mesh if name in _sh.SHARDINGS else None
            kw = dict(
                schedules=args.schedules,
                seeds_per_schedule=args.seeds,
                ticks=args.ticks,
                base_seed=args.base_seed,
            )
            if m is not None:
                try:
                    return run_fleet(SPECS[name], mesh=m, **kw)
                except ValueError as e:
                    # A backend whose group axis doesn't divide this
                    # mesh (e.g. epaxos' 5 columns on a 4-wide group
                    # axis) bricks up on the default device instead of
                    # killing the sweep; real errors stay loud.
                    if "divisible" not in str(e):
                        raise
            return run_fleet(SPECS[name], **kw)

        result = {
            "mode": "fleet",
            "fleet_rows": args.fleet,
            "backends": {
                name: fleet_one(name)
                for name in (backends or list(SPECS))
            },
        }
        result["ok"] = all(
            b["ok"] for b in result["backends"].values()
        )
    else:
        result = sweep(
            backends=backends,
            schedules=args.schedules,
            seeds_per_schedule=args.seeds,
            ticks=args.ticks,
            base_seed=args.base_seed,
        )
    text = json.dumps(result, indent=1)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    print(text)


if __name__ == "__main__":
    main()
