"""Process management (the analog of ``benchmarks/proc.py``): a ``Proc``
abstraction over local subprocesses and remote SSH processes, with
guaranteed cleanup and captured output."""

from __future__ import annotations

import shlex
import signal
import subprocess
from typing import IO, List, Optional, Sequence, Union


class Proc:
    def cmd(self) -> List[str]:
        raise NotImplementedError

    def pid(self) -> Optional[int]:
        raise NotImplementedError

    def wait(self, timeout: Optional[float] = None) -> Optional[int]:
        raise NotImplementedError

    def kill(self) -> None:
        raise NotImplementedError


class PopenProc(Proc):
    """A local subprocess (benchmarks/proc.py PopenProc)."""

    def __init__(
        self,
        args: Sequence[str],
        stdout: Union[str, IO, None] = None,
        stderr: Union[str, IO, None] = None,
        env: Optional[dict] = None,
    ):
        self._args = list(args)
        self._files = []
        if isinstance(stdout, str):
            stdout = open(stdout, "w")
            self._files.append(stdout)
        if isinstance(stderr, str):
            stderr = open(stderr, "w")
            self._files.append(stderr)
        self._popen = subprocess.Popen(
            self._args, stdout=stdout, stderr=stderr, env=env
        )

    def cmd(self) -> List[str]:
        return list(self._args)

    def pid(self) -> Optional[int]:
        return self._popen.pid

    def wait(self, timeout: Optional[float] = None) -> Optional[int]:
        try:
            return self._popen.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            return None

    def kill(self) -> None:
        if self._popen.poll() is None:
            self._popen.send_signal(signal.SIGTERM)
            try:
                self._popen.wait(timeout=2)
            except subprocess.TimeoutExpired:
                self._popen.kill()
        for f in self._files:
            f.close()

    def returncode(self) -> Optional[int]:
        return self._popen.poll()


class SshProc(Proc):
    """A remote process over the system ssh binary (the analog of the
    reference's ParamikoProc, including its nonce trick: the command is
    tagged with a unique nonce environment variable so ``kill`` can
    pkill exactly this process on the remote host even though ssh gives
    us no remote pid; benchmarks/proc.py:88-110)."""

    _nonce_counter = 0

    def __init__(
        self,
        host: str,
        args: Sequence[str],
        stdout: Union[str, IO, None] = None,
        stderr: Union[str, IO, None] = None,
        ssh_args: Sequence[str] = (),
    ):
        SshProc._nonce_counter += 1
        self.host = host
        self.nonce = f"fptpu_nonce_{SshProc._nonce_counter}"
        self._args = list(args)
        # The nonce must appear in a REMOTE process's /proc cmdline for
        # pkill -f to find it. `env NONCE=1 cmd` exec-replaces, losing the
        # nonce, so instead run the command as a child of a nonce-tagged
        # shell (the nonce lives in the shell's -c string).
        remote = f"bash -c ': {self.nonce}; {shlex.join(args)}'"
        self._proc = PopenProc(
            ["ssh", *ssh_args, host, remote], stdout=stdout, stderr=stderr
        )
        self._ssh_args = list(ssh_args)

    def cmd(self) -> List[str]:
        return list(self._args)

    def pid(self) -> Optional[int]:
        return self._proc.pid()

    def wait(self, timeout: Optional[float] = None) -> Optional[int]:
        return self._proc.wait(timeout=timeout)

    def kill(self) -> None:
        # Kill the command (a child of the nonce-tagged shell), then the
        # shell itself.
        subprocess.run(
            [
                "ssh", *self._ssh_args, self.host,
                f"pkill -TERM -P $(pgrep -f {self.nonce} | head -1); "
                f"pkill -TERM -f {self.nonce}",
            ],
            check=False,
        )
        self._proc.kill()
