"""Analysis CLI: turn a recorder CSV, a benchmark directory, or a whole
Suite directory into plots and a summary table — the entry point of the
L6 layer (reference: ``benchmarks/plot_latency_and_throughput.py`` and
the per-paper plot scripts).

    python -m frankenpaxos_tpu.harness.analyze recorder.csv
    python -m frankenpaxos_tpu.harness.analyze /path/to/benchmark_dir
    python -m frankenpaxos_tpu.harness.analyze /path/to/suite_dir
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from frankenpaxos_tpu.harness.analysis import (
    analyze_benchmark_dir,
    plot_latency_and_throughput,
    read_recorder_csvs,
    suite_results,
    summarize,
)


def main() -> None:
    parser = argparse.ArgumentParser(prog="frankenpaxos_tpu.harness.analyze")
    parser.add_argument("path", help="recorder CSV, benchmark dir, or suite dir")
    parser.add_argument("-o", "--output", default=None, help="plot filename")
    parser.add_argument(
        "-d", "--drop", type=float, default=0.0,
        help="drop this many seconds from the start of the run",
    )
    args = parser.parse_args()

    if os.path.isfile(args.path):
        df = read_recorder_csvs([args.path])
        output = args.output or os.path.splitext(args.path)[0] + ".png"
        plot_latency_and_throughput(df, output, drop_seconds=args.drop)
        summary = summarize(df, drop_seconds=args.drop)
        summary["plot"] = output
        print(json.dumps(summary))
        return

    if os.path.exists(os.path.join(args.path, "results.csv")):
        df = suite_results(args.path)
        # The summary table: one row per benchmark, all flattened columns.
        print(df.to_string(index=False))
        return

    summary = analyze_benchmark_dir(
        args.path, output=args.output, drop_seconds=args.drop
    )
    print(json.dumps(summary))


if __name__ == "__main__":
    sys.exit(main())
