"""Kill-and-recover harness: SIGKILL a live serve process at randomized
chunk boundaries, restart it from the latest checkpoint, and assert the
crash-tolerance contract end to end.

This is the HOST-process half of simulation testing (the device-side
half is ``harness/simtest.py``; the in-graph kill-restart twin is
``simtest.run_crash_restart_schedule``): a real subprocess runs the
serve loop (``harness/serve.py``) with async checkpointing
(``tpu/checkpoint.py``), a supervisor SIGKILLs it at chunk boundaries
drawn from a deterministic rng — the new schedule axis — and restarts
it with ``ServeLoop.resume``. After the final restart completes the run,
the harness asserts

  * **liveness** — the run reaches its full chunk budget despite every
    kill (progress strictly resumes after each restart);
  * **invariants** — the backend's full ``check_invariants`` suite
    (conservation, quorum safety, lifecycle books) holds on the final
    state;
  * **exactly-once client effects** — the PR 11 session table's books
    reconcile (``lifecycle_ok``: cache hits <= resubmits, completion
    totals == the workload engine's) across every restart: a crash
    never double-applies a client command because the table IS state
    and restores with it;
  * **bit-exact recovery** — the final State digest equals an
    uninterrupted twin's (the resume replays the twin sha256-identical).

A supervising WATCHDOG covers the hang failure mode SIGKILL testing
can't: the worker heartbeats a progress file every chunk; if the file
goes stale for longer than the hang timeout (a hung dispatch — e.g. a
wedged device runtime), the supervisor SIGKILLs and restarts it the
same way, with CAPPED EXPONENTIAL BACKOFF between restarts so a
crash-looping worker can't spin the host.

CLI::

    # the supervised worker (what the supervisor spawns):
    python -m frankenpaxos_tpu.harness.recovery --worker \\
        --out-dir /tmp/rec --chunks 12 --every 2 [--resume]

    # one SIGKILL-mid-serve + recover + verify (the CI smoke leg):
    python -m frankenpaxos_tpu.harness.recovery --smoke --out-dir /tmp/rec
"""

from __future__ import annotations

import dataclasses
import json
import os
import random as _random
import signal
import subprocess
import sys
import time
from typing import List, Optional

# NOTE: jax is imported lazily inside worker/twin code paths so the
# supervisor process stays light (it only spawns/kills subprocesses).

HEARTBEAT_FILE = "progress.json"
FINAL_FILE = "final.json"
CKPT_SUBDIR = "checkpoints"

# Worker shapes beyond the two serve-grade backends: any of these runs
# the kill-and-recover contract at its canonical analysis_config shape
# with the shaped workload engaged. They carry no session table or
# elastic plane (those thread only multipaxos/compartmentalized), so
# the assertions reduce to liveness + invariants + bit-exact digest —
# which is exactly what host-process death must preserve everywhere.
GENERIC_BACKENDS = ("mencius", "epaxos", "scalog", "craq")


# ---------------------------------------------------------------------------
# Worker: the supervised serve process
# ---------------------------------------------------------------------------


def _worker_cfg(args):
    """The worker's backend config: small flagship (or
    compartmentalized) shape with the session table + shaped workload
    engaged, so the exactly-once and conservation assertions have
    teeth; or one of ``GENERIC_BACKENDS`` at its canonical
    analysis_config shape (workload on, no session/elastic planes).
    ``--elastic`` arms the THIRD serve-grade worker shape:
    padded role planes (tpu/elastic.py) + the reconfig membership
    masks + the SLO/autoscaler ladder, started at the FLOOR so the
    overloaded workload forces live scale-ups — a SIGKILL then lands
    mid-resize and the resume must restore masks, role counts, and the
    autoscaler's ladder position bit-exactly."""
    from frankenpaxos_tpu.tpu.elastic import ElasticPlan
    from frankenpaxos_tpu.tpu.lifecycle import LifecyclePlan
    from frankenpaxos_tpu.tpu.workload import WorkloadPlan

    workload = WorkloadPlan(
        arrival="constant", rate=1.5, backlog_cap=128
    )
    if args.backend in GENERIC_BACKENDS:
        assert not args.elastic, (
            f"--elastic threads only the serve-grade backends, "
            f"not {args.backend}"
        )
        import importlib

        mod = importlib.import_module(
            f"frankenpaxos_tpu.tpu.{args.backend}_batched"
        )
        return mod, mod.analysis_config(workload=workload)
    lifecycle = LifecyclePlan(
        sessions=args.sessions, resubmit_rate=args.resubmit_rate,
        reconfig=bool(args.elastic),
    )
    if args.backend == "compartmentalized":
        from frankenpaxos_tpu.tpu import compartmentalized_batched as mod

        elastic = (
            ElasticPlan(roles=(
                ("proxies", 4, 1), ("batchers", 2, 1),
                ("unbatchers", 2, 1), ("replicas", 3, 1),
            ))
            if args.elastic else ElasticPlan.none()
        )
        cfg = mod.analysis_config(
            workload=workload, lifecycle=lifecycle, elastic=elastic
        )
    else:
        from frankenpaxos_tpu.tpu import multipaxos_batched as mod

        elastic = (
            ElasticPlan(roles=(("groups", args.groups, 2),))
            if args.elastic else ElasticPlan.none()
        )
        cfg = mod.BatchedMultiPaxosConfig(
            f=1, num_groups=args.groups, window=16, slots_per_tick=2,
            retry_timeout=8, workload=workload, lifecycle=lifecycle,
            elastic=elastic,
        )
    return mod, cfg


class _SupervisedLoop:
    """A ServeLoop wrapper that heartbeats a progress file after every
    drained chunk (the watchdog's liveness signal), optionally paces
    chunks (so a supervisor's kill schedule lands mid-serve rather than
    after a toy run finishes), and can simulate a hung dispatch for the
    watchdog tests."""

    def __init__(
        self,
        loop,
        out_dir: str,
        hang_after: Optional[int],
        chunk_delay: float = 0.0,
        membership_script: bool = False,
    ):
        self.loop = loop
        self.out_dir = out_dir
        self.hang_after = hang_after
        self.chunk_delay = chunk_delay
        self.membership_script = membership_script
        loop_drain = loop._drain

        def drain_and_heartbeat(snap):
            out = loop_drain(snap)
            if self.membership_script:
                # Deterministic membership churn keyed on the chunk
                # count: a resumed worker replays from a checkpoint
                # BOUNDARY strictly before the kill, so each verb
                # fires exactly once per (replayed) history and the
                # killed run's masks match the uninterrupted twin's.
                c = self.loop._chunks
                if c == 3:
                    self.loop.swap_acceptor(1)
                elif c == 7:
                    self.loop.reconfigure(True)  # the heal
            self._heartbeat()
            if (
                self.hang_after is not None
                and self.loop._chunks >= self.hang_after
            ):
                while True:  # a hung dispatch: heartbeats stop cold
                    time.sleep(3600)
            if self.chunk_delay:
                time.sleep(self.chunk_delay)
            return out

        loop._drain = drain_and_heartbeat

    def _heartbeat(self, phase: str = "serving"):
        # phase="startup" marks the pre-run heartbeat (imports done,
        # first chunk may still be COLD-COMPILING): the watchdog
        # exempts it from hang_timeout — a long cold jit compile is
        # not a hung dispatch (the supervisor's spawn_timeout still
        # bounds a worker truly wedged in compile).
        payload = {
            "chunks": int(self.loop._chunks),
            "ticks": int(self.loop.cursor.tick),
            "time": time.time(),
            "phase": phase,
        }
        tmp = os.path.join(self.out_dir, HEARTBEAT_FILE + ".tmp")
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, os.path.join(self.out_dir, HEARTBEAT_FILE))


def run_worker(args) -> int:
    """The worker body: fresh start or resume from the newest valid
    checkpoint, serve to the chunk budget, then write the final report
    (state digest + invariants + lifecycle books)."""
    import jax

    from frankenpaxos_tpu.harness.serve import ServeConfig, ServeLoop
    from frankenpaxos_tpu.tpu import checkpoint as checkpoint_mod
    from frankenpaxos_tpu.tpu import lifecycle as lifecycle_mod

    # Persistent XLA compilation cache: a restarted worker recompiles
    # nothing the killed one already built — across restarts the one
    # true cold start is the only compile (the serve-session analog of
    # the tests' conftest cache).
    try:
        jax.config.update(
            "jax_compilation_cache_dir",
            os.environ.get(
                "FRANKENPAXOS_JAX_CACHE", "/tmp/frankenpaxos_jax_cache"
            ),
        )
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", 0.5
        )
    except Exception:
        pass  # older jax without the persistent cache: run uncached

    mod, cfg = _worker_cfg(args)
    os.makedirs(args.out_dir, exist_ok=True)
    ckpt_dir = os.path.join(args.out_dir, CKPT_SUBDIR)
    slo = autoscaler = None
    if args.elastic:
        from frankenpaxos_tpu.monitoring.autoscaler import (
            AutoscalerPolicy,
        )
        from frankenpaxos_tpu.monitoring.slo import SloPolicy

        # A tight p99 target over queue wait + the floor-sized start
        # below guarantee the ladder ACTS (scale-ups march while the
        # backlog clears), so the SIGKILL schedule lands mid-resize.
        slo = SloPolicy(p99_target_ticks=4, source="queue_wait")
        autoscaler = AutoscalerPolicy(cooldown_drains=0, trough_after=4)
    serve = ServeConfig(
        chunk_ticks=args.chunk_ticks,
        telemetry_window=max(2 * args.chunk_ticks, 64),
        max_chunks=args.chunks,
        checkpoint_dir=None if args.no_checkpoint else ckpt_dir,
        checkpoint_every=0 if args.no_checkpoint else args.every,
        slo=slo,
        autoscaler=autoscaler,
    )
    resumed = False
    loop = None
    if args.resume:
        # ONE load+verify: resume raises CheckpointError when no valid
        # checkpoint exists for this config (fresh dir, all torn, or
        # stale fingerprints) — the fresh-start fallback. Probing with
        # latest_valid first would read + CRC the whole npz twice.
        try:
            loop = ServeLoop.resume(mod, cfg, serve)
            resumed = True
        except checkpoint_mod.CheckpointError:
            pass
    if loop is None:
        eplan = getattr(cfg, "elastic", None)
        loop = ServeLoop(
            mod, cfg, serve, seed=args.seed,
            elastic_initial=(
                {n: eplan.floor_of(n) for n in eplan.names}
                if args.elastic and eplan is not None and eplan.active
                else None
            ),
        )
    sup = _SupervisedLoop(
        loop, args.out_dir,
        hang_after=args.hang_after if args.hang_after >= 0 else None,
        chunk_delay=args.chunk_delay,
        membership_script=(
            args.elastic and args.backend == "multipaxos"
        ),
    )
    sup._heartbeat(phase="startup")
    report = loop.run()
    inv = {
        k: bool(v)
        for k, v in mod.check_invariants(cfg, loop.state, loop.t).items()
    }
    lc_plan = getattr(cfg, "lifecycle", None)
    final = {
        "digest": checkpoint_mod.state_digest(loop.state),
        "invariants": inv,
        "invariants_ok": all(inv.values()),
        "ticks": report["ticks"],
        "chunks": loop._chunks,
        "resumed": resumed,
        "resumed_from": loop.resumed_from,
        "report": {k: v for k, v in report.items() if k != "totals"},
        "totals": report["totals"],
        "lifecycle": (
            lifecycle_mod.summary(lc_plan, loop.state.lifecycle)
            if lc_plan is not None and lc_plan.active
            else None
        ),
    }
    # The elastic leg's extra books: the device-side role counts and
    # the autoscaler's FULL host-side ladder context (the smoke
    # asserts both equal the uninterrupted twin's).
    el_plan = getattr(cfg, "elastic", None)
    if el_plan is not None and el_plan.active:
        from frankenpaxos_tpu.tpu import elastic as elastic_mod

        final["elastic"] = elastic_mod.summary(el_plan, loop.state.elastic)
    if getattr(loop, "autoscaler", None) is not None:
        final["autoscaler"] = loop.autoscaler.to_state()
    jax.block_until_ready(loop.state)
    tmp = os.path.join(args.out_dir, FINAL_FILE + ".tmp")
    with open(tmp, "w") as f:
        json.dump(final, f, indent=1)
    os.replace(tmp, os.path.join(args.out_dir, FINAL_FILE))
    return 0 if final["invariants_ok"] else 3


# ---------------------------------------------------------------------------
# Supervisor: randomized SIGKILL schedule + watchdog + capped backoff
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class SupervisorResult:
    ok: bool
    kills: List[int]
    watchdog_kills: int
    restarts: int
    backoffs: List[float]
    final: Optional[dict]
    notes: List[str]

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def _read_progress(out_dir: str) -> Optional[dict]:
    try:
        with open(os.path.join(out_dir, HEARTBEAT_FILE)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _spawn_worker(out_dir: str, argv_extra: List[str], resume: bool):
    # Clear the PREVIOUS worker's heartbeat: the watchdog must never
    # judge a fresh worker (still importing/compiling) by its
    # predecessor's stale timestamps.
    try:
        os.unlink(os.path.join(out_dir, HEARTBEAT_FILE))
    except OSError:
        pass
    argv = [
        sys.executable, "-m", "frankenpaxos_tpu.harness.recovery",
        "--worker", "--out-dir", out_dir, *argv_extra,
    ]
    if resume:
        argv.append("--resume")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    repo = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    log = open(os.path.join(out_dir, "worker.log"), "a")
    return subprocess.Popen(argv, stdout=log, stderr=log, env=env), log


def run_kill_recover(
    out_dir: str,
    *,
    chunks: int = 12,
    every: int = 2,
    chunk_ticks: int = 10,
    seed: int = 0,
    backend: str = "multipaxos",
    elastic: bool = False,
    kill_seed: int = 0,
    max_kills: int = 2,
    chunk_delay: float = 0.0,
    hang_after: int = -1,
    hang_timeout: float = 20.0,
    backoff_base: float = 0.2,
    backoff_cap: float = 5.0,
    max_restarts: int = 8,
    poll: float = 0.2,
    spawn_timeout: float = 600.0,
) -> SupervisorResult:
    """Run the supervised worker to completion under a randomized
    SIGKILL schedule. Kill points (chunk counts) are drawn from a
    deterministic rng over the checkpointed boundaries; each restart
    resumes from the latest valid checkpoint, with capped exponential
    backoff between restarts; a heartbeat staler than ``hang_timeout``
    triggers a watchdog SIGKILL + restart (the hung-dispatch path).
    """
    os.makedirs(out_dir, exist_ok=True)
    for fn in (HEARTBEAT_FILE, FINAL_FILE):
        try:
            os.unlink(os.path.join(out_dir, fn))
        except OSError:
            pass
    rng = _random.Random(kill_seed * 9973 + seed)
    # Randomized kill points: chunk boundaries strictly AFTER the first
    # checkpoint is durable (the boundary-`every` write lands while
    # chunk every+1 computes, so the first killable heartbeat is
    # every+1 — killing earlier would leave an empty checkpoint dir and
    # the 'recovery' would silently degrade to a fresh bit-exact rerun),
    # strictly increasing, never the final boundary.
    candidates = list(range(every + 1, chunks - 1))
    kill_points = sorted(
        rng.sample(candidates, min(max_kills, len(candidates)))
    ) if candidates else []
    argv_extra = [
        "--chunks", str(chunks), "--every", str(every),
        "--chunk-ticks", str(chunk_ticks), "--seed", str(seed),
        "--backend", backend,
    ]
    if elastic:
        argv_extra.append("--elastic")
    if chunk_delay:
        argv_extra += ["--chunk-delay", str(chunk_delay)]
    if hang_after >= 0:
        argv_extra += ["--hang-after", str(hang_after)]

    kills: List[int] = []
    backoffs: List[float] = []
    notes: List[str] = []
    watchdog_kills = 0
    restarts = 0
    resume = False
    final = None
    proc, log = _spawn_worker(out_dir, argv_extra, resume)
    deadline = time.monotonic() + spawn_timeout

    def restart_worker() -> bool:
        """Capped-exponential-backoff restart (shared by the crash-exit,
        scheduled-kill, and watchdog paths). False = budget exhausted."""
        nonlocal proc, log, restarts, resume
        if restarts >= max_restarts:
            notes.append("restart budget exhausted")
            return False
        delay = min(backoff_cap, backoff_base * (2 ** restarts))
        backoffs.append(delay)
        time.sleep(delay)
        restarts += 1
        resume = True
        log.close()
        proc, log = _spawn_worker(out_dir, argv_extra, resume)
        return True

    try:
        while True:
            rc = proc.poll()
            if rc is not None:
                final_path = os.path.join(out_dir, FINAL_FILE)
                if rc == 0 and os.path.exists(final_path):
                    with open(final_path) as f:
                        final = json.load(f)
                    break
                notes.append(f"worker exited rc={rc} without a report")
                if not restart_worker():
                    break
                continue
            if time.monotonic() > deadline:
                notes.append("supervisor timeout")
                proc.kill()
                break
            prog = _read_progress(out_dir)
            now = time.time()
            if (
                kill_points
                and len(kills) < len(kill_points)
                and prog is not None
                and prog["chunks"] >= kill_points[len(kills)]
            ):
                # The scheduled SIGKILL: no shutdown path runs, the OS
                # reaps the process mid-serve.
                os.kill(proc.pid, signal.SIGKILL)
                proc.wait()
                kills.append(prog["chunks"])
                if not restart_worker():
                    break
                continue
            if (
                prog is not None
                and prog.get("phase") != "startup"
                and now - prog["time"] > hang_timeout
            ):
                # Watchdog: heartbeats went stale — a hung dispatch.
                # Startup-phase heartbeats are exempt: the worker may
                # be cold-compiling its first chunk (spawn_timeout is
                # that phase's bound).
                os.kill(proc.pid, signal.SIGKILL)
                proc.wait()
                watchdog_kills += 1
                notes.append(
                    f"watchdog killed a hung worker at chunk "
                    f"{prog['chunks']}"
                )
                # A deliberately-hung worker (--hang-after) would hang
                # again: drop the hang flag for the restart, exactly
                # like an operator rolling a bad build back.
                argv_extra = [
                    a for i, a in enumerate(argv_extra)
                    if a != "--hang-after"
                    and (i == 0 or argv_extra[i - 1] != "--hang-after")
                ]
                if not restart_worker():
                    break
                continue
            time.sleep(poll)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
        log.close()
    ok = (
        final is not None
        and final["invariants_ok"]
        and final["chunks"] == chunks
    )
    return SupervisorResult(
        ok=ok,
        kills=kills,
        watchdog_kills=watchdog_kills,
        restarts=restarts,
        backoffs=backoffs,
        final=final,
        notes=notes,
    )


def uninterrupted_digest(
    *,
    chunks: int,
    every: int,
    chunk_ticks: int,
    seed: int,
    backend: str,
    out_dir: str,
    elastic: bool = False,
) -> dict:
    """The twin: the same worker run IN PROCESS with no kills — its
    final digest is what a killed-and-recovered run must reproduce
    bit for bit. Checkpointing stays ON (same config, same hot path;
    checkpoints are observationally free — the copy is alias-free and
    the State never reads the disk)."""
    import argparse

    args = argparse.Namespace(
        out_dir=out_dir, chunks=chunks, every=every,
        chunk_ticks=chunk_ticks, seed=seed, backend=backend,
        resume=False, hang_after=-1, no_checkpoint=False,
        sessions=4, resubmit_rate=0.1, groups=8, chunk_delay=0.0,
        elastic=elastic,
    )
    os.makedirs(out_dir, exist_ok=True)
    rc = run_worker(args)
    assert rc == 0, f"twin worker failed rc={rc}"
    with open(os.path.join(out_dir, FINAL_FILE)) as f:
        return json.load(f)


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    p = argparse.ArgumentParser(prog="frankenpaxos_tpu.harness.recovery")
    p.add_argument("--worker", action="store_true")
    p.add_argument("--smoke", action="store_true",
                   help="one SIGKILL-mid-serve + recover + bit-exact "
                   "verify (the CI leg)")
    p.add_argument("--out-dir", required=True)
    p.add_argument("--chunks", type=int, default=12)
    p.add_argument("--every", type=int, default=2)
    p.add_argument("--chunk-ticks", type=int, default=10)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--backend", default="multipaxos",
                   choices=("multipaxos", "compartmentalized")
                   + GENERIC_BACKENDS)
    p.add_argument("--groups", type=int, default=8)
    p.add_argument("--sessions", type=int, default=4)
    p.add_argument("--resubmit-rate", type=float, default=0.1)
    p.add_argument("--elastic", action="store_true",
                   help="the elastic worker shape: padded role planes "
                   "+ reconfig masks + the SLO/autoscaler ladder "
                   "(kills land mid-resize)")
    p.add_argument("--resume", action="store_true")
    p.add_argument("--no-checkpoint", action="store_true")
    p.add_argument("--chunk-delay", type=float, default=0.0,
                   help="worker: seconds slept per chunk (paces the "
                   "run so supervisor kill points land mid-serve)")
    p.add_argument("--hang-after", type=int, default=-1,
                   help="worker: stop heartbeating after this many "
                   "chunks (watchdog test)")
    p.add_argument("--kill-seed", type=int, default=0)
    p.add_argument("--max-kills", type=int, default=2)
    args = p.parse_args(argv)

    if args.worker:
        return run_worker(args)

    if args.smoke:
        kill_dir = os.path.join(args.out_dir, "killed")
        twin_dir = os.path.join(args.out_dir, "twin")
        res = run_kill_recover(
            kill_dir,
            chunks=args.chunks, every=args.every,
            chunk_ticks=args.chunk_ticks, seed=args.seed,
            backend=args.backend, elastic=args.elastic,
            kill_seed=args.kill_seed,
            max_kills=1,
            chunk_delay=args.chunk_delay or 0.15,
            poll=0.05,
        )
        assert res.ok, res.to_dict()
        assert res.kills, "smoke drew no kill point"
        # The final worker must have RESUMED from a checkpoint — a
        # fresh rerun would reproduce the twin digest too (same seed,
        # deterministic), so without this the smoke could pass without
        # ever exercising ServeLoop.resume.
        assert res.final.get("resumed"), (
            "killed worker restarted fresh instead of resuming "
            f"(no durable checkpoint at kill time?): {res.to_dict()}"
        )
        twin = uninterrupted_digest(
            chunks=args.chunks, every=args.every,
            chunk_ticks=args.chunk_ticks, seed=args.seed,
            backend=args.backend, out_dir=twin_dir,
            elastic=args.elastic,
        )
        assert res.final["digest"] == twin["digest"], (
            "recovered run diverged from the uninterrupted twin:\n"
            f"  recovered {res.final['digest']}\n"
            f"  twin      {twin['digest']}"
        )
        lc = res.final.get("lifecycle") or {}
        assert lc.get("cache_hits", 0) <= lc.get("resubmits", 0)
        if args.elastic:
            # Mid-resize recovery: the ladder context (targets, latch,
            # streaks) and the device-side role books both replay the
            # twin's, and the run actually resized (the kill had a
            # resize in flight to land on).
            assert res.final["autoscaler"] == twin["autoscaler"], (
                res.final["autoscaler"], twin["autoscaler"],
            )
            assert res.final["elastic"] == twin["elastic"]
            assert res.final["elastic"]["scale_ups"] >= 1
        print(json.dumps({
            "recovery_smoke": "PASS",
            "kills": res.kills,
            "restarts": res.restarts,
            "digest": res.final["digest"],
            "bit_exact_vs_twin": True,
            "invariants_ok": res.final["invariants_ok"],
            "lifecycle": lc,
            "elastic": res.final.get("elastic"),
        }))
        return 0

    res = run_kill_recover(
        args.out_dir,
        chunks=args.chunks, every=args.every,
        chunk_ticks=args.chunk_ticks, seed=args.seed,
        backend=args.backend, elastic=args.elastic,
        kill_seed=args.kill_seed,
        max_kills=args.max_kills, chunk_delay=args.chunk_delay,
    )
    print(json.dumps(res.to_dict()))
    return 0 if res.ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
