"""Per-protocol smoke runs (the analog of ``benchmarks/<proto>/smoke.py``
x18 + ``scripts/benchmark_smoke.sh``):

    python -m frankenpaxos_tpu.harness.smoke            # all
    python -m frankenpaxos_tpu.harness.smoke multipaxos # one

By default protocols smoke in-process on the sim transport (fast) and
``tpu`` smokes the batched backend. With ``--deploy``, EVERY protocol runs
a REAL localhost deployment: each role is its own OS process launched
through the role mains (``frankenpaxos_tpu.mains.run``, or the dedicated
multipaxos main), a closed-loop client process drives it, and the
recorder CSV is summarized:

    python -m frankenpaxos_tpu.harness.smoke --deploy            # all 20
    python -m frankenpaxos_tpu.harness.smoke --deploy epaxos
"""

from __future__ import annotations

import contextlib
import csv
import json
import random
import sys
import tempfile
import time

from frankenpaxos_tpu.harness.benchmark import (
    BenchmarkDirectory,
    summarize_latency_throughput,
)


def _base_port() -> int:
    # Per-process port block so overlapping smoke runs don't collide on
    # EADDRINUSE (each deployment uses role ports at offsets 0-50, the
    # client at 50, and per-role /metrics exporters at 100+).
    import os

    return 20000 + (os.getpid() % 200) * 150


def _role_env() -> dict:
    """Role processes don't touch accelerators; strip env hooks that would
    import heavyweight ML stacks into every subprocess (14 concurrent jax
    imports starve a small machine for >30s)."""
    import os

    return {k: v for k, v in os.environ.items() if k != "PALLAS_AXON_POOL_IPS"}


def _summarize_recorder(path: str) -> dict:
    with open(path) as f:
        rows = [
            {"start": float(r["start"]), "latency_nanos": float(r["latency_nanos"])}
            for r in csv.DictReader(f)
        ]
    assert rows, "no requests completed"
    summary = summarize_latency_throughput(rows)
    return {
        "requests": len(rows),
        "throughput_per_s": (
            round(summary.throughput_per_s, 1) if summary else None
        ),
        "median_ms": round(summary.median_ms, 2) if summary else None,
        "p99_ms": round(summary.p99_ms, 2) if summary else None,
    }


def smoke_multipaxos(
    bench: BenchmarkDirectory,
    duration: float = 3.0,
    num_pseudonyms: int = 3,
    capture_metrics: bool = True,
) -> dict:
    from frankenpaxos_tpu.monitoring.scrape import MetricsScraper, scrape_config

    port = _base_port()

    def hp(i):
        return f"127.0.0.1:{port + i}"

    config = {
        "f": 1,
        "batchers": [],
        "read_batchers": [],
        "leaders": [hp(0), hp(1)],
        "leader_elections": [hp(2), hp(3)],
        "proxy_leaders": [hp(4), hp(5)],
        "acceptors": [[hp(6), hp(7), hp(8)], [hp(9), hp(10), hp(11)]],
        "replicas": [hp(12), hp(13)],
        "proxy_replicas": [],
        "flexible": False,
        "distribution_scheme": "hash",
    }
    config_path = bench.write_string("config.json", json.dumps(config, indent=2))

    env = _role_env()

    def role(label, *extra):
        return bench.popen(label, [
            sys.executable, "-m", "frankenpaxos_tpu.mains.multipaxos",
            "--config", config_path, "--log_level", "error", *extra,
        ], env=env)

    jobs = {}
    metrics_port = [port + 100]

    def metrics_args(role_name):
        if not capture_metrics:
            return ()
        p = metrics_port[0]
        metrics_port[0] += 1
        jobs.setdefault(role_name, []).append(f"127.0.0.1:{p}")
        return ("--prometheus_port", str(p), "--prometheus_host", "127.0.0.1")

    # Dependency order: a leader runs phase 1 at startup, so its acceptors
    # must already be listening (first-connection failures drop messages
    # until the 5s phase-1 resend, which would eat the whole smoke window).
    for g in range(2):
        for i in range(3):
            role(f"acceptor_{g}_{i}", "--role", "acceptor",
                 "--group_index", str(g), "--index", str(i),
                 *metrics_args("acceptor"))
    for i in range(2):
        role(f"replica_{i}", "--role", "replica", "--index", str(i),
             *metrics_args("replica"))
    for i in range(2):
        role(f"proxy_leader_{i}", "--role", "proxy_leader", "--index", str(i),
             *metrics_args("proxy_leader"))
    time.sleep(1.0)
    for i in range(2):
        role(f"leader_{i}", "--role", "leader", "--index", str(i),
             *metrics_args("leader"))
    time.sleep(1.5)  # client lag (the reference's client_lag)

    recorder = bench.abspath("recorder.csv")
    with contextlib.ExitStack() as stack:
        if capture_metrics:
            bench.write_json("prometheus.json", scrape_config(200, jobs))
            stack.enter_context(
                MetricsScraper(jobs, bench.abspath("metrics.csv"))
            )
        client = role(
            "client", "--role", "client", "--listen", hp(50),
            "--duration", str(duration),
            "--num_pseudonyms", str(num_pseudonyms),
            "--workload", '{"type": "read_write", "read_fraction": 0.25}',
            "--output", recorder,
        )
        code = client.wait(timeout=duration + 30)
    assert code == 0, f"client exited with {code}"
    return _summarize_recorder(recorder)


def deploy_smoke(
    name: str,
    bench: BenchmarkDirectory,
    duration: float = 3.0,
    num_pseudonyms: int = 2,
    capture_metrics: bool = True,
    profile_role: str | None = None,
) -> dict:
    """A real localhost deployment of ``name``: every role is its own OS
    process launched via the generic role main
    (``frankenpaxos_tpu.mains.run``), driven by a closed-loop client
    process, summarized from the recorder CSV — the analog of the
    reference's per-protocol ``benchmarks/<proto>/smoke.py`` deployments
    (``scripts/benchmark_smoke.sh:5-20``). With ``capture_metrics`` each
    role exposes /metrics and a scraper captures samples into the bench
    dir's ``metrics.csv``, queryable via ``monitoring.scrape
    .MetricsCapture`` (the per-benchmark Prometheus of
    ``benchmarks/prometheus.py``). ``profile_role`` wraps the first
    process of that role with cProfile (the perf-record/flame-graph
    capability of ``benchmarks/perf_util.py:37-96``); the pstats dump
    lands in the bench dir as ``profile_<role>.pstats``."""
    from frankenpaxos_tpu.mains.registry import (
        REGISTRY,
        iter_role_instances,
    )
    from frankenpaxos_tpu.monitoring.scrape import MetricsScraper, scrape_config

    if name == "multipaxos":
        if profile_role is not None:
            raise ValueError(
                "profile_role is not supported for the multipaxos smoke "
                "(it deploys via its dedicated main)"
            )
        return smoke_multipaxos(
            bench, duration,
            num_pseudonyms=num_pseudonyms,
            capture_metrics=capture_metrics,
        )
    spec = REGISTRY[name]
    if profile_role is not None and profile_role not in spec.roles:
        raise ValueError(
            f"profile_role {profile_role!r} is not a role of {name}; "
            f"roles: {sorted(spec.roles)}"
        )
    port = _base_port()

    def hp(i):
        return f"127.0.0.1:{port + i}"

    config_dict = spec.local_config(hp)
    config_path = bench.write_string(
        "config.json", json.dumps(config_dict, indent=2)
    )
    config = spec.parse_config(config_dict)
    env = _role_env()

    profiled_proc = [None]

    def role_proc(label, *extra):
        prefix = [sys.executable]
        run_for = ()
        profiling = (
            profile_role is not None
            and label.startswith(profile_role)
            and profiled_proc[0] is None
        )
        if profiling:
            prefix = [
                sys.executable, "-m", "cProfile",
                "-o", bench.abspath(f"profile_{profile_role}.pstats"),
            ]
            # The profiler only dumps on clean interpreter exit; schedule
            # the role's shutdown just past the client's run, and the
            # smoke waits for it below (the process reaper would
            # otherwise SIGKILL it before the dump).
            # Generous slack past the client's window: tier sleeps,
            # process spawns, and load can push the client's finish well
            # past its nominal schedule, and an early exit of a singleton
            # role would hang the client.
            run_for = ("--run_for", str(spec.client_lag + duration + 15.0))
        proc = bench.popen(label, [
            *prefix, "-m", "frankenpaxos_tpu.mains.run",
            "--protocol", name, "--config", config_path,
            "--log_level", "error", *extra, *run_for,
        ], env=env)
        if profiling:
            profiled_proc[0] = proc
        return proc

    jobs = {}
    metrics_port = [port + 100]

    def metrics_args(role_name):
        if not capture_metrics:
            return ()
        p = metrics_port[0]
        metrics_port[0] += 1
        jobs.setdefault(role_name, []).append(f"127.0.0.1:{p}")
        return ("--prometheus_port", str(p), "--prometheus_host", "127.0.0.1")

    prev_role = None
    for role_name, role, g, i in iter_role_instances(spec, config):
        if prev_role is not None and role_name != prev_role:
            # A new tier may run startup phases against earlier ones (e.g.
            # a leader's phase 1 against its acceptors): let the previous
            # tier's listeners bind first.
            time.sleep(0.4)
        prev_role = role_name
        label = f"{role_name}_{g}_{i}" if role.grouped else f"{role_name}_{i}"
        extra = ("--group_index", str(g)) if role.grouped else ()
        role_proc(label, "--role", role_name, "--index", str(i), *extra,
                  *metrics_args(role_name))
    time.sleep(1.0)  # let the last tier (usually leaders) finish startup

    time.sleep(spec.client_lag)
    recorder = bench.abspath("recorder.csv")
    with contextlib.ExitStack() as stack:
        if capture_metrics:
            bench.write_json("prometheus.json", scrape_config(200, jobs))
            stack.enter_context(
                MetricsScraper(jobs, bench.abspath("metrics.csv"))
            )
        client = role_proc(
            "client", "--role", "client", "--listen", hp(50),
            "--duration", str(duration),
            "--num_pseudonyms", str(num_pseudonyms),
            "--warmup", "0", "--output", recorder,
        )
        code = client.wait(timeout=duration + 30)
        if profiled_proc[0] is not None:
            # Let the profiled role hit its clean-exit timer and write
            # the pstats dump before the reaper kills everything; a None
            # result is a timeout (PopenProc.wait doesn't raise), which
            # would mean no dump was written.
            rc = profiled_proc[0].wait(timeout=30)
            assert rc is not None, (
                f"profiled {profile_role} role did not exit in time; "
                f"no pstats dump was written"
            )
    assert code == 0, f"client exited with {code}"
    return _summarize_recorder(recorder)


def _drain(t, max_steps=200000):
    steps = 0
    while t.messages and steps < max_steps:
        t.deliver_message(t.messages[0])
        steps += 1


def _sim_smoke(build, operate) -> dict:
    """Generic in-process smoke: construct a cluster, run the ops, count
    completions."""
    from frankenpaxos_tpu.core import FakeLogger, SimTransport
    from frankenpaxos_tpu.core.logger import LogLevel

    t = SimTransport(FakeLogger(LogLevel.FATAL))
    ctx = build(t)
    promises = operate(t, ctx)
    _drain(t)
    for _ in range(6):
        if all(p.done for p in promises):
            break
        for timer in list(t.running_timers()):
            t.trigger_timer(timer.address, timer.name())
        _drain(t)
    done = sum(p.done for p in promises)
    assert done == len(promises), f"only {done}/{len(promises)} completed"
    return {"requests": len(promises)}


def smoke_unreplicated(bench=None) -> dict:
    from frankenpaxos_tpu.core import FakeLogger, SimAddress
    from frankenpaxos_tpu.core.logger import LogLevel
    from frankenpaxos_tpu.protocols import unreplicated as unrep
    from frankenpaxos_tpu.statemachine import KeyValueStore, kv_set

    def build(t):
        server = SimAddress("server")
        unrep.Server(server, t, FakeLogger(LogLevel.FATAL), KeyValueStore())
        return unrep.Client(
            SimAddress("client"), t, FakeLogger(LogLevel.FATAL), server
        )

    def operate(t, client):
        return [
            client.propose(i, kv_set((f"k{i}", "v"))) for i in range(5)
        ]

    return _sim_smoke(build, operate)


def smoke_batchedunreplicated(bench=None) -> dict:
    from frankenpaxos_tpu.core import FakeLogger, SimAddress
    from frankenpaxos_tpu.core.logger import LogLevel
    from frankenpaxos_tpu.protocols import batchedunreplicated as bu
    from frankenpaxos_tpu.statemachine import KeyValueStore, kv_set

    def build(t):
        log = lambda: FakeLogger(LogLevel.FATAL)
        config = bu.BatchedUnreplicatedConfig(
            batcher_addresses=(SimAddress("b0"), SimAddress("b1")),
            server_address=SimAddress("server"),
            proxy_server_addresses=(SimAddress("p0"),),
        )
        for a in config.batcher_addresses:
            bu.BuBatcher(a, t, log(), config, bu.BuBatcherOptions(batch_size=2))
        bu.BuServer(config.server_address, t, log(), config, KeyValueStore())
        for a in config.proxy_server_addresses:
            bu.BuProxyServer(a, t, log(), config)
        return [
            bu.BuClient(SimAddress(f"c{i}"), t, log(), config, seed=i)
            for i in range(2)
        ]

    def operate(t, clients):
        return [
            c.propose(p, kv_set((f"k{i}{p}", "v")))
            for i, c in enumerate(clients)
            for p in range(2)
        ]

    return _sim_smoke(build, operate)


def smoke_paxos(bench=None) -> dict:
    from frankenpaxos_tpu.core import FakeLogger, SimAddress
    from frankenpaxos_tpu.core.logger import LogLevel
    from frankenpaxos_tpu.protocols import paxos as px

    def build(t):
        log = lambda: FakeLogger(LogLevel.FATAL)
        config = px.PaxosConfig(
            f=1,
            leader_addresses=(SimAddress("leader0"), SimAddress("leader1")),
            acceptor_addresses=tuple(
                SimAddress(f"acceptor{i}") for i in range(3)
            ),
        )
        for a in config.leader_addresses:
            px.PaxosLeader(a, t, log(), config)
        for a in config.acceptor_addresses:
            px.PaxosAcceptor(a, t, log(), config)
        return px.PaxosClient(SimAddress("client"), t, log(), config)

    def operate(t, client):
        return [client.propose("smoke")]

    return _sim_smoke(build, operate)


def smoke_fastpaxos(bench=None) -> dict:
    from frankenpaxos_tpu.core import FakeLogger, SimAddress
    from frankenpaxos_tpu.core.logger import LogLevel
    from frankenpaxos_tpu.protocols import fastpaxos as fp

    def build(t):
        log = lambda: FakeLogger(LogLevel.FATAL)
        config = fp.FastPaxosConfig(
            f=1,
            leader_addresses=(SimAddress("leader0"), SimAddress("leader1")),
            acceptor_addresses=tuple(
                SimAddress(f"acceptor{i}") for i in range(3)
            ),
        )
        for a in config.leader_addresses:
            fp.FpLeader(a, t, log(), config)
        for a in config.acceptor_addresses:
            fp.FpAcceptor(a, t, log(), config)
        return fp.FpClient(SimAddress("client"), t, log(), config)

    def operate(t, client):
        return [client.propose("smoke")]

    return _sim_smoke(build, operate)


def smoke_caspaxos(bench=None) -> dict:
    from frankenpaxos_tpu.core import FakeLogger, SimAddress
    from frankenpaxos_tpu.core.logger import LogLevel
    from frankenpaxos_tpu.protocols import caspaxos as cas

    def build(t):
        log = lambda: FakeLogger(LogLevel.FATAL)
        config = cas.CasPaxosConfig(
            f=1,
            leader_addresses=(SimAddress("leader0"), SimAddress("leader1")),
            acceptor_addresses=tuple(
                SimAddress(f"acceptor{i}") for i in range(3)
            ),
        )
        for a in config.leader_addresses:
            cas.CasLeader(a, t, log(), config)
        for a in config.acceptor_addresses:
            cas.CasAcceptor(a, t, log(), config)
        return cas.CasClient(SimAddress("client"), t, log(), config)

    def operate(t, client):
        return [client.propose({1, 2, 3})]

    return _sim_smoke(build, operate)


def smoke_craq(bench=None) -> dict:
    from frankenpaxos_tpu.core import FakeLogger, SimAddress
    from frankenpaxos_tpu.core.logger import LogLevel
    from frankenpaxos_tpu.protocols import craq as cq

    def build(t):
        log = lambda: FakeLogger(LogLevel.FATAL)
        config = cq.CraqConfig(
            f=1,
            chain_node_addresses=tuple(
                SimAddress(f"node{i}") for i in range(3)
            ),
        )
        for i, a in enumerate(config.chain_node_addresses):
            cq.ChainNode(a, t, log(), config, seed=i)
        return cq.CraqClient(SimAddress("client"), t, log(), config)

    def operate(t, client):
        return [client.write(0, "x", "1"), client.read(1, "x")]

    return _sim_smoke(build, operate)


def smoke_epaxos(bench=None) -> dict:
    from frankenpaxos_tpu.core import FakeLogger, SimAddress
    from frankenpaxos_tpu.core.logger import LogLevel
    from frankenpaxos_tpu.protocols import epaxos as ep
    from frankenpaxos_tpu.statemachine import KeyValueStore, kv_set

    def build(t):
        log = lambda: FakeLogger(LogLevel.FATAL)
        config = ep.EPaxosConfig(
            f=1,
            replica_addresses=tuple(
                SimAddress(f"replica{i}") for i in range(3)
            ),
        )
        for i, a in enumerate(config.replica_addresses):
            ep.EpReplica(a, t, log(), config, KeyValueStore(), seed=i)
        return [
            ep.EpClient(SimAddress(f"client{i}"), t, log(), config, seed=10 + i)
            for i in range(2)
        ]

    def operate(t, clients):
        return [
            c.propose(0, kv_set((f"k{i}", "v"))) for i, c in enumerate(clients)
        ]

    return _sim_smoke(build, operate)


def smoke_echo(bench=None) -> dict:
    from frankenpaxos_tpu.core import FakeLogger, SimAddress, SimTransport
    from frankenpaxos_tpu.core.logger import LogLevel
    from frankenpaxos_tpu.protocols.echo import EchoClient, EchoServer

    t = SimTransport(FakeLogger(LogLevel.FATAL))
    server = SimAddress("server")
    EchoServer(server, t, FakeLogger(LogLevel.FATAL))
    client = EchoClient(SimAddress("client"), t, FakeLogger(LogLevel.FATAL), server)
    client.echo("smoke")
    _drain(t)
    assert client.num_messages_received == 1
    return {"requests": 1}


def smoke_simplebpaxos(bench=None) -> dict:
    from frankenpaxos_tpu.core import FakeLogger, SimAddress
    from frankenpaxos_tpu.core.logger import LogLevel
    from frankenpaxos_tpu.protocols import simplebpaxos as bpx
    from frankenpaxos_tpu.statemachine import KeyValueStore, kv_set

    def build(t):
        log = lambda: FakeLogger(LogLevel.FATAL)
        config = bpx.SimpleBPaxosConfig(
            f=1,
            leader_addresses=(SimAddress("bpl0"), SimAddress("bpl1")),
            proposer_addresses=(SimAddress("bpp0"), SimAddress("bpp1")),
            dep_service_node_addresses=tuple(
                SimAddress(f"bpd{i}") for i in range(3)
            ),
            acceptor_addresses=tuple(SimAddress(f"bpa{i}") for i in range(3)),
            replica_addresses=(SimAddress("bpr0"), SimAddress("bpr1")),
        )
        for a in config.leader_addresses:
            bpx.BpLeader(a, t, log(), config)
        for a in config.proposer_addresses:
            bpx.BpProposer(a, t, log(), config)
        for a in config.dep_service_node_addresses:
            bpx.BpDepServiceNode(a, t, log(), config, KeyValueStore())
        for a in config.acceptor_addresses:
            bpx.BpAcceptor(a, t, log(), config)
        for a in config.replica_addresses:
            bpx.BpReplica(a, t, log(), config, KeyValueStore())
        return bpx.BpClient(SimAddress("bpc"), t, log(), config)

    def operate(t, client):
        return [client.propose(0, kv_set(("x", "1")))]

    return _sim_smoke(build, operate)


def smoke_vanillamencius(bench=None) -> dict:
    from frankenpaxos_tpu.core import FakeLogger, SimAddress
    from frankenpaxos_tpu.core.logger import LogLevel
    from frankenpaxos_tpu.protocols import vanillamencius as vmn
    from frankenpaxos_tpu.statemachine import ReadableAppendLog

    def build(t):
        log = lambda: FakeLogger(LogLevel.FATAL)
        config = vmn.VanillaMenciusConfig(
            f=1,
            server_addresses=tuple(SimAddress(f"vms{i}") for i in range(3)),
            heartbeat_addresses=tuple(SimAddress(f"vmh{i}") for i in range(3)),
        )
        for i, a in enumerate(config.server_addresses):
            vmn.VmServer(a, t, log(), config, ReadableAppendLog(), seed=i)
        return [
            vmn.VmClient(SimAddress(f"vmc{i}"), t, log(), config, seed=10 + i)
            for i in range(2)
        ]

    def operate(t, clients):
        return [c.propose(0, f"cmd{i}".encode()) for i, c in enumerate(clients)]

    return _sim_smoke(build, operate)


def smoke_mencius(bench=None) -> dict:
    from frankenpaxos_tpu.core import FakeLogger, SimAddress
    from frankenpaxos_tpu.core.logger import LogLevel
    from frankenpaxos_tpu.protocols import mencius as mnc
    from frankenpaxos_tpu.protocols import multipaxos as mpx
    from frankenpaxos_tpu.statemachine import ReadableAppendLog

    def build(t):
        log = lambda: FakeLogger(LogLevel.FATAL)
        config = mnc.MenciusConfig(
            f=1,
            batcher_addresses=(),
            leader_groups=tuple(
                tuple(SimAddress(f"mnl_{g}_{m}") for m in range(2))
                for g in range(3)
            ),
            leader_election_groups=tuple(
                tuple(SimAddress(f"mne_{g}_{m}") for m in range(2))
                for g in range(3)
            ),
            proxy_leader_addresses=(SimAddress("mnp0"), SimAddress("mnp1")),
            acceptor_addresses=tuple(
                tuple(SimAddress(f"mna_{g}_{i}") for i in range(3))
                for g in range(2)
            ),
            replica_addresses=(SimAddress("mnr0"), SimAddress("mnr1")),
            proxy_replica_addresses=(),
        )
        leaders = [
            mnc.MenciusLeader(a, t, log(), config, seed=i)
            for i, a in enumerate(config.leader_addresses)
        ]
        for i, a in enumerate(config.proxy_leader_addresses):
            mpx.ProxyLeader(a, t, log(), config, seed=10 + i)
        for group in config.acceptor_addresses:
            for a in group:
                mnc.MenciusAcceptor(a, t, log(), config)
        for i, a in enumerate(config.replica_addresses):
            mpx.Replica(a, t, log(), ReadableAppendLog(), config, seed=20 + i)
        clients = [
            mnc.MenciusClient(SimAddress(f"mnc{i}"), t, log(), config, seed=40 + i)
            for i in range(2)
        ]
        return clients, leaders

    def operate(t, ctx):
        clients, leaders = ctx
        promises = [
            c.write(p, f"c{i}p{p}".encode())
            for i, c in enumerate(clients)
            for p in range(2)
        ]
        _drain(t)
        for leader in leaders:
            leader._broadcast_watermark()
        return promises

    return _sim_smoke(build, operate)


def smoke_unanimousbpaxos(bench=None) -> dict:
    from frankenpaxos_tpu.core import FakeLogger, SimAddress
    from frankenpaxos_tpu.core.logger import LogLevel
    from frankenpaxos_tpu.protocols import unanimousbpaxos as ubx
    from frankenpaxos_tpu.statemachine import KeyValueStore, kv_set

    def build(t):
        log = lambda: FakeLogger(LogLevel.FATAL)
        config = ubx.UnanimousBPaxosConfig(
            f=1,
            leader_addresses=(SimAddress("ubl0"), SimAddress("ubl1")),
            dep_service_node_addresses=tuple(
                SimAddress(f"ubd{i}") for i in range(3)
            ),
            acceptor_addresses=tuple(SimAddress(f"uba{i}") for i in range(3)),
        )
        for a in config.leader_addresses:
            ubx.UbLeader(a, t, log(), config, KeyValueStore())
        for a in config.dep_service_node_addresses:
            ubx.UbDepServiceNode(a, t, log(), config, KeyValueStore())
        for a in config.acceptor_addresses:
            ubx.UbAcceptor(a, t, log(), config)
        return ubx.UbClient(SimAddress("ubc"), t, log(), config)

    def operate(t, client):
        return [client.propose(0, kv_set(("x", "1")))]

    return _sim_smoke(build, operate)


def smoke_matchmakerpaxos(bench=None) -> dict:
    from frankenpaxos_tpu.core import FakeLogger, SimAddress
    from frankenpaxos_tpu.core.logger import LogLevel
    from frankenpaxos_tpu.protocols import matchmakerpaxos as mmx

    def build(t):
        log = lambda: FakeLogger(LogLevel.FATAL)
        config = mmx.MatchmakerPaxosConfig(
            f=1,
            client_addresses=(SimAddress("mmc0"),),
            leader_addresses=(SimAddress("mml0"), SimAddress("mml1")),
            matchmaker_addresses=tuple(SimAddress(f"mmm{i}") for i in range(3)),
            acceptor_addresses=tuple(SimAddress(f"mma{i}") for i in range(4)),
        )
        for a in config.leader_addresses:
            mmx.MmLeader(a, t, log(), config)
        for a in config.matchmaker_addresses:
            mmx.MmMatchmaker(a, t, log(), config)
        for a in config.acceptor_addresses:
            mmx.MmAcceptor(a, t, log(), config)
        return mmx.MmClient(config.client_addresses[0], t, log(), config)

    def operate(t, client):
        return [client.propose("smoke")]

    return _sim_smoke(build, operate)


def smoke_fasterpaxos(bench=None) -> dict:
    from frankenpaxos_tpu.core import FakeLogger, SimAddress
    from frankenpaxos_tpu.core.logger import LogLevel
    from frankenpaxos_tpu.protocols import fasterpaxos as fpx
    from frankenpaxos_tpu.statemachine import ReadableAppendLog

    def build(t):
        log = lambda: FakeLogger(LogLevel.FATAL)
        config = fpx.FasterPaxosConfig(
            f=1,
            server_addresses=tuple(SimAddress(f"fps{i}") for i in range(3)),
            heartbeat_addresses=tuple(SimAddress(f"fph{i}") for i in range(3)),
        )
        for i, a in enumerate(config.server_addresses):
            fpx.FprServer(a, t, log(), config, ReadableAppendLog(), seed=i)
        _drain(t)  # round 0 phase 1 + Phase2aAny
        return [
            fpx.FprClient(SimAddress(f"fpc{i}"), t, log(), config, seed=50 + i)
            for i in range(2)
        ]

    def operate(t, clients):
        return [c.propose(0, f"cmd{i}".encode()) for i, c in enumerate(clients)]

    return _sim_smoke(build, operate)


def smoke_horizontal(bench=None) -> dict:
    from frankenpaxos_tpu.core import FakeLogger, SimAddress
    from frankenpaxos_tpu.core.logger import LogLevel
    from frankenpaxos_tpu.protocols import horizontal as hzx
    from frankenpaxos_tpu.statemachine import ReadableAppendLog

    def build(t):
        log = lambda: FakeLogger(LogLevel.FATAL)
        config = hzx.HorizontalConfig(
            f=1,
            leader_addresses=(SimAddress("hzl0"), SimAddress("hzl1")),
            leader_election_addresses=(
                SimAddress("hze0"), SimAddress("hze1"),
            ),
            acceptor_addresses=tuple(SimAddress(f"hza{i}") for i in range(4)),
            replica_addresses=(SimAddress("hzr0"), SimAddress("hzr1")),
        )
        for i, a in enumerate(config.leader_addresses):
            hzx.HzLeader(a, t, log(), config, seed=i)
        for a in config.acceptor_addresses:
            hzx.HzAcceptor(a, t, log(), config)
        for i, a in enumerate(config.replica_addresses):
            hzx.HzReplica(a, t, log(), config, ReadableAppendLog(),
                          seed=30 + i)
        _drain(t)  # initial chunk phase 1
        driver = hzx.HzDriver(SimAddress("hzd"), t, log(), config, seed=99)
        clients = [
            hzx.HzClient(SimAddress(f"hzc{i}"), t, log(), config, seed=50 + i)
            for i in range(2)
        ]
        return driver, clients

    def operate(t, ctx):
        driver, clients = ctx
        promises = [clients[0].propose(0, b"cmd0")]
        _drain(t)
        # An in-log reconfiguration mid-smoke.
        driver.force_reconfiguration(members=(1, 2, 3))
        promises.append(clients[1].propose(0, b"cmd1"))
        return promises

    return _sim_smoke(build, operate)


def smoke_matchmakermultipaxos(bench=None) -> dict:
    from frankenpaxos_tpu.core import FakeLogger, SimAddress
    from frankenpaxos_tpu.core.logger import LogLevel
    from frankenpaxos_tpu.protocols import matchmakermultipaxos as mmx
    from frankenpaxos_tpu.statemachine import ReadableAppendLog

    def build(t):
        log = lambda: FakeLogger(LogLevel.FATAL)
        config = mmx.MatchmakerMultiPaxosConfig(
            f=1,
            leader_addresses=(SimAddress("mxl0"), SimAddress("mxl1")),
            leader_election_addresses=(
                SimAddress("mxe0"), SimAddress("mxe1"),
            ),
            reconfigurer_addresses=(SimAddress("mxr0"), SimAddress("mxr1")),
            matchmaker_addresses=tuple(
                SimAddress(f"mxm{i}") for i in range(4)
            ),
            acceptor_addresses=tuple(SimAddress(f"mxa{i}") for i in range(4)),
            replica_addresses=(SimAddress("mxrep0"), SimAddress("mxrep1")),
        )
        for i, a in enumerate(config.leader_addresses):
            mmx.MmmLeader(a, t, log(), config, seed=i)
        for i, a in enumerate(config.reconfigurer_addresses):
            mmx.MmmReconfigurer(a, t, log(), config, seed=10 + i)
        for a in config.matchmaker_addresses:
            mmx.MmmMatchmaker(a, t, log(), config)
        for a in config.acceptor_addresses:
            mmx.MmmAcceptor(a, t, log(), config)
        for i, a in enumerate(config.replica_addresses):
            mmx.MmmReplica(a, t, log(), config, ReadableAppendLog(),
                           seed=30 + i)
        _drain(t)  # leader 0's matchmaking + phase 1
        driver = mmx.MmmDriver(
            SimAddress("mxd"), t, log(), config, mmx.DoNothing(), seed=99
        )
        clients = [
            mmx.MmmClient(SimAddress(f"mxc{i}"), t, log(), config, seed=50 + i)
            for i in range(2)
        ]
        return driver, clients

    def operate(t, ctx):
        driver, clients = ctx
        promises = [clients[0].propose(0, b"cmd0")]
        _drain(t)
        # Exercise an acceptor reconfiguration mid-smoke.
        driver.force_reconfiguration(members=(1, 2, 3))
        promises.append(clients[1].propose(0, b"cmd1"))
        return promises

    return _sim_smoke(build, operate)


def smoke_simplegcbpaxos(bench=None) -> dict:
    from frankenpaxos_tpu.core import FakeLogger, SimAddress
    from frankenpaxos_tpu.core.logger import LogLevel
    from frankenpaxos_tpu.protocols import simplegcbpaxos as gcb
    from frankenpaxos_tpu.statemachine import KeyValueStore, kv_set

    def build(t):
        log = lambda: FakeLogger(LogLevel.FATAL)
        config = gcb.SimpleGcBPaxosConfig(
            f=1,
            leader_addresses=(SimAddress("gbl0"), SimAddress("gbl1")),
            proposer_addresses=(SimAddress("gbp0"), SimAddress("gbp1")),
            dep_service_node_addresses=tuple(
                SimAddress(f"gbd{i}") for i in range(3)
            ),
            acceptor_addresses=tuple(SimAddress(f"gba{i}") for i in range(3)),
            replica_addresses=(SimAddress("gbr0"), SimAddress("gbr1")),
            garbage_collector_addresses=(
                SimAddress("gbg0"), SimAddress("gbg1"),
            ),
        )
        for i, a in enumerate(config.leader_addresses):
            gcb.GcLeader(a, t, log(), config, seed=i)
        for i, a in enumerate(config.proposer_addresses):
            gcb.GcProposer(a, t, log(), config, seed=10 + i)
        for a in config.dep_service_node_addresses:
            gcb.GcDepServiceNode(a, t, log(), config, KeyValueStore())
        for a in config.acceptor_addresses:
            gcb.GcAcceptor(a, t, log(), config)
        for i, a in enumerate(config.replica_addresses):
            gcb.GcReplica(a, t, log(), config, KeyValueStore(), seed=30 + i)
        for a in config.garbage_collector_addresses:
            gcb.GcGarbageCollector(a, t, log(), config)
        return [
            gcb.GcClient(SimAddress(f"gbc{i}"), t, log(), config, seed=50 + i)
            for i in range(2)
        ]

    def operate(t, clients):
        return [
            c.propose(0, kv_set((f"k{i}", "v"))) for i, c in enumerate(clients)
        ]

    return _sim_smoke(build, operate)


def smoke_fastmultipaxos(bench=None) -> dict:
    from frankenpaxos_tpu.core import FakeLogger, SimAddress
    from frankenpaxos_tpu.core.logger import LogLevel
    from frankenpaxos_tpu.protocols import fastmultipaxos as fmx
    from frankenpaxos_tpu.roundsystem import MixedRoundRobin
    from frankenpaxos_tpu.statemachine import ReadableAppendLog

    def build(t):
        log = lambda: FakeLogger(LogLevel.FATAL)
        config = fmx.FastMultiPaxosConfig(
            f=1,
            leader_addresses=(SimAddress("fml0"), SimAddress("fml1")),
            leader_election_addresses=(
                SimAddress("fme0"), SimAddress("fme1"),
            ),
            leader_heartbeat_addresses=(
                SimAddress("fmh0"), SimAddress("fmh1"),
            ),
            acceptor_addresses=tuple(SimAddress(f"fma{i}") for i in range(3)),
            acceptor_heartbeat_addresses=tuple(
                SimAddress(f"fmah{i}") for i in range(3)
            ),
            round_system=MixedRoundRobin(2),
        )
        for i, a in enumerate(config.leader_addresses):
            fmx.FmpLeader(a, t, log(), config, ReadableAppendLog(), seed=i)
        for i, a in enumerate(config.acceptor_addresses):
            fmx.FmpAcceptor(a, t, log(), config, seed=10 + i)
        _drain(t)  # finish phase 1 + any-suffix before clients write
        return [
            fmx.FmpClient(SimAddress(f"fmc{i}"), t, log(), config, seed=40 + i)
            for i in range(2)
        ]

    def operate(t, clients):
        return [c.propose(0, f"cmd{i}".encode()) for i, c in enumerate(clients)]

    return _sim_smoke(build, operate)


def smoke_scalog(bench=None) -> dict:
    from frankenpaxos_tpu.core import FakeLogger, SimAddress
    from frankenpaxos_tpu.core.logger import LogLevel
    from frankenpaxos_tpu.protocols import scalog as scx
    from frankenpaxos_tpu.protocols.multipaxos.replica import Replica
    from frankenpaxos_tpu.statemachine import ReadableAppendLog

    def build(t):
        log = lambda: FakeLogger(LogLevel.FATAL)
        config = scx.ScalogConfig(
            f=1,
            server_addresses=(
                (SimAddress("scs_0_0"), SimAddress("scs_0_1")),
                (SimAddress("scs_1_0"), SimAddress("scs_1_1")),
            ),
            aggregator_address=SimAddress("scagg"),
            leader_addresses=(SimAddress("scl0"), SimAddress("scl1")),
            acceptor_addresses=tuple(SimAddress(f"sca{i}") for i in range(3)),
            replica_addresses=(SimAddress("scr0"), SimAddress("scr1")),
        )
        for i, a in enumerate(config.flat_servers):
            scx.ScServer(
                a, t, log(), config, scx.ScServerOptions(push_size=1), seed=i
            )
        scx.ScAggregator(
            config.aggregator_address, t, log(), config,
            scx.ScAggregatorOptions(num_shard_cuts_per_proposal=1),
        )
        for i, a in enumerate(config.leader_addresses):
            scx.ScLeader(a, t, log(), config, seed=10 + i)
        for a in config.acceptor_addresses:
            scx.ScAcceptor(a, t, log(), config)
        for i, a in enumerate(config.replica_addresses):
            Replica(
                a, t, log(), ReadableAppendLog(),
                scx.replica_config(config), seed=20 + i,
            )
        return [
            scx.ScClient(SimAddress(f"scc{i}"), t, log(), config, seed=40 + i)
            for i in range(2)
        ]

    def operate(t, clients):
        return [c.write(0, f"cmd{i}".encode()) for i, c in enumerate(clients)]

    return _sim_smoke(build, operate)


def smoke_tpu(bench=None) -> dict:
    import jax

    try:
        jax.devices()
    except RuntimeError:
        # No accelerator available (or its plugin can't initialize): the
        # smoke only checks correctness, so fall back to CPU.
        jax.config.update("jax_platforms", "cpu")

    from frankenpaxos_tpu.tpu import BatchedMultiPaxosConfig, TpuSimTransport

    cfg = BatchedMultiPaxosConfig(
        f=1, num_groups=8, window=16, slots_per_tick=2, lat_min=1, lat_max=2
    )
    sim = TpuSimTransport(cfg, seed=0)
    sim.run(100)
    stats = sim.stats()
    assert stats["committed"] > 0
    assert all(sim.check_invariants().values())
    return {
        "committed": stats["committed"],
        "p50_latency_ticks": stats["commit_latency_p50_ticks"],
    }


SMOKES = {
    "echo": smoke_echo,
    "unreplicated": smoke_unreplicated,
    "batchedunreplicated": smoke_batchedunreplicated,
    "paxos": smoke_paxos,
    "fastpaxos": smoke_fastpaxos,
    "caspaxos": smoke_caspaxos,
    "craq": smoke_craq,
    "epaxos": smoke_epaxos,
    "simplebpaxos": smoke_simplebpaxos,
    "simplegcbpaxos": smoke_simplegcbpaxos,
    "vanillamencius": smoke_vanillamencius,
    "mencius": smoke_mencius,
    "unanimousbpaxos": smoke_unanimousbpaxos,
    "matchmakerpaxos": smoke_matchmakerpaxos,
    "matchmakermultipaxos": smoke_matchmakermultipaxos,
    "horizontal": smoke_horizontal,
    "fasterpaxos": smoke_fasterpaxos,
    "fastmultipaxos": smoke_fastmultipaxos,
    "scalog": smoke_scalog,
    "multipaxos": smoke_multipaxos,
    "tpu": smoke_tpu,
}


def main() -> None:
    argv = sys.argv[1:]
    deploy = "--deploy" in argv
    names = [a for a in argv if a != "--deploy"]
    from frankenpaxos_tpu.mains.registry import REGISTRY

    deployable = sorted(REGISTRY) + ["multipaxos"]
    if deploy:
        names = names or deployable
        unknown = [n for n in names if n not in deployable]
    else:
        names = names or list(SMOKES)
        unknown = [n for n in names if n not in SMOKES]
    if unknown:
        valid = deployable if deploy else list(SMOKES)
        print(
            f"unknown protocol(s) {', '.join(unknown)}; "
            f"choose from: {', '.join(valid)}",
            file=sys.stderr,
        )
        sys.exit(2)
    failures = []
    for name in names:
        kind = "deploy" if deploy else "smoke"
        bench = BenchmarkDirectory(tempfile.mkdtemp(prefix=f"smoke_{name}_"))
        try:
            with bench:
                result = (
                    deploy_smoke(name, bench) if deploy else SMOKES[name](bench)
                )
            print(f"{kind} {name}: OK {result}")
        except Exception as e:  # noqa: BLE001
            failures.append(name)
            print(f"{kind} {name}: FAILED ({e!r}); logs in {bench.path}")
    if failures:
        sys.exit(1)


if __name__ == "__main__":
    main()
