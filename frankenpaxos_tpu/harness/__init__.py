"""Benchmark orchestration harness (the analog of the reference's
``benchmarks/`` Python package, SURVEY.md §2.6)."""

from frankenpaxos_tpu.harness.benchmark import (
    BenchmarkDirectory,
    Reaped,
    Suite,
    SuiteDirectory,
)
from frankenpaxos_tpu.harness.cluster import Cluster
from frankenpaxos_tpu.harness.proc import PopenProc, Proc, SshProc
from frankenpaxos_tpu.harness.workload import (
    BernoulliSingleKeyWorkload,
    ReadWriteWorkload,
    StringWorkload,
    UniformSingleKeyWorkload,
    workload_from_dict,
)

__all__ = [
    "BenchmarkDirectory",
    "BernoulliSingleKeyWorkload",
    "Cluster",
    "PopenProc",
    "Proc",
    "ReadWriteWorkload",
    "Reaped",
    "SshProc",
    "StringWorkload",
    "Suite",
    "SuiteDirectory",
    "UniformSingleKeyWorkload",
    "workload_from_dict",
]
