"""Latency/throughput sweep driven by the Suite runner: deploy a protocol
at increasing client counts, record each point's recorder CSV, and leave
a suite directory with ``results.csv`` + per-point plots — the analog of
the reference's latency-throughput benchmark suites whose committed
result CSVs back its paper figures (``benchmarks/eurosys/``,
``benchmarks/nsdi/fig1_lt_*``).

    python -m frankenpaxos_tpu.harness.lt_sweep --protocol epaxos \\
        --clients 1,2,4 --duration 3 --root /tmp/sweeps

Afterwards: ``python -m frankenpaxos_tpu.harness.analyze <suite_dir>``
prints the summary table.
"""

from __future__ import annotations

import argparse
import dataclasses
import sys

from frankenpaxos_tpu.harness.analysis import analyze_benchmark_dir
from frankenpaxos_tpu.harness.benchmark import Suite
from frankenpaxos_tpu.harness.smoke import deploy_smoke


@dataclasses.dataclass(frozen=True)
class LtInput:
    protocol: str
    num_clients: int
    duration: float


class LtSweepSuite(Suite):
    def __init__(self, protocol: str, client_counts, duration: float):
        self.protocol = protocol
        self.client_counts = client_counts
        self.duration = duration

    def args(self):
        return {
            "protocol": self.protocol,
            "clients": list(self.client_counts),
            "duration": self.duration,
        }

    def inputs(self):
        return [
            LtInput(self.protocol, n, self.duration)
            for n in self.client_counts
        ]

    def run_benchmark(self, bench, args, input: LtInput):
        deploy_smoke(
            input.protocol,
            bench,
            duration=input.duration,
            num_pseudonyms=input.num_clients,
        )
        summary = analyze_benchmark_dir(bench.path)
        summary.pop("plot", None)
        return summary


def main() -> None:
    parser = argparse.ArgumentParser(prog="frankenpaxos_tpu.harness.lt_sweep")
    parser.add_argument("--protocol", required=True)
    parser.add_argument("--clients", default="1,2,4",
                        help="comma-separated closed-loop client counts")
    parser.add_argument("--duration", type=float, default=3.0)
    parser.add_argument("--root", default=".",
                        help="directory to create the suite dir in")
    args = parser.parse_args()

    counts = [int(x) for x in args.clients.split(",") if x]
    suite = LtSweepSuite(args.protocol, counts, args.duration)
    suite_dir = suite.run_suite(args.root, f"lt_{args.protocol}")
    print(f"suite directory: {suite_dir.path}")


if __name__ == "__main__":
    sys.exit(main())
