"""The Suite runner (the analog of ``benchmarks/benchmark.py``):

  * :class:`SuiteDirectory` — a timestamped directory per suite run;
  * :class:`BenchmarkDirectory` — one numbered subdirectory per input,
    holding ``input.json``, per-process cmd/stdout/stderr/returncode
    captures, and arbitrary benchmark files;
  * ``results.csv`` — appended incrementally, one flattened row per
    benchmark, so partial suites still leave usable data;
  * :class:`Reaped` — a context manager guaranteeing child processes are
    killed even when a benchmark raises (benchmark.py:49-67);
  * :class:`Suite` — subclass with ``inputs()``/``run_benchmark()`` and
    call ``run_suite()``.

Latency/throughput summarization of client recorder CSVs mirrors
benchmark.py:310-455: percentiles of request latency and a windowed
throughput series.
"""

from __future__ import annotations

import csv
import dataclasses
import datetime
import json
import os
import statistics
from typing import Any, Dict, Generic, List, Optional, Sequence, TypeVar

from frankenpaxos_tpu.harness.proc import PopenProc, Proc

Input = TypeVar("Input")
Output = TypeVar("Output")


def flatten(value: Any, prefix: str = "") -> Dict[str, Any]:
    """Flatten dataclasses/dicts into dotted csv columns
    (benchmark.py:267-279)."""
    out: Dict[str, Any] = {}
    if dataclasses.is_dataclass(value) and not isinstance(value, type):
        value = dataclasses.asdict(value)
    if isinstance(value, dict):
        for k, v in value.items():
            key = f"{prefix}.{k}" if prefix else str(k)
            out.update(flatten(v, key))
        return out
    out[prefix or "value"] = value
    return out


class Reaped:
    """Kill every registered proc on exit, exception or not."""

    def __init__(self) -> None:
        self.procs: List[Proc] = []

    def register(self, proc: Proc) -> Proc:
        self.procs.append(proc)
        return proc

    def __enter__(self) -> "Reaped":
        return self

    def __exit__(self, *exc) -> None:
        for proc in self.procs:
            try:
                proc.kill()
            except Exception:
                pass


class BenchmarkDirectory:
    def __init__(self, path: str):
        self.path = path
        os.makedirs(path, exist_ok=True)
        self.reaped = Reaped()
        self._proc_count = 0

    def abspath(self, name: str) -> str:
        return os.path.join(self.path, name)

    def write_string(self, name: str, contents: str) -> str:
        path = self.abspath(name)
        with open(path, "w") as f:
            f.write(contents)
        return path

    def write_json(self, name: str, value: Any) -> str:
        if dataclasses.is_dataclass(value) and not isinstance(value, type):
            value = dataclasses.asdict(value)
        return self.write_string(name, json.dumps(value, indent=2, default=str))

    def popen(
        self, label: str, args: Sequence[str], env: Optional[Dict[str, str]] = None
    ) -> PopenProc:
        """Launch a labeled local process capturing cmd/stdout/stderr
        (benchmark.py:183-206)."""
        self._proc_count += 1
        label = f"{self._proc_count:03}_{label}"
        self.write_string(f"{label}_cmd.txt", " ".join(args))
        proc = PopenProc(
            args,
            stdout=self.abspath(f"{label}_stdout.txt"),
            stderr=self.abspath(f"{label}_stderr.txt"),
            env=env,
        )
        self.reaped.register(proc)
        return proc

    def __enter__(self) -> "BenchmarkDirectory":
        self.reaped.__enter__()
        return self

    def __exit__(self, *exc) -> None:
        self.reaped.__exit__(*exc)


class SuiteDirectory:
    def __init__(self, root: str, name: str):
        ts = datetime.datetime.now().strftime("%Y-%m-%d_%H-%M-%S")
        self.path = os.path.join(root, f"{ts}_{name}")
        os.makedirs(self.path, exist_ok=True)
        self._benchmark_count = 0

    def write_json(self, name: str, value: Any) -> str:
        path = os.path.join(self.path, name)
        with open(path, "w") as f:
            json.dump(value, f, indent=2, default=str)
        return path

    def benchmark_directory(self) -> BenchmarkDirectory:
        self._benchmark_count += 1
        return BenchmarkDirectory(
            os.path.join(self.path, f"{self._benchmark_count:03}")
        )


class Suite(Generic[Input, Output]):
    def args(self) -> Dict[str, Any]:
        return {}

    def inputs(self) -> List[Input]:
        raise NotImplementedError

    def summary(self, input: Input, output: Output) -> str:
        return str(output)

    def run_benchmark(
        self, bench: BenchmarkDirectory, args: Dict[str, Any], input: Input
    ) -> Output:
        raise NotImplementedError

    def run_suite(self, root: str, name: str) -> SuiteDirectory:
        suite_dir = SuiteDirectory(root, name)
        suite_dir.write_json("args.json", self.args())
        results_path = os.path.join(suite_dir.path, "results.csv")
        # The whole file is rewritten after every benchmark: partial suites
        # still leave usable data, and rows with new columns (e.g. an
        # optional 'error' field on a failed run) widen the schema instead
        # of raising.
        rows: List[Dict[str, Any]] = []
        fieldnames: List[str] = []

        def write_results() -> None:
            with open(results_path, "w", newline="") as f:
                writer = csv.DictWriter(f, fieldnames=fieldnames, restval="")
                writer.writeheader()
                writer.writerows(rows)

        for input in self.inputs():
            with suite_dir.benchmark_directory() as bench:
                bench.write_json("input.json", input)
                output = self.run_benchmark(bench, self.args(), input)
                bench.write_json("output.json", output)
                row = {
                    **flatten(input, "input"),
                    **flatten(output, "output"),
                }
                rows.append(row)
                for key in row:
                    if key not in fieldnames:
                        fieldnames.append(key)
                write_results()
                print(f"[{bench.path}] {self.summary(input, output)}")
        return suite_dir


# -- Recorder-CSV summarization (benchmark.py:310-455) -----------------------


@dataclasses.dataclass(frozen=True)
class LatencySummary:
    count: int
    mean_ms: float
    median_ms: float
    p90_ms: float
    p99_ms: float
    throughput_per_s: float


def summarize_latency_throughput(
    rows: List[Dict[str, float]]
) -> Optional[LatencySummary]:
    """rows: dicts with 'start' (seconds), 'latency_nanos'."""
    if not rows:
        return None
    lat_ms = sorted(r["latency_nanos"] / 1e6 for r in rows)
    starts = [r["start"] for r in rows]
    duration = max(starts) - min(starts)

    def pct(p: float) -> float:
        # Nearest-rank percentile: ceil(p*n)-1, so p99 of 100 samples is
        # rank 99 (index 98), not the maximum.
        rank = max(1, -(-p * len(lat_ms) // 1))
        return lat_ms[min(len(lat_ms) - 1, int(rank) - 1)]

    return LatencySummary(
        count=len(rows),
        mean_ms=statistics.fmean(lat_ms),
        median_ms=pct(0.5),
        p90_ms=pct(0.9),
        p99_ms=pct(0.99),
        throughput_per_s=len(rows) / duration if duration > 0 else float("nan"),
    )
