"""Data-structure microbenchmarks (the analog of ``jvm/src/bench/scala``:
DependencyGraphBench, IntPrefixSetBench, BufferMapBench,
CompactConflictIndexBench — scalameter replaced by a simple
timeit-style harness):

    python -m frankenpaxos_tpu.harness.microbench            # all
    python -m frankenpaxos_tpu.harness.microbench depgraph

Each benchmark prints ``name,case,ops,seconds,ops_per_sec`` rows; these
guard the perf of the Python hot paths the same way the reference's
scalameter suite guards its JVM ones.
"""

from __future__ import annotations

import random
import sys
import time
from typing import Callable, Dict, List, Tuple


def _timed(fn: Callable[[], int]) -> Tuple[int, float]:
    start = time.perf_counter()
    ops = fn()
    return ops, time.perf_counter() - start


def _report(name: str, case: str, ops: int, seconds: float) -> dict:
    row = {
        "name": name,
        "case": case,
        "ops": ops,
        "seconds": round(seconds, 4),
        "ops_per_sec": round(ops / seconds) if seconds > 0 else 0,
    }
    print(
        f"{row['name']},{row['case']},{row['ops']},{row['seconds']},"
        f"{row['ops_per_sec']}"
    )
    return row


def bench_depgraph(num_commands: int = 5_000, num_leaders: int = 5) -> List[dict]:
    """Commit+execute through every dependency-graph variant on the same
    EPaxos-shaped workload (DependencyGraphBench.scala)."""
    from frankenpaxos_tpu.depgraph import (
        IncrementalTarjanDependencyGraph,
        NaiveDependencyGraph,
        TarjanDependencyGraph,
        ZigzagTarjanDependencyGraph,
    )

    rng = random.Random(0)
    # A conflict-heavy stream: each command depends on the previous few
    # commands of every leader column (prefix-shaped).
    commands = []
    next_id = [0] * num_leaders
    frontier = [0] * num_leaders
    for _ in range(num_commands):
        leader = rng.randrange(num_leaders)
        key = (leader, next_id[leader])
        next_id[leader] += 1
        deps = {
            (col, i)
            for col in range(num_leaders)
            for i in range(max(0, frontier[col] - 2), frontier[col])
        }
        deps.discard(key)
        frontier[leader] = next_id[leader]
        commands.append((key, deps))

    rows = []
    variants: Dict[str, Callable[[], object]] = {
        "Tarjan": TarjanDependencyGraph,
        "IncrementalTarjan": IncrementalTarjanDependencyGraph,
        "Naive": NaiveDependencyGraph,
        "Zigzag": lambda: ZigzagTarjanDependencyGraph(
            num_leaders, garbage_collect_every_n_commands=100
        ),
    }
    for case, make in variants.items():
        graph = make()

        def run() -> int:
            executed = 0
            for seq, (key, deps) in enumerate(commands):
                graph.commit(key, seq, deps)
                if seq % 10 == 9:
                    keys, _ = graph.execute()
                    executed += len(keys)
            for _ in range(num_commands):
                keys, _ = graph.execute()
                executed += len(keys)
                if not keys:
                    break
            # Variants must do the SAME work for ops/sec to compare.
            assert executed == num_commands, (case, executed)
            return executed

        ops, seconds = _timed(run)
        rows.append(_report("depgraph", case, ops, seconds))
    return rows


def bench_int_prefix_set(num_ops: int = 200_000) -> List[dict]:
    """add/contains on the watermark-compressed set
    (IntPrefixSetBench.scala)."""
    from frankenpaxos_tpu.compact import IntPrefixSet

    rows = []

    def sequential() -> int:
        s = IntPrefixSet()
        for i in range(num_ops):
            s.add(i)
        return num_ops

    def scattered() -> int:
        rng = random.Random(1)
        s = IntPrefixSet()
        for _ in range(num_ops):
            s.add(rng.randrange(num_ops * 2))
        return num_ops

    def contains() -> int:
        s = IntPrefixSet()
        for i in range(1000):
            s.add(i)
        hits = 0
        for i in range(num_ops):
            hits += s.contains(i % 2000)
        return num_ops

    for case, fn in [
        ("add_sequential", sequential),
        ("add_scattered", scattered),
        ("contains", contains),
    ]:
        ops, seconds = _timed(fn)
        rows.append(_report("int_prefix_set", case, ops, seconds))
    return rows


def bench_buffer_map(num_ops: int = 200_000) -> List[dict]:
    """put/get/garbage_collect on the watermarked log (BufferMapBench)."""
    from frankenpaxos_tpu.util import BufferMap

    rows = []

    def put_get() -> int:
        m = BufferMap(grow_size=1024)
        for i in range(num_ops):
            m.put(i, i)
            m.get(i)
        return num_ops

    def put_gc() -> int:
        m = BufferMap(grow_size=1024)
        for i in range(num_ops):
            m.put(i, i)
            if i % 1000 == 999:
                m.garbage_collect(i - 500)
        return num_ops

    for case, fn in [("put_get", put_get), ("put_gc", put_gc)]:
        ops, seconds = _timed(fn)
        rows.append(_report("buffer_map", case, ops, seconds))
    return rows


def bench_conflict_index(num_ops: int = 20_000) -> List[dict]:
    """KeyValueStore conflict-index puts + conflict queries
    (CompactConflictIndexBench)."""
    from frankenpaxos_tpu.statemachine import KeyValueStore, kv_set

    rows = []

    def run() -> int:
        index = KeyValueStore().conflict_index()
        rng = random.Random(2)
        for i in range(num_ops):
            cmd = kv_set((f"k{rng.randrange(64)}", "v"))
            index.put(("c", i), cmd)
            if i % 4 == 3:
                index.get_conflicts(cmd)
        return num_ops

    ops, seconds = _timed(run)
    rows.append(_report("conflict_index", "kv_put_conflicts", ops, seconds))
    return rows


BENCHES = {
    "depgraph": bench_depgraph,
    "int_prefix_set": bench_int_prefix_set,
    "buffer_map": bench_buffer_map,
    "conflict_index": bench_conflict_index,
}


def main() -> None:
    names = sys.argv[1:] or list(BENCHES)
    unknown = [n for n in names if n not in BENCHES]
    if unknown:
        print(
            f"unknown bench(es) {', '.join(unknown)}; "
            f"choose from: {', '.join(BENCHES)}",
            file=sys.stderr,
        )
        sys.exit(2)
    print("name,case,ops,seconds,ops_per_sec")
    for name in names:
        BENCHES[name]()


if __name__ == "__main__":
    main()
