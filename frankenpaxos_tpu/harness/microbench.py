"""Data-structure microbenchmarks (the analog of ``jvm/src/bench/scala``:
DependencyGraphBench, IntPrefixSetBench, BufferMapBench,
CompactConflictIndexBench — scalameter replaced by a simple
timeit-style harness):

    python -m frankenpaxos_tpu.harness.microbench            # all
    python -m frankenpaxos_tpu.harness.microbench depgraph

Each benchmark prints ``name,case,ops,seconds,ops_per_sec`` rows; these
guard the perf of the Python hot paths the same way the reference's
scalameter suite guards its JVM ones.
"""

from __future__ import annotations

import random
import sys
import time
from typing import Callable, Dict, List, Tuple


def _timed(fn: Callable[[], int]) -> Tuple[int, float]:
    start = time.perf_counter()
    ops = fn()
    return ops, time.perf_counter() - start


def _report(name: str, case: str, ops: int, seconds: float) -> dict:
    row = {
        "name": name,
        "case": case,
        "ops": ops,
        "seconds": round(seconds, 4),
        "ops_per_sec": round(ops / seconds) if seconds > 0 else 0,
    }
    print(
        f"{row['name']},{row['case']},{row['ops']},{row['seconds']},"
        f"{row['ops_per_sec']}"
    )
    return row


def bench_depgraph(num_commands: int = 5_000, num_leaders: int = 5) -> List[dict]:
    """Commit+execute through every dependency-graph variant on the same
    EPaxos-shaped workload (DependencyGraphBench.scala)."""
    from frankenpaxos_tpu.depgraph import (
        IncrementalTarjanDependencyGraph,
        NaiveDependencyGraph,
        TarjanDependencyGraph,
        ZigzagTarjanDependencyGraph,
    )

    rng = random.Random(0)
    # A conflict-heavy stream: each command depends on the previous few
    # commands of every leader column (prefix-shaped).
    commands = []
    next_id = [0] * num_leaders
    frontier = [0] * num_leaders
    for _ in range(num_commands):
        leader = rng.randrange(num_leaders)
        key = (leader, next_id[leader])
        next_id[leader] += 1
        deps = {
            (col, i)
            for col in range(num_leaders)
            for i in range(max(0, frontier[col] - 2), frontier[col])
        }
        deps.discard(key)
        frontier[leader] = next_id[leader]
        commands.append((key, deps))

    rows = []
    variants: Dict[str, Callable[[], object]] = {
        "Tarjan": TarjanDependencyGraph,
        "IncrementalTarjan": IncrementalTarjanDependencyGraph,
        "Naive": NaiveDependencyGraph,
        "Zigzag": lambda: ZigzagTarjanDependencyGraph(
            num_leaders, garbage_collect_every_n_commands=100
        ),
    }
    for case, make in variants.items():
        graph = make()

        def run() -> int:
            executed = 0
            for seq, (key, deps) in enumerate(commands):
                graph.commit(key, seq, deps)
                if seq % 10 == 9:
                    keys, _ = graph.execute()
                    executed += len(keys)
            for _ in range(num_commands):
                keys, _ = graph.execute()
                executed += len(keys)
                if not keys:
                    break
            # Variants must do the SAME work for ops/sec to compare.
            assert executed == num_commands, (case, executed)
            return executed

        ops, seconds = _timed(run)
        rows.append(_report("depgraph", case, ops, seconds))
    return rows


def bench_int_prefix_set(num_ops: int = 200_000) -> List[dict]:
    """add/contains on the watermark-compressed set
    (IntPrefixSetBench.scala)."""
    from frankenpaxos_tpu.compact import IntPrefixSet

    rows = []

    def sequential() -> int:
        s = IntPrefixSet()
        for i in range(num_ops):
            s.add(i)
        return num_ops

    def scattered() -> int:
        rng = random.Random(1)
        s = IntPrefixSet()
        for _ in range(num_ops):
            s.add(rng.randrange(num_ops * 2))
        return num_ops

    def contains() -> int:
        s = IntPrefixSet()
        for i in range(1000):
            s.add(i)
        hits = 0
        for i in range(num_ops):
            hits += s.contains(i % 2000)
        return num_ops

    for case, fn in [
        ("add_sequential", sequential),
        ("add_scattered", scattered),
        ("contains", contains),
    ]:
        ops, seconds = _timed(fn)
        rows.append(_report("int_prefix_set", case, ops, seconds))
    return rows


def bench_buffer_map(num_ops: int = 200_000) -> List[dict]:
    """put/get/garbage_collect on the watermarked log (BufferMapBench)."""
    from frankenpaxos_tpu.util import BufferMap

    rows = []

    def put_get() -> int:
        m = BufferMap(grow_size=1024)
        for i in range(num_ops):
            m.put(i, i)
            m.get(i)
        return num_ops

    def put_gc() -> int:
        m = BufferMap(grow_size=1024)
        for i in range(num_ops):
            m.put(i, i)
            if i % 1000 == 999:
                m.garbage_collect(i - 500)
        return num_ops

    for case, fn in [("put_get", put_get), ("put_gc", put_gc)]:
        ops, seconds = _timed(fn)
        rows.append(_report("buffer_map", case, ops, seconds))
    return rows


def bench_conflict_index(num_ops: int = 20_000) -> List[dict]:
    """KeyValueStore conflict-index puts + conflict queries
    (CompactConflictIndexBench)."""
    from frankenpaxos_tpu.statemachine import KeyValueStore, kv_set

    rows = []

    def run() -> int:
        index = KeyValueStore().conflict_index()
        rng = random.Random(2)
        for i in range(num_ops):
            cmd = kv_set((f"k{rng.randrange(64)}", "v"))
            index.put(("c", i), cmd)
            if i % 4 == 3:
                index.get_conflicts(cmd)
        return num_ops

    ops, seconds = _timed(run)
    rows.append(_report("conflict_index", "kv_put_conflicts", ops, seconds))
    return rows


def compiled_memory_stats(runner, cfg, state, ticks: int) -> dict:
    """XLA's compiled memory accounting for one ``run_ticks``-shaped
    jit (``runner(cfg, state, t0, ticks, key)``): argument/output/temp/
    alias bytes plus ``peak_bytes`` = arg + out + temp - alias (what
    donation removes). An executable deserialized from the persistent
    compilation cache reports NO aliasing, which would zero the
    donation accounting, so the disk cache is detached for this compile
    (dir=None + reset_cache; flipping jax_enable_compilation_cache
    alone does not stop reads once the cache is initialized) and
    restored afterwards. Shared by the hbm bench below and
    scripts/tpu_layout_bench.py."""
    import jax
    import jax.numpy as jnp

    from jax.experimental.compilation_cache import compilation_cache as cc

    prev_dir = jax.config.jax_compilation_cache_dir
    try:
        jax.config.update("jax_compilation_cache_dir", None)
        cc.reset_cache()
        ma = runner.lower(
            cfg, state, jnp.zeros((), jnp.int32), ticks,
            jax.random.PRNGKey(0),
        ).compile().memory_analysis()
    finally:
        jax.config.update("jax_compilation_cache_dir", prev_dir)
        cc.reset_cache()
    arg_b = int(ma.argument_size_in_bytes)
    out_b = int(ma.output_size_in_bytes)
    tmp_b = int(ma.temp_size_in_bytes)
    alias_b = int(ma.alias_size_in_bytes)
    return {
        "argument_bytes": arg_b,
        "output_bytes": out_b,
        "temp_bytes": tmp_b,
        "alias_bytes": alias_b,
        "peak_bytes": arg_b + out_b + tmp_b - alias_b,
    }


def bench_hbm(
    num_groups: int = 3334,
    window: int = 64,
    slots_per_tick: int = 8,
    ticks: int = 200,
    cases: "tuple | None" = None,
) -> List[dict]:
    """The HBM-bandwidth pass, measured: the flagship 10k-acceptor
    batched-MultiPaxos config under four (dtype x donation) variants —

      * ``int32_nodonate``  — the pre-pass baseline: widened (int32)
        state, no buffer donation (a fresh non-donating jit of the same
        tick program);
      * ``int32_donate``    — donation alone;
      * ``narrow_nodonate`` — the dtype policy alone;
      * ``narrow_donate``   — the shipped configuration.

    Each row reports ticks/sec (ops = ticks) plus a ``HBM_JSON`` line
    with the state footprint and XLA's own compiled memory analysis
    (argument/output/temp/alias bytes): ``peak_bytes`` = arguments +
    outputs + temps - aliased, which is exactly what donation removes —
    the measured-peak-HBM number of the acceptance criteria, reported by
    the compiler rather than asserted. ``bytes_per_tick`` is the
    elementwise-sweep traffic bound 2 x state_bytes (each tick reads and
    rewrites the whole state).
    """
    import functools
    import json

    import jax
    import jax.numpy as jnp

    from frankenpaxos_tpu.tpu import multipaxos_batched as mb
    from frankenpaxos_tpu.tpu.common import state_nbytes, widen_state

    cfg = mb.BatchedMultiPaxosConfig(
        f=1,
        num_groups=num_groups,
        window=window,
        slots_per_tick=slots_per_tick,
        lat_min=1,
        lat_max=3,
        drop_rate=0.0,
        retry_timeout=16,
        thrifty=True,
    )
    nodonate = jax.jit(mb.run_ticks.__wrapped__, static_argnums=(0, 3))
    variants = [
        ("int32_nodonate", True, nodonate),
        ("int32_donate", True, mb.run_ticks),
        ("narrow_nodonate", False, nodonate),
        ("narrow_donate", False, mb.run_ticks),
    ]
    if cases is not None:  # e.g. the smoke test's before/after pair
        variants = [v for v in variants if v[0] in cases]
    key = jax.random.PRNGKey(0)
    t0 = jnp.zeros((), jnp.int32)
    rows = []
    for case, widen, runner in variants:
        make = (
            (lambda: widen_state(mb.init_state(cfg)))
            if widen
            else (lambda: mb.init_state(cfg))
        )
        state = make()
        sbytes = state_nbytes(state)
        mem = compiled_memory_stats(runner, cfg, state, ticks)
        # Warm up (compile + one segment), then time one segment.
        state, t = runner(cfg, state, t0, ticks, key)
        jax.block_until_ready(state)
        state = make()

        def run() -> int:
            out, _ = runner(cfg, state, t0, ticks, key)
            jax.block_until_ready(out)
            return ticks

        ops, seconds = _timed(run)
        row = _report("hbm", case, ops, seconds)
        row.update(
            {
                "state_bytes": sbytes,
                "bytes_per_tick": 2 * sbytes,
                **mem,
                "num_acceptors": cfg.num_acceptors,
                "device": str(jax.devices()[0]),
            }
        )
        print("HBM_JSON " + json.dumps(row))
        rows.append(row)
    return rows


def _interleaved_best(sims: dict, ticks: int, rounds: int) -> dict:
    """The shared overhead-measurement discipline: warm/compile every
    variant with one segment, then INTERLEAVE ``rounds`` timed segments
    across the variants and keep each variant's best. A small-percentage
    budget question cannot survive sequential per-variant timing on a
    shared box (observed ±30% between back-to-back identical segments);
    interleaving makes all variants sample the same noise environment.
    Returns ``{case: best_seconds}`` in ``sims`` insertion order."""
    import time

    best = {}
    for case, sim in sims.items():
        sim.run(ticks)  # compile + warm
        sim.block_until_ready()
        best[case] = float("inf")
    for _ in range(rounds):
        for case, sim in sims.items():
            start = time.perf_counter()
            sim.run(ticks)
            sim.block_until_ready()
            best[case] = min(best[case], time.perf_counter() - start)
    return best


def measure_telemetry_overhead(cfg, ticks: int, rounds: int = 3) -> dict:
    """Head-to-head telemetry-ring overhead on one config: ``ring_off``
    (zero-width ring — record() no-ops at trace time, XLA removes every
    telemetry computation) vs ``ring_on`` (the shipped default ring),
    timed via :func:`_interleaved_best`. Shared by the ``telemetry``
    device bench below and ``bench.py --telemetry``.

    Returns ``{"seconds": {case: best}, "rates": {case: ticks/sec},
    "ratio": on/off, "sim_on": <the ring_on transport>}`` (``sim_on``
    has run ``(rounds + 1) * ticks`` ticks — its ring feeds the
    per-phase breakdown)."""
    from frankenpaxos_tpu.tpu.transport import TpuSimTransport

    sims = {
        case: TpuSimTransport(cfg, seed=0, telemetry_window=tel_window)
        for case, tel_window in (("ring_off", 0), ("ring_on", None))
    }
    best = _interleaved_best(sims, ticks, rounds)
    rates = {case: ticks / s for case, s in best.items()}
    return {
        "seconds": best,
        "rates": rates,
        "ratio": rates["ring_on"] / rates["ring_off"],
        "sim_on": sims["ring_on"],
        "total_ticks_on": (rounds + 1) * ticks,
    }


def bench_telemetry(
    num_groups: int = 3334,
    window: int = 64,
    slots_per_tick: int = 8,
    ticks: int = 200,
) -> List[dict]:
    """The device-telemetry pass, measured on the flagship 10k-acceptor
    config: ``ring_off`` (zero-width ring — record() no-ops at trace
    time, XLA removes every telemetry computation) vs ``ring_on`` (the
    shipped default ring). Each row reports ticks/sec; the on-row's
    ``TELEM_JSON`` line adds the per-phase throughput breakdown read
    FROM the ring itself (commits/executes/proposals/phase-plane
    messages per second) alongside the overhead ratio — the per-phase
    accounting the hbm block can't see."""
    import json

    from frankenpaxos_tpu.tpu import BatchedMultiPaxosConfig
    from frankenpaxos_tpu.tpu.telemetry import COUNTER_FIELDS

    cfg = BatchedMultiPaxosConfig(
        f=1,
        num_groups=num_groups,
        window=window,
        slots_per_tick=slots_per_tick,
        lat_min=1,
        lat_max=3,
        drop_rate=0.0,
        retry_timeout=16,
        thrifty=True,
    )
    measured = measure_telemetry_overhead(cfg, ticks)
    rows = []
    for case in ("ring_off", "ring_on"):
        seconds = measured["seconds"][case]
        row = _report("telemetry", case, ticks, seconds)
        if case == "ring_on":
            summary = measured["sim_on"].telemetry_summary()
            # events/sec = (events/tick over the whole run) x (ticks/sec
            # of the best measured segment).
            ticks_run = measured["total_ticks_on"]
            per_phase = {
                f"{name}_per_sec": round(
                    summary[f"{name}_total"] / ticks_run * (ticks / seconds),
                    1,
                )
                for name in COUNTER_FIELDS
                if name != "queue_depth"
            }
            row.update(
                {
                    "overhead_ratio": round(measured["ratio"], 4),
                    "num_acceptors": cfg.num_acceptors,
                    **per_phase,
                }
            )
            print("TELEM_JSON " + json.dumps(row))
        rows.append(row)
    return rows


DEGRADED_PLAN_KW = dict(
    drop_rate=0.05, dup_rate=0.05, jitter=1, crash_rate=0.005,
    revive_rate=0.1,
)


def measure_fault_overhead(cfg, ticks: int, rounds: int = 3) -> dict:
    """Degraded-mode benchmark: the SAME config run healthy
    (``FaultPlan.none()``) vs under a standard degraded plan
    (``DEGRADED_PLAN_KW``: 5% extra loss, 5% duplication, 1-tick jitter,
    0.5%/10% crash/revive driving real device-side elections).

    Timed via :func:`_interleaved_best`. Returns
    ``{"seconds", "rates" (ticks/sec), "ratio" (faulty/healthy),
    "committed" per case, "sim_faulty"}`` — the faulty transport's
    telemetry ring shows the drops/retries/leader_changes the plan
    injected. Shared by the ``faults`` device bench and
    ``bench.py --faults``."""
    import dataclasses as _dc

    from frankenpaxos_tpu.tpu.faults import FaultPlan
    from frankenpaxos_tpu.tpu.transport import TpuSimTransport

    plan = FaultPlan(**DEGRADED_PLAN_KW)
    sims = {
        case: TpuSimTransport(_dc.replace(cfg, faults=faults), seed=0)
        for case, faults in (
            ("healthy", FaultPlan.none()), ("faulty", plan),
        )
    }
    best = _interleaved_best(sims, ticks, rounds)
    rates = {case: ticks / s for case, s in best.items()}
    return {
        "plan": plan.to_dict(),
        "seconds": best,
        "rates": rates,
        "ratio": rates["faulty"] / rates["healthy"],
        "committed": {case: sims[case].committed() for case in sims},
        "total_ticks": (rounds + 1) * ticks,
        "sim_faulty": sims["faulty"],
    }


def bench_faults(
    num_groups: int = 3334,
    window: int = 64,
    slots_per_tick: int = 8,
    ticks: int = 200,
) -> List[dict]:
    """The degraded-mode device bench on the flagship 10k-acceptor
    config: healthy vs faulty ticks/sec + committed/sec, with the faulty
    run's telemetry totals (drops/retries/leader_changes actually
    injected) on a ``FAULTS_JSON`` line."""
    import json

    from frankenpaxos_tpu.tpu import BatchedMultiPaxosConfig
    from frankenpaxos_tpu.tpu.telemetry import COL

    cfg = BatchedMultiPaxosConfig(
        f=1,
        num_groups=num_groups,
        window=window,
        slots_per_tick=slots_per_tick,
        lat_min=1,
        lat_max=3,
        retry_timeout=16,
        thrifty=True,
    )
    measured = measure_fault_overhead(cfg, ticks)
    rows = []
    for case in ("healthy", "faulty"):
        seconds = measured["seconds"][case]
        row = _report("faults", case, ticks, seconds)
        row["committed"] = measured["committed"][case]
        if case == "faulty":
            tel = measured["sim_faulty"].telemetry()
            row.update(
                {
                    "slowdown_ratio": round(measured["ratio"], 4),
                    "plan": measured["plan"],
                    "drops_total": int(tel.totals[COL["drops"]]),
                    "retries_total": int(tel.totals[COL["retries"]]),
                    "leader_changes_total": int(
                        tel.totals[COL["leader_changes"]]
                    ),
                    "num_acceptors": cfg.num_acceptors,
                }
            )
            print("FAULTS_JSON " + json.dumps(row))
        rows.append(row)
    return rows


BENCHES = {
    "depgraph": bench_depgraph,
    "int_prefix_set": bench_int_prefix_set,
    "buffer_map": bench_buffer_map,
    "conflict_index": bench_conflict_index,
}

# Device benchmarks live in their own registry: they need jax + minutes
# of wall clock at the flagship model size, so the pinned-baseline
# regression test (tests/test_microbench_regression.py) must not sweep
# them up with the Python hot-path benches.
DEVICE_BENCHES = {
    "hbm": bench_hbm,
    "telemetry": bench_telemetry,
    "faults": bench_faults,
}


def main() -> None:
    all_benches = {**BENCHES, **DEVICE_BENCHES}
    names = sys.argv[1:] or list(BENCHES)
    unknown = [n for n in names if n not in all_benches]
    if unknown:
        print(
            f"unknown bench(es) {', '.join(unknown)}; "
            f"choose from: {', '.join(all_benches)}",
            file=sys.stderr,
        )
        sys.exit(2)
    print("name,case,ops,seconds,ops_per_sec")
    for name in names:
        all_benches[name]()


if __name__ == "__main__":
    main()
