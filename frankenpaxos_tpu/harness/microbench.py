"""Data-structure microbenchmarks (the analog of ``jvm/src/bench/scala``:
DependencyGraphBench, IntPrefixSetBench, BufferMapBench,
CompactConflictIndexBench — scalameter replaced by a simple
timeit-style harness):

    python -m frankenpaxos_tpu.harness.microbench            # all
    python -m frankenpaxos_tpu.harness.microbench depgraph

Each benchmark prints ``name,case,ops,seconds,ops_per_sec`` rows; these
guard the perf of the Python hot paths the same way the reference's
scalameter suite guards its JVM ones.
"""

from __future__ import annotations

import random
import sys
import time
from typing import Callable, Dict, List, Tuple


def _timed(fn: Callable[[], int]) -> Tuple[int, float]:
    start = time.perf_counter()
    ops = fn()
    return ops, time.perf_counter() - start


def _report(name: str, case: str, ops: int, seconds: float) -> dict:
    row = {
        "name": name,
        "case": case,
        "ops": ops,
        "seconds": round(seconds, 4),
        "ops_per_sec": round(ops / seconds) if seconds > 0 else 0,
    }
    print(
        f"{row['name']},{row['case']},{row['ops']},{row['seconds']},"
        f"{row['ops_per_sec']}"
    )
    return row


def bench_depgraph(
    num_commands: int = 5_000,
    num_leaders: int = 5,
    batch: int = 208,
    window: int = 64,
    rounds: int = 3,
    closure_iters: int = 25,
) -> List[dict]:
    """Commit+execute through every dependency-graph variant on the same
    EPaxos-shaped workload (DependencyGraphBench.scala), then race the
    device-side ``depgraph_execute`` plane against its host twin:

    - ``bitmask_closure``: the jitted pure-jnp reference (log-depth
      matmul doubling over the whole [batch, window] brick at once),
    - ``pointer_walk``: ``ops.depgraph.oracle_execute`` — the
      sequential iterative-Tarjan pointer walk, one vertex at a time,
      one graph at a time (TarjanDependencyGraph.scala's control flow).

    Both sides consume the SAME random windowed graphs and their
    outputs are asserted bit-identical before any clock starts; the
    timed segments interleave across the two sides with
    best-of-``rounds`` kept, so neither wins by machine drift. Ops are
    graphs executed, so the two rows' ops/sec ratio IS the
    batched-closure speedup (bench.py --depgraph records it)."""
    from frankenpaxos_tpu.depgraph import (
        IncrementalTarjanDependencyGraph,
        NaiveDependencyGraph,
        TarjanDependencyGraph,
        ZigzagTarjanDependencyGraph,
    )

    rng = random.Random(0)
    # A conflict-heavy stream: each command depends on the previous few
    # commands of every leader column (prefix-shaped).
    commands = []
    next_id = [0] * num_leaders
    frontier = [0] * num_leaders
    for _ in range(num_commands):
        leader = rng.randrange(num_leaders)
        key = (leader, next_id[leader])
        next_id[leader] += 1
        deps = {
            (col, i)
            for col in range(num_leaders)
            for i in range(max(0, frontier[col] - 2), frontier[col])
        }
        deps.discard(key)
        frontier[leader] = next_id[leader]
        commands.append((key, deps))

    rows = []
    variants: Dict[str, Callable[[], object]] = {
        "Tarjan": TarjanDependencyGraph,
        "IncrementalTarjan": IncrementalTarjanDependencyGraph,
        "Naive": NaiveDependencyGraph,
        "Zigzag": lambda: ZigzagTarjanDependencyGraph(
            num_leaders, garbage_collect_every_n_commands=100
        ),
    }
    for case, make in variants.items():
        graph = make()

        def run() -> int:
            executed = 0
            for seq, (key, deps) in enumerate(commands):
                graph.commit(key, seq, deps)
                if seq % 10 == 9:
                    keys, _ = graph.execute()
                    executed += len(keys)
            for _ in range(num_commands):
                keys, _ = graph.execute()
                executed += len(keys)
                if not keys:
                    break
            # Variants must do the SAME work for ops/sec to compare.
            assert executed == num_commands, (case, executed)
            return executed

        ops, seconds = _timed(run)
        rows.append(_report("depgraph", case, ops, seconds))

    # ---- Batched bitmask closure vs sequential pointer walk.
    import jax
    import jax.numpy as jnp
    import numpy as np

    from frankenpaxos_tpu.ops import depgraph as dg

    np_rng = np.random.RandomState(0)
    bits = np_rng.random_sample((batch, window, window)) < 0.06
    adj = np.asarray(dg.pack_mask(jnp.asarray(bits)))
    committed = np_rng.random_sample((batch, window)) < 0.5
    active = np_rng.random_sample((batch, window)) < 0.8

    ref = jax.jit(dg.reference_depgraph_execute)
    adj_j = jnp.asarray(adj)
    com_j = jnp.asarray(committed)
    act_j = jnp.asarray(active)
    got = jax.block_until_ready(ref(adj_j, com_j, act_j))  # compile
    got = tuple(np.asarray(x) for x in got)
    # Bit-identity gate: the throughput ratio below is meaningless
    # unless both sides compute EXACTLY the same answer.
    want = [
        dg.oracle_execute(adj[b], committed[b], active[b])
        for b in range(batch)
    ]
    for i, field in enumerate(("eligible", "order", "scc_root")):
        w = np.stack([np.asarray(x[i]) for x in want])
        assert np.array_equal(got[i], w.astype(got[i].dtype)), (
            f"bitmask closure != pointer walk on {field}"
        )

    best = {"bitmask_closure": None, "pointer_walk": None}
    for _ in range(rounds):

        def run_closure() -> int:
            out = None
            for _ in range(closure_iters):
                out = ref(adj_j, com_j, act_j)
            jax.block_until_ready(out)
            return closure_iters * batch

        def run_walk() -> int:
            for b in range(batch):
                dg.oracle_execute(adj[b], committed[b], active[b])
            return batch

        for case, run in (
            ("bitmask_closure", run_closure),
            ("pointer_walk", run_walk),
        ):
            ops, seconds = _timed(run)
            prev = best[case]
            if prev is None or seconds / ops < prev[1] / prev[0]:
                best[case] = (ops, seconds)
    for case in ("bitmask_closure", "pointer_walk"):
        ops, seconds = best[case]
        rows.append(_report("depgraph", case, ops, seconds))
    return rows


def bench_int_prefix_set(num_ops: int = 200_000) -> List[dict]:
    """add/contains on the watermark-compressed set
    (IntPrefixSetBench.scala)."""
    from frankenpaxos_tpu.compact import IntPrefixSet

    rows = []

    def sequential() -> int:
        s = IntPrefixSet()
        for i in range(num_ops):
            s.add(i)
        return num_ops

    def scattered() -> int:
        rng = random.Random(1)
        s = IntPrefixSet()
        for _ in range(num_ops):
            s.add(rng.randrange(num_ops * 2))
        return num_ops

    def contains() -> int:
        s = IntPrefixSet()
        for i in range(1000):
            s.add(i)
        hits = 0
        for i in range(num_ops):
            hits += s.contains(i % 2000)
        return num_ops

    for case, fn in [
        ("add_sequential", sequential),
        ("add_scattered", scattered),
        ("contains", contains),
    ]:
        ops, seconds = _timed(fn)
        rows.append(_report("int_prefix_set", case, ops, seconds))
    return rows


def bench_buffer_map(num_ops: int = 200_000) -> List[dict]:
    """put/get/garbage_collect on the watermarked log (BufferMapBench)."""
    from frankenpaxos_tpu.util import BufferMap

    rows = []

    def put_get() -> int:
        m = BufferMap(grow_size=1024)
        for i in range(num_ops):
            m.put(i, i)
            m.get(i)
        return num_ops

    def put_gc() -> int:
        m = BufferMap(grow_size=1024)
        for i in range(num_ops):
            m.put(i, i)
            if i % 1000 == 999:
                m.garbage_collect(i - 500)
        return num_ops

    for case, fn in [("put_get", put_get), ("put_gc", put_gc)]:
        ops, seconds = _timed(fn)
        rows.append(_report("buffer_map", case, ops, seconds))
    return rows


def bench_conflict_index(num_ops: int = 20_000) -> List[dict]:
    """KeyValueStore conflict-index puts + conflict queries
    (CompactConflictIndexBench)."""
    from frankenpaxos_tpu.statemachine import KeyValueStore, kv_set

    rows = []

    def run() -> int:
        index = KeyValueStore().conflict_index()
        rng = random.Random(2)
        for i in range(num_ops):
            cmd = kv_set((f"k{rng.randrange(64)}", "v"))
            index.put(("c", i), cmd)
            if i % 4 == 3:
                index.get_conflicts(cmd)
        return num_ops

    ops, seconds = _timed(run)
    rows.append(_report("conflict_index", "kv_put_conflicts", ops, seconds))
    return rows


def compiled_memory_stats(runner, cfg, state, ticks: int) -> dict:
    """XLA's compiled memory accounting for one ``run_ticks``-shaped
    jit (``runner(cfg, state, t0, ticks, key)``): argument/output/temp/
    alias bytes plus ``peak_bytes`` = arg + out + temp - alias (what
    donation removes). An executable deserialized from the persistent
    compilation cache reports NO aliasing, which would zero the
    donation accounting, so the disk cache is detached for this compile
    (dir=None + reset_cache; flipping jax_enable_compilation_cache
    alone does not stop reads once the cache is initialized) and
    restored afterwards. Shared by the hbm bench below and
    scripts/tpu_layout_bench.py."""
    import jax
    import jax.numpy as jnp

    from jax.experimental.compilation_cache import compilation_cache as cc

    prev_dir = jax.config.jax_compilation_cache_dir
    try:
        jax.config.update("jax_compilation_cache_dir", None)
        cc.reset_cache()
        ma = runner.lower(
            cfg, state, jnp.zeros((), jnp.int32), ticks,
            jax.random.PRNGKey(0),
        ).compile().memory_analysis()
    finally:
        jax.config.update("jax_compilation_cache_dir", prev_dir)
        cc.reset_cache()
    arg_b = int(ma.argument_size_in_bytes)
    out_b = int(ma.output_size_in_bytes)
    tmp_b = int(ma.temp_size_in_bytes)
    alias_b = int(ma.alias_size_in_bytes)
    return {
        "argument_bytes": arg_b,
        "output_bytes": out_b,
        "temp_bytes": tmp_b,
        "alias_bytes": alias_b,
        "peak_bytes": arg_b + out_b + tmp_b - alias_b,
    }


def bench_hbm(
    num_groups: int = 3334,
    window: int = 64,
    slots_per_tick: int = 8,
    ticks: int = 200,
    cases: "tuple | None" = None,
) -> List[dict]:
    """The HBM-bandwidth pass, measured: the flagship 10k-acceptor
    batched-MultiPaxos config under four (dtype x donation) variants —

      * ``int32_nodonate``  — the pre-pass baseline: widened (int32)
        state, no buffer donation (a fresh non-donating jit of the same
        tick program);
      * ``int32_donate``    — donation alone;
      * ``narrow_nodonate`` — the dtype policy alone;
      * ``narrow_donate``   — the shipped configuration.

    Each row reports ticks/sec (ops = ticks) plus a ``HBM_JSON`` line
    with the state footprint and XLA's own compiled memory analysis
    (argument/output/temp/alias bytes): ``peak_bytes`` = arguments +
    outputs + temps - aliased, which is exactly what donation removes —
    the measured-peak-HBM number of the acceptance criteria, reported by
    the compiler rather than asserted. ``bytes_per_tick`` is the
    elementwise-sweep traffic bound 2 x state_bytes (each tick reads and
    rewrites the whole state).
    """
    import functools
    import json

    import jax
    import jax.numpy as jnp

    from frankenpaxos_tpu.tpu import multipaxos_batched as mb
    from frankenpaxos_tpu.tpu.common import state_nbytes, widen_state

    cfg = mb.BatchedMultiPaxosConfig(
        f=1,
        num_groups=num_groups,
        window=window,
        slots_per_tick=slots_per_tick,
        lat_min=1,
        lat_max=3,
        drop_rate=0.0,
        retry_timeout=16,
        thrifty=True,
    )
    nodonate = jax.jit(mb.run_ticks.__wrapped__, static_argnums=(0, 3))
    variants = [
        ("int32_nodonate", True, nodonate),
        ("int32_donate", True, mb.run_ticks),
        ("narrow_nodonate", False, nodonate),
        ("narrow_donate", False, mb.run_ticks),
    ]
    if cases is not None:  # e.g. the smoke test's before/after pair
        variants = [v for v in variants if v[0] in cases]
    key = jax.random.PRNGKey(0)
    t0 = jnp.zeros((), jnp.int32)
    rows = []
    for case, widen, runner in variants:
        make = (
            (lambda: widen_state(mb.init_state(cfg)))
            if widen
            else (lambda: mb.init_state(cfg))
        )
        state = make()
        sbytes = state_nbytes(state)
        mem = compiled_memory_stats(runner, cfg, state, ticks)
        # Warm up (compile + one segment), then time one segment.
        state, t = runner(cfg, state, t0, ticks, key)
        jax.block_until_ready(state)
        state = make()

        def run() -> int:
            out, _ = runner(cfg, state, t0, ticks, key)
            jax.block_until_ready(out)
            return ticks

        ops, seconds = _timed(run)
        row = _report("hbm", case, ops, seconds)
        row.update(
            {
                "state_bytes": sbytes,
                "bytes_per_tick": 2 * sbytes,
                **mem,
                "num_acceptors": cfg.num_acceptors,
                "device": str(jax.devices()[0]),
            }
        )
        print("HBM_JSON " + json.dumps(row))
        rows.append(row)
    return rows


def _interleaved_best(sims: dict, ticks: int, rounds: int) -> dict:
    """The shared overhead-measurement discipline: warm/compile every
    variant with one segment, then INTERLEAVE ``rounds`` timed segments
    across the variants and keep each variant's best. A small-percentage
    budget question cannot survive sequential per-variant timing on a
    shared box (observed ±30% between back-to-back identical segments);
    interleaving makes all variants sample the same noise environment.
    Returns ``{case: best_seconds}`` in ``sims`` insertion order."""
    import time

    best = {}
    for case, sim in sims.items():
        sim.run(ticks)  # compile + warm
        sim.block_until_ready()
        best[case] = float("inf")
    for _ in range(rounds):
        for case, sim in sims.items():
            start = time.perf_counter()
            sim.run(ticks)
            sim.block_until_ready()
            best[case] = min(best[case], time.perf_counter() - start)
    return best


def measure_telemetry_overhead(cfg, ticks: int, rounds: int = 3) -> dict:
    """Head-to-head telemetry-ring overhead on one config: ``ring_off``
    (zero-width ring — record() no-ops at trace time, XLA removes every
    telemetry computation) vs ``ring_on`` (the shipped default ring),
    timed via :func:`_interleaved_best`. Shared by the ``telemetry``
    device bench below and ``bench.py --telemetry``.

    Returns ``{"seconds": {case: best}, "rates": {case: ticks/sec},
    "ratio": on/off, "sim_on": <the ring_on transport>}`` (``sim_on``
    has run ``(rounds + 1) * ticks`` ticks — its ring feeds the
    per-phase breakdown)."""
    from frankenpaxos_tpu.tpu.transport import TpuSimTransport

    sims = {
        case: TpuSimTransport(cfg, seed=0, telemetry_window=tel_window)
        for case, tel_window in (("ring_off", 0), ("ring_on", None))
    }
    best = _interleaved_best(sims, ticks, rounds)
    rates = {case: ticks / s for case, s in best.items()}
    return {
        "seconds": best,
        "rates": rates,
        "ratio": rates["ring_on"] / rates["ring_off"],
        "sim_on": sims["ring_on"],
        "total_ticks_on": (rounds + 1) * ticks,
    }


def bench_telemetry(
    num_groups: int = 3334,
    window: int = 64,
    slots_per_tick: int = 8,
    ticks: int = 200,
) -> List[dict]:
    """The device-telemetry pass, measured on the flagship 10k-acceptor
    config: ``ring_off`` (zero-width ring — record() no-ops at trace
    time, XLA removes every telemetry computation) vs ``ring_on`` (the
    shipped default ring). Each row reports ticks/sec; the on-row's
    ``TELEM_JSON`` line adds the per-phase throughput breakdown read
    FROM the ring itself (commits/executes/proposals/phase-plane
    messages per second) alongside the overhead ratio — the per-phase
    accounting the hbm block can't see."""
    import json

    from frankenpaxos_tpu.tpu import BatchedMultiPaxosConfig
    from frankenpaxos_tpu.tpu.telemetry import COUNTER_FIELDS

    cfg = BatchedMultiPaxosConfig(
        f=1,
        num_groups=num_groups,
        window=window,
        slots_per_tick=slots_per_tick,
        lat_min=1,
        lat_max=3,
        drop_rate=0.0,
        retry_timeout=16,
        thrifty=True,
    )
    measured = measure_telemetry_overhead(cfg, ticks)
    rows = []
    for case in ("ring_off", "ring_on"):
        seconds = measured["seconds"][case]
        row = _report("telemetry", case, ticks, seconds)
        if case == "ring_on":
            summary = measured["sim_on"].telemetry_summary()
            # events/sec = (events/tick over the whole run) x (ticks/sec
            # of the best measured segment).
            ticks_run = measured["total_ticks_on"]
            per_phase = {
                f"{name}_per_sec": round(
                    summary[f"{name}_total"] / ticks_run * (ticks / seconds),
                    1,
                )
                for name in COUNTER_FIELDS
                if name != "queue_depth"
            }
            row.update(
                {
                    "overhead_ratio": round(measured["ratio"], 4),
                    "num_acceptors": cfg.num_acceptors,
                    **per_phase,
                }
            )
            print("TELEM_JSON " + json.dumps(row))
        rows.append(row)
    return rows


DEGRADED_PLAN_KW = dict(
    drop_rate=0.05, dup_rate=0.05, jitter=1, crash_rate=0.005,
    revive_rate=0.1,
)


def measure_fault_overhead(cfg, ticks: int, rounds: int = 3) -> dict:
    """Degraded-mode benchmark: the SAME config run healthy
    (``FaultPlan.none()``) vs under a standard degraded plan
    (``DEGRADED_PLAN_KW``: 5% extra loss, 5% duplication, 1-tick jitter,
    0.5%/10% crash/revive driving real device-side elections).

    Timed via :func:`_interleaved_best`. Returns
    ``{"seconds", "rates" (ticks/sec), "ratio" (faulty/healthy),
    "committed" per case, "sim_faulty"}`` — the faulty transport's
    telemetry ring shows the drops/retries/leader_changes the plan
    injected. Shared by the ``faults`` device bench and
    ``bench.py --faults``."""
    import dataclasses as _dc

    from frankenpaxos_tpu.tpu.faults import FaultPlan
    from frankenpaxos_tpu.tpu.transport import TpuSimTransport

    plan = FaultPlan(**DEGRADED_PLAN_KW)
    sims = {
        case: TpuSimTransport(_dc.replace(cfg, faults=faults), seed=0)
        for case, faults in (
            ("healthy", FaultPlan.none()), ("faulty", plan),
        )
    }
    best = _interleaved_best(sims, ticks, rounds)
    rates = {case: ticks / s for case, s in best.items()}
    return {
        "plan": plan.to_dict(),
        "seconds": best,
        "rates": rates,
        "ratio": rates["faulty"] / rates["healthy"],
        "committed": {case: sims[case].committed() for case in sims},
        "total_ticks": (rounds + 1) * ticks,
        "sim_faulty": sims["faulty"],
    }


def bench_faults(
    num_groups: int = 3334,
    window: int = 64,
    slots_per_tick: int = 8,
    ticks: int = 200,
) -> List[dict]:
    """The degraded-mode device bench on the flagship 10k-acceptor
    config: healthy vs faulty ticks/sec + committed/sec, with the faulty
    run's telemetry totals (drops/retries/leader_changes actually
    injected) on a ``FAULTS_JSON`` line."""
    import json

    from frankenpaxos_tpu.tpu import BatchedMultiPaxosConfig
    from frankenpaxos_tpu.tpu.telemetry import COL

    cfg = BatchedMultiPaxosConfig(
        f=1,
        num_groups=num_groups,
        window=window,
        slots_per_tick=slots_per_tick,
        lat_min=1,
        lat_max=3,
        retry_timeout=16,
        thrifty=True,
    )
    measured = measure_fault_overhead(cfg, ticks)
    rows = []
    for case in ("healthy", "faulty"):
        seconds = measured["seconds"][case]
        row = _report("faults", case, ticks, seconds)
        row["committed"] = measured["committed"][case]
        if case == "faulty":
            tel = measured["sim_faulty"].telemetry()
            row.update(
                {
                    "slowdown_ratio": round(measured["ratio"], 4),
                    "plan": measured["plan"],
                    "drops_total": int(tel.totals[COL["drops"]]),
                    "retries_total": int(tel.totals[COL["retries"]]),
                    "leader_changes_total": int(
                        tel.totals[COL["leader_changes"]]
                    ),
                    "num_acceptors": cfg.num_acceptors,
                }
            )
            print("FAULTS_JSON " + json.dumps(row))
        rows.append(row)
    return rows


def measure_workload_overhead(cfg, ticks: int, rounds: int = 3) -> dict:
    """Shaping-overhead benchmark: the SAME flagship config run at
    saturation (``WorkloadPlan.none()`` — the structural no-op
    baseline) vs under each workload-engine machinery tier
    (tpu/workload.py): ``constant`` (deterministic fixed-point
    arrivals + Zipf skew + FIFO backlog + exact wait binning),
    ``poisson`` (adds the per-tick Poisson draw), and ``closed``
    (outstanding-request window + think ring). Rates are pinned at the
    config's own slots_per_tick so every variant moves comparable
    protocol work per tick and the ratio prices the SHAPING machinery,
    not a lighter load.

    Timed via :func:`_interleaved_best`. Returns ``{"plans",
    "seconds", "rates" (ticks/sec), "ratios" (case/none — the <2%
    budget gate is the `constant` tier, the matrix's default
    process), "committed", "sims"}``. Shared by the ``workload``
    device bench and ``bench.py --workload``."""
    import dataclasses as _dc

    from frankenpaxos_tpu.tpu.transport import TpuSimTransport
    from frankenpaxos_tpu.tpu.workload import WorkloadPlan

    rate = float(cfg.slots_per_tick)
    plans = {
        "none": WorkloadPlan.none(),
        "constant": WorkloadPlan(
            arrival="constant", rate=rate, zipf_s=0.8
        ),
        "poisson": WorkloadPlan(
            arrival="poisson", rate=rate, zipf_s=0.8
        ),
        "closed": WorkloadPlan(
            closed_window=2 * cfg.slots_per_tick, think_time=2
        ),
    }
    sims = {
        case: TpuSimTransport(_dc.replace(cfg, workload=p), seed=0)
        for case, p in plans.items()
    }
    best = _interleaved_best(sims, ticks, rounds)
    rates = {case: ticks / s for case, s in best.items()}
    return {
        "plans": {case: p.to_dict() for case, p in plans.items()},
        "seconds": best,
        "rates": rates,
        "ratios": {
            case: rates[case] / rates["none"]
            for case in plans
            if case != "none"
        },
        "committed": {case: sims[case].committed() for case in sims},
        "sims": sims,
    }


def bench_workload(
    num_groups: int = 3334,
    window: int = 64,
    slots_per_tick: int = 8,
    ticks: int = 200,
) -> List[dict]:
    """The workload-engine device bench on the flagship 10k-acceptor
    config: saturation vs each shaping tier, ticks/sec + committed,
    with the overhead ratios and the <2% budget verdict (on the
    ``constant`` tier) on a ``WORKLOAD_JSON`` line. Evidence artifact:
    ``results/workload_overhead_r12.json``."""
    import json

    from frankenpaxos_tpu.tpu import BatchedMultiPaxosConfig

    cfg = BatchedMultiPaxosConfig(
        f=1,
        num_groups=num_groups,
        window=window,
        slots_per_tick=slots_per_tick,
        lat_min=1,
        lat_max=3,
        retry_timeout=16,
        thrifty=True,
    )
    measured = measure_workload_overhead(cfg, ticks)
    rows = []
    for case in ("none", "constant", "poisson", "closed"):
        row = _report("workload", case, ticks, measured["seconds"][case])
        row["committed"] = measured["committed"][case]
        if case != "none":
            row["overhead_ratio"] = round(measured["ratios"][case], 4)
        rows.append(row)
    payload = {
        "num_acceptors": cfg.num_acceptors,
        "ticks": ticks,
        "ticks_per_sec": {
            case: round(r, 2) for case, r in measured["rates"].items()
        },
        "committed": measured["committed"],
        "ratios": {
            case: round(r, 4) for case, r in measured["ratios"].items()
        },
        # The budget tier: the matrix's default (constant) machinery.
        "budget_ok": measured["ratios"]["constant"] >= 0.98,
        "plans": measured["plans"],
    }
    print("WORKLOAD_JSON " + json.dumps(payload))
    return rows


def _packed_plane_bytes(state) -> dict:
    """Stored bytes of each PACKED_PLANES plane on a live state: the
    status/rb_status words and the lifecycle occupancy bitmap (zero
    when sessions are off or the unpacked twin carries occupancy in
    the ``sess_last`` sentinel instead)."""
    return {
        "status": int(state.status.nbytes),
        "rb_status": int(state.rb_status.nbytes),
        "sess_occ": int(state.lifecycle.sess_occ.nbytes),
    }


def measure_packing_overhead(cfg, ticks: int, rounds: int = 3) -> dict:
    """Head-to-head bit-packing price on one config: ``unpacked`` (the
    int8 status planes + sentinel occupancy) vs ``packed``
    (``pack_planes=True`` — 2-bit status codes 16/word, 1-bit
    occupancy 32/word, tpu/packing.py). Same seed, and the twin-state
    contract (tests/test_packing.py) makes the two runs bit-identical,
    so the ratio prices ONLY the unpack-at-entry/pack-at-exit shift
    arithmetic against the smaller HBM resident set. Timed via
    :func:`_interleaved_best`. Returns ``{"seconds", "rates"
    (ticks/sec), "ratio" (packed/unpacked), "plane_bytes" (per case),
    "bytes_saved", "committed"}``. Shared by the ``packing`` device
    bench and ``bench.py --sessions``."""
    import dataclasses as _dc

    from frankenpaxos_tpu.tpu.transport import TpuSimTransport

    sims = {
        case: TpuSimTransport(
            _dc.replace(cfg, pack_planes=packed), seed=0
        )
        for case, packed in (("unpacked", False), ("packed", True))
    }
    best = _interleaved_best(sims, ticks, rounds)
    rates = {case: ticks / s for case, s in best.items()}
    plane_bytes = {
        case: _packed_plane_bytes(sim.state) for case, sim in sims.items()
    }
    return {
        "seconds": best,
        "rates": rates,
        "ratio": rates["packed"] / rates["unpacked"],
        "plane_bytes": plane_bytes,
        "bytes_saved": sum(plane_bytes["unpacked"].values())
        - sum(plane_bytes["packed"].values()),
        "committed": {case: sim.committed() for case, sim in sims.items()},
    }


def bench_packing(
    num_groups: int = 3334,
    window: int = 64,
    slots_per_tick: int = 8,
    ticks: int = 200,
) -> List[dict]:
    """The bit-packing device bench on the flagship 10k-acceptor
    config with the session table engaged (the occupancy bitmap is the
    1-bit plane): packed vs unpacked ticks/sec, per-plane stored
    bytes, and the committed-count equality spot check on a
    ``PACKING_JSON`` line. Evidence artifact: the packing block of
    ``results/SESSIONS_r01.json``."""
    import json

    from frankenpaxos_tpu.tpu import BatchedMultiPaxosConfig
    from frankenpaxos_tpu.tpu.lifecycle import LifecyclePlan

    cfg = BatchedMultiPaxosConfig(
        f=1,
        num_groups=num_groups,
        window=window,
        slots_per_tick=slots_per_tick,
        lat_min=1,
        lat_max=3,
        retry_timeout=16,
        thrifty=True,
        lifecycle=LifecyclePlan(sessions=64, resubmit_rate=0.05),
    )
    measured = measure_packing_overhead(cfg, ticks)
    rows = []
    for case in ("unpacked", "packed"):
        row = _report("packing", case, ticks, measured["seconds"][case])
        row["committed"] = measured["committed"][case]
        row["plane_bytes"] = sum(measured["plane_bytes"][case].values())
        rows.append(row)
    payload = {
        "num_acceptors": cfg.num_acceptors,
        "ticks": ticks,
        "ticks_per_sec": {
            case: round(r, 2) for case, r in measured["rates"].items()
        },
        "ratio": round(measured["ratio"], 4),
        "plane_bytes": measured["plane_bytes"],
        "bytes_saved": measured["bytes_saved"],
        "committed_equal": measured["committed"]["packed"]
        == measured["committed"]["unpacked"],
    }
    print("PACKING_JSON " + json.dumps(payload))
    return rows


def _kernel_cases(A=3, G=3334, W=64, N=3334, L=3, KV=16, CW=16, seed=0):
    """Random dtype-policy-native inputs for every registered kernel
    plane (flagship-shaped by default): ``{plane: (args, statics)}``.
    Mirrors the distributions of tests/test_ops.py at benchmark scale."""
    import jax
    import jax.numpy as jnp

    from frankenpaxos_tpu.ops import INF, INF16

    I16, I8 = jnp.int16, jnp.int8
    keys = iter(jax.random.split(jax.random.PRNGKey(seed), 128))

    def nxt():
        return next(keys)

    def clock(shape, p=0.35):
        return jnp.where(
            jax.random.uniform(nxt(), shape) < p,
            jax.random.randint(nxt(), shape, -1, 5),
            INF16,
        ).astype(I16)

    def lat16(shape):
        return jax.random.randint(nxt(), shape, 1, 4).astype(I16)

    t = jnp.int32(33)
    cases: Dict[str, tuple] = {}

    # ---- MultiPaxos planes, acceptor-major [A, G, W].
    status = jax.random.randint(nxt(), (G, W), 0, 3).astype(I8)
    slot_value = jnp.where(
        status > 0, jax.random.randint(nxt(), (G, W), 0, 10000), -1
    )
    propose_tick = jnp.where(
        status > 0, jax.random.randint(nxt(), (G, W), 0, 30), INF
    )
    last_send = jnp.where(
        status > 0, jax.random.randint(nxt(), (G, W), 0, 33), INF
    )
    chosen_tick = jnp.where(
        status == 2, jax.random.randint(nxt(), (G, W), 0, 33), INF
    )
    chosen_round = jnp.where(status == 2, 1, -1).astype(I16)
    chosen_value = jnp.where(status == 2, slot_value, -1)
    replica_arrival = jnp.where(
        status == 2, jax.random.randint(nxt(), (G, W), 30, 40), INF
    )
    p2a, p2b = clock((A, G, W)), clock((A, G, W))
    acc_round = jax.random.randint(nxt(), (A, G), 0, 3).astype(I16)
    leader_round = jax.random.randint(nxt(), (G,), 0, 3).astype(I16)
    vote_round = jax.random.randint(nxt(), (A, G, W), -1, 3).astype(I16)
    vote_value = jnp.where(
        vote_round >= 0, jax.random.randint(nxt(), (A, G, W), 0, 10000), -1
    )
    head = jax.random.randint(nxt(), (G,), 0, 100)
    cases["multipaxos_vote_quorum"] = (
        (
            p2a, acc_round, leader_round, slot_value, vote_round,
            vote_value, p2b, lat16((A, G, W)),
            jax.random.uniform(nxt(), (A, G, W)) < 0.9, head,
        ),
        {},
    )
    cases["multipaxos_p1_promise"] = (
        (
            status, vote_round, vote_value, slot_value, p2a, p2b,
            last_send, jax.random.uniform(nxt(), (G,)) < 0.5,
            jax.random.uniform(nxt(), (A, G)) < 0.7, lat16((A, G, W)), t,
        ),
        {},
    )
    cases["multipaxos_dispatch"] = (
        (
            status, slot_value, propose_tick, last_send, chosen_tick,
            chosen_round, chosen_value, replica_arrival, p2a, p2b,
            vote_round, vote_value,
            jax.random.randint(nxt(), (G, W), 0, A + 1),  # nvotes
            head, head + jax.random.randint(nxt(), (G,), 0, W + 1),
            leader_round, jnp.full((G,), 8, jnp.int32),
            jnp.ones((G,), bool),
            jax.random.uniform(nxt(), (A, G, W)) < 0.6,  # send_ok
            jax.random.uniform(nxt(), (A, G, W)) < 0.9,  # retry_deliv
            lat16((A, G, W)), lat16((A, G, W)),
            jax.random.randint(nxt(), (G, W), 1, 4),
            jnp.arange(G, dtype=jnp.int32), t,
        ),
        dict(f=1, retry_timeout=16, num_groups=G),
    )
    # ---- The whole-tick megakernel: the vote-plane args + the
    # dispatch-only args (clock aging folded in, age=True).
    cases["multipaxos_fused_tick"] = (
        (
            p2a, acc_round, leader_round, slot_value, vote_round,
            vote_value, p2b, lat16((A, G, W)),
            jax.random.uniform(nxt(), (A, G, W)) < 0.9, head,
            status, propose_tick, last_send, chosen_tick,
            chosen_round, chosen_value, replica_arrival,
            head + jax.random.randint(nxt(), (G,), 0, W + 1),
            jnp.full((G,), 8, jnp.int32), jnp.ones((G,), bool),
            jax.random.uniform(nxt(), (A, G, W)) < 0.6,  # send_ok
            jax.random.uniform(nxt(), (A, G, W)) < 0.9,  # retry_deliv
            lat16((A, G, W)), lat16((A, G, W)),
            jax.random.randint(nxt(), (G, W), 1, 4),
            jnp.arange(G, dtype=jnp.int32), t,
        ),
        dict(f=1, retry_timeout=16, num_groups=G, age=True),
    )

    # ---- Fast MultiPaxos vote plane, acceptor-major [A, G, W]: few
    # distinct values so the pairwise-match census sees conflicts.
    fmp_vv = jnp.where(
        jax.random.uniform(nxt(), (A, G, W)) < 0.6,
        jax.random.randint(nxt(), (A, G, W), 0, 6),
        -1,
    )
    fmp_status = jax.random.randint(nxt(), (G, W), 0, 3).astype(I8)
    cases["fastmultipaxos_vote"] = (
        (
            fmp_vv,
            jnp.where(
                fmp_vv >= 0,
                jax.random.randint(nxt(), (A, G, W), 0, 37),
                INF,
            ),
            fmp_status,
            jnp.where(
                fmp_status > 0,
                jax.random.randint(nxt(), (G, W), 0, 33),
                INF,
            ),
            jnp.where(
                jax.random.uniform(nxt(), (G, W)) < 0.2,
                jax.random.randint(nxt(), (G, W), 0, 6),
                -1,
            ),
            jnp.where(
                fmp_status == 1, jax.random.randint(nxt(), (G, W), 0, 6), -1
            ),
            jnp.where(
                (fmp_status == 1)[None]
                & (jax.random.uniform(nxt(), (A, G, W)) < 0.5),
                jax.random.randint(nxt(), (A, G, W), 32, 36),
                INF,
            ),
            jnp.where(
                (fmp_status == 1)[None]
                & (jax.random.uniform(nxt(), (A, G, W)) < 0.4),
                jax.random.randint(nxt(), (A, G, W), 31, 38),
                INF,
            ),
            (fmp_status == 1)[None]
            & (jax.random.uniform(nxt(), (A, G, W)) < 0.4),
            jnp.where(fmp_status == 2, 1, -1),
            jnp.where(
                fmp_status == 2,
                jax.random.randint(nxt(), (G, W), 33, 38),
                INF,
            ),
            jax.random.randint(nxt(), (G, W), 1, 4),
            jax.random.randint(nxt(), (G, W), 1, 4),
            t,
        ),
        dict(fq=2, f=1, recovery_timeout=10),
    )

    # ---- Horizontal vote plane, pool-major [P=2n, G, W].
    Pn = 6
    hz_status = jax.random.randint(nxt(), (G, W), 0, 3).astype(I8)
    hz_epoch = jnp.where(
        hz_status > 0, jax.random.randint(nxt(), (G, W), 0, 4), -1
    ).astype(I16)
    hz_voted = (hz_status > 0)[None] & (
        jax.random.uniform(nxt(), (Pn, G, W)) < 0.4
    )
    cases["horizontal_vote"] = (
        (
            hz_epoch,
            hz_status,
            jnp.where(
                hz_status > 0,
                jax.random.randint(nxt(), (G, W), 0, 33),
                INF,
            ),
            jnp.where(
                (hz_status == 1)[None]
                & (jax.random.uniform(nxt(), (Pn, G, W)) < 0.5),
                jax.random.randint(nxt(), (Pn, G, W), 32, 36),
                INF,
            ),
            jnp.where(
                hz_voted,
                jax.random.randint(nxt(), (Pn, G, W), 31, 38),
                INF,
            ),
            hz_voted,
            jnp.where(hz_voted, hz_epoch[None], -1).astype(I16),
            jax.random.randint(nxt(), (Pn, G, W), 1, 4),
            jax.random.uniform(nxt(), (Pn, G, W)) < 0.9,
            t,
        ),
        dict(n=3, quorum=2),
    )

    # ---- Scalog cut-commit plane, [P, S] with S = the shard axis (the
    # traffic axis: one column per simulated shard).
    SP, SS = 8, N
    sc_cc = jnp.int32(5)
    sc_ids = sc_cc + jnp.arange(SP)
    sc_vec_asc = jax.random.randint(nxt(), (SS,), 0, 20)[None, :] + jnp.cumsum(
        jax.random.randint(nxt(), (SP, SS), 0, 5), axis=0
    )
    cases["scalog_cut_commit"] = (
        (
            jnp.zeros((SP, SS), jnp.int32).at[sc_ids % SP].set(sc_vec_asc),
            jnp.full((SP,), INF, jnp.int32)
            .at[sc_ids % SP]
            .set(jax.random.randint(nxt(), (SP,), 30, 37)),
            jnp.full((SP,), INF, jnp.int32)
            .at[sc_ids % SP]
            .set(jax.random.randint(nxt(), (SP,), 23, 30)),
            jnp.full((SP,), 21, jnp.int32),
            sc_vec_asc[0] - 1,
            sc_cc,
            sc_cc + 6,
            t,
        ),
        {},
    )

    # ---- Mencius vote plane, leader-major [L, W, A] (L = G stripes).
    voted = jax.random.uniform(nxt(), (G, W, A)) < 0.3
    cases["mencius_vote"] = (
        (
            jnp.where(
                jax.random.uniform(nxt(), (G, W, A)) < 0.3,
                jax.random.randint(nxt(), (G, W, A), 31, 36),
                INF,
            ),
            voted,
            jnp.where(
                voted, jax.random.randint(nxt(), (G, W, A), 30, 37), INF
            ),
            jax.random.randint(nxt(), (G, W, A), 1, 4),
            jax.random.uniform(nxt(), (G, W, A)) < 0.9,
            t,
        ),
        {},
    )

    # ---- CRAQ chain plane, [N, CW] write ring + [N, L*KV] node state.
    tail = L - 1
    w_status = jax.random.randint(nxt(), (N, CW), 0, 3).astype(I8)
    cases["craq_chain"] = (
        (
            w_status,
            jax.random.randint(nxt(), (N, CW), 0, KV),
            jax.random.randint(nxt(), (N, CW), 0, 50),
            jnp.where(
                w_status == 2,
                jax.random.randint(nxt(), (N, CW), 0, max(tail, 1)),
                jax.random.randint(nxt(), (N, CW), 0, tail + 1),
            ),
            jnp.where(
                w_status > 0,
                jax.random.randint(nxt(), (N, CW), 32, 36),
                INF,
            ),
            jax.random.randint(nxt(), (N, CW), 0, 33),
            jax.random.randint(nxt(), (N, L * KV), 0, 3),
            jax.random.randint(nxt(), (N, L * KV), -1, 40),
            jax.random.randint(nxt(), (N, CW), 1, 4),
            t,
        ),
        dict(tail=tail, num_keys=KV),
    )

    # ---- Compartmentalized grid-vote plane, grid-major [R, C, G, W]
    # cells + [NR, G, W] replica planes (2x2 grid, 3 replicas — the
    # bench.py --multichip role shape at the flagship group count).
    Rg, Cg, NRg = 2, 2, 3
    cz_status = jax.random.randint(nxt(), (G, W), 0, 3).astype(I8)
    cz_head = jax.random.randint(nxt(), (G,), 0, 50)
    cases["compartmentalized_grid_vote"] = (
        (
            clock((Rg, Cg, G, W)),  # p2a
            clock((Rg, Cg, G, W)),  # p2b
            clock((NRg, G, W)),  # rep_arrival
            cz_status,
            jnp.where(
                cz_status > 0,
                jax.random.randint(nxt(), (G, W), 0, 33),
                INF,
            ),  # last_send
            cz_head[None, :]
            + jax.random.randint(nxt(), (NRg, G), 0, 8),  # rep_exec
            cz_head,
            cz_head + jax.random.randint(nxt(), (G,), 0, W + 1),
            jax.random.uniform(nxt(), (G, W)) < 0.9,  # alive_of_pos
            jax.random.uniform(nxt(), (Rg, Cg, G, W)) < 0.9,  # p2b_del
            jax.random.uniform(nxt(), (Rg, Cg, G, W)) < 0.9,  # retry_del
            jax.random.randint(nxt(), (Rg, Cg, G, W), 1, 4),  # p2b_lat
            jax.random.randint(nxt(), (Rg, Cg, G, W), 1, 4),  # retry_lat
            jax.random.randint(nxt(), (NRg, G, W), 1, 4),  # rep_lat
            t,
        ),
        dict(retry_timeout=8),
    )

    # ---- Dependency-graph execution plane, [B, V, VW] windowed graph
    # views (B batched graphs, V = W window vertices). Sparse random
    # digraphs (avg out-degree ~4) so the closure sees real SCC
    # structure rather than one giant component; at the default sizes
    # the key (max(8, G // 16), W, ceil(W/32)) = (208, 64, 2) is
    # exactly CAPTURE_KEYS["depgraph_execute"] in ops/costmodel.py.
    from frankenpaxos_tpu.ops import depgraph as _dg

    Bd = max(8, G // 16)
    dg_bits = jax.random.uniform(nxt(), (Bd, W, W)) < 0.06
    cases["depgraph_execute"] = (
        (
            _dg.pack_mask(dg_bits),
            jax.random.uniform(nxt(), (Bd, W)) < 0.5,  # committed
            jax.random.uniform(nxt(), (Bd, W)) < 0.8,  # active
        ),
        {},
    )
    return cases


def _tree_equal(a, b) -> bool:
    import jax
    import numpy as np

    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    if len(la) != len(lb):
        return False
    return all(
        np.asarray(x).dtype == np.asarray(y).dtype
        and np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(la, lb)
    )


# Pallas block-size sweep per plane on real TPU; the winners land in the
# checked-in table (ops/autotune.json) under FPX_WRITE_AUTOTUNE=1.
AUTOTUNE_BLOCKS = (128, 256, 512, 1024)


def _multiplane_tick(args, vote_block: int, dispatch_block: int):
    """The megakernel's multi-plane twin at the KERNEL level: clock
    aging + the fused vote/quorum kernel + the fused dispatch kernel
    (interpret mode, each at ITS OWN autotuned block), consuming the
    ``multipaxos_fused_tick`` case args. This is exactly the
    HBM-round-trip program the megakernel deletes, so
    fused-vs-multiplane is an apples-to-apples kernel-path race —
    callers jit this whole composition so the aging fuses into one
    compiled program, as it does in the real multi-plane tick."""
    from frankenpaxos_tpu.ops import fused_mp_dispatch, fused_vote_quorum
    from frankenpaxos_tpu.tpu.common import age_clock

    (p2a, acc_round, leader_round, slot_value, vote_round, vote_value,
     p2b, p2b_lat, delivered, head,
     status, propose_tick, last_send, chosen_tick, chosen_round,
     chosen_value, replica_arrival, next_slot, cap, retry_ok,
     send_ok, retry_deliv, p2a_lat, retry_lat, rep_lat, group_ids,
     t) = args
    p2a_aged = age_clock(p2a)
    p2b_aged = age_clock(p2b)
    vr, vv, p2b2, accr, nvotes, nsends, max_ord = fused_vote_quorum(
        p2a_aged, acc_round, leader_round, slot_value, vote_round,
        vote_value, p2b_aged, p2b_lat, delivered, head,
        block=vote_block, interpret=True,
    )
    outs = fused_mp_dispatch(
        status, slot_value, propose_tick, last_send, chosen_tick,
        chosen_round, chosen_value, replica_arrival, p2a_aged, p2b2,
        vr, vv, nvotes, head, next_slot, leader_round, cap, retry_ok,
        send_ok, retry_deliv, p2a_lat, retry_lat, rep_lat, group_ids, t,
        block=dispatch_block, interpret=True,
        f=1, retry_timeout=16, num_groups=int(head.shape[0]),
    )
    return (*outs, accr, nsends, max_ord)


def bench_fused_tick(iters: int = 3, rounds: int = 3, **sizes) -> List[dict]:
    """The megakernel acceptance race (flagship shape by default): ONE
    ``multipaxos_fused_tick`` call vs the multi-plane kernel path it
    replaces (clock aging + vote kernel + dispatch kernel, jitted as
    one composition), both in interpret mode so the comparison runs
    anywhere. No handicaps: EACH side is swept over ``AUTOTUNE_BLOCKS``
    and races at its own best block, and the timed segments interleave
    across the two sides with best-of-``rounds`` kept (the
    ``_interleaved_best`` discipline — a small-ratio verdict cannot
    survive sequential timing on a shared box). On CPU this prices the
    fusion structurally; the ≥1.3x/10M-entries-per-sec flagship targets
    are re-measured on real TPU, where the megakernel additionally
    deletes the inter-plane HBM round trips. Outputs are checked
    bit-identical between the two paths. A ``FUSED_TICK_JSON`` line
    carries the summary."""
    import functools
    import json

    import jax

    from frankenpaxos_tpu.ops import registry

    cases = _kernel_cases(**sizes)
    args, statics = cases["multipaxos_fused_tick"]
    plane = registry.PLANES["multipaxos_fused_tick"]
    key = plane.key_of(args)

    def sweep(make_fn):
        """(best_seconds, best_block, fn) over the block candidates —
        one warm call plus one timed call per block prunes the field."""
        best = None
        for blk in AUTOTUNE_BLOCKS:
            fn = make_fn(blk)
            jax.block_until_ready(fn())  # compile + warm
            _, s = _timed(lambda: (jax.block_until_ready(fn()), 1)[1])
            if best is None or s < best[0]:
                best = (s, blk, fn)
        return best

    _, fused_blk, fused = sweep(
        lambda blk: functools.partial(
            plane.kernel, *args, block=blk, interpret=True, **statics
        )
    )
    _, multi_blk, multi = sweep(
        lambda blk: functools.partial(
            jax.jit(
                functools.partial(
                    _multiplane_tick, vote_block=blk, dispatch_block=blk
                )
            ),
            args,
        )
    )
    parity = _tree_equal(fused(), multi())

    contenders = {"fused": fused, "multiplane": multi}
    best = {case: float("inf") for case in contenders}
    for _ in range(rounds):
        for case, fn in contenders.items():
            def run() -> int:
                out = None
                for _ in range(iters):
                    out = fn()
                jax.block_until_ready(out)
                return iters

            _, seconds = _timed(run)
            best[case] = min(best[case], seconds)
    rows = [
        _report("fused_tick", case, iters, best[case])
        for case in contenders
    ]
    payload = {
        "backend": jax.default_backend(),
        "iters": iters,
        "rounds": rounds,
        "fused_block": fused_blk,
        "multiplane_block": multi_blk,
        "shape": list(key),
        "fused_per_sec": round(iters / best["fused"], 3),
        "multiplane_per_sec": round(iters / best["multiplane"], 3),
        "speedup": round(best["multiplane"] / best["fused"], 3),
        "bit_identical": bool(parity),
    }
    print("FUSED_TICK_JSON " + json.dumps(payload))
    rows.append({"name": "fused_tick", "case": "summary", **payload})
    return rows


def bench_grid_vote(iters: int = 1, rounds: int = 5, **sizes) -> List[dict]:
    """The ``compartmentalized_grid_vote`` acceptance race: the FUSED
    plane (one Pallas grid program) vs its UNFUSED kernel-path twin
    (``ops.compartmentalized.unfused_grid_vote``: the same work split
    into the aging / vote / vote-count / choose / watermark / retry
    passes the reference tick's dataflow implies, each its own
    ``pallas_call`` with the [R, C, G, W] arrays round-tripping HBM
    between passes). Both sides run through the SAME execution vehicle
    (interpret mode off-TPU) — the fused-tick megakernel race
    discipline, so the ratio prices the fusion itself. The headline
    ``speedup`` races both sides at the DISPATCH-RESOLVED block (what
    ``registry.dispatch`` actually runs this shape at, via the autotune
    table), interleaved best-of-``rounds``; the full per-block sweep
    and each side's best block are recorded alongside (on CPU the
    per-grid-step interpreter cost shrinks as blocks grow, so
    whole-shard blocks converge — the compiled-TPU leg, where HBM
    round trips are the real price, stays pending_tpu_remeasure).
    Outputs are checked bit-identical. A ``GRID_VOTE_JSON`` line
    carries the summary."""
    import functools
    import json

    import jax

    from frankenpaxos_tpu.ops import registry
    from frankenpaxos_tpu.ops.compartmentalized import unfused_grid_vote

    # THIS backend's flagship shape: the bench.py --multichip 100k
    # simulated-acceptor config — 25000 groups x the 2x2 grid, window
    # 32 (not the MultiPaxos flagship G/W the other cases default to).
    sizes.setdefault("G", 25000)
    sizes.setdefault("W", 32)
    cases = _kernel_cases(**sizes)
    args, statics = cases["compartmentalized_grid_vote"]
    plane = registry.PLANES["compartmentalized_grid_vote"]
    key = plane.key_of(args)
    dispatch_blk = registry.block_for(plane.name, key)

    blocks = tuple(sorted(set(AUTOTUNE_BLOCKS) | {dispatch_blk}))
    cells = {}
    for side, kernel_fn in (
        ("fused", plane.kernel), ("unfused", unfused_grid_vote),
    ):
        for blk in blocks:
            fn = functools.partial(
                kernel_fn, *args, block=blk, interpret=True, **statics
            )
            jax.block_until_ready(fn())  # compile + warm
            cells[(side, blk)] = fn
    parity = _tree_equal(
        cells[("fused", dispatch_blk)](),
        cells[("unfused", dispatch_blk)](),
    )
    # One fully INTERLEAVED timing matrix: every (side, block) cell is
    # sampled once per round, best-of-``rounds`` kept — a small-ratio
    # verdict cannot survive phase-separated timing on a shared box
    # (the _interleaved_best discipline, applied across the whole
    # sweep so the two sides and all blocks see the same noise).
    best = {cell: float("inf") for cell in cells}
    for _ in range(rounds):
        for cell, fn in cells.items():
            def run() -> int:
                out = None
                for _ in range(iters):
                    out = fn()
                jax.block_until_ready(out)
                return iters

            _, seconds = _timed(run)
            best[cell] = min(best[cell], seconds)
    sweep = {
        side: {
            str(blk): round(best[(side, blk)] / iters, 4)
            for blk in blocks
        }
        for side in ("fused", "unfused")
    }
    best_blk = {
        side: min(blocks, key=lambda blk: best[(side, blk)])
        for side in ("fused", "unfused")
    }
    rows = [
        _report(
            "grid_vote", f"{side}[b{dispatch_blk}]", iters,
            best[(side, dispatch_blk)],
        )
        for side in ("fused", "unfused")
    ]
    payload = {
        "backend": jax.default_backend(),
        "iters": iters,
        "rounds": rounds,
        "shape": list(key),
        "dispatch_block": dispatch_blk,
        "fused_per_sec": round(iters / best[("fused", dispatch_blk)], 3),
        "unfused_per_sec": round(
            iters / best[("unfused", dispatch_blk)], 3
        ),
        # The acceptance ratio: both sides at the block the registry
        # actually dispatches this shape at.
        "speedup": round(
            best[("unfused", dispatch_blk)] / best[("fused", dispatch_blk)],
            3,
        ),
        "block_sweep_seconds": sweep,
        "best_block": best_blk,
        "speedup_best_vs_best": round(
            best[("unfused", best_blk["unfused"])]
            / best[("fused", best_blk["fused"])],
            3,
        ),
        "bit_identical": bool(parity),
    }
    print("GRID_VOTE_JSON " + json.dumps(payload))
    rows.append({"name": "grid_vote", "case": "summary", **payload})
    return rows


def bench_mesh_kernels(
    ticks: int = 20, rounds: int = 3, groups_per_device: int = 256
) -> List[dict]:
    """Kernels x mesh: the SAME sharded compartmentalized run raced
    with the grid-vote kernel ENGAGED (interpret off-TPU — the actual
    shard_map-lowered kernel path) vs in reference mode (GSPMD over
    pure jnp), on the full host mesh at a fixed per-device group load.
    Off-TPU the interpret row prices the Pallas INTERPRETER, not the
    kernel (bench_kernels' caveat), so the wall-clock verdict is
    reserved for the TPU leg; what this bench pins everywhere is that
    the sharded kernel path COMPILES, runs, and replays the sharded
    reference bit for bit. A ``MESH_KERNELS_JSON`` line carries the
    summary."""
    import dataclasses as _dc
    import json

    import jax
    import jax.numpy as jnp

    from frankenpaxos_tpu.ops import registry as _registry
    from frankenpaxos_tpu.ops.registry import KernelPolicy
    from frankenpaxos_tpu.parallel import sharding as sh
    from frankenpaxos_tpu.tpu import compartmentalized_batched as cbk

    n_dev = len(jax.devices())
    mesh = sh.make_mesh(jax.devices())
    G = groups_per_device * n_dev
    base = _dc.replace(cbk.analysis_config(), num_groups=G)
    cfgs = {
        "sharded_reference": _dc.replace(
            base, kernels=KernelPolicy.reference()
        ),
        "sharded_kernels": _dc.replace(
            base, kernels=KernelPolicy(mode="interpret")
        ),
    }
    t0 = jnp.zeros((), jnp.int32)
    key = jax.random.PRNGKey(0)

    def fresh_state(cfg):
        return sh.shard_state(
            "compartmentalized", cbk.init_state(cfg), mesh
        )

    def run_one(cfg, st):
        st, _ = sh.run_ticks_sharded(
            "compartmentalized", cfg, mesh, st, t0, ticks, key
        )
        jax.block_until_ready(st)
        return st

    finals = {}
    best = {}
    for case, cfg in cfgs.items():
        finals[case] = run_one(cfg, fresh_state(cfg))  # compile + warm
        best[case] = float("inf")
    import numpy as _np

    identical = all(
        _np.array_equal(_np.asarray(a), _np.asarray(b))
        for a, b in zip(
            jax.tree_util.tree_leaves(finals["sharded_reference"]),
            jax.tree_util.tree_leaves(finals["sharded_kernels"]),
        )
    )
    for _ in range(rounds):
        for case, cfg in cfgs.items():
            # State construction stays OUTSIDE the timed region (the
            # donated buffers can't be reused, but rebuilding them is
            # setup, not simulation — and its cost skews the two rows
            # differently).
            st = fresh_state(cfg)
            _, seconds = _timed(lambda: (run_one(cfg, st), ticks)[1])
            best[case] = min(best[case], seconds)
    rows = [
        _report("mesh_kernels", case, ticks, best[case]) for case in cfgs
    ]
    payload = {
        "backend": jax.default_backend(),
        "n_devices": n_dev,
        "num_groups": G,
        "ticks": ticks,
        "rounds": rounds,
        "ticks_per_sec": {
            case: round(ticks / s, 2) for case, s in best.items()
        },
        "bit_identical": bool(identical),
        "committed": int(finals["sharded_kernels"].committed),
        # Off-TPU the kernels row runs the Pallas interpreter — the
        # wall-clock comparison is only meaningful on real hardware.
        "pending_tpu_remeasure": (
            jax.default_backend() not in _registry.TPU_BACKENDS
        ),
    }
    print("MESH_KERNELS_JSON " + json.dumps(payload))
    rows.append({"name": "mesh_kernels", "case": "summary", **payload})
    return rows


def bench_fleet(
    ticks: int = 40,
    schedules: int = 6,
    seeds_per_schedule: int = 2,
    rounds: int = 2,
) -> List[dict]:
    """Fleet brick vs sequential per-config loop at toy size (guards
    the ``bench.py --fleet`` fuzz leg): the SAME randomized traced-rate
    cells run (a) as one ``simtest.run_fleet`` brick — one compiled
    executable for all [schedules x seeds] instances — and (b) as the
    sequential loop of per-cell static-rate ``run_many_seeds`` calls
    (one compile per cell — the pre-fleet cost). Rows time both sides
    end to end INCLUDING compiles (that is the cost the fleet axis
    amortizes); a ``FLEET_JSON`` line carries the summary, with the
    verdict agreement pinned."""
    import json
    import random as _random

    import jax

    from frankenpaxos_tpu.harness import simtest
    from frankenpaxos_tpu.tpu.faults import FaultPlan
    from frankenpaxos_tpu.tpu.workload import WorkloadPlan

    assert rounds >= 1, "bench_fleet needs at least one round"
    spec = simtest.SPECS["multipaxos"]
    rng = _random.Random(0)
    cells = [
        simtest.random_rate_cell(rng, spec) for _ in range(schedules)
    ]
    n_runs = schedules * seeds_per_schedule

    fleet_ok = True

    def run_fleet_side() -> int:
        nonlocal fleet_ok
        res = simtest.run_fleet(
            spec, cells=cells, seeds_per_schedule=seeds_per_schedule,
            ticks=ticks,
        )
        fleet_ok = fleet_ok and res["ok"]
        return n_runs

    seq_ok = True

    def run_seq_side() -> int:
        nonlocal seq_ok
        for cell in cells:
            plan = FaultPlan(
                drop_rate=cell["drop"], dup_rate=cell["dup"],
                crash_rate=cell["crash"], revive_rate=cell["revive"],
            )
            res = simtest.run_many_seeds(
                spec, plan, list(range(seeds_per_schedule)), ticks,
                workload=WorkloadPlan(
                    arrival="constant", rate=cell["rate"]
                ),
            )
            seq_ok = seq_ok and res["ok"]
        return n_runs

    best = {"fleet_brick": float("inf"), "sequential": float("inf")}
    for i in range(rounds):
        # Round 0 pays the compiles on both sides; later rounds are
        # warm. best-of keeps the warm number, the FLEET_JSON carries
        # the cold one too (the amortization story lives in round 0).
        _, s = _timed(run_fleet_side)
        if i == 0:
            cold_fleet = s
        best["fleet_brick"] = min(best["fleet_brick"], s)
        _, s = _timed(run_seq_side)
        if i == 0:
            cold_seq = s
        best["sequential"] = min(best["sequential"], s)
    rows = [
        _report("fleet", case, n_runs, best[case]) for case in best
    ]
    payload = {
        "backend": jax.default_backend(),
        "schedules": schedules,
        "seeds_per_schedule": seeds_per_schedule,
        "ticks": ticks,
        "cold_fleet_seconds": round(cold_fleet, 3),
        "cold_sequential_seconds": round(cold_seq, 3),
        "cold_speedup_x": round(cold_seq / cold_fleet, 2),
        "warm_speedup_x": round(
            best["sequential"] / best["fleet_brick"], 2
        ),
        "fleet_ok": fleet_ok,
        "sequential_ok": seq_ok,
    }
    print("FLEET_JSON " + json.dumps(payload))
    rows.append({"name": "fleet", "case": "summary", **payload})
    return rows


def bench_kernels(iters: int = 20, **sizes) -> List[dict]:
    """Per-plane kernel microbenchmark + autotuner: the jitted pure-jnp
    reference of EVERY registered plane is timed at flagship shapes; on
    real TPU backends the fused Pallas kernel is additionally swept over
    ``AUTOTUNE_BLOCKS`` and its best block + speedup reported (set
    ``FPX_WRITE_AUTOTUNE=1`` to persist winners into ops/autotune.json).
    Elsewhere (CPU CI) the kernel runs once per plane in interpret mode
    at a reduced shape and is checked for BIT-PARITY with the reference
    (timing the pallas interpreter is meaningless). A ``KERNELS_JSON``
    line carries the machine-readable summary."""
    import functools
    import json
    import os

    import jax

    from frankenpaxos_tpu.ops import costmodel, registry

    on_tpu = jax.default_backend() in registry.TPU_BACKENDS
    cases = _kernel_cases(**sizes)
    small = _kernel_cases(A=3, G=48, W=16, N=48, L=3, KV=4, CW=8, seed=1)
    rows: List[dict] = []
    summary: Dict[str, dict] = {}
    winners: Dict[str, int] = {}
    for name, (args, statics) in cases.items():
        plane = registry.PLANES[name]
        ref = jax.jit(functools.partial(plane.reference, **statics))
        jax.block_until_ready(ref(*args))  # compile

        def run_ref() -> int:
            out = None
            for _ in range(iters):
                out = ref(*args)
            jax.block_until_ready(out)
            return iters

        ops, ref_s = _timed(run_ref)
        rows.append(_report("kernels", f"{name}:reference", ops, ref_s))
        entry = {"reference_per_sec": round(iters / ref_s, 2)}
        # Efficiency telemetry: the measured/predicted ratio against
        # the roofline cost model (ops/costmodel.py) under the
        # parameter set matching where the timing ran. ratio >> 1 or
        # << 1 is the costmodel-drift signal; the capture JSON records
        # it so later rounds diff against it.
        if name in costmodel.MODELS:
            cm_params = costmodel.TPU_V5E if on_tpu else costmodel.CPU_JIT
            predicted = costmodel.predict_per_sec(
                name, plane.key_of(args), cm_params
            )
            entry["predicted_per_sec"] = round(predicted, 2)
            entry["efficiency"] = round((iters / ref_s) / predicted, 4)
            entry["costmodel_params"] = cm_params.name
        if on_tpu:
            fused = functools.partial(plane.kernel, **statics)
            best = None
            for blk in AUTOTUNE_BLOCKS:
                jax.block_until_ready(fused(*args, block=blk))

                def run_fused() -> int:
                    out = None
                    for _ in range(iters):
                        out = fused(*args, block=blk)
                    jax.block_until_ready(out)
                    return iters

                _, fs = _timed(run_fused)
                rows.append(
                    _report("kernels", f"{name}:fused[b{blk}]", iters, fs)
                )
                if best is None or fs < best[1]:
                    best = (blk, fs)
            blk, fs = best
            winners[registry.table_key(name, plane.key_of(args))] = blk
            entry.update(
                fused_per_sec=round(iters / fs, 2),
                speedup=round(ref_s / fs, 3),
                best_block=blk,
            )
        else:
            s_args, s_statics = small[name]
            got = plane.kernel(
                *s_args, block=16, interpret=True, **s_statics
            )
            entry["interpret_parity"] = _tree_equal(
                plane.reference(*s_args, **s_statics), got
            )
            # Off-TPU there is nothing to sweep: seed the autotune table
            # with the plane default at the measured shape, so fresh
            # planes get an entry (clearly marked pending a TPU
            # re-measure) and nearest-G fallback has an anchor. Only
            # MISSING keys seed — a CPU run must never clobber a
            # measured (or previously recorded) TPU winner.
            key = registry.table_key(name, plane.key_of(args))
            if key not in registry._table():
                winners[key] = plane.default_block
        summary[name] = entry
    payload = {
        "backend": jax.default_backend(),
        "iters": iters,
        "planes": summary,
    }
    if os.environ.get("FPX_WRITE_AUTOTUNE"):
        note = None
        if not on_tpu:
            note = (
                "PENDING TPU RE-MEASURE: entries written off-TPU are "
                "CPU-seeded plane defaults, not measured winners — "
                "rerun this command on a real TPU backend to sweep "
                "AUTOTUNE_BLOCKS and record measured blocks."
            )
        registry.write_table(winners, note=note)
        payload["autotune_written"] = winners
        payload["autotune_cpu_seeded"] = not on_tpu
    print("KERNELS_JSON " + json.dumps(payload))
    return rows


def bench_costmodel(**sizes) -> List[dict]:
    """Cost-model observatory pass (no kernels run — seconds, not
    minutes): (1) validates every registered plane's STATED byte terms
    against live argument arrays + ``jax.eval_shape`` outputs at the
    flagship shapes, (2) replays every committed
    ``results/kernel_microbench_*.json`` capture through the model's
    drift engine, and (3) emits the envelope verdict JSON the
    ``costmodel-drift`` analysis rule consumes — write it to
    ``results/costmodel_envelope.json`` with ``FPX_WRITE_ENVELOPE=1``.
    A ``COSTMODEL_JSON`` stdout line carries the payload either way."""
    import functools
    import json
    import math
    import os
    import pathlib

    import jax

    from frankenpaxos_tpu.ops import costmodel, registry

    cases = _kernel_cases(**sizes)
    rows: List[dict] = []
    planes_out: Dict[str, dict] = {}
    exact = True
    for name, (args, statics) in cases.items():
        plane = registry.PLANES[name]
        key = plane.key_of(args)
        model_in = costmodel.input_bytes(name, key)
        actual_in = sum(a.nbytes for a in jax.tree_util.tree_leaves(args))
        outs = jax.eval_shape(
            functools.partial(plane.reference, **statics), *args
        )
        actual_out = sum(
            math.prod(o.shape) * o.dtype.itemsize
            for o in jax.tree_util.tree_leaves(outs)
        )
        model_out = costmodel.output_bytes(name, key)
        ok = model_in == actual_in and model_out == actual_out
        exact = exact and ok
        planes_out[name] = {
            "key": list(key),
            "in_bytes": actual_in,
            "out_bytes": actual_out,
            "model_in_bytes": model_in,
            "model_out_bytes": model_out,
            "bytes_exact": ok,
            "flops": costmodel.flops(name, key),
            "predicted_per_sec_cpu": round(
                costmodel.predict_per_sec(name, key, costmodel.CPU_JIT), 2
            ),
            "predicted_per_sec_tpu": round(
                costmodel.predict_per_sec(name, key, costmodel.TPU_V5E), 2
            ),
        }
        rows.append(
            _report(
                "costmodel",
                f"{name}:predicted",
                1,
                costmodel.predict_seconds(name, key, costmodel.CPU_JIT),
            )
        )
    uncovered = sorted(set(registry.PLANES) - set(costmodel.MODELS))
    results_dir = pathlib.Path(__file__).resolve().parents[2] / "results"
    captures = sorted(results_dir.glob("kernel_microbench_*.json"))
    verdicts = {}
    labeled = []
    for path in captures:
        try:
            labeled.append((path.name, json.loads(path.read_text())))
        except (OSError, json.JSONDecodeError):
            continue
    for label, cap in labeled:
        verdicts[label] = costmodel.validate_capture(cap)
    findings = costmodel.drift_findings(labeled)
    payload = {
        "constants_version": costmodel.CONSTANTS_VERSION,
        "envelope": list(costmodel.ENVELOPE),
        "regression_factor": costmodel.REGRESSION_FACTOR,
        "bytes_exact": exact,
        "uncovered_planes": uncovered,
        "planes": planes_out,
        "captures": verdicts,
        "drift_findings": findings,
    }
    if os.environ.get("FPX_WRITE_ENVELOPE"):
        out = results_dir / "costmodel_envelope.json"
        out.write_text(json.dumps(payload, indent=1, sort_keys=True) + "\n")
        payload["envelope_written"] = str(out)
    print("COSTMODEL_JSON " + json.dumps(payload))
    return rows


BENCHES = {
    "depgraph": bench_depgraph,
    "int_prefix_set": bench_int_prefix_set,
    "buffer_map": bench_buffer_map,
    "conflict_index": bench_conflict_index,
}

# Device benchmarks live in their own registry: they need jax + minutes
# of wall clock at the flagship model size, so the pinned-baseline
# regression test (tests/test_microbench_regression.py) must not sweep
# them up with the Python hot-path benches.
DEVICE_BENCHES = {
    "hbm": bench_hbm,
    "telemetry": bench_telemetry,
    "faults": bench_faults,
    "workload": bench_workload,
    "packing": bench_packing,
    "kernels": bench_kernels,
    "costmodel": bench_costmodel,
    "fused_tick": bench_fused_tick,
    "grid_vote": bench_grid_vote,
    "mesh_kernels": bench_mesh_kernels,
    "fleet": bench_fleet,
}


def main() -> None:
    all_benches = {**BENCHES, **DEVICE_BENCHES}
    names = sys.argv[1:] or list(BENCHES)
    unknown = [n for n in names if n not in all_benches]
    if unknown:
        print(
            f"unknown bench(es) {', '.join(unknown)}; "
            f"choose from: {', '.join(all_benches)}",
            file=sys.stderr,
        )
        sys.exit(2)
    print("name,case,ops,seconds,ops_per_sec")
    for name in names:
        all_benches[name]()


if __name__ == "__main__":
    main()
