"""Continuous serve mode: a long-lived driver over any batched backend
with STREAMING observability — the serving shape ROADMAP asks for,
replacing the batch-mode compile/run-N-ticks/dump-JSON lifecycle.

Chunked dispatch with a double-buffered, non-blocking telemetry drain:

    dispatch chunk i          (run_ticks — donated state, async)
    snapshot telemetry_i      (a tiny jitted device-side COPY of the
                               telemetry ring + live workload gauges,
                               enqueued right behind chunk i; the copy
                               is what makes the buffers survive chunk
                               i+1's donation of the state)
    drain snapshot_{i-1}      (jax.device_get on the PREVIOUS chunk's
                               snapshot — it only waits for chunk i-1,
                               which already finished or is finishing,
                               while chunk i keeps computing)

The hot path therefore never syncs: no ``block_until_ready`` on the
state, no ``device_get`` of anything a pending chunk still owns —
spy-asserted by ``tests/test_serve.py`` and pinned structurally by the
``trace-serve-nosync`` analysis rule (the snapshot program must COPY,
i.e. alias nothing, and neither compiled artifact may contain a host
callback). Drains go through a :class:`telemetry.DrainCursor`, so
chunked drains are EXACT: summed chunk rows equal the one-shot capture
bit for bit, no sample lost or double-counted.

On top of the drain sit the streaming consumers:

  * the SLO engine (``monitoring/slo.py``): rolling p99-vs-target and
    shed-rate alarms from the live histograms, with a host control
    plane that CLAMPS admission on alarm through ``workload.set_rate``
    (a traced-state update between chunks — never a recompile) and
    recovers it after the alarm clears;
  * the span sampler (``telemetry.record_spans``, flagship backend):
    sampled per-slot lifecycle tick-stamps, exported together with the
    host dispatch/drain wall-clock spans as ONE Perfetto-loadable
    Chrome trace (``monitoring/traceviz.py``); host spans are also
    wrapped in ``jax.profiler`` annotations so a concurrent profiler
    capture shows them next to the device trace;
  * the scrape CSV (``monitoring/scrape.py`` schema): one device
    sample batch + host span batch per drain, tailed LIVE by
    ``python -m frankenpaxos_tpu.monitoring.dashboard <csv> --live``;
  * the CONTROL PLANE verbs (all zero-recompile edits of traced state
    between chunks): ``set_rate`` (the SLO clamp's knob),
    ``set_fault_rates`` (live fault-leg swaps on a
    ``FaultPlan(traced=True)`` config), and the production-lifecycle
    verbs ``reconfigure``/``swap_acceptor``/``rotate``
    (tpu/lifecycle.py: traced acceptor-membership epochs + forced
    window rolls);
  * CRASH TOLERANCE (tpu/checkpoint.py): every ``checkpoint_every``
    chunks an ALIAS-FREE jitted copy of the full State enqueues behind
    the chunk (the same double-buffer discipline as the drain — no
    added block_until_ready) and drains to a versioned, checksummed,
    torn-write-safe on-disk checkpoint on a writer thread;
    :meth:`ServeLoop.resume` restores it BIT-EXACTLY (state, tick,
    PRNG position, cursors, SLO context) so a killed run's resumed
    twin replays the uninterrupted run sha256-identically — pinned by
    ``tests/test_checkpoint.py`` and the ``checkpoint-alias-free`` /
    ``trace-checkpoint-restore`` rules, exercised for real by
    ``harness/recovery.py`` (SIGKILL + watchdog + backoff).

CLI (a bounded run of the flagship)::

    python -m frankenpaxos_tpu.harness.serve --seconds 10 \\
        --out-dir /tmp/serve [--rate-x 1.1] [--spans 16] \\
        [--slo-p99 24] [--groups 64] [--chunk 32]
"""

from __future__ import annotations

import dataclasses
import functools
import json
import os
import sys
import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp

from frankenpaxos_tpu.monitoring import scrape as scrape_mod
from frankenpaxos_tpu.ops import costmodel
from frankenpaxos_tpu.monitoring import traceviz
from frankenpaxos_tpu.monitoring.autoscaler import (
    Autoscaler,
    AutoscalerPolicy,
)
from frankenpaxos_tpu.monitoring.slo import (
    FleetSloEngine,
    SloEngine,
    SloPolicy,
)
from frankenpaxos_tpu.tpu import checkpoint as checkpoint_mod
from frankenpaxos_tpu.tpu import elastic as elastic_mod
from frankenpaxos_tpu.tpu import lifecycle as lifecycle_mod
from frankenpaxos_tpu.tpu import telemetry as telemetry_mod
from frankenpaxos_tpu.tpu import workload as workload_mod


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Serve-mode knobs (orthogonal to the backend's protocol config)."""

    chunk_ticks: int = 32  # ticks per dispatched chunk
    telemetry_window: int = telemetry_mod.TELEM_WINDOW
    spans: int = 0  # span-sampler reservoir (0 = off)
    slo: Optional[SloPolicy] = None
    scrape_csv: Optional[str] = None  # live CSV (dashboard --live tails it)
    trace_path: Optional[str] = None  # Perfetto trace written at shutdown
    max_chunks: Optional[int] = None
    max_seconds: Optional[float] = None
    # Crash tolerance (tpu/checkpoint.py): every checkpoint_every
    # chunks, enqueue a jitted ALIAS-FREE copy of the full State and
    # drain it to a versioned on-disk checkpoint while the next chunk
    # computes (the telemetry drain's double-buffer discipline — zero
    # added block_until_ready). checkpoint_keep prunes old steps.
    checkpoint_dir: Optional[str] = None
    checkpoint_every: int = 0  # chunks between checkpoints (0 = off)
    checkpoint_keep: int = 3
    # Elastic capacity (tpu/elastic.py + monitoring/autoscaler.py):
    # arming a policy puts the graceful-degradation LADDER between the
    # SLO alarms and the admission clamp — alarms first GROW the
    # bottleneck role's traced count (ServeLoop.resize, zero
    # recompiles) and only clamp admission once every padded role
    # plane is exhausted. Needs slo armed and an ElasticPlan-active
    # backend config.
    autoscaler: Optional[AutoscalerPolicy] = None

    def __post_init__(self):
        assert self.chunk_ticks >= 1
        if self.autoscaler is not None:
            assert self.slo is not None, (
                "the autoscaler ladder is driven by SLO alarms — arm "
                "ServeConfig.slo"
            )
        # Exact drains need the ring to retain at least one full chunk.
        assert self.telemetry_window >= self.chunk_ticks, (
            "telemetry_window must cover a chunk or drains drop ticks"
        )
        assert self.max_chunks is not None or self.max_seconds is not None, (
            "bound the loop with max_chunks and/or max_seconds"
        )
        assert self.checkpoint_every >= 0
        if self.checkpoint_dir is not None:
            assert self.checkpoint_every >= 1, (
                "checkpoint_dir needs checkpoint_every >= 1"
            )
        assert self.checkpoint_keep >= 1


# The jitted device-side copy whose outputs are FRESH buffers (inputs
# not donated, so XLA must materialize copies) — what lets a drain read
# them after the next chunk donates the state they were copied from.
# ONE implementation, shared with tpu/checkpoint.py: the telemetry
# snapshot and the full-State checkpoint snapshot run the same program,
# so the trace-serve-nosync and checkpoint-alias-free rules pin the
# same copy machinery.
_copy_tree = checkpoint_mod._copy_tree
_SNAP = checkpoint_mod._SNAP


def snapshot_leaves(state) -> Dict[str, Any]:
    """The sub-pytree the serve loop snapshots per chunk: the telemetry
    ring + the live workload gauges the SLO engine reads. Tiny (a few
    KB) next to the protocol state."""
    wls = state.workload
    return {
        "telemetry": state.telemetry,
        "wait_hist": wls.wait_hist,
        "offered": wls.offered,
        "shed": wls.shed,
        "backlog": wls.backlog,
    }


def lower_chunk_path(mod, cfg, state=None, chunk_ticks: int = 4):
    """Lower the two compiled artifacts of the serve hot path at a
    given config — (run_ticks, snapshot) — for inspection. Used by the
    ``trace-serve-nosync`` analysis rule and the harness tests; keeping
    it HERE means the rule checks exactly what the loop runs."""
    if state is None:
        state = mod.init_state(cfg)
    run_lowered = mod.run_ticks.lower(
        cfg, state, jnp.zeros((), jnp.int32), chunk_ticks,
        jax.random.PRNGKey(0),
    )
    snap_lowered = _SNAP.lower(snapshot_leaves(state))
    return run_lowered, snap_lowered


class ServeLoop:
    """A long-lived serve driver over one backend module (anything
    exposing the repo's ``init_state(cfg)`` / ``run_ticks(cfg, state,
    t0, n, key)`` protocol — all 14 ``tpu/*_batched.py`` backends)."""

    def __init__(
        self,
        mod,
        cfg,
        serve: ServeConfig,
        seed: int = 0,
        elastic_initial: Optional[Dict[str, int]] = None,
    ):
        self.mod = mod
        self.cfg = cfg
        self.serve = serve
        self.seed = seed
        self.key = jax.random.PRNGKey(seed)
        self.state = mod.init_state(cfg)
        self.state = dataclasses.replace(
            self.state,
            telemetry=telemetry_mod.make_telemetry(
                serve.telemetry_window, spans=serve.spans
            ),
        )
        # Elastic capacity: seed the traced role counts below their
        # padded capacities (the plane the autoscaler grows INTO), and
        # stand up the ladder's policy engine. The autoscaler tracks
        # targets host-side — it is the loop's single writer of them —
        # so the hot path never reads elastic state off the device.
        eplan = getattr(cfg, "elastic", None)
        if elastic_initial:
            assert eplan is not None and eplan.active, (
                "elastic_initial needs an ElasticPlan-active config"
            )
            self.state = dataclasses.replace(
                self.state,
                elastic=elastic_mod.make_state(eplan, elastic_initial),
            )
        self.autoscaler: Optional[Autoscaler] = None
        if serve.autoscaler is not None:
            assert eplan is not None and eplan.active, (
                "ServeConfig.autoscaler needs an ElasticPlan-active "
                "backend config"
            )
            self.autoscaler = Autoscaler(
                serve.autoscaler,
                {
                    name: (
                        eplan.capacity_of(name), eplan.floor_of(name)
                    )
                    for name in eplan.names
                },
                initial=elastic_initial,
            )
        self.t = jnp.zeros((), jnp.int32)
        self.cursor = telemetry_mod.DrainCursor()
        self.clock = traceviz.TickClock()
        self.host_spans: List[dict] = []
        self.spans: List[dict] = []
        self.drains: List[dict] = []
        self.slo: Optional[SloEngine] = (
            SloEngine(serve.slo) if serve.slo else None
        )
        plan = getattr(cfg, "workload", None)
        self._base_rate = (
            float(plan.rate) if plan is not None and plan.shaped else None
        )
        self._prev: Dict[str, Any] = {}  # previous drain's cumulatives
        self._spans_scraped = 0  # host spans already appended to CSV
        self._cap_scraped = 0  # capacity events already appended
        # Efficiency telemetry: the cost model's expected commits/tick
        # for THIS config (0.0 = shape not covered, gauges off) and the
        # previous drain's (ticks, commits) cumulatives for deltas.
        self._model_rate = costmodel.expected_commit_rate_per_tick(cfg)
        self._eff_prev = (0, 0)
        self._chunks = 0
        self._epoch = 0
        self.clean_shutdown = False
        # Crash tolerance (tpu/checkpoint.py).
        self._ckpt_step = 0  # next on-disk checkpoint step
        self._last_ckpt_chunks = -1  # chunk count of the last snapshot
        self._pending_ckpt = None  # (snapshot futures, manifest meta)
        self._resume_snap = None  # pending drain of the restored chunk
        self.checkpoints_written = 0
        self.checkpoint_errors: List[str] = []  # failed writer steps
        self.restores = 0
        self.resumed_from: Optional[dict] = None

    # -- host-side trace spans (also jax.profiler-annotated) ---------------

    def _span(self, name: str, start_unix: float, t0: float, **meta):
        self.host_spans.append(
            {
                "name": name,
                "start_unix": start_unix,
                "duration_s": time.perf_counter() - t0,
                **meta,
            }
        )

    # -- the control plane: verbs steering TRACED state between chunks.
    # Every verb is a host-side dataclasses.replace of a traced leaf —
    # the compiled program never changes (the jit cache stays flat,
    # pinned by tests/test_lifecycle.py and the trace-lifecycle-retrace
    # analysis rule), so a live serve loop turns fault legs on/off,
    # swaps acceptors, and forces window rolls with zero recompiles.

    def set_rate(self, rate: float):
        """Steer the traced offered rate (tpu/workload.py set_rate) —
        the same knob the SLO engine's admission clamp drives."""
        self.state = dataclasses.replace(
            self.state,
            workload=workload_mod.set_rate(self.state.workload, rate),
        )
        self._span("verb:set_rate", time.time(), time.perf_counter(),
                   rate=rate)

    def set_fault_rates(
        self,
        drop: float = 0.0,
        dup: float = 0.0,
        crash: float = 0.0,
        revive: float = 0.0,
    ):
        """Live FaultPlan swap: drive the traced Bernoulli rates of a
        ``FaultPlan(traced=True)`` config mid-serve — fault legs turn
        on/off between chunks with no recompile (the PR 10 follow-up:
        the control plane used to drive only the offered rate)."""
        self.state = dataclasses.replace(
            self.state,
            workload=workload_mod.set_fault_rates(
                self.state.workload,
                drop=drop, dup=dup, crash=crash, revive=revive,
            ),
        )
        self._span("verb:set_fault_rates", time.time(),
                   time.perf_counter(), drop=drop, dup=dup,
                   crash=crash, revive=revive)

    def reconfigure(self, mask):
        """Acceptor-set reconfiguration: install a new membership mask
        over the backend's acceptor axis and bump the traced epoch —
        the next chunk runs the in-graph i/i+1 handoff
        (tpu/lifecycle.py; needs a LifecyclePlan(reconfig=True)
        config). ``mask`` broadcasts (``True`` restores everyone)."""
        self.state = dataclasses.replace(
            self.state,
            lifecycle=lifecycle_mod.set_membership(
                self.state.lifecycle, mask
            ),
        )
        self._span("verb:reconfigure", time.time(), time.perf_counter())

    def swap_acceptor(self, index: int):
        """Reconfigure out the acceptor at ``index`` of the leading
        acceptor axis (the crashed-node swap)."""
        self.state = dataclasses.replace(
            self.state,
            lifecycle=lifecycle_mod.swap_acceptor(
                self.state.lifecycle, index
            ),
        )
        self._span("verb:swap_acceptor", time.time(),
                   time.perf_counter(), index=index)

    def rotate(self):
        """Latch a force-rotation: the next chunk rolls the slot
        window down to the retired quantum (needs a
        LifecyclePlan(rotate_every > 0) config)."""
        self.state = dataclasses.replace(
            self.state,
            lifecycle=lifecycle_mod.request_rotation(
                self.state.lifecycle
            ),
        )
        self._span("verb:rotate", time.time(), time.perf_counter())

    def resize(self, role: str, n: int):
        """Elastic-capacity verb: steer ``role``'s traced TARGET count
        (tpu/elastic.py set_target). Scale-ups take effect next chunk;
        scale-downs drain first (the backend deactivates the tail only
        once its in-flight work lands — no command is lost). A pure
        traced-state edit, so the jit cache stays flat across every
        resize (the ``trace-elastic-retrace`` rule); the span is a
        Perfetto INSTANT marker, so capacity events land on the
        timeline next to the alarm/clamp marks."""
        plan = getattr(self.cfg, "elastic", None)
        assert plan is not None and plan.declares(role), (
            f"config's ElasticPlan does not declare role {role!r}"
        )
        self.state = dataclasses.replace(
            self.state,
            elastic=elastic_mod.set_target(
                plan, self.state.elastic, role, n
            ),
        )
        self._span("verb:resize", time.time(), time.perf_counter(),
                   instant=True, role=role, to=int(n))

    def set_base_rate(self, rate: float):
        """Re-anchor the offered-load BASE rate the SLO clamp scales —
        the diurnal driver's knob (bench.py --elastic sweeps it across
        the compressed day). Applies immediately through the same
        traced ``workload.set_rate`` scalar."""
        self._base_rate = float(rate)
        scale = 1.0
        if self.slo is not None and (
            self.autoscaler is None or self.autoscaler.clamp_engaged
        ):
            scale = self.slo.scale
        self.set_rate(self._base_rate * scale)

    def install_trace(self, words):
        """Install a recorded arrival trace (tpu/packing.py delta
        codec) into the open-loop workload cursor — a pure state swap
        (the trace words are a WorkloadState leaf sized by the plan's
        ``trace_len``), so serving a different recorded day never
        recompiles the brick. Needs a ``WorkloadPlan(arrival="trace")``
        config; rejects length/lane mismatches host-side before any
        device transfer."""
        plan = getattr(self.cfg, "workload", None)
        assert plan is not None and plan.arrival == "trace", (
            "install_trace needs a WorkloadPlan(arrival='trace') config"
        )
        self.state = dataclasses.replace(
            self.state,
            workload=workload_mod.load_trace(self.state.workload, words),
        )
        self._span("verb:install_trace", time.time(),
                   time.perf_counter(), events=int(len(words)))

    # -- crash tolerance: async checkpoint + bit-exact resume --------------
    # Every checkpoint_every chunks the loop enqueues a jitted
    # alias-free copy of the FULL state (+ tick scalar) right behind the
    # just-dispatched chunk, then writes it to disk while the NEXT
    # chunk computes — the telemetry drain's double-buffer discipline
    # applied to durability: the hot path gains no block_until_ready
    # (the disk drain's device_get waits only for work that already
    # finished or is finishing). Because the PRNG is counter-based and
    # fully in-state, restoring the checkpoint plus the small host
    # context below resumes the run BIT-EXACTLY: the resumed run's
    # final State is sha256-identical to the uninterrupted twin's
    # (tests/test_checkpoint.py pins 3-seed twins for the flagship and
    # compartmentalized backends with kernels + FaultPlans engaged).

    def _host_context(self) -> dict:
        """Everything OUTSIDE the State pytree that bit-exact resume
        needs: the PRNG seed + chunk epoch (per-chunk keys are
        fold_in(PRNGKey(seed), epoch)), the drain-cursor position, and
        the SLO engine's full decision state + previous-drain
        cumulatives (so post-resume clamp decisions replay the twin's)."""
        ctx = {
            "seed": int(self.seed),
            "epoch": int(self._epoch),
            "chunks": int(self._chunks),
            "ckpt_step": int(self._ckpt_step),
            "cursor_tick": int(self.cursor.tick),
            "cursor_span": int(self.cursor.span),
            "prev": checkpoint_mod.jsonable(self._prev),
            "slo": self.slo.to_state() if self.slo is not None else None,
            # The ladder's full decision state (targets, clamp latch,
            # cooldown, trough streak): a SIGKILL mid-resize resumes
            # with the autoscaler context restored bit-exactly.
            "autoscaler": (
                self.autoscaler.to_state()
                if self.autoscaler is not None
                else None
            ),
        }
        return ctx

    def _should_checkpoint(self) -> bool:
        serve = self.serve
        return (
            serve.checkpoint_dir is not None
            and serve.checkpoint_every > 0
            and self._chunks > 0
            and self._chunks % serve.checkpoint_every == 0
            and self._chunks != self._last_ckpt_chunks
        )

    def _begin_checkpoint(self):
        """Enqueue the alias-free snapshot + capture the host context
        NOW (before the next dispatch mutates epoch/chunks). No
        blocking call."""
        start, t0 = time.time(), time.perf_counter()
        snap = checkpoint_mod.snapshot_tree(
            {"state": self.state, "t": self.t}
        )
        self._pending_ckpt = (snap, self._host_context())
        self._last_ckpt_chunks = self._chunks
        self._span("checkpoint:snapshot", start, t0,
                   step=self._ckpt_step)

    def _finish_checkpoint(self):
        """Drain the pending snapshot to a versioned on-disk checkpoint
        (write-to-temp-then-rename, per-leaf checksums) — called right
        after the NEXT chunk dispatches. The device_get waits only for
        the alias-free copy (already finished or finishing behind the
        checkpointed chunk); the serialization + disk write then runs
        on a WRITER THREAD so it overlaps the new chunk's compute
        instead of delaying its successor's dispatch. At most one
        writer is in flight (joined here and at shutdown), so steps
        land on disk in order."""
        snap, ctx = self._pending_ckpt
        self._pending_ckpt = None
        # The pull waits only for the alias-free copy (enqueued behind
        # the checkpointed chunk — already finished or finishing). On
        # the CPU backend device_get returns zero-copy VIEWS of the XLA
        # buffers, so the writer closure captures ``snap`` too: the jax
        # Arrays stay strongly referenced until the write lands, and
        # the buffers the views point into cannot be reclaimed under
        # the writer thread (the snapshot is never donated — dropping
        # the last reference is the only way they'd be freed). The big
        # flatten/serialize work stays OFF the loop thread so it
        # overlaps the next chunk's compute.
        host = jax.device_get(snap)
        tick = int(host["t"])
        meta = {
            "config_hash": checkpoint_mod.config_fingerprint(
                self.mod, self.cfg
            ),
            "backend": self.mod.__name__.rsplit(".", 1)[-1],
            "tick": tick,
            "chunk_ticks": self.serve.chunk_ticks,
            "telemetry_window": telemetry_mod.window(
                host["state"].telemetry
            ),
            "spans": telemetry_mod.span_slots(host["state"].telemetry),
            "host": ctx,
        }
        step = self._ckpt_step
        self._ckpt_step += 1
        self._join_ckpt_writer()

        def write(_snap_keepalive=snap):
            # The writer touches NO loop-thread state directly: its
            # span and any error are stashed and merged by the loop
            # thread at join time (a direct host_spans.append would
            # race _drain's scrape cursor and drop spans from the CSV).
            start, t0 = time.time(), time.perf_counter()
            try:
                leaves = checkpoint_mod.flatten_state(host["state"])
                leaves["__t__"] = host["t"]
                checkpoint_mod.save_checkpoint(
                    self.serve.checkpoint_dir,
                    leaves=leaves,
                    meta=meta,
                    step=step,
                    keep=self.serve.checkpoint_keep,
                )
            except BaseException as e:  # noqa: BLE001 — a durability
                # failure (ENOSPC, lost permissions, torn dir) must
                # surface in the report, not die silently with the
                # daemon thread.
                self._ckpt_writer_result = (
                    None, f"checkpoint step {step}: {e!r}"
                )
                return
            self._ckpt_writer_result = (
                {
                    "name": "checkpoint:write",
                    "start_unix": start,
                    "duration_s": time.perf_counter() - t0,
                    "step": step,
                    "tick": tick,
                },
                None,
            )

        import threading

        self._ckpt_writer_result = None
        self._ckpt_writer = threading.Thread(
            target=write, name=f"ckpt-writer-{step}", daemon=True
        )
        self._ckpt_writer.start()

    def _join_ckpt_writer(self):
        writer = getattr(self, "_ckpt_writer", None)
        if writer is not None:
            writer.join()
            self._ckpt_writer = None
            span, err = (
                getattr(self, "_ckpt_writer_result", None) or (None, None)
            )
            self._ckpt_writer_result = None
            if err is not None:
                self.checkpoint_errors.append(err)
                print(f"serve: checkpoint write FAILED: {err}",
                      file=sys.stderr)
            elif span is not None:
                self.checkpoints_written += 1
                self.host_spans.append(span)

    @classmethod
    def resume(
        cls,
        mod,
        cfg,
        serve: ServeConfig,
        ckpt_dir: Optional[str] = None,
    ) -> "ServeLoop":
        """Restore the newest VALID checkpoint (torn/corrupt/stale
        manifests are skipped — the automatic fallback) and return a
        loop that continues the run bit-exactly: State, tick, PRNG
        position, drain cursors, and the SLO/clamp context all resume
        where the checkpoint froze them. The restored state reuses the
        template's exact dtypes/shapes, so in-process the next
        run_ticks hits the existing jit cache (the
        ``trace-checkpoint-restore`` rule); across a process restart
        the one cold-start compile is the only compile."""
        ckpt_dir = ckpt_dir or serve.checkpoint_dir
        assert ckpt_dir, "resume needs a checkpoint directory"
        found = checkpoint_mod.latest_valid(
            ckpt_dir,
            config_hash=checkpoint_mod.config_fingerprint(mod, cfg),
        )
        if found is None:
            raise checkpoint_mod.CheckpointError(
                f"no valid checkpoint for this config under {ckpt_dir}"
            )
        manifest, arrays = found
        ctx = manifest["host"]
        self = cls(mod, cfg, serve, seed=int(ctx["seed"]))
        # Per-chunk PRNG keys are fold_in(seed, epoch): a different
        # chunk size would replay the SAME key sequence over a
        # different tick stream and silently diverge from the twin.
        assert manifest["chunk_ticks"] == serve.chunk_ticks, (
            f"resume chunk_ticks {serve.chunk_ticks} != checkpointed "
            f"{manifest['chunk_ticks']} — bit-exact replay needs the "
            "same chunking"
        )
        assert manifest["telemetry_window"] == telemetry_mod.window(
            self.state.telemetry
        ) and manifest["spans"] == telemetry_mod.span_slots(
            self.state.telemetry
        ), "serve telemetry sizing differs from the checkpointed run"
        t_arr = arrays.pop("__t__")
        self.state = checkpoint_mod.restore_leaves(self.state, arrays)
        self.t = jnp.asarray(t_arr, jnp.int32)
        self._epoch = int(ctx["epoch"])
        self._chunks = int(ctx["chunks"])
        self._last_ckpt_chunks = self._chunks
        self._ckpt_step = int(ctx["ckpt_step"]) + 1
        self.checkpoints_written = 0
        self.cursor = telemetry_mod.DrainCursor(
            tick=int(ctx["cursor_tick"]), span=int(ctx["cursor_span"])
        )
        prev = ctx.get("prev") or {}
        import numpy as _np

        self._prev = {
            k: (_np.asarray(v) if isinstance(v, list) else v)
            for k, v in prev.items()
        }
        if self.slo is not None and ctx.get("slo") is not None:
            self.slo.restore_state(ctx["slo"])
        if (
            self.autoscaler is not None
            and ctx.get("autoscaler") is not None
        ):
            self.autoscaler.restore_state(ctx["autoscaler"])
        # The checkpoint froze the loop BETWEEN chunks: the last chunk's
        # telemetry was still undrained (its rows sit in the restored
        # ring, ahead of the restored cursor), so re-snapshot it as the
        # pending drain — chunked drains stay EXACT across the restart.
        self._resume_snap = _SNAP(snapshot_leaves(self.state))
        self.restores = 1
        self.resumed_from = {
            "step": int(manifest["step"]),
            "tick": int(manifest["tick"]),
            "chunks": self._chunks,
            "skipped": manifest.get("skipped", []),
        }
        # Restart marker: an instant event on the Perfetto timeline
        # (host track) + a span so the scrape CSV records it too.
        self._span("restore", time.time(), time.perf_counter(),
                   instant=True, step=int(manifest["step"]),
                   tick=int(manifest["tick"]))
        return self

    # -- the hot path -------------------------------------------------------

    def _dispatch_chunk(self):
        """Dispatch one chunk + enqueue its telemetry snapshot; returns
        the snapshot (a pytree of futures). NO blocking call here."""
        key = jax.random.fold_in(self.key, self._epoch)
        self._epoch += 1
        start, t0 = time.time(), time.perf_counter()
        with jax.profiler.TraceAnnotation("serve:dispatch"):
            self.state, self.t = self.mod.run_ticks(
                self.cfg, self.state, self.t, self.serve.chunk_ticks, key
            )
            snap = _SNAP(snapshot_leaves(self.state))
        self._span(
            "dispatch", start, t0,
            num_ticks=self.serve.chunk_ticks,
            compile=self._chunks == 0,
        )
        self._chunks += 1
        return snap

    def _drain(self, snap) -> dict:
        """Drain one chunk's snapshot (the ONLY device_get on the hot
        path — and only ever on a snapshot, never on the live state)."""
        start, t0 = time.time(), time.perf_counter()
        with jax.profiler.TraceAnnotation("serve:drain"):
            host = jax.device_get(snap)
        drain = self.cursor.drain(host["telemetry"])
        self._span("drain", start, t0, ticks=drain["ticks_total"])
        self.clock.add_mark(drain["ticks_total"], time.time())
        self.spans.extend(drain["spans"])

        # Streaming consumers: SLO engine + admission control plane.
        if self.slo is not None:
            prev = self._prev
            lat = drain["lat_hist"]
            wait = host["wait_hist"]
            offered = (
                int(host["offered"]) if host["offered"].size else 0
            )
            shed = int(host["shed"]) if host["shed"].size else 0
            status = self.slo.observe(
                lat_hist_delta=lat - prev.get("lat", 0),
                wait_hist_delta=(
                    wait - prev.get("wait", 0) if wait.size else None
                ),
                offered_delta=offered - prev.get("offered", 0),
                shed_delta=shed - prev.get("shed", 0),
            )
            self._prev = {
                "lat": lat, "wait": wait, "offered": offered,
                "shed": shed,
            }
            drain["slo"] = status
            scale = self.slo.scale
            if self.autoscaler is not None:
                # The graceful-degradation LADDER sits between the
                # alarm and the clamp: an alarm first GROWS the
                # bottleneck role's traced count (resize verb — zero
                # recompiles); the admission clamp binds only once
                # every padded role plane is exhausted
                # (decision["effective_scale"] stays 1.0 until then);
                # recovery releases the clamp before any role shrinks.
                decision = self.autoscaler.decide(status)
                drain["autoscaler"] = decision
                for act in decision["actions"]:
                    self.resize(act["role"], act["to"])
                scale = decision["effective_scale"]
            if self._base_rate is not None:
                # The control-plane hook: clamp/recover the offered
                # rate through the TRACED state scalar — the same
                # compiled program keeps running.
                self.state = dataclasses.replace(
                    self.state,
                    workload=workload_mod.set_rate(
                        self.state.workload,
                        self._base_rate * scale,
                    ),
                )
        if self.serve.scrape_csv:
            scrape_mod.append_device_samples(
                self.serve.scrape_csv, host["telemetry"],
                instance="serve",
            )
            # Every span exactly once (a fixed [-2:] window would skip
            # the compile-marked first dispatch and double-write the
            # previous drain at shutdown).
            scrape_mod.append_host_spans(
                self.serve.scrape_csv,
                self.host_spans[self._spans_scraped:],
                instance="serve",
            )
            self._spans_scraped = len(self.host_spans)
            if self.autoscaler is not None:
                # Capacity events, exactly once each (the host-span
                # cursor discipline).
                scrape_mod.append_capacity_events(
                    self.serve.scrape_csv,
                    self.autoscaler.events[self._cap_scraped:],
                    instance="serve",
                )
                self._cap_scraped = len(self.autoscaler.events)
            # Efficiency gauges: this drain's observed commits/tick
            # against the cost model's expected rate for the config.
            if self._model_rate > 0.0:
                ticks = drain["ticks_total"]
                commits = drain["totals"]["commits"]
                pt, pc = self._eff_prev
                self._eff_prev = (ticks, commits)
                if ticks > pt:
                    scrape_mod.append_efficiency_samples(
                        self.serve.scrape_csv,
                        observed_per_tick=(commits - pc) / (ticks - pt),
                        predicted_per_tick=self._model_rate,
                        params=costmodel.CPU_JIT.name,
                        instance="serve",
                    )
        self.drains.append(drain)
        return drain

    def run(self) -> dict:
        """Serve until the configured bound, then shut down cleanly
        (final drain + trace export). Returns the serve report."""
        serve = self.serve
        deadline = (
            time.monotonic() + serve.max_seconds
            if serve.max_seconds is not None
            else None
        )
        start_wall = time.perf_counter()
        self.clock.add_mark(int(jax.device_get(self.t)), time.time())
        prev_snap = self._resume_snap  # pending drain after a resume
        self._resume_snap = None
        while True:
            if serve.max_chunks is not None and (
                self._chunks >= serve.max_chunks
            ):
                break
            if deadline is not None and time.monotonic() >= deadline:
                break
            if self._should_checkpoint():
                # Enqueue the alias-free state copy BEFORE the next
                # dispatch (it snapshots the exact input of the next
                # chunk); the disk write happens after the dispatch so
                # it overlaps that chunk's compute.
                self._begin_checkpoint()
            snap = self._dispatch_chunk()
            if self._pending_ckpt is not None:
                self._finish_checkpoint()
            if prev_snap is not None:
                self._drain(prev_snap)
            prev_snap = snap
        # Shutdown: the last snapshot drains AFTER its chunk completes
        # (the one place a wait is correct), the in-flight checkpoint
        # writer lands (durability before clean_shutdown), then the
        # trace exports.
        if prev_snap is not None:
            self._drain(prev_snap)
        self._join_ckpt_writer()
        jax.block_until_ready(self.state)
        wall = time.perf_counter() - start_wall
        self.clean_shutdown = True
        if serve.trace_path:
            traceviz.write_chrome_trace(
                serve.trace_path,
                device_spans=self.spans,
                host_spans=self.host_spans,
                clock=self.clock,
            )
        return self.report(wall)

    def report(self, wall_s: float) -> dict:
        ticks = self.cursor.tick
        totals = (
            self.drains[-1]["totals"] if self.drains else {}
        )
        out = {
            "backend": self.mod.__name__.rsplit(".", 1)[-1].replace(
                "_batched", ""
            ),
            "chunks": self._chunks,
            "chunk_ticks": self.serve.chunk_ticks,
            "ticks": ticks,
            "wall_s": round(wall_s, 4),
            "ticks_per_sec": round(ticks / wall_s, 2) if wall_s else 0.0,
            "dropped_ticks": sum(
                d["dropped_ticks"] for d in self.drains
            ),
            "dropped_spans": sum(
                d["dropped_spans"] for d in self.drains
            ),
            "spans_exported": len(self.spans),
            "totals": totals,
            "clean_shutdown": self.clean_shutdown,
        }
        if self.serve.checkpoint_dir is not None:
            out["checkpoints_written"] = self.checkpoints_written
            out["checkpoint_dir"] = self.serve.checkpoint_dir
            # Durability failures surface HERE (and on stderr at join
            # time) — a serve run whose writer died of ENOSPC must not
            # read as healthily checkpointed.
            out["checkpoint_errors"] = list(self.checkpoint_errors)
        if self.resumed_from is not None:
            out["resumed_from"] = self.resumed_from
        if self.slo is not None:
            out["slo"] = self.slo.summary()
        if self.autoscaler is not None:
            out["autoscaler"] = self.autoscaler.summary()
        eplan = getattr(self.cfg, "elastic", None)
        if eplan is not None and eplan.active:
            # Device-side resize roll-up (the run is already synced at
            # shutdown, so this tiny pull is off the hot path).
            out["elastic"] = elastic_mod.summary(
                eplan, self.state.elastic
            )
        lc_plan = getattr(self.cfg, "lifecycle", None)
        if lc_plan is not None and lc_plan.active:
            # Rotation / session-table / reconfiguration roll-up (one
            # coalesced pull of the tiny lifecycle leaves; the run is
            # already synced at shutdown).
            out["lifecycle"] = lifecycle_mod.summary(
                lc_plan, self.state.lifecycle
            )
        if self.serve.trace_path:
            out["trace_path"] = self.serve.trace_path
        if self.serve.scrape_csv:
            out["scrape_csv"] = self.serve.scrape_csv
        return out


# ---------------------------------------------------------------------------
# Fleet serving: the observability plane over a PR 14 fleet brick.
# ---------------------------------------------------------------------------
# A fleet brick (parallel/sharding.py: dozens of independent protocol
# instances vmapped into ONE compiled executable) used to be a black
# box — final invariant reductions and nothing else. FleetServeLoop
# extends the double-buffered non-blocking drain discipline to the
# brick: dispatch chunk i (run_ticks_fleet, donated), enqueue the
# jitted FLEET snapshot behind it (an aliased-nothing copy of the
# whole [F, K, cols] ring block plus the in-graph per-instance
# fleet_summary + straggler flags), drain chunk i-1 while i computes —
# ONE block_until_ready total, at shutdown. Per-instance SloEngines
# evaluate the drained per-instance histogram deltas and drive
# PER-INSTANCE admission clamps through the fleet-sharded traced
# WorkloadState.rate (sharding.set_fleet_rates — zero recompiles, the
# jit cache stays flat), closing the loop from "instance 7 is
# saturating" to "instance 7 got clamped" without touching its
# siblings. The ``trace-fleet-drain-nosync`` analysis rule pins the
# compiled shape of all of it (no host callbacks, snapshot aliases
# nothing, summary collectives bounded, clamp re-entry cache-flat).


@dataclasses.dataclass(frozen=True)
class FleetServeConfig:
    """Fleet serve-mode knobs (the fleet twin of :class:`ServeConfig`;
    spans stay a single-instance feature — the reservoir sampler is
    per-instance state the fleet loop does not size)."""

    chunk_ticks: int = 32
    telemetry_window: int = telemetry_mod.TELEM_WINDOW
    slo: Optional[SloPolicy] = None
    scrape_csv: Optional[str] = None
    trace_path: Optional[str] = None
    max_chunks: Optional[int] = None
    max_seconds: Optional[float] = None
    # True: the snapshot carries the full per-instance rings and every
    # drain is EXACT (DrainCursor per instance — the scrape CSV gets
    # per-tick rows). False: summary-only drains — the host pulls the
    # O(F) summary vectors + small gauges per chunk, the scalable mode
    # for wide fleets.
    drain_rings: bool = True
    # Straggler test knobs (telemetry.fleet_summary): k x MAD deviation
    # from the fleet median, plus the optional analytical expected-rate
    # anchor (commits/tick/instance; 0 = off). The MAD test presumes a
    # HOMOGENEOUS fleet (same plan rate per instance) — heterogeneous
    # offered loads make deviation the expected signal, not an anomaly.
    k_mad: int = 4
    expected_rate_per_tick: float = 0.0
    # Where the straggler anchor comes from: "manual" (the hand-fed
    # expected_rate_per_tick constant above — the PR 15 behavior, and
    # what partial-load tests pin) or "model" (ops/costmodel.py
    # derives commits/tick/instance from the backend config at loop
    # construction; expected_rate_per_tick is then ignored). The
    # production entry point (serve_fleet) uses "model".
    expected_rate_source: str = "manual"

    def __post_init__(self):
        assert self.chunk_ticks >= 1
        assert self.telemetry_window >= self.chunk_ticks, (
            "telemetry_window must cover a chunk or drains drop ticks"
        )
        assert self.max_chunks is not None or self.max_seconds is not None, (
            "bound the loop with max_chunks and/or max_seconds"
        )
        assert self.k_mad >= 1
        assert self.expected_rate_per_tick >= 0.0
        assert self.expected_rate_source in ("manual", "model")


@functools.lru_cache(maxsize=None)
def _fleet_snap_fn(k_mad: int, expected_x1000: int, rings: bool):
    """The jitted fleet snapshot program, cached per knob tuple so
    every FleetServeLoop with the same knobs shares ONE executable per
    shape: an aliased-nothing copy of the small per-instance gauges
    (+ the full rings when ``rings``) plus the in-graph
    ``telemetry.fleet_summary`` reduction — the only cross-instance
    collectives a product mesh sees are its tiny median/MAD sorts,
    bounded by the ``trace-fleet-drain-nosync`` census."""

    @jax.jit
    def snap(leaves):
        tel = leaves["telemetry"]
        out = {
            "summary": telemetry_mod.fleet_summary(
                tel,
                wait_hist=leaves["wait_hist"],
                shed=leaves["shed"],
                k_mad=k_mad,
                expected_rate_x1000=expected_x1000,
            )
        }
        small = {
            "ticks": tel.ticks,
            "totals": tel.totals,
            "lat_hist": tel.lat_hist,
            "wait_hist": leaves["wait_hist"],
            "offered": leaves["offered"],
            "shed": leaves["shed"],
        }
        out.update(_copy_tree(small))
        if rings:
            out["telemetry"] = _copy_tree(tel)
        return out

    return snap


def lower_fleet_chunk_path(
    backend: str,
    cfg,
    mesh,
    n: int = 4,
    chunk_ticks: int = 4,
    rates=None,
    fault_rates=None,
    k_mad: int = 4,
    rings: bool = True,
):
    """Lower the two compiled artifacts of the FLEET serve hot path —
    (run_ticks_fleet, fleet snapshot) — for inspection. The
    ``trace-fleet-drain-nosync`` analysis rule compiles these; keeping
    the hook HERE means the rule checks exactly what the loop runs."""
    from frankenpaxos_tpu.parallel import sharding as sharding_mod

    states = sharding_mod.fleet_states(
        backend, cfg, n, rates=rates, fault_rates=fault_rates
    )
    if mesh is not None:
        states = sharding_mod.shard_fleet_state(backend, states, mesh)
    keys = sharding_mod.place_fleet_keys(
        sharding_mod.fleet_keys(range(n)), mesh
    )
    run_lowered = sharding_mod.lower_fleet(
        backend, cfg, mesh, states, jnp.zeros((), jnp.int32),
        chunk_ticks, keys,
    )
    snap_lowered = _fleet_snap_fn(k_mad, 0, rings).lower(
        snapshot_leaves(states)
    )
    return run_lowered, snap_lowered


class FleetServeLoop:
    """A long-lived serve driver over one FLEET brick of a
    sharding-registry backend: ``n`` independent instances with
    per-instance seeds / traced offered rates / traced fault rates,
    dispatched through ``parallel.sharding.run_ticks_fleet`` (ONE
    compiled executable per mesh) with the non-blocking drain
    discipline and a per-instance SLO control plane. Instance i of the
    fleet replays EXACTLY the program ``ServeLoop(seed=seeds[i])``
    replays at the same traced rates (the PR 14 bit-identity contract
    extended to the drains — pinned by ``tests/test_fleet.py``)."""

    def __init__(
        self,
        backend: str,
        cfg,
        fleet: FleetServeConfig,
        n: int,
        seeds=None,
        rates=None,
        fault_rates=None,
        mesh=None,
    ):
        from frankenpaxos_tpu.parallel import sharding as sharding_mod

        self.sharding = sharding_mod
        self.backend = backend
        self.mod = sharding_mod.SHARDINGS[backend].mod()
        self.cfg = cfg
        self.fleet = fleet
        self.n = int(n)
        self.mesh = mesh
        self.seeds = list(seeds) if seeds is not None else list(range(n))
        assert len(self.seeds) == self.n
        base = self.mod.init_state(cfg)
        base = dataclasses.replace(
            base,
            telemetry=telemetry_mod.make_telemetry(
                fleet.telemetry_window
            ),
        )
        self.states = sharding_mod.fleet_states(
            backend, cfg, self.n, rates=rates, fault_rates=fault_rates,
            base=base,
        )
        if mesh is not None:
            sharding_mod.validate_policy(backend, cfg, mesh)
            self.states = sharding_mod.shard_fleet_state(
                backend, self.states, mesh
            )
        self.base_keys = sharding_mod.place_fleet_keys(
            sharding_mod.fleet_keys(self.seeds), mesh
        )
        self.t = jnp.zeros((), jnp.int32)
        self.base_rates = (
            [float(r) for r in rates] if rates is not None else None
        )
        # Fleet elasticity: the brick's F instances ARE the padded
        # role plane; activation is the traced per-instance rate
        # vector (set_active_instances redistributes the total offered
        # load over the first k instances, zeroing the tail).
        self._active_n = self.n
        self._effective_rates = (
            list(self.base_rates) if self.base_rates is not None else None
        )
        # The straggler anchor: either the hand-fed constant or the
        # cost model's expected commits/tick for this backend config
        # (capped by the slowest instance's offered rate when the fleet
        # runs heterogeneous plans — the anchor must not flag an
        # instance for committing exactly what it was offered).
        if fleet.expected_rate_source == "model":
            self._expected_rate = costmodel.expected_commit_rate_per_tick(
                cfg
            )
            if self.base_rates and self._expected_rate > 0.0:
                G = getattr(cfg, "num_groups", 0) or 0
                self._expected_rate = min(
                    self._expected_rate, min(self.base_rates) * G
                )
        else:
            self._expected_rate = fleet.expected_rate_per_tick
        self._snap = _fleet_snap_fn(
            fleet.k_mad,
            int(round(self._expected_rate * 1000)),
            fleet.drain_rings,
        )
        self.cursor = telemetry_mod.DrainCursor()
        self.clock = traceviz.TickClock()
        self.host_spans: List[dict] = []
        self.drains: List[dict] = []
        self.markers: List[dict] = []  # per-instance alarm/clamp marks
        self.straggler_drains: List[List[int]] = []  # flags per drain
        self.slo: Optional[FleetSloEngine] = (
            FleetSloEngine(fleet.slo, self.n) if fleet.slo else None
        )
        self._prev: List[Dict[str, Any]] = [{} for _ in range(self.n)]
        self._spans_scraped = 0
        self._chunks = 0
        self._epoch = 0
        self.clean_shutdown = False

    def _span(self, name: str, start_unix: float, t0: float, **meta):
        self.host_spans.append(
            {
                "name": name,
                "start_unix": start_unix,
                "duration_s": time.perf_counter() - t0,
                **meta,
            }
        )

    def set_rates(self, rates):
        """The per-instance control-plane verb: a new [n] traced-rate
        vector, same compiled executable (sharding.set_fleet_rates)."""
        self.states = self.sharding.set_fleet_rates(
            self.states, rates, self.mesh
        )
        self._span("verb:set_rates", time.time(), time.perf_counter())

    def set_active_instances(self, k: int):
        """Fleet elasticity over the padded instance axis: serve the
        whole fleet's offered load from the first ``k`` instances
        (instance i >= k gets traced rate 0 — deactivated but still
        ticking bit-live, so scaling back up is the same verb). The
        rate redistribution rides ``sharding.set_fleet_rates`` — the
        ONE compiled executable per mesh never changes, and the
        per-instance SLO clamps keep multiplying into the NEW
        effective rates on every drain."""
        assert self.base_rates is not None, (
            "fleet elasticity needs explicit base rates"
        )
        k = int(k)
        assert 1 <= k <= self.n
        prev = self._active_n
        self._active_n = k
        total = sum(self.base_rates)
        self._effective_rates = [
            (total / k if i < k else 0.0) for i in range(self.n)
        ]
        scales = (
            self.slo.scales if self.slo is not None else [1.0] * self.n
        )
        self.states = self.sharding.set_fleet_rates(
            self.states,
            [r * s for r, s in zip(self._effective_rates, scales)],
            self.mesh,
        )
        tick = (
            self.drains[-1]["ticks_total"] if self.drains else 0
        )
        if k != prev:
            self.markers.append({
                "instance": -1, "tick": tick,
                "kind": "scale_up" if k > prev else "scale_down",
                "from": prev, "to": k,
            })
        self._span("verb:set_active_instances", time.time(),
                   time.perf_counter(), instant=True, to=k)

    # -- the hot path -------------------------------------------------------

    def _dispatch_chunk(self):
        """Dispatch one fleet chunk + enqueue its snapshot; NO blocking
        call here (the run_ticks_fleet donation rebinds the states, the
        snapshot copies what the drain will read)."""
        keys = jax.vmap(jax.random.fold_in, in_axes=(0, None))(
            self.base_keys, self._epoch
        )
        self._epoch += 1
        start, t0 = time.time(), time.perf_counter()
        with jax.profiler.TraceAnnotation("fleet-serve:dispatch"):
            self.states, self.t = self.sharding.run_ticks_fleet(
                self.backend, self.cfg, self.mesh, self.states, self.t,
                self.fleet.chunk_ticks, keys,
            )
            snap = self._snap(snapshot_leaves(self.states))
        self._span(
            "dispatch", start, t0,
            num_ticks=self.fleet.chunk_ticks,
            compile=self._chunks == 0,
        )
        self._chunks += 1
        return snap

    def _drain(self, snap) -> dict:
        """Drain one fleet chunk's snapshot: the only device_get on the
        hot path — O(F) summary scalars + small gauges (plus the rings
        when ``drain_rings``), never the protocol state."""
        import numpy as np

        start, t0 = time.time(), time.perf_counter()
        with jax.profiler.TraceAnnotation("fleet-serve:drain"):
            host = jax.device_get(snap)
        summary = np.asarray(host["summary"])
        ticks_total = int(np.max(host["ticks"]))
        drain: Dict[str, Any] = {
            "ticks_total": ticks_total,
            "summary": [
                telemetry_mod.summary_row_dict(summary[i])
                for i in range(self.n)
            ],
            "stragglers": [
                i
                for i in range(self.n)
                if summary[i][telemetry_mod.SUMMARY_COL["straggler"]]
            ],
            "dropped_ticks": 0,
        }
        if self.fleet.drain_rings:
            ring = self.cursor.drain(host["telemetry"])
            drain["instances"] = ring["instances"]
            drain["dropped_ticks"] = ring["dropped_ticks"]
        self._span("drain", start, t0, ticks=ticks_total)
        self.clock.add_mark(ticks_total, time.time())
        self.straggler_drains.append(drain["stragglers"])

        # Per-instance SLO -> per-instance clamp (the control plane).
        if self.slo is not None:
            per = []
            for i in range(self.n):
                prev = self._prev[i]
                lat = np.asarray(host["lat_hist"][i])
                wait = np.asarray(host["wait_hist"][i])
                offered = (
                    int(host["offered"][i])
                    if np.size(host["offered"][i])
                    else 0
                )
                shed = (
                    int(host["shed"][i])
                    if np.size(host["shed"][i])
                    else 0
                )
                per.append(dict(
                    lat_hist_delta=lat - prev.get("lat", 0),
                    wait_hist_delta=(
                        wait - prev.get("wait", 0) if wait.size else None
                    ),
                    offered_delta=offered - prev.get("offered", 0),
                    shed_delta=shed - prev.get("shed", 0),
                ))
                self._prev[i] = {
                    "lat": lat, "wait": wait, "offered": offered,
                    "shed": shed,
                }
            statuses = self.slo.observe(per)
            drain["slo"] = statuses
            for i, st in enumerate(statuses):
                if st["fired"]:
                    self.markers.append({
                        "instance": i, "tick": ticks_total,
                        "kind": "alarm", "p99": st["p99"],
                    })
                if st["cleared"]:
                    self.markers.append({
                        "instance": i, "tick": ticks_total,
                        "kind": "clear",
                    })
            if self.base_rates is not None:
                scales = self.slo.scales
                if any(s < 1.0 for s in scales):
                    for i, st in enumerate(statuses):
                        if st["alarm"] and st["scale"] < 1.0:
                            self.markers.append({
                                "instance": i, "tick": ticks_total,
                                "kind": "clamp",
                                "scale": st["scale"],
                            })
                # One state-side vector update per drain (also when a
                # scale RECOVERS toward 1.0) — never a recompile. The
                # effective rates fold in any set_active_instances
                # redistribution on top of the base rates.
                self.states = self.sharding.set_fleet_rates(
                    self.states,
                    [
                        r * s
                        for r, s in zip(self._effective_rates, scales)
                    ],
                    self.mesh,
                )

        if self.fleet.scrape_csv:
            ts = time.time()
            scrape_mod.append_fleet_summary(
                self.fleet.scrape_csv, drain["summary"], ts=ts,
                scales=(self.slo.scales if self.slo else None),
            )
            if self.fleet.drain_rings:
                for i in range(self.n):
                    scrape_mod.append_device_samples(
                        self.fleet.scrape_csv,
                        telemetry_mod.instance_view(
                            host["telemetry"], i
                        ),
                        instance=str(i),
                        ts=ts,
                    )
            scrape_mod.append_host_spans(
                self.fleet.scrape_csv,
                self.host_spans[self._spans_scraped:],
                instance="fleet",
            )
            self._spans_scraped = len(self.host_spans)
            # Per-instance efficiency gauges against the straggler
            # anchor (model-fed or manual; 0 = anchor off, gauges off).
            # The summary's windowed commit rate is already x1000.
            if self._expected_rate > 0.0:
                for i, row in enumerate(drain["summary"]):
                    scrape_mod.append_efficiency_samples(
                        self.fleet.scrape_csv,
                        observed_per_tick=(
                            row["commit_rate_x1000"] / 1000.0
                        ),
                        predicted_per_tick=self._expected_rate,
                        params=costmodel.CPU_JIT.name,
                        job="fleet",
                        instance=str(i),
                        ts=ts,
                    )
        self.drains.append(drain)
        return drain

    def run(self) -> dict:
        """Serve until the configured bound, then shut down cleanly
        (final drain + ONE block_until_ready + trace export)."""
        fleet = self.fleet
        deadline = (
            time.monotonic() + fleet.max_seconds
            if fleet.max_seconds is not None
            else None
        )
        start_wall = time.perf_counter()
        self.clock.add_mark(0, time.time())
        prev_snap = None
        while True:
            if fleet.max_chunks is not None and (
                self._chunks >= fleet.max_chunks
            ):
                break
            if deadline is not None and time.monotonic() >= deadline:
                break
            snap = self._dispatch_chunk()
            if prev_snap is not None:
                self._drain(prev_snap)
            prev_snap = snap
        if prev_snap is not None:
            self._drain(prev_snap)
        jax.block_until_ready(self.states)
        wall = time.perf_counter() - start_wall
        self.clean_shutdown = True
        if fleet.trace_path:
            traceviz.write_chrome_trace(
                fleet.trace_path,
                host_spans=self.host_spans,
                clock=self.clock,
                extra_events=(
                    traceviz.fleet_metadata_events(self.n)
                    + traceviz.fleet_marker_events(
                        self.markers, self.clock
                    )
                ),
            )
        return self.report(wall)

    def report(self, wall_s: float) -> dict:
        last = self.drains[-1] if self.drains else {}
        flagged = sorted({
            i for flags in self.straggler_drains for i in flags
        })
        out = {
            "backend": self.backend,
            "instances": self.n,
            "mesh": (
                None
                if self.mesh is None
                else [int(s) for s in dict(self.mesh.shape).values()]
            ),
            "chunks": self._chunks,
            "chunk_ticks": self.fleet.chunk_ticks,
            "ticks": last.get("ticks_total", 0),
            "wall_s": round(wall_s, 4),
            "dropped_ticks": sum(
                d["dropped_ticks"] for d in self.drains
            ),
            "summary": last.get("summary", []),
            "stragglers_flagged": flagged,
            "markers": list(self.markers),
            "active_instances": self._active_n,
            "clean_shutdown": self.clean_shutdown,
        }
        if self.slo is not None:
            out["slo"] = self.slo.summary()
        if self.fleet.trace_path:
            out["trace_path"] = self.fleet.trace_path
        if self.fleet.scrape_csv:
            out["scrape_csv"] = self.fleet.scrape_csv
        return out


def serve_fleet(
    seconds: float = 10.0,
    out_dir: str = ".",
    n: int = 4,
    num_groups: int = 64,
    chunk_ticks: int = 32,
    rate_x: float = 1.0,
    slo_p99: Optional[int] = None,
    hostile_instance: Optional[int] = None,
    hostile_drop: float = 0.5,
    seed: int = 0,
    window: int = 16,
    slots_per_tick: int = 2,
    max_chunks: Optional[int] = None,
) -> dict:
    """A bounded FLEET serve run of the flagship backend — the CLI +
    smoke entry point (``--fleet N``). All instances serve the same
    shaped plan at ``rate_x`` x the nominal per-lane admission rate
    (homogeneous, so the straggler test is meaningful);
    ``hostile_instance`` gives ONE instance a hostile traced drop rate
    — the differential-failure demo the fleet observability plane
    exists for."""
    from frankenpaxos_tpu.tpu import multipaxos_batched as mp
    from frankenpaxos_tpu.tpu.faults import FaultPlan

    plan_rate = rate_x * slots_per_tick
    cfg = mp.BatchedMultiPaxosConfig(
        f=1, num_groups=num_groups, window=window,
        slots_per_tick=slots_per_tick, retry_timeout=16,
        workload=workload_mod.WorkloadPlan(
            arrival="constant", rate=plan_rate, backlog_cap=256,
        ),
        faults=FaultPlan(traced=True),
    )
    frates = [[0.0, 0.0, 0.0, 0.0] for _ in range(n)]
    if hostile_instance is not None:
        assert 0 <= hostile_instance < n
        frates[hostile_instance][0] = hostile_drop
    os.makedirs(out_dir, exist_ok=True)
    fleet_cfg = FleetServeConfig(
        chunk_ticks=chunk_ticks,
        telemetry_window=max(
            chunk_ticks * 2, telemetry_mod.TELEM_WINDOW
        ),
        slo=(
            SloPolicy(p99_target_ticks=slo_p99, source="queue_wait")
            if slo_p99 is not None
            else None
        ),
        scrape_csv=os.path.join(out_dir, "fleet_metrics.csv"),
        trace_path=os.path.join(out_dir, "fleet_trace.json"),
        max_seconds=seconds,
        max_chunks=max_chunks,
        # Production path: the straggler anchor comes from the cost
        # model (capped by the offered plan rate inside the loop), not
        # a hand-fed constant.
        expected_rate_source="model",
    )
    loop = FleetServeLoop(
        "multipaxos", cfg, fleet_cfg, n,
        seeds=[seed + i for i in range(n)],
        rates=[plan_rate] * n,
        fault_rates=frates,
    )
    report = loop.run()
    with open(os.path.join(out_dir, "fleet_report.json"), "w") as f:
        json.dump(report, f, indent=1)
    return report


def serve_flagship(
    seconds: float = 10.0,
    out_dir: str = ".",
    num_groups: int = 64,
    chunk_ticks: int = 32,
    spans: int = 16,
    rate_x: Optional[float] = None,
    slo_p99: Optional[int] = None,
    seed: int = 0,
    window: int = 32,
    slots_per_tick: int = 4,
    max_chunks: Optional[int] = None,
    rotate_every: int = 0,
    sessions: int = 0,
    resubmit_rate: float = 0.0,
    session_ttl: int = 0,
    reconfig: bool = False,
    checkpoint_every: int = 0,
    resume: bool = False,
) -> dict:
    """A bounded serve run of the flagship MultiPaxos backend — the CLI
    + smoke entry point. ``rate_x`` shapes the workload at that
    multiple of the config's nominal per-lane admission rate (enabling
    the queue-wait histograms the SLO engine reads); ``slo_p99`` arms
    the SLO engine + admission control plane; ``rotate_every`` /
    ``sessions`` / ``reconfig`` engage the production-lifecycle legs
    (tpu/lifecycle.py) — window rotation keeps an unbounded run in a
    constant slot horizon, the session table answers duplicate
    re-submissions from cache, and ``reconfig`` arms the traced
    membership axis the ``reconfigure``/``swap_acceptor`` verbs steer."""
    from frankenpaxos_tpu.tpu import multipaxos_batched as mp
    from frankenpaxos_tpu.tpu.lifecycle import LifecyclePlan

    kw: dict = {}
    if rate_x is not None:
        kw["workload"] = workload_mod.WorkloadPlan(
            arrival="constant",
            rate=rate_x * slots_per_tick,
            backlog_cap=256,
        )
    if rotate_every or sessions or resubmit_rate or session_ttl or reconfig:
        # resubmit_rate/session_ttl included so a lone flag reaches
        # LifecyclePlan.validate and fails LOUDLY (both need sessions)
        # instead of being silently dropped.
        kw["lifecycle"] = LifecyclePlan(
            rotate_every=rotate_every,
            sessions=sessions,
            resubmit_rate=resubmit_rate,
            session_ttl=session_ttl,
            reconfig=reconfig,
        )
    cfg = mp.BatchedMultiPaxosConfig(
        f=1, num_groups=num_groups, window=window,
        slots_per_tick=slots_per_tick, retry_timeout=16, **kw
    )
    os.makedirs(out_dir, exist_ok=True)
    serve_cfg = ServeConfig(
        chunk_ticks=chunk_ticks,
        telemetry_window=max(
            chunk_ticks * 2, telemetry_mod.TELEM_WINDOW
        ),
        spans=spans,
        slo=(
            SloPolicy(p99_target_ticks=slo_p99, source="queue_wait")
            if slo_p99 is not None
            else None
        ),
        scrape_csv=os.path.join(out_dir, "serve_metrics.csv"),
        trace_path=os.path.join(out_dir, "serve_trace.json"),
        max_seconds=seconds,
        max_chunks=max_chunks,
        checkpoint_dir=(
            os.path.join(out_dir, "checkpoints")
            if checkpoint_every
            else None
        ),
        checkpoint_every=checkpoint_every,
    )
    if resume:
        loop = ServeLoop.resume(mp, cfg, serve_cfg)
    else:
        loop = ServeLoop(mp, cfg, serve_cfg, seed=seed)
    report = loop.run()
    with open(os.path.join(out_dir, "serve_report.json"), "w") as f:
        json.dump(report, f, indent=1)
    return report


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    p = argparse.ArgumentParser(prog="frankenpaxos_tpu.harness.serve")
    p.add_argument("--seconds", type=float, default=10.0)
    p.add_argument("--out-dir", default="serve_out")
    p.add_argument("--groups", type=int, default=64)
    p.add_argument("--chunk", type=int, default=32)
    p.add_argument("--spans", type=int, default=16)
    p.add_argument("--rate-x", type=float, default=None)
    p.add_argument("--slo-p99", type=int, default=None)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--rotate-every", type=int, default=0,
                   help="window-rotation quantum in slots (multiple of "
                   "the window; 0 = off)")
    p.add_argument("--sessions", type=int, default=0,
                   help="client session-table sessions per group")
    p.add_argument("--resubmit-rate", type=float, default=0.0)
    p.add_argument("--session-ttl", type=int, default=0,
                   help="demote idle session records after this many "
                   "ticks (0 = only at rotation margin)")
    p.add_argument("--reconfig", action="store_true",
                   help="arm the traced acceptor-membership axis")
    p.add_argument("--checkpoint-every", type=int, default=0,
                   help="async on-disk checkpoint every N chunks "
                   "(tpu/checkpoint.py; 0 = off)")
    p.add_argument("--resume", action="store_true",
                   help="resume from the newest valid checkpoint in "
                   "<out-dir>/checkpoints (bit-exact)")
    p.add_argument("--fleet", type=int, default=0, metavar="N",
                   help="serve an N-instance FLEET brick instead of a "
                   "single instance (FleetServeLoop: per-instance "
                   "telemetry drains, straggler flags, per-instance "
                   "SLO clamps; 0 = single-instance mode)")
    p.add_argument("--hostile-instance", type=int, default=None,
                   help="--fleet only: give this instance a hostile "
                   "traced drop rate (the differential-failure demo)")
    p.add_argument("--hostile-drop", type=float, default=0.5)
    args = p.parse_args(argv)
    if args.fleet:
        # Single-instance-only knobs are rejected loudly instead of
        # silently dropped (spans are per-instance reservoir state the
        # fleet loop does not size; checkpoint/resume stay
        # single-instance features).
        ignored = [
            name for name, on in (
                ("--spans", args.spans != 16),
                ("--checkpoint-every", bool(args.checkpoint_every)),
                ("--resume", args.resume),
                ("--rotate-every", bool(args.rotate_every)),
                ("--sessions", bool(args.sessions)),
                ("--reconfig", args.reconfig),
            ) if on
        ]
        if ignored:
            p.error(
                f"{', '.join(ignored)} are single-instance serve "
                "knobs; drop them for --fleet runs"
            )
        report = serve_fleet(
            seconds=args.seconds,
            out_dir=args.out_dir,
            n=args.fleet,
            num_groups=args.groups,
            chunk_ticks=args.chunk,
            rate_x=(args.rate_x if args.rate_x is not None else 1.0),
            slo_p99=args.slo_p99,
            hostile_instance=args.hostile_instance,
            hostile_drop=args.hostile_drop,
            seed=args.seed,
        )
        print(json.dumps(report))
        return 0 if report["clean_shutdown"] else 1
    report = serve_flagship(
        seconds=args.seconds,
        out_dir=args.out_dir,
        num_groups=args.groups,
        chunk_ticks=args.chunk,
        spans=args.spans,
        rate_x=args.rate_x,
        slo_p99=args.slo_p99,
        seed=args.seed,
        rotate_every=args.rotate_every,
        sessions=args.sessions,
        resubmit_rate=args.resubmit_rate,
        session_ttl=args.session_ttl,
        reconfig=args.reconfig,
        checkpoint_every=args.checkpoint_every,
        resume=args.resume,
    )
    print(json.dumps(report))
    return 0 if report["clean_shutdown"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
