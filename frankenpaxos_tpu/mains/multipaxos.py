"""MultiPaxos role mains (the analog of
``jvm/.../multipaxos/<Role>Main.scala``):

    python -m frankenpaxos_tpu.mains.multipaxos \\
        --role replica --index 0 --config cluster.json \\
        --state_machine KeyValueStore

The config JSON (the pbtxt analog) looks like::

    {"f": 1,
     "batchers": [], "read_batchers": [],
     "leaders": ["127.0.0.1:10000", ...],
     "leader_elections": ["127.0.0.1:10010", ...],
     "proxy_leaders": [...],
     "acceptors": [["127.0.0.1:10030", ...], [...]],
     "replicas": [...], "proxy_replicas": [...],
     "flexible": false, "distribution_scheme": "hash"}

The client role runs closed-loop benchmark clients (BenchmarkUtil.scala
runFor/timed): each pseudonym keeps one outstanding request; every
completion appends ``start,stop,latency_nanos,label`` to the recorder CSV.
"""

from __future__ import annotations

import argparse
import json
import random
import time

from frankenpaxos_tpu.core.tcp_transport import TcpTransport
from frankenpaxos_tpu.harness.workload import (
    ReadWriteWorkload,
    workload_from_dict,
)
from frankenpaxos_tpu.mains.common import (
    add_common_args,
    host_port,
    host_ports,
    load_config_json,
    make_collectors,
    make_logger,
)
from frankenpaxos_tpu.protocols import multipaxos as mp
from frankenpaxos_tpu.statemachine import from_name as sm_from_name


def load_config(path: str) -> mp.Config:
    data = load_config_json(path)
    return mp.Config(
        f=data["f"],
        batcher_addresses=host_ports(data.get("batchers", [])),
        read_batcher_addresses=host_ports(data.get("read_batchers", [])),
        leader_addresses=host_ports(data["leaders"]),
        leader_election_addresses=host_ports(data["leader_elections"]),
        proxy_leader_addresses=host_ports(data["proxy_leaders"]),
        acceptor_addresses=tuple(
            host_ports(group) for group in data["acceptors"]
        ),
        replica_addresses=host_ports(data["replicas"]),
        proxy_replica_addresses=host_ports(data.get("proxy_replicas", [])),
        flexible=data.get("flexible", False),
        distribution_scheme=mp.DistributionScheme(
            data.get("distribution_scheme", "hash")
        ),
    )


def main() -> None:
    parser = argparse.ArgumentParser(prog="multipaxos")
    parser.add_argument("--role", required=True, choices=[
        "batcher", "read_batcher", "leader", "proxy_leader", "acceptor",
        "replica", "proxy_replica", "client",
    ])
    parser.add_argument("--index", type=int, default=0)
    parser.add_argument("--group_index", type=int, default=0,
                        help="acceptor group (acceptor role only)")
    parser.add_argument("--config", required=True)
    parser.add_argument("--state_machine", default="KeyValueStore")
    parser.add_argument("--seed", type=int, default=0)
    # Options overrides (the --options.<x> analog).
    parser.add_argument("--batch_size", type=int, default=10)
    parser.add_argument("--noop_flush_period", type=float, default=0.1)
    # Client-role flags (ClientMain.scala:24-79).
    parser.add_argument("--listen", help="client listen address host:port")
    parser.add_argument("--duration", type=float, default=5.0)
    parser.add_argument("--warmup", type=float, default=0.5)
    parser.add_argument("--num_pseudonyms", type=int, default=1)
    parser.add_argument("--workload", default='{"type": "read_write", "read_fraction": 0.0}')
    parser.add_argument("--read_consistency", default="linearizable",
                        choices=["linearizable", "sequential", "eventual"])
    parser.add_argument("--resend_period", type=float, default=1.0,
                        help="client request resend period (seconds)")
    parser.add_argument("--output", default="recorder.csv")
    add_common_args(parser)
    args = parser.parse_args()

    config = load_config(args.config)
    logger = make_logger(args)
    collectors = make_collectors(args)
    transport = TcpTransport(logger)

    if args.role == "client":
        run_client(args, config, logger, transport)
        return

    if args.role == "batcher":
        mp.Batcher(config.batcher_addresses[args.index], transport, logger,
                   config, mp.BatcherOptions(batch_size=args.batch_size),
                   collectors=collectors, seed=args.seed)
    elif args.role == "read_batcher":
        mp.ReadBatcher(config.read_batcher_addresses[args.index], transport,
                       logger, config, collectors=collectors, seed=args.seed)
    elif args.role == "leader":
        mp.Leader(config.leader_addresses[args.index], transport, logger,
                  config,
                  mp.LeaderOptions(noop_flush_period=args.noop_flush_period),
                  collectors=collectors, seed=args.seed)
    elif args.role == "proxy_leader":
        mp.ProxyLeader(config.proxy_leader_addresses[args.index], transport,
                       logger, config, collectors=collectors, seed=args.seed)
    elif args.role == "acceptor":
        mp.Acceptor(
            config.acceptor_addresses[args.group_index][args.index],
            transport, logger, config, collectors=collectors,
        )
    elif args.role == "replica":
        mp.Replica(config.replica_addresses[args.index], transport, logger,
                   sm_from_name(args.state_machine), config,
                   collectors=collectors, seed=args.seed)
    elif args.role == "proxy_replica":
        mp.ProxyReplica(config.proxy_replica_addresses[args.index], transport,
                        logger, config, collectors=collectors)
    transport.run()


def run_client(args, config, logger, transport) -> None:
    """Closed-loop clients: BenchmarkUtil.runFor + LabeledRecorder."""
    client = mp.Client(
        host_port(args.listen), transport, logger, config,
        mp.ClientOptions(
            resend_client_request_period=args.resend_period,
            resend_max_slot_requests_period=args.resend_period,
            resend_read_request_period=args.resend_period,
            resend_sequential_read_request_period=args.resend_period,
            resend_eventual_read_request_period=args.resend_period,
        ),
        seed=args.seed,
    )
    workload = workload_from_dict(json.loads(args.workload))
    rng = random.Random(args.seed)
    out = open(args.output, "w")
    out.write("start,stop,latency_nanos,label\n")
    stop_at = None

    def issue(pseudonym: int) -> None:
        command = workload.get(rng)
        is_read = (
            isinstance(workload, ReadWriteWorkload)
            and workload.is_read(command)
        )
        start = time.time()
        if is_read:
            method = {
                "linearizable": client.read,
                "sequential": client.sequential_read,
                "eventual": client.eventual_read,
            }[args.read_consistency]
            label = args.read_consistency
            promise = method(pseudonym, command)
        else:
            label = "write"
            promise = client.write(pseudonym, command)

        def done(p) -> None:
            stop = time.time()
            if p.exception is None and stop_at is not None and stop < stop_at:
                if stop - start >= 0 and time.time() >= warmup_until:
                    out.write(
                        f"{start},{stop},{int((stop - start) * 1e9)},{label}\n"
                    )
                issue(pseudonym)

        promise.on_complete(done)

    def kick() -> None:
        nonlocal stop_at, warmup_until
        stop_at = time.time() + args.duration
        warmup_until = time.time() + args.warmup
        for pseudonym in range(args.num_pseudonyms):
            issue(pseudonym)

    warmup_until = 0.0
    shutdown = transport.timer(
        host_port(args.listen), "shutdown", args.duration + 1.0,
        transport.shutdown,
    )
    shutdown.start()
    transport.run(on_start=kick)
    out.close()


if __name__ == "__main__":
    main()
