"""Deployment entry points (the analog of the reference's
``jvm/src/main/scala/frankenpaxos/<proto>/<Role>Main.scala`` layer):
per-role CLI mains over the real TCP transport, JSON cluster configs (the
pbtxt analog), Prometheus metrics exporters, and closed-loop benchmark
clients writing recorder CSVs (the BenchmarkUtil analog)."""
