"""Generic role main over the deployment registry (the analog of the
reference's ~60 per-role mains, ``jvm/.../<proto>/<Role>Main.scala``):

    python -m frankenpaxos_tpu.mains.run --protocol epaxos \\
        --role replica --index 0 --config cluster.json

    python -m frankenpaxos_tpu.mains.run --protocol epaxos \\
        --role client --listen 127.0.0.1:19050 --config cluster.json \\
        --duration 5 --num_pseudonyms 3 --output recorder.csv

MultiPaxos keeps its dedicated main (``frankenpaxos_tpu.mains.multipaxos``)
for its read-consistency and workload flags; every other protocol deploys
through this one. The client role runs closed-loop benchmark clients
(BenchmarkUtil.scala runFor/timed): each pseudonym keeps one outstanding
operation, and completions append ``start,stop,latency_nanos,label`` rows
to the recorder CSV.
"""

from __future__ import annotations

import argparse
import sys
import time

from frankenpaxos_tpu.core.tcp_transport import TcpTransport
from frankenpaxos_tpu.mains.common import (
    add_common_args,
    host_port,
    load_config_json,
    make_collectors,
    make_logger,
)
from frankenpaxos_tpu.mains.registry import REGISTRY


def main() -> None:
    parser = argparse.ArgumentParser(prog="frankenpaxos_tpu.mains.run")
    parser.add_argument("--protocol", required=True, choices=sorted(REGISTRY))
    parser.add_argument("--role", required=True)
    parser.add_argument("--index", type=int, default=0)
    parser.add_argument("--group_index", type=int, default=0)
    parser.add_argument("--config", required=True)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--run_for", type=float, default=0.0,
                        help="non-client roles: exit cleanly after this many "
                             "seconds (0 = run forever); needed for "
                             "profilers that dump at interpreter exit")
    # Client-role flags (ClientMain.scala:24-79).
    parser.add_argument("--listen", help="client listen address host:port")
    parser.add_argument("--duration", type=float, default=5.0)
    parser.add_argument("--warmup", type=float, default=0.5)
    parser.add_argument("--num_pseudonyms", type=int, default=1)
    parser.add_argument("--output", default="recorder.csv")
    add_common_args(parser)
    args = parser.parse_args()

    spec = REGISTRY[args.protocol]
    config = spec.parse_config(load_config_json(args.config))
    logger = make_logger(args)
    transport = TcpTransport(logger)

    if args.role == "client":
        if not args.listen:
            parser.error("--listen is required for --role client")
        run_client(spec, args, config, logger, transport)
        return

    if args.role not in spec.roles:
        parser.error(
            f"unknown role {args.role!r} for {spec.name}; "
            f"choose from {sorted(spec.roles)} or 'client'"
        )
    actor = spec.roles[args.role].build(
        config, args.index, args.group_index, transport, logger, args.seed
    )
    if args.prometheus_port != -1 and actor is not None:
        # Per-message-type counts + handler latency summaries, exposed on
        # the /metrics endpoint (PrometheusUtil.scala:6-15 analog).
        collectors = make_collectors(args)
        actor.enable_metrics(collectors, f"{spec.name}_{args.role}")
    if args.run_for > 0 and actor is not None:
        shutdown = transport.timer(
            actor.address, "shutdown", args.run_for, transport.shutdown
        )
        shutdown.start()
    transport.run()


def run_client(spec, args, config, logger, transport) -> None:
    listen = host_port(args.listen)
    client = spec.make_client(config, listen, transport, logger, args.seed)
    out = open(args.output, "w")
    out.write("start,stop,latency_nanos,label\n")
    stop_at = None
    warmup_until = 0.0
    counter = [0]

    def issue(pseudonym: int) -> None:
        # Trampoline: a promise that resolves synchronously (e.g. a
        # single-decree client answering from its learned value) must not
        # recurse through its completion callback.
        again = True
        while again:
            again = False
            if spec.max_ops is not None and counter[0] >= spec.max_ops:
                return
            n = counter[0]
            counter[0] += 1
            start = time.time()
            promise = spec.issue(client, pseudonym, n)
            in_call = [True]
            sync = [False]

            def done(p, n=n, start=start, in_call=in_call, sync=sync) -> None:
                stop = time.time()
                if stop_at is None or stop >= stop_at:
                    return
                if p.exception is not None:
                    # Don't let one failed op silently kill this
                    # pseudonym's loop (e.g. a single-pending client
                    # rejecting a concurrent propose): log and retry
                    # shortly — never synchronously, or a persistent
                    # failure would spin.
                    print(f"op {n} failed: {p.exception!r}", file=sys.stderr)
                    retry = transport.timer(
                        listen, f"retryOp{n}", 0.25, lambda: issue(pseudonym)
                    )
                    retry.start()
                    return
                if time.time() >= warmup_until:
                    out.write(
                        f"{start},{stop},{int((stop - start) * 1e9)},op\n"
                    )
                if in_call[0]:
                    sync[0] = True
                else:
                    issue(pseudonym)

            promise.on_complete(done)
            in_call[0] = False
            again = sync[0]

    def kick() -> None:
        nonlocal stop_at, warmup_until
        stop_at = time.time() + args.duration
        warmup_until = time.time() + args.warmup
        if spec.issue is not None:
            for pseudonym in range(args.num_pseudonyms):
                issue(pseudonym)
        # else: an echo-style client drives itself on its ping timer.

    shutdown = transport.timer(
        listen, "shutdown", args.duration + 1.0, transport.shutdown
    )
    shutdown.start()
    run_started = time.time()
    transport.run(on_start=kick)

    if spec.issue is None:
        # Echo-style: completions are reply counts, not promises. Spread
        # the rows over the actual run window so downstream throughput
        # math sees the real duration instead of a zero-length burst.
        n = getattr(client, "num_messages_received", 0)
        if n == 0:
            out.close()
            raise SystemExit(f"no replies received by {spec.name} client")
        elapsed = max(time.time() - run_started, 1e-3)
        for i in range(n):
            ts = run_started + (i + 1) * elapsed / n
            out.write(f"{ts},{ts},0,op\n")
    out.close()


if __name__ == "__main__":
    main()
