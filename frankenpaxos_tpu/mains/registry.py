"""The deployment registry: every protocol's role set, config codec, and
client driver, consumed by the generic role main
(``frankenpaxos_tpu.mains.run``) and the deployment smokes
(``frankenpaxos_tpu.harness.smoke --deploy``).

The reference ships ~60 per-role main objects
(``jvm/src/main/scala/frankenpaxos/<proto>/<Role>Main.scala``); the
idiomatic Python re-design is one data-driven registry: a
``ProtocolSpec`` declares how to parse the cluster JSON into the
protocol's Config, how to construct each role (in dependency-safe start
order), and how a closed-loop benchmark client issues operations
(``jvm/.../ClientMain.scala`` + ``BenchmarkUtil.scala``).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Optional

from frankenpaxos_tpu.mains.common import host_port, host_ports


def _hp_groups(groups) -> tuple:
    return tuple(host_ports(g) for g in groups)


@dataclasses.dataclass(frozen=True)
class RoleDef:
    """One deployable role of a protocol."""

    # config -> flat count, or (num_groups, group_size) when grouped.
    count: Callable[[object], object]
    # (config, index, group_index, transport, logger, seed) -> actor(s).
    build: Callable
    grouped: bool = False


@dataclasses.dataclass(frozen=True)
class ProtocolSpec:
    name: str
    # hp(i) -> "127.0.0.1:<port+i>"; returns the cluster JSON dict.
    local_config: Callable[[Callable[[int], str]], dict]
    parse_config: Callable[[dict], object]
    roles: Dict[str, RoleDef]  # insertion order = start order
    # (config, listen_addr, transport, logger, seed) -> client actor.
    make_client: Optional[Callable] = None
    # (client, pseudonym, counter) -> Promise. None => echo-style client
    # with no promises (completion observed via counters).
    issue: Optional[Callable] = None
    client_lag: float = 1.5
    # Cap on total ops per client process (single-decree protocols resolve
    # repeat proposes synchronously from the learned value; a closed loop
    # would spin). None = run for the full duration.
    max_ops: Optional[int] = None


REGISTRY: Dict[str, ProtocolSpec] = {}


def iter_role_instances(spec: ProtocolSpec, config):
    """Yield ``(role_name, role, group_index, index)`` for every process
    of every role, in the spec start order — shared by the deployment
    smokes and the viz cluster builder."""
    for role_name, role in spec.roles.items():
        cnt = role.count(config)
        if role.grouped:
            groups, per_group = cnt
            for g in range(groups):
                for i in range(per_group):
                    yield role_name, role, g, i
        else:
            for i in range(cnt):
                yield role_name, role, 0, i


def register(spec: ProtocolSpec) -> ProtocolSpec:
    assert spec.name not in REGISTRY, spec.name
    REGISTRY[spec.name] = spec
    return spec


# --------------------------------------------------------------------------
# echo
# --------------------------------------------------------------------------


def _echo_local(hp):
    return {"server": hp(0)}


def _echo_parse(data):
    return host_port(data["server"])


def _echo_build_server(config, index, group, t, logger, seed):
    from frankenpaxos_tpu.protocols.echo import EchoServer

    return EchoServer(config, t, logger)


def _echo_client(config, listen, t, logger, seed):
    from frankenpaxos_tpu.protocols.echo import EchoClient

    return EchoClient(listen, t, logger, config, ping_period=0.05)


register(ProtocolSpec(
    name="echo",
    local_config=_echo_local,
    parse_config=_echo_parse,
    roles={"server": RoleDef(count=lambda c: 1, build=_echo_build_server)},
    make_client=_echo_client,
    issue=None,  # ping timer drives itself; completion = replies received
    client_lag=0.5,
))


# --------------------------------------------------------------------------
# unreplicated
# --------------------------------------------------------------------------


def _unrep_local(hp):
    return {"server": hp(0)}


def _unrep_parse(data):
    return host_port(data["server"])


def _unrep_build_server(config, index, group, t, logger, seed):
    from frankenpaxos_tpu.protocols import unreplicated as unrep
    from frankenpaxos_tpu.statemachine import KeyValueStore

    return unrep.Server(config, t, logger, KeyValueStore())


def _unrep_client(config, listen, t, logger, seed):
    from frankenpaxos_tpu.protocols import unreplicated as unrep

    return unrep.Client(listen, t, logger, config)


def _kv_issue(client, pseudonym, counter):
    from frankenpaxos_tpu.statemachine import kv_set

    return client.propose(pseudonym, kv_set((f"k{counter % 16}", f"v{counter}")))


register(ProtocolSpec(
    name="unreplicated",
    local_config=_unrep_local,
    parse_config=_unrep_parse,
    roles={"server": RoleDef(count=lambda c: 1, build=_unrep_build_server)},
    make_client=_unrep_client,
    issue=_kv_issue,
    client_lag=0.5,
))


# --------------------------------------------------------------------------
# batchedunreplicated
# --------------------------------------------------------------------------


def _bu_local(hp):
    return {
        "batchers": [hp(0), hp(1)],
        "server": hp(2),
        "proxy_servers": [hp(3)],
    }


def _bu_parse(data):
    from frankenpaxos_tpu.protocols import batchedunreplicated as bu

    return bu.BatchedUnreplicatedConfig(
        batcher_addresses=host_ports(data["batchers"]),
        server_address=host_port(data["server"]),
        proxy_server_addresses=host_ports(data["proxy_servers"]),
    )


def _bu_build(role):
    def build(config, index, group, t, logger, seed):
        from frankenpaxos_tpu.protocols import batchedunreplicated as bu
        from frankenpaxos_tpu.statemachine import KeyValueStore

        if role == "server":
            return bu.BuServer(config.server_address, t, logger, config,
                               KeyValueStore())
        if role == "batcher":
            return bu.BuBatcher(config.batcher_addresses[index], t, logger,
                                config, bu.BuBatcherOptions(batch_size=2))
        return bu.BuProxyServer(config.proxy_server_addresses[index], t,
                                logger, config)

    return build


def _bu_client(config, listen, t, logger, seed):
    from frankenpaxos_tpu.protocols import batchedunreplicated as bu

    # Batchers flush only on a full batch (Batcher.scala:128); at smoke
    # load a half-full batch strands until the client's resend lands in a
    # batcher with room, so resend briskly.
    return bu.BuClient(listen, t, logger, config, resend_period=0.3,
                       seed=seed)


register(ProtocolSpec(
    name="batchedunreplicated",
    local_config=_bu_local,
    parse_config=_bu_parse,
    roles={
        "server": RoleDef(count=lambda c: 1, build=_bu_build("server")),
        "proxy_server": RoleDef(
            count=lambda c: len(c.proxy_server_addresses),
            build=_bu_build("proxy_server"),
        ),
        "batcher": RoleDef(
            count=lambda c: len(c.batcher_addresses),
            build=_bu_build("batcher"),
        ),
    },
    make_client=_bu_client,
    issue=_kv_issue,
))


# --------------------------------------------------------------------------
# paxos / fastpaxos / caspaxos (leader+acceptor protocols)
# --------------------------------------------------------------------------


def _la_local(hp):
    return {
        "f": 1,
        "leaders": [hp(0), hp(1)],
        "acceptors": [hp(2), hp(3), hp(4)],
    }


def _paxos_parse(data):
    from frankenpaxos_tpu.protocols import paxos as px

    return px.PaxosConfig(
        f=data["f"],
        leader_addresses=host_ports(data["leaders"]),
        acceptor_addresses=host_ports(data["acceptors"]),
    )


def _paxos_build(role):
    def build(config, index, group, t, logger, seed):
        from frankenpaxos_tpu.protocols import paxos as px

        if role == "leader":
            return px.PaxosLeader(config.leader_addresses[index], t, logger,
                                  config)
        return px.PaxosAcceptor(config.acceptor_addresses[index], t, logger,
                                config)

    return build


def _paxos_client(config, listen, t, logger, seed):
    from frankenpaxos_tpu.protocols import paxos as px

    return px.PaxosClient(listen, t, logger, config)


register(ProtocolSpec(
    name="paxos",
    local_config=_la_local,
    parse_config=_paxos_parse,
    roles={
        "acceptor": RoleDef(count=lambda c: len(c.acceptor_addresses),
                            build=_paxos_build("acceptor")),
        "leader": RoleDef(count=lambda c: len(c.leader_addresses),
                          build=_paxos_build("leader")),
    },
    make_client=_paxos_client,
    # Single-decree: repeated proposes re-learn the one chosen value.
    issue=lambda client, pseudonym, counter: client.propose(f"v{counter}"),
    max_ops=20,
))


def _fastpaxos_parse(data):
    from frankenpaxos_tpu.protocols import fastpaxos as fp

    return fp.FastPaxosConfig(
        f=data["f"],
        leader_addresses=host_ports(data["leaders"]),
        acceptor_addresses=host_ports(data["acceptors"]),
    )


def _fastpaxos_build(role):
    def build(config, index, group, t, logger, seed):
        from frankenpaxos_tpu.protocols import fastpaxos as fp

        if role == "leader":
            return fp.FpLeader(config.leader_addresses[index], t, logger,
                               config)
        return fp.FpAcceptor(config.acceptor_addresses[index], t, logger,
                             config)

    return build


def _fastpaxos_client(config, listen, t, logger, seed):
    from frankenpaxos_tpu.protocols import fastpaxos as fp

    return fp.FpClient(listen, t, logger, config)


register(ProtocolSpec(
    name="fastpaxos",
    local_config=_la_local,
    parse_config=_fastpaxos_parse,
    roles={
        "acceptor": RoleDef(count=lambda c: len(c.acceptor_addresses),
                            build=_fastpaxos_build("acceptor")),
        "leader": RoleDef(count=lambda c: len(c.leader_addresses),
                          build=_fastpaxos_build("leader")),
    },
    make_client=_fastpaxos_client,
    issue=lambda client, pseudonym, counter: client.propose(f"v{counter}"),
    max_ops=20,
))


def _caspaxos_parse(data):
    from frankenpaxos_tpu.protocols import caspaxos as cas

    return cas.CasPaxosConfig(
        f=data["f"],
        leader_addresses=host_ports(data["leaders"]),
        acceptor_addresses=host_ports(data["acceptors"]),
    )


def _caspaxos_build(role):
    def build(config, index, group, t, logger, seed):
        from frankenpaxos_tpu.protocols import caspaxos as cas

        if role == "leader":
            return cas.CasLeader(config.leader_addresses[index], t, logger,
                                 config)
        return cas.CasAcceptor(config.acceptor_addresses[index], t, logger,
                               config)

    return build


def _caspaxos_client(config, listen, t, logger, seed):
    from frankenpaxos_tpu.protocols import caspaxos as cas

    return cas.CasClient(listen, t, logger, config)


register(ProtocolSpec(
    name="caspaxos",
    local_config=_la_local,
    parse_config=_caspaxos_parse,
    roles={
        "acceptor": RoleDef(count=lambda c: len(c.acceptor_addresses),
                            build=_caspaxos_build("acceptor")),
        "leader": RoleDef(count=lambda c: len(c.leader_addresses),
                          build=_caspaxos_build("leader")),
    },
    make_client=_caspaxos_client,
    issue=lambda client, pseudonym, counter: client.propose({counter}),
    max_ops=20,
))


# --------------------------------------------------------------------------
# craq
# --------------------------------------------------------------------------


def _craq_local(hp):
    return {"f": 1, "chain_nodes": [hp(0), hp(1), hp(2)]}


def _craq_parse(data):
    from frankenpaxos_tpu.protocols import craq as cq

    return cq.CraqConfig(
        f=data["f"], chain_node_addresses=host_ports(data["chain_nodes"])
    )


def _craq_build(config, index, group, t, logger, seed):
    from frankenpaxos_tpu.protocols import craq as cq

    return cq.ChainNode(config.chain_node_addresses[index], t, logger,
                        config, seed=seed + index)


def _craq_client(config, listen, t, logger, seed):
    from frankenpaxos_tpu.protocols import craq as cq

    return cq.CraqClient(listen, t, logger, config)


def _craq_issue(client, pseudonym, counter):
    if counter % 4 == 3:
        return client.read(pseudonym, f"k{counter % 8}")
    return client.write(pseudonym, f"k{counter % 8}", f"v{counter}")


register(ProtocolSpec(
    name="craq",
    local_config=_craq_local,
    parse_config=_craq_parse,
    roles={
        "chain_node": RoleDef(count=lambda c: len(c.chain_node_addresses),
                              build=_craq_build),
    },
    make_client=_craq_client,
    issue=_craq_issue,
))


# --------------------------------------------------------------------------
# epaxos
# --------------------------------------------------------------------------


def _epaxos_local(hp):
    return {"f": 1, "replicas": [hp(0), hp(1), hp(2)]}


def _epaxos_parse(data):
    from frankenpaxos_tpu.protocols import epaxos as ep

    return ep.EPaxosConfig(
        f=data["f"], replica_addresses=host_ports(data["replicas"])
    )


def _epaxos_build(config, index, group, t, logger, seed):
    from frankenpaxos_tpu.protocols import epaxos as ep
    from frankenpaxos_tpu.statemachine import KeyValueStore

    return ep.EpReplica(config.replica_addresses[index], t, logger, config,
                        KeyValueStore(), seed=seed + index)


def _epaxos_client(config, listen, t, logger, seed):
    from frankenpaxos_tpu.protocols import epaxos as ep

    return ep.EpClient(listen, t, logger, config, seed=seed)


register(ProtocolSpec(
    name="epaxos",
    local_config=_epaxos_local,
    parse_config=_epaxos_parse,
    roles={
        "replica": RoleDef(count=lambda c: len(c.replica_addresses),
                           build=_epaxos_build),
    },
    make_client=_epaxos_client,
    issue=_kv_issue,
))


# --------------------------------------------------------------------------
# simplebpaxos / unanimousbpaxos / simplegcbpaxos
# --------------------------------------------------------------------------


def _sbp_local(hp):
    return {
        "f": 1,
        "leaders": [hp(0), hp(1)],
        "proposers": [hp(2), hp(3)],
        "dep_service_nodes": [hp(4), hp(5), hp(6)],
        "acceptors": [hp(7), hp(8), hp(9)],
        "replicas": [hp(10), hp(11)],
    }


def _sbp_parse(data):
    from frankenpaxos_tpu.protocols import simplebpaxos as bpx

    return bpx.SimpleBPaxosConfig(
        f=data["f"],
        leader_addresses=host_ports(data["leaders"]),
        proposer_addresses=host_ports(data["proposers"]),
        dep_service_node_addresses=host_ports(data["dep_service_nodes"]),
        acceptor_addresses=host_ports(data["acceptors"]),
        replica_addresses=host_ports(data["replicas"]),
    )


def _sbp_build(role):
    def build(config, index, group, t, logger, seed):
        from frankenpaxos_tpu.protocols import simplebpaxos as bpx
        from frankenpaxos_tpu.statemachine import KeyValueStore

        if role == "leader":
            return bpx.BpLeader(config.leader_addresses[index], t, logger,
                                config)
        if role == "proposer":
            return bpx.BpProposer(config.proposer_addresses[index], t,
                                  logger, config)
        if role == "dep_service_node":
            return bpx.BpDepServiceNode(
                config.dep_service_node_addresses[index], t, logger, config,
                KeyValueStore())
        if role == "acceptor":
            return bpx.BpAcceptor(config.acceptor_addresses[index], t,
                                  logger, config)
        return bpx.BpReplica(config.replica_addresses[index], t, logger,
                             config, KeyValueStore())

    return build


def _sbp_client(config, listen, t, logger, seed):
    from frankenpaxos_tpu.protocols import simplebpaxos as bpx

    return bpx.BpClient(listen, t, logger, config)


register(ProtocolSpec(
    name="simplebpaxos",
    local_config=_sbp_local,
    parse_config=_sbp_parse,
    roles={
        "acceptor": RoleDef(count=lambda c: len(c.acceptor_addresses),
                            build=_sbp_build("acceptor")),
        "dep_service_node": RoleDef(
            count=lambda c: len(c.dep_service_node_addresses),
            build=_sbp_build("dep_service_node")),
        "replica": RoleDef(count=lambda c: len(c.replica_addresses),
                           build=_sbp_build("replica")),
        "proposer": RoleDef(count=lambda c: len(c.proposer_addresses),
                            build=_sbp_build("proposer")),
        "leader": RoleDef(count=lambda c: len(c.leader_addresses),
                          build=_sbp_build("leader")),
    },
    make_client=_sbp_client,
    issue=_kv_issue,
))


def _ubp_local(hp):
    return {
        "f": 1,
        "leaders": [hp(0), hp(1)],
        "dep_service_nodes": [hp(2), hp(3), hp(4)],
        "acceptors": [hp(5), hp(6), hp(7)],
    }


def _ubp_parse(data):
    from frankenpaxos_tpu.protocols import unanimousbpaxos as ubx

    return ubx.UnanimousBPaxosConfig(
        f=data["f"],
        leader_addresses=host_ports(data["leaders"]),
        dep_service_node_addresses=host_ports(data["dep_service_nodes"]),
        acceptor_addresses=host_ports(data["acceptors"]),
    )


def _ubp_build(role):
    def build(config, index, group, t, logger, seed):
        from frankenpaxos_tpu.protocols import unanimousbpaxos as ubx
        from frankenpaxos_tpu.statemachine import KeyValueStore

        if role == "leader":
            return ubx.UbLeader(config.leader_addresses[index], t, logger,
                                config, KeyValueStore())
        if role == "dep_service_node":
            return ubx.UbDepServiceNode(
                config.dep_service_node_addresses[index], t, logger, config,
                KeyValueStore())
        return ubx.UbAcceptor(config.acceptor_addresses[index], t, logger,
                              config)

    return build


def _ubp_client(config, listen, t, logger, seed):
    from frankenpaxos_tpu.protocols import unanimousbpaxos as ubx

    return ubx.UbClient(listen, t, logger, config)


register(ProtocolSpec(
    name="unanimousbpaxos",
    local_config=_ubp_local,
    parse_config=_ubp_parse,
    roles={
        "acceptor": RoleDef(count=lambda c: len(c.acceptor_addresses),
                            build=_ubp_build("acceptor")),
        "dep_service_node": RoleDef(
            count=lambda c: len(c.dep_service_node_addresses),
            build=_ubp_build("dep_service_node")),
        "leader": RoleDef(count=lambda c: len(c.leader_addresses),
                          build=_ubp_build("leader")),
    },
    make_client=_ubp_client,
    issue=_kv_issue,
))


def _gcb_local(hp):
    return {
        "f": 1,
        "leaders": [hp(0), hp(1)],
        "proposers": [hp(2), hp(3)],
        "dep_service_nodes": [hp(4), hp(5), hp(6)],
        "acceptors": [hp(7), hp(8), hp(9)],
        "replicas": [hp(10), hp(11)],
        "garbage_collectors": [hp(12), hp(13)],
    }


def _gcb_parse(data):
    from frankenpaxos_tpu.protocols import simplegcbpaxos as gcb

    return gcb.SimpleGcBPaxosConfig(
        f=data["f"],
        leader_addresses=host_ports(data["leaders"]),
        proposer_addresses=host_ports(data["proposers"]),
        dep_service_node_addresses=host_ports(data["dep_service_nodes"]),
        acceptor_addresses=host_ports(data["acceptors"]),
        replica_addresses=host_ports(data["replicas"]),
        garbage_collector_addresses=host_ports(data["garbage_collectors"]),
    )


def _gcb_build(role):
    def build(config, index, group, t, logger, seed):
        from frankenpaxos_tpu.protocols import simplegcbpaxos as gcb
        from frankenpaxos_tpu.statemachine import KeyValueStore

        if role == "leader":
            return gcb.GcLeader(config.leader_addresses[index], t, logger,
                                config, seed=seed + index)
        if role == "proposer":
            return gcb.GcProposer(config.proposer_addresses[index], t,
                                  logger, config, seed=seed + 10 + index)
        if role == "dep_service_node":
            return gcb.GcDepServiceNode(
                config.dep_service_node_addresses[index], t, logger, config,
                KeyValueStore())
        if role == "acceptor":
            return gcb.GcAcceptor(config.acceptor_addresses[index], t,
                                  logger, config)
        if role == "replica":
            return gcb.GcReplica(config.replica_addresses[index], t, logger,
                                 config, KeyValueStore(), seed=seed + 30 + index)
        return gcb.GcGarbageCollector(
            config.garbage_collector_addresses[index], t, logger, config)

    return build


def _gcb_client(config, listen, t, logger, seed):
    from frankenpaxos_tpu.protocols import simplegcbpaxos as gcb

    return gcb.GcClient(listen, t, logger, config, seed=seed)


register(ProtocolSpec(
    name="simplegcbpaxos",
    local_config=_gcb_local,
    parse_config=_gcb_parse,
    roles={
        "acceptor": RoleDef(count=lambda c: len(c.acceptor_addresses),
                            build=_gcb_build("acceptor")),
        "dep_service_node": RoleDef(
            count=lambda c: len(c.dep_service_node_addresses),
            build=_gcb_build("dep_service_node")),
        "replica": RoleDef(count=lambda c: len(c.replica_addresses),
                           build=_gcb_build("replica")),
        "garbage_collector": RoleDef(
            count=lambda c: len(c.garbage_collector_addresses),
            build=_gcb_build("garbage_collector")),
        "proposer": RoleDef(count=lambda c: len(c.proposer_addresses),
                            build=_gcb_build("proposer")),
        "leader": RoleDef(count=lambda c: len(c.leader_addresses),
                          build=_gcb_build("leader")),
    },
    make_client=_gcb_client,
    issue=_kv_issue,
))


# --------------------------------------------------------------------------
# vanillamencius / fasterpaxos (server-only protocols w/ heartbeats)
# --------------------------------------------------------------------------


def _vm_local(hp):
    return {
        "f": 1,
        "servers": [hp(0), hp(1), hp(2)],
        "heartbeats": [hp(3), hp(4), hp(5)],
    }


def _vm_parse(data):
    from frankenpaxos_tpu.protocols import vanillamencius as vmn

    return vmn.VanillaMenciusConfig(
        f=data["f"],
        server_addresses=host_ports(data["servers"]),
        heartbeat_addresses=host_ports(data["heartbeats"]),
    )


def _vm_build(config, index, group, t, logger, seed):
    from frankenpaxos_tpu.protocols import vanillamencius as vmn
    from frankenpaxos_tpu.statemachine import ReadableAppendLog

    return vmn.VmServer(config.server_addresses[index], t, logger, config,
                        ReadableAppendLog(), seed=seed + index)


def _vm_client(config, listen, t, logger, seed):
    from frankenpaxos_tpu.protocols import vanillamencius as vmn

    return vmn.VmClient(listen, t, logger, config, seed=seed)


def _bytes_issue(client, pseudonym, counter):
    return client.propose(pseudonym, f"cmd{counter}".encode())


register(ProtocolSpec(
    name="vanillamencius",
    local_config=_vm_local,
    parse_config=_vm_parse,
    roles={
        "server": RoleDef(count=lambda c: len(c.server_addresses),
                          build=_vm_build),
    },
    make_client=_vm_client,
    issue=_bytes_issue,
))


def _fpr_local(hp):
    return {
        "f": 1,
        "servers": [hp(0), hp(1), hp(2)],
        "heartbeats": [hp(3), hp(4), hp(5)],
    }


def _fpr_parse(data):
    from frankenpaxos_tpu.protocols import fasterpaxos as fpx

    return fpx.FasterPaxosConfig(
        f=data["f"],
        server_addresses=host_ports(data["servers"]),
        heartbeat_addresses=host_ports(data["heartbeats"]),
    )


def _fpr_build(config, index, group, t, logger, seed):
    from frankenpaxos_tpu.protocols import fasterpaxos as fpx
    from frankenpaxos_tpu.statemachine import ReadableAppendLog

    # Server 0 runs phase 1 + Phase2aAny at startup, racing its peers'
    # socket binds; a short resend converges the startup handshake fast.
    return fpx.FprServer(config.server_addresses[index], t, logger, config,
                         ReadableAppendLog(),
                         fpx.FprServerOptions(resend_phase1as_period=0.5,
                                              resend_phase2a_anys_period=0.5),
                         seed=seed + index)


def _fpr_client(config, listen, t, logger, seed):
    from frankenpaxos_tpu.protocols import fasterpaxos as fpx

    return fpx.FprClient(listen, t, logger, config, resend_period=1.0,
                         seed=seed)


register(ProtocolSpec(
    name="fasterpaxos",
    local_config=_fpr_local,
    parse_config=_fpr_parse,
    roles={
        "server": RoleDef(count=lambda c: len(c.server_addresses),
                          build=_fpr_build),
    },
    make_client=_fpr_client,
    issue=_bytes_issue,
    client_lag=2.5,  # server 0 runs phase 1 + Phase2aAny at startup
))


# --------------------------------------------------------------------------
# mencius (compartmentalized)
# --------------------------------------------------------------------------


def _mnc_local(hp):
    return {
        "f": 1,
        "batchers": [],
        "leader_groups": [[hp(0), hp(1)], [hp(2), hp(3)], [hp(4), hp(5)]],
        "leader_election_groups": [
            [hp(6), hp(7)], [hp(8), hp(9)], [hp(10), hp(11)],
        ],
        "proxy_leaders": [hp(12), hp(13)],
        "acceptors": [[hp(14), hp(15), hp(16)], [hp(17), hp(18), hp(19)]],
        "replicas": [hp(20), hp(21)],
        "proxy_replicas": [],
    }


def _mnc_parse(data):
    from frankenpaxos_tpu.protocols import mencius as mnc

    return mnc.MenciusConfig(
        f=data["f"],
        batcher_addresses=host_ports(data.get("batchers", [])),
        leader_groups=_hp_groups(data["leader_groups"]),
        leader_election_groups=_hp_groups(data["leader_election_groups"]),
        proxy_leader_addresses=host_ports(data["proxy_leaders"]),
        acceptor_addresses=_hp_groups(data["acceptors"]),
        replica_addresses=host_ports(data["replicas"]),
        proxy_replica_addresses=host_ports(data.get("proxy_replicas", [])),
    )


def _mnc_build(role):
    def build(config, index, group, t, logger, seed):
        from frankenpaxos_tpu.protocols import mencius as mnc
        from frankenpaxos_tpu.protocols import multipaxos as mpx
        from frankenpaxos_tpu.statemachine import ReadableAppendLog

        if role == "leader":
            # Flat index over the leader groups (a member per process).
            return mnc.MenciusLeader(
                config.leader_addresses[index], t, logger, config,
                mnc.MenciusLeaderOptions(send_watermark_every_n=1),
                seed=seed + index)
        if role == "proxy_leader":
            return mpx.ProxyLeader(config.proxy_leader_addresses[index], t,
                                   logger, config, seed=seed + 10 + index)
        if role == "acceptor":
            return mnc.MenciusAcceptor(
                config.acceptor_addresses[group][index], t, logger, config)
        return mpx.Replica(config.replica_addresses[index], t, logger,
                           ReadableAppendLog(), config, seed=seed + 20 + index)

    return build


def _mnc_client(config, listen, t, logger, seed):
    from frankenpaxos_tpu.protocols import mencius as mnc

    return mnc.MenciusClient(listen, t, logger, config, seed=seed)


def _write_issue(client, pseudonym, counter):
    return client.write(pseudonym, f"cmd{counter}".encode())


register(ProtocolSpec(
    name="mencius",
    local_config=_mnc_local,
    parse_config=_mnc_parse,
    roles={
        "acceptor": RoleDef(
            count=lambda c: (len(c.acceptor_addresses),
                             len(c.acceptor_addresses[0])),
            build=_mnc_build("acceptor"), grouped=True),
        "replica": RoleDef(count=lambda c: len(c.replica_addresses),
                           build=_mnc_build("replica")),
        "proxy_leader": RoleDef(count=lambda c: len(c.proxy_leader_addresses),
                                build=_mnc_build("proxy_leader")),
        "leader": RoleDef(count=lambda c: len(c.leader_addresses),
                          build=_mnc_build("leader")),
    },
    make_client=_mnc_client,
    issue=_write_issue,
    client_lag=2.5,
))


# --------------------------------------------------------------------------
# fastmultipaxos
# --------------------------------------------------------------------------


def _fmx_local(hp):
    return {
        "f": 1,
        "leaders": [hp(0), hp(1)],
        "leader_elections": [hp(2), hp(3)],
        "leader_heartbeats": [hp(4), hp(5)],
        "acceptors": [hp(6), hp(7), hp(8)],
        "acceptor_heartbeats": [hp(9), hp(10), hp(11)],
    }


def _fmx_parse(data):
    from frankenpaxos_tpu.protocols import fastmultipaxos as fmx
    from frankenpaxos_tpu.roundsystem import MixedRoundRobin

    return fmx.FastMultiPaxosConfig(
        f=data["f"],
        leader_addresses=host_ports(data["leaders"]),
        leader_election_addresses=host_ports(data["leader_elections"]),
        leader_heartbeat_addresses=host_ports(data["leader_heartbeats"]),
        acceptor_addresses=host_ports(data["acceptors"]),
        acceptor_heartbeat_addresses=host_ports(data["acceptor_heartbeats"]),
        round_system=MixedRoundRobin(len(data["leaders"])),
    )


def _fmx_build(role):
    def build(config, index, group, t, logger, seed):
        from frankenpaxos_tpu.protocols import fastmultipaxos as fmx
        from frankenpaxos_tpu.statemachine import ReadableAppendLog

        if role == "leader":
            return fmx.FmpLeader(config.leader_addresses[index], t, logger,
                                 config, ReadableAppendLog(),
                                 seed=seed + index)
        return fmx.FmpAcceptor(config.acceptor_addresses[index], t, logger,
                               config, seed=seed + 10 + index)

    return build


def _fmx_client(config, listen, t, logger, seed):
    from frankenpaxos_tpu.protocols import fastmultipaxos as fmx

    return fmx.FmpClient(listen, t, logger, config, seed=seed)


register(ProtocolSpec(
    name="fastmultipaxos",
    local_config=_fmx_local,
    parse_config=_fmx_parse,
    roles={
        "acceptor": RoleDef(count=lambda c: len(c.acceptor_addresses),
                            build=_fmx_build("acceptor")),
        "leader": RoleDef(count=lambda c: len(c.leader_addresses),
                          build=_fmx_build("leader")),
    },
    make_client=_fmx_client,
    issue=_bytes_issue,
    client_lag=2.5,  # leader 0 finishes phase 1 + any-suffix first
))


# --------------------------------------------------------------------------
# matchmakerpaxos / matchmakermultipaxos / horizontal
# --------------------------------------------------------------------------


def _mmp_local(hp):
    # The client's listen address is part of the config; the deployment
    # smoke's client listens on hp(50) (see harness.smoke.deploy_smoke).
    return {
        "f": 1,
        "clients": [hp(50)],
        "leaders": [hp(1), hp(2)],
        "matchmakers": [hp(3), hp(4), hp(5)],
        "acceptors": [hp(6), hp(7), hp(8), hp(9)],
    }


def _mmp_parse(data):
    from frankenpaxos_tpu.protocols import matchmakerpaxos as mmx

    return mmx.MatchmakerPaxosConfig(
        f=data["f"],
        client_addresses=host_ports(data["clients"]),
        leader_addresses=host_ports(data["leaders"]),
        matchmaker_addresses=host_ports(data["matchmakers"]),
        acceptor_addresses=host_ports(data["acceptors"]),
    )


def _mmp_build(role):
    def build(config, index, group, t, logger, seed):
        from frankenpaxos_tpu.protocols import matchmakerpaxos as mmx

        if role == "leader":
            return mmx.MmLeader(config.leader_addresses[index], t, logger,
                                config)
        if role == "matchmaker":
            return mmx.MmMatchmaker(config.matchmaker_addresses[index], t,
                                    logger, config)
        return mmx.MmAcceptor(config.acceptor_addresses[index], t, logger,
                              config)

    return build


def _mmp_client(config, listen, t, logger, seed):
    from frankenpaxos_tpu.protocols import matchmakerpaxos as mmx

    return mmx.MmClient(listen, t, logger, config)


register(ProtocolSpec(
    name="matchmakerpaxos",
    local_config=_mmp_local,
    parse_config=_mmp_parse,
    roles={
        "acceptor": RoleDef(count=lambda c: len(c.acceptor_addresses),
                            build=_mmp_build("acceptor")),
        "matchmaker": RoleDef(count=lambda c: len(c.matchmaker_addresses),
                              build=_mmp_build("matchmaker")),
        "leader": RoleDef(count=lambda c: len(c.leader_addresses),
                          build=_mmp_build("leader")),
    },
    make_client=_mmp_client,
    issue=lambda client, pseudonym, counter: client.propose(f"v{counter}"),
    max_ops=20,
))


def _mxm_local(hp):
    return {
        "f": 1,
        "leaders": [hp(0), hp(1)],
        "leader_elections": [hp(2), hp(3)],
        "reconfigurers": [hp(4), hp(5)],
        "matchmakers": [hp(6), hp(7), hp(8), hp(9)],
        "acceptors": [hp(10), hp(11), hp(12), hp(13)],
        "replicas": [hp(14), hp(15)],
    }


def _mxm_parse(data):
    from frankenpaxos_tpu.protocols import matchmakermultipaxos as mmx

    return mmx.MatchmakerMultiPaxosConfig(
        f=data["f"],
        leader_addresses=host_ports(data["leaders"]),
        leader_election_addresses=host_ports(data["leader_elections"]),
        reconfigurer_addresses=host_ports(data["reconfigurers"]),
        matchmaker_addresses=host_ports(data["matchmakers"]),
        acceptor_addresses=host_ports(data["acceptors"]),
        replica_addresses=host_ports(data["replicas"]),
    )


def _mxm_build(role):
    def build(config, index, group, t, logger, seed):
        from frankenpaxos_tpu.protocols import matchmakermultipaxos as mmx
        from frankenpaxos_tpu.statemachine import ReadableAppendLog

        if role == "leader":
            return mmx.MmmLeader(config.leader_addresses[index], t, logger,
                                 config, seed=seed + index)
        if role == "reconfigurer":
            return mmx.MmmReconfigurer(config.reconfigurer_addresses[index],
                                       t, logger, config,
                                       seed=seed + 10 + index)
        if role == "matchmaker":
            return mmx.MmmMatchmaker(config.matchmaker_addresses[index], t,
                                     logger, config)
        if role == "acceptor":
            return mmx.MmmAcceptor(config.acceptor_addresses[index], t,
                                   logger, config)
        return mmx.MmmReplica(config.replica_addresses[index], t, logger,
                              config, ReadableAppendLog(),
                              seed=seed + 30 + index)

    return build


def _mxm_client(config, listen, t, logger, seed):
    from frankenpaxos_tpu.protocols import matchmakermultipaxos as mmx

    return mmx.MmmClient(listen, t, logger, config, seed=seed)


register(ProtocolSpec(
    name="matchmakermultipaxos",
    local_config=_mxm_local,
    parse_config=_mxm_parse,
    roles={
        "matchmaker": RoleDef(count=lambda c: len(c.matchmaker_addresses),
                              build=_mxm_build("matchmaker")),
        "acceptor": RoleDef(count=lambda c: len(c.acceptor_addresses),
                            build=_mxm_build("acceptor")),
        "replica": RoleDef(count=lambda c: len(c.replica_addresses),
                           build=_mxm_build("replica")),
        "reconfigurer": RoleDef(count=lambda c: len(c.reconfigurer_addresses),
                                build=_mxm_build("reconfigurer")),
        "leader": RoleDef(count=lambda c: len(c.leader_addresses),
                          build=_mxm_build("leader")),
    },
    make_client=_mxm_client,
    issue=_bytes_issue,
    client_lag=2.5,  # leader 0 matchmakes + runs phase 1 at startup
))


def _hzx_local(hp):
    return {
        "f": 1,
        "leaders": [hp(0), hp(1)],
        "leader_elections": [hp(2), hp(3)],
        "acceptors": [hp(4), hp(5), hp(6), hp(7)],
        "replicas": [hp(8), hp(9)],
    }


def _hzx_parse(data):
    from frankenpaxos_tpu.protocols import horizontal as hzx

    return hzx.HorizontalConfig(
        f=data["f"],
        leader_addresses=host_ports(data["leaders"]),
        leader_election_addresses=host_ports(data["leader_elections"]),
        acceptor_addresses=host_ports(data["acceptors"]),
        replica_addresses=host_ports(data["replicas"]),
    )


def _hzx_build(role):
    def build(config, index, group, t, logger, seed):
        from frankenpaxos_tpu.protocols import horizontal as hzx
        from frankenpaxos_tpu.statemachine import ReadableAppendLog

        if role == "leader":
            return hzx.HzLeader(config.leader_addresses[index], t, logger,
                                config, seed=seed + index)
        if role == "acceptor":
            return hzx.HzAcceptor(config.acceptor_addresses[index], t,
                                  logger, config)
        return hzx.HzReplica(config.replica_addresses[index], t, logger,
                             config, ReadableAppendLog(),
                             seed=seed + 30 + index)

    return build


def _hzx_client(config, listen, t, logger, seed):
    from frankenpaxos_tpu.protocols import horizontal as hzx

    return hzx.HzClient(listen, t, logger, config, seed=seed)


register(ProtocolSpec(
    name="horizontal",
    local_config=_hzx_local,
    parse_config=_hzx_parse,
    roles={
        "acceptor": RoleDef(count=lambda c: len(c.acceptor_addresses),
                            build=_hzx_build("acceptor")),
        "replica": RoleDef(count=lambda c: len(c.replica_addresses),
                           build=_hzx_build("replica")),
        "leader": RoleDef(count=lambda c: len(c.leader_addresses),
                          build=_hzx_build("leader")),
    },
    make_client=_hzx_client,
    issue=_bytes_issue,
    client_lag=2.5,  # leader 0 runs the initial chunk's phase 1
))


# --------------------------------------------------------------------------
# scalog
# --------------------------------------------------------------------------


def _scx_local(hp):
    return {
        "f": 1,
        "servers": [[hp(0), hp(1)], [hp(2), hp(3)]],
        "aggregator": hp(4),
        "leaders": [hp(5), hp(6)],
        "acceptors": [hp(7), hp(8), hp(9)],
        "replicas": [hp(10), hp(11)],
    }


def _scx_parse(data):
    from frankenpaxos_tpu.protocols import scalog as scx

    return scx.ScalogConfig(
        f=data["f"],
        server_addresses=_hp_groups(data["servers"]),
        aggregator_address=host_port(data["aggregator"]),
        leader_addresses=host_ports(data["leaders"]),
        acceptor_addresses=host_ports(data["acceptors"]),
        replica_addresses=host_ports(data["replicas"]),
    )


def _scx_build(role):
    def build(config, index, group, t, logger, seed):
        from frankenpaxos_tpu.protocols import scalog as scx
        from frankenpaxos_tpu.protocols.multipaxos.replica import Replica
        from frankenpaxos_tpu.statemachine import ReadableAppendLog

        if role == "server":
            return scx.ScServer(
                config.server_addresses[group][index], t, logger, config,
                scx.ScServerOptions(push_size=1), seed=seed + index)
        if role == "aggregator":
            return scx.ScAggregator(
                config.aggregator_address, t, logger, config,
                scx.ScAggregatorOptions(num_shard_cuts_per_proposal=1))
        if role == "leader":
            return scx.ScLeader(config.leader_addresses[index], t, logger,
                                config, seed=seed + 10 + index)
        if role == "acceptor":
            return scx.ScAcceptor(config.acceptor_addresses[index], t,
                                  logger, config)
        return Replica(config.replica_addresses[index], t, logger,
                       ReadableAppendLog(), scx.replica_config(config),
                       seed=seed + 20 + index)

    return build


def _scx_client(config, listen, t, logger, seed):
    from frankenpaxos_tpu.protocols import scalog as scx

    return scx.ScClient(listen, t, logger, config, seed=seed)


register(ProtocolSpec(
    name="scalog",
    local_config=_scx_local,
    parse_config=_scx_parse,
    roles={
        "acceptor": RoleDef(count=lambda c: len(c.acceptor_addresses),
                            build=_scx_build("acceptor")),
        "replica": RoleDef(count=lambda c: len(c.replica_addresses),
                           build=_scx_build("replica")),
        "leader": RoleDef(count=lambda c: len(c.leader_addresses),
                          build=_scx_build("leader")),
        "aggregator": RoleDef(count=lambda c: 1,
                              build=_scx_build("aggregator")),
        "server": RoleDef(
            count=lambda c: (len(c.server_addresses),
                             len(c.server_addresses[0])),
            build=_scx_build("server"), grouped=True),
    },
    make_client=_scx_client,
    issue=_write_issue,
    client_lag=2.5,
))
