"""Shared plumbing for role mains: address parsing, logging flags, and the
Prometheus exporter (the analog of jvm ConfigUtil/PrometheusUtil/Flags)."""

from __future__ import annotations

import argparse
import json
from typing import List

from frankenpaxos_tpu.core import HostPort, PrintLogger
from frankenpaxos_tpu.core.logger import LogLevel
from frankenpaxos_tpu.monitoring import PrometheusCollectors


def host_port(s: str) -> HostPort:
    host, port = s.rsplit(":", 1)
    return HostPort(host, int(port))


def host_ports(items) -> tuple:
    return tuple(host_port(x) for x in items)


def add_common_args(parser: argparse.ArgumentParser) -> None:
    parser.add_argument("--log_level", default="warn",
                        choices=["debug", "info", "warn", "error", "fatal"])
    parser.add_argument(
        "--prometheus_port", type=int, default=-1,
        help="metrics exporter port; -1 disables (PrometheusUtil.scala)",
    )
    parser.add_argument("--prometheus_host", default="0.0.0.0")


def make_logger(args) -> PrintLogger:
    return PrintLogger(LogLevel[args.log_level.upper()])


def make_collectors(args) -> PrometheusCollectors:
    collectors = PrometheusCollectors()
    if args.prometheus_port != -1:
        collectors.start_http_server(args.prometheus_port, args.prometheus_host)
    return collectors


def load_config_json(path: str) -> dict:
    with open(path) as f:
        return json.load(f)
