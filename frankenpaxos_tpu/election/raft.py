"""Raft-style leader election (at most one leader per round; 2f+1 nodes).

Capability parity with ``election/raft/Participant.scala:37-330``: states
LeaderlessFollower / Follower / Candidate / Leader; randomized no-ping and
not-enough-votes timeouts; a candidate collects majority votes to become
leader; larger-round pings/vote-requests demote immediately. Callbacks fire
with the new leader's address on follower transitions and on winning an
election.
"""

from __future__ import annotations

import dataclasses
import random
from typing import Callable, Dict, List, Optional, Sequence, Set

from frankenpaxos_tpu.core import Actor, Address, Logger, Transport, wire
from frankenpaxos_tpu.util import random_duration


@wire.message
@dataclasses.dataclass(frozen=True)
class RaftPing:
    round: int


@wire.message
@dataclasses.dataclass(frozen=True)
class VoteRequest:
    round: int


@wire.message
@dataclasses.dataclass(frozen=True)
class Vote:
    round: int


@dataclasses.dataclass(frozen=True)
class ElectionOptions:
    ping_period: float = 1.0
    no_ping_timeout_min: float = 10.0
    no_ping_timeout_max: float = 12.0
    not_enough_votes_timeout_min: float = 10.0
    not_enough_votes_timeout_max: float = 12.0


@dataclasses.dataclass
class LeaderlessFollower:
    no_ping_timer: object


@dataclasses.dataclass
class Follower:
    no_ping_timer: object
    leader: Address


@dataclasses.dataclass
class Candidate:
    not_enough_votes_timer: object
    votes: Set[Address]


@dataclasses.dataclass
class Leader:
    ping_timer: object


class Participant(Actor):
    def __init__(
        self,
        address: Address,
        transport: Transport,
        logger: Logger,
        addresses: Sequence[Address],
        leader: Optional[Address] = None,
        options: ElectionOptions = ElectionOptions(),
        seed: int = 0,
    ):
        super().__init__(address, transport, logger)
        logger.check(address in addresses)
        logger.check_le(options.no_ping_timeout_min, options.no_ping_timeout_max)
        logger.check_le(
            options.not_enough_votes_timeout_min,
            options.not_enough_votes_timeout_max,
        )
        if leader is not None:
            logger.check(leader in addresses)
        self.addresses = list(addresses)
        self.options = options
        self.rng = random.Random(seed)
        self.nodes = {a: self.chan(a) for a in self.addresses}
        self.callbacks: List[Callable[[Address], None]] = []
        self.round = 0
        if leader is not None:
            if address == leader:
                t = self._ping_timer()
                t.start()
                self.state = Leader(t)
            else:
                t = self._no_ping_timer()
                t.start()
                self.state = Follower(t, leader)
        else:
            t = self._no_ping_timer()
            t.start()
            self.state = LeaderlessFollower(t)

    def register(self, callback: Callable[[Address], None]) -> None:
        self.callbacks.append(callback)

    # -- Timers --------------------------------------------------------------

    def _ping_timer(self):
        def fire() -> None:
            for ch in self.nodes.values():
                ch.send(RaftPing(round=self.round))
            timer.start()

        timer = self.timer("pingTimer", self.options.ping_period, fire)
        return timer

    def _no_ping_timer(self):
        def fire() -> None:
            self._become_candidate()

        return self.timer(
            "noPingTimer",
            random_duration(
                self.rng,
                self.options.no_ping_timeout_min,
                self.options.no_ping_timeout_max,
            ),
            fire,
        )

    def _not_enough_votes_timer(self):
        def fire() -> None:
            self._become_candidate()

        return self.timer(
            "notEnoughVotesTimer",
            random_duration(
                self.rng,
                self.options.not_enough_votes_timeout_min,
                self.options.not_enough_votes_timeout_max,
            ),
            fire,
        )

    def _stop_timer(self) -> None:
        s = self.state
        if isinstance(s, LeaderlessFollower):
            s.no_ping_timer.stop()
        elif isinstance(s, Follower):
            s.no_ping_timer.stop()
        elif isinstance(s, Candidate):
            s.not_enough_votes_timer.stop()
        elif isinstance(s, Leader):
            s.ping_timer.stop()

    # -- Transitions ---------------------------------------------------------

    def _become_candidate(self) -> None:
        self._stop_timer()
        self.round += 1
        t = self._not_enough_votes_timer()
        t.start()
        self.state = Candidate(t, set())
        for ch in self.nodes.values():
            ch.send(VoteRequest(round=self.round))

    def _transition_to_follower(self, new_round: int, leader: Address) -> None:
        self._stop_timer()
        self.round = new_round
        t = self._no_ping_timer()
        t.start()
        self.state = Follower(t, leader)
        for callback in self.callbacks:
            callback(leader)

    # -- Handlers ------------------------------------------------------------

    def receive(self, src: Address, msg) -> None:
        if isinstance(msg, RaftPing):
            self._handle_ping(src, msg)
        elif isinstance(msg, VoteRequest):
            self._handle_vote_request(src, msg)
        elif isinstance(msg, Vote):
            self._handle_vote(src, msg)
        else:
            self.logger.fatal(f"unknown raft election message {msg!r}")

    def _handle_ping(self, src: Address, ping: RaftPing) -> None:
        if ping.round < self.round:
            return
        if ping.round > self.round:
            self._transition_to_follower(ping.round, src)
            return
        s = self.state
        if isinstance(s, (LeaderlessFollower, Candidate)):
            self._transition_to_follower(ping.round, src)
        elif isinstance(s, Follower):
            s.no_ping_timer.reset()
        # Leader: ping from ourselves; ignore.

    def _handle_vote_request(self, src: Address, req: VoteRequest) -> None:
        if req.round < self.round:
            return
        if req.round > self.round:
            self._stop_timer()
            self.round = req.round
            t = self._no_ping_timer()
            t.start()
            self.state = LeaderlessFollower(t)
            self.nodes[src].send(Vote(round=self.round))
            return
        if isinstance(self.state, Candidate) and src == self.address:
            self.nodes[src].send(Vote(round=self.round))

    def _handle_vote(self, src: Address, vote: Vote) -> None:
        if vote.round < self.round:
            return
        if vote.round > self.round:
            self.logger.fatal(
                f"received a vote for round {vote.round} but only in round "
                f"{self.round}"
            )
        s = self.state
        if isinstance(s, LeaderlessFollower):
            self.logger.fatal(
                f"received a vote in round {vote.round} as a leaderless follower"
            )
        elif isinstance(s, Candidate):
            s.votes.add(src)
            if len(s.votes) >= len(self.addresses) // 2 + 1:
                self._stop_timer()
                t = self._ping_timer()
                t.start()
                self.state = Leader(t)
                for ch in self.nodes.values():
                    ch.send(RaftPing(round=self.round))
                for callback in self.callbacks:
                    callback(self.address)
        # Follower/Leader: late votes; ignore.
