"""Basic (f+1-node) leader election.

Capability parity with ``election/basic/Participant.scala``: Raft-style
rounds WITHOUT the at-most-one-leader-per-round guarantee — multiple nodes
may consider themselves leader of the same round, so only f+1 participants
are needed to tolerate f faults. A leader pings periodically; a follower
that misses pings for a randomized timeout bumps the round and becomes
leader; a leader seeing a larger (round, leaderIndex) ballot steps down.
``ForceNoPing`` forces a follower to immediately stand for election (used
by chaos drivers). Callbacks fire on this participant's own
leader/follower transitions (Participant.scala:149-164).
"""

from __future__ import annotations

import dataclasses
import enum
import random
from typing import Callable, List, Optional, Sequence

from frankenpaxos_tpu.core import Actor, Address, Logger, Transport, wire
from frankenpaxos_tpu.util import random_duration


@wire.message
@dataclasses.dataclass(frozen=True)
class ElectionPing:
    round: int
    leader_index: int


@wire.message
@dataclasses.dataclass(frozen=True)
class ForceNoPing:
    pass


@dataclasses.dataclass(frozen=True)
class ElectionOptions:
    ping_period: float = 30.0
    no_ping_timeout_min: float = 60.0
    no_ping_timeout_max: float = 120.0


class State(enum.Enum):
    LEADER = "leader"
    FOLLOWER = "follower"


class Participant(Actor):
    def __init__(
        self,
        address: Address,
        transport: Transport,
        logger: Logger,
        addresses: Sequence[Address],
        initial_leader_index: int = 0,
        options: ElectionOptions = ElectionOptions(),
        seed: int = 0,
    ):
        super().__init__(address, transport, logger)
        logger.check(address in addresses)
        logger.check_le(options.no_ping_timeout_min, options.no_ping_timeout_max)
        logger.check_le(0, initial_leader_index)
        logger.check_lt(initial_leader_index, len(addresses))
        self.addresses = list(addresses)
        self.options = options
        self.index = self.addresses.index(address)
        self.others = [self.chan(a) for a in self.addresses if a != address]
        self.callbacks: List[Callable[[int], None]] = []
        self.round = 0
        self.leader_index = initial_leader_index
        rng = random.Random(seed)

        def on_ping_timer() -> None:
            self._ping(self.round, self.index)
            self.ping_timer.start()

        def on_no_ping() -> None:
            self.round += 1
            self.leader_index = self.index
            self._change_state(State.LEADER)

        self.ping_timer = self.timer("pingTimer", options.ping_period, on_ping_timer)
        self.no_ping_timer = self.timer(
            "noPingTimer",
            random_duration(
                rng, options.no_ping_timeout_min, options.no_ping_timeout_max
            ),
            on_no_ping,
        )
        if self.index == initial_leader_index:
            self.state = State.LEADER
            self.ping_timer.start()
        else:
            self.state = State.FOLLOWER
            self.no_ping_timer.start()

    def _ping(self, round: int, leader_index: int) -> None:
        for ch in self.others:
            ch.send(ElectionPing(round=round, leader_index=leader_index))

    def _change_state(self, new_state: State) -> None:
        if self.state == new_state:
            return
        if new_state == State.LEADER:  # follower -> leader
            self.no_ping_timer.stop()
            self.ping_timer.start()
            self.state = State.LEADER
            self._ping(self.round, self.index)
        else:  # leader -> follower
            self.ping_timer.stop()
            self.no_ping_timer.start()
            self.state = State.FOLLOWER
        for callback in self.callbacks:
            callback(self.leader_index)

    def receive(self, src: Address, msg) -> None:
        if isinstance(msg, ElectionPing):
            self._handle_ping(msg)
        elif isinstance(msg, ForceNoPing):
            self._handle_force_no_ping()
        else:
            self.logger.fatal(f"unknown election message {msg!r}")

    def _handle_ping(self, ping: ElectionPing) -> None:
        ping_ballot = (ping.round, ping.leader_index)
        ballot = (self.round, self.leader_index)
        if self.state == State.FOLLOWER:
            if ping_ballot < ballot:
                return  # stale
            if ping_ballot == ballot:
                self.no_ping_timer.reset()
            else:
                self.round = ping.round
                self.leader_index = ping.leader_index
                self.no_ping_timer.reset()
        else:  # LEADER
            if ping_ballot <= ballot:
                return  # stale
            self.round = ping.round
            self.leader_index = ping.leader_index
            self._change_state(State.FOLLOWER)

    def _handle_force_no_ping(self) -> None:
        if self.state == State.LEADER:
            return
        self.round += 1
        self.leader_index = self.index
        self._change_state(State.LEADER)

    def register(self, callback: Callable[[int], None]) -> None:
        """Register a callback fired with the leader index on this node's
        own leader/follower transitions."""
        self.callbacks.append(callback)
