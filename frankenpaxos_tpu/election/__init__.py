from frankenpaxos_tpu.election import basic, raft

__all__ = ["basic", "raft"]
